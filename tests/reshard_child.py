"""Child for the multi-process elastic reshard test.

Two jax.distributed processes x 4 virtual CPU devices.  The engine
starts on the full 8-device mesh, shrinks the kv axis to a 4-device
mesh spanning BOTH processes (2 devices each), grows back to 8 — state
(store + fused optimizer momentum + sparse table rows) must survive
every recut and continued training must aggregate on the new fan-in.
Reshard is a collective: both processes call it with the same mesh.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import faulthandler

faulthandler.dump_traceback_later(240, exit=True)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from pslite_tpu.parallel.engine import CollectiveEngine  # noqa: E402
from pslite_tpu.parallel.sparse import SparseEngine  # noqa: E402


def main() -> int:
    rank = int(os.environ["RESHARD_RANK"])
    coord = os.environ["RESHARD_COORD"]
    jax.distributed.initialize(
        coordinator_address=coord, num_processes=2, process_id=rank
    )
    devices = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    assert len(devices) == 8, devices
    mesh8 = Mesh(np.array(devices), ("kv",))
    # The small mesh spans BOTH processes (2 devices each) so the
    # multi-process path is exercised on both sides of the recut.
    mesh4 = Mesh(np.array(devices[0:2] + devices[4:6]), ("kv",))

    eng = CollectiveEngine(mesh=mesh8, server_handle="sum")
    keys = np.arange(6, dtype=np.uint64)
    val_len = 100  # total 600: padding differs between 8 and 4 shards
    eng.register_dense("b", keys, val_len)
    assert eng._is_multiprocess()

    # 1-D multi-process host contract: rows = MY 4 local worker rows.
    g8 = np.full((4, 600), float(rank + 1), np.float32)
    out = np.asarray(eng.push_pull("b", g8))
    np.testing.assert_allclose(out, 12.0)  # 4*1 + 4*2

    # Momentum bucket: fused optimizer STATE must move with the recut.
    # lr=0.1, mu=0.9; step 1 from zero momentum: store = -0.1 * sum.
    eng.register_dense("m", keys, val_len)
    m1 = np.asarray(eng.push_pull("m", g8, handle="sgd_momentum:0.1,0.9"))
    np.testing.assert_allclose(m1, -0.1 * 12.0, rtol=1e-5)

    # Sparse table alongside (its own collective reshard): every one of
    # my 4 local worker rows pushes 1.0 into global row 3.
    se = SparseEngine(mesh8, "kv")
    se.register_sparse("emb", num_rows=16, dim=4)
    idx8 = np.full((4, 1), 3, np.int32)
    se.push("emb", idx8, np.ones((4, 1, 4), np.float32))
    se.block("emb")

    # ---- shrink: 8 -> 4 shards (both processes keep devices) ----------
    eng.reshard(mesh4)
    se.reshard(mesh4)
    assert eng.num_shards == 4 and se.num_shards == 4
    np.testing.assert_allclose(np.asarray(eng.pull("b")), 12.0)
    idx4 = np.full((2, 1), 3, np.int32)
    got = se.pull("emb", idx4)  # sharded per worker row: read MY shards
    for s in got.addressable_shards:
        np.testing.assert_allclose(np.asarray(s.data), 8.0)

    # Continued training on the new fan-in: my 2 local rows.
    g4 = np.full((2, 600), float(rank + 1), np.float32)
    out = np.asarray(eng.push_pull("b", g4))
    np.testing.assert_allclose(out, 12.0 + 6.0)  # + 2*1 + 2*2

    # Momentum recurrence continues across the recut: the carried
    # momentum (12) decays by mu and adds the new sum (6):
    # store = -1.2 - 0.1*(0.9*12 + 6) = -2.88.
    m2 = np.asarray(eng.push_pull("m", g4, handle="sgd_momentum:0.1,0.9"))
    np.testing.assert_allclose(m2, -0.1 * 12.0 - 0.1 * (0.9 * 12.0 + 6.0),
                               rtol=1e-5)

    # ---- grow: 4 -> 8 shards ------------------------------------------
    eng.reshard(mesh8)
    assert eng.num_shards == 8
    np.testing.assert_allclose(np.asarray(eng.pull("b")), 18.0)
    out = np.asarray(eng.push_pull("b", g8))
    np.testing.assert_allclose(out, 30.0)

    print(f"RESHARD_OK rank={rank}", flush=True)
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as exc:  # noqa: BLE001 - env-limitation sentinel
        if "Multiprocess computations aren't implemented" not in repr(exc):
            raise
        # This jaxlib's CPU backend cannot run cross-process programs
        # at all: report the environment limitation and exit cleanly so
        # the parent can SKIP fast instead of timing out.
        print("MULTIPROC_UNSUPPORTED", flush=True)
        sys.exit(0)
