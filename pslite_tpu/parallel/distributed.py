"""Multi-host bootstrap: jax.distributed from the PS environment.

The reference scales multi-host through its scheduler rendezvous; on TPU
pods the equivalent is ``jax.distributed.initialize`` building one global
mesh across hosts, with XLA collectives riding ICI within a slice and DCN
across slices.  This module derives the coordinator/process topology from
the same DMLC_* variables the PS control plane uses, so one launcher
config drives both planes:

- coordinator = ``DMLC_PS_ROOT_URI : DMLC_PS_ROOT_PORT + 1`` (the port
  next to the scheduler),
- num_processes = worker count (each host is one worker / one JOINT
  process),
- process_id = ``DMLC_RANK``.

Single-process use (tests, one chip) never needs this.
"""

from __future__ import annotations

from typing import Dict, Optional

from .. import environment
from ..utils import logging as log


def distributed_options(env=None) -> Dict[str, object]:
    """Pure computation of jax.distributed.initialize kwargs from env."""
    env = env or environment.get()
    uri = env.find("DMLC_PS_ROOT_URI")
    log.check(uri is not None, "DMLC_PS_ROOT_URI not set")
    port = env.find_int("DMLC_PS_ROOT_PORT", 0) + 1
    num = env.find_int("DMLC_NUM_WORKER", 0)
    log.check(num > 0, "DMLC_NUM_WORKER not set")
    rank = env.find_int("DMLC_RANK", -1)
    log.check(0 <= rank < num,
              "DMLC_RANK must be set per host for multi-host meshes")
    return {
        "coordinator_address": f"{uri}:{port}",
        "num_processes": num,
        "process_id": rank,
    }


def init_distributed(env=None) -> Optional[Dict[str, object]]:
    """Initialize jax.distributed from the PS env (no-op for 1 process).

    Returns the options used, or None when single-process.
    """
    env = env or environment.get()
    if env.find_int("DMLC_NUM_WORKER", 1) <= 1:
        return None
    opts = distributed_options(env)
    import jax

    jax.distributed.initialize(**opts)
    return opts


def global_mesh(axis_name: str = "kv"):
    """1-D mesh over every device of every process (call after
    init_distributed on multi-host)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()), (axis_name,))
