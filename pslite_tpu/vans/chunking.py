"""Chunked streaming transfers (``PS_CHUNK_BYTES`` — docs/chunking.md).

The transports move each message as one monolithic frame, which makes
pipelining and multi-rail striping impossible at message granularity: a
multi-MB push head-of-line blocks every small op queued behind it on
the same peer lane.  This module is the BytePS-style fix — partition
large data messages into fixed-size chunk messages:

- :func:`split_message` turns one large data message into ``total``
  chunk messages, each carrying a contiguous byte range of the logical
  concatenation of the original data segments (zero-copy views) plus a
  :class:`~..message.ChunkInfo` wire extension.  Each chunk rides the
  send path independently, so the lane scheduler can interleave
  higher-priority small ops *between chunks* (bounded HOL wait ≈ one
  chunk) and MultiVan can stripe one transfer across rails.
- :class:`ChunkAssembler` is the receive side: a per-``(sender, xfer)``
  reassembly table that copies chunks into per-segment buffers as they
  land (in any order — rails do not preserve cross-rail order), emits
  *partial* messages (``OPT_XFER_PART``) handing each newly completed
  whole-key prefix of an eligible push straight to the app layer so
  apply overlaps the remaining wire time, and emits the fully
  reassembled message when the last chunk lands.

Partial-emission eligibility is deliberately narrow: plain push
requests (no pull half, no codec/replica/zpull marker, fixed
``k`` values, exactly keys+vals segments).  Everything else — pull
responses, codec-compressed payloads (their scales segment lands
last, docs/compression.md), lens'd pushes — reassembles fully and
takes the normal path, so chunking never changes apply semantics,
only when bytes move.
"""

from __future__ import annotations

import copy
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..message import (
    ChunkInfo,
    Command,
    Control,
    Message,
    OPT_XFER_PART,
    code_dtype,
)
from ..sarray import SArray
from ..utils import logging as log
from ..utils.bounded import BoundedKeySet
from ..wire import (
    CHUNK_MAX_SEGS,
    FRAME_HEADER_SIZE,
    chunk_ext_payload_size,
    pack_meta,
)
from .native import COPY_KERNEL_MIN as _COPY_KERNEL_MIN

_UINT64_CODE = 8  # wire dtype code of the keys segment

# Receive-queue levels (PriorityRecvQueue — utils/queues.py): control
# rides above every data level so a chunk backlog can never starve
# heartbeats/ACKs/barriers; TERMINATE and the shutdown sentinel drain
# LAST, preserving the deliver-queued-traffic-before-retiring contract.
RECV_CONTROL_PRIORITY = 1 << 20
RECV_DRAIN_LAST = -(1 << 30)


def recv_priority(msg) -> int:
    """Receive-queue level of a decoded message (see the constants
    above); data messages use their send-side ``meta.priority``, so a
    priority op that jumped the send lanes jumps the receive backlog
    too — without this, the pump's FIFO re-introduces the head-of-line
    wait chunking removed from the wire."""
    if msg is None:
        return RECV_DRAIN_LAST
    c = msg.meta.control
    if not c.empty():
        if c.cmd == Command.TERMINATE:
            return RECV_DRAIN_LAST
        return RECV_CONTROL_PRIORITY
    return msg.meta.priority


def recv_tenant(msg) -> int:
    """Receive-queue tenant of a decoded message (docs/qos.md):
    control and the shutdown sentinel are tenantless (they ride the
    express/drain bands, never the weighted pool)."""
    if msg is None or not msg.meta.control.empty():
        return 0
    return msg.meta.tenant


def recv_cost(msg) -> int:
    """Weighted-fair clock charge of a decoded message: its payload
    bytes (chunk frames carry theirs in ``data``).  Batch frames
    (docs/batching.md) charge their WHOLE multi-op payload — the
    combiner never merges across tenants or priorities, so the frame's
    envelope fields price every sub-op correctly."""
    if msg is None or not msg.meta.control.empty():
        return 1
    if msg.data:
        return max(1, sum(d.nbytes for d in msg.data))
    return max(1, msg.meta.data_size)


def _flat_u8(arr) -> np.ndarray:
    """A contiguous 1-D uint8 view of an array (copying only the rare
    strided input, like ``wire.pack_frame``)."""
    if not (isinstance(arr, np.ndarray) and arr.flags["C_CONTIGUOUS"]):
        arr = np.ascontiguousarray(arr)
    return arr.reshape(-1).view(np.uint8)


def split_message(msg: Message, chunk_bytes: int,
                  xfer_id: int) -> Optional[List[Message]]:
    """Split one large data message into chunk messages, or ``None``
    when the message must go monolithic (small, control, zpull/shm
    routed, or too many segments for the wire extension).

    The chunk payloads are zero-copy uint8 views of the original
    segments; callers must honor the usual don't-mutate-until-wait
    contract, which they already do for monolithic sends.
    """
    m = msg.meta
    if chunk_bytes <= 0 or not m.control.empty() or m.chunk is not None:
        return None
    n_data = len(msg.data)
    if n_data == 0 or n_data > CHUNK_MAX_SEGS:
        return None
    seg_lens = [d.nbytes for d in msg.data]
    total = sum(seg_lens)
    if total <= chunk_bytes:
        return None
    seg_types = tuple(m.data_type[i] if i < len(m.data_type) else 2
                      for i in range(n_data))
    raws = [_flat_u8(d.data) for d in msg.data]
    bounds = [0]
    for ln in seg_lens:
        bounds.append(bounds[-1] + ln)
    n_chunks = (total + chunk_bytes - 1) // chunk_bytes
    out: List[Message] = []
    for idx in range(n_chunks):
        lo = idx * chunk_bytes
        hi = min(lo + chunk_bytes, total)
        cm = copy.copy(m)
        cm.control = Control()
        cm.data_type = []
        cm.data_size = 0
        cm.chunk = ChunkInfo(
            xfer=xfer_id, index=idx, total=n_chunks, offset=lo,
            seg_lens=tuple(seg_lens), seg_types=seg_types,
        )
        cmsg = Message(meta=cm)
        for si in range(n_data):
            a, b = max(lo, bounds[si]), min(hi, bounds[si + 1])
            if a < b:
                cmsg.add_data(SArray(raws[si][a - bounds[si]:b - bounds[si]]))
        # Canonical chunk meta: add_data stamped this chunk's segment
        # count/bytes into data_type/data_size, which made per-chunk
        # metas differ in LENGTH.  Clear both — receivers default raw
        # chunk slices to uint8 (wire.rebuild_message) and the
        # assembler re-derives the real table from EXT_CHUNK — so every
        # chunk of a transfer packs to the same meta bytes except
        # sid/index/offset, the exact template contract the native
        # splitter patches in place (byte-identical frames).
        cm.data_type = []
        cm.data_size = 0
        out.append(cmsg)
    return out


class NativeDescriptor:
    """One data message prepared for the native sender lanes
    (docs/native_core.md): the packed meta template, the pinned
    contiguous payload arrays, and the chunk-split parameters the C++
    side patches per chunk.  Built by :func:`native_descriptor`."""

    __slots__ = ("meta_buf", "arrs", "chunk_bytes", "ext_off", "n_chunks",
                 "wire_bytes")

    def __init__(self, meta_buf, arrs, chunk_bytes, ext_off, n_chunks,
                 wire_bytes):
        self.meta_buf = meta_buf
        self.arrs = arrs          # MUST stay referenced until reaped
        self.chunk_bytes = chunk_bytes
        self.ext_off = ext_off    # EXT_CHUNK payload offset in meta_buf
        self.n_chunks = n_chunks
        self.wire_bytes = wire_bytes


def native_descriptor(msg: Message, chunk_bytes: int,
                      xfer_seq) -> NativeDescriptor:
    """Prepare one data message for a native sender lane: the meta
    template bytes (sid stamped natively at transmit), the contiguous
    payload arrays the lane transmits zero-copy, and — when the message
    is chunk-eligible under exactly :func:`split_message`'s rules — the
    EXT_CHUNK template whose index/offset fields the native splitter
    patches per chunk, so native frames are byte-identical to the
    Python splitter's (``xfer_seq`` is consumed only then).

    ``wire_bytes`` is the exact on-wire byte count of every frame of
    the transfer (headers + lens tables + metas + payload), matching
    what the Python path's per-frame ``send_msg`` returns summed.
    """
    m = msg.meta
    arrs = [_flat_u8(d.data) for d in msg.data]
    seg_lens = [a.nbytes for a in arrs]
    total = sum(seg_lens)
    n_data = len(arrs)
    chunkable = (
        chunk_bytes > 0 and m.chunk is None and 0 < n_data <= CHUNK_MAX_SEGS
        and total > chunk_bytes
    )
    if not chunkable:
        meta_buf = pack_meta(m)
        wire = FRAME_HEADER_SIZE + 8 * n_data + len(meta_buf) + total
        return NativeDescriptor(meta_buf, arrs, 0, -1, 1, wire)
    seg_types = tuple(m.data_type[i] if i < len(m.data_type) else 2
                      for i in range(n_data))
    cm = copy.copy(m)
    cm.control = Control()
    cm.data_type = []
    cm.data_size = 0
    n_chunks = (total + chunk_bytes - 1) // chunk_bytes
    cm.chunk = ChunkInfo(
        xfer=next(xfer_seq), index=0, total=n_chunks, offset=0,
        seg_lens=tuple(seg_lens), seg_types=seg_types,
    )
    meta_buf = pack_meta(cm)
    # pack_meta appends EXT_CHUNK last, so the payload is the trailing
    # bytes of the template (asserted byte-identical in the parity
    # test).
    ext_off = len(meta_buf) - chunk_ext_payload_size(n_data)
    bounds = [0]
    for ln in seg_lens:
        bounds.append(bounds[-1] + ln)
    wire = total + n_chunks * (FRAME_HEADER_SIZE + len(meta_buf))
    for idx in range(n_chunks):
        lo, hi = idx * chunk_bytes, min((idx + 1) * chunk_bytes, total)
        wire += 8 * sum(
            1 for si in range(n_data)
            if max(lo, bounds[si]) < min(hi, bounds[si + 1])
        )
    return NativeDescriptor(meta_buf, arrs, chunk_bytes, ext_off,
                            n_chunks, wire)


# ChunkInfo.index sentinel on a frame the NATIVE CORE already
# reassembled (cpp/pslite_core.cc AbsorbChunk): the payload is the
# complete transfer; finalize_native_transfer turns it into the
# original message without touching the Python assembler.
NATIVE_XFER_COMPLETE = 0xFFFFFFFF


def finalize_native_transfer(msg: Message) -> Message:
    """Rebuild the original message from a natively-reassembled frame:
    the data segments are already the original segments (zero-copy
    uint8 views over the native frame buffer) — re-view them by the
    EXT_CHUNK dtype table and restore the canonical meta fields the
    chunk template blanked."""
    ck = msg.meta.chunk
    msg.meta.chunk = None
    msg.meta.data_type = list(ck.seg_types)
    msg.meta.data_size = sum(int(ln) for ln in ck.seg_lens)
    for i, seg in enumerate(msg.data):
        raw = seg.data if isinstance(seg, SArray) else seg
        if not isinstance(raw, np.ndarray):
            raw = np.frombuffer(raw, np.uint8)
        msg.data[i] = SArray(raw.view(code_dtype(ck.seg_types[i])))
    return msg


class _Xfer:
    """Reassembly state of one in-flight transfer."""

    __slots__ = (
        "meta", "bufs", "seg_lens", "seg_types", "total", "total_bytes",
        "received", "ends", "got", "contig", "k_bytes", "n_keys",
        "streamable", "emitted_keys", "t_last", "t0_us",
    )

    def __init__(self, ck: ChunkInfo, meta, alloc=None):
        self.meta = meta  # original meta (chunk stripped, option kept)
        self.seg_lens = ck.seg_lens
        self.seg_types = ck.seg_types
        self.total = ck.total
        self.total_bytes = sum(ck.seg_lens)
        # Reassembly buffers through the van's allocator when it has a
        # pooled receive arena (chunk scatter then lands in recycled
        # blocks); numpy otherwise.
        if alloc is None:
            self.bufs = [np.empty(int(ln), np.uint8) for ln in ck.seg_lens]
        else:
            self.bufs = [alloc(int(ln)) for ln in ck.seg_lens]
        self.received = [False] * ck.total
        self.ends = [0] * ck.total  # end offset of each received chunk
        self.got = 0
        self.contig = 0  # chunks contiguous from index 0
        self.t_last = time.monotonic()
        self.t0_us = 0.0
        # Streaming eligibility (module docstring): plain fixed-k push
        # request with exactly keys+vals segments.  Multi-op batch
        # frames (docs/batching.md) never stream-apply — their data
        # section interleaves several ops' segments, so only the fully
        # reassembled frame can be re-sliced per op.
        m = meta
        self.streamable = bool(
            m.push and m.request and not m.pull and not m.simple_app
            and m.option == 0 and m.codec is None and m.batch is None
            and len(ck.seg_lens) == 2
            and ck.seg_types[0] == _UINT64_CODE
            and ck.seg_lens[0] > 0 and ck.seg_lens[0] % 8 == 0
        )
        self.n_keys = int(ck.seg_lens[0]) // 8 if self.streamable else 0
        if self.streamable:
            vb = int(ck.seg_lens[1])
            item = np.dtype(code_dtype(ck.seg_types[1])).itemsize
            # vb > 0: an empty vals segment has no per-key stride (and
            # nothing worth streaming) — k_bytes must stay a divisor.
            if (vb > 0 and self.n_keys and vb % self.n_keys == 0
                    and (vb // self.n_keys) % item == 0):
                self.k_bytes = vb // self.n_keys
            else:
                self.streamable = False
                self.k_bytes = 0
        else:
            self.k_bytes = 0
        self.emitted_keys = 0

    def watermark(self) -> int:
        """Bytes contiguous from the start of the logical stream."""
        return self.ends[self.contig - 1] if self.contig else 0


class ChunkAssembler:
    """Per-(sender, xfer) reassembly table (one per receiving van).

    ``add`` is called from the van's single receive pump, so the lock
    only guards against the cleanup entry points (peer death, stale
    sweeps) that run on other threads.
    """

    def __init__(self, tracer=None, max_entries: int = 256,
                 ttl_s: float = 120.0, alloc=None, copy_kernel=None):
        self._alloc = alloc
        # Optional GIL-free copy kernel (native.memcpy_kernel): the
        # scatter's big slice-assigns run outside the GIL so frame
        # decode and the apply shards stream concurrently.
        self._copy = copy_kernel
        self._mu = threading.Lock()
        self._xfers: Dict[Tuple[int, int], _Xfer] = {}
        # Tombstones of recently COMPLETED transfers: a stale duplicate
        # chunk (retransmit whose ACK was lost, dup older than the
        # resender's bounded signature cache) must not re-create
        # reassembly state — the partial it would emit re-applies
        # already-applied keys on the server.
        self._done: BoundedKeySet = BoundedKeySet(4096)
        self._tracer = tracer
        self._max_entries = max_entries
        self._ttl_s = ttl_s
        self._ticks = 0

    def __len__(self) -> int:
        with self._mu:
            return len(self._xfers)

    def clear(self) -> None:
        with self._mu:
            self._xfers.clear()
            self._done = BoundedKeySet(4096)

    def drop_peer(self, node_id: int) -> int:
        """Reclaim every partial transfer from a dead/recovered sender
        — its xfer counter restarts at 1, so BOTH live entries and the
        completed-transfer tombstones would collide with the new
        incarnation's ids (stale tombstones would silently black-hole
        its first chunked pushes)."""
        with self._mu:
            stale = [k for k in self._xfers if k[0] == node_id]
            for k in stale:
                del self._xfers[k]
            self._done.discard_where(lambda k: k[0] == node_id)
        if stale:
            log.vlog(1, f"reclaimed {len(stale)} partial transfer(s) "
                        f"from node {node_id}")
        return len(stale)

    def _sweep_stale(self) -> None:
        now = time.monotonic()
        with self._mu:
            stale = [k for k, x in self._xfers.items()
                     if now - x.t_last > self._ttl_s]
            for k in stale:
                del self._xfers[k]
        for k in stale:
            log.warning(f"abandoned partial transfer {k[1]} from node "
                        f"{k[0]} reclaimed after {self._ttl_s:.0f}s")

    def add(self, msg: Message) -> List[Message]:
        """Absorb one chunk; returns ready-to-deliver messages: zero or
        one ``OPT_XFER_PART`` partial (the newly completed whole-key
        prefix of a streamable push) and, on the last chunk, the fully
        reassembled original message."""
        ck = msg.meta.chunk
        key = (msg.meta.sender, ck.xfer)
        self._ticks += 1
        if self._ticks % 256 == 0:
            self._sweep_stale()
        with self._mu:
            x = self._xfers.get(key)
            if x is None and key in self._done:
                return []  # stale duplicate of a completed transfer
            if x is None:
                meta = copy.copy(msg.meta)
                meta.chunk = None
                meta.data_type = list(ck.seg_types)
                meta.data_size = sum(ck.seg_lens)
                x = _Xfer(ck, meta, self._alloc)
                if (self._tracer is not None and meta.trace
                        and self._tracer.active):
                    x.t0_us = self._tracer.now_us()
                if len(self._xfers) >= self._max_entries:
                    # Evict the stalest entry: an unbounded table is a
                    # leak when senders die mid-transfer faster than
                    # the TTL sweep runs.
                    victim = min(self._xfers,
                                 key=lambda k: self._xfers[k].t_last)
                    del self._xfers[victim]
                    log.warning(f"reassembly table full: evicted partial "
                                f"transfer {victim[1]} from node "
                                f"{victim[0]}")
                self._xfers[key] = x
        payload = sum(d.nbytes for d in msg.data)
        if (x.total != ck.total or x.seg_lens != ck.seg_lens
                or not (0 <= ck.index < x.total)
                # Bounds BEFORE the scatter: a corrupt frame whose
                # range walks past the transfer must drop the transfer
                # (warn), never trip a CHECK the receive loop escalates
                # to killing the node.
                or ck.offset < 0
                or ck.offset + payload > x.total_bytes):
            log.warning(f"inconsistent chunk for transfer {ck.xfer} from "
                        f"node {msg.meta.sender}; dropping the transfer")
            with self._mu:
                self._xfers.pop(key, None)
            return []
        if x.received[ck.index]:
            return []  # duplicate chunk (retransmit raced its ACK)
        nbytes = self._scatter(x, ck.offset, msg.data)
        x.received[ck.index] = True
        x.ends[ck.index] = ck.offset + nbytes
        x.got += 1
        x.t_last = time.monotonic()
        while x.contig < x.total and x.received[x.contig]:
            x.contig += 1
        out: List[Message] = []
        if x.got >= x.total:
            with self._mu:
                self._xfers.pop(key, None)
                self._done.add(key)  # tombstone against stale dups
            part = self._partial(x, key, final=True)
            if part is not None:
                out.append(part)
            out.append(self._final(x, key))
        else:
            part = self._partial(x, key)
            if part is not None:
                out.append(part)
        return out

    def _scatter(self, x: _Xfer, offset: int, data) -> int:
        """Copy a chunk's payload slices into the per-segment buffers;
        returns the chunk's byte count."""
        pos = offset
        si = 0
        bounds = [0]
        for ln in x.seg_lens:
            bounds.append(bounds[-1] + int(ln))
        total = 0
        for seg in data:
            raw = _flat_u8(seg.data if isinstance(seg, SArray) else seg)
            done = 0
            while done < raw.nbytes:
                while si + 1 < len(bounds) and pos >= bounds[si + 1]:
                    si += 1
                log.check(si < len(x.bufs), "chunk bytes beyond transfer")
                take = min(raw.nbytes - done, bounds[si + 1] - pos)
                b0 = pos - bounds[si]
                if self._copy is not None and take >= _COPY_KERNEL_MIN:
                    self._copy(x.bufs[si].ctypes.data + b0,
                               raw.ctypes.data + done, take)
                else:
                    x.bufs[si][b0:b0 + take] = raw[done:done + take]
                done += take
                pos += take
            total += raw.nbytes
        return total

    def _partial(self, x: _Xfer, key: Tuple[int, int],
                 final: bool = False) -> Optional[Message]:
        """The newly completed whole-key prefix of a streamable push as
        an ``OPT_XFER_PART`` message (views into the reassembly
        buffers), or None when nothing new completed."""
        if not x.streamable:
            return None
        keys_avail = min(x.watermark(), int(x.seg_lens[0])) // 8
        vals_avail = max(0, x.watermark() - int(x.seg_lens[0]))
        done_keys = min(keys_avail, vals_avail // x.k_bytes)
        if final:
            done_keys = x.n_keys
        if done_keys <= x.emitted_keys:
            return None
        a, b = x.emitted_keys, done_keys
        x.emitted_keys = done_keys
        pm = copy.copy(x.meta)
        pm.option = OPT_XFER_PART
        pm.data_type = []
        pm.data_size = 0
        msg = Message(meta=pm)
        msg.add_data(SArray(x.bufs[0][a * 8:b * 8].view(np.uint64)))
        vdtype = code_dtype(x.seg_types[1])
        msg.add_data(SArray(
            x.bufs[1][a * x.k_bytes:b * x.k_bytes].view(vdtype)
        ))
        # In-process routing token for the app layer's stream state
        # (partials never touch the wire, so a plain attribute works).
        msg._xfer_key = key
        msg._xfer_range = (a, b)
        return msg

    def _final(self, x: _Xfer, key: Tuple[int, int]) -> Message:
        meta = copy.copy(x.meta)
        meta.data_type = []
        meta.data_size = 0
        msg = Message(meta=meta)
        for buf, code in zip(x.bufs, x.seg_types):
            msg.add_data(SArray(buf.view(code_dtype(code))))
        msg._xfer_key = key
        msg._xfer_streamed = x.emitted_keys
        if (self._tracer is not None and meta.trace
                and self._tracer.active and x.t0_us):
            self._tracer.span(
                meta.trace, "xfer_recv", x.t0_us,
                args={"from": meta.sender, "chunks": x.total,
                      "bytes": x.total_bytes, "xfer": key[1]},
            )
        return msg
