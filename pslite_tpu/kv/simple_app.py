"""SimpleApp — (head:int, body:bytes) request/response control messaging.

Capability parity with the reference's ``include/ps/simple_app.h``:
requests go to a node or a whole group; ``simple_app=true`` messages bypass
KV parsing; default response handle just counts completions.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Optional

from .. import ps as ps_mod
from ..customer import Customer
from ..message import Message
from ..utils import logging as log


@dataclass
class SimpleData:
    head: int = 0
    body: bytes = b""
    sender: int = 0
    timestamp: int = 0
    customer_id: int = 0


class SimpleApp:
    def __init__(self, app_id: int, customer_id=None, postoffice=None):
        self.po = postoffice or ps_mod.postoffice()
        if customer_id is None:
            # Servers demux incoming messages by app_id (van.cc:428-438), so
            # a server-side app must register under customer_id == app_id.
            customer_id = app_id if self.po.is_server else 0
        self._customer = Customer(app_id, customer_id, self._process, self.po)
        self._request_handle: Callable[[SimpleData, "SimpleApp"], None] = (
            lambda req, app: app.response(req)
        )
        self._response_handle: Callable[[SimpleData, "SimpleApp"], None] = (
            lambda req, app: None
        )
        self._mu = threading.Lock()

    def set_request_handle(self, fn) -> None:
        self._request_handle = fn

    def set_response_handle(self, fn) -> None:
        self._response_handle = fn

    def request(self, head: int, body, recv_id: int) -> int:
        """Send a request to a node id or group; returns the timestamp."""
        ts = self._customer.new_request(recv_id)
        if isinstance(body, str):
            body = body.encode()
        for recver in self._recipients(recv_id):
            msg = Message()
            m = msg.meta
            m.head = head
            m.body = body
            m.app_id = self._customer.app_id
            m.customer_id = self._customer.customer_id
            m.timestamp = ts
            m.request = True
            m.simple_app = True
            m.recver = recver
            self.po.van.send(msg)
        return ts

    def _recipients(self, recv_id: int):
        ids = self.po.get_node_ids(recv_id)
        if recv_id < 8 and self.po.group_size > 1:
            # Instance groups: talk to the matching instance of each group.
            ids = [
                i
                for i in ids
                if i == 1 or (i - 8) // 2 % self.po.group_size == self.po.instance_idx
            ]
        return ids

    def response(self, req: SimpleData, body=b"") -> None:
        if isinstance(body, str):
            body = body.encode()
        msg = Message()
        m = msg.meta
        m.head = req.head
        m.body = body
        m.app_id = self._customer.app_id
        m.customer_id = req.customer_id
        m.timestamp = req.timestamp
        m.request = False
        m.simple_app = True
        m.recver = req.sender
        self.po.van.send(msg)

    def wait(self, timestamp: int) -> None:
        self._customer.wait_request(timestamp)

    def stop(self) -> None:
        self._customer.stop()

    def _process(self, msg: Message) -> None:
        data = SimpleData(
            head=msg.meta.head,
            body=msg.meta.body,
            sender=msg.meta.sender,
            timestamp=msg.meta.timestamp,
            customer_id=msg.meta.customer_id,
        )
        if msg.meta.request:
            log.check(self._request_handle is not None, "no request handle")
            self._request_handle(data, self)
        else:
            self._response_handle(data, self)
