"""CNN and DLRM model families training through the PS data plane."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from pslite_tpu.models.cnn import (
    CNNConfig,
    forward as cnn_forward,
    init_params as cnn_init,
    make_ps_train_step as make_cnn_step,
    toy_batch as cnn_batch,
)
from pslite_tpu.models.dlrm import (
    DLRMConfig,
    make_train_step as make_dlrm_step,
    toy_batch as dlrm_batch,
)
from pslite_tpu.parallel import CollectiveEngine, default_mesh
from pslite_tpu.parallel.sparse import SparseEngine


def test_cnn_forward_shapes():
    cfg = CNNConfig()
    params = cnn_init(jax.random.PRNGKey(0), cfg)
    images = jnp.zeros((2, cfg.image_size, cfg.image_size, 3))
    logits = jax.jit(lambda p, x: cnn_forward(p, x, cfg))(params, images)
    assert logits.shape == (2, cfg.num_classes)
    assert np.isfinite(np.asarray(logits)).all()


def test_cnn_ps_training_loss_decreases():
    cfg = CNNConfig(num_classes=4, channels=(8, 16), image_size=8)
    mesh = default_mesh(axis_name="dp")
    step, store, batch_sharding = make_cnn_step(cfg, mesh, lr=0.05)
    images, labels = cnn_batch(cfg, batch=32, seed=0)
    images = jax.device_put(images, batch_sharding)
    labels = jax.device_put(labels, batch_sharding)
    losses = []
    for _ in range(12):
        store, loss = step(store, images, labels)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9, losses


def test_dlrm_hybrid_training_loss_decreases():
    cfg = DLRMConfig(num_rows=256, emb_dim=8, num_cat=3, num_dense=4,
                     hidden=32)
    mesh = default_mesh()
    engine = CollectiveEngine(mesh=mesh)
    sparse = SparseEngine(mesh, engine.axis)
    step = make_dlrm_step(cfg, engine, sparse, lr=0.2)
    W = engine.num_shards
    idx, dense, labels = dlrm_batch(cfg, workers=W, batch=16, seed=1)
    losses = [float(step(idx, dense, labels)) for _ in range(15)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.95, losses
    # The embedding table actually learned (rows moved away from zero).
    table = np.asarray(sparse.store_array("dlrm_emb"))
    assert np.abs(table).max() > 0


def test_dlrm_row_adagrad_training_loss_decreases():
    """DLRM with the fused row-wise Adagrad embedding optimizer learns
    (and exercises the accumulator across steps)."""
    cfg = DLRMConfig(num_rows=256, emb_dim=8, num_cat=3, num_dense=4,
                     hidden=32)
    mesh = default_mesh()
    engine = CollectiveEngine(mesh=mesh)
    sparse = SparseEngine(mesh, engine.axis)
    step = make_dlrm_step(cfg, engine, sparse, lr=0.2,
                          emb_optimizer="row_adagrad")
    W = engine.num_shards
    idx, dense, labels = dlrm_batch(cfg, workers=W, batch=16, seed=1)
    losses = [float(step(idx, dense, labels)) for _ in range(15)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.95, losses
    acc = np.asarray(sparse.acc_array("dlrm_emb"))
    assert (acc > 0).any()  # accumulator actually tracked G^2
