"""Tail-based request tracing (docs/observability.md): keep policy,
live TRACE_PULL assembly, critical-path attribution, exemplars, and
the batch-plane observer-effect fix."""

import os
import sys
import time

import numpy as np
import pytest

from pslite_tpu import KVServer, KVServerDefaultHandle, KVWorker
from pslite_tpu.environment import Environment
from pslite_tpu.telemetry.critical_path import STAGES
from pslite_tpu.telemetry.metrics import Histogram, Registry
from pslite_tpu.telemetry.trace_store import TailPolicy, TraceCollector
from pslite_tpu.telemetry.tracing import Tracer
from pslite_tpu.utils.logging import CheckError

from helpers import LoopbackCluster

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


# -- keep policy -------------------------------------------------------------


def test_tail_policy_parse():
    p = TailPolicy.parse("slow:p95,errors,floor:0.001")
    assert p.slow_q == 0.95 and p.errors and p.floor == 0.001
    # Bare truthy value expands to the default spec.
    d = TailPolicy.parse("1")
    assert d.slow_q == 0.95 and d.errors and d.floor == 0.001
    assert TailPolicy.parse(None) is None
    assert TailPolicy.parse("0") is None
    assert TailPolicy.parse("off") is None
    only_err = TailPolicy.parse("errors")
    assert only_err.errors and only_err.slow_q is None \
        and only_err.floor == 0.0
    with pytest.raises(CheckError):
        TailPolicy.parse("slow:p95,bogus")
    with pytest.raises(CheckError):
        TailPolicy.parse("floor:2.0")


def _tail_tracer(spec, metrics=None):
    return Tracer(Environment({"PS_TRACE_TAIL": spec}), "worker",
                  metrics=metrics)


def test_tail_keep_slow_kept_fast_dropped():
    tr = _tail_tracer("slow:p95,floor:0")
    assert tr.active and tr.tail is not None
    h = Histogram("kv.pull_latency_s")
    for _ in range(200):
        h.observe(0.001)
    tr.set_tail_source("pull", h)
    # Fast request (at the population's bulk): dropped.
    assert tr.tail_keep(0.001, "pull") is None
    # 10x the p95: kept, with the slow reason.
    assert tr.tail_keep(0.05, "pull") == "slow>p95"
    # A COLD path (no source, no hint): slow rule inactive — nothing
    # kept under this spec (floor 0, no errors).
    assert tr.tail_keep(10.0, "push") is None


def test_tail_keep_error_always_kept_floor_uniform():
    tr = _tail_tracer("errors")
    # Errors keep regardless of latency; the reason is the outcome.
    assert tr.tail_keep(1e-6, "push", outcome="shed") == "shed"
    assert tr.tail_keep(1e-6, "pull", outcome="timeout") == "timeout"
    assert tr.tail_keep(1e-6, "push") is None  # no floor, no slow
    everything = _tail_tracer("floor:1.0")
    assert everything.tail_keep(1e-6, "push") == "floor"
    # Legacy head-sampled mode: the decision was made up front.
    legacy = Tracer(Environment({"PS_TRACE_SAMPLE": "1"}), "worker")
    assert legacy.tail_keep(1e-6, "push") == "sampled"


def test_trace_pull_hints_override_local_histogram():
    tr = _tail_tracer("slow:p95")
    h = Histogram("kv.push_latency_s")
    for _ in range(100):
        h.observe(0.010)
    tr.set_tail_source("push", h)
    local = tr.tail_threshold("push")
    assert local is not None and 0.005 < local < 0.02
    # A scheduler hint (windowed cluster p95) outranks the local view.
    tr.note_hints({"push": {"p95": 0.5}, "pull": {"p95": 0.25}})
    assert tr.tail_threshold("push") == 0.5
    assert tr.tail_threshold("pull") == 0.25
    # Stale hints fall back to the local histogram.
    tr.HINT_TTL_S = 0.0
    assert abs(tr.tail_threshold("push") - local) < 1e-9


def test_tail_ids_unique_and_ring_evicts_oldest():
    reg = Registry()
    tr = _tail_tracer("floor:1.0", metrics=reg)
    ids = {tr.begin_request() for _ in range(1000)}
    assert len(ids) == 1000 and 0 not in ids
    tr.MAX_EVENTS = 4
    for i in range(10):
        tr.span(i + 1, "request", float(i), 1.0)
    assert tr.num_events == 4
    evs, evicted = tr.drain()
    # Oldest evicted, newest retained (ring, not drop-newest).
    assert [e["ts"] for e in evs] == [6.0, 7.0, 8.0, 9.0]
    assert evicted == 6
    assert reg.snapshot()["counters"]["trace.ring_evictions"] == 6
    assert tr.num_events == 0  # drained


# -- exemplars ---------------------------------------------------------------


def test_exemplar_slots_bounded_and_rendered():
    import psmon

    h = Histogram("kv.pull_latency_s")
    # Distinct buckets beyond the cap: oldest-walled slots evict.
    for i in range(Histogram.EXEMPLAR_SLOTS + 4):
        v = 1e-5 * (2 ** i)
        h.observe(v)
        h.attach_exemplar(v, 0x1000 + i, wall=float(i))
    ex = h.exemplars()
    assert len(ex) == Histogram.EXEMPLAR_SLOTS
    walls = sorted(w for _t, _v, w in ex.values())
    assert walls[0] == 4.0  # the 4 oldest evicted
    # Same-bucket attach overwrites in place (no growth).
    h.attach_exemplar(1e-5 * (2 ** 11), 0xBEEF, wall=99.0)
    assert len(h.exemplars()) == Histogram.EXEMPLAR_SLOTS
    snap = h.snapshot()
    assert len(snap["exemplars"]) == Histogram.EXEMPLAR_SLOTS
    cluster_snap = {9: {
        "role": "worker",
        "metrics": {"counters": {}, "gauges": {},
                    "histograms": {"kv.pull_latency_s": snap},
                    "topk": {}},
    }}
    # OpenMetrics rendering carries the exemplar + # EOF; the classic
    # 0.0.4 rendering must NOT (its parsers reject exemplar syntax).
    om = psmon.to_prometheus(cluster_snap, openmetrics=True)
    assert '# {trace_id="beef"}' in om and om.rstrip().endswith("# EOF")
    plain = psmon.to_prometheus(cluster_snap)
    assert "trace_id" not in plain and "# EOF" not in plain
    # serve() negotiates on the Accept header.
    import urllib.request

    httpd = psmon.serve(lambda: cluster_snap, 0)
    try:
        port = httpd.server_address[1]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/metrics",
            headers={"Accept": "application/openmetrics-text"})
        with urllib.request.urlopen(req) as resp:
            assert "openmetrics" in resp.headers["Content-Type"]
            assert b"trace_id" in resp.read()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics") as resp:
            assert "0.0.4" in resp.headers["Content-Type"]
            assert b"trace_id" not in resp.read()
    finally:
        httpd.shutdown()
    h.reset()
    assert h.exemplars() == {}


# -- collector assembly ------------------------------------------------------


def _span(tid, name, ts, dur=0.0, **args):
    a = {"trace": f"{tid:x}"}
    a.update(args)
    return {"name": name, "ph": "X" if dur else "i", "ts": ts,
            "dur": dur, "tid": 1, "args": a}


def test_collector_missing_node_partials_retire_on_ttl():
    coll = TraceCollector(ttl_s=0.05)
    # Server-side spans arrived, but the worker (which holds the root)
    # is MISSING from the pull — the trace must not linger forever.
    coll.ingest(10, "server", [_span(7, "apply", 100.0, 5.0)])
    assert len(coll) == 1 and coll.assembled() == []
    assert coll.retire(now=time.monotonic() + 1.0) == 1
    assert len(coll) == 0
    # A rooted trace survives retirement even with servers missing.
    coll.ingest(9, "worker", [_span(8, "request", 0.0, 50.0,
                                    keep="floor", pull=False)])
    coll.retire(now=time.monotonic() + 1.0)
    asm = coll.assembled()
    assert len(asm) == 1
    b = asm[0].breakdown()
    # No checkpoints at all: the whole wall folds into completion —
    # the sum identity holds regardless of which nodes answered.
    assert abs(sum(b["stages"].values()) - b["wall_us"]) < 1e-6
    assert b["stages"]["completion"] == b["wall_us"]


def test_collector_bounded_eviction():
    coll = TraceCollector(ttl_s=60.0, max_traces=16)
    for i in range(40):
        coll.ingest(10, "server", [_span(i + 1, "apply", float(i), 1.0)])
    assert len(coll) == 16 and coll.evicted == 24


# -- live cluster: capture, pull, assembly, attribution ----------------------


def _boot(cluster):
    servers = []
    for po in cluster.servers:
        s = KVServer(0, postoffice=po)
        s.set_request_handle(KVServerDefaultHandle())
        servers.append(s)
    workers = [KVWorker(0, 0, postoffice=po) for po in cluster.workers]
    return servers, workers


def _stop_all(cluster, servers, workers):
    for w in workers:
        w.stop()
    for s in servers:
        s.stop()
    cluster.finalize()


def test_tail_capture_live_assembly_and_exemplars():
    """floor:1.0 keeps every request: a storm's traces assemble live
    over TRACE_PULL, each breakdown's stages sum exactly to its wall,
    and kept ids land as exemplars on the latency histograms."""
    import psmon

    cluster = LoopbackCluster(
        num_workers=2, num_servers=2,
        env_extra={"PS_TRACE_TAIL": "floor:1.0"},
    )
    cluster.start()
    servers, workers = [], []
    try:
        servers, workers = _boot(cluster)
        keys = np.array([3, 2 ** 62, 2 ** 63 + 9], dtype=np.uint64)
        vals = np.ones(len(keys) * 32, np.float32)
        out = np.zeros_like(vals)
        for _ in range(8):
            tss = [w.push(keys, vals) for w in workers]
            for w, ts in zip(workers, tss):
                w.wait(ts)
        workers[0].wait(workers[0].pull(keys, out))
        coll = cluster.scheduler.collect_cluster_traces(timeout_s=10)
        asm = coll.assembled()
        assert len(asm) >= 17  # 16 pushes + 1 pull, all kept
        server_pids = {po.van.my_node.id for po in cluster.servers}
        saw_server = False
        for tr in asm:
            b = tr.breakdown()
            assert set(b["stages"]) == set(STAGES)
            assert all(v >= 0.0 for v in b["stages"].values())
            # The acceptance identity: stages partition the wall.
            assert abs(sum(b["stages"].values()) - b["wall_us"]) \
                <= max(1e-6, 0.001 * b["wall_us"])
            assert b["keep"] == "floor"
            if b["server"] in server_pids:
                saw_server = True
                assert b["stages"]["apply"] > 0.0 or \
                    b["stages"]["server_queue"] >= 0.0
        assert saw_server, "no trace assembled server-side spans"
        # Kept ids attached as exemplars; the scrape renders them.
        snap = cluster.scheduler.collect_cluster_metrics(timeout_s=10)
        wsnap = next(s for s in snap.values() if s["role"] == "worker")
        hist = wsnap["metrics"]["histograms"]["kv.push_latency_s"]
        assert hist.get("exemplars"), "kept traces left no exemplars"
        assert "# {trace_id=" in psmon.to_prometheus(snap,
                                                     openmetrics=True)
        # A second pull drains fresh spans only (rings emptied) and
        # keeps the earlier traces in the collector.
        n_before = len(coll)
        workers[0].wait(workers[0].push(keys, vals))
        coll2 = cluster.scheduler.collect_cluster_traces(timeout_s=10)
        assert coll2 is coll and len(coll2) >= n_before
    finally:
        _stop_all(cluster, servers, workers)


def test_error_outcome_always_kept():
    """spec='errors': fast clean requests drop, a handler failure's
    trace is kept with the outcome as the keep reason."""

    class Boom:
        def __call__(self, meta, kvs, server):
            if meta.push:
                raise RuntimeError("boom")
            server.response(meta)

    cluster = LoopbackCluster(
        num_workers=1, num_servers=1,
        env_extra={"PS_TRACE_TAIL": "errors", "PS_APPLY_SHARDS": "0"},
    )
    cluster.start()
    servers, workers = [], []
    try:
        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(Boom())
        servers = [srv]
        workers = [KVWorker(0, 0, postoffice=cluster.workers[0])]
        keys = np.array([3], dtype=np.uint64)
        vals = np.ones(4, np.float32)
        with pytest.raises(RuntimeError):
            workers[0].wait(workers[0].push(keys, vals))
        coll = cluster.scheduler.collect_cluster_traces(timeout_s=10)
        asm = coll.assembled()
        assert len(asm) == 1
        b = asm[0].breakdown()
        assert b["keep"] == "error" and b["outcome"] == "error"
    finally:
        _stop_all(cluster, servers, workers)


# -- observer effect: traced ops ride the batch plane ------------------------


def test_traced_run_frame_parity_with_untraced():
    """A traced storm produces the SAME frame count as an untraced
    one: the combiner merges traced ops (ids in the per-op table)
    instead of forcing them out as singles."""
    from pslite_tpu.kv.batching import OpCombiner
    from pslite_tpu.message import Message
    from pslite_tpu.sarray import SArray

    def mk(ts, trace):
        m = Message()
        mm = m.meta
        mm.app_id = 1
        mm.request = True
        mm.push = True
        mm.head = 0
        mm.timestamp = ts
        mm.recver = 8
        m.add_data(SArray(np.array([ts], np.uint64)))
        m.add_data(SArray(np.ones(4, np.float32)))
        mm.trace = trace
        return m

    def frames_for(traces):
        import time as _t

        sent = []
        c = OpCombiner(sent.append, lambda msgs, exc: None,
                       max_bytes=1 << 20)
        # Deterministic: enqueue the whole run, take the group once,
        # flush — exactly what one dispatcher pickup does mid-storm.
        key = None
        with c._cv:
            for i in range(10):
                key, _grp, _ = c._enqueue_locked(mk(i, traces[i]),
                                                 _t.monotonic())
            taken = c._take_locked(key)
        c._stop = True  # no dispatcher thread needed for this test
        c._flush(taken)
        return sent

    untraced = frames_for([0] * 10)
    traced = frames_for([0x100 + i for i in range(10)])
    assert len(untraced) == len(traced) == 1  # one merged frame each
    assert len(traced[0].meta.batch.ops) == 10
    assert [op.trace for op in traced[0].meta.batch.ops] == [
        0x100 + i for i in range(10)]
    assert all(op.trace == 0 for op in untraced[0].meta.batch.ops)


def test_batch_table_trace_wire_roundtrip():
    """The per-op trace id survives the EXT_BATCH wire table, and an
    all-untraced table packs byte-identical to a pre-trace build."""
    from pslite_tpu import wire
    from pslite_tpu.message import BatchInfo, BatchOp, Meta

    meta = Meta(app_id=1, request=True, push=True, timestamp=3,
                sender=9, recver=8)
    meta.batch = BatchInfo(ops=(
        BatchOp(push=True, timestamp=1, key=10, val_len=16, nseg=2,
                trace=0xABCDEF0123),
        BatchOp(pull=True, timestamp=2, key=20, val_len=16, nseg=2),
    ))
    out = wire.unpack_meta(wire.pack_meta(meta))
    assert out.batch.ops[0].trace == 0xABCDEF0123
    assert out.batch.ops[1].trace == 0
    untraced = Meta(app_id=1, request=True, push=True, timestamp=3,
                    sender=9, recver=8)
    untraced.batch = BatchInfo(ops=(
        BatchOp(push=True, timestamp=1, key=10, val_len=16, nseg=2),
    ))
    buf = wire.pack_meta(untraced)
    # trace=0 adds NOTHING: byte-for-byte what an untraced build packs.
    assert b"".join([buf]) == wire.pack_meta(untraced)
    traced = Meta(app_id=1, request=True, push=True, timestamp=3,
                  sender=9, recver=8)
    traced.batch = BatchInfo(ops=(
        BatchOp(push=True, timestamp=1, key=10, val_len=16, nseg=2,
                trace=5),
    ))
    assert len(wire.pack_meta(traced)) == len(buf) + 8  # one u64


def test_multi_get_traced_fanin_spans_and_merging():
    """PR 11 path: a traced multi_get fan-out still coalesces into
    EXT_BATCH frames (one per contacted server), every sub-get's root
    span links the shared parent id, and apply spans land on BOTH
    servers."""
    cluster = LoopbackCluster(
        num_workers=1, num_servers=2,
        env_extra={"PS_TRACE_TAIL": "floor:1.0",
                   "PS_BATCH_BYTES": "65536",
                   "PS_BATCH_NEGOTIATE": "0"},
    )
    cluster.start()
    servers, workers = [], []
    try:
        servers, workers = _boot(cluster)
        w = workers[0]
        rows = [np.array([k], dtype=np.uint64)
                for k in (3, 5, 2 ** 63 + 9, 2 ** 63 + 11)]
        vals = np.ones(16, np.float32)
        for r in rows:
            w.wait(w.push(r, vals))
        handle = w.multi_get(rows, val_len=16)
        handle.wait()
        for i, r in enumerate(rows):
            np.testing.assert_array_equal(handle.outs[i], vals)
        # Traced sub-gets MERGED: request-direction EXT_BATCH frames
        # left this worker (the observer-effect fix, end to end).
        wm = cluster.workers[0].metrics.snapshot()["counters"]
        assert wm.get("van.batched_frames", 0) >= 1
        assert wm.get("van.batch_ops", 0) > wm.get(
            "van.batched_frames", 0)
        coll = cluster.scheduler.collect_cluster_traces(timeout_s=10)
        roots = [t.root for t in coll.assembled()]
        parents = {}
        for r in roots:
            p = (r.get("args") or {}).get("parent")
            if p:
                parents.setdefault(p, []).append(r)
        assert parents, "no sub-get linked a multi_get parent"
        fan = max(parents.values(), key=len)
        assert len(fan) == len(rows)  # one parent spans the fan-out
        # The children's assembled trees cover BOTH servers' applies.
        tids = {(r["args"] or {})["trace"] for r in fan}
        apply_pids = set()
        for tid in tids:
            tr = coll.get(tid)
            for ev in tr.spans:
                if ev["name"] == "apply":
                    apply_pids.add(ev["pid"])
        assert apply_pids == {po.van.my_node.id
                              for po in cluster.servers}
    finally:
        _stop_all(cluster, servers, workers)


def test_psmon_watch_critical_path_footer():
    """psmon --watch appends the tail critical-path footer when handed
    the scheduler's trace collector."""
    import psmon

    from pslite_tpu.telemetry.timeseries import ClusterHistory

    hist = ClusterHistory(po=None, env=None, interval_s=1.0)
    coll = TraceCollector()
    frame = psmon.format_watch(hist, traces=coll)
    assert "critical path: no assembled tail traces" in frame
    coll.ingest(9, "worker", [
        _span(5, "request", 0.0, 1000.0, keep="slow>p95"),
    ])
    frame = psmon.format_watch(hist, traces=coll)
    assert "critical path (1 tail traces" in frame
    assert "completion" in frame  # root-only trace: all wall there


# -- crash safety ------------------------------------------------------------


def test_periodic_flush_is_crash_safe(tmp_path):
    tr = Tracer(Environment({"PS_TRACE_TAIL": "floor:1.0",
                             "PS_TRACE_DIR": str(tmp_path),
                             "PS_TRACE_FLUSH_S": "0.1"}), "worker")
    tr.node_id = 9
    tr.span(0x77, "request", 0.0, 5.0)
    deadline = time.monotonic() + 5.0
    path = tr.default_path()
    while not os.path.exists(path) and time.monotonic() < deadline:
        time.sleep(0.05)
    # No export()/Van.stop() ever ran — the background flush wrote it.
    assert os.path.exists(path)
    import json

    doc = json.load(open(path))
    assert any(e.get("name") == "request" for e in doc["traceEvents"])


# -- acceptance: chaos delay pinned by the attribution -----------------------


def test_chaos_delay_pins_wire_stage_on_slow_server():
    """E2E proof (ISSUE 13): a real-TCP 2w+2s cluster with a chaos
    receive delay on ONE server — the assembled tail's critical-path
    attribution pins the injected stage (wire) on the slow server,
    and every breakdown sums to its wall."""
    import pstrace
    from pslite_tpu.benchmark import _teardown_cluster

    nodes = pstrace._demo_cluster(slow_server_delay_ms=(8, 16))
    sched, server_pos, worker_pos = nodes[0], nodes[1:3], nodes[3:]
    slow_pid = server_pos[1].van.my_node.id
    servers, workers = [], []
    try:
        for po in server_pos:
            s = KVServer(0, postoffice=po)
            s.set_request_handle(KVServerDefaultHandle())
            servers.append(s)
        workers = [KVWorker(0, 0, postoffice=po) for po in worker_pos]
        keys = np.array([3, 2 ** 62, 2 ** 63 + 9, 2 ** 63 + 2 ** 62],
                        dtype=np.uint64)
        vals = np.ones(len(keys) * 64, np.float32)
        out = np.zeros_like(vals)
        for i in range(30):
            tss = [w.push(keys, vals) for w in workers]
            for w, ts in zip(workers, tss):
                w.wait(ts)
            if i % 5 == 4:
                workers[0].wait(workers[0].pull(keys, out))
        coll = pstrace.collect(sched, timeout_s=10)
        rows = coll.breakdowns()
        assert rows, "no tail traces assembled"
        for b in rows:
            assert abs(sum(b["stages"].values()) - b["wall_us"]) \
                <= max(1e-6, 0.001 * b["wall_us"])
        agg = coll.aggregate()
        # The slow set's dominant stage is the injected one, and its
        # critical server is the chaos-delayed node.
        assert agg["top_stage"] == "wire", agg
        slow_rows = sorted(rows, key=lambda b: -b["wall_us"])
        top = slow_rows[:max(1, len(slow_rows) // 4)]
        pinned = [b for b in top if b["server"] == slow_pid]
        assert len(pinned) >= len(top) * 0.7, (
            f"slow traces not pinned to the delayed server: "
            f"{[(b['server'], round(b['wall_us'])) for b in top]}"
        )
        # The CLI renderers digest the same collector.
        table = pstrace.format_top(coll)
        assert "tail lives in: wire" in table
        slowest = pstrace.format_slowest(coll, 3)
        assert "wall=" in slowest and "server=" in slowest
    finally:
        _teardown_cluster(nodes, workers, servers)
