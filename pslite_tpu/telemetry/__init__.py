"""Cluster-wide telemetry: metrics registry + distributed tracing.

The read-side mirror of the perf/fault tiers (send lanes, sharded
apply, deadlines/failover, replication): every hot path publishes
counters/gauges/histograms into a per-node :class:`~.metrics.Registry`,
request lifecycles are stitched across processes by
:class:`~.tracing.Tracer` trace ids carried in ``Message.meta``, and
the scheduler can snapshot every node's registry over the control plane
(``Command.METRICS_PULL`` — see ``tools/psmon.py``).

On top of the point-in-time plane sits the CONTINUOUS tier
(docs/observability.md): :class:`~.timeseries.ClusterHistory` (a
scheduler-side sampler deriving windowed rates/quantiles from snapshot
deltas), the :class:`~.health.Watchdog` SLO rules it feeds
(``Postoffice.health()``), and the per-node
:class:`~.flight.FlightRecorder` fault ring dumped on abnormal
shutdown.

Env knobs (docs/observability.md):

- ``PS_TELEMETRY`` (default 1): 0 swaps every instrument for a shared
  no-op singleton — near-zero cost, empty snapshots.
- ``PS_TRACE_SAMPLE`` (default 0): probability in [0, 1] that a
  ``KVWorker.push/pull`` mints a trace id (legacy head sampling).
- ``PS_TRACE_TAIL`` (default off): tail-based capture spec
  (``slow:p95,errors,floor:0.001``) — every request is stamped, the
  worker keeps only interesting traces at completion, and the
  scheduler assembles them live over ``Command.TRACE_PULL``
  (:class:`~.trace_store.TraceCollector`, ``tools/pstrace.py``).
- ``PS_TRACE_DIR``: directory for the per-node Chrome trace-event JSON
  exports and flight-recorder dumps (default: system tempdir).
- ``PS_TRACE_RING`` / ``PS_TRACE_FLUSH_S``: span-ring capacity and the
  crash-safe periodic export interval.
- ``PS_METRICS_INTERVAL`` (default 0 = off): the scheduler's
  background METRICS_PULL sampling period in seconds.
- ``PS_METRICS_HISTORY`` (default 512): snapshots retained per node.
- ``PS_SLO``: watchdog threshold overrides (``rule=warn:crit``).
- ``PS_FLIGHT_EVENTS`` (default 1024): flight-recorder ring size.
"""

from .flight import FlightRecorder, NULL_FLIGHT  # noqa: F401
from .health import (  # noqa: F401
    CRIT,
    HealthEvent,
    INFO,
    Rule,
    WARN,
    Watchdog,
    parse_slo,
)
from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    NULL_REGISTRY,
    Registry,
    TopK,
    bucket_quantile,
    merge_bucket_lists,
)
from .timeseries import ClusterHistory, NodeSeries  # noqa: F401
from .trace_store import (  # noqa: F401
    AssembledTrace,
    TailPolicy,
    TraceCollector,
)
from .tracing import NULL_TRACER, Tracer  # noqa: F401
