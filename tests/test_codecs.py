"""Quantized transport tier, end to end (docs/compression.md).

The codec math is covered in test_ops.py; this file proves the TIER:
bucket registration, the EXT_CODEC framing surviving chunking /
replication forwards / the native plane, compressed-forward wire
savings, the bit-identical end-state matrix, and the telemetry
surface (codec counters, ef gauge, psmon's compression column).
"""

import sys

import numpy as np
import pytest

sys.path.insert(0, "tests")
from helpers import LoopbackCluster  # noqa: E402

from pslite_tpu.kv.kv_app import (  # noqa: E402
    KVServer,
    KVServerDefaultHandle,
    KVWorker,
)
from pslite_tpu.ops import codecs  # noqa: E402


def _cluster_run(env_extra=None, codec="int8", pushes=3, seed=11,
                 num_servers=2, val_len=4096, pulls=True,
                 concurrent=False):
    """Deterministic compressed push/pull storm; returns (final pulled
    vals, per-node van byte counters snapshot).  ``concurrent=True``
    issues every push before the first wait — the shape that engages
    the small-op combiner (docs/batching.md) when PS_BATCH_BYTES is
    set; per-destination frame order still equals issue order, so the
    end state must stay bit-identical either way."""
    cl = LoopbackCluster(num_workers=1, num_servers=num_servers,
                         env_extra=env_extra or {})
    cl.start()
    servers = []
    out = None
    try:
        for po in cl.servers:
            s = KVServer(0, postoffice=po)
            s.set_request_handle(KVServerDefaultHandle())
            servers.append(s)
        w = KVWorker(0, 0, postoffice=cl.workers[0])
        span = (1 << 64) // max(num_servers, 1)
        keys = np.sort(np.array(
            [r * span + off
             for r in range(num_servers) for off in (3, 1000)],
            dtype=np.uint64,
        ))
        rng = np.random.default_rng(seed)
        w.register_bucket(keys, codec=codec)
        tss = []
        for _ in range(pushes):
            vals = rng.normal(size=len(keys) * val_len).astype(
                np.float32
            )
            ts = w.push(keys, vals)
            if concurrent:
                tss.append(ts)
            else:
                w.wait(ts)
        for ts in tss:
            w.wait(ts)
        out = np.zeros(len(keys) * val_len, np.float32)
        if pulls:
            w.wait(w.pull(keys, out, codec="raw"))
        stats = {
            f"server{i}": po.van.send_bytes
            for i, po in enumerate(cl.servers)
        }
        stats["worker"] = cl.workers[0].van.send_bytes
        w.stop()
    finally:
        for s in servers:
            s.stop()
        cl.finalize()
    return out, stats


def test_register_bucket_routes_and_overrides():
    """register_bucket makes the codec the default for exactly those
    keys; per-call codec='raw' overrides; unknown codecs fail loudly."""
    cl = LoopbackCluster(num_workers=1, num_servers=1)
    cl.start()
    try:
        srv = KVServer(0, postoffice=cl.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        w = KVWorker(0, 0, postoffice=cl.workers[0])
        keys = np.array([5, 9], dtype=np.uint64)
        vals = np.linspace(-1, 1, 2 * 512).astype(np.float32)
        with pytest.raises(Exception):
            w.register_bucket(keys, codec="no_such_codec")
        w.register_bucket(keys, codec="int8")
        before = cl.workers[0].van.send_bytes
        w.wait(w.push(keys, vals))  # bucket codec applies
        wire_compressed = cl.workers[0].van.send_bytes - before
        assert wire_compressed < vals.nbytes / 3
        before = cl.workers[0].van.send_bytes
        w.wait(w.push(keys, vals, codec="raw"))  # explicit override
        wire_raw = cl.workers[0].van.send_bytes - before
        assert wire_raw > vals.nbytes
        # Different keys: no bucket match, travels raw.
        other = np.array([7], dtype=np.uint64)
        before = cl.workers[0].van.send_bytes
        w.wait(w.push(other, np.ones(512, np.float32)))
        assert cl.workers[0].van.send_bytes - before > 512 * 4
        # Unregister restores raw.
        w.register_bucket(keys, codec=None)
        before = cl.workers[0].van.send_bytes
        w.wait(w.push(keys, vals))
        assert cl.workers[0].van.send_bytes - before > vals.nbytes
        w.stop()
        srv.stop()
    finally:
        cl.finalize()


@pytest.mark.parametrize("codec", ["int8", "fp8_e4m3", "bf16"])
def test_chunked_vs_monolithic_compressed_bit_exact(codec):
    """Satellite (ISSUE 7): compressed pushes/pulls under small
    PS_CHUNK_BYTES (scales land in the LAST chunks, any arrival order)
    must decode bit-identically to monolithic sends."""
    if codec not in codecs.names():
        pytest.skip(f"{codec} unavailable")
    mono, _ = _cluster_run(env_extra={"PS_CHUNK_BYTES": "0"},
                           codec=codec)
    chunked, _ = _cluster_run(env_extra={"PS_CHUNK_BYTES": "4096"},
                              codec=codec)
    np.testing.assert_array_equal(mono, chunked)


def test_compressed_replication_forwards_compressed_bytes():
    """Satellite (ISSUE 7): with k=2 replication, the forward hop
    re-sends the COMPRESSED payload — the primary's wire bytes toward
    its replica shrink ~4x vs the old decompress-and-resend — and the
    replica's store stays bit-exact with the primary's."""
    env = {"PS_KV_REPLICATION": "2"}
    cl = LoopbackCluster(num_workers=1, num_servers=2, env_extra=env)
    cl.start()
    servers = []
    try:
        for po in cl.servers:
            s = KVServer(0, postoffice=po)
            s.set_request_handle(KVServerDefaultHandle())
            servers.append(s)
        w = KVWorker(0, 0, postoffice=cl.workers[0])
        # One key on server rank 0 only: its primary forwards every
        # accepted push to rank 1.
        keys = np.array([3], dtype=np.uint64)
        n = 256 * 1024
        vals = np.random.default_rng(0).normal(size=n).astype(
            np.float32
        )
        # Raw leg: forward re-sends the full float32 payload.
        before = cl.servers[0].van.send_bytes
        w.wait(w.push(keys, vals, codec="raw"))
        raw_fwd = cl.servers[0].van.send_bytes - before
        # Compressed leg: the forward carries codes+scales verbatim.
        before = cl.servers[0].van.send_bytes
        w.wait(w.push(keys, vals, codec="int8"))
        comp_fwd = cl.servers[0].van.send_bytes - before
        assert raw_fwd > vals.nbytes  # sanity: it really forwarded
        assert comp_fwd < raw_fwd / 3, (comp_fwd, raw_fwd)
        # Replica store bit-exact with the primary's.
        import time

        primary = servers[0]._handle.store[3]
        for _ in range(100):
            replica = servers[1]._handle.store.get(3)
            # Poll on CONTENT, not length: both pushes carry the same
            # key length, so a length match only proves the FIRST
            # forward landed — the int8 forward may still be in flight.
            if replica is not None and np.array_equal(primary, replica):
                break
            time.sleep(0.02)
        np.testing.assert_array_equal(primary, replica)
        w.stop()
    finally:
        for s in servers:
            s.stop()
        cl.finalize()


def test_matrix_bit_identical_end_state():
    """Acceptance (ISSUE 7): for a fixed input, compressed pushes
    produce BIT-IDENTICAL end state across PS_CHUNK_BYTES in
    {0, small}, PS_KV_REPLICATION in {1, 2}, and PS_NATIVE in {0, 1}
    — encode-once + deterministic codecs + arrival-order apply."""
    results = {}
    for chunk in ("0", "8192"):
        for repl in ("1", "2"):
            for nat in ("0", "1"):
                env = {
                    "PS_CHUNK_BYTES": chunk,
                    "PS_KV_REPLICATION": repl,
                    "PS_NATIVE": nat,
                }
                out, _ = _cluster_run(env_extra=env, codec="int8",
                                      pushes=2, val_len=2048)
                results[(chunk, repl, nat)] = out
    ref = results[("0", "1", "0")]
    for key, out in results.items():
        np.testing.assert_array_equal(ref, out, err_msg=str(key))


def test_matrix_batching_replication_codec_bit_identical():
    """Satellite (ISSUE 10): batching x replication x codec rows added
    to the existing PS_CHUNK_BYTES x PS_KV_REPLICATION x PS_NATIVE
    grid — CONCURRENTLY-issued compressed pushes end bit-identical
    with the small-op combiner on vs off (docs/batching.md: encode-
    once before the combiner + per-destination frame order == issue
    order + per-sub-op replication forwards in op order)."""
    results = {}
    for batch in ("0", "65536"):
        for repl in ("1", "2"):
            for nat in ("0", "1"):
                env = {
                    "PS_BATCH_BYTES": batch,
                    "PS_BATCH_NEGOTIATE": "0",
                    "PS_KV_REPLICATION": repl,
                    "PS_NATIVE": nat,
                }
                out, _ = _cluster_run(env_extra=env, codec="int8",
                                      pushes=4, val_len=512,
                                      concurrent=True)
                results[(batch, repl, nat)] = out
    ref = results[("0", "1", "0")]
    for key, out in results.items():
        np.testing.assert_array_equal(ref, out, err_msg=str(key))


def test_push_pull_honors_bucket_codec_on_push_leg():
    """register_bucket's contract covers the fused round trip: the
    PUSH leg travels encoded (wire shrinks ~4x), the response comes
    back raw, and the aggregated value lands within quantization
    error."""
    cl = LoopbackCluster(num_workers=1, num_servers=1)
    cl.start()
    try:
        srv = KVServer(0, postoffice=cl.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        w = KVWorker(0, 0, postoffice=cl.workers[0])
        keys = np.array([5], dtype=np.uint64)
        n = 64 * 1024
        vals = np.random.default_rng(2).normal(size=n).astype(np.float32)
        out = np.zeros_like(vals)
        w.register_bucket(keys, codec="int8")
        before = cl.workers[0].van.send_bytes
        w.wait(w.push_pull(keys, vals, out))
        wire = cl.workers[0].van.send_bytes - before
        assert wire < vals.nbytes / 3  # push leg compressed
        step = np.repeat(
            np.abs(vals).reshape(-1, 128).max(axis=1) / 127.0, 128
        )
        assert np.all(np.abs(out - vals) <= step * 0.51 + 1e-6)
        w.stop()
        srv.stop()
    finally:
        cl.finalize()


# psmon lives in tools/; make it importable like test_telemetry does.
import os  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))


def test_codec_telemetry_and_psmon_column():
    """Satellite (ISSUE 7): per-node codec.raw_bytes / codec.wire_bytes
    counters and the ef.residual_norm gauge land in the registry
    snapshot; psmon renders the compression-ratio column."""
    import psmon

    cl = LoopbackCluster(num_workers=1, num_servers=1)
    cl.start()
    try:
        srv = KVServer(0, postoffice=cl.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        w = KVWorker(0, 0, postoffice=cl.workers[0])
        keys = np.array([5], dtype=np.uint64)
        vals = np.random.default_rng(1).normal(size=64 * 1024).astype(
            np.float32
        )
        out = np.zeros_like(vals)
        w.register_bucket(keys, codec="int8")
        for _ in range(3):
            w.wait(w.push(keys, vals))
        w.wait(w.pull(keys, out))  # bucket codec: server encodes + EF
        wsnap = cl.workers[0].metrics.snapshot()
        raw = wsnap["counters"]["codec.raw_bytes"]
        wire_b = wsnap["counters"]["codec.wire_bytes"]
        assert raw == 3 * vals.nbytes
        assert 0 < wire_b < raw / 3
        # Worker-side EF bank registered its residual-norm gauge (3
        # pushes folded residuals; norm is nonzero mid-stream).
        assert wsnap["gauges"]["ef.residual_norm"] >= 0.0
        ssnap = cl.servers[0].metrics.snapshot()
        assert ssnap["counters"]["codec.raw_bytes"] == vals.nbytes
        assert ssnap["gauges"]["ef.residual_norm"] > 0.0
        # psmon: compression-ratio column present and populated.
        table = psmon.format_table(
            psmon.collect(cl.scheduler, timeout_s=10)
        )
        assert "cmpr" in table.splitlines()[0]
        rows = [ln for ln in table.splitlines() if " worker" in ln]
        assert rows and any(
            field not in ("-",) and float(field) > 2.0
            for ln in rows
            for field in [ln.split()[12]]
        ), table
        w.stop()
        srv.stop()
    finally:
        cl.finalize()
