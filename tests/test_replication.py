"""Server chain replication (PS_KV_REPLICATION, kv/replication.py):
forwarding bit-exactness, worker failover routing, recovered-server
state restore, and the kill-a-server-mid-push-storm acceptance scenario
(chaos crash hook + deadlines + replication, docs/fault_tolerance.md).
"""

import threading
import time

import numpy as np

from pslite_tpu import KVServer, KVServerDefaultHandle, KVWorker
from pslite_tpu.base import server_rank_to_id
from pslite_tpu.environment import Environment
from pslite_tpu.message import Role
from pslite_tpu.postoffice import Postoffice

from helpers import LoopbackCluster

# Keys in server rank 0's range and rank 1's range (uniform split of
# the uint64 key space over 2 servers).
K0 = np.array([7, 42], dtype=np.uint64)
K1 = np.array([2**63 + 5, 2**63 + 77], dtype=np.uint64)

FT_ENV = {
    "PS_KV_REPLICATION": "2",
    "PS_HEARTBEAT_INTERVAL": "0.3",
    "PS_HEARTBEAT_TIMEOUT": "1.0",
    "PS_REQUEST_TIMEOUT": "0.5",
    "PS_REQUEST_RETRIES": "5",
}


def _spin_up(cluster):
    servers = []
    for po in cluster.servers:
        s = KVServer(0, postoffice=po)
        s.set_request_handle(KVServerDefaultHandle())
        servers.append(s)
    workers = [
        KVWorker(0, 0, postoffice=po) for po in cluster.workers
    ]
    return servers, workers


def _by_rank(servers, rank):
    return next(
        s for s in servers
        if s.po.van.my_node.id == server_rank_to_id(rank)
    )


def _crash_teardown(cluster, servers, workers, dead_pos=()):
    for w in workers:
        w.stop()
    for s in servers:
        if s.po not in dead_pos:
            s.stop()
    # Stop EVERY van, dead ones included (idempotent): a chaos-crashed
    # victim's heartbeat/resender threads otherwise outlive the test and
    # spam delivery-failure warnings into the interpreter shutdown.
    for po in cluster.all_nodes():
        try:
            po.van.stop()
        except Exception:
            pass


def test_chain_forward_bit_exact():
    """Each accepted push chain-forwards to the next rank; because the
    forward stream preserves the primary's arrival order and the apply
    pool keys per-key order to arrival order, the replica's stored
    arrays are BIT-exact with the primary's."""
    cluster = LoopbackCluster(num_workers=1, num_servers=2,
                              env_extra={"PS_KV_REPLICATION": "2"})
    cluster.start()
    servers, workers = _spin_up(cluster)
    worker = workers[0]
    try:
        rng = np.random.default_rng(5)
        for _ in range(6):
            worker.wait(worker.push(
                K0, rng.standard_normal(2 * 16).astype(np.float32)))
            worker.wait(worker.push(
                K1, rng.standard_normal(2 * 16).astype(np.float32)))
        deadline = time.monotonic() + 10
        primary = _by_rank(servers, 0)
        replica = _by_rank(servers, 1)

        def _converged() -> bool:
            # Forwards are async: a key being PRESENT on the replica
            # does not mean every push has applied yet — poll until the
            # stores actually agree (the asserts below then re-check
            # and produce the real diagnostic on timeout).
            for ks, holder, copy in ((K0, primary, replica),
                                     (K1, replica, primary)):
                for k in ks:
                    a = holder._handle.store.get(int(k))
                    b = copy._handle.store.get(int(k))
                    if a is None or b is None or not np.array_equal(a, b):
                        return False
            return True

        while time.monotonic() < deadline and not _converged():
            time.sleep(0.05)
        for k in K0:
            # Bit-exact: float sums applied in the identical order.
            np.testing.assert_array_equal(
                replica._handle.store[int(k)],
                primary._handle.store[int(k)],
            )
        for k in K1:  # the chain wraps: rank1 forwards to rank0
            np.testing.assert_array_equal(
                primary._handle.store[int(k)],
                replica._handle.store[int(k)],
            )
        assert primary._replicator.forwarded > 0
    finally:
        for w in workers:
            w.stop()
        for s in servers:
            s.stop()
        cluster.finalize()


def test_failover_pull_and_push_after_kill():
    """After the detector declares rank 0 dead, the worker re-routes
    rank 0's key range to its first replica: pulls return the replicated
    values, pushes keep applying, and nothing hangs."""
    cluster = LoopbackCluster(num_workers=1, num_servers=2,
                              env_extra=FT_ENV)
    cluster.start()
    servers, workers = _spin_up(cluster)
    worker = workers[0]
    victim_po = _by_rank(servers, 0).po
    vals = np.ones(2 * 16, dtype=np.float32)
    try:
        for _ in range(3):
            worker.wait(worker.push(K0, vals))
        time.sleep(0.3)  # let forwards drain
        victim_po.van.stop()  # crash
        deadline = time.monotonic() + 15
        while (server_rank_to_id(0) not in worker._down_servers
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert server_rank_to_id(0) in worker._down_servers
        out = np.zeros_like(vals)
        t0 = time.monotonic()
        worker.wait(worker.pull(K0, out))
        assert time.monotonic() - t0 < 5.0
        np.testing.assert_array_equal(out, 3 * vals)
        # Pushes to the dead rank's range apply on the replica too.
        worker.wait(worker.push(K0, vals))
        out2 = np.zeros_like(vals)
        worker.wait(worker.pull(K0, out2))
        np.testing.assert_array_equal(out2, 4 * vals)
    finally:
        _crash_teardown(cluster, servers, workers, dead_pos=(victim_po,))


def test_recovered_server_restores_range_from_replica():
    """A recovered server pulls its range's state from its first
    replica BEFORE serving (REPLICA_FETCH) — replacing the old silently
    empty rejoin — and workers route back to it on recovery."""
    cluster = LoopbackCluster(num_workers=1, num_servers=2,
                              env_extra=FT_ENV)
    cluster.start()
    servers, workers = _spin_up(cluster)
    worker = workers[0]
    victim_po = _by_rank(servers, 0).po
    vals = np.arange(2 * 16, dtype=np.float32)
    try:
        worker.wait(worker.push(K0, vals))
        time.sleep(0.3)  # forwards drain
        victim_po.van.stop()
        deadline = time.monotonic() + 15
        while (server_rank_to_id(0) not in worker._down_servers
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert server_rank_to_id(0) in worker._down_servers

        repl_po = Postoffice(Role.SERVER, env=Environment(
            dict(cluster.base_env, **FT_ENV)))
        repl_po.start(0)
        assert repl_po.is_recovery
        assert repl_po.van.my_node.id == server_rank_to_id(0)
        handle = KVServerDefaultHandle()
        repl_srv = KVServer(0, postoffice=repl_po)
        repl_srv.set_request_handle(handle)  # restore happens here
        np.testing.assert_array_equal(handle.store[7], vals[:16])
        np.testing.assert_array_equal(handle.store[42], vals[16:])

        # The worker heard the recovery broadcast: rank 0 serves again.
        deadline = time.monotonic() + 15
        while (server_rank_to_id(0) in worker._down_servers
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert server_rank_to_id(0) not in worker._down_servers
        out = np.zeros_like(vals)
        worker.wait(worker.pull(K0, out))
        np.testing.assert_array_equal(out, vals)
        repl_srv.stop()
        repl_po.van.stop()
    finally:
        _crash_teardown(cluster, servers, workers, dead_pos=(victim_po,))


def test_kill_server_mid_push_storm_acceptance():
    """The acceptance scenario: a server crashes (chaos crash hook)
    mid-push-storm with PS_KV_REPLICATION=2 and PS_REQUEST_TIMEOUT set.
    Every worker completes, no wait() blocks past its retry budget, and
    the failed rank's key range served by the replica is bit-exact with
    a fault-free run of the identical schedule."""
    rounds, crash_after = 12, 8
    vals = np.ones(2 * 16, dtype=np.float32)  # exact float additions

    def run_storm(chaos: bool):
        per_node = (
            {"server0": {"PS_CHAOS": f"crash=recv:{crash_after}"}}
            if chaos else {}
        )
        cluster = LoopbackCluster(
            num_workers=2, num_servers=2,
            van_type="chaos+loopback" if chaos else "loopback",
            env_extra=dict(FT_ENV, PS_RESEND="1",
                           PS_RESEND_TIMEOUT="200"),
            per_node_env=per_node,
        )
        cluster.start()
        servers, workers = _spin_up(cluster)
        victim_po = _by_rank(servers, 0).po
        max_wait = [0.0]
        errors = []

        def storm(w):
            try:
                for _ in range(rounds):
                    for keys in (K0, K1):
                        t0 = time.monotonic()
                        w.wait(w.push(keys, vals))
                        max_wait[0] = max(
                            max_wait[0], time.monotonic() - t0)
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=storm, args=(w,), daemon=True)
                   for w in workers]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "storm hung"
        assert not errors, f"storm waits failed: {errors!r}"
        time.sleep(1.0)  # replication forwards drain
        out = np.zeros_like(vals)
        workers[0].wait(workers[0].pull(K0, out))
        dead = (victim_po,) if chaos else ()
        _crash_teardown(cluster, servers, workers, dead_pos=dead)
        if chaos:
            assert victim_po.van.chaos_crashed.is_set(), \
                "victim never crashed — scenario inert"
        return out, max_wait[0]

    faulty, faulty_max_wait = run_storm(chaos=True)
    clean, _ = run_storm(chaos=False)
    # Bit-exact: the replica-served range equals the fault-free run.
    np.testing.assert_array_equal(faulty, clean)
    np.testing.assert_array_equal(clean, 2 * rounds * vals)
    # No wait() blocked past its deadline budget: detection (~1.3s) +
    # backoff retries, far below the 120s join that would mark a hang.
    assert faulty_max_wait < 60.0, f"wait took {faulty_max_wait:.1f}s"
