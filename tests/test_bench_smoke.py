"""bench.py must stay runnable: exercise its measurement helper on the CPU
mesh and check the JSON contract fields."""

import json
import subprocess
import sys

import pytest

jax = pytest.importorskip("jax")


def test_measure_helper_runs():
    import bench
    from pslite_tpu.parallel.engine import CollectiveEngine

    eng = CollectiveEngine()
    wall, dev = bench._measure(
        eng, "smoke", num_keys=2, val_len=1024, iters=2
    )
    assert wall > 0
    assert dev is None  # CPU mesh: no TPU plane in the trace


def test_bench_cli_contract():
    import os

    # Force the child onto CPU: the axon sitecustomize would otherwise put
    # bench.py on the real TPU tunnel, coupling the unit suite to tunnel
    # health (JAX_PLATFORMS alone is overridden programmatically, so also
    # disable the axon registration).
    env = dict(
        os.environ,
        PS_BENCH_QUICK="1",
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
    )
    out = subprocess.run(
        [sys.executable, "bench.py"],
        capture_output=True,
        timeout=560,
        cwd="/root/repo",
        env=env,
    )
    assert out.returncode == 0, out.stderr.decode()[-1500:]
    lines = [l for l in out.stdout.decode().splitlines() if l.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for field in ("metric", "value", "unit", "vs_baseline"):
        assert field in rec
    assert rec["value"] > 0
