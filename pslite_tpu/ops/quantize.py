"""Blockwise int8 quantization kernels (Pallas, TPU).

Gradient compression for the DCN/TCP vans: the reference moves raw fp32
bytes; quantized push quarters wire bytes on bandwidth-limited links (the
EQuARX-style trade, PAPERS.md).  Symmetric per-row scaling: the flat vector
is laid out as rows of 128 lanes; each row gets ``scale = max|row| / 127``.
Tiles are ``(32, 128)`` (the int8 minimum), so rows are padded to a
multiple of 32.  Scales come back lane-replicated ``[rows, 128]``; send
``scales[:, 0]`` on the wire and re-broadcast on receive.

Kernels fall back to the Pallas interpreter off-TPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

QUANT_BLOCK = 128  # elements per scale (one lane row)
_TILE_ROWS = 32    # int8 min sublane tile


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def np_quantize_int8(x):
    """Host-side (numpy) variant for the DCN/TCP message path: flat fp32 ->
    (int8 [rows,128], fp32 scales [rows]).  Same layout/semantics as the
    Pallas kernel, minus lane replication."""
    import numpy as _np

    x = _np.asarray(x, dtype=_np.float32).reshape(-1)
    n = x.shape[0]
    pad = (-n) % QUANT_BLOCK
    if pad:
        x = _np.pad(x, (0, pad))
    rows = x.shape[0] // QUANT_BLOCK
    x2 = x.reshape(rows, QUANT_BLOCK)
    scales = _np.maximum(
        _np.abs(x2).max(axis=1) / 127.0, 1e-12
    ).astype(_np.float32)
    q = _np.clip(
        _np.rint(x2 / scales[:, None]), -127, 127
    ).astype(_np.int8)
    return q, scales, n


def np_dequantize_int8(q, scales, n: int):
    import numpy as _np

    x = q.astype(_np.float32) * _np.asarray(scales, _np.float32)[:, None]
    return x.reshape(-1)[:n]


def decode_int8_payload(q_sarray, scales_sarray, val_len: int):
    """Decode the wire layout of an int8-compressed message payload
    (data[1] = int8 codes, data[2] = fp32 scales, meta.val_len =
    uncompressed byte count) — the single decoder both directions of the
    message path share."""
    import numpy as _np

    q = q_sarray.astype_view(_np.int8).numpy().reshape(-1, QUANT_BLOCK)
    scales = scales_sarray.astype_view(_np.float32).numpy()
    return np_dequantize_int8(q, scales, val_len // 4)


@jax.jit
def quantize_int8(x):
    """flat fp32 -> (int8 ``[rows, 128]``, fp32 scales ``[rows, 128]``).

    Keep the original length for :func:`dequantize_int8`.
    """
    from jax.experimental import pallas as pl

    x = x.astype(jnp.float32).reshape(-1)
    pad = (-x.shape[0]) % (QUANT_BLOCK * _TILE_ROWS)
    if pad:
        x = jnp.pad(x, (0, pad))
    rows = x.shape[0] // QUANT_BLOCK
    x2 = x.reshape(rows, QUANT_BLOCK)
    grid = rows // _TILE_ROWS

    def kernel(x_ref, q_ref, s_ref):
        blk = x_ref[:, :]
        scale = jnp.maximum(
            jnp.max(jnp.abs(blk), axis=1, keepdims=True) / 127.0, 1e-12
        )
        q_ref[:, :] = jnp.clip(
            jnp.round(blk / scale), -127, 127
        ).astype(jnp.int8)
        s_ref[:, :] = jnp.broadcast_to(scale, blk.shape)

    spec = pl.BlockSpec((_TILE_ROWS, QUANT_BLOCK), lambda i: (i, 0))
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((rows, QUANT_BLOCK), jnp.int8),
            jax.ShapeDtypeStruct((rows, QUANT_BLOCK), jnp.float32),
        ),
        grid=(grid,),
        in_specs=[spec],
        out_specs=(spec, spec),
        interpret=_use_interpret(),
    )(x2)


@functools.partial(jax.jit, static_argnames=("n",))
def dequantize_int8(q, scales, n: int):
    """Inverse of :func:`quantize_int8`; ``n`` is the original length.

    ``scales`` may be lane-replicated ``[rows, 128]`` or compact
    ``[rows]``/``[rows, 1]`` (wire form) — re-broadcast as needed.
    """
    from jax.experimental import pallas as pl

    rows = q.shape[0]
    if scales.ndim == 1:
        scales = scales[:, None]
    if scales.shape[1] != QUANT_BLOCK:
        scales = jnp.broadcast_to(scales[:, :1], (rows, QUANT_BLOCK))

    def kernel(q_ref, s_ref, x_ref):
        x_ref[:, :] = q_ref[:, :].astype(jnp.float32) * s_ref[:, :]

    spec = pl.BlockSpec((_TILE_ROWS, QUANT_BLOCK), lambda i: (i, 0))
    x = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, QUANT_BLOCK), jnp.float32),
        grid=(rows // _TILE_ROWS,),
        in_specs=[spec, spec],
        out_specs=spec,
        interpret=_use_interpret(),
    )(q, jnp.asarray(scales, jnp.float32))
    return x.reshape(-1)[:n]
