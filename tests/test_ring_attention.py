"""Ring attention vs single-device reference on the 8-shard CPU mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from pslite_tpu.parallel.mesh import default_mesh, shard_map_compat
from pslite_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention,
)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference(causal):
    mesh = default_mesh(axis_name="sp")
    S = mesh.shape["sp"]
    B, T, H, D = 2, 4 * S, 3, 8
    rng = np.random.default_rng(0)
    q = rng.normal(size=(B, T, H, D)).astype(np.float32)
    k = rng.normal(size=(B, T, H, D)).astype(np.float32)
    v = rng.normal(size=(B, T, H, D)).astype(np.float32)

    ref = np.asarray(reference_attention(jnp.asarray(q), jnp.asarray(k),
                                         jnp.asarray(v), causal=causal))

    fn = shard_map_compat(
        lambda a, b, c: ring_attention(a, b, c, "sp", causal=causal),
        mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    out = np.asarray(jax.jit(fn)(q, k, v))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)
