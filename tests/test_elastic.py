"""Elastic end-to-end: crash -> keepalive restart -> dead-id recovery ->
cluster continues and finalizes cleanly.

Exercises the full reliability chain in one scenario: heartbeats
(PS_HEARTBEAT_*), scheduler dead-node detection, recovery id inheritance,
launcher keepalive (exit 254), and continued KV traffic afterwards —
the reference's recovery story (van.cc:266-332 + dmlc_local.py keepalive)
driven through real OS processes.
"""

import os
import subprocess
import sys


def test_worker_crash_recovery_end_to_end(tmp_path):
    marker = tmp_path / "crashed"
    child = os.path.join(os.path.dirname(__file__), "elastic_child.py")
    env = dict(
        os.environ,
        PS_HEARTBEAT_INTERVAL="1",
        PS_HEARTBEAT_TIMEOUT="2",
    )
    proc = subprocess.run(
        [
            sys.executable, "-m", "pslite_tpu.tracker.local",
            "-n", "2", "-s", "1", "--",
            sys.executable, child, str(marker),
        ],
        capture_output=True,
        timeout=300,
        env=env,
        cwd="/root/repo",
    )
    out = proc.stdout.decode() + proc.stderr.decode()
    assert proc.returncode == 0, out[-3000:]
    assert marker.exists(), "the crash never happened"
    assert "restarting worker (exit 254)" in out
    assert "RECOVERED_OK" in out
    assert "POLL_OK" in out
    assert out.count("ELASTIC_DONE") == 4  # scheduler, server, 2 workers