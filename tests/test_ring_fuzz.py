"""Interpret-mode fuzz of the fused ring kernel over tile-edge shapes
and ring sizes (r04 verdict ask #4: cheaper hardware evidence than
execution).

Every case runs the ENGINE surface twice — ``impl="pallas"`` (the ring
kernel under the Pallas TPU interpreter, full semaphore/DMA protocol)
vs ``impl="xla"`` (psum_scatter/all_gather, independently trustworthy)
— on identical data, so the kernel's internal padding (`_pad_ring_chunks`
to the (8,128) tile, sliced back out) is exercised at every edge:
1-element buckets, odd lengths, non-multiples of 1024, exact tile
boundaries ±1, and ring sizes 2..16 (16 via a subprocess with a larger
virtual device count).  Reference analog: the RDMA pipeline's chunking
edge cases, rdma_transport.h:323-357.

INTERPRETER ENVELOPE (found by this fuzz, r05): on the 1-vCPU box the
interpret-mode DMA simulator DEADLOCKS (0%% CPU, threads parked in
``_allocate_buffer`` io_callbacks) past a work threshold that scales
with ring size x chunk x per-hop callback count: f32 n=8 hangs at
chunk 12288 (fine at 4096); int8-wire n=8 hangs at its minimum chunk
8192 (fine at n=4, the existing engine-int8 coverage).  Reproducible
with the raw kernel and the pre-r05 grads layout alike, so it is a
simulator callback-pool starvation, not a kernel-protocol or engine
bug; the identical geometries pass real-v5e Mosaic compilation in
docs/AOT_RING.json.  The in-suite sweep therefore stays inside the
envelope (f32 n=8 chunk <= 4096, int8 n=4), and the n=16 subprocess
case runs the UNIDIRECTIONAL kernel at minimum chunk — half the
per-hop work, inside the envelope (~9 s) — for a definitive 16-ring
schedule-closure parity instead of a skip.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import jax.numpy as jnp
from jax.sharding import Mesh

from pslite_tpu.parallel.engine import CollectiveEngine

# Tile-edge lengths (f32 tile = 1024 elems; bidir chunk quantum 2048):
# 1-element bucket, sub-tile odds, one-over/one-under tile and lane
# boundaries, and prime-ish larger odds — capped so the per-device
# chunk stays within the interpreter envelope (module docstring).
EDGE_LENGTHS = [1, 7, 127, 129, 1023, 1025, 4095, 8191]


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("kv",))


def _pair(n, dtype=None, wire=None, handle="sum"):
    mesh = _mesh(n)
    ex = CollectiveEngine(mesh=mesh, impl="xla", server_handle=handle)
    ep = CollectiveEngine(mesh=mesh, impl="pallas", server_handle=handle,
                          wire_compress=wire)
    assert ep._effective_impl(dtype or jnp.float32, handle) == "pallas", \
        "fuzz case fell back to xla — not testing the kernel"
    return ex, ep


def _grads(n, total, seed, dtype=np.float32):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, total)).astype(dtype)


def _roundtrip(eng, name, total, grads_rows, dtype=None):
    """register + two push_pulls (the second catches store corruption
    from the first); returns (pulled1, pulled2) as f32 numpy."""
    eng.register_dense(name, np.arange(1, dtype=np.uint64), total,
                       dtype=dtype)
    p1 = np.asarray(eng.push_pull(name, grads_rows), np.float32)
    p2 = np.asarray(eng.push_pull(name, 0.5 * grads_rows), np.float32)
    return p1, p2


@pytest.mark.parametrize("total", EDGE_LENGTHS)
def test_edge_lengths_f32(total):
    n = 8
    ex, ep = _pair(n)
    g = _grads(n, total, seed=total)
    want1, want2 = _roundtrip(ex, "b", total, g)
    got1, got2 = _roundtrip(ep, "b", total, g)
    np.testing.assert_allclose(got1, want1, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(got2, want2, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("n", [2, 3, 5, 6, 8])
def test_ring_sizes(n):
    """Non-power-of-two rings included: the ring schedule's modular
    chunk walk must close for every n, not just the 2^k meshes."""
    total = 1025
    ex, ep = _pair(n)
    g = _grads(n, total, seed=n)
    want1, want2 = _roundtrip(ex, "b", total, g)
    got1, got2 = _roundtrip(ep, "b", total, g)
    np.testing.assert_allclose(got1, want1, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(got2, want2, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("total", [129, 4097])
def test_edge_bf16(total):
    n = 8
    ex, ep = _pair(n, dtype=jnp.bfloat16)
    g = _grads(n, total, seed=total)
    want1, want2 = _roundtrip(ex, "b", total, g.astype(jnp.bfloat16),
                              dtype=jnp.bfloat16)
    got1, got2 = _roundtrip(ep, "b", total, g.astype(jnp.bfloat16),
                            dtype=jnp.bfloat16)
    # bf16 stores: both paths quantize, but reduction orders differ.
    np.testing.assert_allclose(got1, want1, rtol=3e-2, atol=3e-2)
    np.testing.assert_allclose(got2, want2, rtol=3e-2, atol=3e-2)


@pytest.mark.parametrize("total", [1025, 8191])
def test_edge_int8_wire(total):
    """int8 wire compression at tile edges, vs the UNCOMPRESSED XLA
    result: the error budget is the documented per-hop requantization
    bound (O(hops) * absmax/127), not bit equality.  n=4: int8 at n=8
    is outside the interpreter envelope (module docstring)."""
    n = 4
    ex, ep = _pair(n, wire="int8")
    g = _grads(n, total, seed=total)
    want1, want2 = _roundtrip(ex, "b", total, g)
    got1, got2 = _roundtrip(ep, "b", total, g)
    amax = float(np.abs(g).sum(axis=0).max())
    tol = 3.0 * n * amax / 127.0
    np.testing.assert_allclose(got1, want1, atol=tol)
    np.testing.assert_allclose(got2, want2, atol=tol)


@pytest.mark.parametrize("total", [1, 1023])
def test_push_only_edge(total):
    """Push-only (reduce + update, no gather) at edge lengths: read the
    store back via a zero-gradient push_pull on both engines."""
    n = 8
    ex, ep = _pair(n)
    g = _grads(n, total, seed=total + 100)
    zeros = np.zeros_like(g)
    for eng in (ex, ep):
        eng.register_dense("b", np.arange(1, dtype=np.uint64), total)
        eng.push("b", g)
    want = np.asarray(ex.push_pull("b", zeros), np.float32)
    got = np.asarray(ep.push_pull("b", zeros), np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("total", [1025])
def test_replay_edge(total):
    """The fused replay scan (pallas ring per step) at an odd length."""
    n = 8
    steps = 3
    ex, ep = _pair(n)
    rng = np.random.default_rng(7)
    seq = rng.normal(size=(steps, total)).astype(np.float32)
    for eng in (ex, ep):
        eng.register_dense("b", np.arange(1, dtype=np.uint64), total)
    want = np.asarray(ex.replay("b", seq, keep="last"), np.float32)
    got = np.asarray(ep.replay("b", seq, keep="last"), np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


_RING16_CHILD = r"""
import numpy as np
import jax, jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from pslite_tpu.ops.ring_collective import ring_push_pull, ring_chunk_len
from pslite_tpu.parallel.mesh import shard_map_compat as shard_map

# UNIDIRECTIONAL, minimum chunk: half the per-hop work of the bidir
# form, which keeps a 16-ring inside the interpreter envelope (the
# bidir 16-ring at its minimum chunk starves the simulator — module
# docstring); the modular chunk schedule being proven is the same walk
# the bidir halves each run.
n = 16
chunk = ring_chunk_len(n * 1024, n, bidir=False)
assert jax.device_count() >= n, jax.device_count()
mesh = Mesh(np.array(jax.devices()[:n]), ("kv",))
rng = np.random.RandomState(1)
total = n * chunk
grads = rng.randn(n, total).astype(np.float32)
store0 = rng.randn(total).astype(np.float32)

def body(store_l, grads_l):
    g = grads_l[0].reshape(n, chunk)
    return ring_push_pull(g, store_l, lambda s, a: s + a, "kv", n,
                          bidir=False)

f = jax.jit(shard_map(body, mesh=mesh,
                      in_specs=(P("kv"), P("kv", None)),
                      out_specs=(P("kv"), P(None))))
new_store, pulled = f(jnp.asarray(store0), jnp.asarray(grads))
want = store0 + grads.sum(0)
np.testing.assert_allclose(np.asarray(pulled), want,
                           rtol=1e-4, atol=1e-4)
# new_store is the global updated store (each shard owns its chunk).
np.testing.assert_allclose(np.asarray(new_store), want,
                           rtol=1e-4, atol=1e-4)
print("RING16_OK")
"""


def test_ring_16_subprocess():
    """Ring size 16 — beyond this process's 8 virtual devices, so a
    child process brings up a 16-device CPU mesh (the verdict's 2..16
    sweep upper end).  Runs the unidirectional kernel at minimum chunk
    (definitive n=16 schedule-closure parity in ~seconds); the bidir
    16-ring sits outside the interpreter envelope."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=16",
        PYTHONPATH=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))),
    )
    out = subprocess.run(
        [sys.executable, "-c", _RING16_CHILD],
        capture_output=True,
        text=True,
        timeout=900,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "RING16_OK" in out.stdout
