"""ShmVan (same-host IPC fast path) and MultiVan (multi-rail) tests.

Mirror of the reference's tests/test_ipc_benchmark.cc (co-located
worker+server moving data through shared memory) and
tests/run_benchmark.sh's MultiVan mode.
"""

import os
import threading

import numpy as np

from pslite_tpu import KVServer, KVServerDefaultHandle, KVWorker
from pslite_tpu.customer import Customer
from pslite_tpu.message import Message

from helpers import LoopbackCluster


def _push_pull_roundtrip(cluster, payload_floats=64 * 1024):
    servers = []
    try:
        for po in cluster.servers:
            srv = KVServer(0, postoffice=po)
            srv.set_request_handle(KVServerDefaultHandle())
            servers.append(srv)
        worker = KVWorker(0, 0, postoffice=cluster.workers[0])
        ranges = cluster.workers[0].get_server_key_ranges()
        keys = np.array(
            sorted(r.begin + 1 for r in ranges), dtype=np.uint64
        )
        vals = np.random.default_rng(0).normal(
            size=len(keys) * payload_floats
        ).astype(np.float32)
        worker.wait(worker.push(keys, vals))
        out = np.zeros_like(vals)
        worker.wait(worker.pull(keys, out))
        np.testing.assert_allclose(out, vals, rtol=1e-6)
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_shm_van_push_pull():
    cluster = LoopbackCluster(num_workers=1, num_servers=2, van_type="shm")
    cluster.start()
    # Large payloads ride /dev/shm; verify the data plane stays correct.
    _push_pull_roundtrip(cluster)


def test_shm_van_small_messages_use_tcp():
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1, van_type="shm",
        env_extra={"PS_SHM_MIN_BYTES": str(1 << 30)},  # force TCP path
    )
    cluster.start()
    _push_pull_roundtrip(cluster, payload_floats=16)


def test_shm_preserves_user_body_with_data():
    """A user body riding alongside data segments must survive the shm
    fast path (the descriptor is carried separately, not by clobbering
    meta.body)."""
    cluster = LoopbackCluster(num_workers=1, num_servers=1, van_type="shm")
    cluster.start()
    try:
        received = []
        got_msg = threading.Event()

        def handle(msg):
            received.append(msg)
            got_msg.set()

        Customer(7, 7, handle, cluster.servers[0])
        payload = np.arange(64 * 1024, dtype=np.float32)
        msg = Message()
        msg.meta.app_id = 7
        msg.meta.customer_id = 7
        msg.meta.recver = cluster.servers[0].van.my_node.id
        msg.meta.request = True
        msg.meta.push = True
        msg.meta.key = 42
        msg.meta.body = b"user-body"
        msg.add_data(payload)
        cluster.workers[0].van.send(msg)
        assert got_msg.wait(15), "message never delivered"
        got = received[0]
        assert got.meta.body == b"user-body"
        np.testing.assert_array_equal(
            np.asarray(got.data[0].data, dtype=np.float32), payload
        )
    finally:
        cluster.finalize()


def test_zero_copy_pull_address_identity():
    """is_worker_zpull_ (kv_app.h:727-792): pulls into a registered
    transport-backed buffer are delivered in place — servers write their
    slices directly into the buffer, and the worker skips reassembly.
    Mirrors the registered-buffer address-identity check of
    test_benchmark.cc:169-181, for pulls."""
    cluster = LoopbackCluster(num_workers=1, num_servers=2, van_type="shm")
    cluster.start()
    servers = []
    try:
        for po in cluster.servers:
            srv = KVServer(0, postoffice=po)
            srv.set_request_handle(KVServerDefaultHandle())
            servers.append(srv)
        worker = KVWorker(0, 0, postoffice=cluster.workers[0])
        ranges = cluster.workers[0].get_server_key_ranges()
        keys = np.array(
            sorted(r.begin + 1 for r in ranges), dtype=np.uint64
        )
        val_len = 4096
        vals = np.linspace(0, 1, len(keys) * val_len).astype(np.float32)
        worker.wait(worker.push(keys, vals))

        buf = worker.alloc_pull_buffer(keys, val_len)
        assert buf is not None, "shm van must back registered pull buffers"
        buf[:] = -1.0  # sentinel: delivery must overwrite in place
        worker.wait(worker.pull(keys, buf))
        np.testing.assert_allclose(buf, vals, rtol=1e-6)
        assert worker.zpull_hits == 1, "pull was reassembled, not in-place"

        # Steady state: the same buffer keeps working (segment reuse).
        worker.wait(worker.pull(keys, buf))
        np.testing.assert_allclose(buf, vals, rtol=1e-6)
        assert worker.zpull_hits == 2

        # Ordinary arrays still use the reassembly path.
        plain = np.zeros_like(vals)
        worker.wait(worker.pull(keys, plain))
        np.testing.assert_allclose(plain, vals, rtol=1e-6)
        assert worker.zpull_hits == 2
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_multi_van_push_pull():
    cluster = LoopbackCluster(
        num_workers=2, num_servers=1, van_type="multi",
        env_extra={"DMLC_NUM_PORTS": "3"},
    )
    cluster.start()
    servers = []
    try:
        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        w0 = KVWorker(0, 0, postoffice=cluster.workers[0])
        w1 = KVWorker(0, 0, postoffice=cluster.workers[1])
        keys = np.array([11, 22, 33], dtype=np.uint64)
        vals = np.ones(3 * 512, dtype=np.float32)
        w0.wait(w0.push(keys, vals))
        w1.wait(w1.push(keys, vals))
        out = np.zeros_like(vals)
        w0.wait(w0.pull(keys, out))
        np.testing.assert_allclose(out, 2 * vals)
        # All rails were actually bound.
        assert len(cluster.workers[0].van.my_node.ports) == 3
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_copy_pool_correctness():
    """Native parallel-copy pool (the IPC transport's copy-thread-pool
    analog): byte-exact across the inline/pooled threshold, odd sizes,
    and concurrent callers."""
    import pytest

    from pslite_tpu.vans import native

    if native.load() is None:
        pytest.skip("native core not built")
    pool = native.CopyPool(4)
    try:
        for size in (64, (1 << 20) - 3, 5 * (1 << 20) + 13):
            src = np.random.default_rng(size % 97).integers(
                0, 255, size, dtype=np.uint8
            )
            dst = np.zeros(size, np.uint8)
            pool.copy(dst.ctypes.data, src.ctypes.data, size)
            assert np.array_equal(dst, src), f"mismatch at size={size}"

        errs = []

        def hammer(seed):
            try:
                for _ in range(5):
                    s = np.random.default_rng(seed).integers(
                        0, 255, 2 * (1 << 20) + seed, dtype=np.uint8
                    )
                    d = np.zeros_like(s)
                    pool.copy(d.ctypes.data, s.ctypes.data, s.nbytes)
                    assert np.array_equal(d, s)
            except Exception as exc:  # surfaced below
                errs.append(exc)

        ts = [
            threading.Thread(target=hammer, args=(i,)) for i in range(3)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, errs
    finally:
        pool.close()


def test_shm_van_large_payload_rides_copy_pool():
    """Multi-MB payloads (above _COPY_POOL_MIN) cross /dev/shm via the
    native pool when built; values must stay byte-exact either way."""
    cluster = LoopbackCluster(num_workers=1, num_servers=1, van_type="shm")
    cluster.start()
    # 2M floats = 8 MB > 1 MB threshold: exercises the pooled path.
    _push_pull_roundtrip(cluster, payload_floats=2 * 1024 * 1024)


def test_shm_ring_cluster():
    """PS_SHM_RING=1: the whole same-host cluster's meta plane rides
    shared-memory SPSC byte pipes (the cross-process extension of the
    reference's spsc_queue.h); payloads still ride segments.  Values and
    ordering must be identical to the socket plane."""
    import glob
    import pytest

    from pslite_tpu.vans import native

    if native.load() is None:
        pytest.skip("native core not built")
    cluster = LoopbackCluster(
        num_workers=2, num_servers=2, van_type="shm",
        env_extra={"PS_SHM_RING": "1"},
    )
    cluster.start()
    ns = cluster.base_env["DMLC_PS_ROOT_PORT"]
    # The cluster actually created pipes (not silently on sockets).
    pipes = glob.glob(f"/dev/shm/pslpipe_{ns}_*")
    assert any(not p.endswith(".lock") for p in pipes), pipes
    servers = []
    try:
        for po in cluster.servers:
            srv = KVServer(0, postoffice=po)
            srv.set_request_handle(KVServerDefaultHandle())
            servers.append(srv)
        w0 = KVWorker(0, 0, postoffice=cluster.workers[0])
        w1 = KVWorker(0, 0, postoffice=cluster.workers[1])
        ranges = cluster.workers[0].get_server_key_ranges()
        keys = np.array(
            sorted(r.begin + 2 for r in ranges), dtype=np.uint64
        )
        vals = np.random.default_rng(7).normal(
            size=len(keys) * 4096
        ).astype(np.float32)
        # Interleaved pushes from two workers + pulls: exercises ordered
        # delivery through the pipes under concurrency.
        for _ in range(5):
            t0 = w0.push(keys, vals)
            t1 = w1.push(keys, vals)
            w0.wait(t0)
            w1.wait(t1)
        out = np.zeros_like(vals)
        w0.wait(w0.pull(keys, out))
        np.testing.assert_allclose(out, 10 * vals, rtol=1e-5)
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()
    leftovers = [
        p for p in glob.glob(f"/dev/shm/pslpipe_{ns}_*")
        if not p.endswith(".lock")
    ]
    assert not leftovers, f"pipes not unlinked: {leftovers}"


def test_shm_ring_reclaims_stale_pipe():
    """A dead run's pipe file (no writer flock) must be reclaimed, not
    wedge the pair."""
    import pytest

    from pslite_tpu.vans import native

    if native.load() is None:
        pytest.skip("native core not built")
    from pslite_tpu.utils.network import get_available_port

    port = get_available_port()
    # Plant a stale pipe where the scheduler's port would collide.
    stale = f"/dev/shm/pslpipe_{port}_{port}_{port}"
    with open(stale, "wb") as f:
        f.write(b"\0" * 8192)
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1, van_type="shm",
        env_extra={
            "PS_SHM_RING": "1",
            "DMLC_PS_ROOT_PORT": str(port),
        },
    )
    cluster.start()
    servers = []
    try:
        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        w = KVWorker(0, 0, postoffice=cluster.workers[0])
        keys = np.array([11], dtype=np.uint64)
        vals = np.ones(256, np.float32)
        w.wait(w.push(keys, vals))
        out = np.zeros_like(vals)
        w.wait(w.pull(keys, out))
        np.testing.assert_allclose(out, vals)
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()
        if os.path.exists(stale):
            os.unlink(stale)


def test_shm_ring_composes_with_dmlc_local():
    """All three same-host tiers at once: unix-socket control endpoints
    (DMLC_LOCAL), shm pipes for the meta stream (PS_SHM_RING), and
    /dev/shm segments for payloads."""
    import pytest

    from pslite_tpu.vans import native

    if native.load() is None:
        pytest.skip("native core not built")
    import glob

    cluster = LoopbackCluster(
        num_workers=1, num_servers=1, van_type="shm",
        env_extra={"DMLC_LOCAL": "1", "PS_SHM_RING": "1"},
    )
    cluster.start()
    try:
        # Both tiers actually engaged — no silent fallback to TCP.
        ns = cluster.base_env["DMLC_PS_ROOT_PORT"]
        pipes = [
            p for p in glob.glob(f"/dev/shm/pslpipe_{ns}_*")
            if not p.endswith(".lock")
        ]
        assert pipes, "ring pipes not engaged under DMLC_LOCAL"
        from pslite_tpu.vans.tcp_van import _local_sock_path

        assert os.path.exists(
            _local_sock_path(cluster.workers[0].van.my_node.port)
        ), "unix-socket endpoint not engaged"
    except BaseException:
        # _push_pull_roundtrip finalizes internally; a failed engagement
        # assert must not leak the live cluster and its shm/sock files.
        cluster.finalize()
        raise
    _push_pull_roundtrip(cluster, payload_floats=64 * 1024)


def test_multi_van_shm_rails():
    """PS_MULTI_RAIL_VAN=shm: the multi-rail composite routes over shm
    rails (segments per rail namespace) — rail generality the reference's
    zmq-only MultiVan lacks."""
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1, van_type="multi",
        env_extra={"DMLC_NUM_PORTS": "2", "PS_MULTI_RAIL_VAN": "shm"},
    )
    cluster.start()
    servers = []
    try:
        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        w = KVWorker(0, 0, postoffice=cluster.workers[0])
        keys = np.array([4, 9], dtype=np.uint64)
        vals = np.random.default_rng(3).normal(
            size=2 * 64 * 1024
        ).astype(np.float32)  # 256 KB/key: rides rail shm segments
        w.wait(w.push(keys, vals))
        w.wait(w.push(keys, vals))
        out = np.zeros_like(vals)
        w.wait(w.pull(keys, out))
        np.testing.assert_allclose(out, 2 * vals, rtol=1e-6)
        # The data actually crossed /dev/shm via THIS cluster's per-rail
        # namespaces (psl_<pid>r<rail>_...), not some other test's files.
        import glob

        segs = [
            p for p in glob.glob(f"/dev/shm/psl_{os.getpid()}r*")
            if not p.endswith(".lock")
        ]
        assert segs, "shm rails created no segments"
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_repeated_cluster_cycles_leak_free():
    """A long-lived process repeatedly starting/stopping shm+ring
    clusters must not leak fds, /dev/shm files, or threads — the
    framework-hosting pattern (e.g. a trainer re-creating clusters on
    elastic events)."""
    import glob
    import pytest

    from pslite_tpu.vans import native

    if native.load() is None:
        pytest.skip("native core not built")

    def fd_count():
        return len(os.listdir("/proc/self/fd"))

    def shm_files():
        return sorted(
            p
            for pat in ("/dev/shm/psl_*", "/dev/shm/pslpipe_*")
            for p in glob.glob(pat)
            if not p.endswith(".lock")
        )

    def run_once():
        cluster = LoopbackCluster(
            num_workers=1, num_servers=1, van_type="shm",
            env_extra={"PS_SHM_RING": "1"},
        )
        cluster.start()
        servers = []
        try:
            srv = KVServer(0, postoffice=cluster.servers[0])
            srv.set_request_handle(KVServerDefaultHandle())
            servers.append(srv)
            w = KVWorker(0, 0, postoffice=cluster.workers[0])
            keys = np.array([3], dtype=np.uint64)
            vals = np.ones(64 * 1024, np.float32)
            w.wait(w.push(keys, vals))
            out = np.zeros_like(vals)
            w.wait(w.pull(keys, out))
            np.testing.assert_allclose(out, vals)
        finally:
            for s in servers:
                s.stop()
            cluster.finalize()

    run_once()  # warm up lazy singletons (copy pool, logging, ...)
    fd0, thr0 = fd_count(), threading.active_count()
    shm0 = shm_files()
    for _ in range(5):
        run_once()
    # Modest slack: the OS may reorder fd numbers; absolute growth is
    # what leaks show.
    assert fd_count() <= fd0 + 3, (fd0, fd_count())
    assert threading.active_count() <= thr0 + 2, (
        thr0, threading.active_count()
    )
    assert shm_files() == shm0, (shm0, shm_files())


def test_ps_native_env_override_forces_python_shm():
    """The documented contract: PS_NATIVE=0 forces the pure-Python path
    PER NODE via its Environment override map — the native core, the
    shared copy pool, AND the PS_SHM_RING pipe opt-in must all stay off
    for that node even when the process env/built library would allow
    them (regression: the pool and ring used to consult os.environ via
    native.load() only, ignoring the per-node override)."""
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1, van_type="shm",
        env_extra={"PS_NATIVE": "0", "PS_SHM_RING": "1"},
    )
    cluster.start()
    for po in cluster.all_nodes():
        van = po.van
        assert van._native is None, "PS_NATIVE=0 node went native"
        assert van._copy_pool is None, "copy pool ignored PS_NATIVE=0"
        assert not van._pipe_mode, "ring pipes ignored PS_NATIVE=0"
    # The cluster still works end to end on the pure-Python path
    # (the helper finalizes the cluster).
    _push_pull_roundtrip(cluster, payload_floats=4096)
