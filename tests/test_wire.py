"""Wire-format round-trip tests (Meta pack/unpack + frames)."""

import numpy as np

from pslite_tpu import wire
from pslite_tpu.message import Command, Control, Message, Meta, Node, Role
from pslite_tpu.sarray import SArray


def _sample_meta() -> Meta:
    node_a = Node(
        role=Role.WORKER,
        id=9,
        customer_id=2,
        hostname="10.0.0.1",
        ports=[5001, 5002],
        dev_types=[2, 2],
        dev_ids=[0, 1],
        is_recovery=True,
        endpoint_name=b"\x01\x02ep",
        aux_id=3,
    )
    node_b = Node(role=Role.SERVER, id=8, hostname="10.0.0.2", ports=[6000])
    return Meta(
        head=7,
        app_id=11,
        customer_id=1,
        timestamp=42,
        sender=9,
        recver=8,
        request=True,
        push=True,
        pull=False,
        simple_app=False,
        body=b"hello-body",
        data_type=[8, 10, 5],
        control=Control(
            cmd=Command.ADD_NODE,
            node=[node_a, node_b],
            barrier_group=7,
            msg_sig=0xDEADBEEF,
        ),
        key=123456789,
        addr=0xABCDEF,
        val_len=4096,
        option=-5,
        sid=77,
        data_size=8192,
        priority=9,
        src_dev_type=2,
        src_dev_id=0,
        dst_dev_type=1,
        dst_dev_id=-1,
    )


def test_meta_roundtrip():
    meta = _sample_meta()
    buf = wire.pack_meta(meta)
    out = wire.unpack_meta(buf)
    assert out == meta


def test_empty_meta_roundtrip():
    meta = Meta()
    out = wire.unpack_meta(wire.pack_meta(meta))
    assert out == meta


def test_codec_extension_roundtrip():
    """EXT_CODEC (docs/compression.md) rides the tagged tail like
    trace/chunk: full CodecInfo round-trips, composes with the other
    extensions, and EXT_CHUNK stays the meta's TRAILING bytes (the
    native splitter patches the tail in place — a codec ext packed
    after it would be corrupted by the per-chunk patch)."""
    from pslite_tpu.message import ChunkInfo, CodecInfo

    meta = _sample_meta()
    meta.control = Control()
    meta.trace = 0x1234
    meta.codec = CodecInfo(codec=2, raw_len=1 << 26, block=128, flags=1)
    meta.chunk = ChunkInfo(xfer=5, index=1, total=3, offset=4096,
                           seg_lens=(128, 65536, 2048),
                           seg_types=(8, 2, 10))
    buf = wire.pack_meta(meta)
    out = wire.unpack_meta(buf)
    assert out.codec == meta.codec
    assert out.chunk == meta.chunk
    assert out.trace == meta.trace
    # EXT_CHUNK must be the trailing extension: its payload occupies
    # exactly the last chunk_ext_payload_size bytes of the packed meta.
    tail = wire.chunk_ext_payload_size(3)
    ck_fixed = buf[len(buf) - tail:len(buf) - tail + 8 + 4 + 4 + 8 + 1]
    import struct

    xfer, index, total, offset, nseg = struct.unpack("<QIIQB", ck_fixed)
    assert (xfer, index, total, offset, nseg) == (5, 1, 3, 4096, 3)
    # Codec alone (no chunk) round-trips too.
    meta.chunk = None
    out2 = wire.unpack_meta(wire.pack_meta(meta))
    assert out2.codec == meta.codec and out2.chunk is None


def test_batch_extension_roundtrip():
    """EXT_BATCH (docs/batching.md): the per-op table (flags, ts, key,
    val_len, option, stamp, nseg, per-op codec) round-trips, the
    caller's ``meta.body`` is untouched by the piggybacked table, and
    the extension composes with trace/qos/codec/chunk with EXT_CHUNK
    still trailing."""
    from pslite_tpu.message import BatchInfo, BatchOp, ChunkInfo, CodecInfo

    meta = _sample_meta()
    meta.control = Control()
    meta.trace = 0x77
    meta.tenant = 3
    meta.stamp = 12
    meta.batch = BatchInfo(ops=(
        BatchOp(push=True, timestamp=5, key=100, val_len=4096, nseg=2),
        BatchOp(pull=True, timestamp=6, key=200, val_len=64, nseg=3,
                option=7, stamp=99,
                codec=CodecInfo(codec=2, raw_len=0, block=128)),
        BatchOp(push=True, pull=True, timestamp=7, key=300, val_len=8,
                nseg=2),
    ))
    meta.chunk = ChunkInfo(xfer=5, index=0, total=2, offset=0,
                           seg_lens=(16, 32), seg_types=(8, 10))
    out = wire.unpack_meta(wire.pack_meta(meta))
    assert out.batch == meta.batch
    assert out.body == meta.body  # table stripped back out
    assert out.chunk == meta.chunk and out.trace == meta.trace
    assert out.tenant == 3 and out.stamp == 12
    # Absent batch: no EXT_BATCH byte pattern obligations — just a
    # clean roundtrip with batch None (the PS_BATCH_BYTES=0 parity leg).
    meta.batch = None
    out2 = wire.unpack_meta(wire.pack_meta(meta))
    assert out2.batch is None and out2.body == meta.body


def test_ext_registry_audit():
    """Satellite (ISSUE 10): the wire-extension registry — every EXT_*
    tag in wire.py is unique, and the canonical packing order holds at
    every pack site with EXT_CHUNK STRICTLY LAST (the native splitter
    patches the meta's trailing bytes as the chunk extension; until
    now that contract was enforced only by comments)."""
    import struct

    from pslite_tpu.message import BatchInfo, BatchOp, ChunkInfo, CodecInfo

    # 1. Tag uniqueness, by reflection over the module's EXT_* names.
    tags = {name: getattr(wire, name) for name in dir(wire)
            if name.startswith("EXT_")}
    assert len(tags) >= 5  # trace, chunk, codec, qos, batch
    assert len(set(tags.values())) == len(tags), (
        f"duplicate EXT tag values: {tags}"
    )

    def ext_sequence(buf: bytes, meta: Meta) -> list:
        """Walk the packed meta's extension tail; returns tag order."""
        # Skip fixed + dtypes + body + nodes exactly like unpack_meta.
        fields = wire._META_FIXED.unpack_from(buf, 0)
        num_nodes, num_dtypes, body_len = fields[-3], fields[-2], fields[-1]
        off = wire._META_FIXED.size + num_dtypes + body_len
        view = memoryview(buf)
        for _ in range(num_nodes):
            _node, off = wire._unpack_node(view, off)
        seq = []
        while off + 2 <= len(buf):
            tag, ln = struct.unpack_from("<BB", buf, off)
            seq.append((tag, off, ln))
            off += 2 + ln
        assert off == len(buf), "extension walk did not land on the end"
        return seq

    # 2. Order at the PRIMARY pack site (wire.pack_meta) with EVERY
    #    extension present at once.
    meta = _sample_meta()
    meta.control = Control()
    meta.trace = 1
    meta.tenant = 2
    meta.stamp = 3
    meta.batch = BatchInfo(ops=(
        BatchOp(push=True, timestamp=1, key=1, val_len=4, nseg=2),
        BatchOp(push=True, timestamp=2, key=2, val_len=4, nseg=2),
    ))
    meta.codec = CodecInfo(codec=1, raw_len=64, block=128)
    meta.chunk = ChunkInfo(xfer=1, index=0, total=2, offset=0,
                           seg_lens=(8, 16), seg_types=(8, 10))
    buf = wire.pack_meta(meta)
    seq = ext_sequence(buf, meta)
    order = [t for t, _off, _ln in seq]
    assert order == [wire.EXT_TRACE, wire.EXT_QOS, wire.EXT_BATCH,
                     wire.EXT_CODEC, wire.EXT_CHUNK], order
    # EXT_CHUNK strictly last: its payload is the buffer's tail.
    tag, off, ln = seq[-1]
    assert tag == wire.EXT_CHUNK and off + 2 + ln == len(buf)
    assert ln == wire.chunk_ext_payload_size(2)
    # ... and for every SUBSET of extensions that includes chunk.
    for drop in ("trace", "tenant_stamp", "batch", "codec"):
        m2 = wire.unpack_meta(buf)  # fresh fully-loaded meta
        if drop == "trace":
            m2.trace = 0
        elif drop == "tenant_stamp":
            m2.tenant = m2.stamp = 0
        elif drop == "batch":
            m2.batch = None
        else:
            m2.codec = None
        b2 = wire.pack_meta(m2)
        s2 = ext_sequence(b2, m2)
        assert s2[-1][0] == wire.EXT_CHUNK, f"chunk not last without {drop}"
        assert s2[-1][1] + 2 + s2[-1][2] == len(b2)

    # 3. The SECONDARY pack sites build chunk metas through pack_meta
    #    too — chunking.split_message and the native descriptor both
    #    rely on the trailing-bytes contract; assert it on their actual
    #    output.
    import itertools

    from pslite_tpu.sarray import SArray
    from pslite_tpu.vans import chunking

    msg = Message(meta=Meta(app_id=1, request=True, push=True, head=0))
    msg.meta.trace = 9
    msg.add_data(SArray(np.arange(64, dtype=np.uint64)))
    msg.add_data(SArray(np.ones(4096, np.float32)))
    chunks = chunking.split_message(msg, 1024, xfer_id=7)
    assert chunks is not None
    for c in chunks:
        cb = wire.pack_meta(c.meta)
        cs = ext_sequence(cb, c.meta)
        assert cs[-1][0] == wire.EXT_CHUNK
        assert cs[-1][1] + 2 + cs[-1][2] == len(cb)
    nd = chunking.native_descriptor(msg, 1024, itertools.count(1))
    assert nd.ext_off == len(nd.meta_buf) - wire.chunk_ext_payload_size(2)


def test_frame_roundtrip():
    msg = Message(meta=Meta(app_id=3, timestamp=5, request=True, push=True))
    keys = np.array([1, 2, 3], dtype=np.uint64)
    vals = np.arange(12, dtype=np.float32)
    msg.add_data(SArray(keys))
    msg.add_data(SArray(vals))
    chunks = wire.pack_frame(msg)
    blob = b"".join(bytes(c) for c in chunks)

    meta_len, n_data = wire.unpack_frame_header(blob[: wire.FRAME_HEADER_SIZE])
    assert n_data == 2
    import struct

    off = wire.FRAME_HEADER_SIZE
    lens = struct.unpack_from("<2Q", blob, off)
    off += 16
    meta = wire.unpack_meta(blob[off : off + meta_len])
    off += meta_len
    bufs = []
    for ln in lens:
        bufs.append(blob[off : off + ln])
        off += ln
    out = wire.rebuild_message(meta, bufs)
    np.testing.assert_array_equal(out.data[0].numpy().view(np.uint64), keys)
    np.testing.assert_array_equal(out.data[1].numpy().view(np.float32), vals)
    assert out.meta.data_size == keys.nbytes + vals.nbytes


def test_pack_frame_contiguous_zero_copy():
    """Contiguous data segments pass through pack_frame without a copy
    (the chunk aliases the source buffer); strided views are made
    contiguous with identical bytes."""
    msg = Message(meta=Meta(app_id=1))
    contiguous = np.arange(16, dtype=np.float32)
    strided = np.arange(32, dtype=np.float32)[::2]
    msg.add_data(SArray(contiguous))
    msg.add_data(SArray(strided))
    chunks = wire.pack_frame(msg)
    # chunks: [hdr, lens, meta, data0, data1]
    assert np.shares_memory(np.frombuffer(chunks[3], np.float32),
                            contiguous)
    np.testing.assert_array_equal(
        np.frombuffer(chunks[4], dtype=np.float32), strided)
    assert not np.shares_memory(
        np.frombuffer(chunks[4], np.float32), strided)


def test_rebuild_message_accepts_ndarray_segments():
    """The tcp van's pooled receive path hands rebuild_message uint8
    ndarray views; derived arrays must alias them (base collapse onto
    the pool block) with correct dtypes."""
    vals = np.arange(12, dtype=np.float32)
    block = np.empty(64, np.uint8)
    block[: vals.nbytes] = vals.view(np.uint8)
    meta = Meta(data_type=[10], data_size=vals.nbytes)
    out = wire.rebuild_message(meta, [block[: vals.nbytes]])
    np.testing.assert_array_equal(out.data[0].numpy(), vals)
    assert out.data[0].numpy().base is block


def test_meta_fixed_offsets_match_native_constants():
    """The native core peeks/stamps fields of the packed meta at FIXED
    byte offsets (cpp/pslite_core.cc kMeta* constants, mirrored by
    wire.META_*_OFF).  Derive every offset from _META_FIXED's actual
    struct format so a layout reorder fails HERE instead of silently
    corrupting frames (the lane stamps sid through these offsets)."""
    import struct

    # Field order of wire._META_FIXED (see its format comment).
    fields = [
        ("version", "B"), ("head", "i"), ("app_id", "i"),
        ("customer_id", "i"), ("timestamp", "i"), ("sender", "i"),
        ("recver", "i"), ("flags", "B"), ("key", "Q"), ("addr", "Q"),
        ("val_len", "q"), ("option", "q"), ("sid", "i"),
        ("data_size", "q"), ("priority", "i"), ("src_dev_type", "b"),
        ("src_dev_id", "i"), ("dst_dev_type", "b"), ("dst_dev_id", "i"),
        ("control_cmd", "B"), ("barrier_group", "i"), ("msg_sig", "Q"),
        ("num_nodes", "H"), ("num_data_types", "H"), ("body_len", "I"),
    ]
    fmt = "<" + "".join(f for _, f in fields)
    assert struct.calcsize(fmt) == wire._META_FIXED.size, (
        "field list drifted from _META_FIXED"
    )
    off = {}
    pos = 0
    for name, f in fields:
        off[name] = pos
        pos += struct.calcsize("<" + f)
    # The constants the C++ core mirrors (kMetaSidOff & co).
    assert off["sid"] == wire.META_SID_OFF == 58
    assert off["priority"] == wire.META_PRIORITY_OFF == 70
    assert off["control_cmd"] == wire.META_CONTROL_CMD_OFF == 84
    assert wire._META_FIXED.size == wire.META_FIXED_SIZE == 105
    # Receive-side constants (sender id + variable-tail counters).
    assert off["sender"] == 17      # kMetaSenderOff
    assert off["num_nodes"] == 97   # kMetaNumNodesOff
    assert off["num_data_types"] == 99  # kMetaNumDtypesOff
    assert off["body_len"] == 101   # kMetaBodyLenOff
