from .hot_cache import HotKeyCache
from .kv_app import (ElasticZeroCopyError, KVMeta, KVPairs, KVServer,
                     KVServerDefaultHandle,
                     KVServerOptimizerHandle, KVWorker, OverloadError)
from .simple_app import SimpleApp, SimpleData
from .tiered import TieredStore

__all__ = [
    "ElasticZeroCopyError",
    "HotKeyCache",
    "KVMeta",
    "KVPairs",
    "KVServer",
    "KVServerDefaultHandle",
    "KVServerOptimizerHandle",
    "KVWorker",
    "OverloadError",
    "SimpleApp",
    "SimpleData",
    "TieredStore",
]
