"""ctypes bindings to the native C++ transport core (cpp/pslite_core.cc).

Loads ``cpp/libpslite_core.so`` when present (``make -C cpp``); the TCP van
then runs its socket IO, frame assembly, and receive queue on native
threads, GIL-free — the counterpart of the reference keeping its Van layer
in C++.  ``PS_NATIVE=0`` forces the pure-Python path.
"""

from __future__ import annotations

import ctypes
import os
import struct
import threading
from typing import List, Optional, Tuple

_LIB_PATHS = [
    # Source tree: cpp/ build output (make -C cpp).
    os.path.join(os.path.dirname(__file__), "..", "..", "cpp",
                 "libpslite_core.so"),
    # Installed wheel: the copy `make -C cpp` places inside the package.
    os.path.join(os.path.dirname(__file__), "..", "libpslite_core.so"),
    "libpslite_core.so",
]

_lib = None


class _FrameView(ctypes.Structure):
    _fields_ = [
        ("buf", ctypes.POINTER(ctypes.c_uint8)),
        ("meta_len", ctypes.c_uint32),
        ("n_data", ctypes.c_uint32),
    ]


def load() -> Optional[ctypes.CDLL]:
    """The native library, or None when unavailable/disabled."""
    global _lib
    if _lib is not None:
        return _lib
    if os.environ.get("PS_NATIVE", "1") in ("0", "false"):
        return None
    for path in _LIB_PATHS:
        try:
            lib = ctypes.CDLL(os.path.abspath(path)
                              if os.path.sep in path else path)
            _declare(lib)
        except (OSError, AttributeError):
            # AttributeError = stale .so missing a symbol (make -C cpp not
            # rerun after an update): fall through to the next candidate
            # or the pure-Python path rather than breaking every van.
            continue
        _lib = lib
        return _lib
    return None


def _declare(lib: ctypes.CDLL) -> None:
    """Declare every symbol's signature; a stale .so missing one raises
    AttributeError here (caught by load's candidate loop)."""
    lib.psl_create.restype = ctypes.c_void_p
    lib.psl_bind.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
    lib.psl_connect.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_int,
    ]
    lib.psl_bind_local.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int
    ]
    lib.psl_connect_local.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int
    ]
    lib.psl_pipe_connect.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64
    ]
    lib.psl_pipe_watch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.psl_send.restype = ctypes.c_longlong
    lib.psl_send.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.psl_recv.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(_FrameView), ctypes.c_int
    ]
    lib.psl_frame_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.psl_stop.argtypes = [ctypes.c_void_p]
    lib.psl_destroy.argtypes = [ctypes.c_void_p]
    lib.psl_copy_pool_create.restype = ctypes.c_void_p
    lib.psl_copy_pool_create.argtypes = [ctypes.c_int]
    lib.psl_copy_pool_copy.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64
    ]
    lib.psl_copy_pool_destroy.argtypes = [ctypes.c_void_p]


class CopyPool:
    """Parallel memcpy on persistent native threads — the IPC transport's
    copy-thread-pool analog (rdma_transport.h:469-633).  ctypes releases
    the GIL for the call, so the pool threads and the caller all stream
    bytes concurrently on multi-core hosts."""

    def __init__(self, n_threads: int):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native core not available")
        self._h = self._lib.psl_copy_pool_create(n_threads)

    def copy(self, dst_addr: int, src_addr: int, nbytes: int) -> None:
        """Raw-pointer copy; the caller owns keeping both buffers alive."""
        h = self._h
        if not h:
            raise RuntimeError("copy pool is closed")
        self._lib.psl_copy_pool_copy(h, dst_addr, src_addr, nbytes)

    def close(self) -> None:
        if self._h:
            self._lib.psl_copy_pool_destroy(self._h)
            self._h = None

    def __del__(self):  # best-effort: pools are owned by long-lived vans
        try:
            self.close()
        except Exception:
            pass


_shared_pool: Optional[CopyPool] = None
_shared_pool_mu = threading.Lock()


def shared_copy_pool(n_threads: int) -> Optional[CopyPool]:
    """One process-wide pool, like the reference's single
    BYTEPS_IPC_COPY_NUM_THREADS pool: co-located vans share its threads
    (Copy serializes jobs internally), and its lifetime is the process —
    individual van shutdown never races a peer van's in-flight copy.
    The first caller's thread count wins."""
    global _shared_pool
    if load() is None:
        return None
    with _shared_pool_mu:
        if _shared_pool is None:
            _shared_pool = CopyPool(n_threads)
        return _shared_pool


class NativeTransport:
    """Thin OO wrapper over the C API."""

    def __init__(self):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native core not available")
        self._h = self._lib.psl_create()

    def bind(self, port: int, backlog: int = 128) -> int:
        rc = self._lib.psl_bind(self._h, port, backlog)
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))
        return rc

    def connect(self, node_id: int, host: str, port: int,
                timeout_ms: int = 30000) -> None:
        rc = self._lib.psl_connect(
            self._h, node_id, host.encode(), port, timeout_ms
        )
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))

    def bind_local(self, path: str, backlog: int = 128) -> None:
        """DMLC_LOCAL mode: listen on a unix-domain socket at ``path``."""
        rc = self._lib.psl_bind_local(self._h, path.encode(), backlog)
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))

    def connect_local(self, node_id: int, path: str,
                      timeout_ms: int = 30000) -> None:
        rc = self._lib.psl_connect_local(
            self._h, node_id, path.encode(), timeout_ms
        )
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))

    def pipe_connect(self, node_id: int, path: str, data_bytes: int) -> None:
        """PS_SHM_RING: route this peer's whole stream through a
        shared-memory SPSC byte pipe created at ``path``."""
        rc = self._lib.psl_pipe_connect(
            self._h, node_id, path.encode(), data_bytes
        )
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))

    def pipe_watch(self, directory: str, prefix: str, suffix: str,
                   idle_cap_us: int = 0) -> None:
        """Start attaching inbound pipes named <prefix>*<suffix> in
        ``directory`` as they appear (poller thread).  ``idle_cap_us``
        bounds the poller's idle backoff (0 = keep default)."""
        rc = self._lib.psl_pipe_watch(
            self._h, directory.encode(), prefix.encode(), suffix.encode(),
            idle_cap_us,
        )
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))

    def send(self, node_id: int, meta: bytes, data: List[memoryview]) -> int:
        n = len(data)
        bufs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_uint64 * n)()
        keepalive = []
        for i, d in enumerate(data):
            mv = memoryview(d).cast("B")
            if mv.readonly:
                mv = memoryview(bytearray(mv))
            c = (ctypes.c_uint8 * len(mv)).from_buffer(mv)
            keepalive.append((mv, c))
            bufs[i] = ctypes.addressof(c)
            lens[i] = len(mv)
        meta_buf = (ctypes.c_uint8 * len(meta)).from_buffer_copy(meta)
        rc = self._lib.psl_send(
            self._h, node_id, meta_buf, len(meta), n, bufs, lens
        )
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))
        return int(rc)

    def recv(self, timeout_ms: int = -1) -> Optional[Tuple[bytes, List[bytes]]]:
        """(meta_bytes, data_segments) — None when stopped; raises
        TimeoutError on timeout."""
        view = _FrameView()
        rc = self._lib.psl_recv(self._h, ctypes.byref(view), timeout_ms)
        if rc == -1:
            return None
        if rc == 0:
            raise TimeoutError
        try:
            n_data = view.n_data
            lens_bytes = ctypes.string_at(view.buf, 8 * n_data)
            lens = struct.unpack(f"<{n_data}Q", lens_bytes)
            off = 8 * n_data
            meta = ctypes.string_at(
                ctypes.addressof(view.buf.contents) + off, view.meta_len
            )
            off += view.meta_len
            segs = []
            base = ctypes.addressof(view.buf.contents)
            for ln in lens:
                # Writable copies: receivers may mutate payloads in place
                # (e.g. a server handle averaging pushed gradients), which
                # the pure-Python path permits too.
                segs.append(bytearray(ctypes.string_at(base + off, ln)))
                off += ln
            return meta, segs
        finally:
            self._lib.psl_frame_free(view.buf)

    def stop(self) -> None:
        if self._h:
            self._lib.psl_stop(self._h)

    def destroy(self) -> None:
        if self._h:
            self._lib.psl_destroy(self._h)
            self._h = None
