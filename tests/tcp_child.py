"""Child process for the multi-process TCP cluster test.

Mirrors the reference's tests/local.sh + test_benchmark flow: the role comes
from DMLC_ROLE; workers push then pull and verify multi-worker aggregation.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import pslite_tpu as ps
from pslite_tpu import KVServer, KVServerDefaultHandle, KVWorker
from pslite_tpu.message import Role


def main() -> int:
    role = os.environ["DMLC_ROLE"]
    ps.start_ps()
    server = None
    if role == "server":
        server = KVServer(0)
        server.set_request_handle(KVServerDefaultHandle())
    if role == "worker":
        po = ps.postoffice(Role.WORKER)
        worker = KVWorker(0, 0)
        ranges = po.get_server_key_ranges()
        keys = np.array(
            sorted([ranges[0].begin + 1, ranges[1].begin + 2]), dtype=np.uint64
        )
        vals = np.full(2 * 256, 1.5, dtype=np.float32)
        worker.wait(worker.push(keys, vals))
        # All workers must have pushed before pulling.
        po.barrier(0, ps.WORKER_GROUP)
        out = np.zeros_like(vals)
        worker.wait(worker.pull(keys, out))
        expected = 2 * 1.5  # two workers pushed
        if not np.allclose(out, expected):
            print(f"WORKER_FAIL: got {out[:4]} expected {expected}")
            return 1
        print("WORKER_OK")
    ps.finalize()
    if server is not None:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
