"""IciVan — the flagship TPU transport: XLA collectives over the ICI mesh.

The reference's BASELINE north star: an ``XlaVan/IciVan`` alongside
zmq/rdma/fabric/ucx that maps ``KVWorker::ZPush/ZPull`` and KVServer
aggregation onto reduce-scatter + all-gather over the device mesh, with the
PS roles as logical shards of one SPMD program rather than RDMA endpoints.

Split of planes (mirroring FabricVan nesting a ZMQVan for bootstrap,
fabric_van.h:123-127):

- **Control plane**: inherited message transport (loopback in-process; the
  node still participates in scheduler bootstrap, barriers, heartbeats).
- **Data plane**: a :class:`CollectiveEngine` + :class:`SparseEngine` on the
  mesh.  ``KVWorker`` detects the engine and routes registered dense buckets
  and sparse tables through jitted collectives; unregistered traffic falls
  back to the message path, preserving the full KV contract (the "sync
  collective vs async per-message" duality flagged in SURVEY §7).
"""

from __future__ import annotations

from typing import Optional

from .loopback_van import LoopbackVan


class IciVan(LoopbackVan):
    def __init__(self, postoffice):
        super().__init__(postoffice)
        self.engine = None
        self.sparse_engine = None
        self._mesh = None

    def set_mesh(self, mesh) -> None:
        """Install a specific mesh before start() (tests, multi-host)."""
        self._mesh = mesh

    def start(self, customer_id: int) -> None:
        super().start(customer_id)
        # Only worker instances drive the SPMD data plane; scheduler/server
        # instances keep the control-plane role (barriers, bookkeeping, and
        # the async message fallback path).
        if self.engine is None and self.po.is_worker:
            from ..parallel.engine import CollectiveEngine
            from ..parallel.sparse import SparseEngine

            handle = self.env.find("PS_ICI_SERVER_HANDLE", "sum")
            self.engine = CollectiveEngine(
                mesh=self._mesh, server_handle=handle
            )
            self.sparse_engine = SparseEngine(
                self.engine.mesh, self.engine.axis
            )

    def register_recv_buffer(self, sender_id: int, key: int, buffer) -> None:
        # Donated HBM buffers make delivery-in-place the default on this
        # van; nothing to pin (SURVEY §5 "RegisterRecvBuffer ⇒ donated HBM").
        return None
