"""Asynchronous SGD with the optimizer ON the server.

The reference's async mode (docs/overview.md there): workers push
gradients whenever ready — no inter-worker barrier — and the server
applies each push on arrival.  Here the server owns the optimizer
(``KVServerOptimizerHandle``), so workers exchange raw gradients and
pull ready-to-use parameters.

Run a 2-worker async cluster on one machine::

    python -m pslite_tpu.tracker.local -n 2 -s 1 -- python examples/async_sgd.py
    PS_PRIORITY_SCHED=1 python -m pslite_tpu.tracker.local -n 2 -s 1 -- \
        python examples/async_sgd.py     # + priority send scheduling

Each worker fits y = Wx on its own data shard; staleness from async
application is tolerated by SGD (the classic PS trade described in the
reference's overview).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import pslite_tpu as ps

DIM = 8
KEYS = np.arange(4, dtype=np.uint64)  # 4 param blocks of DIM floats
STEPS = 40


def main() -> None:
    role = os.environ.get("DMLC_ROLE")
    if role is None:
        sys.exit(
            "DMLC_ROLE not set — run under the launcher:\n"
            "  python -m pslite_tpu.tracker.local -n 2 -s 1 -- "
            "python examples/async_sgd.py"
        )
    ps.start_ps()

    server = None
    if role in ("server", "joint"):
        server = ps.KVServer(0)
        server.set_request_handle(
            ps.KVServerOptimizerHandle(kind="sgd_momentum", lr=0.05)
        )

    if role in ("worker", "joint"):
        po = ps.postoffice(ps.Role.WORKER)
        kv = ps.KVWorker(0, 0)
        rank = po.my_rank()
        rng = np.random.default_rng(100 + rank)
        w_true = np.linspace(-1, 1, len(KEYS) * DIM).astype(np.float32)

        params = np.zeros(len(KEYS) * DIM, np.float32)
        last_loss = None
        for step in range(STEPS):
            # Local data shard -> gradient of 0.5*||w - w_true||^2 noise-
            # perturbed (stands in for a minibatch gradient).
            grad = (params - w_true) + rng.normal(
                scale=0.05, size=params.shape
            ).astype(np.float32)
            # Fire-and-forget push (async mode: NO barrier with the other
            # worker); wait only guards local buffer reuse.
            kv.wait(kv.push(KEYS, grad, priority=step % 4))
            kv.wait(kv.pull(KEYS, params))
            last_loss = float(0.5 * np.mean((params - w_true) ** 2))
            if rank == 0 and step % 10 == 0:
                print(f"step {step:3d}  loss {last_loss:.5f}", flush=True)
        print(f"worker {rank}: final loss {last_loss:.5f}", flush=True)
        assert last_loss < 0.05, last_loss

    ps.finalize()
    if server is not None:
        server.stop()
    print(f"{role} DONE", flush=True)


if __name__ == "__main__":
    main()
