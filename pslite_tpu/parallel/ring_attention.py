"""Ring attention — sequence/context parallelism over a mesh axis.

The reference has no attention or sequence code (SURVEY §2.9); long-context
support is new, TPU-first scope for this framework: sequence-sharded
attention where K/V blocks rotate around the ring via ``ppermute`` while
each shard accumulates blockwise softmax online (log-sum-exp carry), so a
sequence of length ``T`` needs only ``T / num_shards`` resident K/V per
device and communication rides neighbor ICI links.

Layout: ``q, k, v`` are ``[B, T_local, H, D]`` per shard inside
``shard_map`` over ``axis_name``; global sequence order is shard-major
(shard s owns positions ``[s*T_local, (s+1)*T_local)``), which the causal
mask uses to compare global positions.
"""

from __future__ import annotations

from functools import partial


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: float | None = None):
    """Blockwise ring attention; call inside shard_map over ``axis_name``.

    Returns the attention output ``[B, T_local, H, D]`` for this shard's
    queries over the *global* key/value sequence.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    B, T, H, D = q.shape
    if scale is None:
        scale = D ** -0.5
    S = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % S) for i in range(S)]

    q_pos = my * T + jnp.arange(T)  # global positions of my queries

    # True -inf so the masked-row guards below can use isfinite().
    neg_inf = -jnp.inf

    def block(carry, i):
        o, lse_m, lse_l, k_cur, v_cur = carry
        # k_cur originated at shard (my - i) mod S.
        src = (my - i) % S
        k_pos = src * T + jnp.arange(T)
        # scores: [B, H, T, Tk]
        scores = jnp.einsum("bthd,bshd->bhts", q, k_cur) * scale
        if causal:
            mask = k_pos[None, :] > q_pos[:, None]  # [T, Tk]
            scores = jnp.where(mask[None, None], neg_inf, scores)
        m_new = jnp.maximum(lse_m, scores.max(axis=-1))
        # Guard fully-masked rows: exp(neg_inf - neg_inf) -> use safe sub.
        p = jnp.exp(scores - m_new[..., None])
        p = jnp.where(jnp.isfinite(scores), p, 0.0)
        alpha = jnp.exp(lse_m - m_new)
        alpha = jnp.where(jnp.isfinite(lse_m), alpha, 0.0)
        lse_l = lse_l * alpha + p.sum(axis=-1)
        o = o * alpha[..., None] + jnp.einsum("bhts,bshd->bthd", p, v_cur
                                              ).transpose(0, 2, 1, 3)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (o, m_new, lse_l, k_next, v_next), None

    o0 = jnp.zeros((B, H, T, D), q.dtype)
    m0 = jnp.full((B, H, T), neg_inf, q.dtype)
    l0 = jnp.zeros((B, H, T), q.dtype)
    (o, _, l, _, _), _ = lax.scan(
        block, (o0, m0, l0, k, v), jnp.arange(S)
    )
    l = jnp.where(l == 0, 1.0, l)  # fully-masked rows output zeros
    out = o / l[..., None]  # [B, H, T, D]
    return out.transpose(0, 2, 1, 3)  # [B, T, H, D]


def reference_attention(q, k, v, causal: bool = False,
                        scale: float | None = None):
    """Single-device reference (same layout) for tests and the 1-chip path."""
    import jax.numpy as jnp

    B, T, H, D = q.shape
    if scale is None:
        scale = D ** -0.5
    scores = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    if causal:
        pos = jnp.arange(T)
        mask = pos[None, :] > pos[:, None]
        scores = jnp.where(mask[None, None], jnp.finfo(q.dtype).min, scores)
    p = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    p = p / p.sum(axis=-1, keepdims=True)
    return jnp.einsum("bhts,bshd->bthd", p, v)  # [B, T, H, D]
