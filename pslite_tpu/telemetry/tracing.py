"""Distributed request tracing — tail-based capture over span rings.

Two capture modes share one span vocabulary:

- **Tail-based** (``PS_TRACE_TAIL``, docs/observability.md): EVERY
  request mints a trace id up front (a counter, not a coin flip) and
  every node records its lifecycle spans into a bounded ring; at
  completion the WORKER keeps the trace only if it is *interesting* —
  slower than a rolling per-path quantile, a failure outcome, or a
  small uniform floor (:class:`~.trace_store.TailPolicy`).  Rings are
  drained live by the scheduler's ``TRACE_PULL`` broadcast
  (``Postoffice.collect_cluster_traces``) and stitched into complete
  request trees by :class:`~.trace_store.TraceCollector`; unkept
  requests' ambient spans simply age out.
- **Head-sampled** (``PS_TRACE_SAMPLE``, the legacy knob): the id is
  minted with probability p at ``KVWorker.push/pull`` and every
  downstream stage keys on it — unchanged behavior, same ring.

A trace id rides in ``Message.meta.trace`` (a backward-compatible
tagged wire extension — wire.py) and, for ops merged into ``EXT_BATCH``
frames, in the per-op table, so traced ops batch exactly like untraced
ones (no observer effect).  Timestamps are ``monotonic_ns`` offsets
re-based onto a per-node wall anchor, so spans from different nodes
share one timeline — both for the live collector and for the per-node
Chrome trace-event JSON exports (``PS_TRACE_DIR``), which a periodic
background flush keeps crash-safe (``PS_TRACE_FLUSH_S``).
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import random
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils.profiling import MonotonicAnchor
from .trace_store import TailPolicy


class Tracer:
    """Per-node span recorder.  ``active`` is False unless head
    sampling (``PS_TRACE_SAMPLE > 0``) or tail capture
    (``PS_TRACE_TAIL``) is configured — every recording call no-ops
    then, so the tracer costs one attribute check on untraced
    deployments."""

    MAX_EVENTS = 65536

    # How long a TRACE_PULL threshold hint outranks the local-histogram
    # fallback (the scheduler's windowed p-quantile is the better
    # signal, but a dead scheduler must not freeze the keep policy).
    HINT_TTL_S = 30.0

    def __init__(self, env, role: str, metrics=None):
        self.sample = env.find_float("PS_TRACE_SAMPLE", 0.0)
        # Tail-based capture (trace_store.TailPolicy): parsed once;
        # None = tail mode off (head sampling only).
        self.tail = TailPolicy.parse(env.find("PS_TRACE_TAIL"))
        self.active = self.sample > 0.0 or self.tail is not None
        self.role = role
        self.node_id = -1  # assigned at bootstrap (export-time pid)
        # Default export into the system tempdir, NOT the cwd: traced
        # clusters launched from a checkout were littering (and once
        # committing) pslite_trace_*.json at the repo root.  The files
        # are also gitignored; set PS_TRACE_DIR to collect them.
        self._dir = env.find("PS_TRACE_DIR") or tempfile.gettempdir()
        ring = env.find_int("PS_TRACE_RING", 0)
        if ring > 0:
            self.MAX_EVENTS = ring  # instance shadow
        self._mu = threading.Lock()
        self._events: collections.deque = collections.deque()
        self.dropped = 0
        # Silent span loss made visible (docs/observability.md):
        # head-sampled mode DROPS the newest span on a full buffer and
        # counts it as ``trace.dropped_events`` (psmon warns the export
        # is incomplete).  Tail mode instead EVICTS the oldest — the
        # ring is a window the TRACE_PULL drain keeps emptying, and
        # overwrite is by design — counted as ``trace.ring_evictions``
        # (no warning; a high rate means pull more often or grow
        # PS_TRACE_RING).
        if metrics is not None:
            self._c_dropped = metrics.counter("trace.dropped_events")
            self._c_evicted = metrics.counter("trace.ring_evictions")
        else:
            from .metrics import NULL_REGISTRY

            self._c_dropped = NULL_REGISTRY.counter("trace.dropped_events")
            self._c_evicted = NULL_REGISTRY.counter("trace.ring_evictions")
        # Cross-node clock alignment: durations come from monotonic_ns,
        # absolute timestamps re-base onto ONE wall anchor per tracer
        # (the Profiler's timebase — utils/profiling.MonotonicAnchor).
        self._anchor = MonotonicAnchor()
        # Tail id minting: node-unique ids without an RNG call per op —
        # a random per-tracer salt in the high bits, a counter below.
        # 30 salt bits keep cross-node collision odds negligible even
        # for hundreds of (restarting) workers (birthday over 2^30),
        # and 33 sequence bits outlast any realistic ring lifetime;
        # ids stay under 2^63 like the head-sampled ones.
        self._id_salt = random.getrandbits(30) | 1
        self._id_seq = itertools.count(1)
        # Tail keep thresholds per path ("push"/"pull"): TRACE_PULL
        # hints (wall-stamped) outrank the local histogram fallback
        # (set_tail_source) for HINT_TTL_S.
        self._thr_mu = threading.Lock()
        self._hints: Dict[str, Tuple[float, float]] = {}  # path->(v, t)
        self._sources: Dict[str, object] = {}
        self._local_thr: Dict[str, Tuple[Optional[float], int]] = {}
        self._evicted_since_drain = 0
        # Crash-safe exports: a background thread rewrites this node's
        # trace file every PS_TRACE_FLUSH_S seconds (tail default 15;
        # 0 disables), so a SIGKILL'd node still leaves its spans.
        self._flush_s = env.find_float(
            "PS_TRACE_FLUSH_S", 15.0 if self.tail is not None else 0.0
        )
        self._flush_thread: Optional[threading.Thread] = None

    # -- ids & clock ---------------------------------------------------------

    def maybe_trace(self) -> int:
        """Legacy head sampling: a fresh nonzero trace id with
        probability ``PS_TRACE_SAMPLE``, else 0 (untraced — every
        downstream stage checks the id, not the sampling knob, so the
        decision is made exactly once)."""
        if self.sample <= 0.0 or random.random() >= self.sample:
            return 0
        return random.getrandbits(63) | 1

    def begin_request(self) -> int:
        """Trace id for a NEW request.  Tail mode: every request gets
        one (cheap counter — the keep/drop decision moves to
        completion, see :meth:`tail_keep`); otherwise the head-sampled
        legacy decision."""
        if self.tail is not None:
            return (self._id_salt << 33) | (next(self._id_seq)
                                            & ((1 << 33) - 1))
        return self.maybe_trace()

    def now_us(self) -> float:
        """Wall-aligned monotonic microseconds (the event timebase)."""
        return self._anchor.now_ns() / 1000.0

    # -- tail keep policy ----------------------------------------------------

    def set_tail_source(self, path: str, hist) -> None:
        """Register the local latency histogram backing ``path``'s
        rolling slow threshold (the fallback when no TRACE_PULL hint
        is fresh) — KVWorker hands over its push/pull histograms."""
        self._sources[path] = hist

    def note_hints(self, hints: dict) -> None:
        """Absorb scheduler-side threshold hints (TRACE_PULL request
        body): ``{"push": {"p95": s, ...}, "pull": {...}}`` from the
        ClusterHistory windowed quantiles."""
        if self.tail is None or self.tail.slow_q is None:
            return
        key = f"p{round(self.tail.slow_q * 100):d}"
        now = time.monotonic()
        with self._thr_mu:
            for path in ("push", "pull"):
                v = (hints.get(path) or {}).get(key)
                if isinstance(v, (int, float)) and v > 0:
                    self._hints[path] = (float(v), now)

    _THR_RECOMPUTE_EVERY = 64
    _THR_MIN_COUNT = 32

    def tail_threshold(self, path: str) -> Optional[float]:
        """Current slow threshold (seconds) for one path: a fresh
        TRACE_PULL hint, else the local histogram's quantile
        (recomputed every few calls, needs a minimum population),
        else None (slow rule inactive while cold)."""
        if self.tail is None or self.tail.slow_q is None:
            return None
        now = time.monotonic()
        with self._thr_mu:
            hint = self._hints.get(path)
            if hint is not None and now - hint[1] < self.HINT_TTL_S:
                return hint[0]
            cached, left = self._local_thr.get(path, (None, 0))
            if left > 0:
                self._local_thr[path] = (cached, left - 1)
                return cached
            hist = self._sources.get(path)
            value = None
            if hist is not None and getattr(hist, "count", 0) \
                    >= self._THR_MIN_COUNT:
                try:
                    value = hist.quantile(self.tail.slow_q)
                except Exception:  # noqa: BLE001 - null instruments
                    value = None
            self._local_thr[path] = (value, self._THR_RECOMPUTE_EVERY)
            return value

    def tail_keep(self, dur_s: float, path: str,
                  outcome: Optional[str] = None) -> Optional[str]:
        """Keep decision for one completed request: a reason string
        ("slow>p95" / the outcome / "floor") when the trace should be
        kept, None to drop.  Head-sampled ids (tail mode off) are
        always kept — their decision was made up front."""
        if self.tail is None:
            return "sampled"
        return self.tail.keep(dur_s, outcome, self.tail_threshold(path))

    # -- recording -----------------------------------------------------------

    def _append(self, ev: dict) -> None:
        with self._mu:
            if len(self._events) >= self.MAX_EVENTS:
                if self.tail is not None:
                    # Ring semantics: oldest out, newest in.
                    self._events.popleft()
                    self._evicted_since_drain += 1
                    self._c_evicted.inc()
                else:
                    self.dropped += 1
                    self._c_dropped.inc()
                    return
            self._events.append(ev)
        if self._flush_s > 0 and self._flush_thread is None:
            self._ensure_flush_thread()

    def span(self, trace_id: int, name: str, t0_us: float,
             dur_us: Optional[float] = None, args: Optional[dict] = None)\
            -> None:
        """A complete ("X") span: ``[t0_us, t0_us + dur_us]``.  With
        ``dur_us`` omitted, the span ends now."""
        if not trace_id or not self.active:
            return
        if dur_us is None:
            dur_us = max(0.0, self.now_us() - t0_us)
        a = {"trace": f"{trace_id:x}"}
        if args:
            a.update(args)
        self._append({
            "name": name, "cat": "pslite", "ph": "X",
            "ts": t0_us, "dur": dur_us,
            "tid": threading.get_ident() & 0xFFFF,
            "args": a,
        })

    def instant(self, trace_id: int, name: str,
                args: Optional[dict] = None) -> None:
        if not trace_id or not self.active:
            return
        a = {"trace": f"{trace_id:x}"}
        if args:
            a.update(args)
        self._append({
            "name": name, "cat": "pslite", "ph": "i",
            "ts": self.now_us(), "s": "t",
            "tid": threading.get_ident() & 0xFFFF,
            "args": a,
        })

    # -- draining (TRACE_PULL) -----------------------------------------------

    def drain(self) -> Tuple[List[dict], int]:
        """Hand the buffered spans to a collector and clear the ring;
        returns ``(events, evictions since the previous drain)`` — the
        eviction count tells the scheduler its pull cadence is losing
        spans."""
        with self._mu:
            events = list(self._events)
            self._events.clear()
            evicted = self._evicted_since_drain
            self._evicted_since_drain = 0
        return events, evicted

    # -- export --------------------------------------------------------------

    @property
    def num_events(self) -> int:
        with self._mu:
            return len(self._events)

    def default_path(self) -> str:
        return os.path.join(
            self._dir, f"pslite_trace_{self.role}_{self.node_id}.json"
        )

    def export(self, path: Optional[str] = None) -> Optional[str]:
        """Write the buffered spans as Chrome trace-event JSON; returns
        the path, or None when nothing was recorded.  Idempotent: the
        buffer is kept, a later export rewrites the same file with any
        additional spans."""
        with self._mu:
            events = list(self._events)
        if not events:
            return None
        pid = self.node_id
        label = f"{self.role} {pid}"
        out = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": label},
        }]
        for ev in events:
            ev = dict(ev)
            ev["pid"] = pid
            out.append(ev)
        path = path or self.default_path()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump({"traceEvents": out, "displayTimeUnit": "ms"}, fh)
        os.replace(tmp, path)
        return path

    def export_if_any(self) -> Optional[str]:
        if not self.active or self.num_events == 0:
            return None
        return self.export()

    def _ensure_flush_thread(self) -> None:
        with self._mu:
            if self._flush_thread is not None:
                return
            t = threading.Thread(target=self._flush_loop,
                                 name="trace-flush", daemon=True)
            self._flush_thread = t
        t.start()

    def _flush_loop(self) -> None:
        # Crash-safety, not lifecycle: the daemon thread just rewrites
        # the export periodically so a killed node leaves its spans.
        while True:
            time.sleep(self._flush_s)
            try:
                self.export_if_any()
            except Exception:  # noqa: BLE001 - flush must never die
                pass


class _NullTracer:
    """Do-nothing tracer for stub postoffices (benches)."""

    active = False
    sample = 0.0
    tail = None
    node_id = -1
    num_events = 0

    def maybe_trace(self) -> int:
        return 0

    def begin_request(self) -> int:
        return 0

    def tail_keep(self, dur_s, path, outcome=None):
        return None

    def tail_threshold(self, path):
        return None

    def set_tail_source(self, path, hist) -> None:
        pass

    def note_hints(self, hints) -> None:
        pass

    def now_us(self) -> float:
        return 0.0

    def span(self, *a, **kw) -> None:
        pass

    def instant(self, *a, **kw) -> None:
        pass

    def drain(self):
        return [], 0

    def export(self, path=None):
        return None

    def export_if_any(self):
        return None


NULL_TRACER = _NullTracer()
