"""Test bootstrap: force the CPU backend with 8 virtual devices.

Sharding/collective tests run on a virtual 8-device CPU mesh; real-TPU
benchmarking happens in bench.py (which does NOT import this).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Unit tests always run on the virtual 8-device CPU mesh, whatever the
# environment (axon sitecustomize) tries to force.
try:
    from pslite_tpu.utils.platform_pin import pin_cpu

    pin_cpu(8)
except ImportError:  # jax-less host: non-jax tests still run
    pass

import pytest

# Best-effort build of the native transport core so the suite exercises the
# C++ path; tests still pass on the pure-Python fallback if g++ is missing.
_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if not os.path.exists(os.path.join(_repo, "cpp", "libpslite_core.so")):
    import subprocess

    subprocess.run(
        ["make", "-C", os.path.join(_repo, "cpp")],
        capture_output=True,
        check=False,
    )


# In-process test clusters host many logical nodes in one interpreter; a
# CHECK failure in one node's pump must not os._exit the whole pytest run.
# Multi-process tests that assert the abort behavior override this.
os.environ.setdefault("PS_CHECK_FATAL", "0")


@pytest.fixture(autouse=True)
def _loopback_isolation(request):
    """Give each test its own loopback namespace and clean registry."""
    os.environ["PS_LOOPBACK_NS"] = request.node.nodeid
    yield
    from pslite_tpu.vans import loopback_van

    loopback_van.reset_registry()
    os.environ.pop("PS_LOOPBACK_NS", None)
