"""MultiVan — multi-rail composite transport.

Equivalent of the reference's MultiVan (``src/multi_van.h``): N inner TCP
rails (one per port / NIC / device channel, ``DMLC_NUM_PORTS``), a shared
receive queue fed by per-rail pump threads, control traffic pinned to rail
0, and data traffic routed by the message's device id (falling back to
round-robin) — the multi-NIC pattern that maps to multiple ICI/DCN rails
on TPU pods.

Send lanes are keyed on ``(recver, rail)`` rather than the base class's
per-peer key: the rail is chosen once at enqueue time (stamped on the
message so dispatch agrees), and data round-robinned across rails to
ONE peer streams down all of them concurrently instead of serializing
behind a single per-peer lane.  Per-rail FIFO is preserved per peer;
cross-rail arrival order was never guaranteed (distinct sockets), which
is exactly why receive-side sid reordering (PS_FORCE_REQ_ORDER) exists.
"""

from __future__ import annotations

import itertools
import threading
from typing import List, Optional

from ..message import Message, Node
from ..utils import logging as log
from ..utils.queues import PriorityRecvQueue, ThreadsafeQueue
from .chunking import recv_cost, recv_priority, recv_tenant
from .tcp_van import TcpVan
from .van import Van


def _rail_class(kind: str):
    """Rail transport type (PS_MULTI_RAIL_VAN): tcp (default) or shm.
    The reference's MultiVan composes zmq rails only (multi_van.h:57);
    shm rails generalize the same routing to the same-host fast path —
    each rail gets its own segment namespace and (with PS_SHM_RING) its
    own pipe pair, the multi-channel-per-device UCX pattern
    (ucx_van.h:938-1006) on host memory."""
    if kind == "shm":
        from .shm_van import ShmVan

        class _ShmRail(ShmVan):
            """Transport-only ShmVan rail (control plane unused)."""

        return _ShmRail

    class _TcpRail(TcpVan):
        """A TcpVan used purely as a transport (control plane unused)."""

    return _TcpRail


class MultiVan(Van):
    def __init__(self, postoffice):
        super().__init__(postoffice)
        self.num_rails = max(postoffice.env.find_int("DMLC_NUM_PORTS", 2), 1)
        rail_kind = postoffice.env.find("PS_MULTI_RAIL_VAN", "tcp")
        log.check(rail_kind in ("tcp", "shm"),
                  f"unknown rail van {rail_kind!r}")
        cls = _rail_class(rail_kind)
        self._rails: List[TcpVan] = [
            cls(postoffice) for _ in range(self.num_rails)
        ]
        for i, rail in enumerate(self._rails):
            if hasattr(rail, "_ns"):
                # Disjoint per-rail segment namespaces: data for one
                # (sender, recver, key) round-robins across rails, and
                # two rails resizing/unlinking ONE shared segment file
                # under each other's cached mmaps would corrupt payloads.
                rail._ns = f"{rail._ns}r{i}"
            if getattr(rail, "_native", None) is not None:
                # A striped transfer lands chunk-by-chunk across SEVERAL
                # rails; no single rail's core ever sees every chunk, so
                # receive-side native reassembly must stay off and the
                # shared Python assembler rebuilds (docs/native_core.md).
                rail._native.set_reassembly(False)
        # Merge queue keeps the rails' priority discipline (chunk
        # backlogs from one rail must not delay another rail's priority
        # frames) — same knob as the rails' own intake queues.
        self._queue = (
            PriorityRecvQueue(recv_priority, tenant_fn=recv_tenant,
                              cost_fn=recv_cost,
                              weights=self._tenant_weights)
            if postoffice.env.find_int("PS_RECV_PRIORITY", 1)
            else ThreadsafeQueue()
        )
        self._pumps: List[threading.Thread] = []
        self._rr = itertools.count()

    def bind_transport(self, node: Node, max_retry: int) -> int:
        ports = []
        for i, rail in enumerate(self._rails):
            # Rail 0 owns the advertised port (the scheduler's root port);
            # extra rails take ephemeral ports.
            want = node.port if i == 0 else 0
            sub = Node(role=node.role, hostname=node.hostname, ports=[want])
            port = rail.bind_transport(sub, max_retry)
            # Rails are transport-only: give each its own identity so
            # same-host detection (shm rails) and pipe naming work.
            rail.my_node.hostname = node.hostname
            rail.my_node.ports = [port]
            ports.append(port)
        node.ports = ports
        for i, rail in enumerate(self._rails):
            t = threading.Thread(
                target=self._pump, args=(rail,), name=f"multivan-pump-{i}",
                daemon=True,
            )
            t.start()
            self._pumps.append(t)
        return ports[0]

    def connect_transport(self, node: Node) -> None:
        for i, rail in enumerate(self._rails):
            sub = Node(
                role=node.role,
                id=node.id,
                hostname=node.hostname,
                ports=[node.ports[i % len(node.ports)]],
            )
            rail.connect_transport(sub)

    def _rail_index(self, msg: Message) -> int:
        """The rail this message rides.  Chosen once (then stamped on
        the message) so the lane key picked at enqueue time and the
        rail used at dispatch time always agree — and so a resender
        retransmit reuses the original rail."""
        rail = getattr(msg, "_rail", None)
        if rail is not None:
            return rail
        if not msg.meta.control.empty():
            rail = 0  # control plane rides rail 0
        elif msg.meta.chunk is not None:
            # Chunked streaming transfer (docs/chunking.md): stripe the
            # chunks of ONE transfer deterministically across every
            # rail instead of pinning the whole message to one — the
            # xfer id offsets the start rail so concurrent transfers
            # don't convoy on rail 0.  Overrides device pinning: the
            # whole point of chunking a device-tagged tensor is to use
            # all rails for it.
            ck = msg.meta.chunk
            rail = (ck.xfer + ck.index) % self.num_rails
        else:
            dev = msg.meta.src_dev_id
            if dev is not None and dev >= 0:
                rail = dev % self.num_rails
            else:
                rail = next(self._rr) % self.num_rails
        msg._rail = rail
        return rail

    def _lane_key(self, msg: Message):
        # (recver, rail): one peer's data streams down every rail
        # concurrently; same-rail frames to a peer stay serialized.
        return (msg.meta.recver, self._rail_index(msg))

    def send_msg(self, msg: Message) -> int:
        return self._rails[self._rail_index(msg)].send_msg(msg)

    def recv_msg(self) -> Optional[Message]:
        return self._queue.wait_and_pop()

    def _pump(self, rail: TcpVan) -> None:
        while True:
            msg = rail.recv_msg()
            if msg is None:
                break
            self._queue.push(msg)

    def stop_transport(self) -> None:
        for rail in self._rails:
            rail.stop_transport()  # unblocks each pump's recv_msg
        for t in self._pumps:
            t.join(timeout=5)
        self._queue.push(None)

    def post_stop(self) -> None:
        for rail in self._rails:
            rail.post_stop()  # frees native cores after pumps exited
