"""Thread-safe queues used by vans and customers.

``ThreadsafeQueue`` is the equivalent of the reference's
(``include/ps/internal/threadsafe_queue.h:18-118``): a mutex+condvar MPMC
queue, with an optional busy-poll mode (``DMLC_LOCKLESS_QUEUE`` /
``DMLC_POLLING_IN_NANOSECOND``) that trades CPU for latency on the hot
receive path.

``LaneQueue`` backs the van's per-peer send lanes: a max-priority heap
that is FIFO within a priority level, with the drain/stop handshake the
lane scheduler needs (the owner supplies scheduler-wide stop/abort
predicates at pop time so one decision governs every lane).
"""

from __future__ import annotations

import collections
import heapq
import threading
import time
from typing import (
    Callable, Deque, Dict, Generic, List, Optional, Tuple, TypeVar,
)

T = TypeVar("T")


class ThreadsafeQueue(Generic[T]):
    def __init__(self, busy_poll_ns: int = 0, maxsize: int = 0):
        self._q: Deque[T] = collections.deque()
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        # Bounded mode (maxsize > 0): push blocks while the queue is
        # full — the backpressure the Customer's executor mode needs so
        # a slow handler stalls the pump instead of ballooning memory.
        self._maxsize = maxsize
        self._not_full = threading.Condition(self._mu)
        # Busy-poll window before falling back to a blocking wait.
        self._busy_poll_s = busy_poll_ns / 1e9

    def push(self, item: T) -> None:
        with self._cv:
            if self._maxsize > 0:
                while len(self._q) >= self._maxsize:
                    self._not_full.wait()
            self._q.append(item)
            self._cv.notify()

    def _popped_locked(self) -> None:
        if self._maxsize > 0:
            self._not_full.notify()

    def wait_and_pop(self, timeout: Optional[float] = None) -> Optional[T]:
        """Pop the next item, blocking.  Returns None on timeout."""
        if self._busy_poll_s > 0:
            deadline = time.monotonic() + self._busy_poll_s
            while time.monotonic() < deadline:
                with self._mu:
                    if self._q:
                        self._popped_locked()
                        return self._q.popleft()
        with self._cv:
            if timeout is None:
                while not self._q:
                    self._cv.wait()
            else:
                deadline = time.monotonic() + timeout
                while not self._q:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cv.wait(remaining):
                        if not self._q:
                            return None
            self._popped_locked()
            return self._q.popleft()

    def try_pop(self) -> Optional[T]:
        with self._mu:
            if not self._q:
                return None
            self._popped_locked()
            return self._q.popleft()

    def __len__(self) -> int:
        with self._mu:
            return len(self._q)


class PriorityRecvQueue(Generic[T]):
    """Receive-side mirror of the lane discipline (docs/chunking.md):
    highest priority first, FIFO within a level.  Without it, a
    priority frame that jumped every send lane still waits behind the
    whole decoded chunk backlog in the receiver's FIFO — the pump, not
    the wire, becomes the head-of-line block.

    ``priority_fn`` maps an item to its level (called at push unless an
    explicit ``priority`` is given — transports that decode lazily pass
    the level they learned at send time).  The shutdown sentinel and
    TERMINATE should map to a very low level so they drain last,
    preserving the FIFO contract that queued traffic is delivered
    before the pump retires."""

    def __init__(self, priority_fn: Callable[[T], int]):
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._heap: List[Tuple[int, int, T]] = []
        self._seq = 0
        self._priority_fn = priority_fn
        # Fence sequence numbers (push(..., fence=True)): while a fence
        # item is queued, nothing pushed AFTER it may overtake it —
        # pops are restricted to items at or before the earliest live
        # fence.  This is what keeps an all-shard barrier op (the apply
        # pool's global requests) starvation-free under a sustained
        # higher-priority stream: without it, one flooded shard could
        # park every sibling shard behind the barrier forever.
        self._fences: set = set()

    def push(self, item: T, priority: Optional[int] = None,
             fence: bool = False) -> None:
        if priority is None:
            priority = self._priority_fn(item)
        with self._cv:
            heapq.heappush(self._heap, (-priority, self._seq, item))
            if fence:
                self._fences.add(self._seq)
            self._seq += 1
            self._cv.notify()

    def _pop_locked(self) -> T:
        if self._fences:
            fmin = min(self._fences)
            if self._heap[0][1] > fmin:
                # The heap top was pushed after the earliest fence:
                # pop the best ELIGIBLE entry instead (highest
                # priority, FIFO within a level, seq <= fence).  Rare
                # path — only while a barrier op is queued — so the
                # linear scan + re-heapify stays off the hot pops.
                best = min(e for e in self._heap if e[1] <= fmin)
                self._heap.remove(best)
                heapq.heapify(self._heap)
                self._fences.discard(best[1])
                return best[2]
            entry = heapq.heappop(self._heap)
            self._fences.discard(entry[1])
            return entry[2]
        return heapq.heappop(self._heap)[2]

    def wait_and_pop(self, timeout: Optional[float] = None) -> Optional[T]:
        with self._cv:
            if timeout is None:
                while not self._heap:
                    self._cv.wait()
            else:
                deadline = time.monotonic() + timeout
                while not self._heap:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cv.wait(remaining):
                        if not self._heap:
                            return None
            return self._pop_locked()

    def try_pop(self) -> Optional[T]:
        with self._mu:
            if not self._heap:
                return None
            return self._pop_locked()

    def __len__(self) -> int:
        with self._mu:
            return len(self._heap)


class LaneQueue(Generic[T]):
    """Priority queue for one send lane: highest priority first, FIFO
    within a priority level (heap ordered by ``(-priority, seq)``; the
    unique seq also keeps the heap from ever comparing items).

    The consumer loop is ``pop`` → work → ``done``; ``inflight`` covers
    the window between the two so ``wait_idle`` cannot report a drained
    lane while its last item is still being dispatched.
    """

    def __init__(self):
        self.cv = threading.Condition()
        self._heap: List[Tuple[int, int, T]] = []
        self._seq = 0
        self._inflight = False
        # Cumulative dispatched bytes per priority level (the owner
        # calls note_dispatch after each wire write).  Backs the van's
        # head-of-line accounting: a message snapshots bytes_below(its
        # priority) at enqueue; a positive delta at dequeue means it
        # waited behind lower-priority bytes (``van.hol_wait_s``).
        self._sent_bytes: Dict[int, int] = {}

    def push(self, priority: int, item: T,
             unless: Optional[Callable[[], bool]] = None) -> bool:
        """Enqueue ``item``; returns False (nothing queued) when the
        ``unless`` predicate holds — checked under the lock, so a
        concurrent drain retiring the consumer cannot strand the item."""
        with self.cv:
            if unless is not None and unless():
                return False
            heapq.heappush(self._heap, (-priority, self._seq, item))
            self._seq += 1
            self.cv.notify()
            return True

    def pop(self, stopping: Callable[[], bool],
            aborting: Callable[[], bool]) -> Tuple[Optional[T], int]:
        """Blocking pop.  Returns ``(item, 0)`` normally; ``(None, n)``
        when the consumer must exit — with ``n`` the number of queued
        items discarded by an abort (0 on a clean drained stop)."""
        with self.cv:
            while True:
                if aborting():
                    dropped = len(self._heap)
                    self._heap.clear()
                    self.cv.notify_all()
                    return None, dropped
                if self._heap:
                    _, _, item = heapq.heappop(self._heap)
                    self._inflight = True
                    return item, 0
                if stopping():
                    return None, 0
                self.cv.wait()

    def done(self) -> None:
        """Mark the popped item dispatched; wakes ``wait_idle`` waiters
        when the lane went idle."""
        with self.cv:
            self._inflight = False
            if not self._heap:
                self.cv.notify_all()

    def wait_idle(self, deadline: float) -> bool:
        """Block until the lane is empty AND nothing is in flight (or
        ``time.monotonic()`` passes ``deadline``); True when idle."""
        with self.cv:
            while ((self._heap or self._inflight)
                   and time.monotonic() < deadline):
                self.cv.wait(timeout=0.1)
            return not (self._heap or self._inflight)

    def note_dispatch(self, priority: int, nbytes: int) -> None:
        """Record ``nbytes`` dispatched at ``priority`` (HOL ledger)."""
        with self.cv:
            self._sent_bytes[priority] = (
                self._sent_bytes.get(priority, 0) + nbytes
            )

    def bytes_below(self, priority: int) -> int:
        """Cumulative bytes this lane has dispatched at priorities
        strictly below ``priority`` (the levels in play are few, so the
        sum is a handful of dict entries)."""
        with self.cv:
            return sum(v for p, v in self._sent_bytes.items()
                       if p < priority)

    def wake(self) -> None:
        """Nudge the consumer to re-check its stop/abort predicates."""
        with self.cv:
            self.cv.notify_all()

    def drain(self) -> List[T]:
        """Remove and return every queued item (heap order).  Used to
        fail a dead peer's parked messages fast instead of letting them
        sit until the drain deadline."""
        with self.cv:
            items = [item for _, _, item in sorted(self._heap)]
            self._heap.clear()
            self.cv.notify_all()
            return items

    def __len__(self) -> int:
        with self.cv:
            return len(self._heap)
