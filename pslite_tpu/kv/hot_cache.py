"""Worker-side hot-key pull cache (docs/qos.md).

"RPC Considered Harmful" (PAPERS.md): for the head of a Zipf key
distribution the round trip itself — not bytes — is the dominant
serving cost.  This cache lets ``KVWorker.pull`` answer a repeat pull
of hot keys locally, with staleness bounded by a *push-driven version
stamp* piggybacked on every server response:

- The server keeps a per-node **push version** — bumped after each push
  has fully applied, *before* its response is emitted — and stamps
  every response with it.  A pull response's stamp is read at request
  intake, so it is a version every value in the response is guaranteed
  to have observed (never ahead of the snapshot).
- The worker records the newest stamp it has seen per server
  (``observe``).  A cached entry is served only while its fill stamp is
  still the newest known for its server — ANY completed push the
  worker hears about (its own pushes above all) invalidates older
  fills, so a worker can never read its own writes stale, and a racing
  fill whose response predates a known push parks invalid on arrival.
- Cross-worker writes the local worker has not heard about are bounded
  by ``PS_HOT_CACHE_TTL_S`` (async-PS serving semantics: a bounded-age
  parameter read, exactly what the DLRM inference path tolerates).

The cache is a byte-bounded LRU (``PS_HOT_CACHE_MB``); ``seed``
restricts admission to a hot set (``KVWorker.seed_hot_cache`` fills it
from the servers' ``kv.hot_keys`` top-k) — unseeded, every smallish
pulled value is admitted and the LRU keeps whatever repeats.

Batching interplay (docs/batching.md): the stamp contract is PER
SUB-OP end to end — a batched request's pull sub-ops each capture
their own intake stamp, the batched response's per-op table carries
each sub-op's stamp, and the worker runs ``observe``/``fill`` per
sub-op — so read-your-writes (and the fill-race skip below) survive
the aggregation plane unchanged.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Optional, Set

import numpy as np


class HotKeyCache:
    """Bounded LRU of per-key pull values with stamp + TTL validity."""

    def __init__(self, max_bytes: int, ttl_s: float = 1.0,
                 max_val_bytes: int = 1 << 20, metrics=None):
        self.max_bytes = int(max_bytes)
        self.ttl_s = float(ttl_s)
        self.max_val_bytes = int(max_val_bytes)
        self._mu = threading.Lock()
        # key -> (vals copy, server id, fill stamp, fill monotonic time)
        self._entries: "collections.OrderedDict[int, tuple]" = (
            collections.OrderedDict()
        )
        self._bytes = 0
        # Newest push-version stamp seen per server node id.
        self._latest: Dict[int, int] = {}
        # Admission hot set (None = admit everything).
        self._hot: Optional[Set[int]] = None
        if metrics is not None:
            self._c_hits = metrics.counter("kv.hot_cache.hits")
            self._c_misses = metrics.counter("kv.hot_cache.misses")
            self._c_invalidations = metrics.counter(
                "kv.hot_cache.invalidations")
            metrics.gauge("kv.hot_cache.bytes", fn=lambda: self._bytes)
            metrics.gauge("kv.hot_cache.entries",
                          fn=lambda: len(self._entries))
        else:  # stub harnesses
            class _N:  # noqa: D401 - trivial no-op counter
                def inc(self, n=1):
                    pass
            self._c_hits = self._c_misses = self._c_invalidations = _N()

    # -- stamps ---------------------------------------------------------------

    def observe(self, server: int, stamp: int) -> None:
        """Record a response stamp.  A newer stamp than previously seen
        from this server invalidates (lazily) every older fill — the
        push-driven invalidation path."""
        if stamp <= 0:
            return
        with self._mu:
            cur = self._latest.get(server, 0)
            if stamp > cur:
                self._latest[server] = stamp
                if cur:
                    self._c_invalidations.inc()

    def invalidate_range(self, begin: int, end: int) -> int:
        """Drop every cached entry whose key lies in ``[begin, end)``
        (docs/elasticity.md): when a key range migrates to a new owner,
        a cached fill's stamp was minted by the OLD owner — the new
        owner's independent version counter can never invalidate it, so
        a migrated key must not be served from the old stamp at all.
        Returns the number of entries dropped."""
        with self._mu:
            doomed = [k for k in self._entries if begin <= k < end]
            for k in doomed:
                seg = self._entries.pop(k)[0]
                self._bytes -= seg.nbytes
            if doomed:
                self._c_invalidations.inc(len(doomed))
            return len(doomed)

    # -- seeding --------------------------------------------------------------

    def seed(self, keys) -> None:
        """Restrict admission to (the union of) seeded hot keys —
        ``KVWorker.seed_hot_cache`` feeds it the servers' ``kv.hot_keys``
        top-k.  Never seeded, everything is admissible."""
        with self._mu:
            if self._hot is None:
                self._hot = set()
            self._hot.update(int(k) for k in np.asarray(keys).reshape(-1))

    # -- fill / serve ---------------------------------------------------------

    def fill(self, server: int, stamp: int, keys: np.ndarray,
             vals: np.ndarray) -> None:
        """Admit one pull-response slice (fixed-k payloads only; the
        caller checked divisibility).  Values are COPIED — response
        buffers live in pooled receive arenas that recycle."""
        n = len(keys)
        if n == 0 or stamp <= 0:
            return
        k = len(vals) // n
        if k * n != len(vals):
            return
        seg_bytes = k * vals.itemsize
        if seg_bytes > self.max_val_bytes:
            return
        now = time.monotonic()
        with self._mu:
            if stamp < self._latest.get(server, 0):
                # The response predates a push we already know about
                # (the invalidation race): filling it would resurrect a
                # stale value behind a fresh-looking lookup path — the
                # entry would be born invalid anyway, so skip the copy.
                return
            hot = self._hot
            for i, key in enumerate(keys):
                key = int(key)
                if hot is not None and key not in hot:
                    continue
                old = self._entries.pop(key, None)
                if old is not None:
                    self._bytes -= old[0].nbytes
                seg = np.array(vals[i * k:(i + 1) * k])  # owned copy
                self._entries[key] = (seg, server, stamp, now)
                self._bytes += seg.nbytes
            while self._bytes > self.max_bytes and self._entries:
                _, (seg, *_rest) = self._entries.popitem(last=False)
                self._bytes -= seg.nbytes

    def serve(self, keys: np.ndarray, out: np.ndarray) -> bool:
        """All-or-nothing local serve: when EVERY requested key has a
        live (stamp-fresh, TTL-fresh) entry, copy the values into
        ``out`` in key order and return True.  Partial hits return
        False untouched — the request then takes the normal round trip
        (whose response re-fills the cache)."""
        n = len(keys)
        if n == 0:
            return False
        now = time.monotonic()
        with self._mu:
            segs = []
            total = 0
            for key in keys:
                e = self._entries.get(int(key))
                if e is None:
                    self._c_misses.inc()
                    return False
                seg, server, stamp, t_fill = e
                if (stamp < self._latest.get(server, 0)
                        or (self.ttl_s > 0 and now - t_fill > self.ttl_s)):
                    # Invalid (superseded by a push, or aged out): drop
                    # it now so the table doesn't hold dead weight.
                    self._entries.pop(int(key), None)
                    self._bytes -= seg.nbytes
                    self._c_misses.inc()
                    return False
                segs.append(seg)
                total += seg.size
            flat = out.reshape(-1)
            if total != flat.size:
                self._c_misses.inc()
                return False  # caller's buffer shape disagrees: miss
            off = 0
            for key, seg in zip(keys, segs):
                flat[off:off + seg.size] = seg
                off += seg.size
                self._entries.move_to_end(int(key))  # LRU touch
            self._c_hits.inc()
            return True

    def serve_mask(self, keys: np.ndarray,
                   out: np.ndarray) -> Optional[np.ndarray]:
        """Partial serve (``KVWorker.multi_get`` fast path): copy every
        LIVE (stamp-fresh, TTL-fresh) entry's values into its key's row
        of ``out`` and return the boolean hit mask — the caller fetches
        only the misses.  Returns ``None`` (nothing touched) when the
        buffer shape cannot be row-partitioned (``out.size`` not
        divisible by ``len(keys)``); a live entry whose size disagrees
        with the row size counts a miss.  Validity rules are exactly
        :meth:`serve`'s
        — a superseded or aged entry counts a miss and is dropped — so
        read-your-writes semantics are identical whether a key is
        served through the all-or-nothing or the partial path.  Hits
        and misses are counted PER KEY (``serve`` counts per call)."""
        n = len(keys)
        if n == 0:
            return None
        flat = out.reshape(-1)
        if flat.size % n:
            return None
        k = flat.size // n
        mask = np.zeros(n, dtype=bool)
        now = time.monotonic()
        with self._mu:
            for i, key in enumerate(keys):
                key = int(key)
                e = self._entries.get(key)
                if e is None:
                    self._c_misses.inc()
                    continue
                seg, server, stamp, t_fill = e
                if (stamp < self._latest.get(server, 0)
                        or (self.ttl_s > 0
                            and now - t_fill > self.ttl_s)):
                    self._entries.pop(key, None)
                    self._bytes -= seg.nbytes
                    self._c_misses.inc()
                    continue
                if seg.size != k:
                    # Cached under a different per-key length (another
                    # pull shape): not servable into this row — a miss,
                    # but still a valid entry for its own shape.
                    self._c_misses.inc()
                    continue
                flat[i * k:(i + 1) * k] = seg
                self._entries.move_to_end(key)  # LRU touch
                mask[i] = True
                self._c_hits.inc()
        return mask

    # -- introspection --------------------------------------------------------

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)

    @property
    def nbytes(self) -> int:
        return self._bytes

    def clear(self) -> None:
        with self._mu:
            self._entries.clear()
            self._bytes = 0
