"""ctypes bindings to the native C++ transport core (cpp/pslite_core.cc).

Loads ``cpp/libpslite_core.so`` when present (``make -C cpp``); the TCP van
then runs its socket IO, frame assembly, and receive queue on native
threads, GIL-free — the counterpart of the reference keeping its Van layer
in C++.  ``PS_NATIVE=0`` forces the pure-Python path.
"""

from __future__ import annotations

import ctypes
import os
import struct
import threading
import weakref
from typing import List, Optional, Tuple

import numpy as np

_LIB_PATHS = [
    # Source tree: cpp/ build output (make -C cpp).
    os.path.join(os.path.dirname(__file__), "..", "..", "cpp",
                 "libpslite_core.so"),
    # Installed wheel: the copy `make -C cpp` places inside the package.
    os.path.join(os.path.dirname(__file__), "..", "libpslite_core.so"),
    "libpslite_core.so",
]

# Must match kAbiVersion in cpp/pslite_core.cc: a stale .so (make -C cpp
# not rerun after a source update) is rejected LOUDLY at load time —
# the old posture silently fell back per-symbol, which left half-built
# hosts running the pure-Python path with no hint why.
ABI_VERSION = 9  # 9: wire-plane counter snapshot (docs/observability.md)

_lib = None
_load_warned = False
_load_failed = False  # negative load() result cache (process lifetime)


class _FrameView(ctypes.Structure):
    _fields_ = [
        ("buf", ctypes.POINTER(ctypes.c_uint8)),
        ("meta_len", ctypes.c_uint32),
        ("n_data", ctypes.c_uint32),
    ]


class _WireStats(ctypes.Structure):
    """Mirror of ``psl_wire_stats`` (cpp/pslite_core.cc): the native
    wire-plane counter block, snapshotted whole in one FFI call.  The
    leading ``abi`` field echoes the library's stamp; the struct only
    grows at the end, and ``psl_stats_snapshot`` returns the byte size
    it wrote so layout drift is detectable."""

    _fields_ = [
        ("abi", ctypes.c_uint64),
        ("tx_syscalls", ctypes.c_uint64),
        ("tx_frames", ctypes.c_uint64),
        ("tx_chunks", ctypes.c_uint64),
        ("tx_bytes", ctypes.c_uint64),
        ("tx_msgs", ctypes.c_uint64),
        ("rx_syscalls", ctypes.c_uint64),
        ("rx_frames", ctypes.c_uint64),
        ("rx_bytes_copy", ctypes.c_uint64),
        ("rx_bytes_zc", ctypes.c_uint64),
        ("rx_pool_hits", ctypes.c_uint64),
        ("rx_pool_misses", ctypes.c_uint64),
    ]


class _NativeFrame(np.ndarray):
    """ndarray view over a pooled native frame buffer.  Exists solely
    because plain ndarrays reject weak references: recv() attaches the
    psl_frame_free finalizer to this subclass view, and every segment
    sliced from it keeps it alive through the base chain."""


# Writable zero-copy memoryview over foreign memory (the pooled frame):
# avoids minting a ctypes array TYPE per distinct frame length.
_PyMemoryView_FromMemory = ctypes.pythonapi.PyMemoryView_FromMemory
_PyMemoryView_FromMemory.restype = ctypes.py_object
_PyMemoryView_FromMemory.argtypes = [
    ctypes.c_void_p, ctypes.c_ssize_t, ctypes.c_int,
]
_PyBUF_WRITE = 0x200


def _warn_once(msg: str) -> None:
    global _load_warned
    if _load_warned:
        return
    _load_warned = True
    from ..utils import logging as log

    log.warning(msg)


def load(env=None) -> Optional[ctypes.CDLL]:
    """The native library, or None when unavailable/disabled.

    ``env`` (an :class:`~..environment.Environment`) routes the
    ``PS_NATIVE`` check through the CALLER's per-node override map —
    in-process multi-node clusters give each node its own Environment,
    and a node-level ``PS_NATIVE=0`` must force the pure-Python path
    for that node even when the process environment allows native.
    """
    if env is not None:
        enabled = env.find("PS_NATIVE", "1")
    else:
        enabled = os.environ.get("PS_NATIVE", "1")
    if enabled in ("0", "false"):
        return None
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    # Cache the NEGATIVE result too: try_iadd calls load() per applied
    # key on the server's push hot path, and re-walking the candidate
    # paths through failed dlopens on every call silently taxes exactly
    # the pure-Python deployment that has no .so to find.
    if _load_failed:
        return None
    for path in _LIB_PATHS:
        try:
            lib = ctypes.CDLL(os.path.abspath(path)
                              if os.path.sep in path else path)
        except OSError:
            continue
        try:
            _declare(lib)
        except AttributeError as exc:
            # Stale .so missing a symbol (make -C cpp not rerun after an
            # update): reject the WHOLE library loudly — per-symbol
            # fallback would mix two ABI generations in one process.
            _warn_once(
                f"stale libpslite_core.so at {path} ({exc}); rebuild "
                f"with `make native` — falling back to pure Python"
            )
            continue
        stamp = lib.psl_abi_version()
        if stamp != ABI_VERSION:
            _warn_once(
                f"libpslite_core.so at {path} has ABI stamp {stamp}, "
                f"expected {ABI_VERSION}; rebuild with `make native` — "
                f"falling back to pure Python"
            )
            continue
        _lib = lib
        return _lib
    _load_failed = True
    return None


def _declare(lib: ctypes.CDLL) -> None:
    """Declare every symbol's signature; a stale .so missing one raises
    AttributeError here (caught by load's candidate loop)."""
    lib.psl_abi_version.restype = ctypes.c_int
    lib.psl_abi_version.argtypes = []
    lib.psl_stats_snapshot.restype = ctypes.c_int
    lib.psl_stats_snapshot.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(_WireStats)
    ]
    lib.psl_create.restype = ctypes.c_void_p
    lib.psl_bind.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
    lib.psl_connect.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_int,
    ]
    lib.psl_bind_local.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int
    ]
    lib.psl_connect_local.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int
    ]
    lib.psl_pipe_connect.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64
    ]
    lib.psl_pipe_watch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.psl_send.restype = ctypes.c_longlong
    lib.psl_send.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.psl_send_enqueue.restype = ctypes.c_longlong
    lib.psl_send_enqueue.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64, ctypes.c_int32,
    ]
    lib.psl_send_reap.restype = ctypes.c_int
    lib.psl_send_reap.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_longlong),
        ctypes.c_int,
    ]
    lib.psl_send_flush.restype = ctypes.c_int
    lib.psl_send_flush.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.psl_send_cancel.restype = ctypes.c_longlong
    lib.psl_send_cancel.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.psl_send_reset_sid.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.psl_set_reassembly.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.psl_set_rails.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.psl_add_rail.restype = ctypes.c_int
    lib.psl_add_rail.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_int, ctypes.c_int,
    ]
    lib.psl_set_sockbuf.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int
    ]
    lib.psl_recv.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(_FrameView), ctypes.c_int
    ]
    lib.psl_frame_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.psl_stop.argtypes = [ctypes.c_void_p]
    lib.psl_destroy.argtypes = [ctypes.c_void_p]
    lib.psl_memcpy.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64
    ]
    lib.psl_iadd_f32.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64
    ]
    lib.psl_iadd_f64.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64
    ]
    lib.psl_copy_pool_create.restype = ctypes.c_void_p
    lib.psl_copy_pool_create.argtypes = [ctypes.c_int]
    lib.psl_copy_pool_copy.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64
    ]
    lib.psl_copy_pool_destroy.argtypes = [ctypes.c_void_p]
    # Fused wire-codec kernels (ops/codecs.py — docs/compression.md).
    lib.psl_codec_set_fp8_tables.restype = None
    lib.psl_codec_set_fp8_tables.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.psl_codec_encode.restype = ctypes.c_int
    lib.psl_codec_encode.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_void_p,
        ctypes.c_void_p,
    ]
    lib.psl_codec_decode.restype = ctypes.c_int
    lib.psl_codec_decode.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int,
        ctypes.c_void_p,
    ]
    lib.psl_codec_encode_mt.restype = ctypes.c_int
    lib.psl_codec_encode_mt.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_int,
    ]
    lib.psl_codec_decode_mt.restype = ctypes.c_int
    lib.psl_codec_decode_mt.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int,
        ctypes.c_void_p, ctypes.c_int,
    ]
    lib.psl_codec_decode_ranges.restype = ctypes.c_int
    lib.psl_codec_decode_ranges.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ctypes.c_uint64, ctypes.c_int, ctypes.c_void_p,
    ]


# -- single-shot GIL-free kernels ------------------------------------------
#
# ctypes releases the GIL around CDLL calls, so routing the receive-side
# hot loops' big numpy ops (chunk-scatter copies, the server's in-place
# apply adds) through the core lets the van-recv pump, the apply shard
# threads, and frame decode stream concurrently instead of serializing
# on one GIL.  Both kernels are bit-identical to the numpy ops they
# replace (memcpy / element-wise IEEE add on the same dtype), so the
# native path can never change stored values — it only removes GIL
# contention.  Calls cost a ctypes trampoline (~1 us), so callers only
# divert work above a size floor.

#: Below this many bytes a numpy slice-assign beats the ctypes call.
COPY_KERNEL_MIN = 64 << 10
#: Below this many elements numpy's ufunc dispatch is cheaper.
IADD_KERNEL_MIN = 4096

_IADD_SYMS = {"float32": "psl_iadd_f32", "float64": "psl_iadd_f64"}


def memcpy_kernel(env=None):
    """The raw ``psl_memcpy(dst_ptr, src_ptr, nbytes)`` ctypes function,
    or None when the native core is unavailable or ``PS_NATIVE=0`` for
    this node.  The caller owns pointer validity and overlap rules
    (memcpy semantics: ranges must not overlap)."""
    lib = load(env)
    return lib.psl_memcpy if lib is not None else None


def scatter_copy_kernel(env=None):
    """A ``(dst_ptr, src_ptr, nbytes)`` copy kernel for the chunk
    assembler's scatter: multi-MiB copies split across the process-wide
    :class:`CopyPool` threads (``PS_COPY_THREADS``, default 4) so the
    receive pump's dominant cost — landing each chunk in the reassembly
    buffer — runs at parallel-memcpy speed; sub-MiB copies degrade to
    one inline native memcpy inside the pool call.  Falls back to the
    single-threaded ``psl_memcpy`` when the pool cannot start, or None
    when the core is unavailable/disabled for this node."""
    lib = load(env)
    if lib is None:
        return None
    n = 4
    if env is not None:
        n = env.find_int("PS_COPY_THREADS", 4)
    else:
        try:
            n = int(os.environ.get("PS_COPY_THREADS", "4"))
        except ValueError:
            n = 4
    if n <= 0:
        return lib.psl_memcpy

    # The pool threads spawn LAZILY on the first real scatter: every
    # Van constructs an assembler (schedulers, control-only nodes,
    # PS_CHUNK_BYTES=0 vans), and eagerly starting a process-wide
    # 4-thread pool for nodes that never receive a chunk wastes
    # threads.  Benign if two pumps race the first call —
    # shared_copy_pool is process-wide idempotent under its own lock.
    state: dict = {}

    def kernel(dst_addr, src_addr, nbytes):
        fn = state.get("fn")
        if fn is None:
            pool = shared_copy_pool(n, env)
            fn = pool.copy if pool is not None else lib.psl_memcpy
            state["fn"] = fn
        fn(dst_addr, src_addr, nbytes)

    return kernel


def try_iadd(dst: np.ndarray, src: np.ndarray, env=None) -> bool:
    """GIL-free in-place ``dst += src`` when eligible; returns False
    (caller must run the numpy path) for small/odd-dtype/unaligned/
    non-contiguous operands or when the core is unavailable.  Result
    bits are identical to numpy's same-dtype in-place add."""
    if dst.size < IADD_KERNEL_MIN or dst.dtype != src.dtype:
        return False
    sym = _IADD_SYMS.get(dst.dtype.name)
    if sym is None:
        return False
    lib = load(env)
    if lib is None:
        return False
    if (not dst.flags.c_contiguous or not src.flags.c_contiguous
            or dst.size != src.size):
        return False
    align = dst.dtype.itemsize
    dp, sp = dst.ctypes.data, src.ctypes.data
    if dp % align or sp % align:
        # The payload view may start at an arbitrary wire offset; the
        # C loop dereferences typed pointers, so misaligned operands
        # stay on the numpy path rather than risk UB.
        return False
    getattr(lib, sym)(dp, sp, dst.size)
    return True


class CopyPool:
    """Parallel memcpy on persistent native threads — the IPC transport's
    copy-thread-pool analog (rdma_transport.h:469-633).  ctypes releases
    the GIL for the call, so the pool threads and the caller all stream
    bytes concurrently on multi-core hosts."""

    def __init__(self, n_threads: int):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native core not available")
        self._h = self._lib.psl_copy_pool_create(n_threads)

    def copy(self, dst_addr: int, src_addr: int, nbytes: int) -> None:
        """Raw-pointer copy; the caller owns keeping both buffers alive."""
        h = self._h
        if not h:
            raise RuntimeError("copy pool is closed")
        self._lib.psl_copy_pool_copy(h, dst_addr, src_addr, nbytes)

    def close(self) -> None:
        if self._h:
            self._lib.psl_copy_pool_destroy(self._h)
            self._h = None

    def __del__(self):  # best-effort: pools are owned by long-lived vans
        try:
            self.close()
        except Exception:
            pass


_shared_pool: Optional[CopyPool] = None
_shared_pool_mu = threading.Lock()


def shared_copy_pool(n_threads: int, env=None) -> Optional[CopyPool]:
    """One process-wide pool, like the reference's single
    BYTEPS_IPC_COPY_NUM_THREADS pool: co-located vans share its threads
    (Copy serializes jobs internally), and its lifetime is the process —
    individual van shutdown never races a peer van's in-flight copy.
    The first caller's thread count wins."""
    global _shared_pool
    if load(env) is None:
        return None
    with _shared_pool_mu:
        if _shared_pool is None:
            _shared_pool = CopyPool(n_threads)
        return _shared_pool


class NativeTransport:
    """Thin OO wrapper over the C API."""

    def __init__(self):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native core not available")
        self._h = self._lib.psl_create()

    def bind(self, port: int, backlog: int = 128) -> int:
        rc = self._lib.psl_bind(self._h, port, backlog)
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))
        return rc

    def stats(self) -> dict:
        """The core's wire-plane counter block as a dict of absolute
        monotonic totals (one struct-snapshot FFI call; the van folds
        these into ``wire.native.*`` registry counters as deltas)."""
        out = _WireStats()
        n = self._lib.psl_stats_snapshot(self._h, ctypes.byref(out))
        if n < ctypes.sizeof(_WireStats):
            raise RuntimeError(
                f"psl_stats_snapshot wrote {n} bytes, expected "
                f"{ctypes.sizeof(_WireStats)} — ABI drift"
            )
        return {name: int(getattr(out, name))
                for name, _ in _WireStats._fields_ if name != "abi"}

    def connect(self, node_id: int, host: str, port: int,
                timeout_ms: int = 30000) -> None:
        rc = self._lib.psl_connect(
            self._h, node_id, host.encode(), port, timeout_ms
        )
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))

    def bind_local(self, path: str, backlog: int = 128) -> None:
        """DMLC_LOCAL mode: listen on a unix-domain socket at ``path``."""
        rc = self._lib.psl_bind_local(self._h, path.encode(), backlog)
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))

    def connect_local(self, node_id: int, path: str,
                      timeout_ms: int = 30000) -> None:
        rc = self._lib.psl_connect_local(
            self._h, node_id, path.encode(), timeout_ms
        )
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))

    def pipe_connect(self, node_id: int, path: str, data_bytes: int) -> None:
        """PS_SHM_RING: route this peer's whole stream through a
        shared-memory SPSC byte pipe created at ``path``."""
        rc = self._lib.psl_pipe_connect(
            self._h, node_id, path.encode(), data_bytes
        )
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))

    def pipe_watch(self, directory: str, prefix: str, suffix: str,
                   idle_cap_us: int = 0) -> None:
        """Start attaching inbound pipes named <prefix>*<suffix> in
        ``directory`` as they appear (poller thread).  ``idle_cap_us``
        bounds the poller's idle backoff (0 = keep default)."""
        rc = self._lib.psl_pipe_watch(
            self._h, directory.encode(), prefix.encode(), suffix.encode(),
            idle_cap_us,
        )
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))

    def send(self, node_id: int, meta: bytes, data: List[memoryview]) -> int:
        n = len(data)
        bufs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_uint64 * n)()
        keepalive = []
        for i, d in enumerate(data):
            mv = memoryview(d).cast("B")
            if mv.readonly:
                mv = memoryview(bytearray(mv))
            c = (ctypes.c_uint8 * len(mv)).from_buffer(mv)
            keepalive.append((mv, c))
            bufs[i] = ctypes.addressof(c)
            lens[i] = len(mv)
        meta_buf = (ctypes.c_uint8 * len(meta)).from_buffer_copy(meta)
        rc = self._lib.psl_send(
            self._h, node_id, meta_buf, len(meta), n, bufs, lens
        )
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))
        return int(rc)

    # -- descriptor handoff: native sender lanes (docs/native_core.md) -------

    def send_enqueue(self, node_id: int, priority: int, meta: bytes,
                     arrs: List[np.ndarray], chunk_bytes: int = 0,
                     chunk_ext_off: int = -1) -> int:
        """Enqueue one data frame (or a whole chunked transfer) onto the
        peer's native sender lane; returns a ticket immediately.  The
        CALLER owns keeping ``arrs`` (contiguous ndarrays) alive and
        unmutated until the ticket is reaped — the native side records
        raw pointers, copying only the small meta template."""
        n = len(arrs)
        bufs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_uint64 * n)()
        for i, a in enumerate(arrs):
            bufs[i] = a.ctypes.data
            lens[i] = a.nbytes
        rc = self._lib.psl_send_enqueue(
            self._h, node_id, priority, meta, len(meta), n, bufs, lens,
            chunk_bytes, chunk_ext_off,
        )
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))
        return int(rc)

    _REAP_CAP = 256

    def send_reap(self, node_id: int) -> List[Tuple[int, int]]:
        """Completed (ticket, status) pairs for one peer; status 0 =
        transmitted, negative = -errno (the frame was abandoned)."""
        out: List[Tuple[int, int]] = []
        tickets = (ctypes.c_uint64 * self._REAP_CAP)()
        status = (ctypes.c_longlong * self._REAP_CAP)()
        while True:
            n = self._lib.psl_send_reap(
                self._h, node_id, tickets, status, self._REAP_CAP
            )
            out.extend((int(tickets[i]), int(status[i])) for i in range(n))
            if n < self._REAP_CAP:
                return out

    def send_flush(self, timeout_ms: int = -1) -> bool:
        """Wait until every lane transmitted (or abandoned) its queue."""
        return self._lib.psl_send_flush(self._h, timeout_ms) == 0

    def send_cancel(self, node_id: int) -> int:
        """Drop the peer's queued descriptors (tickets reap as errors)."""
        return int(self._lib.psl_send_cancel(self._h, node_id))

    def send_reset_sid(self, node_id: int) -> None:
        self._lib.psl_send_reset_sid(self._h, node_id)

    def set_rails(self, n: int) -> None:
        """PS_NATIVE_RAILS: stripe each chunked transfer over ``n`` TCP
        connections per peer (docs/native_core.md).  Must be called
        before ``bind`` (receive pumps spawn there) and before the
        first data send (rail threads spawn with the lane)."""
        self._lib.psl_set_rails(self._h, n)

    def add_rail(self, node_id: int, host: str, port: int,
                 timeout_ms: int = 30000, idx: int = 1) -> None:
        """Dial data rail ``idx`` (1-based beyond the main connection)
        to a peer; re-dialing an index replaces the old connection."""
        rc = self._lib.psl_add_rail(
            self._h, node_id, host.encode(), port, timeout_ms, idx
        )
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))

    def set_sockbuf(self, snd: int, rcv: int) -> None:
        """Apply the van's PS_TCP_SNDBUF/PS_TCP_RCVBUF bounds to native
        sockets (0 = OS default) — the same bounded-buffer discipline
        the Python transport runs under."""
        self._lib.psl_set_sockbuf(self._h, snd, rcv)

    def set_reassembly(self, on: bool) -> None:
        """Toggle receive-side native chunk reassembly: chunk frames
        scatter GIL-free into one pooled buffer per transfer, and recv
        delivers a single complete frame whose ChunkInfo.index is the
        NATIVE_XFER_COMPLETE sentinel (vans/chunking.py).  Leave OFF
        when a layer must see individual chunk frames (resender ACKs,
        force-order sids, multi-rail striping)."""
        self._lib.psl_set_reassembly(self._h, 1 if on else 0)

    def recv(self, timeout_ms: int = -1) -> Optional[Tuple[bytes, List]]:
        """(meta_bytes, data_segments) — None when stopped; raises
        TimeoutError on timeout.  Data segments are zero-copy writable
        uint8 ndarray views over the native frame buffer; the buffer is
        freed when the last derived view is garbage-collected (numpy's
        base chain pins the ctypes holder, whose finalizer calls
        psl_frame_free) — the native counterpart of the pure-Python
        pooled-arena delivery."""
        view = _FrameView()
        rc = self._lib.psl_recv(self._h, ctypes.byref(view), timeout_ms)
        if rc == -1:
            return None
        if rc == 0:
            raise TimeoutError
        n_data = view.n_data
        base = ctypes.addressof(view.buf.contents)
        ptr = ctypes.cast(base, ctypes.POINTER(ctypes.c_uint8))
        fin = None
        try:
            lens = struct.unpack(
                f"<{n_data}Q", ctypes.string_at(view.buf, 8 * n_data)
            )
            total = 8 * n_data + view.meta_len + sum(lens)
            # A memoryview over the raw frame (NOT a per-length ctypes
            # array type: ctypes interns one array type per distinct
            # length forever — size-diverse traffic would grow the
            # interpreter's type cache without bound), viewed as a
            # weakref-able ndarray subclass so the finalizer can
            # return the buffer to the FramePool when the last derived
            # view dies.
            mv = _PyMemoryView_FromMemory(base, total, _PyBUF_WRITE)
            frame = np.frombuffer(mv, dtype=np.uint8).view(_NativeFrame)
            fin = weakref.finalize(frame, self._lib.psl_frame_free, ptr)
            off = 8 * n_data
            meta = frame[off:off + view.meta_len].tobytes()
            off += view.meta_len
            segs = []
            for ln in lens:
                segs.append(frame[off:off + ln])
                off += ln
            return meta, segs
        except BaseException:
            # The frame must not leak whatever failed mid-build; fin()
            # is idempotent with the GC-time finalizer.
            if fin is not None:
                fin()
            else:
                self._lib.psl_frame_free(ptr)
            raise

    def stop(self) -> None:
        if self._h:
            self._lib.psl_stop(self._h)

    def destroy(self) -> None:
        if self._h:
            self._lib.psl_destroy(self._h)
            self._h = None

