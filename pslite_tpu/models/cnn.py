"""CNN model family (ResNet-style) with a PS-integrated training step.

BytePS's flagship workload is CNN data-parallel training (the ResNet-50
gradient stream of ``resnet_trace.py``); this module provides an actual
trainable CNN: conv stem + residual blocks + linear head, pure JAX
(``lax.conv_general_dilated`` NHWC, bf16 matmuls/convs on the MXU), and a
training step using the same PS cycle as the flagship transformer —
pull = all_gather of the sharded flat store, push = psum_scatter of
gradients over the ``dp`` axis, SGD on server shards.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple


@dataclass(frozen=True)
class CNNConfig:
    num_classes: int = 10
    channels: Tuple[int, ...] = (16, 32)
    blocks_per_stage: int = 1
    image_size: int = 16
    in_channels: int = 3
    dtype: str = "float32"


def init_params(rng, cfg: CNNConfig):
    import jax
    import jax.numpy as jnp

    dt = jnp.dtype(cfg.dtype)
    params = {"stages": []}
    key = rng

    def conv(key, kh, kw, cin, cout):
        scale = (kh * kw * cin) ** -0.5
        return (jax.random.normal(key, (kh, kw, cin, cout)) * scale).astype(dt)

    key, k = jax.random.split(key)
    params["stem"] = conv(k, 3, 3, cfg.in_channels, cfg.channels[0])
    cin = cfg.channels[0]
    for cout in cfg.channels:
        stage = []
        for _ in range(cfg.blocks_per_stage):
            key, k1, k2 = jax.random.split(key, 3)
            block = {
                "conv1": conv(k1, 3, 3, cin, cout),
                "conv2": conv(k2, 3, 3, cout, cout),
                "scale1": jnp.ones((cout,), dt),
                "scale2": jnp.ones((cout,), dt),
            }
            if cin != cout:
                key, k3 = jax.random.split(key)
                block["proj"] = conv(k3, 1, 1, cin, cout)
            stage.append(block)
            cin = cout
        params["stages"].append(stage)
    key, k = jax.random.split(key)
    params["head"] = (
        jax.random.normal(k, (cin, cfg.num_classes)) * cin ** -0.5
    ).astype(dt)
    params["head_b"] = jnp.zeros((cfg.num_classes,), dt)
    return params


def _norm(x, scale):
    import jax
    import jax.numpy as jnp

    var = jnp.mean(jnp.square(x), axis=(1, 2), keepdims=True)
    return x * jax.lax.rsqrt(var + 1e-5) * scale


def forward(params, images, cfg: CNNConfig):
    """images [B, H, W, C] -> logits [B, num_classes]."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    compute_dt = jnp.bfloat16 if images.dtype != jnp.float64 else images.dtype

    def conv2d(x, w, stride=1):
        return lax.conv_general_dilated(
            x.astype(compute_dt),
            w.astype(compute_dt),
            window_strides=(stride, stride),
            padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ).astype(x.dtype)

    x = images
    x = jax.nn.relu(conv2d(x, params["stem"]))
    for s, stage in enumerate(params["stages"]):
        for b, block in enumerate(stage):
            stride = 2 if b == 0 and s > 0 else 1
            h = jax.nn.relu(_norm(conv2d(x, block["conv1"], stride),
                                  block["scale1"]))
            h = _norm(conv2d(h, block["conv2"]), block["scale2"])
            shortcut = x
            if "proj" in block:
                shortcut = conv2d(x, block["proj"], stride)
            elif stride != 1:
                shortcut = x[:, ::stride, ::stride, :]
            x = jax.nn.relu(h + shortcut)
    x = x.mean(axis=(1, 2))  # global average pool
    return (x.astype(compute_dt) @ params["head"].astype(compute_dt)
            ).astype(jnp.float32) + params["head_b"]


def loss_fn(params, images, labels, cfg: CNNConfig):
    import jax
    import jax.numpy as jnp

    logits = forward(params, images, cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()


def make_ps_train_step(cfg: CNNConfig, mesh, lr: float = 0.1, seed: int = 0):
    """Data-parallel PS training step over a 1-D ``dp`` mesh: the classic
    BytePS CNN cycle (pull -> grad -> reduce-scatter push -> shard SGD),
    built on the shared flat-store cycle (ps_step.py)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from .ps_step import make_flat_ps_step

    axis = mesh.axis_names[0]
    params0 = init_params(jax.random.PRNGKey(seed), cfg)
    step, flat_store, (batch_sharding, _), _, _ = make_flat_ps_step(
        mesh,
        params0,
        lambda p, img_l, lbl_l: loss_fn(p, img_l, lbl_l, cfg),
        [P(axis), P(axis)],
        lr=lr,
    )
    return step, flat_store, batch_sharding


def toy_batch(cfg: CNNConfig, batch: int, seed: int = 0):
    """Learnable toy data: label = quadrant of the brightest corner."""
    import numpy as np

    rng = np.random.default_rng(seed)
    images = rng.normal(
        size=(batch, cfg.image_size, cfg.image_size, cfg.in_channels)
    ).astype(np.float32)
    labels = rng.integers(0, cfg.num_classes, size=batch).astype(np.int32)
    half = cfg.image_size // 2
    for i, lab in enumerate(labels):
        r = (lab % 2) * half
        c = ((lab // 2) % 2) * half
        images[i, r : r + half, c : c + half] += 2.0 * (lab + 1) / cfg.num_classes
    return images, labels
