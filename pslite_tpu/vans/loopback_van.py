"""Loopback van — in-process transport for unit tests.

This is the "fake backend" tier the reference fork dropped (SURVEY §4): a
whole cluster (scheduler + servers + workers, including instance groups) runs
inside one process, with every message round-tripped through the real wire
format (``wire.pack_frame``/``unpack``) so serialization is exercised on every
test.  The scheduler bootstrap, rank assignment, barriers, heartbeats and
recovery all run for real — only the sockets are replaced by queues.

Endpoints register in a process-global registry keyed by
``(namespace, host, port)``; the namespace (``PS_LOOPBACK_NS``) isolates
concurrently running test clusters.
"""

from __future__ import annotations

import struct
import threading
from typing import Dict, Optional, Tuple

from ..message import Message, Node
from ..utils import logging as log
from ..utils.queues import PriorityRecvQueue, ThreadsafeQueue
from .. import wire
from .chunking import RECV_DRAIN_LAST, recv_cost, recv_priority, recv_tenant
from .van import Van

_registry_mu = threading.Lock()
_registry: Dict[Tuple[str, str, int], "LoopbackVan"] = {}
_port_counter = [20000]


def reset_registry() -> None:
    """Drop all registered endpoints (test teardown helper)."""
    with _registry_mu:
        _registry.clear()


class LoopbackVan(Van):
    def __init__(self, postoffice):
        super().__init__(postoffice)
        self._ns = self.env.find("PS_LOOPBACK_NS", "default")
        # The queue holds packed blobs, so the receive-priority level is
        # computed by the SENDER (which still has the Message) and
        # pushed alongside — same discipline as the socket vans
        # (docs/chunking.md), same PS_RECV_PRIORITY opt-out.
        self._prio_recv = bool(self.env.find_int("PS_RECV_PRIORITY", 1))
        self._queue = (
            PriorityRecvQueue(lambda _b: 0,
                              weights=self._tenant_weights)
            if self._prio_recv else ThreadsafeQueue()
        )
        self._peers: Dict[int, Tuple[str, int]] = {}
        self._bound_key: Optional[Tuple[str, str, int]] = None

    def bind_transport(self, node: Node, max_retry: int) -> int:
        port = node.port
        with _registry_mu:
            if port == 0:
                _port_counter[0] += 1
                port = _port_counter[0]
            key = (self._ns, node.hostname, port)
            log.check(key not in _registry, f"loopback addr in use: {key}")
            _registry[key] = self
            self._bound_key = key
        return port

    def connect_transport(self, node: Node) -> None:
        if node.id >= 0:
            self._peers[node.id] = (node.hostname, node.port)

    def _resolve(self, recver: int) -> "LoopbackVan":
        if recver == self.my_node.id:
            return self
        addr = self._peers.get(recver)
        log.check(addr is not None, f"loopback: unknown recver {recver}")
        with _registry_mu:
            van = _registry.get((self._ns, addr[0], addr[1]))
        log.check(van is not None, f"loopback: no endpoint at {addr}")
        return van

    def send_msg(self, msg: Message) -> int:
        # Thread-safe without any van-level locking: per-peer send lanes
        # may call this concurrently for different recvers, and the
        # registry lookup + queue push are each internally locked.  The
        # one-pass join also serializes the payload HERE (dispatch
        # time), so the zero-copy contract matches the socket vans:
        # callers must not mutate buffers until wait(ts).
        target = self._resolve(msg.meta.recver)
        chunks = wire.pack_frame(msg)
        blob = b"".join(chunks)  # join accepts memoryviews: one copy
        if target._prio_recv:
            # The queue holds packed blobs: priority AND the tenant/
            # cost (docs/qos.md) are computed sender-side while the
            # Message is still in hand.
            target._queue.push(blob, priority=recv_priority(msg),
                               tenant=recv_tenant(msg),
                               cost=recv_cost(msg))
        else:
            target._queue.push(blob)
        return len(blob)

    def recv_msg(self) -> Optional[Message]:
        blob = self._queue.wait_and_pop()
        if blob is None:
            return None
        meta_len, n_data = wire.unpack_frame_header(blob[: wire.FRAME_HEADER_SIZE])
        off = wire.FRAME_HEADER_SIZE
        lens = struct.unpack_from(f"<{n_data}Q", blob, off)
        off += 8 * n_data
        meta = wire.unpack_meta(blob[off : off + meta_len])
        off += meta_len
        bufs = []
        for ln in lens:
            bufs.append(blob[off : off + ln])
            off += ln
        return wire.rebuild_message(meta, bufs)

    def stop_transport(self) -> None:
        if self._prio_recv:
            self._queue.push(None, priority=RECV_DRAIN_LAST)
        else:
            self._queue.push(None)
        if self._bound_key is not None:
            with _registry_mu:
                _registry.pop(self._bound_key, None)
            self._bound_key = None
