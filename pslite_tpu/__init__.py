"""pslite_tpu — a TPU-native parameter-server framework.

A from-scratch re-design of the capabilities of bytedance/ps-lite for TPU:
the same contract (worker/server/scheduler roles, KV push/pull with async
timestamps, pluggable transports, barriers, heartbeats, recovery), with the
data plane re-architected as jit-compiled XLA collectives over an ICI device
mesh (the ``ici`` van) and a TCP van for the DCN/control plane.
"""

from . import base, environment
from .base import (
    ALL_GROUP,
    SCHEDULER_GROUP,
    SERVER_GROUP,
    WORKER_GROUP,
)
from .kv import (
    ElasticZeroCopyError,
    HotKeyCache,
    KVMeta,
    KVPairs,
    KVServer,
    KVServerDefaultHandle,
    KVServerOptimizerHandle,
    KVWorker,
    OverloadError,
    SimpleApp,
)
from .message import Command, Control, Message, Meta, Node, Role
from .postoffice import Postoffice
from .ps import finalize, num_instances, postoffice, start_ps
from .range import Range
from .routing import RouteEntry, RoutingTable
from .sarray import DeviceType, SArray

__version__ = "0.2.0"

# Reference-style spellings.
StartPS = start_ps
Finalize = finalize

__all__ = [
    "ALL_GROUP",
    "SCHEDULER_GROUP",
    "SERVER_GROUP",
    "WORKER_GROUP",
    "Command",
    "Control",
    "DeviceType",
    "Finalize",
    "ElasticZeroCopyError",
    "HotKeyCache",
    "KVMeta",
    "KVPairs",
    "KVServer",
    "KVServerDefaultHandle",
    "KVServerOptimizerHandle",
    "KVWorker",
    "Message",
    "OverloadError",
    "Meta",
    "Node",
    "Postoffice",
    "Range",
    "Role",
    "RouteEntry",
    "RoutingTable",
    "SArray",
    "SimpleApp",
    "StartPS",
    "base",
    "environment",
    "finalize",
    "num_instances",
    "postoffice",
    "start_ps",
]
