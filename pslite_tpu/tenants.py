"""Multi-tenant QoS configuration (docs/qos.md).

``PS_TENANTS`` promotes the priority integer into *named tenants* with
weighted-fair scheduling: ``PS_TENANTS=serve:8,train:1`` declares two
tenants whose bulk traffic shares every contended queue (send lanes,
receive intake, apply shards) in an 8:1 byte ratio.  The tenant id is a
small integer assigned by position in the spec (1-based; id 0 is the
implicit ``default`` tenant every unlabeled message belongs to) and
rides the wire in the tagged ``EXT_QOS`` meta extension, so every node
of a cluster must be launched with the SAME ``PS_TENANTS`` string for
names to mean the same thing everywhere — exactly like the key-range
layout.

Scheduling contract (shared by every tenant-aware queue):

- ``priority > 0`` is the EXPRESS band: strict highest-priority-first,
  FIFO within a level, across ALL tenants — a latency-critical op
  jumps everything regardless of tenant, exactly as before this layer.
- ``priority <= 0`` is the BULK pool: deficit/virtual-time weighted
  fair queuing across tenants by configured weight (bytes-charged),
  and highest-priority-first FIFO *within* a tenant.
- The shutdown/TERMINATE drain level still drains last, globally.

With ``PS_TENANTS`` unset every message is tenant 0 and the weighted
pool degenerates to the old single-heap order bit-for-bit.

Batching interplay (docs/batching.md): the small-op combiner never
merges ops across tenants (the tenant is part of its group key), so a
multi-op ``EXT_BATCH`` frame's envelope tenant prices every sub-op
correctly in the weighted-fair queues — and per-tenant ADMISSION
through a batched frame sheds sub-ops individually (docs/qos.md).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from .utils import logging as log

# Tenant id 0: the implicit tenant of every unlabeled message.
DEFAULT_TENANT = 0
DEFAULT_NAME = "default"

# meta.tenant wire field width (EXT_QOS packs it as u16).
MAX_TENANT_ID = 0xFFFF


class TenantTable:
    """Immutable name <-> id <-> weight mapping parsed from
    ``PS_TENANTS`` (``name:weight,name:weight,...``; a bare ``name``
    gets weight 1).  The reserved name ``default`` re-weights tenant 0
    instead of allocating a new id."""

    def __init__(self, spec: Optional[str] = None):
        self._by_name: Dict[str, int] = {DEFAULT_NAME: DEFAULT_TENANT}
        self._names: Dict[int, str] = {DEFAULT_TENANT: DEFAULT_NAME}
        self._weights: Dict[int, float] = {DEFAULT_TENANT: 1.0}
        spec = (spec or "").strip()
        next_id = 1
        for entry in filter(None, (e.strip() for e in spec.split(","))):
            name, _, w = entry.partition(":")
            name = name.strip()
            log.check(name != "", f"PS_TENANTS: empty tenant name in "
                                  f"{spec!r}")
            # Names feed dotted metric paths (tenant.<name>.requests)
            # and the psmon rollup parser — keep them identifier-like.
            log.check(
                "." not in name and ":" not in name
                and not any(c.isspace() for c in name),
                f"PS_TENANTS: tenant name {name!r} may not contain "
                f"dots, colons, or whitespace",
            )
            weight = float(w) if w.strip() else 1.0
            log.check(weight > 0,
                      f"PS_TENANTS: tenant {name!r} needs weight > 0")
            if name == DEFAULT_NAME:
                self._weights[DEFAULT_TENANT] = weight
                continue
            log.check(name not in self._by_name,
                      f"PS_TENANTS: duplicate tenant {name!r}")
            log.check(next_id <= MAX_TENANT_ID, "PS_TENANTS: too many "
                                                "tenants")
            self._by_name[name] = next_id
            self._names[next_id] = name
            self._weights[next_id] = weight
            next_id += 1

    @classmethod
    def from_env(cls, env) -> "TenantTable":
        spec = env.find("PS_TENANTS") if env is not None else None
        return cls(spec)

    @property
    def enabled(self) -> bool:
        """True when the spec named at least one non-default tenant."""
        return len(self._names) > 1

    def resolve(self, tenant) -> int:
        """Tenant id of a name, an id, or None (the default tenant).
        Unknown names AND ids not in the table fail loudly — a typo'd
        tenant silently riding as ``default`` (or an out-of-range id
        truncated by the u16 wire field onto some OTHER tenant's quota
        and counters) would bypass the isolation this layer exists
        for."""
        if tenant is None:
            return DEFAULT_TENANT
        if isinstance(tenant, (int,)) and not isinstance(tenant, bool):
            tid = int(tenant)
            log.check(tid in self._names,
                      f"unknown tenant id {tid} (PS_TENANTS declares "
                      f"ids {sorted(self._names)})")
            return tid
        tid = self._by_name.get(str(tenant))
        log.check(tid is not None,
                  f"unknown tenant {tenant!r} (PS_TENANTS names: "
                  f"{sorted(self._by_name)})")
        return tid

    def name(self, tid: int) -> str:
        return self._names.get(tid, f"t{tid}")

    def weight(self, tid: int) -> float:
        return self._weights.get(tid, 1.0)

    def weights_by_id(self) -> Dict[int, float]:
        return dict(self._weights)

    def names(self) -> Tuple[str, ...]:
        return tuple(self._by_name)


_cache_mu = threading.Lock()
_cache: Dict[str, TenantTable] = {}


def table_for(env) -> TenantTable:
    """Shared TenantTable for an environment's ``PS_TENANTS`` value
    (parsed once per distinct spec — every van lane, receive queue and
    apply pool of a node asks for it)."""
    spec = (env.find("PS_TENANTS") or "") if env is not None else ""
    with _cache_mu:
        table = _cache.get(spec)
        if table is None:
            table = _cache[spec] = TenantTable(spec)
        return table
