"""Scheduler autopilot (pslite_tpu/cluster/autopilot.py,
docs/autopilot.md): per-rule trigger/hysteresis/cooldown/budget/dry-run
semantics on synthetic ClusterHistory feeds, the snapshot x migration
fence (scheduler ledger defer/veto + server-side refusal), the
cluster-truth replica read policy, and a slow-marked scaled-down
acceptance storm (drifting hot set, chaos on, autopilot on).
"""

import os
import sys
import threading
import time
import types

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from pslite_tpu.cluster.autopilot import (  # noqa: E402
    ACTED,
    Autopilot,
    PLANNED,
    VETOED,
    FAILED,
    _server_rates,
    parse_mode,
)
from pslite_tpu.environment import Environment  # noqa: E402
from pslite_tpu.kv.kv_app import (  # noqa: E402
    KVServer,
    KVServerDefaultHandle,
    KVWorker,
)
from pslite_tpu.routing import RoutingTable  # noqa: E402
from pslite_tpu.telemetry import ClusterHistory, FlightRecorder  # noqa: E402
from pslite_tpu.utils.logging import CheckError  # noqa: E402

from helpers import LoopbackCluster  # noqa: E402

# Server node ids for group ranks 0/1/2 (base.py: 8 + 2r).
S0, S1, S2 = 8, 10, 12


# -- synthetic feed helpers ---------------------------------------------------


def _env(**kw):
    return Environment({k: str(v) for k, v in kw.items()})


def _snap(node_id, role="server", counters=None, gauges=None, topk=None):
    return {
        "node_id": node_id, "role": role,
        "metrics": {
            "counters": counters or {},
            "gauges": gauges or {},
            "histograms": {},
            "topk": topk or {},
            "uptime_s": 10.0,
        },
    }


def _feed_rates(h, wall, rates, gauges=None):
    """Ingest one round of cumulative per-server counters such that the
    windowed rate between consecutive walls equals ``rates[nid]``."""
    h.ingest({
        nid: _snap(nid, counters={
            "kv.server_pull_requests": int(r * wall)},
            gauges=gauges)
        for nid, r in rates.items()
    }, wall=wall)


class FakePo:
    """Duck-typed scheduler Postoffice: just the actuator surface the
    autopilot drives, every call recorded."""

    def __init__(self, env, num_servers=3, elastic=True):
        self.env = env
        self.flight = FlightRecorder(env, "scheduler")
        self.group_size = 1
        self._table = (RoutingTable.initial(num_servers)
                       if elastic else None)
        self.broadcasts = []
        self.retunes = []
        self.snapshot_calls = []
        self.snapshot_exc = None
        self.snapshot_dir = None
        self.van = types.SimpleNamespace(
            broadcast_routing=self._broadcast)

    def _broadcast(self, table):
        self.broadcasts.append(table)
        self._table = table

    def routing_table(self):
        return self._table

    def migrations_in_flight(self):
        t = self._table
        if t is None:
            return []
        return [(t.epoch, e.begin) for e in t.migrations()]

    def hot_key_hint(self):
        return {}

    def snapshot(self, **kw):
        self.snapshot_calls.append(kw)
        if self.snapshot_exc is not None:
            raise self.snapshot_exc
        return {"servers": 1}

    def retune_apply(self, task_bytes, **kw):
        self.retunes.append(task_bytes)
        return {"applied": 1}


def _mk(mode="act", num_servers=3, elastic=True, **env_kw):
    env = _env(**env_kw)
    po = FakePo(env, num_servers=num_servers, elastic=elastic)
    ap = Autopilot(po, env=env, mode=mode)
    h = ClusterHistory(env=None, interval_s=1.0)
    h.autopilot = ap
    return po, ap, h


def _await_followup(ap, outcome, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for d in ap.decisions(32):
            if d.detail.get("followup") and d.outcome == outcome:
                return d
        time.sleep(0.02)
    raise TimeoutError(f"no {outcome} follow-up decision arrived")


# -- mode parsing / kill switch ----------------------------------------------


def test_parse_mode():
    for raw in (None, "", "0", "off", "OFF", "false", "no"):
        assert parse_mode(raw) is None
    for raw in ("plan", "PLAN", "dry", "dryrun", "dry-run"):
        assert parse_mode(raw) == "plan"
    for raw in ("1", "act", "on", "yes"):
        assert parse_mode(raw) == "act"
    # A typo must die loudly, never coerce to live actuation.
    for raw in ("paln", "2", "bogus"):
        with pytest.raises(CheckError):
            parse_mode(raw)


def test_kill_switch_nothing_constructed():
    """PS_AUTOPILOT unset -> the sampler runs with NO engine attached;
    set to plan -> constructed in dry-run mode."""
    cl = LoopbackCluster(env_extra={"PS_METRICS_INTERVAL": "0.3"})
    cl.start()
    try:
        assert cl.scheduler.history is not None
        assert cl.scheduler.history.autopilot is None
    finally:
        cl.scheduler.stop_history()
        cl.finalize()

    cl2 = LoopbackCluster(env_extra={"PS_METRICS_INTERVAL": "0.3",
                                     "PS_AUTOPILOT": "plan"})
    cl2.start()
    try:
        ap = cl2.scheduler.history.autopilot
        assert ap is not None and ap.mode == "plan"
    finally:
        cl2.scheduler.stop_history()
        cl2.finalize()


# -- hot_skew: trigger / hysteresis / actuation ------------------------------


def test_hot_skew_sustain_then_rebalance():
    po, ap, h = _mk(PS_AUTOPILOT_SUSTAIN=3)
    skew = {S0: 90.0, S1: 5.0, S2: 5.0}
    _feed_rates(h, 0.0, skew)          # one sample: no rates yet
    decisions = []
    for w in (1.0, 2.0, 3.0):
        before = len(ap.decision_log)
        _feed_rates(h, w, skew)
        decisions.append(list(ap.decision_log)[before:])
    # Hysteresis: breaches 1 and 2 only ARM the rule.
    assert decisions[0] == [] and decisions[1] == []
    (d,) = decisions[2]
    assert d.rule == "hot_skew" and d.action == "rebalance"
    assert d.outcome == ACTED
    assert d.detail["src"] == 0 and d.detail["dst"] == 1
    # The actuator derived and broadcast a NEW epoch with a migration
    # marker (the existing handoff machinery does the rest).
    (table,) = po.broadcasts
    assert table.epoch == 1 and d.detail["epoch"] == 1
    assert len(table.migrations()) == 1
    assert table.migrations()[0].prev == 0


def test_hot_skew_one_noisy_window_never_moves_data():
    po, ap, h = _mk(PS_AUTOPILOT_SUSTAIN=3)
    skew = {S0: 90.0, S1: 5.0, S2: 5.0}
    flat = {S0: 10.0, S1: 10.0, S2: 10.0}
    _feed_rates(h, 0.0, skew)
    _feed_rates(h, 1.0, skew)   # streak 1
    _feed_rates(h, 2.0, flat)   # recovers -> streak resets
    _feed_rates(h, 3.0, skew)   # streak 1 again
    _feed_rates(h, 4.0, skew)   # streak 2
    assert not po.broadcasts and not ap.decision_log


def test_hot_skew_vetoes_while_migration_in_flight():
    po, ap, h = _mk(PS_AUTOPILOT_SUSTAIN=1,
                    PS_AUTOPILOT_SKEW_COOLDOWN_S=0)
    skew = {S0: 90.0, S1: 5.0, S2: 5.0}
    _feed_rates(h, 0.0, skew)
    _feed_rates(h, 1.0, skew)
    assert [d.outcome for d in ap.decision_log] == [ACTED]
    # The broadcast table carries a live migration: the next sustained
    # breach must NOT stack a second handoff on top of it.
    assert po.migrations_in_flight()
    _feed_rates(h, 2.0, skew)
    d = list(ap.decision_log)[-1]
    assert d.outcome == VETOED and "in flight" in d.detail["veto"]
    assert len(po.broadcasts) == 1


def test_static_routing_veto_refunds_budget():
    po, ap, h = _mk(PS_AUTOPILOT_SUSTAIN=1, elastic=False,
                    PS_AUTOPILOT_SKEW_COOLDOWN_S=0,
                    PS_AUTOPILOT_MAX_ACTIONS=1)
    skew = {S0: 90.0, S1: 5.0, S2: 5.0}
    _feed_rates(h, 0.0, skew)
    _feed_rates(h, 1.0, skew)
    _feed_rates(h, 2.0, skew)
    outs = [d.outcome for d in ap.decision_log]
    assert outs == [VETOED, VETOED]
    # Both vetoes name the static-routing precondition — the second was
    # NOT a budget veto, because a vetoed action spends nothing.
    for d in ap.decision_log:
        assert "static routing" in d.detail["veto"]
    assert len(ap._action_walls) == 0


# -- cooldown / budget / dry-run ---------------------------------------------


def test_cooldown_vetoes_refire():
    po, ap, h = _mk(mode="plan", PS_AUTOPILOT_SUSTAIN=1,
                    PS_AUTOPILOT_SKEW_COOLDOWN_S=100)
    skew = {S0: 90.0, S1: 5.0, S2: 5.0}
    _feed_rates(h, 0.0, skew)
    _feed_rates(h, 1.0, skew)
    _feed_rates(h, 2.0, skew)
    outs = [(d.outcome, d.detail.get("veto", "")) for d in ap.decision_log]
    assert outs[0] == (PLANNED, "")
    assert outs[1][0] == VETOED and "cooldown" in outs[1][1]


def test_global_budget_and_plan_mode_consumes_it():
    po, ap, h = _mk(mode="plan", PS_AUTOPILOT_SUSTAIN=1,
                    PS_AUTOPILOT_SKEW_COOLDOWN_S=0,
                    PS_AUTOPILOT_MAX_ACTIONS=1,
                    PS_AUTOPILOT_WINDOW_S=60)
    skew = {S0: 90.0, S1: 5.0, S2: 5.0}
    _feed_rates(h, 0.0, skew)
    _feed_rates(h, 1.0, skew)
    _feed_rates(h, 2.0, skew)
    outs = [d.outcome for d in ap.decision_log]
    assert outs == [PLANNED, VETOED]
    assert "budget" in list(ap.decision_log)[1].detail["veto"]


def test_dry_run_never_touches_an_actuator():
    po, ap, h = _mk(mode="plan", PS_AUTOPILOT_SUSTAIN=1)
    skew = {S0: 90.0, S1: 5.0, S2: 5.0}
    _feed_rates(h, 0.0, skew)
    _feed_rates(h, 1.0, skew)
    (d,) = ap.decision_log
    assert d.outcome == PLANNED
    assert not po.broadcasts and not po.retunes
    assert not po.snapshot_calls
    # ...but the narration still lands in the flight recorder.
    evs = po.flight.events("autopilot")
    assert evs and evs[0]["outcome"] == PLANNED


# -- shed_scale / scale_in ----------------------------------------------------


def test_shed_scale_vetoes_without_actuator_then_spawns():
    po, ap, h = _mk(PS_AUTOPILOT_SUSTAIN=1,
                    PS_AUTOPILOT_SCALE_COOLDOWN_S=0)

    def shed(w):
        h.ingest({S0: _snap(S0, counters={
            "qos.shed_requests": int(50.0 * w)})}, wall=w)

    shed(0.0)
    shed(1.0)
    d = list(ap.decision_log)[-1]
    assert d.rule == "shed_scale" and d.outcome == VETOED
    assert "no spawn actuator" in d.detail["veto"]

    spawned = []
    ap.spawn_server = lambda: spawned.append(1)
    shed(2.0)
    d = list(ap.decision_log)[-1]
    assert d.outcome == ACTED and spawned == [1]


def test_scale_in_disabled_by_default():
    po, ap, h = _mk(PS_AUTOPILOT_SUSTAIN=1)
    ap.retire_server = lambda rank: pytest.fail("must not retire")
    idle = {S0: 0.5, S1: 0.3, S2: 0.2}
    for w in range(6):
        _feed_rates(h, float(w), idle)
    assert not any(d.rule == "scale_in" for d in ap.decision_log)


def test_scale_in_fires_with_watermark_opt_in():
    po, ap, h = _mk(PS_AUTOPILOT_SUSTAIN=1,
                    PS_AUTOPILOT_SCALE_IN_RATE=10.0,
                    PS_AUTOPILOT_SCALE_IN_SUSTAIN=1,
                    PS_AUTOPILOT_SCALE_COOLDOWN_S=0)
    retired = []
    ap.retire_server = retired.append
    idle = {S0: 5.0, S1: 3.0, S2: 2.0}
    _feed_rates(h, 0.0, idle)
    _feed_rates(h, 1.0, idle)
    d = list(ap.decision_log)[-1]
    assert d.rule == "scale_in" and d.outcome == ACTED
    assert retired == [2]  # the least-loaded rank


# -- snapshot_age: scheduling + exponential backoff --------------------------


def test_snapshot_age_backoff_doubles_on_veto_resets_on_commit():
    po, ap, h = _mk(PS_AUTOPILOT_SNAPSHOT_SUSTAIN=1,
                    PS_AUTOPILOT_SNAPSHOT_COOLDOWN_S=5)
    po.snapshot_dir = "/tmp/snapdir"
    po.snapshot_exc = RuntimeError("apply pool never quiesced")
    rule = next(r for r in ap.rules if r.name == "snapshot_age")
    stale = {"snapshot.age_s": -1.0}  # configured, never committed

    h.ingest({S0: _snap(S0, gauges=stale)}, wall=0.0)
    d = list(ap.decision_log)[-1]
    assert d.rule == "snapshot_age" and d.outcome == ACTED
    f = _await_followup(ap, FAILED)
    assert "quiesced" in f.reason
    # Quiesce-fence pressure doubled the retry horizon.
    assert rule.backoff == 2
    assert rule.effective_cooldown() == pytest.approx(10.0)

    # Inside the widened cooldown: vetoed, no second cut attempted.
    h.ingest({S0: _snap(S0, gauges=stale)}, wall=3.0)
    d = list(ap.decision_log)[-1]
    assert d.outcome == VETOED and "cooldown" in d.detail["veto"]
    assert len(po.snapshot_calls) == 1

    # Past it, with the fence lifted: the cut commits and backoff resets.
    po.snapshot_exc = None
    h.ingest({S0: _snap(S0, gauges=stale)}, wall=50.0)
    _await_followup(ap, ACTED)
    assert rule.backoff == 1 and len(po.snapshot_calls) == 2


def test_snapshot_age_vetoes_without_directory():
    po, ap, h = _mk(PS_AUTOPILOT_SNAPSHOT_SUSTAIN=1)
    h.ingest({S0: _snap(S0, gauges={"snapshot.age_s": 9e9})}, wall=0.0)
    d = list(ap.decision_log)[-1]
    assert d.rule == "snapshot_age" and d.outcome == VETOED
    assert "PS_SNAPSHOT_DIR" in d.detail["veto"]
    assert not po.snapshot_calls


# -- apply_wait: quantum retune ----------------------------------------------


def test_apply_wait_halves_quantum_down_to_floor():
    po, ap, h = _mk(PS_AUTOPILOT_SUSTAIN=1,
                    PS_AUTOPILOT_RETUNE_COOLDOWN_S=0,
                    PS_APPLY_TASK_BYTES=256 << 10)
    ap.trace_source = lambda: {
        "count": 20,
        "slow": {"apply_wait": {"share": 0.8, "total_us": 1000.0}},
    }
    for w in range(3):
        ap.observe(h, wall=float(w))
    outs = [d.outcome for d in ap.decision_log
            if d.rule == "apply_wait"]
    assert outs == [ACTED, ACTED, VETOED]
    assert po.retunes == [128 << 10, 64 << 10]
    assert ap.apply_task_bytes == 64 << 10
    d = list(ap.decision_log)[-1]
    assert "floor" in d.detail["veto"]


def test_apply_wait_needs_enough_traces():
    po, ap, h = _mk(PS_AUTOPILOT_SUSTAIN=1,
                    PS_AUTOPILOT_RETUNE_COOLDOWN_S=0)
    ap.trace_source = lambda: {
        "count": 3,  # below PS_AUTOPILOT_MIN_TRACES (8)
        "slow": {"apply_wait": {"share": 0.9, "total_us": 1000.0}},
    }
    ap.observe(h, wall=0.0)
    assert not po.retunes and not ap.decision_log


def test_apply_widen_doubles_back_to_baseline():
    po, ap, h = _mk(PS_AUTOPILOT_SUSTAIN=1,
                    PS_AUTOPILOT_RETUNE_COOLDOWN_S=0,
                    PS_APPLY_TASK_BYTES=256 << 10)
    ap.apply_task_bytes = 64 << 10  # as left by a narrowing streak
    ap.trace_source = lambda: {
        "count": 20,
        "slow": {"apply_wait": {"share": 0.02, "total_us": 40.0}},
    }
    for w in range(3):
        ap.observe(h, wall=float(w))
    outs = [d.outcome for d in ap.decision_log
            if d.rule == "apply_widen"]
    # Two doublings reach the baseline; the third round senses nothing
    # (quantum already restored) rather than vetoing forever.
    assert outs == [ACTED, ACTED]
    assert po.retunes == [128 << 10, 256 << 10]
    assert ap.apply_task_bytes == 256 << 10


def test_apply_widen_holds_inside_hysteresis_band():
    # Share between the widen threshold (0.15) and the narrow
    # threshold (0.5): NEITHER rule moves the quantum — the band is
    # the thrash guard.
    po, ap, h = _mk(PS_AUTOPILOT_SUSTAIN=1,
                    PS_AUTOPILOT_RETUNE_COOLDOWN_S=0,
                    PS_APPLY_TASK_BYTES=256 << 10)
    ap.apply_task_bytes = 64 << 10
    ap.trace_source = lambda: {
        "count": 20,
        "slow": {"apply_wait": {"share": 0.3, "total_us": 500.0}},
    }
    for w in range(3):
        ap.observe(h, wall=float(w))
    assert not po.retunes
    assert ap.apply_task_bytes == 64 << 10


def test_apply_narrow_then_recover_round_trip():
    po, ap, h = _mk(PS_AUTOPILOT_SUSTAIN=1,
                    PS_AUTOPILOT_RETUNE_COOLDOWN_S=0,
                    PS_APPLY_TASK_BYTES=256 << 10)
    share = {"v": 0.8}
    ap.trace_source = lambda: {
        "count": 20,
        "slow": {"apply_wait": {"share": share["v"],
                                "total_us": 1000.0}},
    }
    for w in range(2):  # pressure: halve twice, down to the floor
        ap.observe(h, wall=float(w))
    assert po.retunes == [128 << 10, 64 << 10]
    share["v"] = 0.0  # pressure gone: widen back out
    for w in range(2, 5):
        ap.observe(h, wall=float(w))
    assert po.retunes == [128 << 10, 64 << 10, 128 << 10, 256 << 10]
    assert ap.apply_task_bytes == 256 << 10


# -- engine plumbing ----------------------------------------------------------


def test_disable_list_and_unknown_rule_is_fatal():
    env = _env(PS_AUTOPILOT_DISABLE="hot_skew,scale_in")
    ap = Autopilot(FakePo(env), env=env, mode="act")
    assert {r.name for r in ap.rules} == {"shed_scale", "snapshot_age",
                                          "apply_wait", "apply_widen"}
    bad = _env(PS_AUTOPILOT_DISABLE="bogus_rule")
    with pytest.raises(CheckError):
        Autopilot(FakePo(bad), env=bad, mode="act")


def test_every_decision_narrated_to_flight_and_health():
    po, ap, h = _mk(PS_AUTOPILOT_SUSTAIN=1)
    skew = {S0: 90.0, S1: 5.0, S2: 5.0}
    _feed_rates(h, 0.0, skew)
    _feed_rates(h, 1.0, skew)
    (ev,) = po.flight.events("autopilot")
    assert ev["rule"] == "hot_skew" and ev["outcome"] == ACTED
    assert ev["action"] == "rebalance" and ev["severity"] == "info"
    infos = h.watchdog.events(min_severity="info")
    assert any(e.rule == "autopilot.hot_skew" and ACTED in e.message
               for e in infos)


def test_broken_autopilot_never_breaks_ingest():
    h = ClusterHistory(env=None, interval_s=1.0)
    h.autopilot = types.SimpleNamespace(
        observe=lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")))
    h.ingest({S0: _snap(S0)}, wall=0.0)  # must not raise
    assert h.latest(S0) is not None


def test_actuator_crash_records_failed():
    po, ap, h = _mk(PS_AUTOPILOT_SUSTAIN=1)
    rule = next(r for r in ap.rules if r.name == "hot_skew")
    rule.act = lambda ap_, proposal: (_ for _ in ()).throw(
        RuntimeError("van mid-teardown"))
    skew = {S0: 90.0, S1: 5.0, S2: 5.0}
    _feed_rates(h, 0.0, skew)
    _feed_rates(h, 1.0, skew)
    (d,) = ap.decision_log
    assert d.outcome == FAILED and "van mid-teardown" in d.detail["error"]


# -- snapshot x migration fence (the PR's race fix) --------------------------


def _snap_cluster(tmp_path, num_servers=2):
    cl = LoopbackCluster(num_workers=1, num_servers=num_servers,
                         env_extra={"PS_SNAPSHOT_DIR": str(tmp_path),
                                    "PS_ELASTIC": "1"})
    cl.start()
    servers = []
    for po in cl.servers:
        s = KVServer(0, postoffice=po)
        s.set_request_handle(KVServerDefaultHandle())
        servers.append(s)
    w = KVWorker(0, 0, postoffice=cl.workers[0])
    return cl, servers, w


def _kill(cl, servers, w):
    w.stop()
    for s in servers:
        s.stop()
    cl.finalize()


def test_snapshot_vetoes_pending_migration_then_retries(tmp_path):
    """A migration parked across the fence: the scheduler defers the
    cut, vetoes loudly past the settle budget, and commits cleanly once
    MIGRATE_DONE clears the ledger."""
    cl, servers, w = _snap_cluster(tmp_path)
    sched = cl.scheduler
    try:
        keys = np.array([3, 2**63 + 5], dtype=np.uint64)
        vals = np.arange(len(keys) * 8, dtype=np.float32)
        w.wait(w.push(keys, vals))

        t2 = sched.routing_table().with_rebalance(0, 1)
        (mig,) = t2.migrations()
        sched.apply_routing(t2)  # ledger arms on the scheduler
        assert sched.migrations_in_flight() == [(t2.epoch, mig.begin)]

        with pytest.raises(CheckError, match="snapshot vetoed"):
            sched.snapshot(settle_timeout_s=0.3)
        kinds = [e["kind"] for e in sched.flight.events()]
        assert "snapshot_deferred" in kinds
        assert "snapshot_end" not in kinds

        # Retry with the handoff completing mid-defer: the cut waits
        # for the ledger to drain, then commits.
        timer = threading.Timer(
            0.4, sched.note_migration_done, args=(t2.epoch, mig.begin))
        timer.start()
        try:
            res = sched.snapshot(settle_timeout_s=10.0)
        finally:
            timer.cancel()
        assert res["servers"] == 2
        assert sched.migrations_in_flight() == []
        # The committed store is intact after the vetoed attempt.
        out = np.zeros_like(vals)
        w.wait(w.pull(keys, out))
        assert np.array_equal(out, vals)
    finally:
        _kill(cl, servers, w)


def test_migration_ledger_expires_with_warning(tmp_path):
    """A lost MIGRATE_DONE must not wedge snapshots forever: ledger
    entries expire after PS_MIGRATION_SETTLE_S with a flight event."""
    cl, servers, w = _snap_cluster(tmp_path)
    sched = cl.scheduler
    try:
        t2 = sched.routing_table().with_rebalance(0, 1)
        sched.apply_routing(t2)
        assert sched.migrations_in_flight()
        sched._migration_settle_s = 0.1
        time.sleep(0.2)
        assert sched.migrations_in_flight() == []
        kinds = [e["kind"] for e in sched.flight.events()]
        assert "migration_expired" in kinds
        assert sched.snapshot()["servers"] == 2
    finally:
        _kill(cl, servers, w)


def test_server_side_fence_refuses_mid_handoff_cut(tmp_path):
    """Defense in depth behind the scheduler ledger: a server that is
    itself mid-handoff (parked requests on an incoming range) refuses
    the cut, and the whole snapshot fails loudly."""
    cl, servers, w = _snap_cluster(tmp_path)
    sched = cl.scheduler
    srv = servers[0]
    try:
        keys = np.array([7], dtype=np.uint64)
        vals = np.ones(8, np.float32)
        w.wait(w.push(keys, vals))

        with srv._elastic_mu:
            srv._pending_ranges[12345] = {"parked": []}
        with pytest.raises(CheckError, match="NOT committed"):
            sched.snapshot()
        with srv._elastic_mu:
            srv._pending_ranges.clear()
        assert sched.snapshot()["servers"] == 2
    finally:
        _kill(cl, servers, w)


# -- replica read policy: cluster-truth load ---------------------------------


def test_least_loaded_member_prefers_history_rates():
    h = ClusterHistory(env=None, interval_s=1.0)
    _feed_rates(h, 0.0, {S0: 500.0, S1: 2.0, S2: 300.0})
    _feed_rates(h, 1.0, {S0: 500.0, S1: 2.0, S2: 300.0})
    fake = types.SimpleNamespace(_cluster_history=h,
                                 _read_share={S0: 9, S1: 9, S2: 9})
    assert KVWorker._least_loaded_member(fake, [S0, S1, S2]) == S1
    # Without history (or with none of the members rated) it falls back
    # to the local spread counts.
    fake2 = types.SimpleNamespace(_cluster_history=None,
                                  _read_share={S0: 5, S1: 2, S2: 7})
    assert KVWorker._least_loaded_member(fake2, [S0, S1, S2]) == S1
    # A rate tie breaks on the local counts, keeping the spread fair.
    h2 = ClusterHistory(env=None, interval_s=1.0)
    _feed_rates(h2, 0.0, {S0: 5.0, S1: 5.0})
    _feed_rates(h2, 1.0, {S0: 5.0, S1: 5.0})
    fake3 = types.SimpleNamespace(_cluster_history=h2,
                                  _read_share={S0: 8, S1: 1})
    assert KVWorker._least_loaded_member(fake3, [S0, S1]) == S1


def test_load_policy_routes_reads_by_cluster_truth():
    """PS_REPLICA_READ_POLICY=load with a history attached steers pulls
    at the member the CLUSTER sees as least loaded, not just the one
    this worker used least."""
    cl = LoopbackCluster(num_workers=1, num_servers=3, env_extra={
        "PS_KV_REPLICATION": "3",
        "PS_REPLICA_READS": "1",
        "PS_REPLICA_READ_POLICY": "load",
        "PS_REQUEST_TIMEOUT": "2.0",
        "PS_REQUEST_RETRIES": "8",
        "PS_HOT_CACHE": "0",
    })
    cl.start()
    servers = []
    for po in cl.servers:
        s = KVServer(0, postoffice=po)
        s.set_request_handle(KVServerDefaultHandle())
        servers.append(s)
    w = KVWorker(0, 0, postoffice=cl.workers[0])
    try:
        keys = np.arange(16, dtype=np.uint64)  # rank 0's range
        vals = np.arange(16 * 4, dtype=np.float32)
        w.wait(w.push(keys, vals))
        out = np.zeros_like(vals)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            out[:] = 0
            w.wait(w.pull(keys, out))
            if np.array_equal(out, vals):
                break
            time.sleep(0.05)
        assert np.array_equal(out, vals), "replicas never converged"

        # Cluster truth: S1 is nearly idle, the others are slammed.
        h = ClusterHistory(env=None, interval_s=1.0)
        _feed_rates(h, 0.0, {S0: 400.0, S1: 1.0, S2: 300.0})
        _feed_rates(h, 1.0, {S0: 400.0, S1: 1.0, S2: 300.0})
        w.attach_history(h)
        w._read_share.clear()
        for _ in range(20):
            w.wait(w.pull(keys, out))
        assert np.array_equal(out, vals)
        share = dict(w._read_share)
        assert share.get(S1, 0) >= 15, share

        # Detach: the policy degrades to local spread counts and keeps
        # balancing instead of crashing or pinning.
        w.attach_history(None)
        w._read_share.clear()
        for _ in range(30):
            w.wait(w.pull(keys, out))
        share = dict(w._read_share)
        assert all(share.get(nid, 0) >= 5 for nid in (S0, S1, S2)), share
    finally:
        w.stop()
        for s in servers:
            s.stop()
        cl.finalize()


# -- scaled-down acceptance storm --------------------------------------------


@pytest.mark.slow
def test_autopilot_acceptance_storm():
    """ROADMAP acceptance, CI-sized: a drifting Zipf-style hot set under
    chaos (drop + delay), autopilot on.  The run must end with per-
    server load within 2x of the mean, the store bit-exact, ZERO
    operator actions, and every autopilot decision in the flight ring.
    """
    n_keys, dim = 48, 64
    cl = LoopbackCluster(
        num_workers=1, num_servers=3,
        van_type="chaos+loopback",
        env_extra={
            "PS_CHAOS": "seed=7,drop=0.02,delay=0.5:2",
            # Dropped frames retransmit in ~60ms instead of stalling a
            # whole PS_REQUEST_TIMEOUT (the chaos-tier pairing).
            "PS_RESEND": "1",
            "PS_RESEND_TIMEOUT": "60",
            "PS_ELASTIC": "1",
            "PS_AUTOPILOT": "1",
            "PS_METRICS_INTERVAL": "0.2",
            "PS_AUTOPILOT_SUSTAIN": "2",
            "PS_AUTOPILOT_SKEW_RATIO": "1.5",
            "PS_AUTOPILOT_SKEW_COOLDOWN_S": "1.0",
            "PS_AUTOPILOT_MIN_RATE": "5.0",
            "PS_AUTOPILOT_MAX_ACTIONS": "8",
            "PS_AUTOPILOT_TRACE_EVERY": "0",
            "PS_REQUEST_TIMEOUT": "2.0",
            "PS_REQUEST_RETRIES": "8",
            "PS_HOT_CACHE": "0",
        })
    cl.start()
    sched = cl.scheduler
    servers = []
    for po in cl.servers:
        s = KVServer(0, postoffice=po)
        s.set_request_handle(KVServerDefaultHandle())
        servers.append(s)
    w = KVWorker(0, 0, postoffice=cl.workers[0])
    try:
        span = (1 << 64) // n_keys
        keys = (np.arange(n_keys, dtype=np.uint64) * np.uint64(span)
                + np.uint64(1))
        vals = (np.arange(n_keys * dim, dtype=np.float32) % 31) + 1.0
        # Zipf-style hot bands, entirely inside rank 0's third at
        # first, drifting to the adjacent band mid-storm.
        rng = np.random.default_rng(11)
        zipf_w = 1.0 / np.arange(1, 13)
        zipf_w /= zipf_w.sum()
        hot_a, hot_b = keys[:12], keys[12:24]
        hot_out = np.zeros(8 * dim, np.float32)

        pushes = 0
        errors = []
        skews = []  # per-server load skew samples, late-storm only
        storm_s = 8.0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < storm_s:
            try:
                w.wait(w.push(keys, vals))
                pushes += 1
                band = (hot_a
                        if time.perf_counter() - t0 < storm_s / 2
                        else hot_b)
                for _ in range(6):
                    hot = np.sort(rng.choice(band, size=8, replace=False,
                                             p=zipf_w))
                    w.wait(w.pull(hot, hot_out))
            except Exception as exc:  # noqa: BLE001
                errors.append(repr(exc))
                break
            # Skew must be measured while traffic flows (windowed
            # rates decay to zero once the storm stops): sample the
            # post-drift tail, after remediation had time to land.
            if time.perf_counter() - t0 > storm_s - 2.5:
                rates = _server_rates(sched.history)
                if len(rates) == 3:
                    mean = sum(rates.values()) / len(rates)
                    if mean > 0:
                        skews.append(max(rates.values()) / mean)
        assert not errors, errors

        # Bit-exact store: pushes are additive, so the final table is
        # exactly vals * pushes despite chaos and live range handoffs.
        out = np.zeros_like(vals)
        w.wait(w.pull(keys, out))
        assert np.array_equal(out, vals * pushes)

        ap = sched.history.autopilot
        assert ap is not None
        counts = ap.counts()
        assert counts.get(ACTED, 0) >= 1, counts  # it DID rebalance
        # ZERO operator actions: nothing in this test ever touched a
        # control-plane lever — every epoch past 0 is the autopilot's.
        assert sched.current_routing().epoch >= 1
        # Every decision and veto is in the flight ring.
        evs = sched.flight.events("autopilot")
        assert len(evs) == len(ap.decision_log)
        # Late-storm per-server load within 2x of the mean.
        assert skews, "no skew sample with all 3 servers rated"
        assert min(skews) <= 2.0, skews
    finally:
        sched.stop_history()
        w.stop()
        for s in servers:
            s.stop()
        cl.finalize()
