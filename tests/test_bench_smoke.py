"""bench.py must stay runnable: exercise its measurement helper on the CPU
mesh and check the JSON contract fields.

Tier-1 note: the canonical gate these tests ride under is pinned as
``make tier1`` (Makefile — the verbatim ROADMAP.md invocation), so the
builder and reviewer never drift apart on pytest flags."""

import json
import subprocess
import sys

import pytest

jax = pytest.importorskip("jax")


def test_measure_helper_runs():
    import bench
    from pslite_tpu.parallel.engine import CollectiveEngine

    eng = CollectiveEngine()
    wall, dev = bench._measure(
        eng, "smoke", num_keys=2, val_len=1024, iters=2
    )
    assert wall > 0
    assert dev is None  # CPU mesh: no TPU plane in the trace


def test_latency_samples_helper():
    """_latency_samples (full-mode-only path: the driver is otherwise
    its first executor) returns per-op wall latencies; no device mean
    on the CPU mesh."""
    import bench
    from pslite_tpu.parallel.engine import CollectiveEngine

    eng = CollectiveEngine()
    lats, dev_us = bench._latency_samples(eng, "lat_smoke", 2, 1024, 3)
    assert len(lats) == 3 and all(l > 0 for l in lats)
    assert dev_us is None
    p50, p99 = bench._pctls(lats)
    assert p50 <= p99


def test_van_latency_harness():
    """The van_latency section's exact harness (full-mode-only): a
    1w+1s tcp cluster through the launcher must yield a parseable
    us-per-key line."""
    import os
    import re

    cmd = [
        sys.executable, "-m", "pslite_tpu.tracker.local",
        "-n", "1", "-s", "1", "--van", "tcp", "--",
        sys.executable, "-m", "pslite_tpu.benchmark",
        "--len", "65536", "--repeat", "2", "--mode", "push_pull",
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    out = subprocess.run(cmd, capture_output=True, text=True,
                         timeout=300, cwd="/root/repo", env=env)
    assert out.returncode == 0, out.stderr[-1500:]
    lats = re.findall(r"avg latency ([0-9.]+) us/key", out.stdout)
    assert lats and float(lats[0]) > 0, out.stdout[-800:]


def test_recorder_retry_and_partial(tmp_path):
    """_Recorder.run retries a flapping section, records a persistent
    failure in sections_failed, and keeps the on-disk record valid."""
    import bench

    rec = bench._Recorder(str(tmp_path / "partial.json"))
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient flap")
        return {"ok_field": 1}

    assert rec.run("flaky", flaky, retries=1, retry_sleep_s=0.0)
    assert calls["n"] == 2

    def dead():
        raise RuntimeError("hard down")

    assert not rec.run("dead", dead, retries=1, retry_sleep_s=0.0)
    snap = json.loads((tmp_path / "partial.json").read_text())
    assert snap["ok_field"] == 1
    assert snap["sections_done"] == ["flaky"]
    assert snap["sections_failed"] == [
        {"section": "dead", "error": "RuntimeError: hard down"}
    ]


def test_bench_kill9_leaves_valid_partial(tmp_path):
    """VERDICT r04 ask #2 'done' criterion: kill -9 mid-run still yields
    a valid, SHA-stamped partial JSON on disk."""
    import os

    partial = tmp_path / "partial.json"
    env = dict(
        os.environ,
        PS_BENCH_QUICK="1",
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        PS_BENCH_PARTIAL=str(partial),
    )
    proc = subprocess.Popen(
        [sys.executable, "bench.py"],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        cwd="/root/repo",
        env=env,
        text=True,
    )
    try:
        # Wait for the per-op sweep to COMPLETE (the replay_sweep mark
        # means per_op_sweep's fields were flushed), then SIGKILL.  The
        # stderr read runs on a helper thread so a silently hung child
        # fails the test at the deadline instead of blocking readline
        # forever.
        import threading

        hit = threading.Event()

        def _scan():
            for line in proc.stderr:
                if "replay_sweep" in line:
                    hit.set()
                    return

        t = threading.Thread(target=_scan, daemon=True)
        t.start()
        assert hit.wait(timeout=240), \
            "bench never reached the replay_sweep section"
    finally:
        proc.kill()
        proc.wait(timeout=30)
    snap = json.loads(partial.read_text())
    assert snap["git_sha"]
    assert snap["started_at"]
    assert "per_op_sweep" in snap["sections_done"]
    assert "sweep_1key_wall" in snap
    # The record says it is incomplete, not a finished measurement.
    assert snap["error"]


def test_bench_cli_contract(tmp_path):
    import os

    # Force the child onto CPU: the axon sitecustomize would otherwise put
    # bench.py on the real TPU tunnel, coupling the unit suite to tunnel
    # health (JAX_PLATFORMS alone is overridden programmatically, so also
    # disable the axon registration).  The partial record goes to a temp
    # path: the repo-root default must stay reserved for REAL bench runs
    # (a stale quick-smoke partial there could be mistaken for evidence).
    env = dict(
        os.environ,
        PS_BENCH_QUICK="1",
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        PS_BENCH_PARTIAL=str(tmp_path / "partial.json"),
        # The multi_tenant, small_op_batching, serving_fanin,
        # replica_read, durable_store, and autopilot sections cost
        # real-process / elastic-cluster storms each and have their
        # own dedicated harness tests (admission probe, dlrm_serve,
        # test_qos.py, test_batching.py, test_multi_get.py,
        # test_replica_read.py, test_durability.py,
        # test_tiered_store.py, test_autopilot.py + the harness
        # smokes below) — keep the CLI-contract smoke inside the
        # tier-1 wall budget; the skip markers they record are
        # exactly what bench_diff treats as absent.
        PS_BENCH_SKIP="multi_tenant,small_op_batching,serving_fanin,"
                      "replica_read,durable_store,autopilot",
    )
    out = subprocess.run(
        [sys.executable, "bench.py"],
        capture_output=True,
        timeout=560,
        cwd="/root/repo",
        env=env,
    )
    assert out.returncode == 0, out.stderr.decode()[-1500:]
    lines = [l for l in out.stdout.decode().splitlines() if l.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for field in ("metric", "value", "unit", "vs_baseline"):
        assert field in rec
    assert rec["value"] > 0
    assert rec.get("multi_tenant_skipped") == "PS_BENCH_SKIP"
    assert rec.get("small_op_batching_skipped") == "PS_BENCH_SKIP"
    assert rec.get("serving_fanin_skipped") == "PS_BENCH_SKIP"
    assert rec.get("replica_read_skipped") == "PS_BENCH_SKIP"
    assert rec.get("durable_skipped") == "PS_BENCH_SKIP"
    assert rec.get("autopilot_skipped") == "PS_BENCH_SKIP"


def test_telemetry_overhead_guard():
    """The telemetry layer must never silently become the bottleneck:
    the kv loopback storm with PS_TELEMETRY on — INCLUDING the
    continuous METRICS_PULL sampler at a 1 s interval
    (docs/observability.md) — stays within 10% of telemetry-off on the
    stub bench, and so does TAIL TRACING at the production floor rate
    (every request stamped and span-recorded, keep decided at
    completion).  Min-of-3 per leg to damp scheduler noise, plus a
    small absolute epsilon for sub-second walls."""
    from pslite_tpu.benchmark import kv_loopback_storm

    def best(telemetry: bool, extra=None) -> float:
        walls = []
        for _ in range(3):
            r = kv_loopback_storm(
                n_workers=2, n_servers=2, msgs_per_worker=40,
                keys_per_msg=8, val_len=512, telemetry=telemetry,
                env_extra=extra,
            )
            walls.append(r["wall_s"])
        return min(walls)

    # Interleave-insensitive order: off first warms every code path.
    off = best(False)
    on = best(True, {"PS_METRICS_INTERVAL": "1"})
    assert on <= off * 1.10 + 0.05, (
        f"telemetry overhead too high: on={on:.3f}s off={off:.3f}s "
        f"({on / off:.2f}x)"
    )
    tail = best(True, {"PS_TRACE_TAIL": "slow:p95,errors,floor:0.001"})
    assert tail <= off * 1.10 + 0.05, (
        f"tail-tracing overhead too high: tail={tail:.3f}s "
        f"off={off:.3f}s ({tail / off:.2f}x)"
    )
    # And the instrumented leg actually measured something.
    r = kv_loopback_storm(n_workers=1, n_servers=1, msgs_per_worker=5,
                          telemetry=True)
    tel = r["telemetry"]
    worker = next(v for k, v in tel.items() if k.startswith("worker"))
    assert worker["counters"]["kv.pushes"] == 5
    assert worker["histograms"]["kv.push_latency_s"]["count"] == 5


def test_chunk_hol_harness():
    """The chunk_streaming section's harness: one subprocess leg of
    ``--mode chunk_hol`` (real tcp cluster via the local tracker) must
    produce the measurement line.  Ratios are asserted nowhere — the
    bench records them; see docs/chunking.md."""
    from pslite_tpu.benchmark import _chunk_run

    r = _chunk_run(8, 1, str(256 << 10))
    assert r["push_gbps"] > 0
    assert r["pull_p50_ms"] >= 0 and r["pull_p99_ms"] >= r["pull_p50_ms"]


def test_quantized_push_harness():
    """The quantized_push section's harness: one subprocess leg of
    ``--mode quantized_push`` with a codec set (real tcp cluster via
    the local tracker) must produce the measurement line; goodput is
    defined over RAW bytes (effective goodput)."""
    from pslite_tpu.benchmark import _chunk_run

    r = _chunk_run(8, 1, str(256 << 10),
                   extra_env={"PS_BENCH_CODEC": "int8",
                              "PS_CODEC_EF": "0"},
                   mode="quantized_push")
    assert r["push_gbps"] > 0
    assert r["pull_p99_ms"] >= r["pull_p50_ms"] >= 0


def _bench_record(**over):
    rec = {
        "chunk_chunked_push_gbps": 10.0,
        "native_goodput_ratio": 2.0,
        "quantized_goodput_ratio_int8": 2.5,
        "small_op_batching_msgs_ratio": 4.2,
        "kv_storm_msgs_per_s": 1000.0,
        "fault_recovery_detect_s": 1.0,
        "some_untracked_wall_s": 5.0,
    }
    rec.update(over)
    return rec


@pytest.mark.slow
def test_small_op_storm_harness():
    """The small_op_batching section's harness: one short subprocess
    leg of ``--mode small_op_storm`` with the combiner on (real tcp
    cluster via the local tracker) must produce the measurement line
    with batches actually formed and the order-sensitive store check
    passing.  Slow-marked like the dlrm harness: the plane's semantics
    are covered by the fast loopback tests in tests/test_batching.py —
    the ratio itself is the bench's job."""
    from pslite_tpu.benchmark import _small_op_run

    r = _small_op_run(1.0, batch=True)
    assert r["ops"] > 0 and r["msgs_per_s"] > 0
    assert r["ops_per_frame"] > 1.0  # multi-op frames really formed
    assert r["store_exact"]
    assert r["p99_ms"] >= r["p50_ms"] >= 0


@pytest.mark.slow
def test_serving_fanin_harness():
    """The serving_fanin section's harness: one short subprocess leg
    of ``--mode serving_fanin`` with the aggregation planes on (real
    1w+2s tcp cluster via the local tracker) must produce the
    measurement line with the fan-in actually formed (response frames
    per request far below the fan-out) and every spot-checked request
    bit-exact.  Slow-marked like the small-op harness: the plane's
    semantics are covered by the fast loopback tests in
    tests/test_multi_get.py — the ratio itself is the bench's job."""
    from pslite_tpu.benchmark import _serving_fanin_run

    r = _serving_fanin_run(1.0, batch=True)
    assert r["reqs"] > 0 and r["reqs_per_s"] > 0
    assert r["servers"] == 2
    # Fan-in really formed: ~1 frame per contacted server, nowhere
    # near one frame per lookup.
    assert r["frames_per_req"] < r["fanout"] / 4
    assert r["store_exact"]
    assert r["p99_ms"] >= r["p50_ms"] >= 0


def test_bench_diff_gates_serving_fanin(tmp_path):
    """The serving_fanin guard: a collapsing requests/s ratio (or
    ballooning frames/request) fails the check; the PS_BENCH_SKIP
    marker reads as absent, never a vanished metric."""
    import sys as _sys

    _sys.path.insert(0, "tools")
    import bench_diff

    old = tmp_path / "BENCH_r07.json"
    new = tmp_path / "BENCH_r08.json"
    base = _bench_record(serving_fanin_req_ratio=4.0,
                         serving_fanin_frames_per_req=1.6)
    old.write_text(json.dumps(base))
    new.write_text(json.dumps(_bench_record(
        serving_fanin_req_ratio=4.0,
        serving_fanin_frames_per_req=8.0,  # 5x more frames: regression
    )))
    assert bench_diff.main([str(old), str(new)]) == 1
    rec = _bench_record()
    rec["serving_fanin_skipped"] = "PS_BENCH_SKIP"
    new.write_text(json.dumps(rec))
    assert bench_diff.main([str(old), str(new)]) == 0


def test_bench_diff_gates_small_op_ratio(tmp_path):
    """The small_op_batching guard: a collapsing msgs ratio (or a
    ballooning low-load p50 ratio) fails the check; the section's
    PS_BENCH_SKIP marker reads as absent, never a vanished metric."""
    import sys as _sys

    _sys.path.insert(0, "tools")
    import bench_diff

    old = tmp_path / "BENCH_r07.json"
    new = tmp_path / "BENCH_r08.json"
    old.write_text(json.dumps(_bench_record()))
    new.write_text(json.dumps(_bench_record(
        small_op_batching_msgs_ratio=2.0,  # -52%: regression
    )))
    assert bench_diff.main([str(old), str(new)]) == 1
    rec = _bench_record()
    del rec["small_op_batching_msgs_ratio"]
    rec["small_op_batching_skipped"] = "PS_BENCH_SKIP"
    new.write_text(json.dumps(rec))
    assert bench_diff.main([str(old), str(new)]) == 0


def test_bench_diff_history(tmp_path):
    """``bench_diff --history`` (ISSUE 10 satellite): the full
    BENCH_r*.json trajectory renders one sparkline row per guarded
    metric with min/max/last, flags a newest-record blind spot, and
    shows per-round status so a blind stretch (the r04/r05 mode) is
    visible at a glance."""
    import sys as _sys

    _sys.path.insert(0, "tools")
    import bench_diff

    for rnd, ratio in ((1, 4.0), (2, 4.4), (3, 4.2)):
        (tmp_path / f"BENCH_r{rnd:02d}.json").write_text(
            json.dumps(_bench_record(small_op_batching_msgs_ratio=ratio)))
    lines = bench_diff.history(str(tmp_path))
    text = "\n".join(lines)
    assert "r01..r03" in text
    row = next(l for l in lines
               if l.strip().startswith("small_op_batching_msgs_ratio"))
    assert "4" in row and "4.4" in row  # min/max/last columns
    assert any(ch in row for ch in bench_diff._SPARK)
    # A blind newest round: the metric row flags it, and the round
    # status line shows zero guarded fields.
    (tmp_path / "BENCH_r04.json").write_text(json.dumps(
        {"error": "tunnel down", "sections_done": []}))
    lines2 = bench_diff.history(str(tmp_path))
    text2 = "\n".join(lines2)
    assert "BLIND" in text2
    # The blind round renders an explicit ∅ sparkline cell (distinct
    # from '·' = metric predates its section) plus the legend.
    row2 = next(l for l in lines2
                if l.strip().startswith("small_op_batching_msgs_ratio"))
    assert "∅" in row2 and "∅ blind" in row2
    assert any("legend" in l for l in lines2)
    # CLI flag: exits 0 and prints the table.
    assert bench_diff.main(["--history", "--dir", str(tmp_path)]) == 0


def test_bench_diff_guard(tmp_path):
    """tools/bench_diff.py (``make bench-check``): per-section deltas,
    exit 0 within threshold, exit nonzero on a >25% regression in a
    guarded transport metric — direction-aware (a LOWER detect time
    passes, a lower goodput ratio fails), untracked fields never
    gate."""
    import sys as _sys

    _sys.path.insert(0, "tools")
    import bench_diff

    old = tmp_path / "BENCH_r07.json"
    new = tmp_path / "BENCH_r08.json"
    old.write_text(json.dumps(_bench_record()))
    # Within threshold + an improvement + untracked field regressing.
    new.write_text(json.dumps(_bench_record(
        chunk_chunked_push_gbps=9.0,     # -10%: ok
        fault_recovery_detect_s=0.5,     # lower = better
        some_untracked_wall_s=50.0,      # untracked: ignored
    )))
    assert bench_diff.main([str(old), str(new)]) == 0
    # Newest-two discovery inside a directory.
    assert bench_diff.main(["--dir", str(tmp_path)]) == 0
    # A guarded ratio collapsing fails the check.
    new.write_text(json.dumps(_bench_record(
        quantized_goodput_ratio_int8=1.0,  # -60%: regression
    )))
    assert bench_diff.main([str(old), str(new)]) == 1
    # Direction awareness: detect time ballooning fails too.
    new.write_text(json.dumps(_bench_record(
        fault_recovery_detect_s=2.0,
    )))
    assert bench_diff.main([str(old), str(new)]) == 1
    # Threshold is configurable.
    assert bench_diff.main(
        [str(old), str(new), "--threshold", "1.5"]
    ) == 0
    # A guarded metric VANISHING from the newer record fails loudly —
    # a crashed section must never read as a pass (the r04/r05 blind-
    # record failure mode).
    rec = _bench_record()
    del rec["quantized_goodput_ratio_int8"]
    new.write_text(json.dumps(rec))
    assert bench_diff.main([str(old), str(new)]) == 1


def test_bench_diff_skipped_sections_not_regressions(tmp_path):
    """A section that degraded with an explicit ``{"skipped": reason}``
    (device down, toolchain absent) must read as ABSENT, not as a
    vanished-metric regression — `make bench-check` on a device-down
    round must still pass."""
    import sys as _sys

    _sys.path.insert(0, "tools")
    import bench_diff

    old = tmp_path / "BENCH_r07.json"
    new = tmp_path / "BENCH_r08.json"
    old.write_text(json.dumps(_bench_record()))
    rec = _bench_record()
    # The native section skipped this round: its guarded metrics are
    # gone but the skip marker names why.
    del rec["native_goodput_ratio"]
    rec["native_skipped"] = "native core unavailable"
    new.write_text(json.dumps(rec))
    assert bench_diff.main([str(old), str(new)]) == 0
    # Without the marker the same vanishing still fails (r04/r05 mode).
    rec2 = _bench_record()
    del rec2["native_goodput_ratio"]
    new.write_text(json.dumps(rec2))
    assert bench_diff.main([str(old), str(new)]) == 1


def test_bench_check_on_committed_records():
    """`make bench-check` wiring (tier-1 smoke): bench_diff against the
    repo's committed BENCH_r*.json pair must succeed — the trajectory
    guard stays runnable on every checkout."""
    import sys as _sys

    _sys.path.insert(0, "tools")
    import bench_diff

    pair = bench_diff.newest_two("/root/repo")
    assert pair is not None, "committed BENCH_r*.json records missing"
    assert bench_diff.main(list(pair)) == 0
    # And the Makefile target that CI runs exists.
    mk = open("/root/repo/Makefile").read()
    assert "bench-check:" in mk and "bench_diff" in mk


def test_multi_tenant_admission_probe():
    """The multi_tenant section's admission half (docs/qos.md): the
    loopback flood sheds with OPT_OVERLOAD fast-fails, nothing hangs,
    store bit-exact at applied-count."""
    from pslite_tpu.benchmark import admission_probe

    r = admission_probe()
    assert r["applied"] + r["shed"] == r["offered"]
    assert r["shed"] > 0
    assert r["store_exact"]


@pytest.mark.slow
def test_dlrm_serve_harness():
    """The multi_tenant section's DLRM half: one subprocess leg of
    ``--mode dlrm_serve`` with the hot cache on (real tcp cluster via
    the local tracker) must produce the measurement line with a
    nonzero hit rate and bit-exact spot checks.  Slow-marked: the
    tier-1 wall budget is tight and the cache semantics are already
    covered by the fast loopback tests in tests/test_qos.py — this
    harness is exercised by the bench itself."""
    from pslite_tpu.benchmark import _dlrm_run

    r = _dlrm_run(150, cache=True)
    assert r["samples"] == 150
    assert r["hit_rate"] > 0.3
    assert r["pull_p50_ms"] >= 0


def test_send_lanes_fanout_harness():
    """The send_lanes section's harness: laned fan-out must beat the
    serialized (PS_SEND_LANES=0) replay on a stub transport with a
    fixed per-message delay."""
    from pslite_tpu.benchmark import fanout_wall_times

    laned, serial = fanout_wall_times(n_peers=6, delay_s=0.02, rounds=2)
    assert laned > 0 and serial > 0
    # Serial must cost ~6x the delay; laned ~1-2x.  Keep the bound loose
    # for CI noise but strictly below the no-overlap regime.
    assert laned < serial, (laned, serial)
    assert laned < 0.6 * serial, (laned, serial)
