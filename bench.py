"""Headline benchmark: dense KV push-pull application goodput.

Mirrors the reference's ``tests/test_benchmark`` PUSH_PULL mode
(test_benchmark.cc:388-396): goodput counts application payload bytes
(push + pull) per second, over the default dense workload (40 keys x
1 MB, repeat-timed).  Runs on whatever accelerator JAX exposes (the real
TPU chip under the driver; do NOT set JAX_PLATFORMS=cpu here).

Timing basis — every field is labeled by suffix:
- ``*_device`` / the headline ``value``: goodput over XPlane
  device-seconds (the union of XLA-op intervals on the TPU timeline).
  The ONLY basis the repo trusts: wall clock through the axon tunnel
  swings 20-50x between elision and serialization regimes (r02 recorded
  a "goodput" above the chip's physical HBM bandwidth; r03 recorded
  0.4% of it for identical code).
- ``*_wall``: host wall clock, recorded for continuity and labeled
  untrustworthy under the tunnel (``wall_unreliable``).

The headline runs with ``zero_copy=True`` (in-place pull delivery — the
returned array aliases the store, the reference's RegisterRecvBuffer
contract); ``headline_copy_pull_device`` records the copying path.  The
``impl`` object records which data plane produced the numbers
(PS_ICI_IMPL resolution — the ring kernel needs >=2 ring devices, so
single-chip numbers are always the XLA path).

Honesty notes (single chip):
- On a 1-device mesh ``psum_scatter``/``all_gather`` degenerate to local
  HBM ops — the headline is an HBM/dispatch benchmark, NOT an ICI
  benchmark.  ``vs_baseline`` (normalized against 0.7 x 100 GB/s =
  70 GB/s/chip, the driver's >=70%-of-ICI-line-rate bar) is an
  ICI-budget ratio the single-chip path never traverses;
  ``hbm_util_vs_measured`` (headline traffic = 3x payload/iter vs the
  device-basis triad peak) is the honest single-chip measure.
- The reference publishes no absolute numbers (BASELINE.json
  "published": {}).

Resilience: the TPU tunnel can flap (round 1 recorded rc=1 with no
number; round 4 lost an entire run to a mid-run outage).  Defenses:
- Backend init is probed in a subprocess with a timeout and retried
  with backoff; on final failure ONE parseable JSON line with an
  ``error`` field is printed (value 0) instead of a traceback.
- The run is split into named sections; each section's fields are
  merged into the record and the ENTIRE record so far is atomically
  rewritten to ``BENCH_PARTIAL.json`` (override: ``PS_BENCH_PARTIAL``)
  as the section completes — a kill -9 at any moment leaves a valid,
  git-SHA-stamped partial JSON on disk (the reference's incremental
  LOG_DURATION reporting, test_benchmark.cc:388-396).
- A failed section is retried once (flaps are transient), then recorded
  in ``sections_failed`` while the rest of the run continues; the
  watchdog timeout emits everything measured so far, not a bare error.
- Every record carries ``git_sha`` + ``started_at`` so numbers are
  traceable to the exact code state they measured.

Prints ONE JSON line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

# Rough per-chip HBM bandwidth (GB/s) by device_kind substring, for the
# utilization estimate.  Public figures; best-effort match.
_HBM_GBPS = (
    ("v6", 1640.0),
    ("v5p", 2765.0),
    ("v5e", 819.0),
    ("v5 lite", 819.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
)

# The probe honors an explicitly-set JAX_PLATFORMS (the axon sitecustomize
# force-overrides the env var programmatically, so it must be re-applied
# via jax.config after import — e.g. the PS_BENCH_QUICK CPU smoke).
_PROBE_SRC = (
    "import json, os, jax; p = os.environ.get('JAX_PLATFORMS'); "
    "jax.config.update('jax_platforms', p) if p else None; "
    "d = jax.devices()[0]; "
    "print(json.dumps({'platform': d.platform, "
    "'device_kind': d.device_kind, 'n': jax.device_count()}))"
)


def _probe_backend(attempts: int = 3, timeout_s: int = 180) -> dict:
    """Initialize the JAX backend in a THROWAWAY subprocess with a hard
    timeout — ``jax.devices()`` hangs forever when the axon tunnel is
    down, and a hung in-process init cannot be recovered.  Retries with
    backoff because the tunnel flaps transiently."""
    delays = (20, 60)
    last = ""
    for i in range(attempts):
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True,
                text=True,
                timeout=timeout_s,
            )
            if out.returncode == 0 and out.stdout.strip():
                return json.loads(out.stdout.strip().splitlines()[-1])
            last = (out.stderr or out.stdout or "").strip()[-500:]
        except subprocess.TimeoutExpired:
            last = f"backend init timed out after {timeout_s}s (tunnel down?)"
        except Exception as exc:  # noqa: BLE001 - report, don't crash
            last = repr(exc)
        if i < attempts - 1:
            time.sleep(delays[min(i, len(delays) - 1)])
    return {"error": last or "backend probe failed"}


def _hbm_estimate(device_kind: str) -> float | None:
    kind = (device_kind or "").lower()
    for sub, gbps in _HBM_GBPS:
        if sub in kind:
            return gbps
    return None


def _device_busy(run) -> float | None:
    """MEAN per-device busy seconds of the TPU work in ``run()`` (XPlane).

    The honest denominator under the axon tunnel: the device-side
    timeline cannot be elided.  The mean across device planes (not the
    sum) keeps bytes/busy dimensionally identical to bytes/elapsed — on
    an n-chip mesh the chips work concurrently, so summing their busy
    time would deflate goodput by ~n exactly when the wall number
    doesn't.  Returns None when no TPU plane shows up (CPU smoke)."""
    import shutil
    import tempfile

    from pslite_tpu.utils import xplane
    from pslite_tpu.utils.profiling import device_trace

    d = tempfile.mkdtemp(prefix="psbench_xp_")
    try:
        # Engine/loop errors must PROPAGATE (main turns them into the
        # parseable error line) — a silently-swallowed mid-loop failure
        # would publish a plausible number computed from incomplete
        # work.  PROFILER start/stop and the XPlane parse stay
        # best-effort: a flaky trace must degrade this measurement to
        # its wall number, not abort the whole bench.
        ctx = device_trace(d)
        traced = True
        try:
            ctx.__enter__()
        except Exception:  # noqa: BLE001 - profiler is best-effort
            traced = False
        try:
            run()
        finally:
            if traced:
                try:
                    ctx.__exit__(None, None, None)
                except Exception:  # noqa: BLE001
                    traced = False
        if not traced:
            return None
        try:
            busy = xplane.device_busy_seconds(d)
        except Exception:  # noqa: BLE001 - parsing is best-effort
            return None
        if not busy:
            return None
        return sum(busy.values()) / len(busy)
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _traced(run) -> tuple[float | None, float]:
    """(device_busy_seconds | None, wall_seconds) of ONE traced run —
    both clocks from the same execution.  Wall is timed around run()
    ALONE (inside the trace context): profiler start/stop, XSpace
    parsing, and tempdir teardown stay out of every *_wall field."""
    wall = {}

    def wrapped():
        t0 = time.perf_counter()
        run()
        wall["s"] = time.perf_counter() - t0

    busy = _device_busy(wrapped)
    return busy, wall["s"]


def _dual_measure(store: dict):
    """A ``measure`` hook (models/resnet_trace.replay contract) that
    returns device-busy seconds AND records the loop's wall seconds in
    ``store["wall"]`` — both clocks from one execution, so the heavy
    model workloads run once instead of once per basis."""

    def m(loop):
        busy, wall = _traced(loop)
        store["wall"] = wall
        return busy

    return m


def _hbm_peak_measured(iters: int = 50) -> tuple[float, float | None]:
    """Practical HBM peak (GB/s) via a chained donated triad
    (s = s*a + g, 64 MB, traffic = read s + read g + write s = 3x).

    Returns (wall_peak, device_peak): the wall number inherits every
    tunnel distortion in BOTH directions — r02 saw a 9.8 TB/s "triad"
    (elision), r03 a 108 GB/s one (round-trip dominated).  The device
    peak comes from the XPlane trace of the same loop and is the
    apples-to-apples denominator for the device-time headline."""
    import jax
    import jax.numpy as jnp

    n = 16 << 20
    g = jnp.ones((n,), jnp.float32)
    step = jax.jit(lambda s, g: s * 0.999 + g, donate_argnums=(0,))
    s = jnp.zeros((n,), jnp.float32)
    s = step(s, g)
    s.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        s = step(s, g)
    s.block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    wall = 3 * (n * 4) / dt / 1e9

    state = {"s": s}

    def run():
        for _ in range(iters):
            state["s"] = step(state["s"], g)
        state["s"].block_until_ready()

    busy = _device_busy(run)
    dev = 3 * (n * 4) * iters / busy / 1e9 if busy else None
    return wall, dev


def _measure(eng, name: str, num_keys: int, val_len: int, iters: int,
             host_grads: bool = False, handle=None, dtype=None,
             zero_copy: bool = False) -> tuple[float, float | None]:
    """(wall_gbps, device_gbps | None) of iterated push_pull on one
    registered bucket, both clocks from the same traced loop.

    ``host_grads=True`` measures the message-origin path real users hit:
    the host->HBM ``device_put`` of a (persistent) host numpy buffer runs
    inside the timed loop.  ``dtype`` (default float32) sets the bucket
    dtype; goodput counts actual payload bytes.  ``zero_copy`` requests
    in-place pull delivery (engine.push_pull docs)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    if dtype is None:
        dtype = jnp.float32
    itemsize = np.dtype(dtype).itemsize
    keys = np.arange(num_keys, dtype=np.uint64)
    eng.register_dense(name, keys, val_len, dtype=dtype)
    bucket = eng.bucket(name)
    sharding = NamedSharding(eng.mesh, P(eng.axis, None))
    if host_grads:
        inp = np.ones((eng.num_shards, bucket.padded_len),
                      np.dtype(dtype))
    elif zero_copy and eng.flat_zc_eligible(handle):
        # The degenerate zero-copy program takes grads FLAT (rank
        # squeezes relayout packed dtypes at ~47 GB/s — engine
        # _prep_grads_flat docs); pass the preferred form.
        inp = jax.device_put(
            jnp.ones((bucket.padded_len,), dtype),
            NamedSharding(eng.mesh, P(eng.axis)),
        )
    elif eng.flat_ring_eligible(dtype, handle):
        # The 1-D ring programs take grads FLAT [W*padded] — passing
        # the 2-D rows would relayout per call INSIDE the timed loop.
        inp = jax.device_put(
            jnp.ones((eng.num_shards * bucket.padded_len,), dtype),
            NamedSharding(eng.mesh, P(eng.axis)),
        )
    else:
        inp = jax.device_put(
            jnp.ones((eng.num_shards, bucket.padded_len), dtype),
            sharding,
        )
    # Warmup: compile + first-touch (the rendezvous equivalent).
    for _ in range(3):
        out = eng.push_pull(name, inp, handle=handle, zero_copy=zero_copy)
    out.block_until_ready()

    def run():
        out = None
        for _ in range(iters):
            out = eng.push_pull(name, inp, handle=handle,
                                zero_copy=zero_copy)
        out.block_until_ready()

    busy, wall = _traced(run)
    payload = num_keys * val_len * itemsize  # bytes per direction
    moved = 2 * payload * iters  # push + pull
    return (moved / wall / 1e9,
            moved / busy / 1e9 if busy else None)


def _measure_replay(eng, name: str, num_keys: int, val_len: int,
                    steps: int) -> tuple[float, float | None]:
    """(wall, device) goodput GB/s of ONE fused T-step replay program —
    the dispatch-amortized form of the 1-key sweep (VERDICT r02 #2: the
    sub-1MB sweep was 38-680x off the headline purely on per-op
    dispatch overhead).  The sequence is staged from host numpy (the
    slab layout builds host-side with zero device relayout copies) and
    the pull is zero-copy — wall time therefore includes the host->HBM
    staging; device time is the scan program itself."""
    import numpy as np

    keys = np.arange(num_keys, dtype=np.uint64)
    eng.register_dense(name, keys, val_len)
    payload = num_keys * val_len * 4
    seq = np.ones((steps, num_keys * val_len), np.float32)
    out = eng.replay(name, seq, keep="last", zero_copy=True)  # compile
    out.block_until_ready()

    def run():
        eng.replay(name, seq, keep="last",
                   zero_copy=True).block_until_ready()

    busy, wall = _traced(run)
    moved = 2 * payload * steps
    return (moved / wall / 1e9,
            moved / busy / 1e9 if busy else None)


def _latency_samples(eng, name: str, num_keys: int, val_len: int,
                     samples: int, zero_copy: bool = True):
    """Per-op completion latencies (µs) of INDIVIDUALLY-awaited
    push_pull calls — the reference's latency regime (one Wait per
    round, test_benchmark.cc:393) as opposed to :func:`_measure`'s
    pipelined loop, whose per-iteration time hides dispatch latency
    behind device queuing.  Returns (wall_us_list, device_us_mean|None);
    the device mean is the op's on-chip occupancy, the floor the
    dispatch path adds its overhead to."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    keys = np.arange(num_keys, dtype=np.uint64)
    eng.register_dense(name, keys, val_len)
    bucket = eng.bucket(name)
    if zero_copy and eng.flat_zc_eligible(None):
        inp = jax.device_put(
            jnp.ones((bucket.padded_len,), jnp.float32),
            NamedSharding(eng.mesh, P(eng.axis)),
        )
    elif eng.flat_ring_eligible(jnp.float32, None):
        # Flat [W*padded]: the ring programs' native layout (_measure).
        inp = jax.device_put(
            jnp.ones((eng.num_shards * bucket.padded_len,), jnp.float32),
            NamedSharding(eng.mesh, P(eng.axis)),
        )
    else:
        inp = jax.device_put(
            jnp.ones((eng.num_shards, bucket.padded_len), jnp.float32),
            NamedSharding(eng.mesh, P(eng.axis, None)),
        )
    for _ in range(3):
        eng.push_pull(name, inp, zero_copy=zero_copy).block_until_ready()
    lats: list[float] = []

    def run():
        for _ in range(samples):
            t0 = time.perf_counter()
            eng.push_pull(name, inp,
                          zero_copy=zero_copy).block_until_ready()
            lats.append((time.perf_counter() - t0) * 1e6)

    busy = _device_busy(run)
    return lats, (busy / samples * 1e6 if busy else None)


def _pctls(lats) -> tuple[float, float]:
    import numpy as np

    a = np.asarray(lats)
    return (float(np.percentile(a, 50)), float(np.percentile(a, 99)))


def _sparse_engine(eng):
    from pslite_tpu.parallel.sparse import SparseEngine

    return SparseEngine(eng.mesh, eng.axis)


_emit_mu = threading.Lock()
_emitted = False


def _mark(section: str) -> None:
    """Progress stamp on STDERR (stdout carries exactly one JSON line):
    a watchdog-timeout or driver-kill then shows WHERE the run stalled
    (the r04 tunnel outage produced timeouts with no trace)."""
    print(f"[bench {time.strftime('%H:%M:%S')}] {section}",
          file=sys.stderr, flush=True)


def _git_sha() -> str | None:
    """HEAD SHA of the repo this bench file lives in (best effort) —
    every emitted record must be traceable to a code state (VERDICT r04
    weak #2: no bench artifact recorded what it measured)."""
    try:
        out = subprocess.run(
            ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
             "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=10,
        )
        if out.returncode == 0:
            return out.stdout.strip()
    except Exception:  # noqa: BLE001 - provenance is best-effort
        pass
    return None


class _Recorder:
    """Incremental result accumulator with an atomically-rewritten
    on-disk partial record.

    The r04 driver artifact was an empty error line because bench.py
    emitted one JSON at the very end and the tunnel flapped mid-run
    (VERDICT r04 weak #1).  The reference harness reports incrementally
    every LOG_DURATION rounds (test_benchmark.cc:388-396); the analog
    here: after EVERY section the full record so far is rewritten to
    ``path`` via write-tmp + os.replace, so a kill -9 at any moment
    still leaves a valid, SHA-stamped partial JSON on disk."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._mu = threading.Lock()
        self._io_mu = threading.Lock()  # serializes flush vs watchdog
        self._fields: dict = {}
        self._done: list[str] = []
        self._failed: list[dict] = []

    def merge(self, fields: dict) -> None:
        with self._mu:
            self._fields.update(fields)

    def drop(self, key: str) -> None:
        with self._mu:
            self._fields.pop(key, None)

    def section_ok(self, name: str) -> None:
        with self._mu:
            self._done.append(name)

    def section_fail(self, name: str, err: str) -> None:
        with self._mu:
            self._failed.append({"section": name, "error": err[-300:]})

    def snapshot(self) -> dict:
        with self._mu:
            obj = dict(self._fields)
            obj["sections_done"] = list(self._done)
            obj["sections_failed"] = list(self._failed)
            return obj

    def flush(self) -> None:
        # _io_mu: the watchdog thread flushes concurrently with the main
        # thread; an unserialized write-tmp/replace pair could promote an
        # interleaved half-written tmp file — the one corruption mode the
        # atomic rewrite exists to rule out.
        with self._io_mu:
            self._flush_locked()

    def _flush_locked(self) -> None:
        obj = self.snapshot()
        try:
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(obj, f, indent=1)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except Exception:  # noqa: BLE001 - disk record is best-effort
            pass

    def run(self, name: str, fn, retries: int = 1,
            retry_sleep_s: float = 10.0) -> bool:
        """Run one section: merge its returned fields, rewrite the disk
        record, and on failure retry (tunnel flaps are transient) before
        recording it in ``sections_failed`` and moving on."""
        err = ""
        for attempt in range(retries + 1):
            _mark(name if attempt == 0 else f"{name} (retry {attempt})")
            try:
                fields = fn()
                if fields:
                    self.merge(fields)
                self.section_ok(name)
                self.flush()
                return True
            except Exception as exc:  # noqa: BLE001 - isolate sections
                err = f"{type(exc).__name__}: {exc}"
                _mark(f"{name} FAILED: {err[:200]}")
                if attempt < retries:
                    time.sleep(retry_sleep_s)
        self.section_fail(name, err)
        self.flush()
        return False


# Device/TPU section names per mode, used to stamp per-section skip
# reasons when the backend never comes up (the sections themselves are
# defined in main(); transport sections live in _transport_sections).
_DEVICE_SECTIONS_QUICK = ("engine_init", "headline", "host_origin",
                          "latency")
_DEVICE_SECTIONS_FULL = (
    "engine_init", "per_op_sweep", "replay_sweep", "headline",
    "copy_pull", "host_origin", "dtype_variants", "resnet", "embedding",
    "coalesced", "latency", "stress", "hbm_peak",
)


def _transport_sections(quick: bool) -> list:
    """``(name, fn)`` pairs for the HOST-SIDE transport sections — no
    device backend required.  These run (and emit real numbers) even
    when the TPU tunnel is down: BENCH json was blind device-side from
    r04 on, so the transport trajectory must never depend on device
    availability (device sections skip with a reason instead).

    ``PS_BENCH_SKIP`` (comma-separated section names) records an
    explicit ``<name>_skipped`` marker instead of running — used by
    the tier-1 CLI-contract smoke to keep heavyweight sections (which
    have their own dedicated harness tests) out of the suite's wall
    budget; bench_diff treats the marker as absent, never a vanished
    metric."""

    def sec_send_lanes():
        # Per-peer send-lane overlap (the fan-out serialization the
        # lane scheduler removed): N stub peers, each charging a
        # fixed per-message transport delay.  Serialized dispatch
        # (PS_SEND_LANES=0, the old van-wide-lock regime) costs
        # ~N*delay per round; lanes cost ~delay.  Pure host-side —
        # no backend, no sockets — so it prices the Van scheduler
        # itself, tunnel-independent.
        from pslite_tpu.benchmark import fanout_wall_times

        n_peers, delay_s, rounds = 8, 0.010, 3
        laned, serial = fanout_wall_times(n_peers, delay_s, rounds)
        return {
            "send_lanes_fanout_peers": n_peers,
            "send_lanes_per_msg_delay_ms": delay_s * 1e3,
            "send_lanes_laned_ms": round(laned * 1e3, 2),
            "send_lanes_serialized_ms": round(serial * 1e3, 2),
            "send_lanes_overlap_x": round(serial / max(laned, 1e-9), 2),
        }

    def sec_server_apply():
        # Server-side sharded apply (the receive-path mirror of
        # send_lanes): a 4-worker-stub push storm through ONE
        # dispatcher thread, applied serially (PS_APPLY_SHARDS=0,
        # the pre-shard regime) vs through the 4-shard apply pool.
        from pslite_tpu.benchmark import apply_storm_rates

        shards = 4
        cfg = (dict(n_workers=4, msgs_per_worker=4, keys_per_msg=8,
                    val_len=1 << 20, rounds=2) if quick
               else dict(n_workers=4, msgs_per_worker=8,
                         keys_per_msg=8, val_len=1 << 20, rounds=2))
        serial = apply_storm_rates(0, **cfg)
        sharded = apply_storm_rates(shards, **cfg)
        return {
            "server_apply_serial_msgs_per_s": round(serial, 1),
            "server_apply_sharded_msgs_per_s": round(sharded, 1),
            "server_apply_shards": shards,
            "server_apply_workers": cfg["n_workers"],
            "server_apply_msg_mb": round(
                cfg["keys_per_msg"] * cfg["val_len"] * 4 / 2**20, 1),
            # None (not a bogus ratio) when either leg timed out.
            "server_apply_speedup_x": (
                round(sharded / serial, 2)
                if serial > 0 and sharded > 0 else None),
        }

    def sec_kv_telemetry():
        # Registry snapshot embedded in the emitted record
        # (docs/observability.md): a live loopback KV storm's
        # counters + histogram quantiles land next to the throughput
        # numbers so perf regressions come with their context.  Rates
        # are WINDOWED (counter deltas over the measured interval,
        # per-node "windowed_per_s" sub-dicts) — uptime averages fold
        # bootstrap time into the denominator and go stale within
        # minutes.  The kv_windowed_* roll-ups are context only:
        # bench_diff ignores them (interval-dependent, host-noisy).
        from pslite_tpu.benchmark import kv_loopback_storm

        storm = kv_loopback_storm(msgs_per_worker=20 if quick else 60)
        windowed = {}
        for node, cond in storm["telemetry"].items():
            for cname, rate in cond.get("windowed_per_s", {}).items():
                if cname in ("kv.pushes", "kv.pulls",
                             "apply.sharded_requests"):
                    key = ("kv_windowed_"
                           + cname.replace(".", "_") + "_per_s")
                    windowed[key] = round(
                        windowed.get(key, 0.0) + rate, 2)
        return {
            "kv_storm_msgs_per_s": storm["msgs_per_s"],
            "kv_storm_wall_s": storm["wall_s"],
            **windowed,
            "telemetry": storm["telemetry"],
        }

    def sec_kv_tracing():
        # Tail-based request tracing (docs/observability.md): the same
        # loopback storm with PS_TRACE_TAIL on, followed by a live
        # TRACE_PULL assembly round — the record carries the kept/
        # assembled counts and the slow set's per-stage shares, so a
        # perf regression comes with its own "where did the tail
        # live" attribution.  Context only: bench_diff notes but never
        # gates kv_tracing_* fields (host-load-shaped, like the
        # windowed rates).
        from pslite_tpu.benchmark import kv_tracing_storm

        r = kv_tracing_storm(msgs_per_worker=15 if quick else 40)
        return {
            "kv_tracing_msgs_per_s": r["msgs_per_s"],
            "kv_tracing_assembled": r["assembled"],
            "kv_tracing_collected": r["collected"],
            "kv_tracing_wall_p50_us": r["trace_wall_p50_us"],
            "kv_tracing_wall_max_us": r["trace_wall_max_us"],
            "kv_tracing": {
                "top_stage": r["top_stage"],
                "stage_shares": r["stage_shares"],
            },
        }

    def sec_chunk_streaming():
        # Chunked streaming transfers (docs/chunking.md): 64 MiB
        # push goodput chunked vs monolithic, and the headline —
        # small-pull p99 under a concurrent 64 MiB background push.
        # Real 1w+1s tcp cluster, one process per node.
        from pslite_tpu.benchmark import chunk_streaming_bench

        cs = chunk_streaming_bench(quick=quick)
        return {f"chunk_{k}": v for k, v in cs.items()}

    def sec_native_goodput():
        # Native zero-copy data plane (docs/native_core.md): 64 MiB
        # push goodput with the C++ sender lanes (PS_NATIVE=1) vs the
        # pure-Python path (PS_NATIVE=0), same 1w+1s tcp harness —
        # plus the small-pull p99 on both legs (the priority
        # discipline must survive the GIL-free plane).
        from pslite_tpu.benchmark import native_goodput_bench

        ng = native_goodput_bench(quick=quick)
        return {f"native_{k}": v for k, v in ng.items()}

    def sec_quantized_push():
        # Quantized transport tier (docs/compression.md): effective
        # goodput (raw bytes/s) of the 64 MiB push storm, uncompressed
        # vs int8 / fp8_e4m3 / int8+EF, same 1w+1s tcp harness as
        # native_goodput, plus the priority small-pull p99 guard.
        from pslite_tpu.benchmark import quantized_push_bench

        qp = quantized_push_bench(quick=quick)
        return {f"quantized_{k}": v for k, v in qp.items()}

    def sec_multi_tenant():
        # Multi-tenant serving QoS (docs/qos.md): weighted-fair lanes
        # + admission + the worker hot-key cache.  Real tcp processes:
        # a bulk tenant at ~10x capacity vs the serving tenant's
        # small-pull p99 (acceptance <= 2x uncontended), and the DLRM
        # Zipf pull storm with the hot cache (acceptance >= 5x p50,
        # hit rate >= 60%), plus the loopback admission probe (sheds
        # fail fast with OPT_OVERLOAD, stores bit-exact).
        from pslite_tpu.benchmark import multi_tenant_bench

        mt = multi_tenant_bench(quick=quick)
        return {f"multi_tenant_{k}": v for k, v in mt.items()}

    def sec_small_op_batching():
        # Small-op aggregation plane (docs/batching.md): the ops/s
        # regime — 4 KiB ops over a real 1w+1s tcp cluster, combiner
        # on (EXT_BATCH multi-op frames + batched server apply) vs
        # PS_BATCH_BYTES=0, interleaved rounds.  Acceptance: >= 4x
        # msgs/s, low-load single-op p50 within 1.5x, stores
        # bit-exact on both legs.
        from pslite_tpu.benchmark import small_op_bench

        so = small_op_bench(quick=quick)
        return {f"small_op_batching_{k}": v for k, v in so.items()}

    def sec_serving_fanin():
        # Serving fan-in (docs/batching.md): multi-get + server-side
        # response aggregation — the DLRM Zipf fan-out storm (64
        # single-row lookups/request, 2 tcp servers, hot cache COLD),
        # aggregated (one EXT_BATCH frame per server each way) vs
        # PS_BATCH_BYTES=0, interleaved rounds.  Acceptance: >= 3x
        # requests/s, response frames/request ~= contacted servers,
        # low-load single-pull p50 within 1.5x, bit-exact both legs.
        from pslite_tpu.benchmark import serving_fanin_bench

        sf = serving_fanin_bench(quick=quick)
        return {f"serving_fanin_{k}": v for k, v in sf.items()}

    def sec_replica_read():
        # Replica read fan-out (docs/serving_reads.md): read-heavy
        # Zipf storm against one rank's range over real tcp, k=3
        # (pulls spread across the whole replica chain, push-stamp
        # validated) vs k=1 (primary funnel), interleaved rounds.
        # Acceptance: >= 2.5x reads/s, ZERO read-your-writes
        # violations, bit-exact spot checks — plus the live
        # namespace publish/flip/rollback under storm with zero
        # failed requests.
        from pslite_tpu.benchmark import replica_read_bench

        rr = replica_read_bench(quick=quick)
        return {f"replica_read_{k}": v for k, v in rr.items()}

    def sec_elastic_scale():
        # Elastic membership (docs/elasticity.md): scale 2 -> 4 -> 2
        # servers mid push-storm with no global restart — stores
        # bit-exact, zero hung waits (wrong-epoch slices re-route),
        # and the priority small-pull p99 bounded (<= 3x the
        # uncontended window) through the migration.
        from pslite_tpu.benchmark import elastic_scale_bench

        es = elastic_scale_bench(quick=quick)
        return {f"elastic_{k}": v for k, v in es.items()}

    def sec_durable_store():
        # Durable state tier (docs/durability.md): the beyond-RAM
        # tiered store — DLRM Zipf storm against a table ~4x
        # PS_STORE_RAM_MB over real tcp processes, hot-set p99 vs the
        # all-RAM twin (acceptance <= 2x, interleaved-round medians,
        # bit-exact every 64th pull) — plus the coordinated
        # snapshot + full-cluster-kill + PS_SNAPSHOT_RESTORE=1 boot
        # wall times, restored store verified bit-exact.
        from pslite_tpu.benchmark import durable_store_bench

        ds = durable_store_bench(quick=quick)
        return {f"durable_{k}": v for k, v in ds.items()}

    def sec_autopilot():
        # Self-driving cluster (docs/autopilot.md): a hot-set storm
        # skews two elastic servers ~2:1; the autopilot senses the
        # sustained rate skew through ClusterHistory and rebalances
        # the hot range itself.  Gates: load_skew_ratio (final-window
        # max/mean per-server rate, lower is better) and
        # operator_actions (must stay 0 — no human lever-pulling).
        from pslite_tpu.benchmark import autopilot_bench

        apb = autopilot_bench(quick=quick)
        return {f"autopilot_{k}": v for k, v in apb.items()}

    def sec_wire():
        # Wire-plane observatory (docs/observability.md): syscalls/op,
        # frames/op, combiner batch fill, lane residency p99, zc byte
        # share — the wire.* counter deltas of a bursty small-op tcp
        # storm with the combiner on.  Host-side only; the syscall and
        # frame ratios gate (lower is better), the rest is context.
        from pslite_tpu.benchmark import wire_observatory_storm

        wo = wire_observatory_storm(quick=quick)
        return {f"wire_{k}": v for k, v in wo.items()}

    def sec_fault_recovery():
        # Recovery path gets a tracked number like the perf paths:
        # server kill -> detector broadcast -> failover pull success
        # (loopback in-process cluster, PS_KV_REPLICATION=2,
        # deadlines on — docs/fault_tolerance.md).
        from pslite_tpu.benchmark import fault_recovery_times

        ft = fault_recovery_times(quick=quick)
        return {f"fault_recovery_{k}": v for k, v in ft.items()}

    def sec_van_latency():
        # The SOCKET vans' per-key latency — the reference's exact
        # reporting regime (test_benchmark.cc:393).  Runs a 1w+1s
        # cluster per van over localhost via the launcher; children
        # pin JAX_PLATFORMS=cpu, so it is tunnel-independent.
        import re

        out = {}
        for van in ("tcp", "shm"):
            cmd = [
                sys.executable, "-m", "pslite_tpu.tracker.local",
                "-n", "1", "-s", "1", "--van", van, "--",
                sys.executable, "-m", "pslite_tpu.benchmark",
                "--len", "65536",
                "--repeat", "4" if quick else "10",
                "--mode", "push_pull",
            ]
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       PALLAS_AXON_POOL_IPS="")
            r = subprocess.run(
                cmd, capture_output=True, text=True, timeout=600,
                cwd=os.path.dirname(os.path.abspath(__file__)),
                env=env,
            )
            lats = sorted(
                float(m) for m in re.findall(
                    r"avg latency ([0-9.]+) us/key", r.stdout)
            )
            gbps = [
                float(m) for m in re.findall(
                    r": ([0-9.]+) Gbps", r.stdout)
            ]
            if lats:
                out[f"van_{van}_us_per_key_p50"] = round(
                    lats[len(lats) // 2], 3)
                out[f"van_{van}_us_per_key_worst"] = round(lats[-1], 3)
            if gbps:
                out[f"van_{van}_gbps"] = round(max(gbps), 3)
        return out

    secs = [
        ("send_lanes", sec_send_lanes),
        ("server_apply", sec_server_apply),
        ("chunk_streaming", sec_chunk_streaming),
        ("native_goodput", sec_native_goodput),
        ("quantized_push", sec_quantized_push),
        ("multi_tenant", sec_multi_tenant),
        ("small_op_batching", sec_small_op_batching),
        ("serving_fanin", sec_serving_fanin),
        ("replica_read", sec_replica_read),
        ("elastic_scale", sec_elastic_scale),
        ("autopilot", sec_autopilot),
        ("durable_store", sec_durable_store),
        ("kv_telemetry", sec_kv_telemetry),
        ("kv_tracing", sec_kv_tracing),
        ("wire", sec_wire),
        ("fault_recovery", sec_fault_recovery),
    ]
    if not quick:
        secs.insert(0, ("van_latency", sec_van_latency))
    skip = {
        s.strip()
        for s in os.environ.get("PS_BENCH_SKIP", "").split(",")
        if s.strip()
    }
    if skip:
        # Marker key per section = the section's METRIC prefix (what a
        # section's own ``{"skipped": ...}`` return produces through
        # its field-prefixing), so bench_diff._section_skipped
        # recognizes it — a raw "<section>_skipped" would read as a
        # vanished metric for sections whose name != metric prefix.
        marker = {
            "chunk_streaming": "chunk_skipped",
            "native_goodput": "native_skipped",
            "quantized_push": "quantized_skipped",
            "kv_telemetry": "kv_skipped",
            "kv_tracing": "kv_tracing_skipped",
            "van_latency": "van_skipped",
            "elastic_scale": "elastic_skipped",
            "durable_store": "durable_skipped",
        }
        secs = [
            (name, fn) if name not in skip
            else (name, (lambda k=marker.get(name, f"{name}_skipped"):
                         {k: "PS_BENCH_SKIP"}))
            for name, fn in secs
        ]
    return secs


def _emit(obj: dict) -> None:
    """Print the ONE result line (idempotent: watchdog vs main race)."""
    global _emitted
    with _emit_mu:
        if _emitted:
            return
        _emitted = True
        print(json.dumps(obj), flush=True)


def _error_line(msg: str, extra: dict | None = None) -> dict:
    line = {
        "metric": "dense push-pull goodput (40x1MB, fused RS+update+AG)",
        "value": 0.0,
        "unit": "GB/s/chip",
        "vs_baseline": 0.0,
        "error": msg,
    }
    if extra:
        line.update(extra)
    return line


def main() -> None:
    quick = bool(int(os.environ.get("PS_BENCH_QUICK", "0")))
    partial_path = os.environ.get("PS_BENCH_PARTIAL") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_PARTIAL.json"
    )
    rec = _Recorder(partial_path)
    rec.merge(_error_line("run incomplete (in progress or killed)"))
    rec.merge({
        "git_sha": _git_sha(),
        "started_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
    })
    rec.flush()  # even a pre-probe kill leaves a stamped record

    probe = _probe_backend(attempts=1 if quick else 3,
                           timeout_s=60 if quick else 180)
    device_reason = probe.get("error")
    if device_reason is None:
        rec.merge({
            "platform": probe.get("platform"),
            "device_kind": probe.get("device_kind"),
            "n_devices": probe.get("n"),
        })
    rec.flush()

    if device_reason is not None:
        # Per-section degrade (VERDICT r04/r05: the tunnel being down
        # blinded the ENTIRE record): device sections record a skip
        # REASON, the host-side transport sections still run and emit
        # real numbers — the transport trajectory never goes dark.
        reason = f"backend unavailable: {device_reason}"
        names = (_DEVICE_SECTIONS_QUICK if quick
                 else _DEVICE_SECTIONS_FULL)
        for name in names:
            rec.merge({name: {"skipped": reason}})
        rec.merge({"device_sections_skipped": reason})
        rec.flush()
        for name, fn in _transport_sections(quick):
            rec.run(name, fn)
        rec.merge(_error_line(
            f"device sections skipped ({reason}); transport sections "
            f"measured", extra={"wall_unreliable": True}))
        rec.flush()
        _emit(rec.snapshot())
        return

    # The probe only covers its own subprocess; the tunnel can still flap
    # before the in-process backend init below, which would hang forever
    # (un-catchable).  A watchdog guarantees one parseable line — carrying
    # every section completed so far, not a bare error (VERDICT r04 #2).
    deadline = int(os.environ.get("PS_BENCH_TIMEOUT_S", "1500"))

    def _watchdog_fire():
        # A fire racing the main thread's final drop/flush/emit must
        # not taint the on-disk record with a timeout that didn't
        # happen: once the success (or error) line is out on stdout,
        # the watchdog stands down.  (main() also cancels the timer
        # BEFORE its final drop/flush/emit; this check covers a fire
        # already in flight when cancel ran.)
        with _emit_mu:
            if _emitted:
                return
        rec.merge({"error": (
            f"bench exceeded {deadline}s (backend hang after successful "
            f"probe — tunnel flapped mid-run?); partial results attached"
        )})
        rec.flush()
        _emit(rec.snapshot())
        os._exit(0)

    watchdog = threading.Timer(deadline, _watchdog_fire)
    watchdog.daemon = True
    watchdog.start()

    # Cross-section state consumed by the finalize step.
    st: dict = {}

    try:
        explicit = os.environ.get("JAX_PLATFORMS")
        if explicit:
            # Re-apply an explicit platform choice over the sitecustomize's
            # programmatic override (same counter-measure as the probe).
            import jax

            jax.config.update("jax_platforms", explicit)

        import jax.numpy as jnp
        import numpy as np

        from pslite_tpu.parallel.engine import CollectiveEngine

        def sec_engine_init():
            eng = CollectiveEngine()
            st["eng"] = eng
            # Which data plane produces these numbers (VERDICT r03 weak
            # #7).  The zero-copy flag reflects what the engine will
            # actually DO for the headline config — on a multi-shard
            # mesh in-place delivery silently degrades to copying.
            st["zc_headline"] = eng._zc_pull_eligible(jnp.float32, "sum")
            return {"impl": {
                "configured": eng.impl,
                "effective": eng._effective_impl(jnp.float32, "sum"),
                "zero_copy_pull": st["zc_headline"],
            }}

        if not rec.run("engine_init", sec_engine_init):
            rec.merge(_error_line("engine init failed — no measurements"))
            rec.flush()
            _emit(rec.snapshot())
            return
        eng = st["eng"]

        # Reference sweep 1KB..64MB per key (test.sh / README.md:123-135);
        # headline config: 40 keys x 1MB (test_benchmark.cc:407-414).
        # PS_BENCH_QUICK=1 shrinks everything (CI smoke on CPU).
        sizes = (1 << 10, 64 << 10) if quick else (
            1 << 10, 64 << 10, 1 << 20, 16 << 20, 64 << 20
        )

        def _size_label(size: int) -> str:
            return (f"{size >> 20}MB" if size >= 1 << 20
                    else f"{size >> 10}KB")

        def sec_per_op_sweep():
            # Per-op dispatch sweep (one push_pull per iteration, the
            # ZPush/ZPull analog), wall + device from the same loop.
            sweep_wall, sweep_dev = {}, {}
            for size in sizes:
                iters = 2 if quick else max(
                    4, min(30, (256 << 20) // max(size, 1 << 20))
                )
                w, d = _measure(eng, f"sweep_{size}", 1, size // 4,
                                iters, zero_copy=True)
                sweep_wall[_size_label(size)] = round(w, 2)
                if d is not None:
                    sweep_dev[_size_label(size)] = round(d, 2)
            return {"sweep_1key_wall": sweep_wall,
                    "sweep_1key_device": sweep_dev}

        def sec_replay_sweep():
            # Dispatch-amortized sweep: the same 1-key buckets through
            # ONE fused T-step replay program (lax.scan over the donated
            # store); T scaled so each program moves >=64MB of payload.
            rp_wall, rp_dev = {}, {}
            for size in sizes:
                steps = 4 if quick else max(8, min(256, (64 << 20) // size))
                w, d = _measure_replay(
                    eng, f"replay_{size}", 1, size // 4, steps
                )
                rp_wall[_size_label(size)] = round(w, 2)
                if d is not None:
                    rp_dev[_size_label(size)] = round(d, 2)
            return {"sweep_1key_replay_wall": rp_wall,
                    "sweep_1key_replay_device": rp_dev}

        rec.run("per_op_sweep", sec_per_op_sweep)
        rec.run("replay_sweep", sec_replay_sweep)

        def sec_headline_quick():
            st["headline_cfg"] = "4x64KB quick"
            w, d = _measure(eng, "bench", 4, (64 << 10) // 4, 2,
                            zero_copy=True)
            st["headline_wall"], st["headline_dev"] = w, d
            return {"wallclock_goodput": round(w, 2)}

        def sec_headline():
            st["headline_cfg"] = "40x1MB"
            iters = 30
            # Median of 3 traced runs, keyed on the DEVICE number (the
            # basis the median is meant to guard — wall medians would
            # let a straggler trace with a middling wall time through).
            runs = sorted(
                (_measure(eng, "bench", 40, (1 << 20) // 4, iters,
                          zero_copy=True)
                 for _ in range(3)),
                key=lambda wd: (wd[1] is None, wd[1] or 0.0, wd[0]),
            )
            # Median among the runs that HAVE a device number — a
            # single surviving device trace must win over wall-clock
            # fallback (flaky XPlane capture drops planes, not runs).
            dev_runs = [r for r in runs if r[1] is not None]
            if dev_runs:
                w, d = dev_runs[len(dev_runs) // 2]
            else:
                w, d = runs[1]
            st["headline_wall"], st["headline_dev"] = w, d
            return {"wallclock_goodput": round(w, 2)}

        def sec_copy_pull():
            # The copying pull path (zero_copy=False): XLA gives the
            # gathered output its own buffer — the contract for callers
            # who hold pulled results across steps.
            _, d = _measure(eng, "bench_copy", 40, (1 << 20) // 4, 30,
                            zero_copy=False)
            return {"headline_copy_pull_device": (
                round(d, 2) if d is not None else None)}

        def sec_host_origin():
            nk, vl, it = ((4, (64 << 10) // 4, 2) if quick
                          else (40, (1 << 20) // 4, 8))
            w, d = _measure(eng, "bench_host", nk, vl, it,
                            host_grads=True)
            return {
                "host_origin_goodput_wall": round(w, 2),
                "host_origin_goodput_device": (
                    round(d, 2) if d is not None else None),
            }

        def sec_dtype_variants():
            # Fused Pallas optimizer pass (sgd+momentum) between the
            # reduce-scatter and all-gather: the server aggregation hot
            # loop (kv_app.h:430-452) as one HBM pass.  bf16 buckets:
            # same element count as the headline, half the bytes — the
            # TPU-native dtype for gradient exchange.
            fused = _measure(
                eng, "bench_fused", 40, (1 << 20) // 4, 8,
                handle="sgd_momentum:0.01,0.9", zero_copy=True,
            )
            bf16 = _measure(
                eng, "bench_bf16", 40, (1 << 20) // 4, 8,
                dtype=jnp.bfloat16, zero_copy=True,
            )
            return {
                "fused_sgdm_goodput_wall": round(fused[0], 2),
                "fused_sgdm_goodput_device": (
                    round(fused[1], 2) if fused[1] is not None else None),
                "bf16_goodput_wall": round(bf16[0], 2),
                "bf16_goodput_device": (
                    round(bf16[1], 2) if bf16[1] is not None else None),
            }

        def sec_resnet():
            # Model-shaped workload: the ResNet-50 gradient trace
            # (~205 MB/step in ~35 size-bucketed tensors) as one grouped
            # dispatch per step — the BASELINE config-4 replay.  One
            # execution per workload, both clocks (_dual_measure).
            from pslite_tpu.models.resnet_trace import replay as rn50

            out = {}
            clocks = {}
            rn_bytes, rn_dt = rn50(eng, steps=5,
                                   measure=_dual_measure(clocks))
            out["resnet50_trace_wall"] = round(
                rn_bytes / (clocks["wall"] / 5) / 1e9, 2)
            if rn_dt:
                out["resnet50_trace_device"] = round(
                    rn_bytes / rn_dt / 1e9, 2)
            # Host-origin trace replay: gradients start as host numpy
            # every step; serial vs double-buffered staging.  Device
            # basis shows the collective cost alone (staging is
            # host-side); the wall pair carries the overlap comparison.
            clocks = {}
            hb, hd = rn50(eng, steps=3, host_origin=True, overlap=False,
                          measure=_dual_measure(clocks))
            out["resnet50_host_trace_wall"] = round(
                hb / (clocks["wall"] / 3) / 1e9, 2)
            if hd:
                out["resnet50_host_trace_device"] = round(hb / hd / 1e9, 2)
            hb2, hd2 = rn50(eng, steps=3, host_origin=True, overlap=True)
            out["resnet50_host_overlap_wall"] = round(hb2 / hd2 / 1e9, 2)
            return out

        def sec_embedding():
            # Sparse tier: the 1M-key zipf-skewed embedding push/pull —
            # the BASELINE config-5 replay (gather + scatter-add bound).
            from pslite_tpu.models.embedding import replay as emb

            se = st.setdefault("se", _sparse_engine(eng))
            clocks = {}
            emb_bytes, emb_dt = emb(se, steps=5,
                                    measure=_dual_measure(clocks))
            return {
                "embedding_1m_ms_per_step_wall": round(
                    clocks["wall"] / 5 * 1e3, 1),
                "embedding_1m_ms_per_step_device": (
                    round(emb_dt * 1e3, 2) if emb_dt else None),
            }

        def sec_coalesced():
            # Coalesced per-op path (VERDICT r03 #3): 32 concurrent
            # 64KB per-op push_pulls through the micro-batching
            # dispatcher — the async ZPush/Wait contract, ~1 grouped
            # dispatch per window instead of 32.
            import jax as _jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            kn, ksz = 32, (64 << 10) // 4
            co_names = [f"co_{i}" for i in range(kn)]
            for nm in co_names:
                eng.register_dense(nm, np.arange(1, dtype=np.uint64), ksz)
            co_in = _jax.device_put(
                jnp.ones((eng.num_shards, ksz), jnp.float32),
                NamedSharding(eng.mesh, P(eng.axis, None)),
            )
            co_iters = 8
            with eng.coalescer(window_us=2_000) as disp:
                # warm (compiles the 32-bucket grouped program)
                for t in [disp.push_pull(nm, co_in) for nm in co_names]:
                    t.result().block_until_ready()

                def run():
                    last = None
                    for _ in range(co_iters):
                        ts = [disp.push_pull(nm, co_in)
                              for nm in co_names]
                        last = [t.result() for t in ts][-1]
                    last.block_until_ready()

                co_busy, co_wall = _traced(run)
            co_moved = 2 * kn * ksz * 4 * co_iters
            return {
                "coalesced_64k_32b_wall": round(co_moved / co_wall / 1e9, 2),
                "coalesced_64k_32b_device": (
                    round(co_moved / co_busy / 1e9, 2) if co_busy else None),
            }

        def sec_stress():
            # The reference's stress patterns (test_benchmark_stress.cc:
            # 271-279: 30.72MB tensors), device basis (VERDICT r03 #8).
            from pslite_tpu.stress import run_pattern

            se = st.setdefault("se", _sparse_engine(eng))
            out = {}
            for pattern in ("dense", "gather", "scatter", "datascatter"):
                gbps = run_pattern(eng, se, pattern, 30_720_000, 8,
                                   measure=_device_busy)
                if gbps:
                    # Gbps -> GB/s to match every other field.
                    out[f"stress_{pattern}_device"] = round(gbps / 8.0, 2)
            return out

        def sec_latency():
            # Latency regime (VERDICT r04 weak #5): the reference
            # reports ns/key alongside goodput (test_benchmark.cc:393)
            # — bandwidth parity with unknown latency is half a claim.
            # Every sample is an individually-awaited round trip; wall
            # clock, so tunnel-distorted (wall_unreliable), with the
            # device occupancy mean as the tunnel-proof floor.
            import jax as _jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            out: dict = {}
            nk, vl = (4, (64 << 10) // 4) if quick else (40, (1 << 20) // 4)
            n = 5 if quick else 30
            out["latency_headline_cfg"] = (
                "4x64KB quick" if quick else "40x1MB")
            lats, dev_us = _latency_samples(eng, "lat_headline", nk, vl, n)
            p50, p99 = _pctls(lats)
            out["latency_headline_p50_us"] = round(p50, 1)
            out["latency_headline_p99_us"] = round(p99, 1)
            # The reference's exact metric: avg round latency / total
            # keys, in ns (test_benchmark.cc:393).
            out["latency_headline_ns_per_key"] = round(p50 * 1e3 / nk, 1)
            if dev_us is not None:
                out["latency_headline_device_us"] = round(dev_us, 1)
            if quick:
                return out
            # Small-op regime: 1 key x 64KB, where dispatch dominates.
            lats, dev_us = _latency_samples(
                eng, "lat_64kb", 1, (64 << 10) // 4, 50)
            p50, p99 = _pctls(lats)
            out["latency_64kb_p50_us"] = round(p50, 1)
            out["latency_64kb_p99_us"] = round(p99, 1)
            if dev_us is not None:
                out["latency_64kb_device_us"] = round(dev_us, 1)
            # Coalescer tax: the same 64KB op through the dispatcher —
            # the flush path (caller waits immediately) and the
            # idle-close path (fire-and-forget; includes the adaptive
            # window cost, the trade VERDICT r04 weak #5 wanted priced).
            ksz = (64 << 10) // 4
            np_keys = np.arange(1, dtype=np.uint64)
            eng.register_dense("lat_co", np_keys, ksz)
            co_in = _jax.device_put(
                jnp.ones((eng.num_shards, ksz), jnp.float32),
                NamedSharding(eng.mesh, P(eng.axis, None)),
            )
            with eng.coalescer() as disp:
                disp.push_pull("lat_co", co_in).result().block_until_ready()
                flush_l, idle_l = [], []
                for _ in range(50):
                    t0 = time.perf_counter()
                    disp.push_pull(
                        "lat_co", co_in).result().block_until_ready()
                    flush_l.append((time.perf_counter() - t0) * 1e6)
                for _ in range(50):
                    t0 = time.perf_counter()
                    tk = disp.push_pull("lat_co", co_in)
                    tk.wait(10.0)
                    tk.result().block_until_ready()
                    idle_l.append((time.perf_counter() - t0) * 1e6)
            p50, p99 = _pctls(flush_l)
            out["latency_coalesced_flush_p50_us"] = round(p50, 1)
            out["latency_coalesced_flush_p99_us"] = round(p99, 1)
            p50, p99 = _pctls(idle_l)
            out["latency_coalesced_idle_p50_us"] = round(p50, 1)
            out["latency_coalesced_idle_p99_us"] = round(p99, 1)
            # Batch completion: 32 concurrent 64KB ops -> ALL done.
            bnames = [f"lat_cob_{i}" for i in range(32)]
            for nm in bnames:
                eng.register_dense(nm, np_keys, ksz)
            with eng.coalescer(window_us=2_000) as disp:
                for t in [disp.push_pull(nm, co_in) for nm in bnames]:
                    t.result().block_until_ready()
                batch_l = []
                for _ in range(10):
                    t0 = time.perf_counter()
                    ts = [disp.push_pull(nm, co_in) for nm in bnames]
                    for t in ts:
                        t.result()
                    ts[-1].result().block_until_ready()
                    batch_l.append((time.perf_counter() - t0) * 1e6)
            p50, p99 = _pctls(batch_l)
            out["latency_coalesced_batch32_p50_us"] = round(p50, 1)
            out["latency_coalesced_batch32_p99_us"] = round(p99, 1)
            # Replay per-step latency: the scan program's amortized cost
            # per PS step (the dispatch-free regime's floor).
            steps = 64
            eng.register_dense("lat_replay", np_keys, (1 << 20) // 4)
            seq = np.ones((steps, (1 << 20) // 4), np.float32)
            eng.replay("lat_replay", seq, keep="last",
                       zero_copy=True).block_until_ready()

            def run():
                eng.replay("lat_replay", seq, keep="last",
                           zero_copy=True).block_until_ready()

            busy, wall = _traced(run)
            out["latency_replay_step_wall_us"] = round(wall / steps * 1e6, 1)
            if busy:
                out["latency_replay_step_device_us"] = round(
                    busy / steps * 1e6, 1)
            return out

        def sec_hbm_peak():
            wall, dev = _hbm_peak_measured()
            st["hbm_peak_wall"], st["hbm_peak_dev"] = wall, dev
            return {
                "hbm_peak_wall": round(wall, 1) if wall else None,
                "hbm_peak_device": round(dev, 1) if dev else None,
            }

        if quick:
            headline_ok = rec.run("headline", sec_headline_quick)
            rec.run("host_origin", sec_host_origin)
            rec.run("latency", sec_latency)
        else:
            headline_ok = rec.run("headline", sec_headline)
            rec.run("copy_pull", sec_copy_pull)
            rec.run("host_origin", sec_host_origin)
            rec.run("dtype_variants", sec_dtype_variants)
            rec.run("resnet", sec_resnet)
            rec.run("embedding", sec_embedding)
            rec.run("coalesced", sec_coalesced)
            rec.run("latency", sec_latency)
        # Host-side transport sections (shared with the device-down
        # path): always run, tunnel-independent.
        for name, fn in _transport_sections(quick):
            rec.run(name, fn)
        if not quick:
            rec.run("stress", sec_stress)
            rec.run("hbm_peak", sec_hbm_peak)

        _mark("finalize")
        single_chip = probe.get("n", 1) == 1 or eng.num_shards == 1
        hbm_spec = _hbm_estimate(probe.get("device_kind", ""))
        hbm_peak_wall = st.get("hbm_peak_wall")
        hbm_peak_dev = st.get("hbm_peak_dev")
        if not headline_ok:
            rec.merge(_error_line(
                "headline section failed — value is not a measurement"))
            rec.merge({"hbm_spec": hbm_spec})
            rec.flush()
            _emit(rec.snapshot())
            return
        headline_wall = st["headline_wall"]
        headline_dev = st["headline_dev"]
        # The HEADLINE is device-time goodput when a TPU trace is
        # available — the number wall clock cannot inflate.
        value = headline_dev if headline_dev is not None else headline_wall
        basis = "device-time" if headline_dev is not None else "wall-clock"
        # HBM traffic of the zero-copy fused 1-device step: read grads +
        # read store + write store (in place) = exactly 3 x payload per
        # iter; goodput GB/s = 2 x payload / s, so traffic = 1.5 x
        # goodput.  Utilizations compare the headline VALUE against the
        # public spec and against a triad peak measured on the SAME
        # basis (mixing clocks would compare two different regimes).
        hbm_peak = hbm_peak_dev if basis == "device-time" else hbm_peak_wall
        hbm_util = round(1.5 * value / hbm_spec, 3) if hbm_spec else None
        hbm_util_meas = (
            round(1.5 * value / hbm_peak, 3) if hbm_peak else None
        )
        # The suspect guard applies to whatever basis produced the
        # value: device-time utilizations > 1 would mean the trace is
        # wrong; wall-clock ones mean the tunnel elided work.
        timing_suspect = (
            basis == "wall-clock" and bool(hbm_peak_wall) and (
                (hbm_spec is not None and hbm_peak_wall > 1.5 * hbm_spec)
                or hbm_peak_wall > 3300.0
            )
        ) or (hbm_util is not None and hbm_util > 1.0) or (
            hbm_util_meas is not None and hbm_util_meas > 1.0
        )
        suspect_note = (
            "; TIMING SUSPECT: measurement exceeds physical device "
            "bandwidth — treat the number as an upper bound"
            if timing_suspect else ""
        )

        baseline = 70.0  # GB/s: 70% of a ~100 GB/s per-chip ICI budget
        rec.merge({
            "metric": (
                f"dense push-pull goodput ({st['headline_cfg']}, "
                f"fused RS+update+AG, "
                f"{'zero-copy' if st['zc_headline'] else 'copy'} pull, "
                f"{basis})"
            ),
            "value": round(value, 2),
            "unit": "GB/s/chip",
            "vs_baseline": round(value / baseline, 3),
            "timing_basis": basis,
            "wall_unreliable": True,
            "hbm_util_vs_spec": hbm_util,
            "hbm_util_vs_measured": hbm_util_meas,
            "hbm_peak_measured": round(hbm_peak, 1) if hbm_peak else None,
            "hbm_spec": hbm_spec,
            "timing_suspect": timing_suspect,
            "note": (
                "single-chip: collectives degenerate to HBM-local ops; "
                "vs_baseline is an ICI-budget ratio the 1-device path "
                "does not traverse — hbm_util_vs_* are the honest "
                "single-chip measures; *_wall fields are tunnel-"
                "distorted (see wall_unreliable); stress_* are GB/s"
                + suspect_note
            ) if single_chip else "multi-chip ICI path" + suspect_note,
        })
        # A completed run is not an errored run: drop the in-progress
        # error marker BEFORE the final flush so the on-disk record and
        # the stdout line agree ('"error" in record' means failure).
        # The watchdog is cancelled FIRST: a timer firing between the
        # drop and the emit would re-merge a timeout error onto disk
        # while stdout carries the success line.
        watchdog.cancel()
        rec.drop("error")
        rec.flush()
        _emit(rec.snapshot())
    except Exception as exc:  # noqa: BLE001 - one parseable line, always
        rec.merge(_error_line(f"{type(exc).__name__}: {exc}"))
        rec.flush()
        _emit(rec.snapshot())
    finally:
        watchdog.cancel()


if __name__ == "__main__":
    main()
