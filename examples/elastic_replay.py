"""Elastic recut + dispatch amortization on the collective data plane.

Single-process demo (the ici van's in-process control plane) showing the
round-3 tiers:

1. ``KVWorker.replay``   — T training steps fused into ONE device program
   (lax.scan over the donated store; the ns/key steady-state regime).
2. ``KVWorker.push_pull_stream`` — host-origin gradients staged on a
   background thread while the collectives run (transfer/compute overlap).
3. ``KVWorker.reshard``  — live elastic recut of the server fleet: the
   kv axis shrinks to half the devices mid-run, state (including fused
   optimizer slots) survives, training continues on the new fan-in.

Run (any machine; uses the local jax devices)::

    python examples/elastic_replay.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# 8 virtual devices when no accelerator is attached (must precede jax).
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import numpy as np

import pslite_tpu as ps
from pslite_tpu.environment import Environment
from pslite_tpu.message import Role


def main() -> None:
    env = {
        "DMLC_NUM_WORKER": "1",
        "DMLC_NUM_SERVER": "1",
        "DMLC_PS_ROOT_URI": "lo",
        "DMLC_PS_ROOT_PORT": "20700",
        "PS_VAN_TYPE": "ici",
        "PS_ICI_SERVER_HANDLE": "sgd_momentum:0.1,0.9",
    }
    import threading

    scheduler = ps.Postoffice(Role.SCHEDULER, env=Environment(env))
    server = ps.Postoffice(Role.SERVER, env=Environment(env))
    worker_po = ps.Postoffice(Role.WORKER, env=Environment(env))
    # Bootstrap concurrently: the scheduler's start blocks until every
    # node has registered.
    threads = [threading.Thread(target=po.start, args=(0,))
               for po in (scheduler, server, worker_po)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    kv = ps.KVWorker(0, 0, postoffice=worker_po)
    eng = kv.engine
    n = eng.num_shards
    print(f"mesh: {n} server shards (devices)")
    if n < 2:
        print(
            "NOTE: only 1 device visible (an accelerator backend or a "
            "preset XLA_FLAGS overrides the 8-virtual-device fallback) — "
            "the elastic recut below will be a no-op; run with "
            "JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_"
            "device_count=8 to see the fleet shrink."
        )

    keys = np.arange(8, dtype=np.uint64)
    val_len = 1024
    kv.register_dense("params", keys, val_len)
    total = 8 * val_len

    # --- 1. fused replay: 10 optimizer steps, one dispatch -------------
    rng = np.random.default_rng(0)
    seq = rng.normal(size=(10, total)).astype(np.float32) * 0.01
    pulled = np.asarray(kv.replay("params", seq))
    print(f"replay: 10 fused sgd+momentum steps -> params[0]="
          f"{pulled[-1][0]:+.5f}")

    # --- 2. streamed host-origin steps ---------------------------------
    batches = (rng.normal(size=(total,)).astype(np.float32) * 0.01
               for _ in range(5))
    last = None
    for out in kv.push_pull_stream("params", batches):
        last = out
    print(f"stream: 5 staged steps  -> params[0]={np.asarray(last)[0]:+.5f}")

    # --- 3. elastic recut: half the fleet ------------------------------
    import jax
    from jax.sharding import Mesh

    half = Mesh(np.array(jax.devices()[: max(1, n // 2)]), ("kv",))
    kv.reshard(half)
    print(f"reshard: {n} -> {eng.num_shards} shards (state preserved)")
    out = np.asarray(kv.replay("params", seq[:2], keep="last"))
    print(f"post-recut replay ok    -> params[0]={out[0]:+.5f}")

    # Finalize concurrently (the shutdown barrier spans every role).
    fin = [threading.Thread(target=po.finalize, args=(0,))
           for po in (worker_po, server, scheduler)]
    for t in fin:
        t.start()
    for t in fin:
        t.join()
    print("DONE")


if __name__ == "__main__":
    main()
