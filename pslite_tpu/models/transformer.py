"""PSFormer — flagship transformer LM, written TPU-first.

Pure-JAX (functional params pytree), bfloat16-friendly matmuls for the MXU,
ring attention over a sequence-parallel mesh axis for long context, and a
training step where the parameter server IS the optimizer loop:

    pull   = all_gather of the sharded flat parameter store
    push   = psum_scatter of the flat gradient (cross-worker aggregation)
    update = server handle applied to the local store shard

i.e. the BytePS gradient push/pull cycle (reference docs/overview.md:44-125)
as one jit-compiled SPMD program.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional


@dataclass(frozen=True)
class ModelConfig:
    vocab: int = 256
    dim: int = 128
    heads: int = 4
    layers: int = 2
    mlp_ratio: int = 4
    dtype: str = "float32"  # params dtype; matmuls cast to bfloat16 on TPU


def init_params(rng, cfg: ModelConfig):
    import jax
    import jax.numpy as jnp

    dt = jnp.dtype(cfg.dtype)
    keys = jax.random.split(rng, 2 + cfg.layers)
    D, H = cfg.dim, cfg.heads
    scale = D ** -0.5

    def dense(key, shape):
        return (jax.random.normal(key, shape) * scale).astype(dt)

    params = {
        "embed": dense(keys[0], (cfg.vocab, D)),
        "ln_f": jnp.ones((D,), dt),
        "layers": [],
    }
    for i in range(cfg.layers):
        k1, k2, k3, k4 = jax.random.split(keys[2 + i], 4)
        params["layers"].append(
            {
                "ln1": jnp.ones((D,), dt),
                "ln2": jnp.ones((D,), dt),
                "qkv": dense(k1, (D, 3 * D)),
                "proj": dense(k2, (D, D)),
                "mlp_in": dense(k3, (D, cfg.mlp_ratio * D)),
                "mlp_out": dense(k4, (cfg.mlp_ratio * D, D)),
            }
        )
    return params


def _rmsnorm(x, scale):
    import jax
    import jax.numpy as jnp

    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(var + 1e-6) * scale


def forward(
    params,
    tokens,
    cfg: ModelConfig,
    attn_fn: Optional[Callable] = None,
    pos_offset=0,
):
    """Token ids [B, T_local] -> logits [B, T_local, vocab].

    ``attn_fn(q, k, v)`` defaults to the single-device causal reference;
    under shard_map pass a ring_attention closure and the shard's global
    ``pos_offset``.
    """
    import jax
    import jax.numpy as jnp

    from ..parallel.ring_attention import reference_attention

    if attn_fn is None:
        attn_fn = lambda q, k, v: reference_attention(q, k, v, causal=True)

    D, H = cfg.dim, cfg.heads
    hd = D // H
    x = params["embed"][tokens]  # [B, T, D]
    B, T, _ = x.shape
    # Rotary-free learned-less sinusoidal positions (global under SP).
    pos = pos_offset + jnp.arange(T)
    freqs = jnp.exp(-jnp.arange(0, D, 2) / D * jnp.log(10000.0))
    ang = pos[:, None] * freqs[None, :]
    pe = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)
    x = x + pe[None].astype(x.dtype)

    compute_dt = jnp.bfloat16 if x.dtype != jnp.float64 else x.dtype

    for layer in params["layers"]:
        h = _rmsnorm(x, layer["ln1"])
        qkv = (h.astype(compute_dt) @ layer["qkv"].astype(compute_dt)).astype(
            x.dtype
        )
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, hd)
        k = k.reshape(B, T, H, hd)
        v = v.reshape(B, T, H, hd)
        o = attn_fn(q, k, v).reshape(B, T, D)
        x = x + (o.astype(compute_dt) @ layer["proj"].astype(compute_dt)
                 ).astype(x.dtype)
        h = _rmsnorm(x, layer["ln2"])
        h = (h.astype(compute_dt) @ layer["mlp_in"].astype(compute_dt))
        h = jax.nn.gelu(h.astype(x.dtype))
        x = x + (h.astype(compute_dt) @ layer["mlp_out"].astype(compute_dt)
                 ).astype(x.dtype)

    x = _rmsnorm(x, params["ln_f"])
    logits = (x.astype(compute_dt) @ params["embed"].T.astype(compute_dt)
              ).astype(jnp.float32)
    return logits


def loss_fn(params, inputs, targets, cfg: ModelConfig, attn_fn=None,
            pos_offset=0):
    """Mean next-token cross-entropy over the local block."""
    import jax
    import jax.numpy as jnp

    logits = forward(params, inputs, cfg, attn_fn=attn_fn,
                     pos_offset=pos_offset)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()
