"""Per-message event tracing (ENABLE_PROFILING), van byte counters."""

import numpy as np

from pslite_tpu import KVServer, KVServerDefaultHandle, KVWorker

from helpers import LoopbackCluster


def test_profiler_event_log_and_byte_counters(tmp_path):
    path = tmp_path / "trace.csv"
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1,
        env_extra={"ENABLE_PROFILING": "1", "PROFILE_PATH": str(path)},
    )
    cluster.start()
    servers = []
    try:
        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        worker = KVWorker(0, 0, postoffice=cluster.workers[0])
        keys = np.array([9], dtype=np.uint64)
        vals = np.ones(32, dtype=np.float32)
        worker.wait(worker.push(keys, vals))
        out = np.zeros_like(vals)
        worker.wait(worker.pull(keys, out))

        van = cluster.workers[0].van
        assert van.send_bytes > 0
        assert van.recv_bytes > 0
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()

    lines = path.read_text().strip().splitlines()
    # key,event_kind,timestamp_us — the reference's (key, event, µs) format.
    assert any(line.startswith("9,send_push,") for line in lines), lines
    assert any(line.startswith("9,recv_pull,") for line in lines), lines
    for line in lines:
        key, event, ts = line.split(",")
        assert event.split("_")[0] in ("send", "recv")
        assert int(ts) > 0
