"""Native zero-copy data plane (docs/native_core.md).

Frame-level parity: the frames the C++ sender lanes put on the wire
must be BYTE-IDENTICAL to ``wire.pack_frame`` over ``split_message``'s
chunks — that is what lets mixed native/non-native clusters
interoperate (ISSUE 6 acceptance).  Captured off a raw accepted socket
so nothing but the lane's own encoder touches the bytes.

Also: the mixed-cluster storm (native worker <-> PS_NATIVE=0 servers,
bit-exact vs all-Python), the ABI-stamp freshness assert, and the
stale-.so rejection guard (compiles a wrong-stamp library when a C++
toolchain is present; SKIPS otherwise).
"""

import copy
import os
import re
import shutil
import socket
import subprocess
import sys

import numpy as np
import pytest

from pslite_tpu import wire
from pslite_tpu.message import OPT_COMPRESS_INT8, Message
from pslite_tpu.sarray import SArray
from pslite_tpu.vans import native as native_mod
from pslite_tpu.vans.chunking import native_descriptor, split_message

from helpers import LoopbackCluster

_PEER = 77


def _require_native():
    if native_mod.load() is None:
        pytest.skip("native core unavailable (make native)")


def _msg(segs, push=True, option=0, trace=0, sender=9, recver=_PEER,
         timestamp=3):
    msg = Message()
    m = msg.meta
    m.sender, m.recver = sender, recver
    m.request = True
    m.push = push
    m.app_id = 0
    m.timestamp = timestamp
    m.option = option
    m.trace = trace
    for a in segs:
        msg.add_data(SArray(a))
    return msg


def _variants():
    """(name, message, chunk_bytes) — every encoder feature the parity
    contract covers: plain, empty-vals, int8 options, trace extension
    tails, the chunk extension (chunked transfer), and the EXT_CODEC
    tail of the quantized transport tier (docs/compression.md) —
    monolithic AND re-chunked, where EXT_CHUNK must stay the meta's
    trailing bytes with the codec ext intact ahead of it."""
    from pslite_tpu.message import CodecInfo
    from pslite_tpu.ops import codecs

    rng = np.random.default_rng(7)
    keys = np.arange(16, dtype=np.uint64)
    vals = rng.normal(size=16 * 256).astype(np.float32)
    big_vals = rng.normal(size=16 * 2048).astype(np.float32)
    codec = codecs.get_codec("int8")
    codes, scales, flags = codec.encode(big_vals)
    cmsg = _msg([keys, np.ascontiguousarray(codes), scales],
                trace=0x77AA)
    cmsg.meta.codec = CodecInfo(codec=codec.wire_id,
                                raw_len=big_vals.nbytes,
                                block=codec.block, flags=flags)
    cmsg2 = _msg([keys, np.ascontiguousarray(codes), scales])
    cmsg2.meta.codec = cmsg.meta.codec
    out = [
        ("plain_push", _msg([keys, vals]), 0),
        ("empty_vals", _msg([keys, np.empty(0, np.float32)]), 0),
        ("int8_options",
         _msg([keys, (rng.normal(size=512) * 10).astype(np.int8),
               rng.normal(size=16).astype(np.float32)],
              option=OPT_COMPRESS_INT8, trace=0xABCDEF), 0),
        ("traced_chunked", _msg([keys, vals], trace=0x1234), 4096),
        ("chunked_with_lens",
         _msg([keys, vals, np.full(16, 256, np.int32)]), 4096),
        ("codec_ext_mono", cmsg, 0),
        ("codec_ext_chunked", cmsg2, 8192),
    ]
    return out


def _python_wire_bytes(msg, chunk_bytes, xfer_id, sid_start):
    """What the pure-Python path puts on the wire for this message:
    split_message's chunks (or the monolithic frame), each pack_framed
    with the sid the (in-order) lane would stamp at dispatch."""
    chunks = (split_message(copy.deepcopy(msg), chunk_bytes, xfer_id)
              if chunk_bytes > 0 else None)
    if chunks is None:
        chunks = [copy.deepcopy(msg)]
    blob = bytearray()
    for i, c in enumerate(chunks):
        c.meta.sid = sid_start + i
        for part in wire.pack_frame(c):
            blob += bytes(part)
    return bytes(blob), len(chunks)


def _recv_exact(conn, n):
    buf = bytearray()
    conn.settimeout(10.0)
    while len(buf) < n:
        got = conn.recv(min(1 << 20, n - len(buf)))
        if not got:
            break
        buf += got
    return bytes(buf)


def test_native_frames_byte_identical_to_python():
    """Acceptance: for every encoder variant, the native sender lane's
    byte stream equals the Python encoder's exactly — including the
    chunk split boundaries, per-chunk sids, lens tables, and the
    trace/chunk extension tails."""
    _require_native()
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    nt = native_mod.NativeTransport()
    try:
        nt.connect(_PEER, "127.0.0.1", port)
        conn, _ = srv.accept()
        try:
            sid = 0
            for name, msg, chunk_bytes in _variants():
                xfer_id = 1000 + sid
                expected, n_chunks = _python_wire_bytes(
                    msg, chunk_bytes, xfer_id, sid)
                desc = native_descriptor(msg, chunk_bytes, iter([xfer_id]))
                assert desc.n_chunks == n_chunks, name
                assert desc.wire_bytes == len(expected), name
                nt.send_enqueue(_PEER, 0, desc.meta_buf, desc.arrs,
                                desc.chunk_bytes, desc.ext_off)
                assert nt.send_flush(10000)
                got = _recv_exact(conn, len(expected))
                assert got == expected, (
                    f"{name}: native frame bytes differ from pack_frame"
                )
                done = nt.send_reap(_PEER)
                assert [st for _, st in done] == [0]
                sid += n_chunks
        finally:
            conn.close()
    finally:
        nt.stop()
        nt.destroy()
        srv.close()


def test_native_descriptor_wire_bytes_accounting():
    """desc.wire_bytes must equal the summed pack_frame byte counts —
    it feeds van.send_bytes and the sent-bytes counters at reap."""
    for name, msg, chunk_bytes in _variants():
        expected, n_chunks = _python_wire_bytes(msg, chunk_bytes, 55, 0)
        desc = native_descriptor(msg, chunk_bytes, iter([55]))
        assert desc.wire_bytes == len(expected), name
        assert desc.n_chunks == n_chunks, name


# -- mixed-cluster interop ---------------------------------------------------


def _tcp_storm(env_extra=None, per_node_env=None, seed=42):
    """Deterministic mixed storm over a REAL in-process tcp cluster;
    returns the final pulled state (same shape as test_chunking's
    loopback _storm, but through the socket transports the native data
    plane actually drives)."""
    from pslite_tpu.kv.kv_app import KVServer, KVServerDefaultHandle, KVWorker

    base = {"PS_CHUNK_BYTES": "8192"}
    base.update(env_extra or {})
    cl = LoopbackCluster(num_workers=1, num_servers=2, van_type="tcp",
                         env_extra=base, per_node_env=per_node_env)
    cl.start()
    servers = []
    for po in cl.servers:
        s = KVServer(0, postoffice=po)
        s.set_request_handle(KVServerDefaultHandle())
        servers.append(s)
    w = KVWorker(0, 0, postoffice=cl.workers[0])
    span = (1 << 64) // 8
    big_keys = (np.arange(8, dtype=np.uint64) * span + 1).astype(np.uint64)
    small_keys = (np.arange(8, dtype=np.uint64) * span + 2).astype(np.uint64)
    rng = np.random.default_rng(seed)
    big = rng.normal(size=8 * 4096).astype(np.float32)
    small = rng.normal(size=8 * 16).astype(np.float32)
    for i in range(6):
        ts1 = w.push(big_keys, big)
        ts2 = w.push(small_keys, small, priority=1)
        w.wait(ts1)
        w.wait(ts2)
        if i % 2:
            w.wait(w.push(big_keys, big, compress="int8"))
    out_b = np.zeros_like(big)
    out_s = np.zeros_like(small)
    w.wait(w.pull(big_keys, out_b))
    w.wait(w.pull(small_keys, out_s))
    w.stop()
    for s in servers:
        s.stop()
    cl.finalize()
    return out_b, out_s


def test_mixed_cluster_storm_bit_exact():
    """Acceptance: a native worker pushing to PS_NATIVE=0 servers (and
    the scheduler) produces stores BIT-EXACT with an all-Python
    cluster — frames from either encoder decode identically."""
    _require_native()
    py_only = {k: {"PS_NATIVE": "0"}
               for k in ("scheduler", "server0", "server1")}
    mixed = _tcp_storm(per_node_env=py_only)
    allpy = _tcp_storm(env_extra={"PS_NATIVE": "0"})
    np.testing.assert_array_equal(mixed[0], allpy[0])
    np.testing.assert_array_equal(mixed[1], allpy[1])


def test_native_cluster_storm_bit_exact():
    """All-native cluster vs all-Python: same stores, both directions
    of every link exercising the native lanes + express recv."""
    _require_native()
    native = _tcp_storm()
    allpy = _tcp_storm(env_extra={"PS_NATIVE": "0"})
    np.testing.assert_array_equal(native[0], allpy[0])
    np.testing.assert_array_equal(native[1], allpy[1])


def test_native_reassembly_storm_bit_exact():
    """PS_NATIVE_REASSEMBLY=1 with 2 rails: chunk payloads direct-read
    into the core's SHARED transfer table (one transfer's stripes land
    on different per-stream receive pumps and scatter into one buffer)
    and each transfer reaches Python as ONE complete frame
    (finalize_native_transfer) — stores bit-exact vs all-Python,
    int8 + priority traffic included."""
    _require_native()
    reasm = _tcp_storm(env_extra={"PS_NATIVE_REASSEMBLY": "1",
                                  "PS_NATIVE_RAILS": "2"})
    allpy = _tcp_storm(env_extra={"PS_NATIVE": "0"})
    np.testing.assert_array_equal(reasm[0], allpy[0])
    np.testing.assert_array_equal(reasm[1], allpy[1])


# -- stale-.so guard (satellite: version-stamped library) --------------------


def test_abi_stamp_matches():
    """The checked-in/built .so must carry native.py's ABI_VERSION —
    load() would have rejected it otherwise, so reaching a loaded lib
    and re-reading the stamp asserts the build is fresh."""
    _require_native()
    lib = native_mod.load()
    assert lib.psl_abi_version() == native_mod.ABI_VERSION


def _cxx():
    return shutil.which(os.environ.get("CXX", "g++"))


def test_stale_so_rejected(tmp_path, monkeypatch):
    """A library whose compiled-in stamp mismatches ABI_VERSION must be
    rejected at load() (loudly, not per-symbol) so every van falls back
    to pure Python together.  SKIPS without a C++ toolchain."""
    cxx = _cxx()
    if cxx is None:
        pytest.skip("no C++ toolchain")
    src = os.path.join(os.path.dirname(native_mod.__file__),
                       "..", "..", "cpp", "pslite_core.cc")
    text = open(src).read()
    stale_text, n = re.subn(r"kAbiVersion = \d+", "kAbiVersion = 9999",
                            text, count=1)
    assert n == 1
    stale_src = tmp_path / "stale_core.cc"
    stale_src.write_text(stale_text)
    stale_so = tmp_path / "libstale_core.so"
    try:
        subprocess.run(
            [cxx, "-O0", "-std=c++17", "-fPIC", "-shared", "-pthread",
             "-o", str(stale_so), str(stale_src)],
            check=True, capture_output=True, timeout=300,
        )
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired):
        pytest.skip("toolchain cannot build the core here")
    # load() in a SUBPROCESS: dlopen caching and the module-level _lib
    # cache in this process must not see the stale candidate.
    code = (
        "from pslite_tpu.vans import native\n"
        f"native._LIB_PATHS = [{str(stale_so)!r}]\n"
        "assert native.load() is None, 'stale .so was accepted'\n"
        "print('REJECTED')\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120,
        env={**os.environ,
             "PYTHONPATH": os.pathsep.join(sys.path)},
    )
    assert r.returncode == 0, r.stderr
    assert "REJECTED" in r.stdout
    assert "ABI stamp 9999" in (r.stderr + r.stdout)
