"""End-to-end KV app tests over the in-process loopback cluster.

Restores the functional tier the reference fork dropped (its Travis config
references test_kv_app/test_simple_app binaries that no longer exist —
SURVEY §4): bootstrap + rank assignment, push/pull with server aggregation,
multi-worker aggregation, variable-length values, and SimpleApp.
"""

import numpy as np
import pytest

from pslite_tpu import (
    KVPairs,
    KVServer,
    KVServerDefaultHandle,
    KVWorker,
    SimpleApp,
)
from pslite_tpu.base import (
    SCHEDULER_ID,
    server_rank_to_id,
    worker_rank_to_id,
)

from helpers import LoopbackCluster


def test_bootstrap_assigns_ranks():
    cluster = LoopbackCluster(num_workers=2, num_servers=2)
    cluster.start()
    try:
        worker_ids = sorted(po.van.my_node.id for po in cluster.workers)
        server_ids = sorted(po.van.my_node.id for po in cluster.servers)
        assert worker_ids == [worker_rank_to_id(0), worker_rank_to_id(1)]
        assert server_ids == [server_rank_to_id(0), server_rank_to_id(1)]
        assert cluster.scheduler.van.my_node.id == SCHEDULER_ID
        ranges = cluster.workers[0].get_server_key_ranges()
        assert len(ranges) == 2
        assert ranges[0].end == ranges[1].begin
    finally:
        cluster.finalize()


def test_push_pull_single_worker():
    cluster = LoopbackCluster(num_workers=1, num_servers=2)
    cluster.start()
    servers = []
    try:
        for po in cluster.servers:
            srv = KVServer(0, postoffice=po)
            srv.set_request_handle(KVServerDefaultHandle())
            servers.append(srv)
        worker = KVWorker(0, 0, postoffice=cluster.workers[0])

        num_keys, k = 8, 16
        # Spread keys across both server ranges.
        ranges = cluster.workers[0].get_server_key_ranges()
        keys = np.array(
            [ranges[i % 2].begin + i for i in range(num_keys)], dtype=np.uint64
        )
        keys.sort()
        vals = np.random.default_rng(0).normal(size=num_keys * k).astype(np.float32)

        ts = worker.push(keys, vals)
        worker.wait(ts)
        out = np.zeros_like(vals)
        ts = worker.pull(keys, out)
        worker.wait(ts)
        np.testing.assert_allclose(out, vals, rtol=1e-6)

        # Second push accumulates server-side.
        worker.wait(worker.push(keys, vals))
        out2 = np.zeros_like(vals)
        worker.wait(worker.pull(keys, out2))
        np.testing.assert_allclose(out2, 2 * vals, rtol=1e-6)
    finally:
        for srv in servers:
            srv.stop()
        cluster.finalize()


def test_multi_worker_aggregation():
    cluster = LoopbackCluster(num_workers=2, num_servers=1)
    cluster.start()
    servers = []
    try:
        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        w0 = KVWorker(0, 0, postoffice=cluster.workers[0])
        w1 = KVWorker(0, 0, postoffice=cluster.workers[1])

        keys = np.array([10, 20, 30], dtype=np.uint64)
        v0 = np.ones(3 * 4, dtype=np.float32)
        v1 = 2 * np.ones(3 * 4, dtype=np.float32)
        w0.wait(w0.push(keys, v0))
        w1.wait(w1.push(keys, v1))

        out = np.zeros_like(v0)
        w0.wait(w0.pull(keys, out))
        np.testing.assert_allclose(out, 3 * np.ones_like(v0))
    finally:
        for srv in servers:
            srv.stop()
        cluster.finalize()


def test_push_pull_fused():
    cluster = LoopbackCluster(num_workers=1, num_servers=2)
    cluster.start()
    servers = []
    try:
        for po in cluster.servers:
            srv = KVServer(0, postoffice=po)
            srv.set_request_handle(KVServerDefaultHandle())
            servers.append(srv)
        worker = KVWorker(0, 0, postoffice=cluster.workers[0])
        ranges = cluster.workers[0].get_server_key_ranges()
        keys = np.array([ranges[0].begin, ranges[1].begin + 5], dtype=np.uint64)
        vals = np.arange(8, dtype=np.float32)
        out = np.zeros_like(vals)
        worker.wait(worker.push_pull(keys, vals, out))
        np.testing.assert_allclose(out, vals)
    finally:
        for srv in servers:
            srv.stop()
        cluster.finalize()


def test_variable_length_values():
    cluster = LoopbackCluster(num_workers=1, num_servers=2)
    cluster.start()
    servers = []
    try:
        class VarHandle:
            def __init__(self):
                self.store = {}

            def __call__(self, meta, data, server):
                if meta.push:
                    off = 0
                    for key, ln in zip(data.keys, data.lens):
                        seg = data.vals[off : off + int(ln)]
                        self.store[int(key)] = seg.copy()
                        off += int(ln)
                    server.response(meta)
                else:
                    vals = [self.store[int(k)] for k in data.keys]
                    lens = np.array([len(v) for v in vals], dtype=np.int32)
                    server.response(
                        meta,
                        KVPairs(
                            keys=data.keys,
                            vals=np.concatenate(vals),
                            lens=lens,
                        ),
                    )

        for po in cluster.servers:
            srv = KVServer(0, postoffice=po)
            srv.set_request_handle(VarHandle())
            servers.append(srv)
        worker = KVWorker(0, 0, postoffice=cluster.workers[0])
        ranges = cluster.workers[0].get_server_key_ranges()
        keys = np.array(
            [ranges[0].begin, ranges[1].begin + 1], dtype=np.uint64
        )
        lens = np.array([3, 5], dtype=np.int32)
        vals = np.arange(8, dtype=np.float32)
        worker.wait(worker.push(keys, vals, lens=lens))
        out = np.zeros_like(vals)
        out_lens = np.zeros(2, dtype=np.int32)
        worker.wait(worker.pull(keys, out, lens=out_lens))
        np.testing.assert_allclose(out, vals)
        np.testing.assert_array_equal(out_lens, lens)
    finally:
        for srv in servers:
            srv.stop()
        cluster.finalize()


def test_early_push_buffered_until_server_app_ready():
    """A push that lands before the server app registers must neither block
    the receive loop nor be dropped — it is parked and flushed on
    registration (the reference instead stalls its recv loop up to 5s,
    van.cc:435-438, which inverts priority with barrier responses)."""
    import time

    cluster = LoopbackCluster(num_workers=1, num_servers=1)
    cluster.start()
    servers = []
    try:
        worker = KVWorker(0, 0, postoffice=cluster.workers[0])
        keys = np.array([3], dtype=np.uint64)
        vals = np.ones(16, dtype=np.float32)
        ts = worker.push(keys, vals)  # server app does not exist yet
        time.sleep(0.3)
        # Control traffic must still flow while the push is parked.
        cluster.barrier_all()

        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        worker.wait(ts)  # flushed on registration, then answered
        out = np.zeros_like(vals)
        worker.wait(worker.pull(keys, out))
        np.testing.assert_allclose(out, vals)
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_simple_app():
    cluster = LoopbackCluster(num_workers=1, num_servers=1)
    cluster.start()
    apps = []
    try:
        received = []

        def handle(req, app):
            received.append((req.head, bytes(req.body)))
            app.response(req, b"pong")

        server_app = SimpleApp(5, postoffice=cluster.servers[0])
        server_app.set_request_handle(handle)
        apps.append(server_app)

        replies = []
        worker_app = SimpleApp(5, postoffice=cluster.workers[0])
        worker_app.set_response_handle(
            lambda res, app: replies.append(bytes(res.body))
        )
        apps.append(worker_app)

        ts = worker_app.request(42, b"ping", server_rank_to_id(0))
        worker_app.wait(ts)
        assert received == [(42, b"ping")]
        assert replies == [b"pong"]
    finally:
        for app in apps:
            app.stop()
        cluster.finalize()


def test_compressed_push():
    """int8 gradient compression on the message path: values land within
    quantization error, wire bytes shrink ~4x."""
    cluster = LoopbackCluster(num_workers=1, num_servers=1)
    cluster.start()
    servers = []
    try:
        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        worker = KVWorker(0, 0, postoffice=cluster.workers[0])
        keys = np.array([5], dtype=np.uint64)
        n = 64 * 1024
        vals = np.random.default_rng(0).normal(size=n).astype(np.float32)

        before = cluster.workers[0].van.send_bytes
        worker.wait(worker.push(keys, vals, compress="int8"))
        wire_bytes = cluster.workers[0].van.send_bytes - before
        assert wire_bytes < vals.nbytes / 3  # ~4x smaller + overhead

        out = np.zeros_like(vals)
        worker.wait(worker.pull(keys, out))
        step = np.abs(vals).reshape(-1, 128).max(axis=1) / 127.0
        tol = np.repeat(step, 128) * 0.51 + 1e-6
        assert np.all(np.abs(out - vals) <= tol)
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_compressed_pull():
    """int8 compression on pull responses (the pull-side mirror of
    compressed push): the server quantizes its response slice, wire bytes
    shrink ~4x, values land within quantization error."""
    cluster = LoopbackCluster(num_workers=1, num_servers=2)
    cluster.start()
    servers = []
    try:
        for po in cluster.servers:
            srv = KVServer(0, postoffice=po)
            srv.set_request_handle(KVServerDefaultHandle())
            servers.append(srv)
        worker = KVWorker(0, 0, postoffice=cluster.workers[0])
        ranges = cluster.workers[0].get_server_key_ranges()
        keys = np.array(
            sorted(r.begin + 2 for r in ranges), dtype=np.uint64
        )
        n = len(keys) * 32 * 1024
        vals = np.random.default_rng(1).normal(size=n).astype(np.float32)
        worker.wait(worker.push(keys, vals))

        before = sum(po.van.send_bytes for po in cluster.servers)
        out = np.zeros_like(vals)
        worker.wait(worker.pull(keys, out, compress="int8"))
        wire_bytes = sum(
            po.van.send_bytes for po in cluster.servers
        ) - before
        assert wire_bytes < vals.nbytes / 3  # ~4x smaller + overhead

        step = np.abs(vals).reshape(-1, 128).max(axis=1) / 127.0
        tol = np.repeat(step, 128) * 0.51 + 1e-6
        assert np.all(np.abs(out - vals) <= tol)

        # Plain pull still returns exact values.
        exact = np.zeros_like(vals)
        worker.wait(worker.pull(keys, exact))
        np.testing.assert_allclose(exact, vals)
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_compressed_pull_variable_length_quantizes_per_key():
    """Ragged (lens) responses now ride the codec tier too — per-key
    blockwise scaling (docs/compression.md), where the old one-off int8
    path declined and fell back to raw float32.  The response must land
    within quantization error and the worker must receive the lens."""
    from pslite_tpu.kv.kv_app import KVPairs

    cluster = LoopbackCluster(num_workers=1, num_servers=1)
    cluster.start()
    servers = []
    try:
        vals = np.arange(256, dtype=np.float32)

        def handle(req_meta, req_data, server):
            if req_meta.pull:
                server.response(req_meta, KVPairs(
                    keys=req_data.keys,
                    vals=vals,
                    lens=np.array([256], dtype=np.int32),
                ))
            else:
                server.response(req_meta)

        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(handle)
        servers.append(srv)
        worker = KVWorker(0, 0, postoffice=cluster.workers[0])
        keys = np.array([3], dtype=np.uint64)
        out = np.zeros_like(vals)
        worker.wait(worker.pull(keys, out, compress="int8"))
        # Quantized: within half a step of the per-key 128-elem blocks.
        step = np.repeat(
            np.abs(vals).reshape(-1, 128).max(axis=1) / 127.0, 128
        )
        assert np.all(np.abs(out - vals) <= step * 0.51 + 1e-6)
        assert not np.array_equal(out, vals)  # it really was quantized
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_registered_recv_buffer_identity():
    """The reference benchmark proves zero-copy delivery by checking pushes
    land in the pre-registered buffer (test_benchmark.cc:169-181); the
    app-level contract here: the handler's vals alias the registered
    buffer's memory."""
    cluster = LoopbackCluster(num_workers=1, num_servers=1)
    cluster.start()
    servers = []
    try:
        seen = {}

        def handle(meta, data, server):
            if meta.push:
                seen["vals"] = data.vals
            server.response(meta)

        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(handle)
        servers.append(srv)

        worker = KVWorker(0, 0, postoffice=cluster.workers[0])
        worker_id = cluster.workers[0].van.my_node.id
        registered = np.zeros(64, dtype=np.float32)
        srv.register_recv_buffer(worker_id, 7, registered)

        vals = np.arange(64, dtype=np.float32)
        worker.wait(worker.push(np.array([7], np.uint64), vals))
        assert "vals" in seen
        assert np.shares_memory(seen["vals"], registered)
        np.testing.assert_allclose(registered, vals)
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_registered_recv_buffer_transport_delivery_shm():
    """On the shm van the TRANSPORT delivers pushes into the registered
    buffer (register_recv_buffer hook) — not the kv_app copy fallback:
    KVServer.delivered_in_place counts the hook firing."""
    cluster = LoopbackCluster(num_workers=1, num_servers=1,
                              van_type="shm",
                              env_extra={"PS_SHM_MIN_BYTES": "1"})
    cluster.start()
    servers = []
    try:
        seen = {}

        def handle(meta, data, server):
            if meta.push:
                seen["vals"] = data.vals
            server.response(meta)

        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(handle)
        servers.append(srv)

        worker = KVWorker(0, 0, postoffice=cluster.workers[0])
        worker_id = cluster.workers[0].van.my_node.id
        registered = np.zeros(4096, dtype=np.float32)
        srv.register_recv_buffer(worker_id, 7, registered)

        vals = np.arange(4096, dtype=np.float32)
        worker.wait(worker.push(np.array([7], np.uint64), vals))
        assert "vals" in seen
        assert np.shares_memory(seen["vals"], registered)
        np.testing.assert_allclose(registered, vals)
        assert srv.delivered_in_place == 1, srv.delivered_in_place
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_server_optimizer_handle_async_sgd():
    """Async-PS: two workers push gradients with NO inter-worker barrier;
    the server owns the optimizer (KVServerOptimizerHandle) and applies
    each push on arrival.  Plain SGD is order-independent, so the final
    params equal -lr * sum(all grads)."""
    from pslite_tpu import KVServerOptimizerHandle

    cluster = LoopbackCluster(num_workers=2, num_servers=1)
    cluster.start()
    servers = []
    try:
        handle = KVServerOptimizerHandle(kind="sgd", lr=0.1)
        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(handle)
        servers.append(srv)
        workers = [KVWorker(0, 0, postoffice=po) for po in cluster.workers]

        keys = np.array([3, 9], np.uint64)
        rng = np.random.default_rng(0)
        grads = [rng.normal(size=8).astype(np.float32) for _ in range(6)]
        ts = []
        for i, g in enumerate(grads):  # interleaved, unsynchronized
            ts.append((workers[i % 2], workers[i % 2].push(keys, g)))
        for w, t in ts:
            w.wait(t)
        out = np.zeros(8, np.float32)
        workers[0].wait(workers[0].pull(keys, out))
        expected = -0.1 * np.sum(grads, axis=0)
        np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-6)
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_server_optimizer_handle_momentum_adam():
    """Stateful kinds match a host reference loop (single worker, so
    application order is deterministic)."""
    from pslite_tpu import KVServerOptimizerHandle

    for kind in ("sgd_momentum", "adam"):
        cluster = LoopbackCluster(num_workers=1, num_servers=1)
        cluster.start()
        servers = []
        try:
            handle = KVServerOptimizerHandle(kind=kind, lr=0.05)
            handle.init(1, np.ones(4, np.float32))
            srv = KVServer(0, postoffice=cluster.servers[0])
            srv.set_request_handle(handle)
            servers.append(srv)
            w = KVWorker(0, 0, postoffice=cluster.workers[0])

            rng = np.random.default_rng(7)
            grads = [rng.normal(size=4).astype(np.float32)
                     for _ in range(5)]
            for g in grads:
                w.wait(w.push(np.array([1], np.uint64), g))
            out = np.zeros(4, np.float32)
            w.wait(w.pull(np.array([1], np.uint64), out))

            # Host reference.
            p = np.ones(4, np.float32)
            if kind == "sgd_momentum":
                m = np.zeros(4)
                for g in grads:
                    m = 0.9 * m + g
                    p = p - 0.05 * m
            else:
                m = np.zeros(4)
                v = np.zeros(4)
                for t, g in enumerate(grads, 1):
                    m = 0.9 * m + 0.1 * g
                    v = 0.999 * v + 0.001 * g * g
                    p = p - 0.05 * (m / (1 - 0.9 ** t)) / (
                        np.sqrt(v / (1 - 0.999 ** t)) + 1e-8
                    )
            np.testing.assert_allclose(out, p, rtol=1e-5, atol=1e-6)
        finally:
            for s in servers:
                s.stop()
            cluster.finalize()
