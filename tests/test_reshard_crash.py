"""Crash consistency of the elastic reshard (VERDICT r03 missing #5).

Semantics under test (documented in vans/ici_van.py reshard_engines):
- a peer dying BEFORE the entry barrier: survivors time out and abort
  with engines untouched (live 2-process kill test);
- a failure DURING the recut (a mid-collective peer death surfaces as
  an exception through jax's collective timeout — injected here
  deterministically at the placement layer): the staged commit aborts
  with the engine fully on the old mesh, stores never torn;
- a peer dying AFTER the recut, before the resume barrier: survivors
  hold committed, consistent new-mesh state and the op raises a
  degraded-cluster error.

Reference analog: recovery tolerates death at any moment
(/root/reference/src/van.cc:266-332); on the collective data plane the
roster is the mesh, so the same tolerance applies to mesh recuts.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from pslite_tpu.parallel import CollectiveEngine, default_mesh
from pslite_tpu.parallel.mesh import make_mesh
from pslite_tpu.parallel.sparse import SparseEngine
from pslite_tpu.utils.logging import CheckError


def _failing_placement(monkeypatch, fail_on_call: int):
    """Patch placement to raise on its Nth call (reshard resolves
    place_host_array from the module at call time)."""
    from pslite_tpu.parallel import placement

    real = placement.place_host_array
    calls = {"n": 0}

    def flaky(*a, **kw):
        calls["n"] += 1
        if calls["n"] == fail_on_call:
            raise RuntimeError("injected recut failure (dead peer)")
        return real(*a, **kw)

    monkeypatch.setattr(placement, "place_host_array", flaky)
    return calls


def test_engine_recut_failure_is_atomic(monkeypatch):
    """A failure midway through the recut (bucket 2 of 2, with opt
    state) leaves EVERY bucket on the old mesh — then a clean retry
    succeeds (abort-and-redo)."""
    mesh8 = default_mesh()
    eng = CollectiveEngine(mesh=mesh8, server_handle="adam:0.01")
    keys = np.arange(2, dtype=np.uint64)
    for name in ("a", "b"):
        eng.register_dense(name, keys, 64)
        eng.push_pull(name, np.ones((8, 128), np.float32))
    before = {n: np.asarray(eng.pull(n)) for n in ("a", "b")}
    old_padded = {n: eng.bucket(n).padded_len for n in ("a", "b")}

    mesh4 = make_mesh((4,), ("kv",))
    calls = _failing_placement(monkeypatch, fail_on_call=3)
    with pytest.raises(RuntimeError, match="injected"):
        eng.reshard(mesh4)
    assert calls["n"] >= 3
    # Fully on the old mesh: no field or bucket may have moved.
    assert eng.mesh is mesh8
    assert eng.num_shards == 8
    for n in ("a", "b"):
        assert eng.bucket(n).padded_len == old_padded[n]
        np.testing.assert_allclose(np.asarray(eng.pull(n)), before[n])
        # Optimizer state still live: another step runs.
        eng.push_pull(n, np.ones((8, 128), np.float32))

    # Retry without the fault: the redo completes.
    monkeypatch.undo()
    eng.reshard(mesh4)
    assert eng.num_shards == 4


def test_sparse_recut_failure_is_atomic(monkeypatch):
    """Same staged-commit contract for the sparse tier (tables + fused
    optimizer accumulators)."""
    mesh8 = default_mesh()
    se = SparseEngine(mesh8)
    se.register_sparse("t1", 64, 4)
    se.register_sparse("t2", 32, 4)
    idx = np.tile(np.arange(8, dtype=np.int32)[:, None], (1, 2))
    g = np.ones((8, 2, 4), np.float32)
    se.push("t1", idx, g, handle="row_adagrad:0.1,1e-8")
    se.push("t2", idx, g)
    se.block("t1")
    se.block("t2")
    before1 = np.asarray(se.pull("t1", idx))
    old_shards = se.num_shards

    calls = _failing_placement(monkeypatch, fail_on_call=2)
    with pytest.raises(RuntimeError, match="injected"):
        se.reshard(make_mesh((4,), ("kv",)))
    assert calls["n"] >= 2
    assert se.num_shards == old_shards
    np.testing.assert_allclose(np.asarray(se.pull("t1", idx)), before1)

    monkeypatch.undo()
    se.reshard(make_mesh((4,), ("kv",)))
    assert se.num_shards == 4
    np.testing.assert_allclose(
        np.asarray(se.pull("t1", idx[:4])), before1[:4]
    )


def _barrier_death_cluster(dying_call: int, expect_match: str,
                           expect_new_mesh: bool):
    """Drive KVWorker.reshard with the Nth barrier raising a timeout
    (barrier order: 1=entry, 2=commit, 3=resume)."""
    from tests.helpers import LoopbackCluster

    from pslite_tpu import KVServer, KVServerDefaultHandle, KVWorker

    c = LoopbackCluster(num_workers=1, num_servers=1, van_type="ici_shm")
    c.start()
    servers = []
    try:
        srv = KVServer(0, postoffice=c.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        worker = KVWorker(0, 0, postoffice=c.workers[0])
        eng = worker.engine
        keys = np.arange(2, dtype=np.uint64)
        worker.register_dense("g", keys, 16)
        W = eng.num_shards
        outs = np.zeros(32, np.float32)
        worker.wait(worker.push_pull(keys, np.ones(32, np.float32), outs))

        po = c.workers[0]
        real_barrier = po.barrier
        state = {"n": 0}

        def dying_barrier(*a, **kw):
            state["n"] += 1
            if state["n"] == dying_call:
                raise CheckError("barrier timed out (injected death)")
            return real_barrier(*a, **kw)

        po.barrier = dying_barrier
        new_mesh = make_mesh((W // 2,), ("kv",))
        with pytest.raises(CheckError, match=expect_match):
            worker.reshard(new_mesh)
        po.barrier = real_barrier
        assert eng.num_shards == (W // 2 if expect_new_mesh else W)
        # Stores carried either way.
        out2 = np.zeros(32, np.float32)
        worker.wait(worker.pull(keys, out2))
        np.testing.assert_allclose(out2, outs)
    finally:
        for s in servers:
            s.stop()
        c.finalize()


def test_commit_barrier_death_aborts_together_on_old_mesh():
    """A peer that fails STAGING never joins the commit barrier: the
    survivors' commit-barrier timeout aborts their staged state, so the
    whole cluster stays on the old mesh together."""
    _barrier_death_cluster(2, "aborted together", expect_new_mesh=False)


def test_resume_barrier_death_reports_degraded_committed_state():
    """A peer dying between the commit and the resume barrier: this
    process's recut has COMMITTED (new mesh, consistent stores) and the
    op raises the degraded-cluster error."""
    _barrier_death_cluster(3, "degraded", expect_new_mesh=True)


def _live_crash_cluster(mode: str, rank1_rc: int, timeout0: int):
    """Drive the 2-process crash child in ``mode``; returns
    (worker0_out, worker1_out, rank1_returncode)."""
    from pslite_tpu.utils.network import get_available_port

    port = get_available_port()
    child = os.path.join(os.path.dirname(__file__),
                         "reshard_crash_child.py")
    base_env = dict(
        os.environ,
        DMLC_NUM_WORKER="2",
        DMLC_NUM_SERVER="1",
        DMLC_PS_ROOT_URI="127.0.0.1",
        DMLC_PS_ROOT_PORT=str(port),
        DMLC_NODE_HOST="127.0.0.1",
        PS_VAN_TYPE="ici_tcp",
        PS_ICI_MULTIHOST="1",
        PS_RESHARD_TMO_S="10",
        PS_CRASH_MODE=mode,
    )
    for var in ("JAX_PLATFORMS", "XLA_FLAGS"):
        base_env.pop(var, None)
    roles = [("scheduler", None), ("server", None), ("worker", 0),
             ("worker", 1)]
    procs = []
    for role, rank in roles:
        env = dict(base_env, DMLC_ROLE=role)
        if rank is not None:
            env["DMLC_RANK"] = str(rank)
        procs.append(
            subprocess.Popen(
                [sys.executable, child],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )
        )
    # Worker 0 (procs[2]) carries the assertion; scheduler/server stay
    # up by design (the cluster is degraded, never finalized).
    try:
        out0, _ = procs[2].communicate(timeout=timeout0)
        out1, _ = procs[3].communicate(timeout=120)
    finally:
        for p in procs:
            p.kill()
    if "MULTIPROC_UNSUPPORTED" in out0.decode() + out1.decode():
        pytest.skip("this jaxlib's CPU backend lacks multiprocess "
                    "computations (environment limitation)")
    return out0.decode(), out1.decode(), procs[3].returncode


def test_peer_death_before_entry_barrier():
    """LIVE 2-process cluster: worker 1 dies before calling reshard;
    worker 0 times out at the entry barrier and aborts untouched."""
    out0, out1, rc1 = _live_crash_cluster("exit_before", 42, 420)
    assert rc1 == 42, out1[-800:]
    assert "CRASH_OK rank=0 untouched=True" in out0, out0[-1500:]
    assert "CRASH_FAIL" not in out0, out0[-1500:]


def test_peer_staging_failure_aborts_cluster_together():
    """LIVE 2-process cluster: worker 1's STAGING fails (after the
    collective snapshot legs) and goes silent; worker 0 times out at
    the COMMIT barrier and aborts — both ranks end on the old mesh
    (no cross-process mesh divergence; the failed rank must not
    release the survivor's commit barrier with a stray resume
    request)."""
    out0, out1, rc1 = _live_crash_cluster("stage_fail", 0, 480)
    assert rc1 == 0, out1[-800:]
    assert "CRASH_OK rank=1 untouched=True RuntimeError" in out1, \
        out1[-1500:]
    assert "CRASH_OK rank=0 untouched=True" in out0, out0[-1500:]
    assert "CRASH_FAIL" not in out0 + out1, (out0 + out1)[-1500:]


def test_pair_atomicity_dense_and_sparse(monkeypatch):
    """A failure in the SPARSE staging of a coordinated recut leaves the
    DENSE engine untouched too: both engines stage before either
    commits (reshard_engines' pair contract)."""
    from tests.helpers import LoopbackCluster

    from pslite_tpu import KVServer, KVServerDefaultHandle, KVWorker

    c = LoopbackCluster(num_workers=1, num_servers=1, van_type="ici_shm")
    c.start()
    servers = []
    try:
        srv = KVServer(0, postoffice=c.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        worker = KVWorker(0, 0, postoffice=c.workers[0])
        eng = worker.engine
        se = worker.po.van.sparse_engine
        keys = np.arange(2, dtype=np.uint64)
        worker.register_dense("g", keys, 16)
        W = eng.num_shards
        outs = np.zeros(32, np.float32)
        worker.wait(worker.push_pull(keys, np.ones(32, np.float32), outs))
        se.register_sparse("emb", 16, 4)

        # Dense staging places 1 store; the NEXT placement is the
        # sparse table's — fail there.
        calls = _failing_placement(monkeypatch, fail_on_call=2)
        new_mesh = make_mesh((W // 2,), ("kv",))
        with pytest.raises(RuntimeError, match="injected"):
            worker.reshard(new_mesh)
        assert calls["n"] >= 2
        assert eng.num_shards == W, "dense engine committed alone"
        assert se.num_shards == W, "sparse engine committed alone"
        out2 = np.zeros(32, np.float32)
        worker.wait(worker.pull(keys, out2))
        np.testing.assert_allclose(out2, outs)

        # Redo without the fault: the pair moves together.
        monkeypatch.undo()
        worker.reshard(new_mesh)
        assert eng.num_shards == W // 2 and se.num_shards == W // 2
    finally:
        for s in servers:
            s.stop()
        c.finalize()
