# Top-level build/test entry points (reference: Makefile + make/ps.mk).
#
#   make native         build the C++ transport core
#   make native ASAN=1  ... with AddressSanitizer
#   make native TSAN=1  ... with ThreadSanitizer (io thread vs callers)
#   make test           run the full suite (virtual 8-device CPU mesh)
#   make tier1          THE tier-1 gate: the exact ROADMAP.md invocation
#   make bench          run the headline benchmark on the local accelerator
#   make lint           byte-compile every Python module

SHELL := /bin/bash

ASAN ?= 0
TSAN ?= 0
ifeq ($(ASAN)$(TSAN), 11)
$(error ASAN and TSAN are mutually exclusive)
endif
ifeq ($(ASAN), 1)
CPPFLAGS_EXTRA = CXXFLAGS="-O1 -g -std=c++17 -fPIC -Wall -Wextra -pthread -fsanitize=address"
endif
ifeq ($(TSAN), 1)
CPPFLAGS_EXTRA = CXXFLAGS="-O1 -g -std=c++17 -fPIC -Wall -Wextra -pthread -fsanitize=thread"
endif

.PHONY: all native test tier1 bench bench-check soak soak-smoke lint clean

all: native

native:
	$(MAKE) -C cpp $(CPPFLAGS_EXTRA)

test: native
	python -m pytest tests/ -x -q

# The tier-1 verification gate, verbatim from ROADMAP.md ("Tier-1
# verify") so builder and reviewer run ONE pinned invocation instead of
# drifting copies (referenced by tests/test_bench_smoke.py).  Prints
# DOTS_PASSED=<n> and exits with pytest's status.
tier1:
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

bench: native
	python bench.py

# Trajectory guard (tools/bench_diff.py, referenced from
# tests/test_bench_smoke.py): compares the two newest BENCH_r*.json
# and fails on >25% regression in any always-on transport metric.
bench-check:
	python tools/bench_diff.py

# Graded production-matrix soak (tools/pssoak.py): tenants x
# replication x elastic x batching x tracing x native cells, each
# verified for correctness, with telemetry overhead self-measured and
# asserted < 2% of op wall.  Exits nonzero on grade C/F.
soak: native
	env JAX_PLATFORMS=cpu python tools/pssoak.py

# Tier-1-safe scaled-down soak: python plane only, <= 45 s wall,
# CPU-only (referenced by tests/test_pssoak.py).
soak-smoke:
	env JAX_PLATFORMS=cpu python tools/pssoak.py --smoke

lint:
	python -m compileall -q pslite_tpu tests bench.py __graft_entry__.py

clean:
	$(MAKE) -C cpp clean
	find . -name __pycache__ -type d -exec rm -rf {} +
