#!/usr/bin/env python
"""pssoak — graded production-matrix soak harness (docs/observability.md).

Runs the production feature matrix — combiner batching, named tenants,
replication, elastic membership, tail tracing, everything-at-once —
each cell a live in-process tcp cluster driven by a push/pull storm
for its slice of the wall budget, with the native data plane soaked as
a second leg of every cell when the C++ core is loadable.  Each cell
is verified against a numpy model of the store (bit-exact pulls), and
the wire-plane observatory's counters summarize how the bytes actually
moved (syscalls/op, frames/op, batch fill, zero-copy share).

The harness also measures ITSELF: the per-record cost of the wire
telemetry hot path is microbenchmarked in-process, multiplied by the
records the soak actually generated, and asserted to stay under 2% of
the storm wall — the observatory may not become the perturbation it
exists to detect.

The report is graded:

    A   every cell ran and verified, telemetry overhead < 2%,
        no feature cell slower than 1/5 of the baseline cell
    B   every cell verified, but a drift or a budget-starved cell
    C   telemetry overhead breached 2%, or >1/3 of cells starved
    F   any correctness failure or cell crash

Usage::

    python tools/pssoak.py --budget-s 300          # full matrix
    python tools/pssoak.py --smoke                 # <=60s, tier-1 safe
    python tools/pssoak.py --json soak.json        # machine-readable
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

OVERHEAD_LIMIT = 0.02  # telemetry share of op wall: the 2% assertion
DRIFT_FLOOR = 0.2      # feature cell ops/s vs baseline: < 1/5 flags


def _matrix(native: bool, smoke: bool) -> List[Tuple[str, dict]]:
    """(cell name, env overrides) pairs.  Smoke keeps the three cells
    that exercise distinct code paths end-to-end and stays on one
    plane; the full matrix doubles every cell with PS_NATIVE=1 when
    the C++ core loads."""
    base = [
        ("baseline", {}),
        ("batching", {"PS_BATCH_BYTES": str(64 << 10)}),
        ("tenants", {"PS_TENANTS": "serve:8,train:1"}),
        ("replication", {"PS_KV_REPLICATION": "2"}),
        ("elastic", {"PS_ELASTIC": "1"}),
        ("tracing", {"PS_TRACE_TAIL": "slow:p90,errors,floor:0.05"}),
        ("combined", {
            "PS_BATCH_BYTES": str(64 << 10),
            "PS_TENANTS": "serve:8,train:1",
            "PS_KV_REPLICATION": "2",
            "PS_ELASTIC": "1",
            "PS_TRACE_TAIL": "slow:p90,errors,floor:0.05",
        }),
    ]
    if smoke:
        base = [base[0], base[1], base[-1]]
    out = []
    for name, env in base:
        out.append((name, dict(env, PS_NATIVE="0")))
        if native and not smoke:
            out.append((f"{name}+native", dict(env, PS_NATIVE="1")))
    return out


def _wire_digest(pre: List[dict], post: List[dict]) -> dict:
    """Cluster-wide wire-plane summary from per-node registry
    snapshot pairs — both planes summed (the soak judges the whole
    data plane, not one half of it)."""
    def delta(name: str) -> int:
        tot = 0
        for p0, p1 in zip(pre, post):
            d = (p1.get("counters", {}).get(name, 0)
                 - p0.get("counters", {}).get(name, 0))
            if d > 0:
                tot += d
        return tot

    def both(suffix: str) -> int:
        return delta("wire." + suffix) + delta("wire.native." + suffix)

    ops = both("tx.ops") + delta("wire.rx.ops")
    syscalls = both("tx.syscalls") + both("rx.syscalls")
    frames = (both("tx.frames") + delta("wire.rx.frames")
              + delta("wire.native.rx.frames"))
    zc = (both("tx.bytes_zc") + delta("wire.rx.bytes_zc")
          + delta("wire.native.rx.bytes_zc"))
    copied = (delta("wire.tx.bytes_copy") + delta("wire.rx.bytes_copy")
              + delta("wire.native.rx.bytes_copy"))
    occ_n = 0
    occ_sum = 0.0
    for p0, p1 in zip(pre, post):
        h1 = p1.get("histograms", {}).get("wire.batch_occupancy") or {}
        h0 = p0.get("histograms", {}).get("wire.batch_occupancy") or {}
        occ_n += max(h1.get("count", 0) - h0.get("count", 0), 0)
        occ_sum += max(h1.get("sum", 0.0) - h0.get("sum", 0.0), 0.0)
    return {
        "ops": ops,
        "syscalls_per_op": (round(syscalls / ops, 3) if ops else None),
        "frames_per_op": (round(frames / ops, 3) if ops else None),
        "batch_fill": (round(occ_sum / occ_n, 2) if occ_n else None),
        "zc_share": (round(zc / (zc + copied), 3)
                     if zc + copied else None),
        "records": delta("wire.telemetry.records"),
        "flushes": delta("wire.telemetry.flushes"),
    }


def run_cell(name: str, env: dict, budget_s: float,
             smoke: bool) -> dict:
    """One matrix cell: boot a 1w+2s tcp cluster with the cell's env,
    storm push/pull rounds until the budget expires, verify the store
    against the numpy model, and digest the wire counters."""
    import numpy as np

    from pslite_tpu.benchmark import _loopback_cluster, _teardown_cluster
    from pslite_tpu.kv.kv_app import (KVServer, KVServerDefaultHandle,
                                      KVWorker)

    t_boot = time.perf_counter()
    nodes = _loopback_cluster(1, 2, f"soak-{name}", dict(env),
                              van_type="tcp")
    servers: list = []
    workers: list = []
    cell: Dict[str, object] = {"cell": name, "env": env}
    try:
        for po in nodes[1:3]:
            srv = KVServer(0, postoffice=po)
            srv.set_request_handle(KVServerDefaultHandle())
            servers.append(srv)
        w = KVWorker(0, 0, postoffice=nodes[3])
        workers.append(w)
        n_keys, dim = (8, 64) if smoke else (16, 256)
        span = (1 << 64) // n_keys
        keys = np.arange(n_keys, dtype=np.uint64) * np.uint64(span) + 3
        vals = ((np.arange(n_keys * dim, dtype=np.float32) % 13) + 1.0)
        out = np.zeros_like(vals)
        burst = 4 if smoke else 8
        w.wait(w.push(keys, vals))  # warm path + model round 1
        pushes = 1
        pre = [po.telemetry_snapshot()["metrics"] for po in nodes]
        t0 = time.perf_counter()
        deadline = t0 + max(budget_s - (t0 - t_boot), 0.5)
        rounds = 0
        while time.perf_counter() < deadline:
            tss = [w.push(keys, vals) for _ in range(burst)]
            for ts in tss:
                w.wait(ts)
            pushes += burst
            w.wait(w.pull(keys, out))
            rounds += 1
            if smoke and rounds >= 6:
                break  # smoke is a plumbing check, not a soak
        wall = time.perf_counter() - t0
        post = [po.telemetry_snapshot()["metrics"] for po in nodes]
        expect = vals * pushes
        ok = bool(np.array_equal(out, expect))
        if not ok:
            bad = int(np.sum(out != expect))
            cell["verify_detail"] = (f"{bad}/{out.size} elements "
                                     f"diverged after {pushes} pushes")
        cell.update({
            "verified": ok,
            "rounds": rounds,
            "pushes": pushes,
            "wall_s": round(wall, 3),
            "ops_per_s": round((pushes + rounds) / max(wall, 1e-9), 1),
            "starved": rounds < 3,
            "wire": _wire_digest(pre, post),
        })
    except Exception as exc:  # noqa: BLE001 - a crashed cell is an F,
        cell.update({"verified": False,    # not a crashed harness
                     "error": repr(exc)[:200]})
    finally:
        _teardown_cluster(nodes, workers, servers)
    return cell


def measure_record_ns(n: int = 200_000) -> float:
    """Per-record cost of the wire-telemetry hot path, measured on
    THIS host right now — the price the soak's own counters paid.
    Times the REPRESENTATIVE record mix a round trip generates (tx
    msg + frame + syscall batch, lane residency, rx msg + syscall
    batch), flush amortization included, not just the cheapest
    call."""
    from pslite_tpu.environment import Environment
    from pslite_tpu.telemetry.metrics import Registry
    from pslite_tpu.telemetry.wire import make_wire_stats

    ws = make_wire_stats(Registry(), Environment({}))
    rounds = max(n // 6, 1)
    t0 = time.perf_counter_ns()
    for _ in range(rounds):
        ws.tx_msg(4)
        ws.tx_frame(1, 4096, 128)
        ws.tx_syscalls(1)
        ws.lane_residency(2e-4)
        ws.rx_msg(4, 4096)
        ws.rx_syscalls(3)
    t1 = time.perf_counter_ns()
    ws.flush()
    return (t1 - t0) / (rounds * 6)


def grade(cells: List[dict], overhead_share: Optional[float]) -> str:
    if any(not c.get("verified") for c in cells):
        return "F"
    starved = sum(1 for c in cells if c.get("starved"))
    if (overhead_share is not None and overhead_share >= OVERHEAD_LIMIT) \
            or starved > len(cells) / 3:
        return "C"
    base = {c["cell"].split("+")[0]: c for c in cells}.get("baseline")
    drift = False
    if base and base.get("ops_per_s"):
        for c in cells:
            if c.get("skipped"):
                continue  # never ran: starved, not drifting
            rate = c.get("ops_per_s") or 0.0
            if rate < DRIFT_FLOOR * base["ops_per_s"]:
                drift = True
                c["drift"] = (f"{rate:.0f} ops/s < "
                              f"{DRIFT_FLOOR:g}x baseline "
                              f"({base['ops_per_s']:.0f})")
    if drift or starved:
        return "B"
    return "A"


def run_soak(budget_s: float, smoke: bool) -> dict:
    from pslite_tpu.vans import native as native_mod

    native = False
    if not smoke:
        try:
            native = native_mod.load() is not None
        except Exception:  # noqa: BLE001 - unloadable core = python-only
            native = False
    cells_spec = _matrix(native, smoke)
    per_cell = max(budget_s / len(cells_spec), 1.0)
    t0 = time.perf_counter()
    cells = []
    for name, env in cells_spec:
        remaining = budget_s - (time.perf_counter() - t0)
        if remaining <= 0.5:
            cells.append({"cell": name, "env": env, "verified": True,
                          "starved": True, "rounds": 0,
                          "skipped": "wall budget exhausted"})
            continue
        cells.append(run_cell(name, env, min(per_cell, remaining),
                              smoke))
    wall = time.perf_counter() - t0
    op_wall = sum(c.get("wall_s", 0.0) for c in cells)
    records = sum((c.get("wire") or {}).get("records", 0)
                  for c in cells)
    per_record_ns = measure_record_ns(20_000 if smoke else 200_000)
    overhead_share = (per_record_ns * records / (op_wall * 1e9)
                      if op_wall > 0 else None)
    report = {
        "grade": None,
        "budget_s": budget_s,
        "wall_s": round(wall, 2),
        "native_plane": native,
        "smoke": smoke,
        "cells": cells,
        "telemetry_overhead": {
            "per_record_ns": round(per_record_ns, 1),
            "records": records,
            "op_wall_s": round(op_wall, 3),
            "share": (round(overhead_share, 6)
                      if overhead_share is not None else None),
            "limit": OVERHEAD_LIMIT,
            "ok": (overhead_share is None
                   or overhead_share < OVERHEAD_LIMIT),
        },
    }
    report["grade"] = grade(cells, overhead_share)
    return report


def format_report(rep: dict) -> str:
    lines = [
        f"pssoak grade {rep['grade']}  "
        f"({len(rep['cells'])} cells, {rep['wall_s']:.1f}s of "
        f"{rep['budget_s']:g}s budget, native plane "
        f"{'on' if rep['native_plane'] else 'off'})",
        "",
        f"  {'cell':<22} {'ok':>3} {'rounds':>6} {'ops/s':>9} "
        f"{'sys/op':>7} {'frm/op':>7} {'fill':>6} {'zc%':>6}",
    ]
    for c in rep["cells"]:
        wd = c.get("wire") or {}

        def f(v, w, fmt="{:>{w}.2f}"):
            return (fmt.format(v, w=w) if isinstance(v, (int, float))
                    else f"{'-':>{w}}")

        ok = ("ok" if c.get("verified") else "FAIL")
        if c.get("skipped"):
            ok = "skip"
        zc = wd.get("zc_share")
        lines.append(
            f"  {c['cell']:<22} {ok:>4} {c.get('rounds', 0):>6} "
            f"{f(c.get('ops_per_s'), 9)} "
            f"{f(wd.get('syscalls_per_op'), 7)} "
            f"{f(wd.get('frames_per_op'), 7)} "
            f"{f(wd.get('batch_fill'), 6)} "
            + (f"{zc * 100:>5.1f}%" if isinstance(zc, float)
               else f"{'-':>6}")
            + (f"   {c['error']}" if c.get("error") else "")
            + (f"   [{c['drift']}]" if c.get("drift") else "")
        )
    oh = rep["telemetry_overhead"]
    share = oh["share"]
    lines.append("")
    lines.append(
        f"  telemetry overhead: {oh['per_record_ns']:.0f} ns/record x "
        f"{oh['records']} records / {oh['op_wall_s']:.2f}s storm wall "
        f"= {share * 100:.4f}% " if share is not None else
        "  telemetry overhead: no storm wall measured "
    )
    if share is not None:
        lines[-1] += (f"({'<' if oh['ok'] else '>='} "
                      f"{oh['limit'] * 100:g}% limit — "
                      f"{'ok' if oh['ok'] else 'BREACH'})")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--budget-s", type=float, default=300.0,
                    help="total wall budget split across matrix cells")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1-safe scaled-down run: 3 cells, "
                         "python plane only, <=60s")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="also write the report as JSON to PATH "
                         "('-' for stdout)")
    args = ap.parse_args(argv)
    budget = min(args.budget_s, 45.0) if args.smoke else args.budget_s
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    rep = run_soak(budget, args.smoke)
    if args.json == "-":
        print(json.dumps(rep, indent=1))
    else:
        print(format_report(rep))
        if args.json:
            with open(args.json, "w") as f:
                json.dump(rep, f, indent=1)
    return 0 if rep["grade"] in ("A", "B") else 1


if __name__ == "__main__":
    sys.exit(main())
