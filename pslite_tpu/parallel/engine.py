"""CollectiveEngine — the ICI data plane for dense KV push/pull.

This is the TPU-native replacement for the reference's RDMA/UCX hot path
(SURVEY §2.4, §3.2-3.4), re-architected rather than translated:

- Workers and server shards are the *same* devices of one SPMD mesh (the
  colocated/JOINT deployment, reference ``ps.h:59-76``): the ``kv`` mesh
  axis is simultaneously the worker fan-in axis and the server key-range
  sharding axis.
- ``push`` of a dense bucket is a jit-compiled ``psum_scatter`` (the
  bandwidth-optimal half of an all-reduce): each device receives the
  cross-worker **sum** of its own key range — the server-side aggregation of
  ``KVServerDefaultHandle`` (kv_app.h:430-452) executed *inside* the
  collective, on ICI, at line rate.
- The server handler (sum / assign / SGD / custom jittable fn) is fused
  between the reduce-scatter and the ``all_gather`` that implements
  ``pull`` — one XLA program per (bucket shape, dtype, op), cached exactly
  like the reference caches rendezvous addresses per (key, push, recver)
  (rdma_van.h:250-325): first touch compiles, steady state replays.
- Store shards are donated on every step, so the server state never
  double-buffers in HBM.

Zero-copy parity: ``RegisterRecvBuffer``'s "payload lands at this exact
address" contract (test_benchmark.cc:169-181) maps to donated device buffers
— the pulled array aliases the donated input's memory, no host round trip.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass
from functools import partial
from typing import Callable, Dict, Optional, Union

import numpy as np

from ..utils import logging as log
from .mesh import shard_map_compat as shard_map


@dataclass
class DenseBucket:
    """A registered dense key bucket: the unit of collective push/pull.

    Mirrors the reference benchmark's layout of ``NUM_KEY_PER_SERVER`` keys
    of ``len`` bytes each (test_benchmark.cc:407-414): ``keys[i]`` owns
    ``val_len`` consecutive values in the flat bucket vector.
    """

    name: str
    keys: np.ndarray
    val_len: int
    dtype: object
    total_len: int  # len(keys) * val_len
    padded_len: int  # rounded up to a multiple of the mesh axis size


ServerHandle = Union[str, Callable]


def _pad_ring_chunks(g, s, kchunk: int, chunk0: int):
    """Pad per-ring-position grads [n, chunk0] and store [chunk0] (or a
    pre-padded store passed as None) up to the kernel tile chunk."""
    import jax.numpy as jnp

    if kchunk == chunk0:
        return g, s
    g = jnp.pad(g, ((0, 0), (0, kchunk - chunk0)))
    if s is not None:
        s = jnp.pad(s, (0, kchunk - chunk0))
    return g, s


def _slice_ring_pulled(pulled, n: int, kchunk: int, chunk0: int):
    """Drop the kernel tile padding from a pulled [n*kchunk] vector."""
    if kchunk == chunk0:
        return pulled
    return pulled.reshape(n, kchunk)[:, :chunk0].reshape(-1)


def _aggregate(grads_l, axis, worker_axis=None):
    """Worker-reduction of a local grads block — psum_scatter on the 1-D
    colocated layout (reduce+shard in one hop), psum over the worker axis
    on a 2-D layout (the kv sharding is already in the data layout)."""
    from jax import lax

    if worker_axis is None:
        return lax.psum_scatter(
            grads_l[0], axis, scatter_dimension=0, tiled=True
        )
    return lax.psum(grads_l[0], worker_axis)


def _rs_update_ag(store_l, grads_l, handle, axis, worker_axis=None):
    """The core per-bucket aggregation semantics shared by the single and
    grouped programs: reduce(-scatter) across workers, apply the server
    handle to this shard, all-gather the updated store (push=aggregate,
    update, pull — kv_app.h:430-452 fused into the collectives).

    See :func:`_aggregate` for the 1-D vs 2-D reduction shapes."""
    from jax import lax

    agg = _aggregate(grads_l, axis, worker_axis)
    new_store = handle(store_l, agg)
    pulled = lax.all_gather(new_store, axis, tiled=True)
    return new_store, pulled


class CollectiveEngine:
    """Dense KV push/pull over one mesh axis.

    ``grads`` arguments are globally shaped ``[W, total_len]`` (row w = the
    gradient contributed by worker shard w), sharded ``P(axis, None)``; the
    store is ``[padded_len]`` sharded ``P(axis)``.  All ops are async
    (jax dispatch); ``block()`` or Customer wait-hooks give ZPush/Wait
    semantics.
    """

    def __init__(
        self,
        mesh=None,
        axis_name: str = "kv",
        server_handle: ServerHandle = "sum",
        profiler=None,
        worker_axis: Optional[str] = None,
        impl: Optional[str] = None,
        wire_compress: Optional[str] = None,
    ):
        """``impl``: data-plane implementation for stateless ``push_pull``
        — ``"xla"`` (default; psum_scatter → handle → all_gather as three
        XLA ops) or ``"pallas"`` (the fused ring kernel of
        ``ops/ring_collective.py``: one kernel per device, the update
        applied in VMEM between the reduce-scatter and all-gather ring
        phases).  Defaults to env ``PS_ICI_IMPL``.  Configs the kernel
        cannot serve (1-device mesh, 2-D mesh, stateful handles,
        non-f32/bf16 dtypes) fall back to XLA transparently.

        ``worker_axis``: optional second mesh axis carrying the worker
        fan-in, decoupling worker count from server-shard count (the
        reference's W workers vs S servers asymmetry, on the collective
        path).  With a 2-D mesh ``(dp, kv)``: gradients are summed over
        ``dp`` (the worker reduction) and scattered over ``kv`` (the
        server key-range sharding); stores live sharded over ``kv``,
        replicated over ``dp``.  Default None = the 1-D colocated layout
        where the one axis is both."""
        import jax

        from .mesh import default_mesh

        from .placement import local_shard_count, mesh_is_multiprocess

        if isinstance(axis_name, (tuple, list)):
            # MULTI-AXIS kv plane (>=3-D torus with worker_axis): the
            # store shards over the PRODUCT of these axes
            # (P(("kv1","kv2"))) and the pulled broadcast gathers over
            # both — with the fused dp sub-rings, one push_pull then
            # drives all three torus axes' links (the reference's 32
            # ports/devices per node, message.h:66-134, ucx_van.h:938-
            # 1006; v5p pods are 3-D tori).
            axis_name = tuple(axis_name)
            log.check(len(axis_name) >= 1, "empty kv axis tuple")
            for a in axis_name:
                log.check(a in (mesh.axis_names if mesh is not None
                                else ()),
                          f"kv axis {a!r} not in mesh (tuple axes "
                          f"require an explicit mesh)")
        self.mesh = mesh if mesh is not None else default_mesh(axis_name)
        self.axis = axis_name
        self.worker_axis = worker_axis
        kv_axes = (
            axis_name if isinstance(axis_name, tuple) else (axis_name,)
        )
        if worker_axis is not None:
            log.check(worker_axis in self.mesh.axis_names,
                      f"worker axis {worker_axis!r} not in mesh")
            log.check(worker_axis not in kv_axes,
                      "worker_axis must differ from the kv axis (leave it "
                      "None for the 1-D colocated layout)")
        self.num_shards = int(
            np.prod([self.mesh.shape[a] for a in kv_axes])
        )
        # Worker fan-in rows of the grads array.
        self.num_workers = (
            self.mesh.shape[worker_axis] if worker_axis is not None
            else self.num_shards
        )
        # Fixed at construction; cached off the hot path.
        self._multiprocess = mesh_is_multiprocess(self.mesh)
        self._mesh_platform = next(
            iter(self.mesh.devices.flat)
        ).platform
        # Ring kernels interpret (CPU Pallas interpreter) iff the MESH
        # is not TPU — AOT topology meshes compile real Mosaic even from
        # a CPU-default process (see ring_collective._use_interpret).
        self._ring_interpret = self._mesh_platform != "tpu"
        self._local_shard_count = (
            local_shard_count(self.mesh) if self._multiprocess
            else self.num_shards
        )
        self.impl = impl or os.environ.get("PS_ICI_IMPL", "xla")
        log.check(self.impl in ("xla", "pallas"),
                  f"unknown engine impl {self.impl!r}")
        # Per-step payload threshold for the flat replay slab layout
        # (see _flat_replay); tunable for tests / unusual chips.
        self.replay_flat_min_bytes = int(
            os.environ.get("PS_REPLAY_FLAT_MIN_BYTES", 1 << 20)
        )
        # Wire compression on the ring data plane (pallas impl only):
        # "int8" quantizes every hop payload with an embedded absmax
        # scale — 4x fewer ICI bytes, lossy (the reference's int8 wire
        # compression applied to the collective itself).  f32 buckets
        # only; other configs ignore it.
        self.wire_compress = (
            wire_compress
            if wire_compress is not None
            else os.environ.get("PS_ICI_COMPRESS", "")
        ) or None
        log.check(self.wire_compress in (None, "int8"),
                  f"unknown wire_compress {self.wire_compress!r}")
        self._server_handle = server_handle
        self._buckets: Dict[str, DenseBucket] = {}
        self._stores: Dict[str, jax.Array] = {}
        # Optimizer state for stateful server handles (sgd_momentum: mom;
        # adam: m, v, step), sharded like the store and donated each step.
        self._opt_states: Dict[str, tuple] = {}
        self._opt_kinds: Dict[str, str] = {}
        # Pinned pull-output buffers (PinMemory / w_pool_ analog,
        # ucx_van.h:603-623): pulls for a registered bucket land in the
        # same HBM buffer every time via donation of the previous output.
        self._pinned_pulls: Dict[str, object] = {}
        self._programs: Dict[tuple, Callable] = {}
        self._mu = threading.Lock()
        # Per-bucket write locks: the jitted programs donate the store
        # buffer, so the load-run-store sequence must be atomic per bucket
        # (two concurrent pushes of one bucket would otherwise hand the
        # same donated buffer to two programs).  Per-bucket rather than
        # engine-wide so different buckets still dispatch concurrently.
        self._bucket_mu: Dict[str, threading.Lock] = {}
        # Observability (reference: van.cc:29-77 event log + van.h:183-184
        # byte counters): application-payload bytes moved through the
        # collective data plane, surfaced next to Van.send_bytes/recv_bytes.
        self.profiler = profiler
        self.push_bytes = 0
        self.pull_bytes = 0
        self._counter_mu = threading.Lock()

    # -- registration --------------------------------------------------------

    def register_dense(
        self,
        name: str,
        keys,
        val_len: int,
        dtype=None,
        init: Optional[np.ndarray] = None,
    ) -> DenseBucket:
        """Register a dense bucket and allocate its sharded store.

        This is the moment the reference performs rendezvous + memory
        registration (rdma_van.h:520-548); here it allocates the sharded
        HBM store and (lazily) compiles the bucket's programs.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        if dtype is None:
            dtype = jnp.float32
        keys = np.ascontiguousarray(np.asarray(keys, dtype=np.uint64))
        total = len(keys) * val_len
        padded = -(-total // self.num_shards) * self.num_shards
        bucket = DenseBucket(
            name=name,
            keys=keys,
            val_len=val_len,
            dtype=dtype,
            total_len=total,
            padded_len=padded,
        )
        sharding = NamedSharding(self.mesh, P(self.axis))
        if init is not None:
            flat = np.zeros(padded, dtype=np.dtype(dtype))
            flat[:total] = np.asarray(init).reshape(-1)
            store = self._place(flat, sharding)
        elif self._is_multiprocess():
            store = self._place(np.zeros(padded, np.dtype(dtype)), sharding)
        else:
            store = jax.device_put(
                jnp.zeros(padded, dtype=dtype), sharding
            )
        with self._mu:
            self._buckets[name] = bucket
            self._stores[name] = store
            self._bucket_mu.setdefault(name, threading.Lock())
        return bucket

    def bucket(self, name: str) -> DenseBucket:
        return self._buckets[name]

    # -- compiled programs ---------------------------------------------------

    def _resolved_handle_fn(self, handle_key) -> Callable:
        """The handle fn for a program cache key ("_default" resolves to
        the engine's configured server handle) — the one definition of
        that sentinel rule."""
        return self._handle_fn(
            self._server_handle if handle_key == "_default" else handle_key
        )

    def _handle_fn(self, handle: ServerHandle) -> Callable:
        """Server-side update applied to (store_shard, aggregated_grads)."""
        if callable(handle):
            return handle
        if handle == "sum":
            return lambda store, agg: store + agg
        if handle == "assign":
            return lambda store, agg: agg
        if self._is_stateful(handle):
            raise ValueError(
                f"{handle!r} is stateful — resolved via _stateful_handle"
            )
        if handle.startswith("sgd"):
            lr = float(handle.split(":", 1)[1]) if ":" in handle else 0.01
            return lambda store, agg: store - lr * agg
        raise ValueError(f"unknown server handle {handle!r}")

    @staticmethod
    def _handle_params(handle: str, defaults):
        parts = handle.split(":", 1)
        vals = list(defaults)
        if len(parts) == 2 and parts[1]:
            toks = parts[1].split(",")
            log.check(
                len(toks) <= len(vals),
                f"handle {handle!r} has {len(toks)} parameters but at "
                f"most {len(vals)} are supported",
            )
            for i, tok in enumerate(toks):
                vals[i] = float(tok)
        return vals

    def _stateful_handle(self, handle: str):
        """(n_state, fn) for the fused-kernel server handles.

        ``fn(store_l, state_l, agg) -> (new_store_l, new_state_l)`` runs
        per shard inside shard_map, applying the whole optimizer step as
        one Pallas pass over the shard (the aggregation hot loop of
        kv_app.h:430-452 fused with the reduce-scatter's output).
        """
        from ..ops import fused_update

        if handle.startswith("sgd_momentum"):
            lr, momentum = self._handle_params(handle, (0.01, 0.9))

            def fn(store_l, state_l, agg):
                new_store, new_mom = fused_update.sgd_update(
                    store_l, state_l[0], agg, lr=lr, momentum=momentum
                )
                return new_store, (new_mom,)

            return 1, fn
        if handle.startswith("adam"):
            lr, b1, b2, eps = self._handle_params(
                handle, (1e-3, 0.9, 0.999, 1e-8)
            )

            def fn(store_l, state_l, agg):
                m_l, v_l, step_l = state_l
                step = step_l[0] + 1.0
                new_store, new_m, new_v = fused_update.adam_update(
                    store_l, m_l, v_l, agg, step, lr=lr,
                    beta1=b1, beta2=b2, eps=eps,
                )
                return new_store, (new_m, new_v, step_l + 1.0)

            return 3, fn
        if handle.startswith("adagrad"):
            lr, eps = self._handle_params(handle, (0.01, 1e-8))

            def fn(store_l, state_l, agg):
                new_store, new_acc = fused_update.adagrad_update(
                    store_l, state_l[0], agg, lr=lr, eps=eps
                )
                return new_store, (new_acc,)

            return 1, fn
        raise ValueError(f"not a stateful handle: {handle!r}")

    @staticmethod
    def _is_stateful(handle) -> bool:
        return isinstance(handle, str) and (
            handle.startswith("sgd_momentum")
            or handle.startswith("adam")
            or handle.startswith("adagrad")
        )

    @property
    def handle_is_stateful(self) -> bool:
        """Whether the engine's default server handle carries optimizer
        state (fused sgd_momentum/adam/adagrad) — such handles are
        unsupported by the grouped program (public predicate for
        callers)."""
        return self._is_stateful(self._server_handle)

    def _program(self, op: str, padded_len: int, dtype, handle_key) -> Callable:
        """Jitted SPMD program for (op, shape, dtype, handle) — the
        executable-cache analog of the reference's per-(key,push,recver)
        rendezvous cache."""
        key = (op, padded_len, str(dtype), handle_key)
        with self._mu:
            prog = self._programs.get(key)
        if prog is not None:
            return prog

        import jax
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        axis = self.axis
        mesh = self.mesh
        if op in ("push_st", "push_pull_st", "push_pull_st_zc"):
            return self._stateful_program(op, key, handle_key)
        if op in ("pull", "pull_pinned"):
            handle = None  # pull is read-only; no server update to fuse
        else:
            handle = self._handle_fn(
                self._server_handle if handle_key == "_default" else handle_key
            )
        waxis = self.worker_axis
        store_spec = P(axis)
        grads_spec = P(axis, None) if waxis is None else P(waxis, axis)
        repl_spec = P(None)

        def _push_pull(store_l, grads_l):
            # grads_l: [1, padded]; reduce-scatter across workers => my shard
            return _rs_update_ag(store_l, grads_l, handle, axis, waxis)

        # The degenerate 1-worker zero-copy program takes grads FLAT
        # [padded]: squeezing [1, padded] inside the program forces a
        # rank-changing relayout that runs at ~47 GB/s for packed
        # dtypes (bf16's (2,128)(2,1) tiling; measured 73% of the zc
        # step's device time) — f32 only escapes it by bitcast luck.
        flat_zc = self.num_shards == 1 and waxis is None

        def _push_pull_zc(store_l, grads_l):
            # In-place pull delivery (kv axis size 1: the gather is the
            # identity, so the updated store IS the pulled value).  The
            # copy-free analog of the reference's RegisterRecvBuffer
            # delivery (rdma_van.h:520-548): without it XLA must give the
            # second output its own buffer — a full read+write that was
            # 40% of the headline's device time (r03 verdict, weak #1).
            if flat_zc:
                return handle(store_l, grads_l)
            agg = _aggregate(grads_l, axis, waxis)
            return handle(store_l, agg)

        def _push(store_l, grads_l):
            agg = _aggregate(grads_l, axis, waxis)
            new = handle(store_l, agg)
            # Tiny non-donated completion token: callers block on this
            # instead of the store (which the next push donates).
            return new, new[:1]

        def _pull(store_l):
            return lax.all_gather(store_l, axis, tiled=True)

        def _pull_pinned(prev_l, store_l):
            # prev_l is the previous pinned output, passed to donate its
            # buffer: jit pairs it with the shape-identical output, so the
            # gather lands at the registered address.  The output must
            # *use* prev_l or jit prunes the arg and drops the alias; the
            # integer bitcast &0 keeps the dependence without float
            # arithmetic (prev*0 would resurrect NaNs from stale lanes).
            import jax.numpy as jnp

            pulled = lax.all_gather(store_l, axis, tiled=True)
            nbits = np.dtype(pulled.dtype).itemsize * 8
            idt = jnp.dtype(f"int{nbits}")
            dep = lax.bitcast_convert_type(prev_l, idt) & jnp.array(0, idt)
            return pulled + lax.bitcast_convert_type(dep, pulled.dtype)

        if op == "push_pull":
            fn = shard_map(
                _push_pull,
                mesh=mesh,
                in_specs=(store_spec, grads_spec),
                out_specs=(store_spec, repl_spec),
            )
            jitted = jax.jit(fn, donate_argnums=(0,))
        elif op == "push_pull_zc":
            fn = shard_map(
                _push_pull_zc,
                mesh=mesh,
                in_specs=(store_spec,
                          store_spec if flat_zc else grads_spec),
                out_specs=store_spec,
            )
            jitted = jax.jit(fn, donate_argnums=(0,))
        elif op == "push":
            fn = shard_map(
                _push,
                mesh=mesh,
                in_specs=(store_spec, grads_spec),
                out_specs=(store_spec, store_spec),
            )
            jitted = jax.jit(fn, donate_argnums=(0,))
        elif op == "pull":
            fn = shard_map(
                _pull, mesh=mesh, in_specs=(store_spec,), out_specs=repl_spec
            )
            jitted = jax.jit(fn)
        elif op == "pull_pinned":
            fn = shard_map(
                _pull_pinned,
                mesh=mesh,
                in_specs=(repl_spec, store_spec),
                out_specs=repl_spec,
            )
            jitted = jax.jit(fn, donate_argnums=(0,))
        else:
            raise ValueError(op)
        with self._mu:
            self._programs[key] = jitted
        return jitted

    def _effective_impl(self, dtype, resolved_handle) -> str:
        """Resolve the configured impl against what the fused ring kernel
        supports; everything else runs the XLA collective path.  Custom
        callable handles are excluded: the kernel applies the handle
        blockwise in VMEM (with tile-padding lanes flowing through it),
        which is only guaranteed sound for the built-in elementwise
        handles.

        2-D (worker_axis) meshes run the MULTI-AXIS plane: the fused
        ring executes the worker reduction + update + re-replication as
        per-column sub-rings along the worker axis, and the pulled
        broadcast rides XLA's all_gather on the kv-axis links — both
        torus axes carry the one push_pull."""
        if self.impl != "pallas":
            return "xla"
        if self.worker_axis is None and isinstance(self.axis, tuple):
            # A composite kv axis has no single ring dimension; the
            # multi-axis plane needs worker_axis sub-rings.
            return "xla"
        ring_n = (
            self.num_workers if self.worker_axis is not None
            else self.num_shards
        )
        if ring_n < 2:
            return "xla"
        if np.dtype(dtype).itemsize not in (2, 4):
            return "xla"
        if callable(resolved_handle):
            return "xla"
        if self._multiprocess:
            # Real multi-host TPU rings ride ICI fine, but the off-TPU
            # interpreter cannot DMA to another process's devices.  The
            # MESH's platform decides, not the process default backend:
            # an AOT compile-only TPU mesh (jax.experimental.topologies)
            # must select the kernel even when this process defaults to
            # CPU, and a multi-process CPU mesh must not select it even
            # under a TPU-default process.
            if self._mesh_platform != "tpu":
                return "xla"
        return "pallas"

    def _ring_program(self, padded_len: int, dtype, handle_key) -> Callable:
        """Fused ring RS+update+AG push_pull (ops/ring_collective.py):
        same signature and cache discipline as the XLA push_pull program.

        The kernel needs the per-device chunk tiled to (sublane, 128);
        buckets whose chunk is not already tile-aligned are padded inside
        the program (XLA fuses the pad) and sliced on the way out, so the
        engine-visible shapes are unchanged."""
        return self._ring_program_op("push_pull", padded_len, dtype,
                                     handle_key)

    def _ring_compress(self, dtype) -> bool:
        return (
            self.wire_compress == "int8"
            and np.dtype(dtype) == np.float32
        )

    def _ring_program_op(self, op: str, padded_len: int, dtype,
                         handle_key) -> Callable:
        compress = self._ring_compress(dtype)
        key = (f"ring_{op}", padded_len, str(dtype), handle_key, compress,
               self.worker_axis)
        with self._mu:
            prog = self._programs.get(key)
        if prog is not None:
            return prog
        if self.worker_axis is not None:
            return self._ring_program_op_2d(op, key, padded_len, dtype,
                                            handle_key, compress)

        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ..ops.ring_collective import (
            derive_collective_id,
            ring_chunk_len,
            ring_push,
            ring_push_pull,
        )

        handle = self._resolved_handle_fn(handle_key)
        axis = self.axis
        n = self.num_shards
        chunk0 = padded_len // n
        kchunk = ring_chunk_len(padded_len, n, dtype, compress=compress)
        cid = derive_collective_id(*key)
        interp = self._ring_interpret

        def _padded(store_l, grads_l):
            # grads_l: my FLAT row [padded] (see _prep_grads_ring — the
            # flat parameter keeps 2-byte dtypes packed; a (1, padded)
            # block would sublane-pad to 2x the bytes).
            return _pad_ring_chunks(
                grads_l.reshape(n, chunk0), store_l, kchunk, chunk0
            )

        def body_pp(store_l, grads_l):
            g, s = _padded(store_l, grads_l)
            new, pulled = ring_push_pull(
                g, s, handle, axis, n, collective_id=cid,
                compress=compress, interpret=interp,
            )
            if kchunk != chunk0:
                new = new[:chunk0]
            pulled = _slice_ring_pulled(pulled, n, kchunk, chunk0)
            return new, pulled

        def body_push(store_l, grads_l):
            g, s = _padded(store_l, grads_l)
            new = ring_push(g, s, handle, axis, n, collective_id=cid,
                            compress=compress, interpret=interp)
            if kchunk != chunk0:
                new = new[:chunk0]
            # Completion token, same contract as the XLA push program.
            return new, new[:1]

        if op == "push_pull":
            body, out_specs = body_pp, (P(axis), P(None))
        else:
            body, out_specs = body_push, (P(axis), P(axis))
        fn = shard_map(
            body,
            mesh=self.mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=out_specs,
        )
        jitted = jax.jit(fn, donate_argnums=(0,))
        with self._mu:
            self._programs[key] = jitted
        return jitted

    def _ring_program_op_2d(self, op: str, key, padded_len: int, dtype,
                            handle_key, compress: bool) -> Callable:
        """Multi-axis (2-D torus) ring data plane — VERDICT r02 #1.

        The worker reduction + server update + dp re-replication run as
        the fused Pallas ring along the WORKER axis: B independent
        size-A sub-rings (one per kv column) inside one kernel launch,
        each doing RS + update-in-VMEM + AG exactly like the 1-D plane.
        The pulled broadcast then rides XLA's native all_gather over the
        kv axis — a bare gather with nothing to fuse, which XLA already
        schedules bidirectionally.  Together the two phases drive both
        torus axes' links for one push_pull, the TPU analog of the
        reference spreading one transfer across per-device NICs
        (multi_van.h:173-197, ucx_van.h:938-1006)."""
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from ..ops.ring_collective import derive_collective_id

        handle = self._resolved_handle_fn(handle_key)
        axis = self.axis
        cid = derive_collective_id(*key)
        _updated_shard = self._ring_2d_shard_fn(
            handle, padded_len, dtype, compress, cid
        )

        def body_pp(store_l, grads_l):
            new_store = _updated_shard(store_l, grads_l)
            pulled = lax.all_gather(new_store, axis, tiled=True)
            return new_store, pulled

        def body_push(store_l, grads_l):
            new_store = _updated_shard(store_l, grads_l)
            return new_store, new_store[:1]

        if op == "push_pull":
            body, out_specs = body_pp, (P(axis), P(None))
        else:
            body, out_specs = body_push, (P(axis), P(axis))
        fn = shard_map(
            body,
            mesh=self.mesh,
            in_specs=(P(axis), P(self.worker_axis, axis)),
            out_specs=out_specs,
        )
        jitted = jax.jit(fn, donate_argnums=(0,))
        with self._mu:
            self._programs[key] = jitted
        return jitted

    def _ring_2d_shard_fn(self, handle, padded_len: int, dtype,
                          compress: bool, cid: int):
        """Shard-level body of the 2-D fused data plane: a function
        ``(store_l, grads_l) -> updated kv shard`` running the dp-axis
        sub-ring (RS + update-in-VMEM + AG) for use inside a shard_map
        over the full (dp, kv) mesh.  Shared by the single-bucket and
        grouped programs."""
        import jax.numpy as jnp
        from jax import lax

        from ..ops.ring_collective import ring_chunk_len, ring_push_pull

        waxis = self.worker_axis
        A = self.num_workers
        B = self.num_shards
        interp = self._ring_interpret
        chunk_kv = padded_len // B  # my kv shard (replicated over dp)
        ksub = ring_chunk_len(chunk_kv, A, dtype, compress=compress)
        maxes = tuple(
            (name, self.mesh.shape[name]) for name in self.mesh.axis_names
        )

        def _updated_shard(store_l, grads_l):
            d = lax.axis_index(waxis)
            g = grads_l[0]
            s = store_l
            if A * ksub != chunk_kv:
                g = jnp.pad(g, (0, A * ksub - chunk_kv))
                s = jnp.pad(s, (0, A * ksub - chunk_kv))
            g = g.reshape(A, ksub)
            s_sub = lax.dynamic_slice(s, (d * ksub,), (ksub,))
            _, pulled_dp = ring_push_pull(
                g, s_sub, handle, waxis, A, collective_id=cid,
                compress=compress, mesh_axes=maxes, interpret=interp,
            )
            if A * ksub != chunk_kv:
                pulled_dp = pulled_dp[:chunk_kv]
            return pulled_dp

        return _updated_shard

    def _stateful_program(self, op: str, key, handle_key: str) -> Callable:
        """Program for the fused-kernel handles: the Pallas optimizer pass
        runs between the reduce-scatter and the all-gather, with store AND
        optimizer state donated (one HBM pass per step, no double
        buffering).  On a 2-D mesh the worker reduction is the psum over
        ``worker_axis`` and state lives sharded over kv / replicated over
        dp, exactly like the store."""
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        n_state, sfn = self._stateful_handle(handle_key)
        axis = self.axis
        waxis = self.worker_axis
        store_spec = P(axis)
        grads_spec = P(axis, None) if waxis is None else P(waxis, axis)
        repl_spec = P(None)

        def _push(store_l, *rest):
            state_l, grads_l = rest[:-1], rest[-1]
            agg = _aggregate(grads_l, axis, waxis)
            new_store, new_state = sfn(store_l, tuple(state_l), agg)
            return (new_store, *new_state, new_store[:1])  # token last

        def _push_pull(store_l, *rest):
            state_l, grads_l = rest[:-1], rest[-1]
            agg = _aggregate(grads_l, axis, waxis)
            new_store, new_state = sfn(store_l, tuple(state_l), agg)
            pulled = lax.all_gather(new_store, axis, tiled=True)
            return (new_store, *new_state, pulled)

        def _push_pull_zc(store_l, *rest):
            # In-place pull delivery: see _program's _push_pull_zc.
            state_l, grads_l = rest[:-1], rest[-1]
            agg = _aggregate(grads_l, axis, waxis)
            new_store, new_state = sfn(store_l, tuple(state_l), agg)
            return (new_store, *new_state)

        if op == "push_st":
            body, tails = _push, (store_spec,)
        elif op == "push_pull_st_zc":
            body, tails = _push_pull_zc, ()
        else:
            body, tails = _push_pull, (repl_spec,)
        fn = shard_map(
            body,
            mesh=self.mesh,
            in_specs=(store_spec, *([store_spec] * n_state), grads_spec),
            out_specs=(store_spec, *([store_spec] * n_state), *tails),
        )
        jitted = jax.jit(fn, donate_argnums=tuple(range(1 + n_state)))
        with self._mu:
            self._programs[key] = jitted
        return jitted

    def _ensure_opt_state(self, name: str, handle: str, bucket) -> None:
        """Allocate (or validate) the bucket's optimizer state.  Call with
        the bucket lock held."""
        kind = handle.split(":", 1)[0]
        have = self._opt_kinds.get(name)
        if have == kind:
            return
        log.check(have is None,
                  f"bucket {name!r} already has {have!r} state; cannot "
                  f"switch to {kind!r}")
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(self.mesh, P(self.axis))
        dt = np.dtype(bucket.dtype)
        if kind in ("sgd_momentum", "adagrad"):
            state = (self._place(np.zeros(bucket.padded_len, dt), sharding),)
        else:  # adam
            state = (
                self._place(np.zeros(bucket.padded_len, dt), sharding),
                self._place(np.zeros(bucket.padded_len, dt), sharding),
                self._place(np.zeros(self.num_shards, np.float32), sharding),
            )
        self._opt_states[name] = state
        self._opt_kinds[name] = kind

    def opt_state(self, name: str):
        """Snapshot of the bucket's optimizer state (checkpointing).
        Returns (kind, arrays) or None when the bucket has none."""
        import jax.numpy as jnp

        with self._bucket_mu[name]:
            if name not in self._opt_states:
                return None
            return self._opt_kinds[name], tuple(
                jnp.copy(s) for s in self._opt_states[name]
            )

    def set_opt_state(self, name: str, kind: str, values) -> None:
        """Restore optimizer state (checkpoint resume).

        Fleet-size portable: vector states may arrive de-padded
        (``total_len``, the v2 checkpoint layout) and are re-padded for
        THIS engine's shard count; the adam step counter may arrive as
        any length (a v2 scalar or an old per-shard vector) and is
        re-broadcast to ``num_shards`` entries — so state saved on an
        8-shard fleet restores onto 4 shards and vice versa."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        import jax

        log.check(name in self._buckets, f"bucket {name!r} not registered")
        bucket = self._buckets[name]
        sharding = NamedSharding(self.mesh, P(self.axis))
        norm = []
        placed_device = {}
        for i, v in enumerate(values):
            if isinstance(v, jax.Array) and not (kind == "adam" and i == 2):
                # Fleet-portable DEVICE restore (orbax v2): logical
                # vectors pad+reshard on device, no host fetch.
                import jax.numpy as jnp

                # Mirror set_store_array's dense dtype check: a slot of
                # the wrong dtype (bucket re-registered differently than
                # at save time) must fail HERE, not steps later as an
                # opaque XLA dtype error inside the fused update.
                log.check_eq(
                    np.dtype(v.dtype), np.dtype(bucket.dtype),
                    f"bad opt restore dtype for bucket {name!r}",
                )
                log.check(
                    v.size in (bucket.total_len, bucket.padded_len),
                    f"bad optimizer state length {v.size} for bucket "
                    f"{name!r} (want {bucket.total_len} or "
                    f"{bucket.padded_len})",
                )
                if v.size == bucket.total_len != bucket.padded_len:
                    v = jnp.pad(
                        v.reshape(-1),
                        (0, bucket.padded_len - bucket.total_len),
                    )
                placed_device[i] = jax.device_put(
                    v.reshape(-1), sharding
                )
                norm.append(None)
                continue
            arr = np.ascontiguousarray(np.asarray(v))
            if kind == "adam" and i == 2:
                step = float(arr.reshape(-1)[0]) if arr.size else 0.0
                arr = np.full(self.num_shards, step, np.float32)
            else:
                # Reject mismatched vectors HERE, not steps later as an
                # opaque XLA shape error (e.g. a v1 checkpoint's
                # other-fleet padding: neither total nor this padded).
                log.check(
                    arr.size in (bucket.total_len, bucket.padded_len),
                    f"bad optimizer state length {arr.size} for bucket "
                    f"{name!r} (want {bucket.total_len} or "
                    f"{bucket.padded_len})",
                )
                if arr.size == bucket.total_len != bucket.padded_len:
                    out = np.zeros(bucket.padded_len, arr.dtype)
                    out[: bucket.total_len] = arr.reshape(-1)
                    arr = out
            norm.append(arr)
        placed = tuple(
            placed_device[i] if a is None else self._place(a, sharding)
            for i, a in enumerate(norm)
        )
        with self._bucket_mu[name]:
            self._opt_states[name] = placed
            self._opt_kinds[name] = kind

    # -- data plane ops ------------------------------------------------------

    def _is_multiprocess(self) -> bool:
        return self._multiprocess

    def _place(self, host_arr, sharding):
        from .placement import place_host_array

        return place_host_array(
            self.mesh, host_arr, sharding, self._multiprocess
        )

    def _local_shards(self) -> int:
        """Worker rows owned by THIS process on a multi-process mesh."""
        return self._local_shard_count

    def _normalize_host_grads(self, grads, rows, bucket, xp,
                              steps: bool = False,
                              row_msg: str = "bad worker dim"):
        """Coerce a grads array to ``[(T,)? rows, padded]``: dtype cast,
        broadcast a missing row dim to ``rows``, validate the row count,
        pad the value tail.  The one definition behind every host/device
        staging path (1-D/2-D x single/multi-process x single/replay);
        ``xp`` is np (host staging) or jnp (device staging)."""
        arr = xp.asarray(grads, dtype=np.dtype(bucket.dtype))
        want = 3 if steps else 2
        log.check(arr.ndim in (want - 1, want), "bad grads rank")
        if arr.ndim == want - 1:
            if steps:
                arr = xp.broadcast_to(
                    arr[:, None, :], (arr.shape[0], rows, arr.shape[1])
                )
            else:
                arr = xp.broadcast_to(arr, (rows, arr.shape[0]))
        log.check_eq(int(arr.shape[-2]), rows, row_msg)
        if arr.shape[-1] != bucket.padded_len:
            log.check_eq(int(arr.shape[-1]), bucket.total_len,
                         "bad grad len")
            pad = bucket.padded_len - bucket.total_len
            pads = [(0, 0)] * (arr.ndim - 1) + [(0, pad)]
            arr = xp.pad(arr, pads)
        return arr

    def _prep_grads_flat(self, bucket: DenseBucket, grads):
        """``[padded]`` FLAT grads for the degenerate 1-worker zero-copy
        program (see ``_push_pull_zc``'s flat_zc note): host arrays
        flatten for free; device ``[1, padded]`` arrays pay one reshape
        per call (a bitcast for f32, a relayout copy for packed dtypes
        — pass flat device arrays on the hot path)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(self.mesh, P(self.axis))
        if isinstance(grads, jax.Array):
            # Same worker-dim discipline as _prep_grads: a (2, N/2)
            # array must fail loud, not silently flatten into one
            # concatenated gradient.
            log.check(grads.ndim in (1, 2), "bad grads rank")
            if grads.ndim == 2:
                log.check_eq(int(grads.shape[0]), 1, "bad worker dim")
                g = grads.reshape(-1)
            else:
                g = grads
            if int(g.shape[0]) == bucket.padded_len:
                if g.sharding == sharding:
                    return g
                return jax.device_put(g, sharding)
            # Unpadded device arrays fall through to host normalization
            # (padded == total on every zc-eligible config, so this is
            # only reachable for malformed lengths, which it rejects).
        arr = self._normalize_host_grads(grads, 1, bucket, np)
        return jax.device_put(
            np.ascontiguousarray(arr).reshape(-1), sharding
        )

    def _prep_grads_ring(self, bucket: DenseBucket, grads):
        """``[W*padded]`` FLAT grads, sharded ``P(axis)``, for the
        single-bucket 1-D fused ring programs.

        Why flat: the ``[W, padded]`` form gives each device a
        ``(1, padded)`` parameter block, and TPU tiled layouts pad the
        sublane dim — ``T(2,128)`` for 2-byte dtypes stores (and reads)
        TWICE the bytes for a bf16 grads operand (caught by
        tools/aot_ring_compile.py's memory cross-check; f32's
        ``T(1,128)`` happens to be packed).  The flat form is the same
        bits per device (row-major row d == device d's flat slice) but
        always lays out packed.  Host arrays flatten for free; a
        ``[W, padded]`` device array pays one relayout per call (pass
        flat device arrays on the hot path, as with _prep_grads_flat).
        """
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(self.mesh, P(self.axis))
        W = self.num_shards
        flat_len = W * bucket.padded_len
        if isinstance(grads, jax.Array):
            if grads.ndim == 2 and int(grads.shape[1]) == bucket.padded_len:
                log.check_eq(int(grads.shape[0]), W, "bad worker dim")
                return jax.device_put(grads.reshape(-1), sharding)
            if grads.ndim == 1 and int(grads.shape[0]) == flat_len:
                if grads.sharding == sharding:
                    return grads
                return jax.device_put(grads, sharding)
            # Unpadded / broadcast forms fall through to host staging.
        if self._is_multiprocess():
            arr = self._normalize_host_grads(
                grads, self._local_shards(), bucket, np,
                row_msg="bad local worker dim (rows = this process's "
                        "devices on a multi-process mesh)",
            )
            return jax.make_array_from_process_local_data(
                sharding,
                np.ascontiguousarray(arr).reshape(-1),
                (flat_len,),
            )
        arr = self._normalize_host_grads(grads, W, bucket, np)
        return jax.device_put(
            np.ascontiguousarray(arr).reshape(-1), sharding
        )

    def _prep_grads(self, bucket: DenseBucket, grads):
        """Accept [W, total] (or [total] broadcast) host/device arrays and
        deliver a [W, padded] device array sharded over the worker axis.

        Multi-process host-array contracts differ by layout:
        - 1-D mesh: the host array is this PROCESS's contribution —
          [total] broadcasts to the process's local worker rows,
          [local, total] maps row-for-row; the global array is assembled
          with make_array_from_process_local_data (device_put cannot
          target non-addressable devices).
        - 2-D (worker_axis) mesh: the host array is the GLOBAL
          [W, total] grads and must be IDENTICAL on every process — a
          process's devices span a rectangle of the (dp, kv) grid, so
          there is no per-process row ownership to map a local
          contribution onto."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self.worker_axis is not None:
            sharding = NamedSharding(
                self.mesh, P(self.worker_axis, self.axis)
            )
        else:
            sharding = NamedSharding(self.mesh, P(self.axis, None))
        if isinstance(grads, jax.Array) and grads.ndim == 2:
            if grads.shape[1] == bucket.padded_len:
                # Row count must match the worker fan-in exactly — a
                # silent reshard would drop rows (the shard body reads
                # one local row per device position).
                log.check_eq(int(grads.shape[0]), self.num_workers,
                             "bad worker dim")
                if grads.sharding == sharding:
                    return grads
                return jax.device_put(grads, sharding)
        if self.worker_axis is not None:
            if self._is_multiprocess():
                arr = self._normalize_host_grads(
                    grads, self.num_workers, bucket, np
                )
                return self._place(np.ascontiguousarray(arr), sharding)
            arr = self._normalize_host_grads(
                grads, self.num_workers, bucket, jnp
            )
            return jax.device_put(arr, sharding)
        if self._is_multiprocess():
            arr = self._normalize_host_grads(
                grads, self._local_shards(), bucket, np,
                row_msg="bad local worker dim (rows = this process's "
                        "devices on a multi-process mesh)",
            )
            return jax.make_array_from_process_local_data(
                sharding, np.ascontiguousarray(arr),
                (self.num_shards, bucket.padded_len),
            )
        arr = self._normalize_host_grads(
            grads, self.num_shards, bucket, jnp
        )
        return jax.device_put(arr, sharding)

    def _observe(self, name: str, op: str, bucket: DenseBucket,
                 t0: float) -> None:
        """Account one data-plane op: byte counters always, the
        (bucket, op, bytes, µs) event when profiling is on.

        The µs field is DISPATCH latency (op entry to async enqueue), not
        device execution time — collectives are dispatched asynchronously;
        use ``utils.profiling.device_trace`` (XPlane) for transfer-level
        timing, as documented in record_engine's consumer docs."""
        payload = bucket.total_len * np.dtype(bucket.dtype).itemsize
        with self._counter_mu:
            if op in ("push", "push_pull"):
                self.push_bytes += payload
            if op in ("pull", "push_pull"):
                self.pull_bytes += payload
        if self.profiler is not None and getattr(
            self.profiler, "enabled", False
        ):
            dur_us = int((time.perf_counter() - t0) * 1e6)
            nbytes = payload * (2 if op == "push_pull" else 1)
            self.profiler.record_engine(name, op, nbytes, dur_us)

    def _resolve_handle(self, handle: Optional[ServerHandle]):
        resolved = self._server_handle if handle is None else handle
        if self._is_stateful(resolved):
            return resolved, resolved  # stateful handles key by full string
        return resolved, ("_default" if handle is None else handle)

    def _zc_pull_eligible(self, dtype, resolved) -> bool:
        """Whether in-place pull delivery can serve this config: the kv
        axis has size 1 (the all-gather is the identity, so the updated
        store IS the pulled value — and ``padded_len == total_len``), and
        the data plane is the XLA path (the ring kernel needs >=2 ring
        devices and defines its own output layout).  Mirrors the
        reference's RegisterRecvBuffer: in-place delivery happens where
        the transport allows it, transparently copied elsewhere."""
        if self.num_shards != 1:
            return False
        if self._is_stateful(resolved):
            return True
        return self._effective_impl(dtype, resolved) == "xla"

    def flat_ring_eligible(self, dtype, handle: Optional[ServerHandle] = None
                           ) -> bool:
        """Whether ``push_pull``/``push`` for this config routes to the
        1-D fused ring programs, which take FLAT ``[W*padded]`` grads
        (``_prep_grads_ring``) — hot-path callers holding device arrays
        should pre-build that layout to skip the per-call relayout.
        The ONE definition the op routing and benchmarks share."""
        resolved, _ = self._resolve_handle(handle)
        return (
            not self._is_stateful(resolved)
            and self.worker_axis is None
            and self._effective_impl(dtype, resolved) == "pallas"
        )

    def flat_zc_eligible(self, handle: Optional[ServerHandle] = None
                         ) -> bool:
        """Whether a zero-copy push_pull for ``handle`` takes the FLAT
        grads program (callers that pre-build device inputs should then
        pass [padded] 1-D arrays — see _prep_grads_flat).  The ONE
        definition bench and callers share with push_pull's routing."""
        resolved, _ = self._resolve_handle(handle)
        return (self.num_shards == 1
                and not self._is_stateful(resolved)
                and self.worker_axis is None)

    def push_pull(self, name: str, grads, handle: Optional[ServerHandle] = None,
                  zero_copy: bool = False):
        """Fused push+aggregate+update+pull; returns the replicated pulled
        array (async).  The benchmark hot path (SURVEY §3.2).

        ``zero_copy=True`` requests in-place pull delivery: where the
        topology allows it (see :meth:`_zc_pull_eligible`) the returned
        array ALIASES the bucket store — zero extra HBM traffic, but it
        is invalidated by the bucket's next mutating op (the next push
        donates the buffer; stale holders raise on use rather than read
        torn data).  Same caller contract as the reference's
        RegisterRecvBuffer pulls (the next pull overwrites the registered
        buffer in place).  Configs the in-place path cannot serve fall
        back to the copying path transparently."""
        t0 = time.perf_counter()
        bucket = self._buckets[name]
        resolved, handle_key = self._resolve_handle(handle)
        zc = zero_copy and self._zc_pull_eligible(bucket.dtype, resolved)
        flat_zc = zc and self.flat_zc_eligible(handle)
        ring_1d = self.flat_ring_eligible(bucket.dtype, handle)
        if flat_zc:
            g = self._prep_grads_flat(bucket, grads)
        elif ring_1d:
            g = self._prep_grads_ring(bucket, grads)
        else:
            g = self._prep_grads(bucket, grads)
        if self._is_stateful(resolved):
            prog = self._program(
                "push_pull_st_zc" if zc else "push_pull_st",
                bucket.padded_len, bucket.dtype, handle_key
            )
            with self._bucket_mu[name]:
                self._ensure_opt_state(name, resolved, bucket)
                outs = prog(
                    self._stores[name], *self._opt_states[name], g
                )
                n_state = len(self._opt_states[name])
                self._stores[name] = outs[0]
                self._opt_states[name] = tuple(outs[1:1 + n_state])
                pulled = outs[0] if zc else outs[-1]
            self._observe(name, "push_pull", bucket, t0)
            return pulled if zc else pulled[: bucket.total_len]
        if self._effective_impl(bucket.dtype, resolved) == "pallas":
            prog = self._ring_program(
                bucket.padded_len, bucket.dtype, handle_key
            )
        elif zc:
            prog = self._program(
                "push_pull_zc", bucket.padded_len, bucket.dtype, handle_key
            )
        else:
            prog = self._program(
                "push_pull", bucket.padded_len, bucket.dtype, handle_key
            )
        with self._bucket_mu[name]:
            if zc:
                new_store = prog(self._stores[name], g)
                pulled = new_store
            else:
                new_store, pulled = prog(self._stores[name], g)
            self._stores[name] = new_store
        self._observe(name, "push_pull", bucket, t0)
        return pulled if zc else pulled[: bucket.total_len]

    def push(self, name: str, grads, handle: Optional[ServerHandle] = None):
        t0 = time.perf_counter()
        bucket = self._buckets[name]
        resolved, handle_key = self._resolve_handle(handle)
        ring_1d = self.flat_ring_eligible(bucket.dtype, handle)
        g = (self._prep_grads_ring(bucket, grads) if ring_1d
             else self._prep_grads(bucket, grads))
        if self._is_stateful(resolved):
            prog = self._program(
                "push_st", bucket.padded_len, bucket.dtype, handle_key
            )
            with self._bucket_mu[name]:
                self._ensure_opt_state(name, resolved, bucket)
                outs = prog(
                    self._stores[name], *self._opt_states[name], g
                )
                self._stores[name] = outs[0]
                self._opt_states[name] = tuple(outs[1:-1])
                token = outs[-1]
            self._observe(name, "push", bucket, t0)
            return token
        if self._effective_impl(bucket.dtype, resolved) == "pallas":
            prog = self._ring_program_op(
                "push", bucket.padded_len, bucket.dtype, handle_key
            )
        else:
            prog = self._program(
                "push", bucket.padded_len, bucket.dtype, handle_key
            )
        with self._bucket_mu[name]:
            new_store, token = prog(self._stores[name], g)
            self._stores[name] = new_store
        self._observe(name, "push", bucket, t0)
        # The token is a tiny non-donated output that becomes ready when
        # the push completes — block on it freely (the store itself is
        # donated by the next push, so it must not escape).
        return token

    def coalescer(self, handle: Optional[ServerHandle] = None, **kw):
        """A :class:`~pslite_tpu.parallel.coalesce.CoalescingDispatcher`
        over this engine: concurrently-issued per-op push_pulls
        micro-batch into grouped programs (the async ZPush/ZPull
        amortization — see the module docstring)."""
        from .coalesce import CoalescingDispatcher

        return CoalescingDispatcher(self, handle=handle, **kw)

    def push_pull_group(self, names, grads_list,
                        handle: Optional[ServerHandle] = None):
        """Fused push_pull over SEVERAL buckets in ONE jitted program —
        one dispatch instead of len(names) (the bucketed-gradient-stream
        pattern of a model step, e.g. the ResNet-50 trace's ~35 buckets).

        Stateless handles only (sum/assign/sgd/custom); returns the list
        of pulled arrays in ``names`` order.
        """
        log.check(len(names) == len(grads_list), "names/grads mismatch")
        log.check(len(set(names)) == len(names),
                  "duplicate bucket in group (stores are donated)")
        resolved, handle_key = self._resolve_handle(handle)
        log.check(not self._is_stateful(resolved),
                  "push_pull_group supports stateless handles only")
        t0 = time.perf_counter()
        buckets = [self._buckets[n] for n in names]
        # MUST mirror _group_program's use_ring resolution: the grouped
        # 1-D ring program takes each bucket's grads FLAT (same sublane
        # -pad rationale as _prep_grads_ring).
        group_flat = self.worker_axis is None and all(
            self._effective_impl(b.dtype, resolved) == "pallas"
            for b in buckets
        )
        prep = self._prep_grads_ring if group_flat else self._prep_grads
        gs = [prep(b, g) for b, g in zip(buckets, grads_list)]
        prog = self._group_program(
            tuple((b.padded_len, str(np.dtype(b.dtype))) for b in buckets),
            handle_key,
        )
        # Lock every bucket in sorted order (deadlock-free against other
        # group/single ops) for the whole load-run-store.
        ordered = sorted(set(names))
        for n in ordered:
            self._bucket_mu[n].acquire()
        try:
            outs = prog(*[self._stores[n] for n in names], *gs)
            k = len(names)
            for i, n in enumerate(names):
                self._stores[n] = outs[i]
            pulled = outs[k:]
        finally:
            for n in reversed(ordered):
                self._bucket_mu[n].release()
        for i, (n, b) in enumerate(zip(names, buckets)):
            # One dispatch happened: attribute its latency to the first
            # bucket's event only (zero for the rest) so summed profiler
            # durations aren't inflated k-fold; byte counters are per
            # bucket as usual.
            self._observe(n, "push_pull", b,
                          t0 if i == 0 else time.perf_counter())
        return [p[: b.total_len] for p, b in zip(pulled, buckets)]

    def _group_program(self, shapes_key, handle_key) -> Callable:
        # The ring gate is _effective_impl per bucket dtype — the same
        # resolution the single-bucket path applies (incl. the
        # multiprocess/off-TPU interpreter restriction, which cannot DMA
        # across processes).
        resolved = (
            self._server_handle if handle_key == "_default" else handle_key
        )
        use_ring = all(
            self._effective_impl(dt, resolved) == "pallas"
            for _, dt in shapes_key
        )
        key = ("group_pp", shapes_key, handle_key, use_ring,
               self.worker_axis)
        with self._mu:
            prog = self._programs.get(key)
        if prog is not None:
            return prog

        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        axis = self.axis
        waxis = self.worker_axis
        handle = self._resolved_handle_fn(handle_key)
        k = len(shapes_key)
        store_spec = P(axis)
        # 1-D ring groups take each bucket's grads FLAT [W*padded]
        # (packed layout for 2-byte dtypes — _prep_grads_ring); the XLA
        # and 2-D paths keep the row form.
        if waxis is not None:
            grads_spec = P(waxis, axis)
        elif use_ring:
            grads_spec = P(axis)
        else:
            grads_spec = P(axis, None)
        repl_spec = P(None)
        n = self.num_shards
        interp = self._ring_interpret

        def _ring_one(i, padded_len, dtype, store_l, grads_l):
            from ..ops.ring_collective import (
                derive_collective_id,
                ring_chunk_len,
                ring_push_pull,
            )

            compress = self._ring_compress(dtype)
            cid = derive_collective_id(*key, i)
            if waxis is not None:
                # 2-D: dp sub-ring for this bucket, kv gather for pull.
                shard_fn = self._ring_2d_shard_fn(
                    handle, padded_len, dtype, compress, cid
                )
                new = shard_fn(store_l, grads_l)
                pulled = lax.all_gather(new, axis, tiled=True)
                return new, pulled
            chunk0 = padded_len // n
            kchunk = ring_chunk_len(padded_len, n, dtype,
                                    compress=compress)
            # grads_l: my FLAT row [padded] (grads_spec P(axis)).
            g, s = _pad_ring_chunks(
                grads_l.reshape(n, chunk0), store_l, kchunk, chunk0
            )
            new, pulled = ring_push_pull(
                g, s, handle, axis, n,
                collective_id=cid,
                compress=compress, interpret=interp,
            )
            if kchunk != chunk0:
                new = new[:chunk0]
            pulled = _slice_ring_pulled(pulled, n, kchunk, chunk0)
            return new, pulled

        def _body(*args):
            stores, grads = args[:k], args[k:]
            new_stores, pulled = [], []
            for i, (store_l, grads_l) in enumerate(zip(stores, grads)):
                if use_ring:
                    padded_len, dt = shapes_key[i]
                    new, out = _ring_one(i, padded_len, dt, store_l,
                                         grads_l)
                else:
                    new, out = _rs_update_ag(store_l, grads_l, handle,
                                             axis, waxis)
                new_stores.append(new)
                pulled.append(out)
            return (*new_stores, *pulled)

        fn = shard_map(
            _body,
            mesh=self.mesh,
            in_specs=tuple([store_spec] * k + [grads_spec] * k),
            out_specs=tuple([store_spec] * k + [repl_spec] * k),
        )
        jitted = jax.jit(fn, donate_argnums=tuple(range(k)))
        with self._mu:
            self._programs[key] = jitted
        return jitted

    # -- fused multi-step replay --------------------------------------------

    def replay(self, name: str, grads_seq, handle: Optional[ServerHandle] = None,
               keep: str = "all", zero_copy: bool = False):
        """Run T consecutive ``push_pull`` steps as ONE jitted program —
        a ``lax.scan`` over the donated store (and optimizer state for
        stateful handles), so the per-op Python+dispatch cost (~50-100 µs,
        which dominates small buckets) is paid once for the whole
        sequence.  The steady-state analog of the reference's ns/key
        replay loop (test_benchmark.cc:388-396): first touch compiles,
        thereafter the whole T-step pipeline is device-resident.

        Args:
          grads_seq: ``[T, total]`` (each step's gradient broadcast to
            every worker) or ``[T, W, total]`` (row per worker per step);
            host arrays on single-process meshes, any layout of
            ``jax.Array``.  Multi-process host arrays follow
            ``_prep_grads``'s contracts: 1-D mesh = ``[T, local, total]``
            (this process's worker rows); 2-D mesh = the GLOBAL
            ``[T, W, total]``, identical on every process.
          keep: ``"all"`` materializes every step's pulled result
            (returns ``[T, total]``); ``"last"`` returns only the final
            pulled vector ``[total]`` — intermediate all-gathers are
            dead code XLA removes, making it the fused form of
            T×ZPush + one pull.
          zero_copy: with ``keep="last"`` on a zc-eligible config (see
            :meth:`push_pull`), skip the final gather and return the
            store itself — invalidated by the bucket's next mutating op.
        """
        log.check(keep in ("all", "last"), f"bad keep {keep!r}")
        t0 = time.perf_counter()
        bucket = self._buckets[name]
        resolved, handle_key = self._resolve_handle(handle)
        stateful = self._is_stateful(resolved)
        zc = (zero_copy and keep == "last"
              and self._zc_pull_eligible(bucket.dtype, resolved))
        steps = int(np.shape(grads_seq)[0])
        flat = self._flat_replay(
            bucket.padded_len, bucket.dtype, handle_key, stateful, steps
        )
        g = self._prep_grads_seq(bucket, grads_seq, flat=flat)
        if stateful:
            prog = self._replay_program(
                steps, bucket.padded_len, bucket.dtype, handle_key, keep,
                stateful=True, zero_copy=zc,
            )
            with self._bucket_mu[name]:
                self._ensure_opt_state(name, resolved, bucket)
                outs = prog(
                    self._stores[name], *self._opt_states[name], g
                )
                n_state = len(self._opt_states[name])
                self._stores[name] = outs[0]
                self._opt_states[name] = tuple(outs[1:1 + n_state])
                pulled = outs[0] if zc else outs[-1]
        else:
            prog = self._replay_program(
                steps, bucket.padded_len, bucket.dtype, handle_key, keep,
                stateful=False, zero_copy=zc,
            )
            with self._bucket_mu[name]:
                if zc:
                    new_store = prog(self._stores[name], g)
                    pulled = new_store
                else:
                    new_store, pulled = prog(self._stores[name], g)
                self._stores[name] = new_store
        payload = bucket.total_len * np.dtype(bucket.dtype).itemsize
        with self._counter_mu:
            self.push_bytes += payload * steps
            self.pull_bytes += payload * (steps if keep == "all" else 1)
        if self.profiler is not None and getattr(
            self.profiler, "enabled", False
        ):
            dur_us = int((time.perf_counter() - t0) * 1e6)
            nbytes = payload * (steps + (steps if keep == "all" else 1))
            self.profiler.record_engine(name, "replay", nbytes, dur_us)
        if zc:
            return pulled  # aliases the store; padded == total on zc configs
        if keep == "all":
            return pulled[:, : bucket.total_len]
        return pulled[: bucket.total_len]

    def push_pull_stream(self, name: str, grads_iter,
                         handle: Optional[ServerHandle] = None,
                         depth: int = 2):
        """Generator over ``push_pull`` results with host->HBM staging
        pipelined against the collectives — the HOST-ORIGIN fast path
        for one bucket (see :meth:`push_pull_multi_stream`)."""
        return self.push_pull_multi_stream(
            ((name, g) for g in grads_iter), handle=handle, depth=depth
        )

    def push_pull_multi_stream(self, pairs_iter,
                               handle: Optional[ServerHandle] = None,
                               depth: int = 2):
        """Generator over ``push_pull`` results for ``(bucket_name,
        grads)`` pairs with host->HBM staging pipelined against the
        collectives.

        A background thread runs ``_prep_grads`` (the ``device_put``
        staging) up to ``depth`` batches ahead while the caller's thread
        dispatches the collective on the previously staged batch, so
        transfer(i+1) overlaps compute(i) even when the transport makes
        ``device_put`` effectively synchronous.  This is the collective
        analog of the reference's pinned-memory + async-RDMA overlap on
        its host path (CPU tensors staged into registered buffers while
        the NIC drains earlier ones); a bucketed gradient stream (e.g.
        the ResNet-50 trace) pipelines bucket i+1's transfer under
        bucket i's collective.

        The iterator is consumed on the stager thread; results yield in
        order.  A stager-side exception re-raises on the caller's
        thread; closing the generator early releases the stager.  Each
        yielded array follows the usual async-dispatch contract (block
        or np.asarray to materialize)."""
        import queue as _queue

        log.check(depth >= 1, "depth must be >= 1")
        q: "_queue.Queue" = _queue.Queue(maxsize=depth)
        _DONE = object()
        stop = threading.Event()

        def _put(item) -> bool:
            # Bounded put that notices an abandoned consumer (generator
            # closed early) instead of blocking forever on a full queue.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        def _stager():
            try:
                for name, g in pairs_iter:
                    staged = self._prep_grads(self._buckets[name], g)
                    if not _put(("ok", name, staged)):
                        return
            except BaseException as exc:  # surfaced on the caller thread
                _put(("err", exc, None))
                return
            _put((_DONE, None, None))

        t = threading.Thread(target=_stager, name="engine-stager",
                             daemon=True)
        t.start()
        try:
            while True:
                kind, a, b = q.get()
                if kind is _DONE:
                    break
                if kind == "err":
                    raise a
                yield self.push_pull(a, b, handle=handle)
        finally:
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except _queue.Empty:
                pass
            t.join(timeout=30)

    def _prep_grads_seq(self, bucket: DenseBucket, grads_seq,
                        flat: bool = False):
        """[T, W, padded] device array sharded like the grads of T
        stacked push calls (leading step axis replicated) — or, with
        ``flat=True`` (1-D layouts only, see :meth:`_flat_replay`), the
        slab layout ``[W, T*padded]`` where worker w's T steps are one
        contiguous run."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        if flat:
            log.check(self.worker_axis is None,
                      "flat replay layout is 1-D only")
            sharding = NamedSharding(self.mesh, P(self.axis, None))

            def _to_slab(arr, xp):
                # [T, rows, padded] -> [rows, T*padded]; note shape[1] is
                # read from the PRE-swap array (the row count).  No
                # pre-slabbed fast path: a [W, T*padded] slab is
                # indistinguishable from a broadcast [T, total] whenever
                # T == W and total % padded == 0, and guessing wrong
                # silently collapses T steps into one.
                rows = arr.shape[1]
                arr = xp.swapaxes(arr, 0, 1)
                if xp is np:
                    arr = np.ascontiguousarray(arr)
                return arr.reshape(rows, -1)

            if self._is_multiprocess():
                arr = self._normalize_host_grads(
                    grads_seq, self._local_shards(), bucket, np, steps=True,
                    row_msg="bad local worker dim (rows = this process's "
                            "devices on a multi-process mesh)",
                )
                arr = _to_slab(arr, np)
                return jax.make_array_from_process_local_data(
                    sharding, arr,
                    (self.num_shards, arr.shape[1]),
                )
            if isinstance(grads_seq, jax.Array):
                # Device arrays must relayout on device (tiled 2-D rows
                # are physically interleaved; slabs need contiguity).
                arr = self._normalize_host_grads(
                    grads_seq, self.num_shards, bucket, jnp, steps=True
                )
                return jax.device_put(_to_slab(arr, jnp), sharding)
            # Host arrays: build the slab layout host-side (free views
            # for W=1, one transpose copy otherwise) so the device sees
            # ONE transfer and ZERO relayout copies — the relayouts were
            # ~68% of the replay's device time when done on device.
            arr = self._normalize_host_grads(
                grads_seq, self.num_shards, bucket, np, steps=True
            )
            return jax.device_put(_to_slab(arr, np), sharding)
        if self.worker_axis is not None:
            sharding = NamedSharding(
                self.mesh, P(None, self.worker_axis, self.axis)
            )
        else:
            sharding = NamedSharding(self.mesh, P(None, self.axis, None))
        if isinstance(grads_seq, jax.Array) and grads_seq.ndim == 3:
            if grads_seq.shape[1:] == (self.num_workers, bucket.padded_len):
                if grads_seq.sharding == sharding:
                    return grads_seq
                return jax.device_put(grads_seq, sharding)
        if self._is_multiprocess():
            if self.worker_axis is not None:
                # Same GLOBAL-array contract as _prep_grads' 2-D branch.
                arr = self._normalize_host_grads(
                    grads_seq, self.num_workers, bucket, np, steps=True
                )
                return self._place(np.ascontiguousarray(arr), sharding)
            arr = self._normalize_host_grads(
                grads_seq, self._local_shards(), bucket, np, steps=True,
                row_msg="bad local worker dim (rows = this process's "
                        "devices on a multi-process mesh)",
            )
            return jax.make_array_from_process_local_data(
                sharding, np.ascontiguousarray(arr),
                (arr.shape[0], self.num_shards, bucket.padded_len),
            )
        arr = self._normalize_host_grads(
            grads_seq, self.num_workers, bucket, jnp, steps=True
        )
        return jax.device_put(arr, sharding)

    def _replay_use_ring(self, dtype, handle_key, stateful: bool) -> bool:
        """Whether a replay scans the fused ring step.  Wire compression
        stays off the replay ring: scanning the per-hop-requantizing
        kernel is unvalidatable off-TPU (the interpreter takes minutes
        per step) and compounds quantization error T-fold; compressed
        configs replay on the XLA step while their single-step/grouped
        ops keep the compressed ring."""
        resolved = (
            self._server_handle if handle_key == "_default" else handle_key
        )
        return (
            not stateful
            and self._effective_impl(dtype, resolved) == "pallas"
            and not self._ring_compress(dtype)
        )

    def _flat_replay(self, padded_len: int, dtype, handle_key,
                     stateful: bool, steps: int) -> bool:
        """Whether the replay sequence uses the FLAT slab layout
        ``[W, T*padded]`` (each worker's T steps contiguous) instead of
        the stacked ``[T, W, padded]``.

        The stacked form makes XLA slice step t out of a sublane-tiled
        ``[T, padded]`` block — a strided read that measured ~190 GB/s on
        a 685 GB/s chip and caused the r03 16MB replay cliff (112 vs 314
        GB/s at 1MB) — plus two full relayout copies of the whole
        sequence on entry.  Flat slabs make each step an aligned
        contiguous ``dynamic_slice`` that fuses with the update (measured
        ~674 GB/s at 16MB).  Below ~1MB per step XLA's software pipelining
        of the stacked layout wins instead (it stages slices into VMEM
        ahead of use), so small buckets keep the stacked form."""
        return (
            not stateful
            and self.worker_axis is None
            and not self._replay_use_ring(dtype, handle_key, stateful)
            and padded_len * np.dtype(dtype).itemsize
            >= self.replay_flat_min_bytes
            # Slab offsets are int32 inside the scan; a slab at or over
            # 2^31 elements would wrap (dynamic_slice clamps silently).
            and steps * padded_len < (1 << 31)
        )

    @staticmethod
    def _replay_unroll(padded_len: int, dtype, steps: int) -> int:
        """Inner unroll factor for the flat replay scan: the largest of
        16/8/4/2 no bigger than the step count that keeps the
        per-iteration slab read at or under 64MB (larger slabs regress —
        the 64MB-step sweep point measured 342 vs 454 GB/s with U=2).
        Step counts not divisible by U run a tail scan for the
        remainder, so odd T keeps the amortization for its bulk."""
        bytes_step = padded_len * np.dtype(dtype).itemsize
        cap = max(1, (64 << 20) // max(bytes_step, 1))
        for u in (16, 8, 4, 2):
            if u <= cap and u <= steps:
                return u
        return 1

    def _replay_program(self, steps: int, padded_len: int, dtype,
                        handle_key, keep: str, stateful: bool,
                        zero_copy: bool = False) -> Callable:
        """Jitted T-step scan program; cached per (T, shape, dtype,
        handle, keep) like every other engine executable.

        Stateless replays on a qualifying pallas config scan the FUSED
        RING step (the steady-state persistent program: T ring
        collectives with VMEM updates, one dispatch); everything else
        scans the XLA collective step.  ``zero_copy`` (only meaningful
        with ``keep="last"`` on a zc-eligible config, see
        :meth:`_zc_pull_eligible`) skips the final all-gather and returns
        the store as the pulled value."""
        use_ring = self._replay_use_ring(dtype, handle_key, stateful)
        flat = self._flat_replay(padded_len, dtype, handle_key, stateful,
                                 steps)
        key = ("replay", steps, padded_len, str(dtype), handle_key, keep,
               stateful, use_ring, flat, zero_copy)
        with self._mu:
            prog = self._programs.get(key)
        if prog is not None:
            return prog
        if use_ring:
            return self._replay_ring_program(key, padded_len, dtype,
                                             handle_key, keep)

        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        axis = self.axis
        waxis = self.worker_axis
        store_spec = P(axis)
        grads_spec = (
            P(None, axis, None) if waxis is None else P(None, waxis, axis)
        )
        if stateful:
            n_state, sfn = self._stateful_handle(handle_key)

            def _body(store_l, *rest):
                state_l, grads_l = rest[:-1], rest[-1]

                def step(carry, g):
                    store_c, state_c = carry[0], carry[1:]
                    agg = _aggregate([g], axis, waxis)
                    new_store, new_state = sfn(store_c, tuple(state_c), agg)
                    out = (
                        lax.all_gather(new_store, axis, tiled=True)
                        if keep == "all" else 0.0
                    )
                    return (new_store, *new_state), out

                carry, outs = lax.scan(
                    step, (store_l, *state_l), grads_l[:, 0]
                )
                if keep == "last":
                    if zero_copy:
                        return carry
                    outs = lax.all_gather(carry[0], axis, tiled=True)
                return (*carry, outs)

            tails = () if (keep == "last" and zero_copy) else (
                (P(None, None),) if keep == "all" else (P(None),)
            )
            fn = shard_map(
                _body,
                mesh=self.mesh,
                in_specs=(store_spec, *([store_spec] * n_state), grads_spec),
                out_specs=(store_spec, *([store_spec] * n_state), *tails),
            )
            jitted = jax.jit(fn, donate_argnums=tuple(range(1 + n_state)))
        else:
            import jax.numpy as jnp

            handle = self._resolved_handle_fn(handle_key)

            def _step_out(new_store):
                if keep == "all":
                    return lax.all_gather(new_store, axis, tiled=True)
                return 0.0

            def _finish(new_store, outs):
                if keep == "last":
                    if zero_copy:
                        return new_store
                    outs = lax.all_gather(new_store, axis, tiled=True)
                return new_store, outs

            if flat:
                U = self._replay_unroll(padded_len, dtype, steps)

                def _body(store_l, grads_l):
                    # grads_l: [1, T*padded] — my T slabs, contiguous, so
                    # each step is an aligned dynamic_slice that fuses
                    # with the update (see _flat_replay).  The scan runs
                    # T//U outer iterations that each pull a U-step slab
                    # and apply U UNROLLED updates: the store carry stays
                    # resident across the inner steps, amortizing its
                    # read+write to 2P/U per step (traffic -> P + 2P/U;
                    # tools/profile_ops.py measured the engine sweep go
                    # 343 -> ~705 GB/s at 1MB steps and 445 -> ~905 at
                    # 16MB).  A non-divisible step count runs the
                    # remainder as an un-unrolled tail scan.
                    seq = grads_l[0]

                    def inner(carry, u_off):
                        g = lax.dynamic_slice(seq, (u_off,), (padded_len,))
                        new_store = handle(
                            carry, _aggregate([g], axis, waxis)
                        )
                        return new_store, _step_out(new_store)

                    bulk = (steps // U) * U
                    if U == 1:
                        new_store, outs = lax.scan(
                            inner, store_l,
                            jnp.arange(steps, dtype=jnp.int32) * padded_len,
                        )
                    else:
                        def outer(carry, t):
                            offs = (t * (U * padded_len)
                                    + jnp.arange(U, dtype=jnp.int32)
                                    * padded_len)
                            return lax.scan(inner, carry, offs,
                                            unroll=True)

                        new_store, outs = lax.scan(
                            outer, store_l,
                            jnp.arange(steps // U, dtype=jnp.int32),
                        )
                        if keep == "all":
                            # [T//U, U, L] -> [bulk, L]
                            outs = outs.reshape(
                                (bulk,) + outs.shape[2:]
                            )
                        if bulk < steps:
                            tail_offs = (
                                jnp.arange(bulk, steps, dtype=jnp.int32)
                                * padded_len
                            )
                            new_store, tail_outs = lax.scan(
                                inner, new_store, tail_offs
                            )
                            if keep == "all":
                                outs = jnp.concatenate(
                                    [outs, tail_outs], axis=0
                                )
                    return _finish(new_store, outs)

                grads_in_spec = P(axis, None)
            else:
                def _body(store_l, grads_l):
                    # grads_l: [T, 1, padded] (my worker row per step).
                    def step(carry, g):
                        new_store = handle(carry, _aggregate([g], axis, waxis))
                        return new_store, _step_out(new_store)

                    new_store, outs = lax.scan(step, store_l, grads_l[:, 0])
                    return _finish(new_store, outs)

                grads_in_spec = grads_spec

            if keep == "last" and zero_copy:
                out_specs = store_spec
            elif keep == "all":
                out_specs = (store_spec, P(None, None))
            else:
                out_specs = (store_spec, P(None))
            fn = shard_map(
                _body,
                mesh=self.mesh,
                in_specs=(store_spec, grads_in_spec),
                out_specs=out_specs,
            )
            jitted = jax.jit(fn, donate_argnums=(0,))
        with self._mu:
            self._programs[key] = jitted
        return jitted

    def _replay_ring_program(self, key, padded_len: int, dtype,
                             handle_key, keep: str) -> Callable:
        """T-step scan over the FUSED RING step: each iteration runs the
        ring RS + VMEM update (+ ring AG for keep="all") kernel; the
        collective_id is safely reused because scan iterations execute
        sequentially in SPMD lockstep and the kernel drains every
        semaphore to zero at exit.  keep="last" scans the push-only
        ring and gathers once at the end (the T×ZPush + pull shape)."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from ..ops.ring_collective import (
            derive_collective_id,
            ring_chunk_len,
            ring_push,
            ring_push_pull,
        )

        handle = self._resolved_handle_fn(handle_key)
        axis = self.axis
        waxis = self.worker_axis
        compress = self._ring_compress(dtype)
        cid = derive_collective_id(*key)
        interp = self._ring_interpret
        store_spec = P(axis)

        if waxis is not None:
            shard_fn = self._ring_2d_shard_fn(
                handle, padded_len, dtype, compress, cid
            )

            def _body(store_l, grads_l):
                def step(carry, g):
                    new = shard_fn(carry, g)
                    out = (
                        lax.all_gather(new, axis, tiled=True)
                        if keep == "all" else 0.0
                    )
                    return new, out

                new_store, outs = lax.scan(step, store_l, grads_l)
                if keep == "last":
                    outs = lax.all_gather(new_store, axis, tiled=True)
                return new_store, outs

            grads_spec = P(None, waxis, axis)
        else:
            n = self.num_shards
            chunk0 = padded_len // n
            kchunk = ring_chunk_len(padded_len, n, dtype,
                                    compress=compress)

            def _body(store_l, grads_l):
                s = store_l
                if kchunk != chunk0:
                    s = jnp.pad(s, (0, kchunk - chunk0))

                def step(carry, g):
                    gr, _ = _pad_ring_chunks(
                        g[0].reshape(n, chunk0), None, kchunk, chunk0
                    )
                    if keep == "all":
                        new, pulled = ring_push_pull(
                            gr, carry, handle, axis, n,
                            collective_id=cid, compress=compress,
                            interpret=interp,
                        )
                        return new, _slice_ring_pulled(
                            pulled, n, kchunk, chunk0
                        )
                    new = ring_push(gr, carry, handle, axis, n,
                                    collective_id=cid, compress=compress,
                                    interpret=interp)
                    return new, 0.0

                s, outs = lax.scan(step, s, grads_l)
                s_out = s[:chunk0] if kchunk != chunk0 else s
                if keep == "last":
                    outs = lax.all_gather(s_out, axis, tiled=True)
                return s_out, outs

            grads_spec = P(None, axis, None)

        fn = shard_map(
            _body,
            mesh=self.mesh,
            in_specs=(store_spec, grads_spec),
            out_specs=(
                store_spec,
                P(None, None) if keep == "all" else P(None),
            ),
        )
        jitted = jax.jit(fn, donate_argnums=(0,))
        with self._mu:
            self._programs[key] = jitted
        return jitted

    def pull(self, name: str):
        t0 = time.perf_counter()
        bucket = self._buckets[name]
        if name in self._pinned_pulls:
            prog = self._program(
                "pull_pinned", bucket.padded_len, bucket.dtype,
                "_pull_pinned",
            )
            with self._bucket_mu[name]:
                # Re-fetch under the lock: a concurrent unregister may
                # have popped the entry since the unlocked check above.
                pinned = self._pinned_pulls.get(name)
                if pinned is not None:
                    pulled = prog(pinned, self._stores[name])
                    self._pinned_pulls[name] = pulled
                    self._observe(name, "pull", bucket, t0)
                    # Padded length: the caller registered the buffer and
                    # owns its layout — slicing here would materialize a
                    # copy and break the address-identity contract.
                    return pulled
        prog = self._program("pull", bucket.padded_len, bucket.dtype, "_pull")
        # Bucket lock: a concurrent push donates the store buffer; reading
        # it unlocked could hand an already-donated array to the pull
        # program.  Dispatch is async, so this only serializes enqueue.
        with self._bucket_mu[name]:
            pulled = prog(self._stores[name])
        self._observe(name, "pull", bucket, t0)
        return pulled[: bucket.total_len]

    def register_pull_buffer(self, name: str):
        """Pin a persistent pull-output buffer for ``name`` — the
        PinMemory / ``w_pool_`` contract of the reference's UCX van
        (ucx_van.h:603-623): after this, every ``pull(name)`` delivers the
        gathered store into the SAME device buffer (donation aliases the
        previous output to the next), the collective analog of responses
        RDMA-written to the worker's registered address
        (test_benchmark.cc:169-181).  Returns the initial (zeroed,
        padded-length, replicated) buffer.

        The usual registered-buffer contract applies: at most one
        outstanding pull per bucket, and the caller must not hold stale
        references across pulls (the old array's buffer is donated)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        bucket = self._buckets[name]
        # _place handles multi-process meshes (device_put cannot target
        # non-addressable devices).
        buf = self._place(
            np.zeros(bucket.padded_len, dtype=np.dtype(bucket.dtype)),
            NamedSharding(self.mesh, P(None)),
        )
        with self._bucket_mu[name]:
            self._pinned_pulls[name] = buf
        return buf

    def unregister_pull_buffer(self, name: str) -> None:
        with self._bucket_mu[name]:
            self._pinned_pulls.pop(name, None)

    def pinned_pull_buffer(self, name: str):
        """The current pinned output (identity checks / zero-copy reads)."""
        with self._bucket_mu[name]:
            return self._pinned_pulls.get(name)

    def store_array(self, name: str):
        """A consistent snapshot of the sharded server state (for
        checkpointing).

        Copied under the bucket lock: the live buffer may be donated by
        the next push the moment the lock is released, so handing out the
        live reference would hand out a to-be-deleted array."""
        import jax.numpy as jnp

        with self._bucket_mu[name]:
            return jnp.copy(self._stores[name])

    def store_spec(self, name: str):
        """Shape/dtype/sharding of a store without copying it (restore
        targets)."""
        import jax

        with self._bucket_mu[name]:
            arr = self._stores[name]
            return jax.ShapeDtypeStruct(
                arr.shape, arr.dtype, sharding=arr.sharding
            )

    def set_store_array(self, name: str, value) -> None:
        """Restore server state (checkpoint resume).

        Accepts a host array (placed onto the bucket's sharding) or a
        ``jax.Array`` already laid out for this store (multi-host orbax
        restores pass these through untouched — fetching them to host
        would fail across non-addressable devices).
        """
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        bucket = self._buckets[name]
        sharding = NamedSharding(self.mesh, P(self.axis))
        if isinstance(value, jax.Array):
            if (tuple(value.shape) == (bucket.total_len,)
                    and bucket.total_len != bucket.padded_len):
                # Fleet-portable DEVICE restore (orbax v2): a global
                # LOGICAL array saved by any shard count — pad to THIS
                # engine's padded length and reshard, all device-side
                # (multi-host arrays are not host-fetchable).
                import jax.numpy as jnp

                value = jnp.pad(
                    value.astype(bucket.dtype),
                    (0, bucket.padded_len - bucket.total_len),
                )
            if tuple(value.shape) == (bucket.padded_len,):
                log.check_eq(value.dtype, np.dtype(bucket.dtype),
                             "bad restore dtype")
                placed = jax.device_put(value, sharding)
                with self._bucket_mu[name]:
                    self._stores[name] = placed
                return
        arr = np.zeros(bucket.padded_len, dtype=np.dtype(bucket.dtype))
        flat = np.asarray(value).reshape(-1)
        log.check(len(flat) in (bucket.total_len, bucket.padded_len),
                  "bad restore length")
        arr[: len(flat)] = flat
        placed = self._place(arr, sharding)
        with self._bucket_mu[name]:
            self._stores[name] = placed

    def reshard(self, mesh, axis_name: Optional[str] = None) -> None:
        """Re-lay every registered bucket (store + optimizer state) onto
        a new mesh — the engine-side ELASTIC tier.  See
        :meth:`reshard_staged` for the stage/commit split that
        coordinated multi-engine recuts use for pair atomicity.

        The reference's recovery path re-admits a node into the same
        roster under the dead node's id (van.cc:266-332); on the
        collective data plane the roster IS the mesh, so scaling the
        server fleet up/down means resharding the live state onto the
        new device set.  Key-range shards are recut for the new shard
        count (GetServerKeyRanges semantics, postoffice.cc:257-268),
        optimizer state moves with the stores, and compiled programs are
        dropped and rebuilt lazily on first touch — exactly like
        first-push rendezvous after a topology change.

        State moves via a host round trip on either kind of mesh.  On a
        multi-process mesh (old or new side) reshard is a COLLECTIVE:
        every participating process must call it with the same new mesh
        in the same order — the snapshot assembles non-addressable
        shards with process_allgather and the rebuild scatters through
        the callback placement path.  (Roster-level recovery keeps the
        mesh: a replacement inherits the dead node's id and devices, so
        no reshard fires; this is the SCALE-change tier the launcher or
        app invokes when the server fleet itself grows or shrinks.)

        A 2-D engine (``worker_axis``) reshards onto any new mesh
        carrying both its axes — worker fan-in and server-shard count
        both recut.  Callers' grads arrays must use the NEW worker
        fan-in after this returns.
        """
        with self.reshard_staged(mesh, axis_name) as commit:
            commit()

    @contextlib.contextmanager
    def reshard_staged(self, mesh, axis_name: Optional[str] = None):
        """Stage a recut and yield its zero-failure commit closure.

        The snapshot + new-mesh placements (everything that can fail,
        including the multi-process collectives) run on entry; the
        yielded ``commit()`` performs plain field/dict assignments only.
        A coordinated multi-engine recut stages EVERY engine first and
        only then commits them all, so a failure in any engine's staging
        aborts the whole group with every engine untouched — the
        pair-level crash-consistency contract of
        ``reshard_engines`` (tests/test_reshard_crash.py).  Bucket locks
        are held until the context exits."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .placement import (
            local_shard_count,
            mesh_is_multiprocess,
            to_host_global,
        )

        new_multiprocess = mesh_is_multiprocess(mesh)
        axis = axis_name or self.axis
        if isinstance(axis, (tuple, list)):
            axis = tuple(axis)
        kv_axes = axis if isinstance(axis, tuple) else (axis,)
        for a in kv_axes:
            log.check(a in mesh.axis_names,
                      f"kv axis {a!r} not in new mesh")
        if self.worker_axis is not None:
            log.check(
                self.worker_axis in mesh.axis_names,
                f"worker axis {self.worker_axis!r} not in new mesh "
                f"(a 2-D engine stays 2-D across reshards)",
            )
            log.check(self.worker_axis not in kv_axes,
                      "worker_axis must differ from the kv axis")
        with self._mu:
            names = list(self._buckets)
        ordered = sorted(names)
        for n in ordered:
            self._bucket_mu[n].acquire()
        try:
            # Snapshot all live state to host while every bucket is
            # quiesced (the donated buffers cannot be in flight).  On a
            # multi-process OLD mesh this is the collective gather leg:
            # iterate in SORTED order so every process issues the same
            # allgather sequence regardless of registration order (the
            # buckets themselves — and their opt-state presence — must
            # already be symmetric across processes, as all engine
            # collectives require).
            old_mp = self._multiprocess
            names = ordered
            snap = {}
            for n in names:
                b = self._buckets[n]
                store = to_host_global(
                    self._stores[n], old_mp
                )[: b.total_len].copy()
                opt = None
                if n in self._opt_states:
                    opt = (
                        self._opt_kinds[n],
                        [to_host_global(a, old_mp).copy()
                         for a in self._opt_states[n]],
                    )
                snap[n] = (b, store, opt)

            # STAGE: build every new placement against the NEW mesh
            # without touching engine state.  Any failure in this block
            # aborts with the engine fully on the OLD mesh — a crashed
            # or failed recut must never leave torn stores (the
            # crash-consistency contract of the cluster-coordinated
            # reshard; reference analog: recovery tolerates death at
            # any moment, van.cc:266-332).
            from .placement import place_host_array

            new_num_shards = int(
                np.prod([mesh.shape[a] for a in kv_axes])
            )
            new_num_workers = (
                mesh.shape[self.worker_axis]
                if self.worker_axis is not None
                else new_num_shards
            )
            sharding = NamedSharding(mesh, P(axis))

            def _nplace(host_arr, shard_spec):
                return place_host_array(
                    mesh, host_arr, shard_spec, new_multiprocess
                )

            def _repad(flat_host, total, padded, dt):
                out = np.zeros(padded, dtype=np.dtype(dt))
                out[:total] = flat_host[:total]
                return _nplace(out, sharding)

            staged = {}
            for n in names:
                b, store, opt = snap[n]
                padded = (
                    -(-b.total_len // new_num_shards) * new_num_shards
                )
                entry = {
                    "padded": padded,
                    "store": _repad(store, b.total_len, padded, b.dtype),
                }
                if n in self._pinned_pulls:
                    # Re-pin on the new mesh: the old pinned buffer's
                    # devices/shape no longer match (a fresh address —
                    # same as re-registering after recovery).
                    entry["pinned"] = _nplace(
                        np.zeros(padded, dtype=np.dtype(b.dtype)),
                        NamedSharding(mesh, P(None)),
                    )
                if opt is not None:
                    kind, arrs = opt
                    if kind in ("sgd_momentum", "adagrad"):
                        state = (
                            _repad(arrs[0], b.total_len, padded, b.dtype),
                        )
                    else:  # adam: m, v, per-shard step counter
                        step = float(arrs[2][0]) if len(arrs[2]) else 0.0
                        state = (
                            _repad(arrs[0], b.total_len, padded, b.dtype),
                            _repad(arrs[1], b.total_len, padded, b.dtype),
                            _nplace(
                                np.full(new_num_shards, step, np.float32),
                                sharding,
                            ),
                        )
                    entry["opt"] = state
                staged[n] = entry

            # COMMIT closure: plain field/dict assignments only —
            # cannot fail partway, so observers see the old mesh or the
            # new one, never a mixture.
            def commit() -> None:
                self.mesh = mesh
                self.axis = axis
                self.num_shards = new_num_shards
                self.num_workers = new_num_workers
                self._multiprocess = new_multiprocess
                self._mesh_platform = next(
                    iter(mesh.devices.flat)
                ).platform
                self._ring_interpret = self._mesh_platform != "tpu"
                self._local_shard_count = (
                    local_shard_count(mesh) if new_multiprocess
                    else new_num_shards
                )
                with self._mu:
                    self._programs.clear()
                for n in names:
                    b = snap[n][0]
                    entry = staged[n]
                    b.padded_len = entry["padded"]
                    self._stores[n] = entry["store"]
                    if "pinned" in entry:
                        self._pinned_pulls[n] = entry["pinned"]
                    if "opt" in entry:
                        self._opt_states[n] = entry["opt"]
                    else:
                        self._opt_states.pop(n, None)
                        self._opt_kinds.pop(n, None)

            yield commit
        finally:
            for n in reversed(ordered):
                self._bucket_mu[n].release()

    def block(self, name: Optional[str] = None) -> None:
        """Wait for outstanding device work (ZPush/Wait semantics)."""
        if name is not None:
            names = [name]
        else:
            with self._mu:
                names = list(self._stores)
        for n in names:
            # Held across the wait so no concurrent push can donate the
            # array between the read and block_until_ready.
            with self._bucket_mu[n]:
                self._stores[n].block_until_ready()
