"""Elastic reshard across OS processes (VERDICT r02 #4).

Two jax.distributed processes shrink and grow the engine's kv axis live
— the deployment shape the reference's recovery path serves
(van.cc:266-332), on the collective data plane."""

import os
import subprocess
import sys

import pytest

from pslite_tpu.utils.network import get_available_port


def test_reshard_across_two_processes():
    port = get_available_port()
    child = os.path.join(os.path.dirname(__file__), "reshard_child.py")
    procs = []
    for rank in range(2):
        env = dict(
            os.environ,
            RESHARD_RANK=str(rank),
            RESHARD_COORD=f"127.0.0.1:{port}",
        )
        # The child pins its own platform/device-count env before jax
        # import; scrub any inherited conftest pin.
        env.pop("XLA_FLAGS", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, child],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out.decode())
    if any("MULTIPROC_UNSUPPORTED" in o for o in outs):
        pytest.skip("this jaxlib's CPU backend lacks multiprocess "
                    "computations (environment limitation)")
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"reshard child failed:\n{out}"
    assert sum("RESHARD_OK" in o for o in outs) == 2, outs
