"""CollectiveEngine / SparseEngine numerics on an 8-device virtual CPU mesh.

Validates that the ICI data plane reproduces the reference's server
aggregation semantics (push => sum across workers, pull => broadcast;
kv_app.h:430-452) as jitted reduce-scatter/all-gather collectives.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from pslite_tpu.parallel import CollectiveEngine, default_mesh
from pslite_tpu.parallel.sparse import SparseEngine


@pytest.fixture(scope="module")
def mesh():
    m = default_mesh()
    assert m.shape["kv"] == 8, "conftest must provide 8 virtual devices"
    return m


def test_dense_push_pull_aggregates(mesh):
    eng = CollectiveEngine(mesh=mesh)
    keys = np.arange(4, dtype=np.uint64)
    val_len = 100  # total 400, not divisible by 8 -> exercises padding
    eng.register_dense("b0", keys, val_len)
    W = eng.num_shards
    base = np.arange(4 * val_len, dtype=np.float32)
    grads = np.stack([(w + 1) * base for w in range(W)])  # [W, total]
    pulled = np.asarray(eng.push_pull("b0", grads))
    expected = base * sum(range(1, W + 1))
    np.testing.assert_allclose(pulled, expected, rtol=1e-5)


def test_dense_push_accumulates_then_pull(mesh):
    eng = CollectiveEngine(mesh=mesh)
    keys = np.arange(3, dtype=np.uint64)
    eng.register_dense("b1", keys, 64)
    ones = np.ones(3 * 64, dtype=np.float32)
    eng.push("b1", ones)  # broadcast to all 8 workers -> sum = 8
    eng.push("b1", ones)
    out = np.asarray(eng.pull("b1"))
    np.testing.assert_allclose(out, 16 * ones)


def test_dense_sgd_handle(mesh):
    eng = CollectiveEngine(mesh=mesh, server_handle="sgd:0.5")
    keys = np.arange(2, dtype=np.uint64)
    init = np.full(2 * 8, 10.0, dtype=np.float32)
    eng.register_dense("b2", keys, 8, init=init)
    grads = np.ones((8, 16), dtype=np.float32)  # sum = 8
    pulled = np.asarray(eng.push_pull("b2", grads))
    np.testing.assert_allclose(pulled, 10.0 - 0.5 * 8.0 * np.ones(16))


def test_fused_sgd_momentum_handle_parity(mesh):
    """The Pallas sgd+momentum kernel fused into the push program must
    match the host momentum recurrence over several steps."""
    lr, mu = 0.1, 0.9
    eng = CollectiveEngine(
        mesh=mesh, server_handle=f"sgd_momentum:{lr},{mu}"
    )
    keys = np.arange(3, dtype=np.uint64)
    val_len = 100  # padding exercised (300 % 8 != 0)
    init = np.linspace(1, 2, 3 * val_len).astype(np.float32)
    eng.register_dense("sgdm", keys, val_len, init=init)
    W = eng.num_shards
    rng = np.random.default_rng(7)

    ref_store = init.copy()
    ref_mom = np.zeros_like(ref_store)
    for step in range(4):
        grads = rng.normal(size=(W, 3 * val_len)).astype(np.float32)
        pulled = np.asarray(eng.push_pull("sgdm", grads))
        agg = grads.sum(axis=0)
        ref_mom = mu * ref_mom + agg
        ref_store = ref_store - lr * ref_mom
        np.testing.assert_allclose(pulled, ref_store, rtol=2e-5, atol=2e-5)


def test_fused_adam_handle_parity(mesh):
    """The Pallas Adam kernel (with bias correction via the step counter)
    must match the host Adam recurrence."""
    lr, b1, b2, eps = 1e-2, 0.9, 0.999, 1e-8
    eng = CollectiveEngine(mesh=mesh)
    keys = np.arange(2, dtype=np.uint64)
    val_len = 64
    init = np.full(2 * val_len, 5.0, np.float32)
    eng.register_dense("adam", keys, val_len, init=init)
    W = eng.num_shards
    rng = np.random.default_rng(11)

    ref_store = init.copy().astype(np.float64)
    ref_m = np.zeros_like(ref_store)
    ref_v = np.zeros_like(ref_store)
    for step in range(1, 4):
        grads = rng.normal(size=(W, 2 * val_len)).astype(np.float32)
        pulled = np.asarray(
            eng.push_pull("adam", grads, handle=f"adam:{lr}")
        )
        g = grads.sum(axis=0).astype(np.float64)
        ref_m = b1 * ref_m + (1 - b1) * g
        ref_v = b2 * ref_v + (1 - b2) * g * g
        alpha = lr * np.sqrt(1 - b2 ** step) / (1 - b1 ** step)
        ref_store = ref_store - alpha * ref_m / (np.sqrt(ref_v) + eps)
        np.testing.assert_allclose(pulled, ref_store, rtol=1e-4, atol=1e-4)


def test_fused_handle_push_then_pull(mesh):
    """Stateful handles work on the separate push/pull ops too, and the
    returned token is blockable."""
    eng = CollectiveEngine(mesh=mesh, server_handle="sgd_momentum:0.5,0.0")
    keys = np.arange(1, dtype=np.uint64)
    init = np.zeros(32, np.float32)
    eng.register_dense("tok", keys, 32, init=init)
    token = eng.push("tok", np.ones(32, np.float32))  # agg = 8
    token.block_until_ready()
    out = np.asarray(eng.pull("tok"))
    np.testing.assert_allclose(out, -0.5 * 8.0 * np.ones(32))


def test_fused_handle_kind_switch_rejected(mesh):
    eng = CollectiveEngine(mesh=mesh, server_handle="sgd_momentum")
    keys = np.arange(1, dtype=np.uint64)
    eng.register_dense("sw", keys, 16)
    eng.push("sw", np.ones(16, np.float32))
    with pytest.raises(Exception, match="cannot"):
        eng.push("sw", np.ones(16, np.float32), handle="adam")


def test_fused_handle_checkpoint_resume(mesh, tmp_path):
    """Optimizer state (momentum) survives save/restore: resuming after 2
    steps matches 4 uninterrupted steps."""
    from pslite_tpu import checkpoint

    handle = "sgd_momentum:0.1,0.9"
    keys = np.arange(2, dtype=np.uint64)
    val_len = 32
    init = np.ones(2 * val_len, np.float32)
    rng = np.random.default_rng(3)
    grads = [
        rng.normal(size=(8, 2 * val_len)).astype(np.float32)
        for _ in range(4)
    ]

    ref = CollectiveEngine(mesh=mesh, server_handle=handle)
    ref.register_dense("ck", keys, val_len, init=init)
    for g in grads:
        expected = np.asarray(ref.push_pull("ck", g))

    eng1 = CollectiveEngine(mesh=mesh, server_handle=handle)
    eng1.register_dense("ck", keys, val_len, init=init)
    for g in grads[:2]:
        eng1.push_pull("ck", g)
    path = str(tmp_path / "state")
    checkpoint.save_engine(eng1, path)

    eng2 = CollectiveEngine(mesh=mesh, server_handle=handle)
    eng2.register_dense("ck", keys, val_len, init=init)
    checkpoint.restore_engine(eng2, path)
    for g in grads[2:]:
        resumed = np.asarray(eng2.push_pull("ck", g))
    np.testing.assert_allclose(resumed, expected, rtol=1e-5, atol=1e-5)


def test_two_axis_mesh_decouples_workers_from_shards():
    """2-D (dp, kv) mesh: 2 worker rows x 4 server shards — the W != S
    asymmetry of the reference, on the collective path."""
    from pslite_tpu.parallel.mesh import make_mesh

    mesh2 = make_mesh((2, 4), ("dp", "kv"))
    eng = CollectiveEngine(mesh=mesh2, worker_axis="dp")
    assert eng.num_workers == 2 and eng.num_shards == 4
    keys = np.arange(3, dtype=np.uint64)
    val_len = 40
    eng.register_dense("b2d", keys, val_len)
    rng = np.random.default_rng(21)
    grads = rng.normal(size=(2, 3 * val_len)).astype(np.float32)
    pulled = np.asarray(eng.push_pull("b2d", grads))
    np.testing.assert_allclose(pulled, grads.sum(axis=0), rtol=1e-5)

    # push-only + pull round trip accumulates.
    token = eng.push("b2d", grads)
    token.block_until_ready()
    out = np.asarray(eng.pull("b2d"))
    np.testing.assert_allclose(out, 2 * grads.sum(axis=0), rtol=1e-5)

    # Wrong worker-row count must fail loud, not silently drop rows —
    # including the pre-sharded device-array fast path.
    import jax

    bad_host = np.ones((4, eng.bucket("b2d").padded_len), np.float32)
    with pytest.raises(Exception, match="bad worker dim"):
        eng.push_pull("b2d", bad_host)
    bad_dev = jax.device_put(bad_host)
    with pytest.raises(Exception, match="bad worker dim"):
        eng.push_pull("b2d", bad_dev)


def test_push_pull_group_matches_singles(mesh):
    """One grouped program over several buckets == per-bucket push_pulls
    (same aggregation, one dispatch)."""
    eng_a = CollectiveEngine(mesh=mesh)
    eng_b = CollectiveEngine(mesh=mesh)
    rng = np.random.default_rng(9)
    names, glist = [], []
    for i, val_len in enumerate((40, 100, 16)):
        name = f"grp{i}"
        keys = np.arange(2, dtype=np.uint64) + 10 * i
        eng_a.register_dense(name, keys, val_len)
        eng_b.register_dense(name, keys, val_len)
        g = rng.normal(size=(8, 2 * val_len)).astype(np.float32)
        names.append(name)
        glist.append(g)
    grouped = eng_a.push_pull_group(names, glist)
    singles = [eng_b.push_pull(n, g) for n, g in zip(names, glist)]
    for got, want in zip(grouped, singles):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5
        )
    # Second grouped round accumulates in the stores like singles do.
    grouped2 = eng_a.push_pull_group(names, glist)
    singles2 = [eng_b.push_pull(n, g) for n, g in zip(names, glist)]
    for got, want in zip(grouped2, singles2):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5
        )


def test_dense_bfloat16_bucket(mesh):
    """bfloat16 buckets (the MXU-native dtype) work through the fused
    push_pull path with tolerable precision."""
    import jax.numpy as jnp

    eng = CollectiveEngine(mesh=mesh)
    keys = np.arange(2, dtype=np.uint64)
    val_len = 64
    eng.register_dense("bf16", keys, val_len, dtype=jnp.bfloat16)
    W = eng.num_shards
    grads = np.ones((W, 2 * val_len), dtype=np.float32)
    pulled = np.asarray(eng.push_pull("bf16", grads), dtype=np.float32)
    np.testing.assert_allclose(pulled, float(W), rtol=1e-2)


def test_dense_init_roundtrip(mesh):
    eng = CollectiveEngine(mesh=mesh)
    keys = np.arange(5, dtype=np.uint64)
    init = np.random.default_rng(1).normal(size=5 * 32).astype(np.float32)
    eng.register_dense("b3", keys, 32, init=init)
    np.testing.assert_allclose(np.asarray(eng.pull("b3")), init, rtol=1e-6)


def test_sparse_push_pull(mesh):
    eng = SparseEngine(mesh)
    rng = np.random.default_rng(7)
    num_rows, dim, n = 37, 4, 6
    eng.register_sparse("emb", num_rows, dim)
    W = eng.num_shards
    # Skewed indices with duplicates within and across workers.
    idx = rng.integers(0, num_rows, size=(W, n)).astype(np.int32)
    idx[:, 0] = 3  # hot row pushed by every worker
    grads = rng.normal(size=(W, n, dim)).astype(np.float32)

    eng.push("emb", idx, grads)

    # Host reference: scatter-add.
    ref = np.zeros((num_rows, dim), dtype=np.float32)
    for w in range(W):
        for i in range(n):
            ref[idx[w, i]] += grads[w, i]

    pulled = np.asarray(eng.pull("emb", idx))  # [W, n, dim]
    for w in range(W):
        np.testing.assert_allclose(pulled[w], ref[idx[w]], rtol=1e-4,
                                   atol=1e-5)


def test_sparse_pull_zero_init(mesh):
    eng = SparseEngine(mesh)
    eng.register_sparse("z", 16, 2)
    idx = np.zeros((8, 3), dtype=np.int32)
    out = np.asarray(eng.pull("z", idx))
    assert out.shape == (8, 3, 2)
    np.testing.assert_array_equal(out, 0)


def test_sparse_row_adagrad(mesh):
    """Fused row-wise Adagrad (DLRM embedding optimizer): per-row
    aggregate gradient -> accumulator += mean(G^2) -> row -= lr*G/
    (sqrt(acc)+eps); untouched rows unchanged; state persists across
    pushes."""
    eng = SparseEngine(mesh)
    rng = np.random.default_rng(11)
    num_rows, dim, n = 23, 4, 5
    init = rng.normal(size=(num_rows, dim)).astype(np.float32)
    eng.register_sparse("emb", num_rows, dim, init=init)
    W = eng.num_shards
    lr, eps = 0.1, 1e-8

    ref = init.copy().astype(np.float64)
    acc = np.zeros(num_rows, np.float64)
    for step in range(3):
        idx = rng.integers(0, num_rows, size=(W, n)).astype(np.int32)
        idx[:, 0] = 7  # hot row from every worker
        grads = rng.normal(size=(W, n, dim)).astype(np.float32)
        eng.push("emb", idx, grads, handle=f"row_adagrad:{lr},{eps}")

        G = np.zeros((num_rows, dim), np.float64)
        for w in range(W):
            for i in range(n):
                G[idx[w, i]] += grads[w, i]
        acc += np.mean(G ** 2, axis=1)
        denom = np.sqrt(acc)[:, None] + eps
        step_arr = np.where(denom > eps, lr * G / denom, 0.0)
        ref -= step_arr

    all_idx = np.tile(np.arange(num_rows, dtype=np.int32), (W, 1))
    pulled = np.asarray(eng.pull("emb", all_idx))[0]
    np.testing.assert_allclose(pulled, ref, rtol=1e-4, atol=1e-4)

    # Accumulator snapshot / restore roundtrip.
    snap = np.asarray(eng.acc_array("emb"))
    eng.set_acc_array("emb", snap)
    assert snap.shape == (eng.table("emb").rows_per_shard * W,)


def test_fused_adagrad_handle_parity(mesh):
    """The fused Adagrad kernel as a dense server handle must match the
    host recurrence (dense twin of the sparse row_adagrad)."""
    lr, eps = 0.05, 1e-8
    eng = CollectiveEngine(mesh=mesh)
    keys = np.arange(3, dtype=np.uint64)
    val_len = 100
    init = np.linspace(-1, 1, 3 * val_len).astype(np.float32)
    eng.register_dense("ag", keys, val_len, init=init)
    W = eng.num_shards
    rng = np.random.default_rng(13)

    ref_store = init.copy().astype(np.float64)
    ref_acc = np.zeros_like(ref_store)
    for _ in range(4):
        grads = rng.normal(size=(W, 3 * val_len)).astype(np.float32)
        pulled = np.asarray(
            eng.push_pull("ag", grads, handle=f"adagrad:{lr},{eps}")
        )
        g = grads.sum(axis=0).astype(np.float64)
        ref_acc = ref_acc + g * g
        ref_store = ref_store - lr * g / (np.sqrt(ref_acc) + eps)
        np.testing.assert_allclose(pulled, ref_store, rtol=1e-4, atol=1e-4)


def test_sparse_group_ops_match_single(mesh):
    """push_group/pull_group over heterogeneous tables (different rows,
    dims, batch sizes) match per-table push/pull — one dispatch for the
    many-embedding-tables recommender pattern."""
    specs = {"a": (17, 4, 3), "b": (33, 8, 5), "c": (9, 2, 2)}
    rng = np.random.default_rng(21)

    grp = SparseEngine(mesh)
    one = SparseEngine(mesh)
    W = grp.num_shards
    data = {}
    for n, (rows, dim, nb) in specs.items():
        init = rng.normal(size=(rows, dim)).astype(np.float32)
        grp.register_sparse(n, rows, dim, init=init)
        one.register_sparse(n, rows, dim, init=init)
        idx = rng.integers(0, rows, size=(W, nb)).astype(np.int32)
        g = rng.normal(size=(W, nb, dim)).astype(np.float32)
        data[n] = (idx, g)

    names = list(specs)
    # Plain scatter-add group push.
    grp.push_group(names, [data[n][0] for n in names],
                   [data[n][1] for n in names])
    for n in names:
        one.push(n, *data[n])
    outs = grp.pull_group(names, [data[n][0] for n in names])
    for n, out in zip(names, outs):
        want = np.asarray(one.pull(n, data[n][0]))
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4,
                                   atol=1e-5)

    # Row-adagrad group push (accumulators advance per table).
    grp.push_group(names, [data[n][0] for n in names],
                   [data[n][1] for n in names], handle="row_adagrad:0.1")
    for n in names:
        one.push(n, *data[n], handle="row_adagrad:0.1")
    for n in names:
        rows = specs[n][0]
        all_idx = np.broadcast_to(
            np.arange(rows, dtype=np.int32), (W, rows)
        )
        np.testing.assert_allclose(
            np.asarray(grp.pull(n, all_idx))[0],
            np.asarray(one.pull(n, all_idx))[0],
            rtol=1e-4, atol=1e-5, err_msg=n,
        )
        np.testing.assert_allclose(
            np.asarray(grp.acc_array(n)), np.asarray(one.acc_array(n)),
            rtol=1e-5, atol=1e-6, err_msg=n,
        )


def test_pinned_pull_buffer_address_identity(mesh):
    """PinMemory / w_pool_ analog (ucx_van.h:603-623): once a pull buffer
    is registered, every pull lands the gathered store at the SAME device
    addresses — the collective version of the reference's registered
    recv-buffer identity check (test_benchmark.cc:169-181)."""
    eng = CollectiveEngine(mesh=mesh)
    keys = np.arange(4, dtype=np.uint64)
    eng.register_dense("pin0", keys, 64)  # total 256, divisible by 8
    eng.register_pull_buffer("pin0")

    def addrs(arr):
        return sorted(
            s.data.unsafe_buffer_pointer() for s in arr.addressable_shards
        )

    ones = np.ones(4 * 64, dtype=np.float32)
    eng.push("pin0", ones)  # each of 8 workers pushes ones -> sum = 8
    p1 = eng.pull("pin0")
    a1 = addrs(p1)
    np.testing.assert_allclose(np.asarray(p1), 8 * ones)
    eng.push("pin0", ones)
    p2 = eng.pull("pin0")
    a2 = addrs(p2)
    np.testing.assert_allclose(np.asarray(p2), 16 * ones)
    assert a1 == a2, f"pull output moved: {a1} vs {a2}"
    # A third pull without an intervening push: same address again.
    p3 = eng.pull("pin0")
    assert addrs(p3) == a1
    np.testing.assert_allclose(np.asarray(p3), 16 * ones)

    # Unregister restores plain (sliced, non-pinned) pulls.
    eng.unregister_pull_buffer("pin0")
    p4 = eng.pull("pin0")
    np.testing.assert_allclose(np.asarray(p4), 16 * ones)


def test_pinned_pull_padded_bucket(mesh):
    """Padding: the pinned buffer is padded-length; values beyond
    total_len are gather artifacts the caller ignores."""
    eng = CollectiveEngine(mesh=mesh)
    keys = np.arange(3, dtype=np.uint64)
    eng.register_dense("pin1", keys, 33)  # total 99 -> padded 104
    eng.register_pull_buffer("pin1")
    base = np.arange(99, dtype=np.float32)
    grads = np.stack([base for _ in range(eng.num_shards)])
    eng.push("pin1", grads)
    pulled = eng.pull("pin1")
    assert pulled.shape[0] == eng._buckets["pin1"].padded_len
    np.testing.assert_allclose(
        np.asarray(pulled)[:99], 8 * base, rtol=1e-6
    )


def test_replay_matches_sequential_push_pull(mesh):
    """T fused scan steps must equal T separate push_pull dispatches,
    per step, for a stateless handle."""
    keys = np.arange(3, dtype=np.uint64)
    val_len = 100  # padded
    rng = np.random.default_rng(31)
    W = 8
    T = 4
    seq = rng.normal(size=(T, W, 3 * val_len)).astype(np.float32)

    ref = CollectiveEngine(mesh=mesh)
    ref.register_dense("rp_ref", keys, val_len)
    expected = [np.asarray(ref.push_pull("rp_ref", seq[t]))
                for t in range(T)]

    eng = CollectiveEngine(mesh=mesh)
    eng.register_dense("rp", keys, val_len)
    pulled = np.asarray(eng.replay("rp", seq))
    assert pulled.shape == (T, 3 * val_len)
    for t in range(T):
        np.testing.assert_allclose(pulled[t], expected[t], rtol=1e-5)
    # Store state advanced identically: one more single step agrees.
    extra = rng.normal(size=(W, 3 * val_len)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(eng.push_pull("rp", extra)),
        np.asarray(ref.push_pull("rp_ref", extra)),
        rtol=1e-5,
    )


def test_replay_keep_last_and_broadcast_grads(mesh):
    """keep='last' returns only the final pull; [T, total] grads
    broadcast to all workers like the single-step path."""
    keys = np.arange(2, dtype=np.uint64)
    eng = CollectiveEngine(mesh=mesh)
    eng.register_dense("rpl", keys, 64)
    T = 5
    seq = np.ones((T, 2 * 64), dtype=np.float32)
    out = np.asarray(eng.replay("rpl", seq, keep="last"))
    # Each step adds sum-over-8-workers of ones.
    np.testing.assert_allclose(out, T * 8 * np.ones(128, np.float32))


def test_replay_stateful_adam(mesh):
    """Replay threads optimizer state through the scan: must match the
    same steps dispatched one by one."""
    keys = np.arange(2, dtype=np.uint64)
    val_len = 64
    rng = np.random.default_rng(33)
    T = 3
    seq = rng.normal(size=(T, 8, 2 * val_len)).astype(np.float32)
    init = np.linspace(0, 1, 2 * val_len).astype(np.float32)

    ref = CollectiveEngine(mesh=mesh, server_handle="adam:0.01")
    ref.register_dense("ra_ref", keys, val_len, init=init)
    expected = [np.asarray(ref.push_pull("ra_ref", seq[t]))
                for t in range(T)]

    eng = CollectiveEngine(mesh=mesh, server_handle="adam:0.01")
    eng.register_dense("ra", keys, val_len, init=init)
    pulled = np.asarray(eng.replay("ra", seq))
    for t in range(T):
        np.testing.assert_allclose(pulled[t], expected[t],
                                   rtol=2e-5, atol=2e-5)


def test_replay_two_axis_mesh():
    """Replay on a 2-D (dp, kv) mesh: worker reduction over dp inside
    the scan."""
    from pslite_tpu.parallel.mesh import make_mesh

    mesh2 = make_mesh((2, 4), ("dp", "kv"))
    eng = CollectiveEngine(mesh=mesh2, worker_axis="dp")
    keys = np.arange(2, dtype=np.uint64)
    eng.register_dense("rp2d", keys, 40)
    rng = np.random.default_rng(35)
    T = 3
    seq = rng.normal(size=(T, 2, 80)).astype(np.float32)
    pulled = np.asarray(eng.replay("rp2d", seq))
    acc = np.zeros(80, np.float32)
    for t in range(T):
        acc = acc + seq[t].sum(axis=0)
        np.testing.assert_allclose(pulled[t], acc, rtol=1e-5)


@pytest.mark.parametrize("shape,axes", [((2, 4), ("dp", "kv")),
                                        ((4, 2), ("dp", "kv"))])
def test_two_axis_ring_kernel_matches_xla(shape, axes):
    """Multi-axis data plane (VERDICT r02 #1): the fused ring along the
    worker axis + XLA all_gather along kv must match the pure-XLA 2-D
    path on a (dp, kv) torus — push_pull, push+pull, and a second step
    (store donation chain intact)."""
    from pslite_tpu.parallel.mesh import make_mesh

    mesh2 = make_mesh(shape, axes)
    keys = np.arange(3, dtype=np.uint64)
    val_len = 700  # padded + non-tile-aligned sub-chunks
    rng = np.random.default_rng(41)
    W = shape[0]
    grads1 = rng.normal(size=(W, 3 * val_len)).astype(np.float32)
    grads2 = rng.normal(size=(W, 3 * val_len)).astype(np.float32)

    ref = CollectiveEngine(mesh=mesh2, worker_axis="dp", impl="xla")
    ref.register_dense("x2", keys, val_len)
    eng = CollectiveEngine(mesh=mesh2, worker_axis="dp", impl="pallas")
    eng.register_dense("r2", keys, val_len)

    p_ref = np.asarray(ref.push_pull("x2", grads1))
    p_ring = np.asarray(eng.push_pull("r2", grads1))
    np.testing.assert_allclose(p_ring, p_ref, rtol=1e-5, atol=1e-5)

    # push-only keeps the dp-replicated store consistent for a later pull.
    ref.push("x2", grads2).block_until_ready()
    eng.push("r2", grads2).block_until_ready()
    np.testing.assert_allclose(
        np.asarray(eng.pull("r2")), np.asarray(ref.pull("x2")),
        rtol=1e-5, atol=1e-5,
    )


def test_two_axis_ring_kernel_int8_compress():
    """int8 wire compression on the 2-D ring: lossy but bounded, and the
    pulled result must be identical on every device (owner-quantized AG
    payloads)."""
    from pslite_tpu.parallel.mesh import make_mesh

    mesh2 = make_mesh((2, 4), ("dp", "kv"))
    eng = CollectiveEngine(mesh=mesh2, worker_axis="dp", impl="pallas",
                           wire_compress="int8")
    keys = np.arange(2, dtype=np.uint64)
    val_len = 4096
    eng.register_dense("c2", keys, val_len)
    rng = np.random.default_rng(43)
    grads = rng.normal(size=(2, 2 * val_len)).astype(np.float32)
    pulled = np.asarray(eng.push_pull("c2", grads))
    want = grads.sum(axis=0)
    # absmax ~3.5, 2 ring hops of int8 quantization: tolerance scales
    # with amax/127 per hop.
    tol = 3 * np.abs(grads).max() / 127
    np.testing.assert_allclose(pulled, want, atol=tol)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_two_axis_stateful_fused_handles(impl):
    """Stateful (fused optimizer) handles on a 2-D (dp, kv) mesh — the
    dp-psum aggregation feeding the Pallas optimizer pass, state sharded
    over kv / replicated over dp.  Must match the 1-D reference engine
    step for step.  (impl only routes the stateless path; stateful
    programs are XLA either way — parametrized to prove the resolve
    logic doesn't mis-route.)"""
    from pslite_tpu.parallel.mesh import make_mesh

    lr, mu = 0.1, 0.9
    mesh2 = make_mesh((2, 4), ("dp", "kv"))
    eng = CollectiveEngine(mesh=mesh2, worker_axis="dp", impl=impl,
                           server_handle=f"sgd_momentum:{lr},{mu}")
    keys = np.arange(3, dtype=np.uint64)
    val_len = 100
    init = np.linspace(1, 2, 3 * val_len).astype(np.float32)
    eng.register_dense("st2", keys, val_len, init=init)
    rng = np.random.default_rng(47)

    ref_store = init.copy()
    ref_mom = np.zeros_like(ref_store)
    for _ in range(3):
        grads = rng.normal(size=(2, 3 * val_len)).astype(np.float32)
        pulled = np.asarray(eng.push_pull("st2", grads))
        agg = grads.sum(axis=0)
        ref_mom = mu * ref_mom + agg
        ref_store = ref_store - lr * ref_mom
        np.testing.assert_allclose(pulled, ref_store, rtol=2e-5, atol=2e-5)


def test_two_axis_adam_replay():
    """Stateful replay on a 2-D mesh: adam state threaded through the
    scan with the dp-psum reduction."""
    from pslite_tpu.parallel.mesh import make_mesh

    mesh2 = make_mesh((2, 4), ("dp", "kv"))
    keys = np.arange(2, dtype=np.uint64)
    val_len = 64
    init = np.linspace(0, 1, 2 * val_len).astype(np.float32)
    rng = np.random.default_rng(49)
    T = 3
    seq = rng.normal(size=(T, 2, 2 * val_len)).astype(np.float32)

    ref = CollectiveEngine(mesh=mesh2, worker_axis="dp",
                           server_handle="adam:0.01")
    ref.register_dense("ar_ref", keys, val_len, init=init)
    expected = [np.asarray(ref.push_pull("ar_ref", seq[t]))
                for t in range(T)]

    eng = CollectiveEngine(mesh=mesh2, worker_axis="dp",
                           server_handle="adam:0.01")
    eng.register_dense("ar", keys, val_len, init=init)
    pulled = np.asarray(eng.replay("ar", seq))
    for t in range(T):
        np.testing.assert_allclose(pulled[t], expected[t],
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_two_axis_push_pull_group(impl):
    """Grouped dispatch on a 2-D mesh (both impls) must match per-bucket
    singles — the W != S decoupling now covers the model-step group
    path."""
    from pslite_tpu.parallel.mesh import make_mesh

    mesh2 = make_mesh((2, 4), ("dp", "kv"))
    eng = CollectiveEngine(mesh=mesh2, worker_axis="dp", impl=impl)
    ref = CollectiveEngine(mesh=mesh2, worker_axis="dp", impl="xla")
    rng = np.random.default_rng(51)
    names, grads_list = [], []
    for i, val_len in enumerate((40, 700, 256)):
        name = f"gb{i}"
        keys = np.arange(2, dtype=np.uint64)
        eng.register_dense(name, keys, val_len)
        ref.register_dense(name, keys, val_len)
        names.append(name)
        grads_list.append(
            rng.normal(size=(2, 2 * val_len)).astype(np.float32)
        )
    grouped = eng.push_pull_group(names, grads_list)
    for name, g, out in zip(names, grads_list, grouped):
        want = np.asarray(ref.push_pull(name, g))
        np.testing.assert_allclose(np.asarray(out), want,
                                   rtol=1e-5, atol=1e-5)


def test_push_pull_stream_matches_sequential(mesh):
    """push_pull_stream (background-staged host transfers) must produce
    exactly the sequence of results that per-op push_pull does."""
    keys = np.arange(2, dtype=np.uint64)
    val_len = 100
    rng = np.random.default_rng(53)
    T = 5
    seq = [rng.normal(size=(8, 2 * val_len)).astype(np.float32)
           for _ in range(T)]

    ref = CollectiveEngine(mesh=mesh)
    ref.register_dense("ps_ref", keys, val_len)
    expected = [np.asarray(ref.push_pull("ps_ref", g)) for g in seq]

    eng = CollectiveEngine(mesh=mesh)
    eng.register_dense("ps", keys, val_len)
    outs = [np.asarray(o)
            for o in eng.push_pull_stream("ps", iter(seq), depth=2)]
    assert len(outs) == T
    for got, want in zip(outs, expected):
        np.testing.assert_allclose(got, want, rtol=1e-5)

    # Early abandonment must not wedge the stager thread.
    gen = eng.push_pull_stream("ps", iter(seq), depth=1)
    next(gen)
    gen.close()


def test_resnet_trace_host_origin_overlap(mesh):
    """Host-origin trace replay (serial and overlapped staging) runs and
    moves the advertised bytes."""
    from pslite_tpu.models.resnet_trace import replay

    eng = CollectiveEngine(mesh=mesh)
    for overlap in (False, True):
        nbytes, dt = replay(eng, steps=1, bucket_bytes=16 << 20,
                            host_origin=True, overlap=overlap)
        assert nbytes > 100 << 20 and dt > 0


def test_push_pull_stream_overlaps_staging_latency(mesh):
    """The stream pipeline must PIPELINE: the stager thread pulls (and
    stages) item i+1 while the consumer is still working on item i.

    Asserted structurally (event ordering), not by wall-clock margins —
    on a contended 1-vCPU host the CPU-bound legs can't overlap each
    other, so timing-based assertions are inherently flaky; what the
    pipeline guarantees on ANY host is that source latency (the
    transfer leg) runs concurrently with consumption."""
    import time

    keys = np.arange(1, dtype=np.uint64)
    val_len = 1024
    eng = CollectiveEngine(mesh=mesh)
    eng.register_dense("ov", keys, val_len)
    g = np.ones(val_len, np.float32)
    T = 4
    hold = 0.15  # how long the consumer keeps each result

    pulled_at = []
    done_at = []

    def source():
        for i in range(T):
            pulled_at.append(time.perf_counter())
            yield g

    for out in eng.push_pull_stream("ov", source(), depth=2):
        np.asarray(out)
        time.sleep(hold)  # consumer-side work on this result
        done_at.append(time.perf_counter())

    assert len(pulled_at) == len(done_at) == T
    # Pipelining: the stager asked the source for item i+1 while the
    # consumer was still holding item i (i.e. before done_at[i]).  A
    # serial implementation would only pull i+1 after the consumer
    # finished i.
    for i in range(T - 1):
        assert pulled_at[i + 1] < done_at[i], (
            f"no pipelining at step {i}: pull(i+1)="
            f"{pulled_at[i + 1]:.3f} >= done(i)={done_at[i]:.3f}"
        )


@pytest.mark.parametrize("keep", ["all", "last"])
def test_replay_ring_matches_xla(mesh, keep):
    """Stateless replay on the pallas impl scans the fused ring step;
    it must match the XLA-scan replay exactly (1-D mesh)."""
    keys = np.arange(2, dtype=np.uint64)
    val_len = 300  # padded, non-tile-aligned chunks
    rng = np.random.default_rng(57)
    T = 3
    seq = rng.normal(size=(T, 8, 2 * val_len)).astype(np.float32)

    ref = CollectiveEngine(mesh=mesh, impl="xla")
    ref.register_dense("rr_ref", keys, val_len)
    want = np.asarray(ref.replay("rr_ref", seq, keep=keep))

    eng = CollectiveEngine(mesh=mesh, impl="pallas")
    eng.register_dense("rr", keys, val_len)
    got = np.asarray(eng.replay("rr", seq, keep=keep))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    # Stores advanced identically.
    np.testing.assert_allclose(
        np.asarray(eng.pull("rr")), np.asarray(ref.pull("rr_ref")),
        rtol=1e-5, atol=1e-5,
    )


@pytest.mark.parametrize("keep", ["all", "last"])
def test_replay_ring_two_axis(keep):
    """Ring replay on the 2-D torus: dp sub-ring step inside the scan,
    both keep modes (last = sub-ring pushes + one final kv gather)."""
    from pslite_tpu.parallel.mesh import make_mesh

    mesh2 = make_mesh((2, 4), ("dp", "kv"))
    keys = np.arange(2, dtype=np.uint64)
    val_len = 200
    rng = np.random.default_rng(59)
    T = 3
    seq = rng.normal(size=(T, 2, 2 * val_len)).astype(np.float32)

    ref = CollectiveEngine(mesh=mesh2, worker_axis="dp", impl="xla")
    ref.register_dense("r2_ref", keys, val_len)
    want = np.asarray(ref.replay("r2_ref", seq, keep=keep))

    eng = CollectiveEngine(mesh=mesh2, worker_axis="dp", impl="pallas")
    eng.register_dense("r2", keys, val_len)
    got = np.asarray(eng.replay("r2", seq, keep=keep))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_replay_compressed_config_falls_back_to_xla():
    """wire_compress engines replay on the XLA step (the compressed ring
    stays single-step/grouped — see _replay_program): results are exact,
    not quantized."""
    mesh1 = default_mesh()
    eng = CollectiveEngine(mesh=mesh1, impl="pallas",
                           wire_compress="int8")
    keys = np.arange(2, dtype=np.uint64)
    val_len = 4096
    eng.register_dense("rc", keys, val_len)
    rng = np.random.default_rng(61)
    T = 2
    seq = rng.normal(size=(T, 8, 2 * val_len)).astype(np.float32)
    pulled = np.asarray(eng.replay("rc", seq))
    acc = np.zeros(2 * val_len, np.float32)
    for t in range(T):
        acc = acc + seq[t].sum(axis=0)
        # Exact (rtol only): the XLA path carries full precision.
        np.testing.assert_allclose(pulled[t], acc, rtol=1e-5, atol=1e-5)


def test_push_pull_zero_copy_single_device():
    """In-place pull delivery on a degenerate gather (kv axis size 1):
    values match the copying path, the returned array IS the store, and
    the next mutating op invalidates stale holders (the reference's
    RegisterRecvBuffer contract: the next pull overwrites the registered
    buffer in place, rdma_van.h:520-548)."""
    from pslite_tpu.parallel.mesh import make_mesh

    mesh1 = make_mesh((1,), ("kv",))
    keys = np.arange(3, dtype=np.uint64)
    rng = np.random.default_rng(71)
    g1 = rng.normal(size=(1, 300)).astype(np.float32)
    g2 = rng.normal(size=(1, 300)).astype(np.float32)

    ref = CollectiveEngine(mesh=mesh1)
    ref.register_dense("zr", keys, 100)
    exp1 = np.asarray(ref.push_pull("zr", g1))
    exp2 = np.asarray(ref.push_pull("zr", g2))

    eng = CollectiveEngine(mesh=mesh1)
    eng.register_dense("zc", keys, 100)
    out1 = eng.push_pull("zc", g1, zero_copy=True)
    assert out1 is eng._stores["zc"]  # aliases, no gather copy
    np.testing.assert_allclose(np.asarray(out1), exp1, rtol=1e-5)
    out2 = eng.push_pull("zc", g2, zero_copy=True)
    np.testing.assert_allclose(np.asarray(out2), exp2, rtol=1e-5)
    # out1's buffer was donated into the second step: stale holders see
    # a deleted array (clear error), never torn data.
    assert out1.is_deleted()


def test_push_pull_zero_copy_falls_back_multi_device(mesh):
    """On a real multi-shard gather zero_copy degrades to the copying
    path: correct values, prior results stay live."""
    eng = CollectiveEngine(mesh=mesh)
    keys = np.arange(2, dtype=np.uint64)
    eng.register_dense("zf", keys, 64)
    ones = np.ones((8, 128), dtype=np.float32)
    out1 = eng.push_pull("zf", ones, zero_copy=True)
    out2 = eng.push_pull("zf", ones, zero_copy=True)
    np.testing.assert_allclose(np.asarray(out1), 8 * np.ones(128))
    np.testing.assert_allclose(np.asarray(out2), 16 * np.ones(128))
    assert not out1.is_deleted()


def test_push_pull_zero_copy_stateful():
    """Stateful handles ride the same in-place delivery."""
    from pslite_tpu.parallel.mesh import make_mesh

    mesh1 = make_mesh((1,), ("kv",))
    keys = np.arange(2, dtype=np.uint64)
    init = np.linspace(0, 1, 128).astype(np.float32)
    rng = np.random.default_rng(73)
    seq = rng.normal(size=(3, 1, 128)).astype(np.float32)

    ref = CollectiveEngine(mesh=mesh1, server_handle="adam:0.01")
    ref.register_dense("sr", keys, 64, init=init)
    eng = CollectiveEngine(mesh=mesh1, server_handle="adam:0.01")
    eng.register_dense("sz", keys, 64, init=init)
    for t in range(3):
        exp = np.asarray(ref.push_pull("sr", seq[t]))
        got = eng.push_pull("sz", seq[t], zero_copy=True)
        assert got is eng._stores["sz"]
        np.testing.assert_allclose(np.asarray(got), exp,
                                   rtol=2e-5, atol=2e-5)


def test_replay_flat_slab_matches_sequential(mesh):
    """The flat [W, T*padded] slab layout (large per-step payloads, see
    _flat_replay) must reproduce the stacked layout's numerics for every
    keep mode and input form."""
    keys = np.arange(3, dtype=np.uint64)
    val_len = 100
    rng = np.random.default_rng(75)
    W, T = 8, 4
    seq = rng.normal(size=(T, W, 3 * val_len)).astype(np.float32)

    ref = CollectiveEngine(mesh=mesh)
    ref.register_dense("fr", keys, val_len)
    expected = [np.asarray(ref.push_pull("fr", seq[t])) for t in range(T)]

    eng = CollectiveEngine(mesh=mesh)
    eng.replay_flat_min_bytes = 4  # force the slab layout on tiny buckets
    eng.register_dense("ff", keys, val_len)
    assert eng._flat_replay(eng.bucket("ff").padded_len, np.float32,
                            "_default", False, 4)
    pulled = np.asarray(eng.replay("ff", seq))
    assert pulled.shape == (T, 3 * val_len)
    for t in range(T):
        np.testing.assert_allclose(pulled[t], expected[t], rtol=1e-5)

    # keep="last" + broadcast [T, total] form on a fresh engine.
    eng2 = CollectiveEngine(mesh=mesh)
    eng2.replay_flat_min_bytes = 4
    eng2.register_dense("fb", keys, val_len)
    bseq = np.ones((5, 3 * val_len), dtype=np.float32)
    out = np.asarray(eng2.replay("fb", bseq, keep="last"))
    np.testing.assert_allclose(out, 5 * 8 * np.ones(300, np.float32))


def test_replay_zero_copy_last_single_device():
    """replay(keep='last', zero_copy=True) on a 1-device mesh skips the
    final gather: result aliases the store and matches T sequential
    steps."""
    from pslite_tpu.parallel.mesh import make_mesh

    mesh1 = make_mesh((1,), ("kv",))
    keys = np.arange(2, dtype=np.uint64)
    rng = np.random.default_rng(77)
    T = 4
    seq = rng.normal(size=(T, 1, 128)).astype(np.float32)

    ref = CollectiveEngine(mesh=mesh1)
    ref.register_dense("zl_ref", keys, 64)
    for t in range(T):
        exp = np.asarray(ref.push_pull("zl_ref", seq[t]))

    eng = CollectiveEngine(mesh=mesh1)
    eng.register_dense("zl", keys, 64)
    out = eng.replay("zl", seq, keep="last", zero_copy=True)
    assert out is eng._stores["zl"]
    np.testing.assert_allclose(np.asarray(out), exp, rtol=1e-5)


def test_three_axis_torus_parity():
    """3-D torus (dp, kv1, kv2): store sharded over BOTH kv axes, fused
    dp sub-rings (ring positions translate through three axes'
    coordinates), pulled broadcast gathered over both kv axes — ring
    matches XLA (VERDICT r03 missing #4)."""
    from pslite_tpu.parallel.mesh import make_mesh

    mesh3 = make_mesh((2, 2, 2), ("dp", "kv1", "kv2"))
    keys = np.arange(3, dtype=np.uint64)
    val_len = 101  # total 303: not divisible by 4 -> padding path
    rng = np.random.default_rng(91)
    g = rng.normal(size=(2, 303)).astype(np.float32)

    outs = {}
    for impl in ("xla", "pallas"):
        eng = CollectiveEngine(mesh=mesh3, axis_name=("kv1", "kv2"),
                               worker_axis="dp", impl=impl)
        assert eng.num_shards == 4
        assert eng._effective_impl(np.float32, "sum") == impl
        eng.register_dense("t3", keys, val_len)
        assert eng.bucket("t3").padded_len > eng.bucket("t3").total_len
        outs[impl] = np.asarray(eng.push_pull("t3", g))
        np.testing.assert_allclose(outs[impl], g.sum(axis=0),
                                   rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(outs["pallas"], outs["xla"],
                               rtol=1e-5, atol=1e-5)


def test_three_axis_torus_stateful_and_replay():
    """Stateful handles + replay on the 3-D torus match a 1-D reference
    engine step for step."""
    from pslite_tpu.parallel.mesh import make_mesh

    mesh3 = make_mesh((2, 2, 2), ("dp", "kv1", "kv2"))
    mesh1 = default_mesh()
    keys = np.arange(2, dtype=np.uint64)
    rng = np.random.default_rng(93)
    T = 3
    # 1-D reference: 8 workers; 3-D: 2 workers — use grads that sum the
    # same: each of the 2 dp rows carries 4x the base row.
    base = rng.normal(size=(T, 128)).astype(np.float32)
    seq3 = np.stack([np.stack([4 * b, 4 * b]) for b in base])  # [T,2,128]
    seq1 = np.stack([np.stack([b] * 8) for b in base])         # [T,8,128]

    ref = CollectiveEngine(mesh=mesh1, server_handle="adam:0.01")
    ref.register_dense("r1", keys, 64)
    eng = CollectiveEngine(mesh=mesh3, axis_name=("kv1", "kv2"),
                           worker_axis="dp", server_handle="adam:0.01")
    eng.register_dense("r3", keys, 64)
    exp = np.asarray(ref.replay("r1", seq1, keep="last"))
    got = np.asarray(eng.replay("r3", seq3, keep="last"))
    np.testing.assert_allclose(got, exp, rtol=2e-5, atol=2e-5)


def test_tuple_axis_without_worker_axis_colocated():
    """A composite kv axis with no worker axis: the 1-D colocated
    semantics hold (workers = product of the axes) and the ring gate
    falls back to XLA (no single ring dimension)."""
    from pslite_tpu.parallel.mesh import make_mesh

    mesh3 = make_mesh((2, 4), ("kv1", "kv2"))
    eng = CollectiveEngine(mesh=mesh3, axis_name=("kv1", "kv2"),
                           impl="pallas")
    assert eng.num_shards == 8
    assert eng._effective_impl(np.float32, "sum") == "xla"
    keys = np.arange(2, dtype=np.uint64)
    eng.register_dense("c2", keys, 64)
    rng = np.random.default_rng(95)
    g = rng.normal(size=(8, 128)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(eng.push_pull("c2", g)), g.sum(axis=0), rtol=1e-5
    )


def test_three_axis_torus_reshard():
    """The elastic tier handles composite kv axes: a (2,2,2)-torus
    engine reshards onto a 1-D mesh and back without losing state."""
    from pslite_tpu.parallel.mesh import make_mesh

    mesh3 = make_mesh((2, 2, 2), ("dp", "kv1", "kv2"))
    eng = CollectiveEngine(mesh=mesh3, axis_name=("kv1", "kv2"),
                           worker_axis="dp")
    keys = np.arange(2, dtype=np.uint64)
    eng.register_dense("rs3", keys, 64)
    ones = np.ones((2, 128), np.float32)
    eng.push_pull("rs3", ones)  # store = 2

    mesh2 = make_mesh((2, 4), ("dp", "kv"))
    eng.reshard(mesh2, axis_name="kv")
    assert eng.num_shards == 4
    np.testing.assert_allclose(np.asarray(eng.pull("rs3"))[:128],
                               2 * np.ones(128))
    eng.reshard(mesh3, axis_name=("kv1", "kv2"))
    assert eng.num_shards == 4
    np.testing.assert_allclose(
        np.asarray(eng.push_pull("rs3", ones)), 4 * np.ones(128)
    )


def test_replay_flat_odd_step_count(mesh):
    """Non-power-of-two T exercises the unrolled bulk + tail split of
    the flat replay scan (both keep modes match sequential steps)."""
    keys = np.arange(2, dtype=np.uint64)
    val_len = 64
    rng = np.random.default_rng(97)
    T = 7  # bulk 4 + tail 3 at U=4 (min-bytes lowered below)
    seq = rng.normal(size=(T, 8, 128)).astype(np.float32)

    ref = CollectiveEngine(mesh=mesh)
    ref.register_dense("od_ref", keys, val_len)
    expected = [np.asarray(ref.push_pull("od_ref", seq[t]))
                for t in range(T)]

    eng = CollectiveEngine(mesh=mesh)
    eng.replay_flat_min_bytes = 4
    eng.register_dense("od", keys, val_len)
    assert eng._replay_unroll(eng.bucket("od").padded_len,
                              np.float32, T) == 4
    pulled = np.asarray(eng.replay("od", seq))
    for t in range(T):
        np.testing.assert_allclose(pulled[t], expected[t], rtol=1e-5)

    eng2 = CollectiveEngine(mesh=mesh)
    eng2.replay_flat_min_bytes = 4
    eng2.register_dense("od2", keys, val_len)
    out = np.asarray(eng2.replay("od2", seq, keep="last"))
    np.testing.assert_allclose(out, expected[-1], rtol=1e-5)


def test_sparse_adagrad_segment_sum_matches_dense_reference(mesh):
    """The O(batch) segment-sum adagrad (packed-layout path) must match
    the dense [R, d]-aggregate recurrence exactly, including DUPLICATE
    rows within and across workers (the segment sum exists to combine
    them before squaring)."""
    import jax.numpy as jnp

    from pslite_tpu.parallel.sparse import (
        SparseEngine,
        _adagrad_rows,
        _deinterleave_rows,
    )

    rows, dim, lr, eps = 37, 4, 0.1, 1e-8
    rng = np.random.default_rng(101)
    se = SparseEngine(mesh)
    se.register_sparse("sa", rows, dim)
    assert se.table("sa").pack == 32  # the packed layout is in play

    # Host reference: dense-aggregate recurrence over global rows.
    ref_store = np.zeros((rows, dim), np.float64)
    ref_acc = np.zeros(rows, np.float64)
    for step in range(3):
        # Heavy collisions: 8 workers x 6 entries over 37 rows, plus a
        # forced shared hot row.
        idx = rng.integers(0, rows, size=(8, 6)).astype(np.int32)
        idx[:, 0] = 5
        g = rng.normal(size=(8, 6, dim)).astype(np.float32)
        se.push("sa", idx, g, handle=f"row_adagrad:{lr},{eps}")
        se.block("sa")
        G = np.zeros((rows, dim), np.float64)
        np.add.at(G, idx.reshape(-1), g.reshape(-1, dim).astype(np.float64))
        ref_acc = ref_acc + np.mean(G ** 2, axis=1)
        ref_store = ref_store - lr * G / (np.sqrt(ref_acc)[:, None] + eps)

    got = np.asarray(
        se.pull("sa", np.tile(np.arange(rows, dtype=np.int32), (8, 1)))
    )[0]
    np.testing.assert_allclose(got, ref_store, rtol=1e-4, atol=1e-4)
    t = se.table("sa")
    acc = _deinterleave_rows(
        np.asarray(se.acc_array("sa")), rows, t.rows_per_shard,
        se.num_shards,
    )
    np.testing.assert_allclose(acc, ref_acc, rtol=1e-4, atol=1e-4)
    # Anchor the retained dense reference recurrence to the same host
    # model with NONZERO gradients (one step).
    G1 = jnp.asarray(rng.normal(size=(rows, dim)).astype(np.float32))
    s2, a2 = _adagrad_rows(jnp.zeros((rows, dim)), jnp.zeros(rows),
                           G1, lr, eps)
    Gh = np.asarray(G1, np.float64)
    ah = np.mean(Gh ** 2, axis=1)
    sh = -lr * Gh / (np.sqrt(ah)[:, None] + eps)
    np.testing.assert_allclose(np.asarray(s2), sh, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(a2), ah, rtol=1e-5)


def test_set_opt_state_device_restore_rejects_bad_dtype(mesh):
    """The orbax-v2 device-restore branch must reject an optimizer slot
    whose dtype doesn't match the bucket's (mirroring set_store_array's
    dense 'bad restore dtype' check) instead of deferring to an opaque
    XLA error steps later."""
    import jax.numpy as jnp

    from pslite_tpu.utils import logging as log

    eng = CollectiveEngine(mesh=mesh)
    keys = np.arange(2, dtype=np.uint64)
    eng.register_dense("odt", keys, 10)  # float32, total 20, padded 24
    bucket = eng._buckets["odt"]
    bad = jnp.zeros(bucket.padded_len, jnp.int32)  # device array, wrong dtype
    with pytest.raises(log.CheckError, match="bad opt restore dtype"):
        eng.set_opt_state("odt", "sgd_momentum", [bad])
    # Matching dtype passes through the same branch.
    good = jnp.zeros(bucket.padded_len, jnp.float32)
    eng.set_opt_state("odt", "sgd_momentum", [good])
    kind, slots = eng.opt_state("odt")
    assert kind == "sgd_momentum" and len(slots) == 1
