"""SArray zero-copy semantics (reference: include/ps/sarray.h)."""

import numpy as np

from pslite_tpu.range import Range, find_range
from pslite_tpu.sarray import DeviceType, SArray


def test_zero_copy_assignment():
    a = SArray(np.arange(10, dtype=np.float32))
    b = SArray(a)
    assert a.shares_memory(b)
    b.data[0] = 99.0
    assert a.data[0] == 99.0


def test_segment_is_view():
    a = SArray(np.arange(10, dtype=np.float32), src_device=DeviceType.TPU,
               src_device_id=3)
    seg = a.segment(2, 5)
    assert seg.size == 3
    assert seg.shares_memory(a)
    assert seg.src_device == DeviceType.TPU and seg.src_device_id == 3
    seg.data[0] = -1.0
    assert a.data[2] == -1.0


def test_reinterpret_cast():
    a = SArray(np.arange(4, dtype=np.uint64))
    b = a.astype_view(np.uint8)
    assert b.nbytes == a.nbytes
    assert b.size == 32
    assert b.shares_memory(a)


def test_from_bytes():
    a = SArray(b"\x01\x00\x00\x00", dtype=np.int32)
    assert a.size == 1 and int(a[0]) == 1


def test_find_range():
    keys = np.array([2, 4, 8, 16, 32], dtype=np.uint64)
    r = find_range(keys, 4, 17)
    assert (r.begin, r.end) == (1, 4)
    assert Range(3, 7).size() == 4
