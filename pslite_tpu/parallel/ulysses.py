"""Ulysses-style sequence parallelism — all-to-all head/sequence swap.

The complementary long-context strategy to ``ring_attention``: instead of
rotating K/V blocks around a ring, one ``all_to_all`` re-shards the
activations from sequence-parallel ``[B, T/S, H, D]`` to head-parallel
``[B, T, H/S, D]``, attention runs *locally* over the full sequence for
this shard's heads, and a second ``all_to_all`` swaps back.  Two
collectives per attention call (each moving ``1/S`` of the activations)
versus the ring's ``S`` neighbor hops — the better trade when heads are
plentiful and the mesh axis is small, while ring attention wins at very
long sequences that do not fit even transposed.  (The reference has no
sequence code at all — SURVEY §2.9; both strategies are new, TPU-first
scope.)

Layout inside ``shard_map`` over ``axis_name``: inputs are the
sequence-sharded ``[B, T_local, H, D]`` with global order shard-major,
matching ``ring_attention`` exactly, so the two are drop-in
interchangeable.  Requires ``H`` divisible by the axis size.
"""

from __future__ import annotations


def _all_to_all_seq_to_heads(x, axis_name: str, num_shards: int):
    """[B, T_local, H, D] -> [B, T_global, H/S, D] via one all_to_all."""
    from jax import lax

    B, T, H, D = x.shape
    S = num_shards
    # Split the head dim into S groups, all_to_all the group dim against
    # the sequence: shard s ends up holding head-group s for EVERY
    # sequence shard, i.e. the full sequence for its heads.
    x = x.reshape(B, T, S, H // S, D)
    # all_to_all over axis: split_axis=2 (head groups), concat_axis=1
    # (sequence blocks, shard-major => global order preserved).
    y = lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                       tiled=True)
    return y.reshape(B, T * S, H // S, D)


def _all_to_all_heads_to_seq(x, axis_name: str, num_shards: int):
    """[B, T_global, H/S, D] -> [B, T_local, H, D] (inverse transform)."""
    from jax import lax

    B, Tg, Hs, D = x.shape
    S = num_shards
    x = x.reshape(B, S, Tg // S, Hs, D)
    y = lax.all_to_all(x, axis_name, split_axis=1, concat_axis=3,
                       tiled=True)
    return y.reshape(B, Tg // S, Hs * S, D)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      scale: float | None = None):
    """All-to-all sequence-parallel attention; call inside shard_map over
    ``axis_name``.  Same contract as :func:`ring_attention`: inputs and
    output are ``[B, T_local, H, D]`` per shard, shard-major global
    order."""
    from jax import lax

    from .ring_attention import reference_attention

    S = lax.psum(1, axis_name)
    qh = _all_to_all_seq_to_heads(q, axis_name, S)
    kh = _all_to_all_seq_to_heads(k, axis_name, S)
    vh = _all_to_all_seq_to_heads(v, axis_name, S)
    # Full-sequence attention over this shard's head group; the
    # reference kernel already returns [B, T_global, H/S, D].
    oh = reference_attention(qh, kh, vh, causal=causal, scale=scale)
    return _all_to_all_heads_to_seq(oh, axis_name, S)
