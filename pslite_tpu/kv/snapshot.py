"""Coordinated cluster snapshots of the message-path KV tier
(docs/durability.md).

The scheduler broadcasts ``Command.SNAPSHOT`` to every live server;
each server fences a consistent cut (its apply pool quiesces behind a
submit token while the request thread holds new arrivals), streams its
owned ranges through the ``export_range`` iterator into per-range
segment files under the snapshot directory, and replies with per-range
digests.  The scheduler COMMITS the cut by writing the cluster
``MANIFEST.json`` — a snapshot without a manifest never restores, so a
crash mid-snapshot can only ever leave ignorable garbage, never a
half-restored store.

Restore (``PS_SNAPSHOT_RESTORE=1``) runs at server boot, before any
request is served: the manifest's ranges are digest-verified and
imported through ``import_range`` — optimizer slots included, because
the optimizer handle packs them into the same iterator currency.  A
digest mismatch fails the restore LOUDLY (CheckError): serving silently
corrupted parameters is strictly worse than refusing to boot.

Segment files are written through ``checkpoint.py`` — orbax when
available and asked for (``PS_SNAPSHOT_FORMAT=orbax``), the
dependency-free ``.npz`` layout otherwise — so snapshots work on any
host.
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..checkpoint import load_range_segment, save_range_segment
from ..utils import logging as log

# meta.head of the LOCAL snapshot marker a server's control hook posts
# into its own customer queue: processing it on the request thread
# serializes the cut against every earlier queued request (they apply
# before the fence; later ones wait behind it), exactly like the
# elastic routing cutover (ROUTING_LOCAL_CMD).  Never on the wire.
SNAPSHOT_LOCAL_CMD = 0x5A47

MANIFEST_NAME = "MANIFEST.json"


def range_digest(keys: np.ndarray, vals: np.ndarray,
                 lens: Optional[np.ndarray]) -> str:
    """Content digest of one exported range: crc32 chained over the
    key/val/len bytes AND their dtypes — a dtype swap with identical
    bytes must not verify."""
    crc = zlib.crc32(str(vals.dtype).encode())
    crc = zlib.crc32(np.ascontiguousarray(keys), crc)
    crc = zlib.crc32(np.ascontiguousarray(vals), crc)
    if lens is not None:
        crc = zlib.crc32(np.ascontiguousarray(
            np.asarray(lens, dtype=np.int64)), crc)
    return f"{crc:08x}"


def segment_filename(begin: int, end: int, uid: str = "") -> str:
    """Per-range segment name.  ``uid`` (the scheduler-minted attempt
    id) keeps each snapshot ATTEMPT's files distinct: without it, a
    later attempt that gets vetoed (one server errored after another
    already wrote) would have overwritten the previously COMMITTED
    snapshot's bytes in place, bricking the restore point the stale
    manifest still references."""
    base = f"range_{begin:016x}_{end:016x}"
    return f"{base}.{uid}" if uid else base


def write_range_segment(directory: str, begin: int, end: int,
                        keys: np.ndarray, vals: np.ndarray,
                        lens: Optional[np.ndarray],
                        fmt: str = "npz", uid: str = "") -> dict:
    """Write one exported range to its segment file; returns the
    manifest entry (begin/end/file/key count/bytes/digest/format)."""
    os.makedirs(directory, exist_ok=True)
    name = segment_filename(begin, end, uid)
    fmt = save_range_segment(
        os.path.join(directory, name), keys, vals, lens, fmt=fmt
    )
    return {
        "begin": int(begin),
        "end": int(end),
        "file": name,
        "keys": int(len(keys)),
        "nbytes": int(keys.nbytes + vals.nbytes
                      + (lens.nbytes if lens is not None else 0)),
        "digest": range_digest(keys, vals, lens),
        "format": fmt,
    }


def read_range_segment(directory: str, entry: dict) -> Tuple[
        np.ndarray, np.ndarray, Optional[np.ndarray]]:
    """Load one manifest entry's segment and VERIFY its digest; raises
    CheckError on mismatch or a missing file — a restore must fail
    loudly, never serve silently corrupted state."""
    keys, vals, lens = load_range_segment(
        os.path.join(directory, entry["file"]),
        fmt=entry.get("format", "npz"),
    )
    got = range_digest(keys, vals, lens)
    log.check(
        got == entry["digest"],
        f"snapshot digest mismatch for range [{entry['begin']:#x}, "
        f"{entry['end']:#x}): manifest says {entry['digest']}, segment "
        f"file {entry['file']!r} hashes to {got} — the snapshot is "
        f"corrupt; refusing to restore",
    )
    return keys, vals, lens


def write_manifest(directory: str, epoch: int, entries: List[dict],
                   extra: Optional[dict] = None) -> str:
    """Atomically commit the cluster manifest (the snapshot exists only
    once this file does)."""
    os.makedirs(directory, exist_ok=True)
    doc = {
        "version": 1,
        "epoch": int(epoch),
        "wall_time": time.time(),
        "ranges": sorted(entries, key=lambda e: e["begin"]),
    }
    if extra:
        doc.update(extra)
    path = os.path.join(directory, MANIFEST_NAME)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    from ..checkpoint import fsync_dir

    fsync_dir(directory)
    return path


def prune_segments(directory: str, manifest: dict) -> int:
    """Best-effort GC after a COMMIT: remove ``range_*`` segment files
    (and their writers' leftover temporaries) that the just-committed
    manifest does not reference — the previous snapshot's segments and
    any vetoed attempt's orphans.  Runs only AFTER the new manifest is
    durable, so the restore point is never without a full segment set.
    Returns the number of entries removed; IO errors are ignored (a
    shared directory may race another writer — garbage is harmless,
    a failed prune must not fail the snapshot)."""
    referenced = {e["file"] for e in manifest.get("ranges", [])}
    removed = 0
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    for name in names:
        # npz segments live as "<entry>.npz" on disk; orbax segments
        # are directories named exactly "<entry>".
        base = name[:-4] if name.endswith(".npz") else name
        if not base.startswith("range_") or base in referenced:
            continue
        path = os.path.join(directory, name)
        try:
            if os.path.isdir(path):
                import shutil

                shutil.rmtree(path, ignore_errors=True)
            else:
                os.unlink(path)
            removed += 1
        except OSError:
            pass
    return removed


def load_manifest(directory: Optional[str]) -> Optional[dict]:
    """The committed manifest, or None (no directory / never
    snapshotted / manifest unreadable — unreadable is logged, not
    fatal: restore then declines like a cold start)."""
    if not directory:
        return None
    path = os.path.join(directory, MANIFEST_NAME)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as fh:
            return json.load(fh)
    except Exception as exc:  # noqa: BLE001 - corrupt manifest
        log.warning(f"unreadable snapshot manifest {path!r}: {exc!r}")
        return None


def manifest_age_s(directory: Optional[str]) -> float:
    """Seconds since the newest committed manifest, or -1.0 when none
    exists — the ``snapshot.age_s`` gauge the SLO watchdog's
    ``snapshot_age`` rule grades (negative = never snapshotted, which
    the rule skips rather than alarming on un-configured clusters)."""
    if not directory:
        return -1.0
    path = os.path.join(directory, MANIFEST_NAME)
    try:
        return max(0.0, time.time() - os.path.getmtime(path))
    except OSError:
        return -1.0


def _filter_to_ranges(keys: np.ndarray, vals: np.ndarray,
                      lens: Optional[np.ndarray], owned) -> Tuple[
                          np.ndarray, np.ndarray, Optional[np.ndarray]]:
    """Keep only the keys inside the caller's owned ranges (an elastic
    reboot can own a different cut than the manifest's writer did).
    Keys are sorted (export_range sorts), so each range is one slice."""
    mask = np.zeros(len(keys), dtype=bool)
    for rng in owned:
        lo = int(np.searchsorted(keys, rng.begin))
        hi = int(np.searchsorted(keys, rng.end))
        mask[lo:hi] = True
    if mask.all():
        return keys, vals, lens
    if lens is not None:
        # abs(): negative lens tag slot-packed optimizer records; the
        # magnitude is the record length (kv_app state iterator).
        offs = np.concatenate(
            ([0], np.cumsum(np.abs(np.asarray(lens, dtype=np.int64)))))
        parts = [vals[offs[i]:offs[i + 1]]
                 for i in np.nonzero(mask)[0]]
        out_vals = (np.concatenate(parts) if parts
                    else vals[:0])
        return keys[mask], out_vals, np.asarray(lens)[mask]
    k = len(vals) // max(len(keys), 1)
    return keys[mask], vals.reshape(len(keys), k)[mask].reshape(-1), None


def restore_into(handle, directory: str, owned_ranges,
                 manifest: Optional[dict] = None) -> Tuple[int, int]:
    """Restore every manifest range intersecting ``owned_ranges`` into
    ``handle`` (digest-verified, optimizer slots riding the handle's
    ``import_range``).  Returns ``(keys, bytes)`` restored; (0, 0) when
    no manifest is committed.  Digest mismatches and missing segment
    files raise (loud restore failure)."""
    from .replication import import_range

    manifest = manifest or load_manifest(directory)
    if manifest is None:
        return 0, 0
    total_keys = 0
    total_bytes = 0
    for entry in manifest.get("ranges", []):
        if not any(rng.begin < entry["end"] and entry["begin"] < rng.end
                   for rng in owned_ranges):
            continue
        keys, vals, lens = read_range_segment(directory, entry)
        keys, vals, lens = _filter_to_ranges(keys, vals, lens,
                                             owned_ranges)
        if not len(keys):
            continue
        import_range(handle, keys, vals, lens)
        total_keys += len(keys)
        total_bytes += int(vals.nbytes)
    return total_keys, total_bytes


def snapshot_summary(replies: Dict[int, dict]) -> Tuple[
        List[dict], List[str]]:
    """Split the scheduler's gathered per-server replies into manifest
    entries and error strings (an errored or silent server VETOES the
    commit — a manifest that is missing a range would restore a
    silently truncated store)."""
    entries: List[dict] = []
    errors: List[str] = []
    for nid, rep in sorted(replies.items()):
        if rep.get("error"):
            errors.append(f"node {nid}: {rep['error']}")
            continue
        entries.extend(rep.get("ranges", []))
    return entries, errors
