"""Sparse KV tables — embedding-style push/pull over the mesh.

The reference's sparse capability is KVPairs with arbitrary subsets of a
huge key space, sliced to servers by key range and aggregated server-side
(kv_app.h:430-452); its stress benchmark drives gather/scatter traffic
(test_benchmark_stress.cc:249-431).  The TPU-native design shards the table
rows over the ``kv`` mesh axis and turns push/pull into collectives with
static shapes:

- ``push``: all_gather the (indices, grads) of every worker shard, then each
  table shard scatter-adds the rows it owns (``segment-sum`` aggregation —
  the server handler as a reduction).
- ``pull``: every shard materializes the owned rows for every worker's
  index list (zeros elsewhere); a ``psum_scatter`` over the worker dimension
  both sums the one-hot contributions and routes each worker exactly its
  own batch — gather traffic rides the same bandwidth-optimal collective as
  dense push.

Row ownership is round-robin (``row % num_shards``) rather than contiguous
range: skewed key distributions (the 1M-key embedding workload,
BASELINE.md config 5) then load-balance across shards by construction.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

import numpy as np

from ..utils import logging as log
from .mesh import shard_map_compat as shard_map


@dataclass
class SparseTable:
    name: str
    num_rows: int  # global rows
    dim: int
    rows_per_shard: int
    dtype: object
    # Lane packing factor: pack logical rows per physical store row
    # (pack*dim = 128 lanes).  TPU tiling gives a [rows, dim<128] table
    # no good layout — XLA's scatter wants it column-major, its gather
    # wants row-major, and whichever the store commits, the other op
    # transposes the WHOLE table every step (1.65 ms of the 1M-row
    # embedding step).  Packing to full 128-lane rows makes row-major
    # canonical for BOTH ops: measured 2.0 -> 0.35 ms/step.  pack == 1
    # means unpacked (dim >= 128, dim not dividing 128, or a table
    # demoted by the orbax demotion-era checkpoint compat shim).
    pack: int = 1

    @property
    def phys_rows(self) -> int:
        """Physical store rows per shard."""
        return self.rows_per_shard // self.pack



def _interleave_rows(glob, num_rows: int, rps: int, S: int, dtype):
    """Global-order rows -> the sharded store layout: global row r
    lives on shard r % S at local row r // S.  ``glob`` is [num_rows]
    or [num_rows, dim]; returns the flat interleaved array of
    rps*S (x dim) entries.  The ONE definition of the layout —
    register_sparse init, reshard stores, and reshard accumulators all
    route through it (pull correctness depends on them agreeing)."""
    glob = np.asarray(glob, dtype=np.dtype(dtype))
    shape = (rps * S,) + glob.shape[1:]
    arr = np.zeros(shape, dtype=np.dtype(dtype))
    arr[:num_rows] = glob
    if arr.ndim == 1:
        return arr.reshape(rps, S).transpose(1, 0).reshape(-1)
    return arr.reshape(rps, S, -1).transpose(1, 0, 2).reshape(
        -1, arr.shape[1]
    )


def _deinterleave_rows(inter, num_rows: int, rps: int, S: int):
    """Inverse of :func:`_interleave_rows`: the sharded store layout
    back to global row order ([num_rows] or [num_rows, dim]).  Same
    one-definition rule — checkpoint saves and reshard snapshots route
    through it."""
    inter = np.asarray(inter)
    if inter.ndim == 1:
        return inter.reshape(S, rps).transpose(1, 0).reshape(
            -1
        )[:num_rows].copy()
    return inter.reshape(S, rps, -1).transpose(1, 0, 2).reshape(
        -1, inter.shape[1]
    )[:num_rows].copy()


def _pack_host(inter, rps: int, S: int, pack: int, dim: int):
    """Shard-interleaved LOGICAL rows [rps*S, dim] -> the PHYSICAL
    packed store [phys*S, pack*dim] (pure contiguous reshapes: each
    shard's rps logical rows become rps/pack 128-lane rows)."""
    if pack == 1:
        return inter
    inter = np.ascontiguousarray(inter)
    return inter.reshape(S, rps // pack, pack * dim).reshape(
        S * (rps // pack), pack * dim
    )


def _unpack_host(phys, rps: int, S: int, pack: int, dim: int):
    """Inverse of :func:`_pack_host`."""
    if pack == 1:
        return np.asarray(phys)
    return np.ascontiguousarray(phys).reshape(
        S, rps, dim
    ).reshape(S * rps, dim)


def _store_out_format(store, mesh, axis):
    """Output Format pinning a program's donated store output to the
    LIVE store's committed layout (left alone, XLA commits the scatter
    output in a different layout than the pull program wants and every
    pull pays a full-table transpose).  The ONE definition the single
    and group program builders share; falls back to a plain
    NamedSharding when the layout API is unavailable."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    try:
        from jax.experimental.layout import Format

        fmt = getattr(store, "format", None)
        if fmt is not None and fmt.layout is not None:
            return Format(fmt.layout, NamedSharding(mesh, P(axis, None)))
    except Exception:  # noqa: BLE001 - layout API is optional
        pass
    return NamedSharding(mesh, P(axis, None))


def _scatter_rows(axis, S, R, pack, dim, store_l, idx_l, grads_l):
    """Sum-handle push: scatter-add the owned rows DIRECTLY into the
    donated (possibly packed) store.  A dense-aggregate form reads +
    writes the whole table per push (768MB of traffic for a 4096-row
    update on the 1M-row workload); this touches only the updated rows.
    Unowned rows map out of bounds and mode="drop" discards them.
    Shared by the single-table and group programs."""
    from jax import lax
    import jax.numpy as jnp

    all_idx = lax.all_gather(idx_l[0], axis, tiled=True)  # [W*n]
    all_g = lax.all_gather(grads_l[0], axis, tiled=True)  # [W*n, d]
    my = lax.axis_index(axis)
    owned = (all_idx % S) == my
    local = all_idx // S
    masked = jnp.where(owned[:, None], all_g, 0)
    if pack == 1:
        rows = jnp.where(owned, local, R)  # R = out of bounds -> drop
        return store_l.at[rows].add(masked, mode="drop")
    phys = jnp.where(owned, local // pack, R // pack)
    slot = (local % pack).astype(jnp.int32)
    onehot = (slot[:, None] == jnp.arange(pack, dtype=jnp.int32)[None])
    packed = (
        onehot[:, :, None] * masked[:, None, :]
    ).reshape(all_idx.shape[0], pack * dim)
    return store_l.at[phys].add(packed, mode="drop")


def _adagrad_rows(store_l, acc_l, G, lr, eps):
    """Row-wise Adagrad on a DENSE aggregated gradient [R, d] (the
    DLRM-standard embedding update): acc += mean(G^2, rows); row -=
    lr*G/(sqrt+eps).  Untouched rows see G == 0 and are unchanged.
    Kept as the REFERENCE recurrence the sparse form below must match
    (tests assert parity); production paths use _adagrad_sparse."""
    import jax.numpy as jnp

    acc_new = acc_l + jnp.mean(G.astype(jnp.float32) ** 2, axis=1)
    step = (lr * G.astype(jnp.float32)
            / (jnp.sqrt(acc_new)[:, None] + eps))
    return store_l - step.astype(store_l.dtype), acc_new


def _adagrad_sparse(axis, S, R, pack, dim, store_l, acc_l, idx_l,
                    grads_l, lr, eps):
    """Row-wise Adagrad WITHOUT the dense [R, d] aggregate: the dense
    form reads+writes the whole table per push (a full-table pass even
    for a 4096-row batch) and cannot serve the lane-packed layout.
    Here duplicates are combined by a SEGMENT SUM over the sorted
    gathered indices (O(batch) workspaces, exact same per-row G as the
    dense form), the accumulator rows are gathered/updated/scattered
    1-D, and the store step scatter-adds through the packed layout —
    identical numerics to _adagrad_rows on the touched rows, untouched
    rows never read or written."""
    from jax import lax
    import jax.numpy as jnp

    all_idx = lax.all_gather(idx_l[0], axis, tiled=True)   # [m]
    all_g = lax.all_gather(grads_l[0], axis, tiled=True)   # [m, d]
    my = lax.axis_index(axis)
    owned = (all_idx % S) == my
    local = jnp.where(owned, all_idx // S, R)  # R = sentinel (dropped)
    m = all_idx.shape[0]

    # Segment-sum duplicates: sort by local row, one segment per unique
    # row (sentinel rows sort last into their own segments).
    order = jnp.argsort(local)
    sr = local[order]
    sg = jnp.where(owned[order][:, None], all_g[order], 0)
    first = jnp.concatenate(
        [jnp.ones((1,), bool), sr[1:] != sr[:-1]]
    )
    seg = jnp.cumsum(first) - 1                            # [m]
    G_seg = jnp.zeros((m, sg.shape[1]), sg.dtype).at[seg].add(sg)
    # Row of each segment (slots beyond the unique count stay at the
    # sentinel and scatter harmlessly via drop/zero-G).
    row_seg = jnp.full((m,), R, jnp.int32).at[seg].set(
        sr.astype(jnp.int32)
    )
    valid = row_seg < R

    # Accumulator: gather the touched rows, apply, scatter back (1-D
    # logical rows — independent of the store's lane packing).
    acc_rows = acc_l[jnp.where(valid, row_seg, 0)]
    g2 = jnp.mean(G_seg.astype(jnp.float32) ** 2, axis=1)
    acc_new_rows = acc_rows + g2
    new_acc = acc_l.at[jnp.where(valid, row_seg, R)].set(
        acc_new_rows, mode="drop"
    )
    step = (lr * G_seg.astype(jnp.float32)
            / (jnp.sqrt(acc_new_rows)[:, None] + eps))
    step = jnp.where(valid[:, None], step, 0).astype(store_l.dtype)

    # Store: scatter-subtract the step through the (packed) layout.
    if pack == 1:
        new_store = store_l.at[jnp.where(valid, row_seg, R)].add(
            -step, mode="drop"
        )
    else:
        phys = jnp.where(valid, row_seg // pack, R // pack)
        slot = (row_seg % pack).astype(jnp.int32)
        onehot = (slot[:, None]
                  == jnp.arange(pack, dtype=jnp.int32)[None])
        packed = (onehot[:, :, None] * (-step)[:, None, :]).reshape(
            m, pack * dim
        )
        new_store = store_l.at[phys].add(packed, mode="drop")
    return new_store, new_acc


def _pull_rows(axis, S, store_l, idx_l, pack: int = 1, dim: int = None):
    """Per-shard pull body: materialize owned rows for every worker's
    index list, route each worker its batch via psum_scatter over the
    worker dimension.  Shared single/group; packed stores gather the
    128-lane physical row and select the logical slot (see
    SparseTable.pack)."""
    from jax import lax
    import jax.numpy as jnp

    all_idx = lax.all_gather(idx_l[0], axis, tiled=True)  # [W*n]
    my = lax.axis_index(axis)
    owned = (all_idx % S) == my
    local = all_idx // S
    if pack == 1:
        rows = store_l[jnp.where(owned, local, 0)]  # [W*n, d]
        d = store_l.shape[1]
    else:
        d = dim
        m = all_idx.shape[0]
        phys = store_l[jnp.where(owned, local // pack, 0)]  # [W*n, 128]
        slot = (local % pack).astype(jnp.int32)
        rows = jnp.take_along_axis(
            phys.reshape(m, pack, d), slot[:, None, None], axis=1
        )[:, 0]
    vals = jnp.where(owned[:, None], rows, 0)
    vals = vals.reshape(S, -1, d)  # [W, n, d]
    return lax.psum_scatter(vals, axis, scatter_dimension=0,
                            tiled=True)[0]  # [n, d] for my indices


class SparseEngine:
    """Sparse tables on the same mesh/axis as a CollectiveEngine."""

    def __init__(self, mesh, axis_name: str = "kv", profiler=None):
        from .placement import local_shard_count, mesh_is_multiprocess

        self.mesh = mesh
        self.axis = axis_name
        self.num_shards = mesh.shape[axis_name]
        self._multiprocess = mesh_is_multiprocess(mesh)
        self._local_shard_count = (
            local_shard_count(mesh) if self._multiprocess
            else self.num_shards
        )
        # Observability mirroring CollectiveEngine (van.cc:29-77 analog).
        self.profiler = profiler
        self.push_bytes = 0
        self.pull_bytes = 0
        self._counter_mu = threading.Lock()
        self._tables: Dict[str, SparseTable] = {}
        self._stores: Dict[str, object] = {}
        # Row-wise Adagrad accumulators ([rows], same modulo row-sharding
        # as the table), created lazily by push(handle="row_adagrad:...").
        self._acc: Dict[str, object] = {}
        self._programs: Dict[tuple, Callable] = {}
        self._mu = threading.Lock()
        # Per-table write locks: push donates the store buffer, so the
        # load-run-store sequence must be atomic per table (same contract
        # as CollectiveEngine._bucket_mu).
        self._table_mu: Dict[str, threading.Lock] = {}

    def register_sparse(self, name: str, num_rows: int, dim: int, dtype=None,
                        init=None) -> SparseTable:
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        if dtype is None:
            dtype = jnp.float32
        pack = 128 // dim if (dim < 128 and 128 % dim == 0) else 1
        rows_per_shard = -(-num_rows // self.num_shards)
        # Round to the packing factor so each shard's logical rows fill
        # whole 128-lane physical rows (see SparseTable.pack).
        rows_per_shard = -(-rows_per_shard // pack) * pack
        table = SparseTable(name, num_rows, dim, rows_per_shard, dtype,
                            pack=pack)
        sharding = NamedSharding(self.mesh, P(self.axis, None))
        S = self.num_shards
        if init is not None:
            store = self._place(
                _pack_host(
                    _interleave_rows(init, num_rows, rows_per_shard,
                                     S, dtype),
                    rows_per_shard, S, pack, dim,
                ),
                sharding,
            )
        elif self._is_multiprocess():
            store = self._place(
                np.zeros((table.phys_rows * S, pack * dim),
                         np.dtype(dtype)),
                sharding,
            )
        else:
            store = jax.device_put(
                jnp.zeros((table.phys_rows * S, pack * dim), dtype=dtype),
                sharding,
            )
        with self._mu:
            self._tables[name] = table
            self._stores[name] = store
            self._table_mu.setdefault(name, threading.Lock())
        return table

    def _sparse_program(self, op: str, table: SparseTable, batch: int):
        key = (op, table.name, batch, table.pack)
        with self._mu:
            prog = self._programs.get(key)
        if prog is not None:
            return prog

        import jax
        from jax import lax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        axis = self.axis
        S = self.num_shards
        R = table.rows_per_shard
        pack = table.pack
        dim = table.dim

        # Pin the store's OUTPUT layout to its live committed layout
        # (see _store_out_format).  Inputs stay AUTO (jit refuses
        # mismatched explicit input layouts instead of relayouting);
        # pinning only the output makes the layout a fixed point from
        # the first push onward, and the pull program then compiles
        # against that stable layout with no transpose.
        store_fmt = _store_out_format(
            self._stores[table.name], self.mesh, axis
        )

        def _sh(spec):
            return NamedSharding(self.mesh, spec)

        def _push(store_l, idx_l, grads_l):
            # Scatter-add directly into the donated (packed) store —
            # see _scatter_rows for the traffic/layout rationale.
            new = _scatter_rows(axis, S, R, pack, dim, store_l, idx_l,
                                grads_l)
            # Tiny non-donated completion token: callers block on this
            # instead of the store (which the next push donates).
            return new, new[:1, :1]

        def _push_row_adagrad(store_l, acc_l, idx_l, grads_l, lr, eps):
            # Sync-PS optimizer semantics (kv_app.h:430-452 as one fused
            # program); lr/eps arrive as traced scalars, so per-step
            # schedules reuse ONE compiled program.  Segment-sum form:
            # O(batch) work and packed-layout compatible (no dense
            # [R, d] aggregate, no full-table pass, no demotion).
            new, acc_new = _adagrad_sparse(
                axis, S, R, pack, dim, store_l, acc_l, idx_l, grads_l,
                lr, eps,
            )
            return new, acc_new, new[:1, :1]

        def _pull(store_l, idx_l):
            return _pull_rows(axis, S, store_l, idx_l, pack=pack,
                              dim=dim)

        if op == "push":
            fn = shard_map(
                _push,
                mesh=self.mesh,
                in_specs=(P(axis, None), P(axis, None), P(axis, None, None)),
                out_specs=(P(axis, None), P(axis, None)),
            )
            jitted = jax.jit(
                fn, donate_argnums=(0,),
                out_shardings=(store_fmt, _sh(P(axis, None))),
            )
        elif op == "push_row_adagrad":
            # lr/eps are traced scalar args (replicated): one compiled
            # program serves every learning-rate schedule step.
            fn = shard_map(
                _push_row_adagrad,
                mesh=self.mesh,
                in_specs=(P(axis, None), P(axis), P(axis, None),
                          P(axis, None, None), P(), P()),
                out_specs=(P(axis, None), P(axis), P(axis, None)),
            )
            jitted = jax.jit(
                fn, donate_argnums=(0, 1),
                out_shardings=(store_fmt, _sh(P(axis)),
                               _sh(P(axis, None))),
            )
        elif op == "pull":
            fn = shard_map(
                _pull,
                mesh=self.mesh,
                in_specs=(P(axis, None), P(axis, None)),
                out_specs=P(axis, None),
            )
            jitted = jax.jit(fn)
        else:
            raise ValueError(op)
        with self._mu:
            self._programs[key] = jitted
        return jitted

    def _is_multiprocess(self) -> bool:
        return self._multiprocess

    def _local_shards(self) -> int:
        return self._local_shard_count

    def _place(self, host_arr, sharding):
        from .placement import place_host_array

        return place_host_array(
            self.mesh, host_arr, sharding, self._multiprocess
        )

    def _prep(self, table: SparseTable, indices, grads=None):
        """[W, n] indices (+ [W, n, d] grads) sharded over the worker axis.

        On a multi-process mesh the host inputs carry only THIS process's
        worker rows ([local, n] / [local, n, d])."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        idx_sharding = NamedSharding(self.mesh, P(self.axis, None))
        g_sharding = NamedSharding(self.mesh, P(self.axis, None, None))
        if self._is_multiprocess():
            idx = np.ascontiguousarray(np.asarray(indices, dtype=np.int32))
            local = self._local_shards()
            log.check_eq(int(idx.shape[0]), local,
                         "bad local worker dim (rows = this process's "
                         "devices on a multi-process mesh)")
            idx_sh = jax.make_array_from_process_local_data(
                idx_sharding, idx, (self.num_shards,) + idx.shape[1:]
            )
            if grads is None:
                return idx_sh, None
            g = np.ascontiguousarray(
                np.asarray(grads, dtype=np.dtype(table.dtype))
            )
            g_sh = jax.make_array_from_process_local_data(
                g_sharding, g, (self.num_shards,) + g.shape[1:]
            )
            return idx_sh, g_sh
        idx = jnp.asarray(indices, dtype=jnp.int32)
        log.check_eq(int(idx.shape[0]), self.num_shards, "bad worker dim")
        idx_sh = jax.device_put(idx, idx_sharding)
        if grads is None:
            return idx_sh, None
        g = jnp.asarray(grads, dtype=table.dtype)
        g_sh = jax.device_put(g, g_sharding)
        return idx_sh, g_sh

    def _observe(self, name: str, op: str, table: SparseTable,
                 batch: int, t0: float) -> None:
        payload = (
            self.num_shards * batch * table.dim
            * np.dtype(table.dtype).itemsize
        )
        with self._counter_mu:
            if op == "push":
                self.push_bytes += payload
            else:
                self.pull_bytes += payload
        if self.profiler is not None and getattr(
            self.profiler, "enabled", False
        ):
            dur_us = int((time.perf_counter() - t0) * 1e6)
            self.profiler.record_engine(name, f"sparse_{op}", payload,
                                        dur_us)

    def _ensure_acc(self, name: str, table: SparseTable) -> None:
        if name in self._acc:
            return
        from jax.sharding import NamedSharding, PartitionSpec as P

        self._acc[name] = self._place(
            np.zeros(table.rows_per_shard * self.num_shards, np.float32),
            NamedSharding(self.mesh, P(self.axis)),
        )

    def ensure_acc(self, name: str) -> None:
        """Create the (zero) Adagrad accumulator for a registered table —
        needed before an orbax restore in a fresh process, where the
        restore target must exist without running a push first."""
        with self._table_mu[name]:
            self._ensure_acc(name, self._tables[name])

    def acc_array(self, name: str):
        """Adagrad accumulator snapshot (checkpointing); row-interleaved
        like the table store."""
        import jax.numpy as jnp

        with self._table_mu[name]:
            log.check(name in self._acc, f"no accumulator for {name!r}")
            return jnp.copy(self._acc[name])

    def set_acc_array(self, name: str, value,
                      global_rows: bool = False) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        table = self._tables[name]
        expected = (table.rows_per_shard * self.num_shards,)
        sharding = NamedSharding(self.mesh, P(self.axis))
        if global_rows and isinstance(value, jax.Array):
            # Device-side global restore (see set_store_array).
            import jax.numpy as jnp

            S, rps = self.num_shards, table.rows_per_shard
            log.check_eq(tuple(value.shape), (table.num_rows,),
                         "bad global-rows accumulator shape")
            v = jnp.pad(value.astype(np.float32),
                        (0, rps * S - table.num_rows))
            inter = v.reshape(rps, S).transpose(1, 0).reshape(-1)
            placed = jax.device_put(inter, sharding)
            with self._table_mu[name]:
                self._acc[name] = placed
            return
        if global_rows and not isinstance(value, jax.Array):
            host = np.asarray(value, np.float32)
            log.check_eq(tuple(host.shape), (table.num_rows,),
                         "bad global-rows accumulator shape")
            value = _interleave_rows(
                host, table.num_rows, table.rows_per_shard,
                self.num_shards, np.float32,
            )
        if isinstance(value, jax.Array):
            # Sharded restores (multi-host): assign directly, same
            # contract as set_store_array.
            equivalent = value.sharding == sharding or (
                hasattr(value.sharding, "is_equivalent_to")
                and value.sharding.is_equivalent_to(sharding, value.ndim)
            )
            if equivalent:
                log.check_eq(tuple(value.shape), expected,
                             "bad accumulator shape")
                with self._table_mu[name]:
                    self._acc[name] = value
                return
        host = np.asarray(value, np.float32)
        log.check_eq(host.shape, expected, "bad accumulator shape")
        placed = self._place(host, sharding)
        with self._table_mu[name]:
            self._acc[name] = placed

    def _ensure_unpacked(self, name: str) -> None:
        """Demote a lane-packed table to the unpacked layout (one-time
        host round trip).  COMPAT SHIM only: adagrad once required the
        unpacked layout (the dense-aggregate era) and orbax checkpoints
        saved then hold unpacked stores; restore_engine_orbax demotes a
        packed table to match.  Collective on multi-process meshes.
        Call with the table lock HELD."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .placement import to_host_global

        t = self._tables[name]
        if t.pack == 1:
            return
        host = _unpack_host(
            to_host_global(self._stores[name], self._multiprocess),
            t.rows_per_shard, self.num_shards, t.pack, t.dim,
        )
        # Place FIRST: a placement failure must not leave t.pack
        # describing a layout the live store doesn't have.
        placed = self._place(
            np.ascontiguousarray(host),
            NamedSharding(self.mesh, P(self.axis, None)),
        )
        self._stores[name] = placed
        t.pack = 1

    @staticmethod
    def _parse_handle(handle: str) -> tuple:
        kind, _, rest = handle.partition(":")
        log.check(kind == "row_adagrad", f"unknown sparse handle {kind!r}")
        lr, eps = 0.01, 1e-8
        if rest:
            parts = rest.split(",")
            lr = float(parts[0])
            if len(parts) > 1:
                eps = float(parts[1])
        return kind, (lr, eps)

    def push(self, name: str, indices, grads, handle: str = None):
        """indices: [W, n] int rows per worker; grads: [W, n, d].
        Duplicate rows (within or across workers) accumulate — the
        aggregation contract of the default server handle.

        ``handle="row_adagrad:lr,eps"`` instead applies the
        DLRM-standard row-wise Adagrad: the per-row aggregate gradient
        updates a per-row accumulator, and the row steps by
        ``-lr * G / (sqrt(acc) + eps)`` — the fused sparse analog of the
        dense engine's optimizer handles."""
        t0 = time.perf_counter()
        table = self._tables[name]
        idx, g = self._prep(table, indices, grads)
        batch = int(idx.shape[1])
        if handle is None:
            with self._table_mu[name]:
                # Program selection reads table.pack, which the orbax
                # compat shim can mutate — resolve it under the lock.
                prog = self._sparse_program("push", table, batch)
                new_store, token = prog(self._stores[name], idx, g)
                self._stores[name] = new_store
        else:
            import jax.numpy as jnp

            _, (lr, eps) = self._parse_handle(handle)
            with self._table_mu[name]:
                prog = self._sparse_program("push_row_adagrad", table,
                                            batch)
                self._ensure_acc(name, table)
                new_store, new_acc, token = prog(
                    self._stores[name], self._acc[name], idx, g,
                    jnp.float32(lr), jnp.float32(eps),
                )
                self._stores[name] = new_store
                self._acc[name] = new_acc
        self._observe(name, "push", table, batch, t0)
        # The token is a tiny non-donated output that becomes ready when
        # the push completes — block on it freely (the store itself is
        # donated by the next push, so it must not escape).
        return token

    def _sparse_group_program(self, op: str, tables, batches: tuple):
        """One jitted program over SEVERAL tables (one dispatch instead
        of len(tables) — the many-embedding-tables pattern of a real
        recommender step, dense analog: engine.push_pull_group)."""
        key = (op, tuple((t.name, t.pack) for t in tables), batches)
        with self._mu:
            prog = self._programs.get(key)
        if prog is not None:
            return prog

        import jax
        from jax import lax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        axis = self.axis
        S = self.num_shards
        k = len(tables)
        Rs = [t.rows_per_shard for t in tables]

        store_spec = P(axis, None)
        acc_spec = P(axis)
        idx_spec = P(axis, None)
        g_spec = P(axis, None, None)

        packs = [t.pack for t in tables]
        dims = [t.dim for t in tables]

        from jax.sharding import NamedSharding

        store_fmts = tuple(
            _store_out_format(self._stores[t.name], self.mesh, axis)
            for t in tables
        )
        tok_sh = NamedSharding(self.mesh, P(axis, None))
        acc_sh = NamedSharding(self.mesh, P(axis))

        if op == "push":
            def body(*args):
                stores = args[:k]
                idxs = args[k:2 * k]
                grads = args[2 * k:]
                new = [
                    _scatter_rows(axis, S, Rs[i], packs[i], dims[i],
                                  s, idxs[i], grads[i])
                    for i, s in enumerate(stores)
                ]
                return (*new, new[0][:1, :1])

            fn = shard_map(
                body, mesh=self.mesh,
                in_specs=tuple([store_spec] * k + [idx_spec] * k
                               + [g_spec] * k),
                out_specs=tuple([store_spec] * k + [store_spec]),
            )
            jitted = jax.jit(
                fn, donate_argnums=tuple(range(k)),
                out_shardings=(*store_fmts, tok_sh),
            )
        elif op == "push_row_adagrad":
            def body(*args):
                stores = args[:k]
                accs = args[k:2 * k]
                idxs = args[2 * k:3 * k]
                grads = args[3 * k:4 * k]
                lr, eps = args[4 * k], args[4 * k + 1]
                new_s, new_a = [], []
                for i, (s, a) in enumerate(zip(stores, accs)):
                    n2, a2 = _adagrad_sparse(
                        axis, S, Rs[i], packs[i], dims[i], s, a,
                        idxs[i], grads[i], lr, eps,
                    )
                    new_s.append(n2)
                    new_a.append(a2)
                return (*new_s, *new_a, new_s[0][:1, :1])

            fn = shard_map(
                body, mesh=self.mesh,
                in_specs=tuple([store_spec] * k + [acc_spec] * k
                               + [idx_spec] * k + [g_spec] * k
                               + [P(), P()]),
                out_specs=tuple([store_spec] * k + [acc_spec] * k
                                + [store_spec]),
            )
            jitted = jax.jit(
                fn, donate_argnums=tuple(range(2 * k)),
                out_shardings=(*store_fmts, *([acc_sh] * k), tok_sh),
            )
        elif op == "pull":
            def body(*args):
                stores = args[:k]
                idxs = args[k:]
                return tuple(
                    _pull_rows(axis, S, s, idxs[i], pack=packs[i],
                               dim=dims[i])
                    for i, s in enumerate(stores)
                )

            fn = shard_map(
                body, mesh=self.mesh,
                in_specs=tuple([store_spec] * k + [idx_spec] * k),
                out_specs=tuple([store_spec] * k),
            )
            jitted = jax.jit(fn)
        else:
            raise ValueError(op)
        with self._mu:
            self._programs[key] = jitted
        return jitted

    def _lock_tables(self, names):
        ordered = sorted(set(names))
        for n in ordered:
            self._table_mu[n].acquire()
        return ordered

    def _unlock_tables(self, ordered):
        for n in reversed(ordered):
            self._table_mu[n].release()

    def push_group(self, names, indices_list, grads_list,
                   handle: str = None):
        """Push SEVERAL tables in one dispatch; same semantics per table
        as :meth:`push` (``handle`` applies to all)."""
        log.check(len(names) == len(indices_list) == len(grads_list),
                  "group length mismatch")
        log.check(len(set(names)) == len(names),
                  "duplicate table in group (stores are donated)")
        t0 = time.perf_counter()
        tables = [self._tables[n] for n in names]
        prepped = [
            self._prep(t, i, g)
            for t, i, g in zip(tables, indices_list, grads_list)
        ]
        idxs = [p[0] for p in prepped]
        gs = [p[1] for p in prepped]
        batches = tuple(int(i.shape[1]) for i in idxs)
        ordered = self._lock_tables(names)
        try:
            if handle is None:
                prog = self._sparse_group_program("push", tables, batches)
                outs = prog(*[self._stores[n] for n in names], *idxs, *gs)
                for i, n in enumerate(names):
                    self._stores[n] = outs[i]
                token = outs[len(names)]
            else:
                import jax.numpy as jnp

                _, (lr, eps) = self._parse_handle(handle)
                prog = self._sparse_group_program(
                    "push_row_adagrad", tables, batches
                )
                for n, t in zip(names, tables):
                    self._ensure_acc(n, t)
                outs = prog(
                    *[self._stores[n] for n in names],
                    *[self._acc[n] for n in names],
                    *idxs, *gs, jnp.float32(lr), jnp.float32(eps),
                )
                kk = len(names)
                for i, n in enumerate(names):
                    self._stores[n] = outs[i]
                    self._acc[n] = outs[kk + i]
                token = outs[2 * kk]
        finally:
            self._unlock_tables(ordered)
        for i, (n, t) in enumerate(zip(names, tables)):
            # One dispatch: attribute latency to the first table only so
            # summed profiler durations aren't inflated k-fold.
            self._observe(n, "push", t, batches[i],
                          t0 if i == 0 else time.perf_counter())
        return token

    def pull_group(self, names, indices_list):
        """Pull SEVERAL tables in one dispatch; returns the list of
        [W, n_i, d_i] arrays in ``names`` order."""
        log.check(len(names) == len(indices_list), "group length mismatch")
        t0 = time.perf_counter()
        tables = [self._tables[n] for n in names]
        idxs = [self._prep(t, i)[0] for t, i in zip(tables, indices_list)]
        batches = tuple(int(i.shape[1]) for i in idxs)
        ordered = self._lock_tables(names)
        try:
            # Resolve table.pack under the locks (see push).
            prog = self._sparse_group_program("pull", tables, batches)
            outs = prog(*[self._stores[n] for n in names], *idxs)
        finally:
            self._unlock_tables(ordered)
        for i, (n, t) in enumerate(zip(names, tables)):
            self._observe(n, "pull", t, batches[i],
                          t0 if i == 0 else time.perf_counter())
        return [
            o.reshape(self.num_shards, -1, t.dim)
            for o, t in zip(outs, tables)
        ]

    def pull(self, name: str, indices):
        """indices: [W, n] -> [W, n, d] rows, each worker shard receiving its
        own batch."""
        t0 = time.perf_counter()
        table = self._tables[name]
        idx, _ = self._prep(table, indices)
        with self._table_mu[name]:
            # Resolve table.pack under the lock (see push).
            prog = self._sparse_program("pull", table, int(idx.shape[1]))
            out = prog(self._stores[name], idx)  # global [W*n, d]
        self._observe(name, "pull", table, int(idx.shape[1]), t0)
        return out.reshape(self.num_shards, -1, table.dim)

    def store_array(self, name: str):
        """A consistent snapshot of the sharded table in the LOGICAL
        shard-interleaved layout [rps*S, dim] (for checkpointing) —
        lane-packed tables are unpacked on the way out, so consumers
        never see the physical packing.  Copied under the table lock —
        see CollectiveEngine.store_array.  For a plain device-drain use
        :meth:`block` (no copy)."""
        import jax.numpy as jnp

        with self._table_mu[name]:
            t = self._tables[name]
            # Capture layout metadata WITH the snapshot so a concurrent
            # pack change (orbax compat shim) cannot desynchronize the
            # copy from its unpack.
            pack, rps = t.pack, t.rows_per_shard
            host = np.asarray(jnp.copy(self._stores[name]))
        return _unpack_host(host, rps, self.num_shards, pack, t.dim)

    def store_raw(self, name: str):
        """A consistent snapshot of the PHYSICAL sharded store (the
        lane-packed layout, matching :meth:`store_spec`) — what
        legacy-format orbax checkpoints saved and restore verbatim."""
        import jax.numpy as jnp

        with self._table_mu[name]:
            return jnp.copy(self._stores[name])

    def store_global_device(self, name: str):
        """The GLOBAL logical table ``[num_rows, dim]`` as a DEVICE
        computation (no host fetch — multi-host safe): unpack the lane
        packing and de-interleave the shard layout with pure
        reshape/transpose ops, the jnp mirror of
        :func:`_deinterleave_rows`.  This is what the fleet-size-portable
        orbax checkpoint (v2) saves: a logical array any shard count can
        restore."""
        import jax.numpy as jnp

        with self._table_mu[name]:
            t = self._tables[name]
            S, rps, pack, dim = (self.num_shards, t.rows_per_shard,
                                 t.pack, t.dim)
            num_rows = t.num_rows
            store = jnp.copy(self._stores[name])
        # Unpack ([phys*S, pack*dim] -> per-shard rows) and de-interleave
        # in one reshape/transpose chain.
        return store.reshape(S, rps, dim).transpose(1, 0, 2).reshape(
            rps * S, dim
        )[:num_rows]

    def acc_global_device(self, name: str):
        """GLOBAL logical Adagrad accumulator ``[num_rows]``, device-side
        (see :meth:`store_global_device`)."""
        import jax.numpy as jnp

        with self._table_mu[name]:
            t = self._tables[name]
            log.check(name in self._acc, f"no accumulator for {name!r}")
            S, rps = self.num_shards, t.rows_per_shard
            acc = jnp.copy(self._acc[name])
        return acc.reshape(S, rps).transpose(1, 0).reshape(-1)[:t.num_rows]

    def store_spec(self, name: str):
        """Shape/dtype/sharding of a table without copying it (restore
        targets)."""
        import jax

        with self._table_mu[name]:
            arr = self._stores[name]
            return jax.ShapeDtypeStruct(
                arr.shape, arr.dtype, sharding=arr.sharding
            )

    def block(self, name: Optional[str] = None) -> None:
        """Wait for outstanding device work without copying the table."""
        if name is not None:
            names = [name]
        else:
            with self._mu:
                names = list(self._stores)
        for n in names:
            with self._table_mu[n]:
                self._stores[n].block_until_ready()

    def set_store_array(self, name: str, value,
                        global_rows: bool = False) -> None:
        """Restore a table (checkpoint resume).  ``global_rows=True``
        accepts the fleet-size-portable GLOBAL row order ([num_rows,
        dim], the v2 checkpoint layout) and interleaves it for THIS
        engine's shard count; otherwise host arrays must already be in
        the shard-interleaved layout ``store_array`` exposes.  Sharded
        ``jax.Array``s (multi-host restores) are assigned directly."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        log.check(name in self._tables, f"table {name!r} not registered")
        table = self._tables[name]
        S = self.num_shards
        # Host arrays arrive in LOGICAL layouts (global rows or
        # interleaved — what store_array exposes) and are packed here;
        # sharded jax.Arrays (orbax same-fleet restores) carry the
        # PHYSICAL store shape.
        expected = (table.rows_per_shard * S, table.dim)
        phys_expected = (table.phys_rows * S, table.pack * table.dim)
        sharding = NamedSharding(self.mesh, P(self.axis, None))
        if global_rows and isinstance(value, jax.Array):
            # Fleet-portable DEVICE restore (orbax v2): interleave +
            # re-pack on device — the jnp mirror of _interleave_rows +
            # _pack_host, multi-host safe (no host fetch).
            import jax.numpy as jnp

            log.check_eq(tuple(value.shape), (table.num_rows, table.dim),
                         "bad global-rows restore shape")
            rps, dim, pack = table.rows_per_shard, table.dim, table.pack
            v = jnp.pad(
                value.astype(table.dtype),
                ((0, rps * S - table.num_rows), (0, 0)),
            )
            inter = v.reshape(rps, S, dim).transpose(1, 0, 2)
            phys = inter.reshape(S * table.phys_rows, pack * dim)
            placed = jax.device_put(phys, sharding)
            with self._table_mu[name]:
                self._stores[name] = placed
            return
        if global_rows and not isinstance(value, jax.Array):
            host = np.asarray(value)
            log.check_eq(tuple(host.shape), (table.num_rows, table.dim),
                         "bad global-rows restore shape")
            value = _interleave_rows(
                host, table.num_rows, table.rows_per_shard,
                S, table.dtype,
            )
        if isinstance(value, jax.Array):
            equivalent = value.sharding == sharding or (
                hasattr(value.sharding, "is_equivalent_to")
                and value.sharding.is_equivalent_to(sharding, value.ndim)
            )
            if equivalent:
                log.check_eq(tuple(value.shape), phys_expected,
                             "bad restore shape")
                with self._table_mu[name]:
                    self._stores[name] = value
                return
        host = np.asarray(value)
        unrounded_rps = -(-table.num_rows // S)
        if (tuple(host.shape) != expected
                and host.ndim == 2 and host.shape[1] == table.dim
                and host.shape[0] == unrounded_rps * S):
            # COMPAT, narrowly: a v1 checkpoint from an engine with the
            # SAME shard count whose rows_per_shard was the plain
            # ceil(num_rows/S) (pre-lane-packing rounding).  The shape
            # alone cannot distinguish other shard counts (v1 meta has
            # no num_shards), so only this exact size re-interleaves —
            # anything else still fails loud below.
            host = _interleave_rows(
                _deinterleave_rows(host, table.num_rows, unrounded_rps,
                                   S),
                table.num_rows, table.rows_per_shard, S, table.dtype,
            )
        log.check_eq(tuple(host.shape), expected, "bad restore shape")
        placed = self._place(
            _pack_host(host, table.rows_per_shard, S, table.pack,
                       table.dim),
            sharding,
        )
        with self._table_mu[name]:
            self._stores[name] = placed

    def reshard(self, mesh, axis_name: Optional[str] = None) -> None:
        """Re-lay every registered table onto a new mesh — the sparse
        half of the engine elastic tier (see CollectiveEngine.reshard
        and reshard_staged for the pair-atomicity split).

        Rows are de-interleaved to global order on the host, the
        row→shard mapping is recut for the new shard count (global row r
        lives on shard ``r % S`` — the modulo sharding that load-balances
        skewed key distributions), and programs rebuild lazily.

        Multi-process meshes work on either side; reshard is then a
        COLLECTIVE — every participating process calls it with the same
        new mesh (see CollectiveEngine.reshard)."""
        with self.reshard_staged(mesh, axis_name) as commit:
            commit()

    @contextlib.contextmanager
    def reshard_staged(self, mesh, axis_name: Optional[str] = None):
        """Stage a table recut and yield its zero-failure commit
        closure — same contract as CollectiveEngine.reshard_staged
        (everything fallible on entry, commit is assignments only,
        table locks held until exit)."""
        from .placement import (
            local_shard_count,
            mesh_is_multiprocess,
            to_host_global,
        )

        new_multiprocess = mesh_is_multiprocess(mesh)
        axis = axis_name or self.axis
        log.check(axis in mesh.axis_names,
                  f"axis {axis!r} not in new mesh")
        with self._mu:
            names = list(self._tables)
        ordered = sorted(names)
        for n in ordered:
            self._table_mu[n].acquire()
        try:
            # Sorted iteration: the multi-process snapshot is a sequence
            # of collectives — every process must issue them in the same
            # order (see CollectiveEngine.reshard).
            old_mp = self._multiprocess
            names = ordered
            snap = {}
            for n in names:
                t = self._tables[n]
                S, rps = self.num_shards, t.rows_per_shard
                host = _unpack_host(
                    to_host_global(self._stores[n], old_mp),
                    rps, S, t.pack, t.dim,
                )
                glob = _deinterleave_rows(host, t.num_rows, rps, S)
                acc_glob = None
                if n in self._acc:
                    acc_glob = _deinterleave_rows(
                        to_host_global(self._acc[n], old_mp),
                        t.num_rows, rps, S,
                    )
                snap[n] = (t, glob, acc_glob)

            # STAGE: build every new placement against the NEW mesh
            # without touching engine state — a failed recut aborts with
            # every table intact on the old mesh (crash-consistency, see
            # CollectiveEngine.reshard's staged commit).
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .placement import place_host_array

            new_num_shards = mesh.shape[axis]
            row_sharding = NamedSharding(mesh, P(axis, None))
            acc_sharding = NamedSharding(mesh, P(axis))
            staged = {}
            for n in names:
                t, glob, acc_glob = snap[n]
                rps = -(-t.num_rows // new_num_shards)
                rps = -(-rps // t.pack) * t.pack
                store = place_host_array(
                    mesh,
                    _pack_host(
                        _interleave_rows(glob, t.num_rows, rps,
                                         new_num_shards, t.dtype),
                        rps, new_num_shards, t.pack, t.dim,
                    ),
                    row_sharding, new_multiprocess,
                )
                acc = None
                if acc_glob is not None:
                    acc = place_host_array(
                        mesh,
                        _interleave_rows(acc_glob, t.num_rows, rps,
                                         new_num_shards, np.float32),
                        acc_sharding, new_multiprocess,
                    )
                staged[n] = (
                    SparseTable(n, t.num_rows, t.dim, rps, t.dtype,
                                pack=t.pack),
                    store,
                    acc,
                )

            # COMMIT closure: plain assignments only — never a torn
            # table set.
            def commit() -> None:
                self.mesh = mesh
                self.axis = axis
                self.num_shards = new_num_shards
                self._multiprocess = new_multiprocess
                self._local_shard_count = (
                    local_shard_count(mesh) if new_multiprocess
                    else new_num_shards
                )
                with self._mu:
                    self._programs.clear()
                    for n in names:
                        table, store, acc = staged[n]
                        self._tables[n] = table
                        self._stores[n] = store
                        if acc is not None:
                            self._acc[n] = acc

            yield commit
        finally:
            for n in reversed(ordered):
                self._table_mu[n].release()

    def table(self, name: str) -> SparseTable:
        return self._tables[name]
