"""Small-op aggregation plane, end to end (docs/batching.md).

The wire format is covered in test_wire.py; this file proves the
TIER: the worker-side combiner (grouping, parity, caps, failure
routing), the capability negotiation, the server's batched group
apply (per-op results, per-op admission sheds, per-op errors), the
hot-cache read-your-writes contract through batched frames, and the
decline matrix (elastic, zpull, traced ops, custom cmds).
"""

import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, "tests")
from helpers import LoopbackCluster  # noqa: E402

from pslite_tpu.kv import batching  # noqa: E402
from pslite_tpu.kv.batching import (  # noqa: E402
    OpCombiner,
    batchable,
    build_batch_message,
    op_wire_cost,
    split_batch_message,
)
from pslite_tpu.kv.kv_app import (  # noqa: E402
    ElasticZeroCopyError,
    KVMeta,
    KVPairs,
    KVServer,
    KVServerDefaultHandle,
    KVServerOptimizerHandle,
    KVWorker,
    OverloadError,
)
from pslite_tpu.message import Message  # noqa: E402
from pslite_tpu.sarray import SArray  # noqa: E402


def _op_msg(ts, key, vals, recver=8, tenant=0, priority=0, pull=False):
    msg = Message()
    m = msg.meta
    m.request = True
    m.head = 0
    m.push = not pull
    m.pull = pull
    m.timestamp = ts
    m.key = key
    m.recver = recver
    m.tenant = tenant
    m.priority = priority
    msg.add_data(SArray(np.array([key], np.uint64)))
    msg.add_data(SArray(np.asarray(vals, np.float32)))
    m.val_len = msg.data[1].nbytes
    return msg


# -- combiner units ----------------------------------------------------------


def test_combiner_groups_never_cross_tenant_priority_codec():
    """The group key is the LANE identity (destination, tenant,
    priority) — batching never crosses those.  Codec-mismatched ops
    SHARE the group's FIFO (order never relaxes within a lane) but
    never MERGE: a flush emits them as separate consecutive frames."""
    from pslite_tpu.message import CodecInfo

    base = _op_msg(1, 1, np.ones(4))
    other_dest = _op_msg(2, 2, np.ones(4), recver=10)
    other_tenant = _op_msg(3, 3, np.ones(4), tenant=1)
    other_prio = _op_msg(4, 4, np.ones(4), priority=1)
    keys = {OpCombiner.group_key(m)
            for m in (base, other_dest, other_tenant, other_prio)}
    assert len(keys) == 4
    # Same lane => same group, even with a codec (order preservation).
    coded = _op_msg(5, 5, np.ones(4))
    coded.meta.codec = CodecInfo(codec=1, raw_len=16, block=128)
    assert OpCombiner.group_key(base) == OpCombiner.group_key(coded)
    # ... but codec-mismatched ops never merge: raw, raw, coded, raw
    # flushes as [batch(2), coded single, raw single] — in order.
    sent = []
    c = OpCombiner(lambda m: sent.append(m) or 0, lambda msgs, exc: None,
                   max_bytes=1 << 20)
    items = [(_op_msg(1, 1, np.ones(4)), 40, True),
             (_op_msg(2, 2, np.ones(4)), 40, True),
             (coded, 40, True),
             (_op_msg(6, 6, np.ones(4)), 40, True)]
    c._flush(items)
    shapes = [len(m.meta.batch.ops) if m.meta.batch else 1 for m in sent]
    assert shapes == [2, 1, 1]
    got = [op.timestamp for m in sent
           for op in (m.meta.batch.ops if m.meta.batch else [m.meta])]
    assert got == [1, 2, 5, 6]  # submission order, never relaxed


def test_combiner_single_op_passthrough_and_merge():
    """A lone op is sent as its ORIGINAL message (low-load parity); a
    concurrent burst merges into one EXT_BATCH frame in submission
    order."""
    sent = []
    done = threading.Event()

    def send(m):
        sent.append(m)
        if len(sent) >= 2:
            done.set()
        return 0

    c = OpCombiner(send, lambda msgs, exc: None, max_bytes=1 << 20)
    lone = _op_msg(1, 1, np.ones(4))
    c.submit(lone)
    for _ in range(100):
        if sent:
            break
        time.sleep(0.01)
    assert sent and sent[0] is lone and sent[0].meta.batch is None
    # Burst: queue while the dispatcher is parked on a fresh group
    # (first_enq pinned in the past so the adaptive hold closes at the
    # very next pickup).
    with c._cv:  # hold the lock so the burst lands as one group
        key = OpCombiner.group_key(lone)
        for i in range(2, 6):
            c._groups.setdefault(key, []).append(
                (_op_msg(i, i, np.ones(4)), 32, True))
        c._first_enq[key] = 0.0
        c._cv.notify_all()
    for _ in range(200):
        if len(sent) >= 2:
            break
        time.sleep(0.01)
    env = sent[1]
    assert env.meta.batch is not None
    assert [op.timestamp for op in env.meta.batch.ops] == [2, 3, 4, 5]
    c.stop()


def test_combiner_flush_splits_at_op_cap():
    """A backpressured group larger than the per-frame op cap emits as
    consecutive capped frames, order preserved."""
    sent = []
    c = OpCombiner(lambda m: sent.append(m) or 0, lambda msgs, exc: None,
                   max_bytes=1 << 30, max_ops=4)
    batch = [(_op_msg(i, i, np.ones(2)), 8, True) for i in range(10)]
    c._flush(batch)
    assert [len(m.meta.batch.ops) if m.meta.batch else 1
            for m in sent] == [4, 4, 2]
    got = [op.timestamp for m in sent
           for op in (m.meta.batch.ops if m.meta.batch else [m.meta])]
    assert got == list(range(10))


def test_combiner_error_hook_routes_failures():
    """A transport failure during a flush reaches on_error with the
    member messages (the worker fails each sub-op's slice from it)."""
    failed = []

    def send(m):
        raise ConnectionError("down")

    c = OpCombiner(send, lambda msgs, exc: failed.append((msgs, exc)),
                   max_bytes=1 << 20)
    c._flush([(_op_msg(1, 1, np.ones(2)), 8, True),
              (_op_msg(2, 2, np.ones(2)), 8, True)])
    assert len(failed) == 1 and len(failed[0][0]) == 2
    assert isinstance(failed[0][1], ConnectionError)


def test_build_split_roundtrip_preserves_ops():
    msgs = [_op_msg(i, i * 10, np.full(4, float(i))) for i in range(1, 5)]
    env = build_batch_message(msgs)
    assert env.meta.push and not env.meta.pull
    assert len(env.data) == 8
    subs = split_batch_message(env)
    assert len(subs) == 4
    for i, s in enumerate(subs, start=1):
        assert s.meta.timestamp == i and s.meta.key == i * 10
        np.testing.assert_array_equal(
            s.data[1].numpy(), np.full(4, np.float32(i)))


def test_batchable_declines():
    """Structural decline rows: custom cmds, zpull-marked, chunk
    frames, and >3-segment (lens'd) payloads pass through.  Traced ops
    MERGE (the trace id rides the per-op table — tracing must not
    perturb the batch plane it measures, docs/observability.md)."""
    from pslite_tpu.message import OPT_ZPULL, ChunkInfo

    ok = _op_msg(1, 1, np.ones(4))
    assert batchable(ok)
    traced = _op_msg(1, 1, np.ones(4))
    traced.meta.trace = 99
    assert batchable(traced)
    env = build_batch_message([traced, _op_msg(2, 2, np.ones(4))])
    assert env.meta.trace == 0  # the ENVELOPE stays untraced
    assert [op.trace for op in env.meta.batch.ops] == [99, 0]
    subs = split_batch_message(env)
    assert [s.meta.trace for s in subs] == [99, 0]
    cmd = _op_msg(1, 1, np.ones(4))
    cmd.meta.head = 0x77
    assert not batchable(cmd)
    zp = _op_msg(1, 1, np.ones(4))
    zp.meta.option = OPT_ZPULL
    assert not batchable(zp)
    ck = _op_msg(1, 1, np.ones(4))
    ck.meta.chunk = ChunkInfo(xfer=1, index=0, total=2)
    assert not batchable(ck)
    # A raw ragged push is keys+vals+LENS = 3 segments: excluded (the
    # batched intake is a fixed-k contract) — while a codec push's 3
    # segments (keys+codes+scales) stay eligible.
    from pslite_tpu.message import CodecInfo

    lens = _op_msg(1, 1, np.ones(4))
    lens.add_data(SArray(np.ones(1, np.int32)))
    assert len(lens.data) == 3 and not batchable(lens)
    coded = _op_msg(1, 1, np.ones(4))
    coded.meta.codec = CodecInfo(codec=1, raw_len=16, block=128)
    coded.add_data(SArray(np.ones(1, np.float32)))  # scales
    assert len(coded.data) == 3 and batchable(coded)
    coded.add_data(SArray(np.ones(1, np.int32)))  # codec + lens: out
    assert not batchable(coded)
    assert op_wire_cost(ok) == ok.data[0].nbytes + ok.data[1].nbytes


# -- end to end --------------------------------------------------------------


def _storm_cluster(env_extra=None, num_servers=1, handle=None):
    cl = LoopbackCluster(num_workers=1, num_servers=num_servers,
                         env_extra={"PS_BATCH_BYTES": "65536",
                                    **(env_extra or {})})
    cl.start()
    servers = []
    for po in cl.servers:
        s = KVServer(0, postoffice=po)
        s.set_request_handle(handle() if handle else
                             KVServerDefaultHandle())
        servers.append(s)
    w = KVWorker(0, 0, postoffice=cl.workers[0])
    return cl, servers, w


def _teardown(cl, servers, w):
    w.stop()
    for s in servers:
        s.stop()
    cl.finalize()


def test_batched_push_storm_bit_exact_and_batches_formed():
    """Concurrent small pushes coalesce into EXT_BATCH frames; the
    accumulated store is bit-exact vs the arithmetic sum; the van's
    batch counters advance (the psmon ops/frame source)."""
    cl, servers, w = _storm_cluster(num_servers=2)
    try:
        span = (1 << 64) // 2
        keys = np.sort(np.array([3, 77, span + 5, span + 900], np.uint64))
        rng = np.random.default_rng(7)
        total = np.zeros(4 * 64, np.float32)
        tss = []
        for _ in range(150):
            vals = rng.normal(size=4 * 64).astype(np.float32)
            total += vals
            tss.append(w.push(keys, vals.copy()))
        for ts in tss:
            w.wait(ts)
        out = np.zeros_like(total)
        w.wait(w.pull(keys, out))
        np.testing.assert_allclose(out, total, rtol=1e-4)
        assert w.combiner is not None
        assert w.combiner.flushed_frames > 0
        van = cl.workers[0].van
        assert van._c_batched_frames.value == w.combiner.flushed_frames
        assert van._c_batch_ops.value == w.combiner.flushed_ops
        assert van._c_batch_ops.value > van._c_batched_frames.value
    finally:
        _teardown(cl, servers, w)


def test_batched_pulls_return_correct_per_op_data():
    """Concurrent small pulls coalesce; the ONE batched response frame
    carries each op's own keys+vals and every destination buffer lands
    bit-exact."""
    cl, servers, w = _storm_cluster()
    try:
        nkeys = 24
        all_keys = np.arange(nkeys, dtype=np.uint64)
        vals = np.arange(nkeys * 16, dtype=np.float32)
        w.wait(w.push(all_keys, vals))
        outs = [np.zeros(16, np.float32) for _ in range(nkeys)]
        tss = [w.pull(np.array([k], np.uint64), outs[k])
               for k in range(nkeys)]
        for ts in tss:
            w.wait(ts)
        for k in range(nkeys):
            np.testing.assert_array_equal(
                outs[k], vals[k * 16:(k + 1) * 16])
    finally:
        _teardown(cl, servers, w)


def test_mixed_push_pull_batches_and_order():
    """Pushes and pulls of the same keys share a group (same dest/
    tenant/priority); each pull observes every push WAITED before it
    was issued (per-dest frame order == submission order)."""
    cl, servers, w = _storm_cluster()
    try:
        keys = np.array([5], np.uint64)
        acc = np.zeros(32, np.float32)
        for i in range(20):
            vals = np.full(32, float(i + 1), np.float32)
            acc += vals
            w.wait(w.push(keys, vals))
        out = np.zeros(32, np.float32)
        w.wait(w.pull(keys, out))
        np.testing.assert_array_equal(out, acc)
    finally:
        _teardown(cl, servers, w)


def test_parity_batching_off_sends_no_batch_frames():
    """PS_BATCH_BYTES=0 (the default): no combiner, no EXT_BATCH frame
    ever leaves — byte-identical to a pre-batching build."""
    cl = LoopbackCluster(num_workers=1, num_servers=1,
                         env_extra={"PS_BATCH_BYTES": "0"})
    cl.start()
    servers = []
    try:
        s = KVServer(0, postoffice=cl.servers[0])
        s.set_request_handle(KVServerDefaultHandle())
        servers.append(s)
        w = KVWorker(0, 0, postoffice=cl.workers[0])
        assert w.combiner is None
        keys = np.array([1, 2], np.uint64)
        tss = [w.push(keys, np.ones(2 * 8, np.float32))
               for _ in range(20)]
        for ts in tss:
            w.wait(ts)
        van = cl.workers[0].van
        assert van._c_batched_frames.value == 0
        assert van._c_batch_ops.value == 0
        # ... and no capability probe traffic either: with batching
        # off the negotiation machinery must stay silent.
        assert not w._batch_probe_ts and not w._batch_caps
        w.stop()
    finally:
        for s in servers:
            s.stop()
        cl.finalize()


def test_capability_negotiation_and_incapable_peer():
    """The first eligible op probes the destination (BATCH_PROBE_CMD);
    a capable server answers and batching engages.  A destination
    recorded INCAPABLE never receives an EXT_BATCH frame — old
    decoders must never see a frame they cannot parse."""
    cl, servers, w = _storm_cluster()
    try:
        keys = np.array([1], np.uint64)
        vals = np.ones(8, np.float32)
        w.wait(w.push(keys, vals))
        dest = None
        for _ in range(200):
            with w._mu:
                caps = dict(w._batch_caps)
            if caps:
                dest = next(iter(caps))
                break
            time.sleep(0.01)
        assert dest is not None and caps[dest] is True
        # Storm: batches now form.
        tss = [w.push(keys, vals) for _ in range(60)]
        for ts in tss:
            w.wait(ts)
        assert w.combiner.flushed_frames > 0
        # Flip the destination to incapable: every further op passes
        # through unbatched.
        before = w.combiner.flushed_frames
        with w._mu:
            w._batch_caps[dest] = False
        tss = [w.push(keys, vals) for _ in range(60)]
        for ts in tss:
            w.wait(ts)
        assert w.combiner.flushed_frames == before
    finally:
        _teardown(cl, servers, w)


def test_negotiate_off_asserts_capable():
    """PS_BATCH_NEGOTIATE=0: the operator asserts a homogeneous
    cluster — no probe round trip, batching engages immediately."""
    cl, servers, w = _storm_cluster(
        env_extra={"PS_BATCH_NEGOTIATE": "0"})
    try:
        keys = np.array([1], np.uint64)
        tss = [w.push(keys, np.ones(8, np.float32)) for _ in range(60)]
        for ts in tss:
            w.wait(ts)
        assert not w._batch_probe_ts  # no probes ever sent
        assert w.combiner.flushed_frames > 0
        out = np.zeros(8, np.float32)
        w.wait(w.pull(keys, out))
        np.testing.assert_array_equal(out, np.full(8, 60.0, np.float32))
    finally:
        _teardown(cl, servers, w)


def test_admission_sheds_sub_ops_individually():
    """Per-tenant admission through a batched frame sheds SUB-OPS, not
    the whole frame (docs/qos.md note): some waits raise the retryable
    OverloadError, the rest apply, and the store ends bit-exact at
    applied-count."""
    cl, servers, w = _storm_cluster(env_extra={
        "PS_TENANTS": "serve:8,train:1",
        "PS_TENANT_QUEUE_LIMIT": "4",
        "PS_BATCH_NEGOTIATE": "0",
    })
    try:
        keys = np.arange(8, dtype=np.uint64)
        vals = np.ones(8 * 1024, np.float32)
        tss = [w.push(keys, vals, tenant="train") for _ in range(64)]
        applied = shed = 0
        for ts in tss:
            try:
                w.wait(ts)
                applied += 1
            except OverloadError:
                shed += 1
        assert applied + shed == 64 and applied > 0
        out = np.zeros_like(vals)
        w.wait(w.pull(keys, out, tenant="train"))
        assert np.all(out == np.float32(applied)), (applied, out[:2])
        # Batches really formed (sheds rode per-op OPT_OVERLOAD codes
        # inside batched responses, not whole-frame rejects).
        assert w.combiner.flushed_frames > 0
    finally:
        _teardown(cl, servers, w)


class _PoisonKeyHandle(KVServerDefaultHandle):
    """Raises while applying key 13 — the per-op error-code path."""

    def apply_shard(self, meta, keys, segs):
        if meta.push and 13 in keys.tolist():
            raise RuntimeError("poison key")
        return super().apply_shard(meta, keys, segs)


def test_per_op_error_codes_fail_only_the_poisoned_op():
    """A sub-op whose apply raises fails ITS wait() fast
    (OPT_APPLY_ERROR in the per-op table); sibling sub-ops in the same
    frame complete normally."""
    cl, servers, w = _storm_cluster(handle=_PoisonKeyHandle,
                                    env_extra={"PS_BATCH_NEGOTIATE": "0"})
    try:
        good = [w.push(np.array([k], np.uint64), np.ones(64, np.float32))
                for k in (1, 2, 3)]
        bad = w.push(np.array([13], np.uint64), np.ones(64, np.float32))
        good += [w.push(np.array([k], np.uint64), np.ones(64, np.float32))
                 for k in (4, 5)]
        for ts in good:
            w.wait(ts)  # siblings unaffected
        with pytest.raises(RuntimeError, match="failed server-side"):
            w.wait(bad)
        out = np.zeros(64, np.float32)
        w.wait(w.pull(np.array([4], np.uint64), out))
        np.testing.assert_array_equal(out, np.ones(64, np.float32))
    finally:
        _teardown(cl, servers, w)


def test_serial_path_batches_without_apply_pool():
    """PS_APPLY_SHARDS=0 (no shard pool): batched frames still decode
    once and answer with ONE response frame via the serial inline
    loop — the per-frame saving without shard concurrency."""
    cl, servers, w = _storm_cluster(env_extra={
        "PS_APPLY_SHARDS": "0", "PS_BATCH_NEGOTIATE": "0"})
    try:
        keys = np.array([2, 9], np.uint64)
        tss = [w.push(keys, np.ones(2 * 16, np.float32))
               for _ in range(40)]
        for ts in tss:
            w.wait(ts)
        out = np.zeros(2 * 16, np.float32)
        w.wait(w.pull(keys, out))
        np.testing.assert_array_equal(out, np.full(2 * 16, 40.0,
                                                   np.float32))
        assert w.combiner.flushed_frames > 0
        # The server answered batched frames with batched responses —
        # counted on the RESPONSE-direction ledger (psmon "resp
        # ops/F"), never mixed into the request-direction one.
        srv_van = cl.servers[0].van
        assert srv_van._c_resp_batched_frames.value > 0
        assert srv_van._c_batched_frames.value == 0
    finally:
        _teardown(cl, servers, w)


def test_optimizer_handle_order_preserved_through_batching():
    """KVServerOptimizerHandle is ORDER-SENSITIVE (momentum): a
    batched storm must apply per-key in submission order — compare
    against an unbatched run of the identical sequence."""

    def run(batch_bytes):
        cl = LoopbackCluster(num_workers=1, num_servers=1,
                             env_extra={"PS_BATCH_BYTES": batch_bytes,
                                        "PS_BATCH_NEGOTIATE": "0"})
        cl.start()
        servers = []
        try:
            s = KVServer(0, postoffice=cl.servers[0])
            s.set_request_handle(KVServerOptimizerHandle(
                kind="sgd_momentum", lr=0.1))
            servers.append(s)
            w = KVWorker(0, 0, postoffice=cl.workers[0])
            keys = np.array([3], np.uint64)
            rng = np.random.default_rng(11)
            tss = [w.push(keys, rng.normal(size=32).astype(np.float32))
                   for _ in range(50)]
            for ts in tss:
                w.wait(ts)
            out = np.zeros(32, np.float32)
            w.wait(w.pull(keys, out))
            w.stop()
            return out
        finally:
            for s in servers:
                s.stop()
            cl.finalize()

    batched = run("65536")
    unbatched = run("0")
    np.testing.assert_array_equal(batched, unbatched)


def test_unmergeable_ops_never_overtake_queued_siblings():
    """An op that cannot MERGE (here: a custom-cmd push, which
    ``batchable`` declines) still rides the combiner's per-lane FIFO
    in position — it must never overtake queued mergeable siblings to
    the SAME key.  Proven with the order-sensitive momentum optimizer:
    a concurrent sequence interleaving plain and custom-cmd pushes of
    one key must end bit-identical to the unbatched run."""

    def run(batch_bytes):
        cl = LoopbackCluster(num_workers=1, num_servers=1,
                             env_extra={"PS_BATCH_BYTES": batch_bytes,
                                        "PS_BATCH_NEGOTIATE": "0"})
        cl.start()
        servers = []
        try:
            s = KVServer(0, postoffice=cl.servers[0])
            s.set_request_handle(KVServerOptimizerHandle(
                kind="sgd_momentum", lr=0.1))
            servers.append(s)
            w = KVWorker(0, 0, postoffice=cl.workers[0])
            keys = np.array([3], np.uint64)
            rng = np.random.default_rng(23)
            tss = []
            for i in range(40):
                vals = rng.normal(size=32).astype(np.float32)
                # Every 5th op carries a custom cmd: structurally
                # unmergeable, so it MUST flow through the lane FIFO
                # as a single frame in position — under the old
                # bypass it overtook the queued batch and momentum
                # diverged.
                tss.append(w.push(keys, vals, cmd=5 if i % 5 == 4
                                  else 0))
            for ts in tss:
                w.wait(ts)
            out = np.zeros(32, np.float32)
            w.wait(w.pull(keys, out))
            w.stop()
            return out
        finally:
            for s in servers:
                s.stop()
            cl.finalize()

    np.testing.assert_array_equal(run("65536"), run("0"))


# -- hot cache x batching (satellite) ----------------------------------------


def test_hot_cache_read_your_writes_through_batching():
    """Satellite (ISSUE 10): per-sub-op stamps keep the hot-cache
    contract through batched frames — a batched PUSH's response
    invalidates older fills (read-your-writes), a batched PULL's
    response fills with its intake stamp, and a racing stale fill
    parks invalid."""
    cl, servers, w = _storm_cluster(env_extra={
        "PS_HOT_CACHE": "1", "PS_BATCH_NEGOTIATE": "0"})
    try:
        nkeys = 8
        outs = [np.zeros(16, np.float32) for _ in range(nkeys)]
        one_keys = [np.array([k], np.uint64) for k in range(nkeys)]
        vals = np.arange(nkeys * 16, dtype=np.float32)
        w.wait(w.push(np.arange(nkeys, dtype=np.uint64), vals))
        # Batched pulls fill the cache (per-op stamps from the table).
        tss = [w.pull(one_keys[k], outs[k]) for k in range(nkeys)]
        for ts in tss:
            w.wait(ts)
        assert len(w.hot_cache) > 0
        hits0 = w.po.metrics.counter("kv.hot_cache.hits").value
        # Repeat pulls serve locally.
        for k in range(nkeys):
            w.wait(w.pull(one_keys[k], outs[k]))
        assert w.po.metrics.counter("kv.hot_cache.hits").value > hits0
        # Batched pushes of the same keys: the response's per-op stamps
        # must invalidate the cached fills — the next pulls observe the
        # NEW values (read-your-writes survives batching).
        tss = [w.push(one_keys[k], np.full(16, 100.0 + k, np.float32))
               for k in range(nkeys)]
        for ts in tss:
            w.wait(ts)
        for k in range(nkeys):
            w.wait(w.pull(one_keys[k], outs[k]))
            np.testing.assert_array_equal(
                outs[k],
                vals[k * 16:(k + 1) * 16] + np.float32(100.0 + k),
            )
        # Fill-race skip: a fill whose stamp predates a known push
        # parks invalid (HotKeyCache.fill's stamp check) — simulate
        # the race directly against the cache.
        cache = w.hot_cache
        stale_stamp = 1
        cache.observe(next(iter(cl.servers)).van.my_node.id
                      if hasattr(next(iter(cl.servers)), "van") else 8,
                      1 << 60)
        n_before = len(cache)
        cache.fill(8, stale_stamp, np.array([999], np.uint64),
                   np.ones(4, np.float32))
        assert len(cache) == n_before  # born-invalid fill skipped
    finally:
        _teardown(cl, servers, w)


# -- decline matrix ----------------------------------------------------------


def test_elastic_declines_batching_and_zpull_raises():
    """PS_ELASTIC=1: the combiner declines (warned, unbatched sends)
    and — the ISSUE 10 satellite fix — ZPush/ZPull registered buffers
    now raise the documented ElasticZeroCopyError instead of the PR 9
    silent decline."""
    cl = LoopbackCluster(num_workers=1, num_servers=1,
                         env_extra={"PS_ELASTIC": "1",
                                    "PS_BATCH_BYTES": "65536",
                                    "PS_HEARTBEAT_INTERVAL": "0"})
    cl.start()
    servers = []
    try:
        s = KVServer(0, postoffice=cl.servers[0])
        s.set_request_handle(KVServerDefaultHandle())
        servers.append(s)
        w = KVWorker(0, 0, postoffice=cl.workers[0])
        assert w.combiner is None  # declined loudly at construction
        with pytest.raises(ElasticZeroCopyError):
            w.alloc_pull_buffer(np.array([1, 2], np.uint64), 8)
        w.stop()
    finally:
        for s in servers:
            s.stop()
        cl.finalize()


class _RaggedHandle:
    """Serial-path handler answering pulls with RAGGED (lens) results
    — per-key value lengths differ, so the response must carry the
    lens segment through the batched response table too."""

    def __call__(self, meta, kvs, server):
        if meta.pull:
            k = int(kvs.keys[0])
            vals = np.full(k + 1, float(k), np.float32)  # len = key+1
            server.response(meta, KVPairs(
                keys=kvs.keys, vals=vals,
                lens=np.array([k + 1], np.int32),
            ))
        else:
            server.response(meta)


def test_ragged_pull_responses_carry_lens_through_batching():
    """A batched pull whose (serial-path) result is ragged gets its
    per-op LENS segment back — dropping it would hand the worker
    un-segmentable values (review regression)."""
    cl = LoopbackCluster(num_workers=1, num_servers=1,
                         env_extra={"PS_BATCH_BYTES": "65536",
                                    "PS_BATCH_NEGOTIATE": "0",
                                    "PS_APPLY_SHARDS": "0"})
    cl.start()
    servers = []
    try:
        s = KVServer(0, postoffice=cl.servers[0])
        s.set_request_handle(_RaggedHandle())
        servers.append(s)
        w = KVWorker(0, 0, postoffice=cl.workers[0])
        outs = {k: np.zeros(k + 1, np.float32) for k in (1, 2, 3, 4)}
        lens_out = {k: np.zeros(1, np.int32) for k in outs}
        tss = [w.pull(np.array([k], np.uint64), outs[k],
                      lens=lens_out[k]) for k in outs]
        for ts in tss:
            w.wait(ts)
        for k in outs:
            np.testing.assert_array_equal(
                outs[k], np.full(k + 1, float(k), np.float32))
            assert lens_out[k][0] == k + 1
        w.stop()
    finally:
        for s in servers:
            s.stop()
        cl.finalize()


def test_abandoned_batch_frame_fails_every_sub_op():
    """The van's give-up path (dead peer / resender exhausted) is
    batch-aware: one abandoned EXT_BATCH frame synthesizes an
    OPT_SEND_FAILED per SUB-OP, so every member's wait() raises
    instead of only the envelope's first timestamp."""
    cl, servers, w = _storm_cluster(env_extra={"PS_BATCH_NEGOTIATE": "0"})
    try:
        dest = cl.servers[0].van.my_node.id
        subs = []
        tss = []
        for k in (1, 2, 3):
            ts = w._customer.new_request(dest)
            tss.append(ts)
            sub = _op_msg(ts, k, np.ones(8), recver=dest)
            sub.meta.app_id = w._customer.app_id
            sub.meta.customer_id = w._customer.customer_id
            subs.append(sub)
        env = build_batch_message(subs)
        cl.workers[0].van._delivery_failed(env, RuntimeError("gone"))
        for ts in tss:
            with pytest.raises(TimeoutError):
                w.wait(ts)
    finally:
        _teardown(cl, servers, w)


def test_replication_batched_storm_bit_exact_replica():
    """Batching x replication: batched pushes chain-forward PER
    SUB-OP in arrival order — the replica's store ends bit-exact with
    the primary's."""
    cl, servers, w = _storm_cluster(
        num_servers=2,
        env_extra={"PS_KV_REPLICATION": "2", "PS_BATCH_NEGOTIATE": "0"})
    try:
        keys = np.array([3], np.uint64)  # rank 0's range only
        rng = np.random.default_rng(5)
        tss = [w.push(keys, rng.normal(size=256).astype(np.float32))
               for _ in range(60)]
        for ts in tss:
            w.wait(ts)
        assert w.combiner.flushed_frames > 0
        primary = servers[0]._handle.store[3]
        replica = None
        for _ in range(200):
            replica = servers[1]._handle.store.get(3)
            if replica is not None and np.array_equal(primary, replica):
                break
            time.sleep(0.02)
        np.testing.assert_array_equal(primary, replica)
    finally:
        _teardown(cl, servers, w)
