"""Reference-benchmark workload generators (BASELINE configs 4 & 5)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from pslite_tpu.models.embedding import replay as emb_replay, skewed_indices
from pslite_tpu.models.resnet_trace import (
    make_buckets,
    replay as rn50_replay,
    resnet50_param_sizes,
    total_params,
)
from pslite_tpu.parallel import CollectiveEngine, default_mesh
from pslite_tpu.parallel.sparse import SparseEngine


def test_resnet50_trace_shape():
    total = total_params()
    # ResNet-50 has ~25.5M params; the trace must land close.
    assert 25_000_000 < total < 26_000_000, total
    buckets = make_buckets(4 << 20)
    assert sum(n for _, n in buckets) == total
    # Partitioning: no bucket exceeds BYTEPS_PARTITION_BYTES-equivalent.
    assert all(n <= (4 << 20) // 4 for _, n in buckets)


def test_resnet50_replay_small():
    eng = CollectiveEngine(mesh=default_mesh())
    step_bytes, dt = rn50_replay(eng, steps=1, bucket_bytes=64 << 20)
    assert step_bytes == 2 * 4 * total_params()
    assert dt > 0


def test_embedding_skew_and_replay():
    idx = skewed_indices(1000, 8, 256, seed=1)
    assert idx.shape == (8, 256)
    assert idx.min() >= 0 and idx.max() < 1000
    # Zipf skew: the most common row should dominate.
    _, counts = np.unique(idx, return_counts=True)
    assert counts.max() > 10 * np.median(counts)

    eng = SparseEngine(default_mesh())
    step_bytes, dt = emb_replay(eng, num_rows=512, dim=8, batch=64, steps=2)
    assert step_bytes == 2 * 4 * 8 * 64 * 8
    assert dt > 0
