"""Train the flagship transformer through the PS data plane.

Single process drives the whole device mesh (every device is worker AND
server shard — the JOINT deployment).  On a TPU slice this runs over ICI;
on a CPU dev box, force a virtual mesh (BOTH vars — an axon sitecustomize
may override JAX_PLATFORMS programmatically)::

    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_flagship.py --steps 20

Add ``--moe`` for the expert-parallel variant.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--moe", action="store_true")
    ap.add_argument("--lr", type=float, default=0.3)
    ap.add_argument("--checkpoint", default="")
    args = ap.parse_args()

    import jax

    from pslite_tpu.checkpoint import save_train_state
    from pslite_tpu.models.train import make_ps_train_step, toy_batch
    from pslite_tpu.models.transformer import ModelConfig
    from pslite_tpu.parallel.mesh import make_mesh

    n = len(jax.devices())
    sp = 2 if n % 2 == 0 else 1
    mesh = make_mesh((n // sp, sp), ("dp", "sp"))
    print(f"devices={n} mesh=(dp={n // sp}, sp={sp}) "
          f"backend={jax.default_backend()}")

    cfg = ModelConfig(
        vocab=256, dim=args.dim, heads=4, layers=args.layers,
        moe_experts=4 * sp if args.moe else 0,
    )
    step, store, tok_sharding, _ = make_ps_train_step(cfg, mesh, lr=args.lr)

    # Batch shards over dp and sequence over sp: round both up so the
    # example runs on any slice size.
    dp = n // sp
    batch = -(-args.batch // dp) * dp
    seq = -(-args.seq // sp) * sp
    if (batch, seq) != (args.batch, args.seq):
        print(f"note: batch/seq padded to mesh factors: "
              f"batch {args.batch}->{batch}, seq {args.seq}->{seq}")
    inputs, targets = toy_batch(cfg, batch=batch, seq=seq)
    inputs = jax.device_put(inputs, tok_sharding)
    targets = jax.device_put(targets, tok_sharding)

    # Warm up (jit compile) before timing, like pslite_tpu/benchmark.py.
    store, loss = step(store, inputs, targets)
    print(f"step {0:4d}  loss {float(loss):.4f}  (compile)")
    timed_steps = args.steps - 1
    t0 = time.perf_counter()
    for i in range(1, args.steps):
        store, loss = step(store, inputs, targets)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}")
    store.block_until_ready()
    dt = time.perf_counter() - t0
    if timed_steps > 0:
        toks = batch * seq * timed_steps
        print(f"{toks / dt:,.0f} tokens/s (steady state, "
              f"{timed_steps} timed steps)")
    else:
        print("(need --steps >= 2 for a steady-state throughput number)")

    if args.checkpoint:
        written = save_train_state(store, args.steps, args.checkpoint)
        print(f"saved {written}")


if __name__ == "__main__":
    main()
