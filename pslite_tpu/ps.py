"""Top-level lifecycle API: start_ps / finalize / postoffice accessors.

Capability parity with the reference's ``include/ps/ps.h``: role parsing
(worker / server / scheduler / **joint**), instance-group fan-out with one
thread per instance (``_StartPS``/``_StartPSGroup``, ps.h:38-138), the
finalize barrier, and exit callbacks.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from . import environment
from .base import EMPTY_ID
from .message import Role
from .postoffice import Postoffice
from .utils import logging as log

_mu = threading.Lock()
_instances: Dict[Tuple[Role, int], Postoffice] = {}


def _parse_role(role) -> Role:
    if isinstance(role, Role):
        return role
    log.check(role is not None, "role not given and DMLC_ROLE unset")
    return Role[str(role).upper()]


def _role_list(role: Role, group_size: int):
    roles = [Role.SERVER, Role.WORKER] if role == Role.JOINT else [role]
    for r in roles:
        for idx in range(group_size if r != Role.SCHEDULER else 1):
            yield r, idx


def start_ps(
    customer_id: int = 0,
    role=None,
    rank: Optional[int] = None,
    do_barrier: bool = True,
    env: Optional[environment.Environment] = None,
) -> None:
    """Create and start every Postoffice instance this process hosts.

    With JOINT roles and/or ``DMLC_GROUP_SIZE`` > 1 several instances start
    concurrently (each blocks in the startup barrier until the full cluster
    has registered), so instances are started on threads and joined.
    """
    env = env or environment.get()
    if role is None:
        role = env.find("DMLC_ROLE")
    role = _parse_role(role)
    if rank is not None:
        env.set("DMLC_RANK", str(rank))
    group_size = max(env.find_int("DMLC_GROUP_SIZE", 1), 1)

    created = []
    with _mu:
        for r, idx in _role_list(role, group_size):
            key = (r, idx)
            if key not in _instances:
                _instances[key] = Postoffice(r, instance_idx=idx, env=env)
            created.append(_instances[key])

    errors = []

    def _start(po: Postoffice) -> None:
        try:
            po.start(customer_id, do_barrier=do_barrier)
        except Exception as exc:  # surfaced after join
            errors.append((po, exc))

    threads = [
        threading.Thread(target=_start, args=(po,), name=f"start-{po.role_str()}-{po.instance_idx}")
        for po in created
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0][1]


def finalize(customer_id: int = 0, do_barrier: bool = True) -> None:
    """Finalize every instance this process hosts (reference: ps.h:183-192)."""
    with _mu:
        pos = list(_instances.values())
    threads = [
        threading.Thread(
            target=po.finalize, args=(customer_id, do_barrier),
            name=f"finalize-{po.role_str()}-{po.instance_idx}",
        )
        for po in pos
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if customer_id == 0:
        with _mu:
            _instances.clear()


def postoffice(role=None, instance_idx: int = 0) -> Postoffice:
    """Accessor for a started Postoffice instance.

    Without ``role``, prefers WORKER, then SERVER, then SCHEDULER — the
    common case for app code running on a joint node.
    """
    with _mu:
        if role is not None:
            return _instances[(_parse_role(role), instance_idx)]
        for r in (Role.WORKER, Role.SERVER, Role.SCHEDULER):
            if (r, instance_idx) in _instances:
                return _instances[(r, instance_idx)]
    raise KeyError("no Postoffice started in this process")


def num_instances() -> int:
    with _mu:
        return len(_instances)
