"""IciTcpVan: collective data plane over the TCP control plane, across
real OS processes — the fabric_van pattern (fabric_van.h:123-127) with
jax.distributed supplying the cross-process device mesh.

2 worker processes x 4 virtual CPU devices each = one global 8-device
mesh; a dense push_pull must aggregate across both processes and match
the host model (the PS aggregation contract of kv_app.h:430-452).
"""

import os
import subprocess
import sys

import pytest

from pslite_tpu.utils.network import get_available_port


@pytest.mark.parametrize("van,extra", [
    ("ici_tcp", {}),
    # Same-host co-located flavor: bootstrap + message fallback ride
    # /dev/shm (segments + ring pipes), collectives ride the global mesh.
    ("ici_shm", {"PS_SHM_RING": "1"}),
])
def test_ici_two_process_push_pull(van, extra):
    port = get_available_port()
    child = os.path.join(os.path.dirname(__file__), "ici_tcp_child.py")
    base_env = dict(
        os.environ,
        DMLC_NUM_WORKER="2",
        DMLC_NUM_SERVER="1",
        DMLC_PS_ROOT_URI="127.0.0.1",
        DMLC_PS_ROOT_PORT=str(port),
        DMLC_NODE_HOST="127.0.0.1",
        PS_VAN_TYPE=van,
        PS_ICI_MULTIHOST="1",
        PS_VERBOSE="1",
        **extra,
    )
    # The children pin their own platform; scrub any inherited forcing.
    for var in ("JAX_PLATFORMS", "XLA_FLAGS"):
        base_env.pop(var, None)
    roles = [("scheduler", None), ("server", None), ("worker", 0),
             ("worker", 1)]
    procs = []
    for role, rank in roles:
        env = dict(base_env, DMLC_ROLE=role)
        if rank is not None:
            env["DMLC_RANK"] = str(rank)
        procs.append(
            subprocess.Popen(
                [sys.executable, child],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )
        )
    outputs = []
    for p in procs:
        try:
            # 1-CPU host: 4 interpreter startups serialize, plus the
            # cross-process shard_map compile; be generous.
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outputs.append(out.decode())
    if any("MULTIPROC_UNSUPPORTED" in o for o in outputs):
        pytest.skip("this jaxlib's CPU backend lacks multiprocess "
                    "computations (environment limitation)")
    for p, out in zip(procs, outputs):
        assert p.returncode == 0, f"child failed:\n{out}"
    worker_outs = [o for o in outputs if "WORKER_OK 24.0" in o]
    assert len(worker_outs) == 2, f"expected 2 worker OKs, got: {outputs}"
    if extra.get("PS_SHM_RING"):
        # The ring pipes must actually engage — a native-core fallback
        # would pass this test on plain sockets, masking pipe regressions.
        assert not any("staying on sockets" in o for o in outputs), outputs


def test_init_distributed_idempotent(monkeypatch):
    """A process hosting several worker instances (groups/JOINT) must
    join jax.distributed once; later calls are no-ops."""
    import jax

    from pslite_tpu.environment import Environment
    from pslite_tpu.parallel import distributed

    env = Environment({
        "DMLC_NUM_WORKER": "2",
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": "12345",
        "DMLC_RANK": "0",
    })
    calls = []
    # Restore module lease state after the test (monkeypatch teardown).
    monkeypatch.setattr(distributed, "_leases", 0)
    monkeypatch.setattr(distributed, "_opts", None)
    monkeypatch.setattr(distributed, "_owned", False)
    # raising=False: jax<0.5 has no is_initialized — the distributed
    # module's compat probe picks the patched attribute up either way.
    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: True,
                        raising=False)
    monkeypatch.setattr(
        jax.distributed, "initialize",
        lambda **kw: calls.append(kw),
    )
    assert distributed.init_distributed(env) is None
    assert calls == []

    # acquire() on an externally-owned runtime takes a lease but release()
    # must never shut that runtime down.
    shutdowns = []
    monkeypatch.setattr(jax.distributed, "shutdown",
                        lambda: shutdowns.append(1))
    assert distributed.acquire(env) is True
    distributed.release()
    assert shutdowns == []


def test_acquire_release_owned_lifecycle(monkeypatch):
    """Owned path: acquire initializes once; two leases; the runtime is
    shut down exactly once, on the LAST release.  Mismatched cluster
    options are refused."""
    import jax
    import pytest

    from pslite_tpu.environment import Environment
    from pslite_tpu.parallel import distributed
    from pslite_tpu.utils import logging as log

    env = Environment({
        "DMLC_NUM_WORKER": "2",
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": "12345",
        "DMLC_RANK": "0",
    })
    monkeypatch.setattr(distributed, "_leases", 0)
    monkeypatch.setattr(distributed, "_opts", None)
    monkeypatch.setattr(distributed, "_owned", False)
    state = {"init": 0, "shutdown": 0, "up": False}
    monkeypatch.setattr(jax.distributed, "is_initialized",
                        lambda: state["up"], raising=False)

    def fake_init(**kw):
        state["init"] += 1
        state["up"] = True

    def fake_shutdown():
        state["shutdown"] += 1
        state["up"] = False

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    monkeypatch.setattr(jax.distributed, "shutdown", fake_shutdown)

    assert distributed.acquire(env) is True   # initializes
    assert distributed.acquire(env) is True   # reuses (same opts)
    assert state["init"] == 1

    # A different cluster description must be refused while leased.
    env_other = Environment({
        "DMLC_NUM_WORKER": "2",
        "DMLC_PS_ROOT_URI": "10.0.0.9",
        "DMLC_PS_ROOT_PORT": "999",
        "DMLC_RANK": "0",
    })
    with pytest.raises(log.CheckError, match="mismatched"):
        distributed.acquire(env_other)

    distributed.release()
    assert state["shutdown"] == 0  # sibling lease still active
    distributed.release()
    assert state["shutdown"] == 1  # last owned lease out
    distributed.release()          # extra release is a no-op
    assert state["shutdown"] == 1

    # Single-process configs never touch the distributed runtime.
    env1 = Environment({"DMLC_NUM_WORKER": "1"})
    monkeypatch.setattr(jax.distributed, "is_initialized",
                        lambda: (_ for _ in ()).throw(AssertionError),
                        raising=False)
    assert distributed.init_distributed(env1) is None
