"""Metrics registry: lock-cheap Counter / Gauge / Histogram / TopK.

One :class:`Registry` per NODE (attached to its ``Postoffice``), not
per process: the in-process test clusters host many logical nodes, and
``METRICS_PULL`` snapshots must stay per-node there too.  Code without
a postoffice (stub benches) falls back to :data:`NULL_REGISTRY`.

Cost model:

- **Counters** are a bare Python ``int +=`` with no lock — callers on
  hot paths already hold their own locks (``_bytes_mu``, lane transmit
  locks, the single apply-dispatch thread), and telemetry tolerates the
  rare lost increment a GIL switch could cause elsewhere.
- **Histograms** take a tiny per-histogram lock: they update several
  fields and are observed per *request*, not per byte.
- **Disabled** (``PS_TELEMETRY=0``): every constructor returns a shared
  no-op singleton, so instrumented call sites pay one attribute call on
  a do-nothing method and the registry snapshots empty.

Histogram buckets are fixed log-scale (powers of ``2`` above a floor),
so latencies (seconds) and sizes (bytes) both fit one shape and
quantiles come from a 64-slot array walk, never a sample buffer.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple


class Counter:
    """Monotonic counter.  ``inc`` is a bare int add — see the module
    docstring for why that is the right cost/accuracy trade."""

    __slots__ = ("name", "_v")

    def __init__(self, name: str):
        self.name = name
        self._v = 0

    def inc(self, n: int = 1) -> None:
        self._v += n

    @property
    def value(self) -> int:
        return self._v

    def reset(self) -> None:
        self._v = 0


class Gauge:
    """Point-in-time value: either ``set()`` by the owner, or backed by
    a ``fn`` sampled lazily at snapshot time (queue depths — reading a
    live structure at snapshot beats updating a gauge on every push)."""

    __slots__ = ("name", "_v", "_fn")

    def __init__(self, name: str, fn: Optional[Callable[[], float]] = None):
        self.name = name
        self._v = 0.0
        self._fn = fn

    def set(self, v: float) -> None:
        self._v = v

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 - a dying gauge must not
                return 0.0     # break an unrelated snapshot
        return self._v

    def reset(self) -> None:
        self._v = 0.0


class Histogram:
    """Fixed log2-bucket histogram.

    Bucket ``i`` covers ``[lo * 2**(i-1), lo * 2**i)`` (bucket 0 is
    everything ``<= lo``; the last bucket is open-ended).  ``lo``
    defaults to 1 µs for latencies in seconds; use ``lo=1.0`` for byte
    sizes.  Quantiles interpolate geometrically inside the bucket.
    """

    NBUCKETS = 64
    # Bounded exemplar slots (docs/observability.md): at most this
    # many buckets hold a (trace id, value, wall) exemplar at once —
    # the hook that links a Prometheus histogram panel straight to the
    # tail trace that produced the bucket's latest observation.
    EXEMPLAR_SLOTS = 8

    __slots__ = ("name", "lo", "_mu", "count", "sum", "min", "max",
                 "buckets", "_exemplars")

    def __init__(self, name: str, lo: float = 1e-6):
        self.name = name
        self.lo = lo
        self._mu = threading.Lock()
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = 0.0
        self.buckets = [0] * self.NBUCKETS
        # bucket index -> (trace id hex, value, wall time); bounded at
        # EXEMPLAR_SLOTS distinct buckets, oldest wall evicted.
        self._exemplars: Dict[int, tuple] = {}

    def bucket_index(self, v: float) -> int:
        if v <= self.lo:
            return 0
        # int(v/lo).bit_length() is ceil(log2(v/lo)) +- 1 step; exact
        # powers land on the boundary bucket, which is all quantile
        # estimation needs from a log-scale histogram.
        return min(self.NBUCKETS - 1, int(v / self.lo).bit_length())

    def bucket_bound(self, i: int) -> float:
        """Upper bound of bucket ``i``."""
        return self.lo * (2.0 ** i)

    def observe(self, v: float) -> None:
        i = self.bucket_index(v)
        with self._mu:
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            self.buckets[i] += 1

    def attach_exemplar(self, v: float, trace_id: int,
                        wall: Optional[float] = None) -> None:
        """Attach a KEPT trace id to the bucket its latency landed in
        (OpenMetrics exemplars — psmon ``--serve`` renders them as
        ``# {trace_id=...}`` suffixes).  Same-bucket exemplars
        overwrite (newest wins); past ``EXEMPLAR_SLOTS`` distinct
        buckets the oldest-walled slot evicts, so the table stays a
        bounded sketch, not a trace store."""
        if not trace_id:
            return
        i = self.bucket_index(v)
        wall = time.time() if wall is None else wall
        with self._mu:
            self._exemplars[i] = (f"{trace_id:x}", float(v), wall)
            while len(self._exemplars) > self.EXEMPLAR_SLOTS:
                victim = min(self._exemplars,
                             key=lambda b: self._exemplars[b][2])
                del self._exemplars[victim]

    def exemplars(self) -> Dict[int, tuple]:
        with self._mu:
            return dict(self._exemplars)

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0..1) from the bucket counts; 0.0 when
        empty.  Clamped into [min, max] so tiny populations don't report
        a bucket bound wider than anything actually observed."""
        with self._mu:
            if self.count == 0:
                return 0.0
            target = q * self.count
            acc = 0
            for i, n in enumerate(self.buckets):
                acc += n
                if acc >= target and n:
                    # Geometric midpoint of the bucket's span.
                    hi = self.bucket_bound(i)
                    lo = hi / 2.0 if i else 0.0
                    est = (lo * hi) ** 0.5 if lo > 0 else hi / 2.0
                    return min(max(est, self.min), self.max)
            return self.max

    def snapshot(self) -> dict:
        with self._mu:
            count, total = self.count, self.sum
            mn = self.min if self.count else 0.0
            mx = self.max
            nonzero = [[i, n] for i, n in enumerate(self.buckets) if n]
            ex = [[i, t, v, w]
                  for i, (t, v, w) in sorted(self._exemplars.items())]
        out = {"count": count, "sum": total, "min": mn, "max": mx,
               "lo": self.lo, "buckets": nonzero}
        if ex:
            out["exemplars"] = ex
        for q, label in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
            out[label] = self.quantile(q)
        return out

    def merge_shard(self, count: int, total: float, mn: float, mx: float,
                    buckets: Dict[int, int]) -> None:
        """Fold a pre-bucketed shard (same ``lo`` geometry) in under one
        lock acquisition — the flush half of the wire-plane thread-local
        shards, which observe into private bucket arrays off the hot
        path and merge here every few dozen ops."""
        if count <= 0:
            return
        with self._mu:
            self.count += count
            self.sum += total
            if mn < self.min:
                self.min = mn
            if mx > self.max:
                self.max = mx
            for i, n in buckets.items():
                if 0 <= i < self.NBUCKETS and n > 0:
                    self.buckets[i] += n

    def reset(self) -> None:
        with self._mu:
            self.count = 0
            self.sum = 0.0
            self.min = float("inf")
            self.max = 0.0
            self.buckets = [0] * self.NBUCKETS
            self._exemplars.clear()


class TopK:
    """Bounded hot-key tracker (Space-Saving-lite): a dict capped at
    ``cap`` entries; when full, a new key evicts the current minimum and
    inherits its count (the classic overestimate-but-never-miss
    trade)."""

    __slots__ = ("name", "_cap", "_mu", "_d")

    def __init__(self, name: str, cap: int = 128):
        self.name = name
        self._cap = max(1, cap)
        self._mu = threading.Lock()
        self._d: Dict[int, int] = {}

    def add(self, key: int, n: int = 1) -> None:
        with self._mu:
            cur = self._d.get(key)
            if cur is not None:
                self._d[key] = cur + n
            elif len(self._d) < self._cap:
                self._d[key] = n
            else:
                victim = min(self._d, key=self._d.__getitem__)
                floor = self._d.pop(victim)
                self._d[key] = floor + n

    def top(self, k: int = 10) -> List[Tuple[int, int]]:
        with self._mu:
            items = sorted(self._d.items(), key=lambda kv: -kv[1])
        return items[:k]

    def reset(self) -> None:
        with self._mu:
            self._d.clear()


class _NullInstrument:
    """Shared do-nothing stand-in for every instrument type when
    telemetry is disabled: one attribute call on a no-op method."""

    name = "<null>"
    value = 0
    count = 0
    sum = 0.0

    def inc(self, n: int = 1) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    def attach_exemplar(self, v: float, trace_id: int, wall=None) -> None:
        pass

    def merge_shard(self, count, total, mn, mx, buckets) -> None:
        pass

    def exemplars(self) -> dict:
        return {}

    def add(self, key: int, n: int = 1) -> None:
        pass

    def top(self, k: int = 10) -> list:
        return []

    def quantile(self, q: float) -> float:
        return 0.0

    def snapshot(self) -> dict:
        return {}

    def reset(self) -> None:
        pass


_NULL = _NullInstrument()


class Registry:
    """Per-node instrument registry.  ``counter``/``gauge``/
    ``histogram``/``topk`` are idempotent get-or-create (thread-safe),
    so call sites never coordinate creation; a name can hold exactly
    one instrument type."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._mu = threading.Lock()
        self._instruments: Dict[str, object] = {}
        self._created = time.monotonic()

    def _get_or_create(self, name: str, cls, *args, **kw):
        if not self.enabled:
            return _NULL
        with self._mu:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls(name, *args, **kw)
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, not {cls.__name__}"
                )
            return inst

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str,
              fn: Optional[Callable[[], float]] = None) -> Gauge:
        g = self._get_or_create(name, Gauge)
        if fn is not None and isinstance(g, Gauge):
            g._fn = fn
        return g

    def histogram(self, name: str, lo: float = 1e-6) -> Histogram:
        return self._get_or_create(name, Histogram, lo)

    def topk(self, name: str, cap: int = 128) -> TopK:
        return self._get_or_create(name, TopK, cap)

    @property
    def uptime_s(self) -> float:
        return time.monotonic() - self._created

    def counters_with_prefix(self, prefix: str) -> Dict[str, int]:
        with self._mu:
            return {
                name: inst.value for name, inst in self._instruments.items()
                if isinstance(inst, Counter) and name.startswith(prefix)
            }

    def snapshot(self) -> dict:
        """JSON-serializable point-in-time view: counters, sampled
        gauges, histogram summaries (count/sum/min/max/quantiles), and
        top-k tables, plus registry uptime for rate derivation."""
        with self._mu:
            items = list(self._instruments.items())
        counters: Dict[str, int] = {}
        gauges: Dict[str, float] = {}
        hists: Dict[str, dict] = {}
        topks: Dict[str, list] = {}
        for name, inst in items:
            if isinstance(inst, Counter):
                counters[name] = inst.value
            elif isinstance(inst, Gauge):
                gauges[name] = inst.value
            elif isinstance(inst, Histogram):
                hists[name] = inst.snapshot()
            elif isinstance(inst, TopK):
                topks[name] = [[int(k), int(n)] for k, n in inst.top(10)]
        return {
            "uptime_s": round(self.uptime_s, 3),
            "counters": counters,
            "gauges": gauges,
            "histograms": hists,
            "topk": topks,
        }

    def reset(self) -> None:
        with self._mu:
            items = list(self._instruments.values())
            self._created = time.monotonic()
        for inst in items:
            inst.reset()


def merge_bucket_lists(*bucket_lists) -> Dict[int, int]:
    """Sum several snapshot ``buckets`` lists (``[[index, count], ...]``
    — the raw log2 buckets every histogram snapshot carries) into one
    ``{index: count}`` table.  The exact-merge primitive behind psmon's
    combined push+pull quantile and the windowed quantiles of
    ``timeseries.ClusterHistory`` (two histograms with the same ``lo``
    share bucket geometry, so merging counts IS merging populations)."""
    out: Dict[int, int] = {}
    for buckets in bucket_lists:
        for item in buckets or []:
            try:
                i, n = int(item[0]), int(item[1])
            except (TypeError, ValueError, IndexError):
                continue
            if n > 0:
                out[i] = out.get(i, 0) + n
    return out


def bucket_quantile(counts: Dict[int, int], lo: float, q: float,
                    clamp_lo: Optional[float] = None,
                    clamp_hi: Optional[float] = None) -> float:
    """Estimated q-quantile from a ``{bucket_index: count}`` table with
    bucket geometry ``lo`` (the same log2 layout as :class:`Histogram`;
    same geometric-midpoint estimate as :meth:`Histogram.quantile`).
    Returns 0.0 for an empty table.  ``clamp_lo``/``clamp_hi`` bound
    the estimate like the live histogram's observed min/max do."""
    total = sum(n for n in counts.values() if n > 0)
    if total <= 0:
        return 0.0
    target = q * total
    acc = 0
    est = 0.0
    # acc always reaches total >= target for q <= 1, so the break is
    # guaranteed; est stays 0.0 (then clamps) for a degenerate q > 1.
    for i in sorted(counts):
        n = counts[i]
        if n <= 0:
            continue
        acc += n
        if acc >= target:
            hi = lo * (2.0 ** i)
            lo_b = hi / 2.0 if i else 0.0
            est = (lo_b * hi) ** 0.5 if lo_b > 0 else hi / 2.0
            break
    if clamp_lo is not None:
        est = max(est, clamp_lo)
    if clamp_hi is not None:
        est = min(est, clamp_hi)
    return est


NULL_REGISTRY = Registry(enabled=False)


def node_registry(maybe_reg: Optional[Registry]) -> Registry:
    """``maybe_reg`` when present — even disabled — else a PRIVATE
    enabled registry for registry-less harnesses (stub postoffices).

    This replaced ``enabled_registry``: components whose counters
    pre-date telemetry (``van._send_syscalls``, ``pool.sharded_requests``,
    ``replicator.forwarded``, ``van.chaos_stats``) used to get a private
    always-on registry under ``PS_TELEMETRY=0`` so their legacy
    attributes kept counting while the node snapshot stayed empty.  Those
    counters now live in the node registry proper (the attributes are
    thin read-throughs), so the knob means one thing everywhere: off is
    off, and the export path has no special case to skip the private
    shadow registries."""
    return maybe_reg if maybe_reg is not None else Registry()
