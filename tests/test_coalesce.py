"""CoalescingDispatcher: concurrently-issued per-op ops batch into one
grouped program, with the async ZPush/Wait contract unchanged
(include/ps/kv_app.h:218-247 — issue any time, Wait later)."""

import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from pslite_tpu.parallel import CoalescingDispatcher, CollectiveEngine, \
    default_mesh


@pytest.fixture(scope="module")
def mesh():
    return default_mesh()


def _register(eng, names, val_len=64):
    for n in names:
        eng.register_dense(n, np.arange(2, dtype=np.uint64), val_len)


def test_coalesced_matches_per_op(mesh):
    """Results and final stores equal the sequential per-op path."""
    names = [f"c{i}" for i in range(6)]
    rng = np.random.default_rng(81)
    grads = {n: rng.normal(size=(8, 128)).astype(np.float32)
             for n in names}

    ref = CollectiveEngine(mesh=mesh)
    _register(ref, names)
    expected = {n: np.asarray(ref.push_pull(n, grads[n])) for n in names}

    eng = CollectiveEngine(mesh=mesh)
    _register(eng, names)
    with eng.coalescer(window_us=50_000) as disp:
        tickets = {n: disp.push_pull(n, grads[n]) for n in names}
        for n in names:
            np.testing.assert_allclose(
                np.asarray(tickets[n].result()), expected[n], rtol=1e-5
            )


def test_window_groups_into_one_dispatch(mesh):
    """Ops enqueued inside one window run as ONE grouped program."""
    names = [f"g{i}" for i in range(8)]
    eng = CollectiveEngine(mesh=mesh)
    _register(eng, names)
    calls = []
    orig = eng.push_pull_group

    def counting(ns, gs, handle=None):
        calls.append(list(ns))
        return orig(ns, gs, handle=handle)

    eng.push_pull_group = counting
    ones = np.ones((8, 128), np.float32)
    # Long window so every enqueue lands before the drain wakes; the
    # first result() flushes.
    with eng.coalescer(window_us=200_000) as disp:
        tickets = [disp.push_pull(n, ones) for n in names]
        for t in tickets:
            t.result()
    assert calls == [names]


def test_same_bucket_preserves_order(mesh):
    """Duplicate buckets in a window split into sequential sub-batches:
    the first ticket sees only op1's effect, the second sees both."""
    eng = CollectiveEngine(mesh=mesh)
    _register(eng, ["dup"])
    ones = np.ones((8, 128), np.float32)
    with eng.coalescer(window_us=200_000) as disp:
        t1 = disp.push_pull("dup", ones)
        t2 = disp.push_pull("dup", 2 * ones)
        # sum over 8 workers: op1 adds 8, op2 adds 16 more.
        np.testing.assert_allclose(np.asarray(t1.result()),
                                   8 * np.ones(128))
        np.testing.assert_allclose(np.asarray(t2.result()),
                                   24 * np.ones(128))


def test_concurrent_issuers(mesh):
    """Ops issued from many threads all complete with correct values."""
    names = [f"t{i}" for i in range(8)]
    eng = CollectiveEngine(mesh=mesh)
    _register(eng, names)
    results = {}
    errs = []

    with eng.coalescer(window_us=1_000) as disp:
        def issue(n, scale):
            try:
                t = disp.push_pull(
                    n, scale * np.ones((8, 128), np.float32)
                )
                results[n] = np.asarray(t.result())
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [
            threading.Thread(target=issue, args=(n, i + 1))
            for i, n in enumerate(names)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    assert not errs
    for i, n in enumerate(names):
        np.testing.assert_allclose(results[n],
                                   8 * (i + 1) * np.ones(128))


def test_error_delivery(mesh):
    """A bad op fails ITS ticket with the original exception."""
    eng = CollectiveEngine(mesh=mesh)
    with eng.coalescer() as disp:
        t = disp.push_pull("never_registered", np.ones(4, np.float32))
        with pytest.raises(KeyError):
            t.result()


def test_stateful_handle_rejected(mesh):
    eng = CollectiveEngine(mesh=mesh, server_handle="adam:0.01")
    with pytest.raises(Exception):
        eng.coalescer()


def test_trickled_ops_share_one_window(mesh):
    """Ops arriving one by one WITHIN the window still coalesce into a
    single grouped dispatch — the window must not close on the second
    enqueue's cv notify."""
    import time as _time

    names = [f"w{i}" for i in range(5)]
    eng = CollectiveEngine(mesh=mesh)
    _register(eng, names)
    calls = []
    orig = eng.push_pull_group

    def counting(ns, gs, handle=None):
        calls.append(list(ns))
        return orig(ns, gs, handle=handle)

    eng.push_pull_group = counting
    ones = np.ones((8, 128), np.float32)
    with eng.coalescer(window_us=500_000) as disp:
        tickets = []
        for n in names:
            tickets.append(disp.push_pull(n, ones))
            _time.sleep(0.01)  # trickle well inside the 500ms window
        for t in tickets:
            t.result()
    assert calls == [names]


def test_adaptive_idle_close_beats_hard_window(mesh):
    """A lone op dispatches at the idle close (~window/10), far before
    the hard cap — without any flush from the caller."""
    import time as _time

    eng = CollectiveEngine(mesh=mesh)
    _register(eng, ["solo"])
    # Hard cap 4s, idle close 400ms: completing in well under 2s proves
    # the idle close fired (generous margins for the 1-vCPU box).
    with eng.coalescer(window_us=4_000_000) as disp:
        t0 = _time.monotonic()
        t = disp.push_pull("solo", np.ones((8, 128), np.float32))
        assert t.wait(timeout=3.0), "op never dispatched"
        assert _time.monotonic() - t0 < 2.0, \
            "idle close did not fire before the hard window"


def test_idle_zero_restores_fixed_window(mesh):
    """idle_us=0 disables the early close: a lone unflushed op stays
    pending until the hard window elapses."""
    eng = CollectiveEngine(mesh=mesh)
    _register(eng, ["fixed"])
    with eng.coalescer(window_us=3_000_000, idle_us=0) as disp:
        t = disp.push_pull("fixed", np.ones((8, 128), np.float32))
        # Well inside the 3s hard window: must still be pending.
        assert not t.wait(timeout=0.5)
        # result() flushes — the op completes without waiting out the cap.
        np.testing.assert_allclose(np.asarray(t.result()),
                                   8 * np.ones(128))


def test_bad_op_does_not_poison_batchmates(mesh):
    """An unknown bucket fails only ITS ticket; a valid op in the same
    window still completes."""
    eng = CollectiveEngine(mesh=mesh)
    _register(eng, ["good"])
    ones = np.ones((8, 128), np.float32)
    with eng.coalescer(window_us=200_000) as disp:
        t_bad = disp.push_pull("missing", ones)
        t_good = disp.push_pull("good", ones)
        np.testing.assert_allclose(np.asarray(t_good.result()),
                                   8 * np.ones(128))
        with pytest.raises(KeyError):
            t_bad.result()
