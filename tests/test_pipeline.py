"""Pipeline parallelism (parallel/pipeline.py): GPipe microbatch schedule
over a ``pp`` mesh axis — forward parity, gradient parity, and dp x pp
composition against a single-device sequential reference.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from pslite_tpu.parallel.mesh import shard_map_compat as shard_map
from pslite_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_loss,
    stack_layers,
)

D = 16


def _params(rng, n_layers):
    ws = [
        {"w": (rng.randn(D, D) * 0.3).astype(np.float32)}
        for _ in range(n_layers)
    ]
    head = (rng.randn(D, D) * 0.3).astype(np.float32)
    return ws, head


def _layer(w, x):
    return x + jnp.tanh(x @ w)


def _stage_fn(stage_params, x):
    # stage_params["w"]: [layers_per_stage, D, D]
    def body(x, w):
        return _layer(w, x), None

    x, _ = jax.lax.scan(body, x, stage_params["w"])
    return x


def _seq_forward(ws, x):
    for layer in ws:
        x = _layer(layer["w"], x)
    return x


def _head_loss(head, outs, tgt_micros):
    pred = outs @ head
    return jnp.mean((pred - tgt_micros) ** 2)


def test_forward_parity():
    S, L, M, mb = 4, 8, 4, 2
    rng = np.random.RandomState(0)
    ws, _ = _params(rng, L)
    x = rng.randn(M, mb, D).astype(np.float32)
    stacked = stack_layers([jax.tree.map(jnp.asarray, w) for w in ws])

    mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))

    def body(stacked_l, x_micros):
        outs = pipeline_apply(_stage_fn, stacked_l, x_micros, "pp", S)
        # Valid on the last stage only; psum replicates (others are 0).
        return jax.lax.psum(outs, "pp")

    f = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P("pp"), P(None)),
            out_specs=P(None),
        )
    )
    outs = np.asarray(f(stacked, jnp.asarray(x)))
    want = np.asarray(_seq_forward(ws, jnp.asarray(x.reshape(M * mb, D))))
    np.testing.assert_allclose(
        outs.reshape(M * mb, D), want, rtol=1e-5, atol=1e-5
    )


def test_gradient_parity():
    S, L, M, mb = 4, 8, 4, 2
    rng = np.random.RandomState(1)
    ws, head = _params(rng, L)
    x = rng.randn(M, mb, D).astype(np.float32)
    tgt = rng.randn(M, mb, D).astype(np.float32)
    stacked = stack_layers([jax.tree.map(jnp.asarray, w) for w in ws])

    mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))

    def pp_loss(stacked_l, head_r, x_micros, tgt_micros):
        return pipeline_loss(
            _stage_fn,
            lambda h, outs: _head_loss(h, outs, tgt_micros),
            stacked_l,
            head_r,
            x_micros,
            "pp",
            S,
        )

    def body(stacked_l, head_r, x_micros, tgt_micros):
        loss, grads = jax.value_and_grad(pp_loss, argnums=(0, 1))(
            stacked_l, head_r, x_micros, tgt_micros
        )
        gw, gh = grads
        # Head stays replicated: sum its per-stage grads (zero off-last).
        return loss, gw, jax.lax.psum(gh, "pp")

    f = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P("pp"), P(None), P(None), P(None)),
            out_specs=(P(), P("pp"), P(None)),
        )
    )
    loss, gw, gh = f(stacked, jnp.asarray(head), jnp.asarray(x),
                     jnp.asarray(tgt))

    # Sequential reference (microbatch mean == full mean: equal sizes).
    def seq_loss(stacked_r, head_r, x_all, tgt_all):
        def body(x, w):
            return _layer(w, x), None

        out, _ = jax.lax.scan(body, x_all, stacked_r["w"])
        return jnp.mean((out @ head_r - tgt_all) ** 2)

    want_loss, (want_gw, want_gh) = jax.value_and_grad(
        seq_loss, argnums=(0, 1)
    )(stacked, jnp.asarray(head), jnp.asarray(x.reshape(M * mb, D)),
      jnp.asarray(tgt.reshape(M * mb, D)))

    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(gw["w"]), np.asarray(want_gw["w"]), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(gh), np.asarray(want_gh), rtol=1e-4, atol=1e-5
    )


def test_dp_pp_composition():
    """(dp=2, pp=4): batch sharded over dp, layers over pp; dp-psum'd
    gradients match the single-device whole-batch gradients."""
    S, L, M, mb = 4, 4, 2, 2
    dp = 2
    rng = np.random.RandomState(2)
    ws, head = _params(rng, L)
    # Global batch: dp shards each see [M, mb, D].
    x = rng.randn(dp, M, mb, D).astype(np.float32)
    tgt = rng.randn(dp, M, mb, D).astype(np.float32)
    stacked = stack_layers([jax.tree.map(jnp.asarray, w) for w in ws])

    devs = np.array(jax.devices()[: dp * S]).reshape(dp, S)
    mesh = Mesh(devs, ("dp", "pp"))

    def body(stacked_l, head_r, x_l, tgt_l):
        def pp_loss(sl, hr):
            return pipeline_loss(
                _stage_fn,
                lambda h, outs: _head_loss(h, outs, tgt_l[0]),
                sl,
                hr,
                x_l[0],
                "pp",
                S,
            )

        loss, grads = jax.value_and_grad(pp_loss, argnums=(0, 1))(
            stacked_l, head_r
        )
        gw, gh = grads
        # Average over data-parallel replicas; sum head over stages.
        loss = jax.lax.pmean(loss, "dp")
        gw = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), gw)
        gh = jax.lax.pmean(jax.lax.psum(gh, "pp"), "dp")
        return loss, gw, gh

    f = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P("pp"), P(None), P("dp"), P("dp")),
            out_specs=(P(), P("pp"), P(None)),
        )
    )
    loss, gw, gh = f(stacked, jnp.asarray(head), jnp.asarray(x),
                     jnp.asarray(tgt))

    def seq_loss(stacked_r, head_r):
        def body(xc, w):
            return _layer(w, xc), None

        x_all = jnp.asarray(x.reshape(-1, D))
        out, _ = jax.lax.scan(body, x_all, stacked_r["w"])
        return jnp.mean((out @ head_r - jnp.asarray(tgt.reshape(-1, D))) ** 2)

    want_loss, (want_gw, want_gh) = jax.value_and_grad(
        seq_loss, argnums=(0, 1)
    )(stacked, jnp.asarray(head))
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(gw["w"]), np.asarray(want_gw["w"]), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(gh), np.asarray(want_gh), rtol=1e-4, atol=1e-5
    )


def test_single_microbatch_and_full_mesh():
    # Degenerate schedules: M=1 (pure fill/drain) and S=8 (whole mesh).
    S, L, M, mb = 8, 8, 1, 3
    rng = np.random.RandomState(3)
    ws, _ = _params(rng, L)
    x = rng.randn(M, mb, D).astype(np.float32)
    stacked = stack_layers([jax.tree.map(jnp.asarray, w) for w in ws])
    mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))

    def body(stacked_l, x_micros):
        outs = pipeline_apply(_stage_fn, stacked_l, x_micros, "pp", S)
        return jax.lax.psum(outs, "pp")

    f = jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=(P("pp"), P(None)), out_specs=P(None)
        )
    )
    outs = np.asarray(f(stacked, jnp.asarray(x)))
    want = np.asarray(_seq_forward(ws, jnp.asarray(x[0])))
    np.testing.assert_allclose(outs[0], want, rtol=1e-5, atol=1e-5)
