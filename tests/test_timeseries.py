"""Continuous telemetry plane (docs/observability.md): ClusterHistory
windowed math, the SLO watchdog, psmon --watch / --serve, and the
fault flight recorder."""

import glob
import json
import os
import re
import sys
import time
import urllib.request

import numpy as np
import pytest

from pslite_tpu import KVServer, KVServerDefaultHandle, KVWorker
from pslite_tpu.environment import Environment
from pslite_tpu.telemetry import (
    ClusterHistory,
    FlightRecorder,
    Watchdog,
    bucket_quantile,
    merge_bucket_lists,
    parse_slo,
)
from pslite_tpu.utils.logging import CheckError

from helpers import LoopbackCluster

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import psmon  # noqa: E402


# -- synthetic snapshot helpers ----------------------------------------------


def _snap(node_id=9, role="worker", counters=None, gauges=None,
          hists=None, routing=None):
    s = {
        "node_id": node_id, "role": role,
        "metrics": {
            "counters": counters or {},
            "gauges": gauges or {},
            "histograms": hists or {},
            "topk": {},
            "uptime_s": 10.0,
        },
    }
    if routing is not None:
        s["routing"] = routing
    return s


def _hist(buckets, count, lo=1e-6, mn=1e-4, mx=0.5):
    return {"count": count, "sum": 0.0, "min": mn, "max": mx,
            "lo": lo, "buckets": buckets}


# -- windowed rate / quantile math -------------------------------------------


def test_windowed_rate_from_counter_deltas():
    h = ClusterHistory(env=None, interval_s=1.0)
    h.ingest({9: _snap(counters={"van.sent_messages": 100})}, wall=100.0)
    assert h.rate(9, "van.sent_messages") is None  # one sample: no window
    h.ingest({9: _snap(counters={"van.sent_messages": 350})}, wall=102.0)
    assert h.rate(9, "van.sent_messages") == pytest.approx(125.0)
    # Absent counter reads 0 -> 0 rate; unknown node reads None.
    assert h.rate(9, "no.such.counter") == 0.0
    assert h.rate(77, "van.sent_messages") is None
    # A registry reset (negative delta) poisons the window, not the rate.
    h.ingest({9: _snap(counters={"van.sent_messages": 5})}, wall=104.0)
    assert h.rate(9, "van.sent_messages", window_s=2.5) is None


def test_windowed_quantile_from_bucket_deltas():
    """The windowed p50 reflects ONLY the window's observations: the
    cumulative histogram holds old fast samples, the window all-slow."""
    h = ClusterHistory(env=None, interval_s=1.0)
    fast = [[10, 100]]                 # ~0.5-1 ms mass, pre-window
    slow = [[10, 100], [18, 50]]       # window adds ~0.13-0.26 s mass
    h.ingest({9: _snap(hists={"kv.push_latency_s": _hist(fast, 100)})},
             wall=0.0)
    h.ingest({9: _snap(hists={"kv.push_latency_s": _hist(slow, 150)})},
             wall=2.0)
    q = h.window_quantile(9, "kv.push_latency_s", 0.5)
    assert q is not None and 0.1 < q < 0.3, q
    # The cumulative snapshot's own p50 would still sit in the fast
    # mass — the windowed view is the one that sees the regression.
    cum = bucket_quantile(merge_bucket_lists(slow), 1e-6, 0.5)
    assert cum < 0.01
    # Merged multi-histogram window (the psmon request column).
    q2 = h.window_quantile(
        9, ["kv.push_latency_s", "kv.pull_latency_s"], 0.5)
    assert q2 == pytest.approx(q)
    # No observations inside the window -> None, not a stale estimate.
    h.ingest({9: _snap(hists={"kv.push_latency_s": _hist(slow, 150)})},
             wall=3.0)
    assert h.window_quantile(9, "kv.push_latency_s", 0.5,
                             window_s=0.5) is None


def test_epoch_and_membership_change_log():
    h = ClusterHistory(env=None, interval_s=1.0)
    r0 = {"epoch": 0, "active": [0, 1], "leaving": []}
    r1 = {"epoch": 1, "active": [0, 1, 2], "leaving": []}
    h.ingest({1: _snap(1, "scheduler", routing=r0)}, wall=0.0)
    h.ingest({1: _snap(1, "scheduler", routing=r1),
              8: _snap(8, "server", routing=r1)}, wall=1.0)
    log = h.membership_log()
    assert [e["change"] for e in log] == ["epoch", "epoch",
                                         "node_appeared"]
    assert log[1]["epoch"] == 1 and log[1]["active"] == [0, 1, 2]
    assert log[2]["node_id"] == 8


def test_departed_server_retires_from_history():
    """A server that cleanly LEFT via elastic membership must not read
    as perpetually stale: its series retires when the routing block's
    active+leaving set drops its rank (node_stale is for nodes that
    SHOULD be answering)."""
    from pslite_tpu.base import server_rank_to_id

    wd = Watchdog(None)
    h = ClusterHistory(env=None, interval_s=1.0, watchdog=wd)
    s0, s1 = server_rank_to_id(0), server_rank_to_id(1)
    r0 = {"epoch": 1, "active": [0, 1], "leaving": []}
    r1 = {"epoch": 2, "active": [0], "leaving": []}
    h.ingest({1: _snap(1, "scheduler", routing=r0),
              s0: _snap(s0, "server"), s1: _snap(s1, "server")}, wall=0.0)
    # Rank 1 decommissions; it stops replying from now on.
    h.ingest({1: _snap(1, "scheduler", routing=r1),
              s0: _snap(s0, "server")}, wall=1.0)
    assert s1 not in h.node_ids()
    for w in (2.0, 3.0, 4.0, 5.0, 6.0, 7.0):
        h.ingest({1: _snap(1, "scheduler", routing=r1),
                  s0: _snap(s0, "server")}, wall=w)
    assert h.stale_ages() == {}
    assert not [e for e in wd.events(min_severity="warn")
                if e.rule == "node_stale"], wd.events()
    assert any(c["change"] == "node_departed" and c["node_id"] == s1
               for c in h.membership_log())


def test_stale_ages_and_trend():
    h = ClusterHistory(env=None, interval_s=1.0)
    for w in (0.0, 1.0, 2.0):
        round_ = {9: _snap(9, counters={"van.sent_messages": int(10 * w)})}
        if w < 2.0:
            round_[8] = _snap(8, "server")
        h.ingest(round_, wall=w)
    ages = h.stale_ages()
    assert set(ages) == {8} and ages[8] == pytest.approx(1.0)
    tr = h.trend(9, "van.sent_messages")
    assert tr == [pytest.approx(10.0), pytest.approx(10.0)]


# -- SLO watchdog ------------------------------------------------------------


def test_slo_spec_parsing():
    rules = parse_slo("shed_rate=0.5:5,queue_growth=off")
    assert rules["shed_rate"].warn == 0.5
    assert rules["shed_rate"].crit == 5
    assert not rules["queue_growth"].enabled
    assert rules["req_p99"].warn == 0.5  # untouched default
    with pytest.raises(CheckError):
        parse_slo("no_such_rule=1:2")
    with pytest.raises(CheckError):
        parse_slo("shed_rate=5:1")  # warn > crit
    # Environment wiring.
    wd = Watchdog(Environment({"PS_SLO": "repl_lag=10:20"}))
    assert wd.rules["repl_lag"].warn == 10


def test_watchdog_trips_on_shed_rate_and_stays_quiet_idle():
    wd = Watchdog(None)
    h = ClusterHistory(env=None, interval_s=1.0, watchdog=wd)
    h.ingest({8: _snap(8, "server",
                       counters={"tenant.bulk.shed": 0,
                                 "qos.shed_requests": 0})}, wall=0.0)
    assert wd.events(min_severity="warn") == []
    h.ingest({8: _snap(8, "server",
                       counters={"tenant.bulk.shed": 100,
                                 "qos.shed_requests": 100})}, wall=2.0)
    evs = wd.events(min_severity="warn")
    crit = [e for e in evs if e.rule == "shed_rate"
            and e.severity == "crit"]
    assert crit, evs
    assert any(e.tenant == "bulk" for e in crit)
    ev = crit[0]
    assert ev.node_id == 8 and ev.value == pytest.approx(50.0)
    assert ev.threshold == 10.0 and ev.window_s > 0
    json.dumps(ev.as_dict())  # structured + serializable
    # Idle control: several identical samples -> zero WARN/CRIT.
    wd2 = Watchdog(None)
    h2 = ClusterHistory(env=None, interval_s=1.0, watchdog=wd2)
    for w in range(4):
        h2.ingest({8: _snap(8, "server",
                            counters={"tenant.bulk.shed": 100,
                                      "van.sent_messages": 500},
                            gauges={"van.lane_depth": 0.0,
                                    "replication.lag": 0.0})},
                  wall=float(w))
    assert wd2.events(min_severity="warn") == []


def test_watchdog_replication_lag_and_queue_growth():
    wd = Watchdog(None)
    h = ClusterHistory(env=None, interval_s=1.0, watchdog=wd)
    h.ingest({8: _snap(8, "server",
                       gauges={"replication.lag": 0.0,
                               "van.lane_depth": 0.0})}, wall=0.0)
    # Replica chain died: forwards park in the lanes, lag climbs.
    h.ingest({8: _snap(8, "server",
                       gauges={"replication.lag": 100.0,
                               "van.lane_depth": 0.0})}, wall=1.0)
    evs = wd.events(min_severity="warn")
    lag = [e for e in evs if e.rule == "repl_lag"]
    assert lag and lag[0].severity == "warn"  # 100 in [64, 512)
    # Queue growth across the window trips its own rule.
    h.ingest({8: _snap(8, "server",
                       gauges={"replication.lag": 100.0,
                               "van.lane_depth": 5000.0})}, wall=2.0)
    growth = [e for e in wd.events(min_severity="warn")
              if e.rule == "queue_growth"]
    assert growth and growth[0].severity == "crit"


def test_watchdog_retransmit_burst_and_node_stale():
    wd = Watchdog(None)
    h = ClusterHistory(env=None, interval_s=1.0, watchdog=wd)
    h.ingest({9: _snap(counters={"resender.retransmits": 0}),
              8: _snap(8, "server")}, wall=0.0)
    h.ingest({9: _snap(counters={"resender.retransmits": 200})}, wall=2.0)
    rules = {e.rule for e in wd.events(min_severity="warn")}
    assert "retransmit_burst" in rules
    # Node 8 answered nothing for 2 intervals -> node_stale WARN.
    h.ingest({9: _snap(counters={"resender.retransmits": 200})}, wall=3.0)
    stale = [e for e in wd.events(min_severity="warn")
             if e.rule == "node_stale"]
    assert stale and stale[0].node_id == 8


def test_watchdog_holdoff_and_escalation():
    """A sustained breach emits once per window; an escalation to CRIT
    always emits."""
    wd = Watchdog(None)
    h = ClusterHistory(env=None, interval_s=10.0, watchdog=wd)
    h.ingest({8: _snap(8, gauges={"replication.lag": 0.0})}, wall=0.0)
    h.ingest({8: _snap(8, gauges={"replication.lag": 100.0})}, wall=1.0)
    h.ingest({8: _snap(8, gauges={"replication.lag": 100.0})}, wall=2.0)
    assert len([e for e in wd.events() if e.rule == "repl_lag"]) == 1
    h.ingest({8: _snap(8, gauges={"replication.lag": 1000.0})}, wall=3.0)
    lag = [e for e in wd.events() if e.rule == "repl_lag"]
    assert [e.severity for e in lag] == ["warn", "crit"]


# -- psmon merged quantiles + stale rows (satellites) ------------------------


def test_psmon_merged_push_pull_quantiles():
    """The request column merges the RAW buckets of both histograms:
    a slow-but-quiet pull path must move the merged p99 (the old
    busier-path-wins approximation reported the fast push numbers)."""
    m = {
        "histograms": {
            # 90 fast pushes (~bucket 10 = 0.5-1ms)
            "kv.push_latency_s": _hist([[10, 90]], 90, mn=5e-4, mx=1e-3),
            # 10 slow pulls (~bucket 18 = 0.13-0.26s)
            "kv.pull_latency_s": _hist([[18, 10]], 10, mn=0.13, mx=0.26),
        },
    }
    p50, p99 = psmon._req_quantiles(m)
    assert p50 < 2.0       # ms — the bulk is fast
    assert p99 > 100.0     # ms — the slow tail is VISIBLE
    # The old approximation (busier path wins) would have said ~1ms.
    busy_p99 = 1e-3 * 1e3
    assert p99 > 50 * busy_p99


def test_psmon_stale_rows_and_trace_drop_warning():
    snap = {9: _snap(9, counters={"trace.dropped_events": 7})}
    table = psmon.format_table(snap, stale={11: 12.5})
    assert "last seen 12.5s ago" in table
    assert "11" in table
    assert "dropped 7 span(s)" in table
    # Clean snapshot: no warning block, no stale rows.
    clean = psmon.format_table({9: _snap(9)})
    assert "dropped" not in clean and "last seen" not in clean


def test_tracer_dropped_spans_land_on_registry():
    from pslite_tpu.telemetry.metrics import Registry
    from pslite_tpu.telemetry.tracing import Tracer

    reg = Registry()
    tr = Tracer(Environment({"PS_TRACE_SAMPLE": "1"}), "worker",
                metrics=reg)
    tr.MAX_EVENTS = 2  # instance shadow for the test
    for _ in range(5):
        tr.span(123, "request", 0.0, 1.0)
    assert tr.dropped == 3
    assert reg.snapshot()["counters"]["trace.dropped_events"] == 3


# -- OpenMetrics / Prometheus exposition -------------------------------------


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^}]*\})? (?P<value>\S+)$"
)


def _parse_prometheus(text):
    """Minimal exposition parser: returns (types, samples) where
    samples is [(name, labels_dict, value_str)].  Raises on any line
    that is neither a comment nor a well-formed sample."""
    types = {}
    samples = []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split()
            if parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        labels = {}
        if m.group("labels"):
            for kv in m.group("labels")[1:-1].split(","):
                k, _, v = kv.partition("=")
                labels[k] = v.strip('"')
        float(m.group("value").replace("+Inf", "inf"))  # numeric
        samples.append((m.group("name"), labels, m.group("value")))
    return types, samples


def _snap_with_hist():
    return {
        9: _snap(9, counters={"van.sent_messages": 10,
                              "tenant.bulk.shed": 3},
                 gauges={"van.lane_depth": 2.0},
                 hists={"kv.push_latency_s": _hist(
                     [[10, 5], [12, 4], [18, 6]], 15)}),
        8: _snap(8, "server", counters={"kv.server_push_requests": 4}),
    }


def test_prometheus_exposition_parses_and_le_monotone():
    text = psmon.to_prometheus(_snap_with_hist())
    types, samples = _parse_prometheus(text)
    assert types["pslite_van_sent_messages_total"] == "counter"
    assert types["pslite_van_lane_depth"] == "gauge"
    assert types["pslite_kv_push_latency_s"] == "histogram"
    # Tenant counters collapse into one family with a tenant label.
    tenant = [(labels, v) for name, labels, v in samples
              if name == "pslite_tenant_shed_total"]
    assert tenant == [({"node": "9", "role": "worker",
                        "tenant": "bulk"}, "3")]
    # Histogram contract: le strictly increasing, cumulative counts
    # non-decreasing, +Inf last and equal to _count.
    buckets = [(labels["le"], int(v)) for name, labels, v in samples
               if name == "pslite_kv_push_latency_s_bucket"]
    assert buckets[-1][0] == "+Inf"
    les = [float(le.replace("+Inf", "inf")) for le, _ in buckets]
    counts = [c for _, c in buckets]
    assert les == sorted(les) and len(set(les)) == len(les)
    assert counts == sorted(counts)
    count = next(int(v) for name, _l, v in samples
                 if name == "pslite_kv_push_latency_s_count")
    assert buckets[-1][1] == count == 15
    # Every node appears with its labels.
    assert any(l.get("node") == "8" and l.get("role") == "server"
               for _n, l, _v in samples)


def test_prometheus_serve_endpoint():
    snap = _snap_with_hist()
    httpd = psmon.serve(lambda: snap, 0)
    try:
        port = httpd.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as resp:
            assert resp.status == 200
            ctype = resp.headers["Content-Type"]
            body = resp.read().decode()
        assert ctype == psmon.PROM_CONTENT_TYPE
        assert "version=0.0.4" in ctype
        types, _samples = _parse_prometheus(body)
        assert types["pslite_van_sent_messages_total"] == "counter"
        # Unknown paths 404 instead of crashing the server.
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=5)
    finally:
        httpd.shutdown()


# -- flight recorder ---------------------------------------------------------


def test_flight_recorder_ring_and_dump(tmp_path):
    env = Environment({"PS_TRACE_DIR": str(tmp_path),
                       "PS_FLIGHT_EVENTS": "16"})
    fr = FlightRecorder(env, "server")
    fr.node_id = 8
    assert fr.dump() is None  # nothing recorded, nothing written
    for i in range(20):
        fr.record("overload_shed", tenant="bulk", n=i)
    assert fr.num_events == 16 and fr.dropped == 4
    assert not fr.abnormal
    assert fr.dump_if_abnormal() is None  # warn events alone: clean stop
    fr.record("check_failure", severity="crit", error="boom")
    path = fr.dump_if_abnormal()
    assert path and os.path.exists(path)
    doc = json.load(open(path))
    assert doc["abnormal"] and doc["node_id"] == 8
    assert doc["abnormal_reason"].startswith("check_failure")
    kinds = [e["kind"] for e in doc["events"]]
    assert kinds[-1] == "check_failure"
    assert all("ts_us" in e for e in doc["events"])
    # Timestamps ride the shared wall-anchored monotonic timebase.
    assert doc["events"][0]["ts_us"] <= doc["events"][-1]["ts_us"]


def test_flight_dump_on_induced_van_abort(tmp_path):
    """A chaos crash-at-phase abort marks the victim's stop abnormal
    and Van.stop() writes the flight dump with the chaos_crash event —
    the postmortem attachment chaos-test failures rely on."""
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1, van_type="chaos+loopback",
        env_extra={"PS_TRACE_DIR": str(tmp_path)},
        per_node_env={"server0": {"PS_CHAOS": "seed=3,crash=recv:3"}},
    )
    cluster.start()
    servers, workers = [], []
    try:
        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        w = KVWorker(0, 0, postoffice=cluster.workers[0])
        workers.append(w)
        keys = np.array([3], dtype=np.uint64)
        vals = np.ones(16, np.float32)
        for _ in range(3):
            w.wait(w.push(keys, vals))
        # Past the crash budget: fire-and-forget pushes (the server is
        # about to go deaf; waiting would hang).
        for _ in range(8):
            w.push(keys, vals)
        victim = cluster.servers[0].van
        t0 = time.monotonic()
        while not victim.chaos_crashed.is_set():
            assert time.monotonic() - t0 < 10, "chaos crash never tripped"
            w.push(keys, vals)
            time.sleep(0.02)
    finally:
        for po in cluster.all_nodes():
            try:
                po.van.stop()
            except Exception:
                pass
    files = glob.glob(str(tmp_path / "pslite_flight_server_*.json"))
    assert files, "abnormal stop produced no flight dump"
    doc = json.load(open(files[0]))
    assert doc["abnormal"]
    assert any(e["kind"] == "chaos_crash" and e["severity"] == "crit"
               for e in doc["events"])


# -- live cluster: sampler, watch path, overload storm -----------------------


def test_watch_path_end_to_end_smoke():
    """--watch acceptance: sampler on (PS_METRICS_INTERVAL), history
    populated with every node, windowed rates nonzero, health clean,
    format_watch renders."""
    cluster = LoopbackCluster(
        num_workers=2, num_servers=2,
        env_extra={"PS_METRICS_INTERVAL": "0.2"},
    )
    cluster.start()
    servers, workers = [], []
    try:
        for po in cluster.servers:
            s = KVServer(0, postoffice=po)
            s.set_request_handle(KVServerDefaultHandle())
            servers.append(s)
        workers = [KVWorker(0, 0, postoffice=po)
                   for po in cluster.workers]
        hist = cluster.scheduler.history
        assert hist is not None and hist.running, \
            "PS_METRICS_INTERVAL did not start the sampler"
        keys = np.array([3, 2 ** 63 + 9], dtype=np.uint64)
        vals = np.ones(2 * 16, np.float32)
        deadline = time.monotonic() + 15
        while hist.samples < 4:
            assert time.monotonic() < deadline, "sampler never sampled"
            for w in workers:
                w.wait(w.push(keys, vals))
            time.sleep(0.05)
        assert len(hist.node_ids()) == 5  # scheduler + 2s + 2w
        wid = cluster.workers[0].van.my_node.id
        assert hist.rate(wid, "van.sent_messages") > 0
        assert hist.stale_ages() == {}
        # Healthy cluster: ZERO watchdog findings at WARN or above.
        assert cluster.scheduler.health(min_severity="warn") == []
        frame = psmon.format_watch(hist)
        assert "out/s" in frame and "health" in frame
        assert f"\n{wid:>5} " in "\n" + frame
        for w in workers:
            w.stop()
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_overload_storm_trips_shed_crit_and_flight_records(tmp_path):
    """ISSUE 12 acceptance: a tenant overload storm trips the
    shed-rate rule to CRIT within 2 sample intervals, and the victim
    server's flight recorder holds the matching overload_shed
    events."""
    interval = 0.2
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1,
        env_extra={
            "PS_METRICS_INTERVAL": str(interval),
            "PS_TENANTS": "serve:8,train:1",
            "PS_TENANT_QUEUE_LIMIT": "4",
            "PS_SLO": "shed_rate=0.5:2,req_p99=off,queue_growth=off",
            "PS_TRACE_DIR": str(tmp_path),
        },
    )
    cluster.start()
    servers, workers = [], []
    try:
        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        w = KVWorker(0, 0, postoffice=cluster.workers[0])
        workers.append(w)
        from pslite_tpu.kv.kv_app import OverloadError

        keys = np.arange(8, dtype=np.uint64)
        vals = np.ones(8 * 1024, np.float32)
        shed = 0
        storm_end = time.monotonic() + 6 * interval
        while time.monotonic() < storm_end:
            tss = [w.push(keys, vals, tenant="train") for _ in range(32)]
            for ts in tss:
                try:
                    w.wait(ts)
                except OverloadError:
                    shed += 1
        assert shed > 0, "flood never tripped the tenant bound"
        # Within 2 further sample intervals the watchdog reports CRIT.
        deadline = time.monotonic() + 2 * interval + 2.0
        crit = []
        while time.monotonic() < deadline:
            crit = [e for e in cluster.scheduler.health("crit")
                    if e.rule == "shed_rate"]
            if crit:
                break
            time.sleep(interval / 2)
        assert crit, cluster.scheduler.health(min_severity="info")
        assert any(e.tenant == "train" for e in crit)
        # The flight recorder kept the matching per-shed events.
        sheds = cluster.servers[0].flight.events("overload_shed")
        assert sheds and any(e.get("tenant") == "train" for e in sheds)
        # On-demand dump contains them too (the chaos-postmortem path).
        path = cluster.servers[0].flight.dump(
            str(tmp_path / "flight_server.json"))
        doc = json.load(open(path))
        assert any(e["kind"] == "overload_shed" for e in doc["events"])
        w.stop()
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_postoffice_health_empty_without_history():
    cluster = LoopbackCluster(num_workers=1, num_servers=1)
    cluster.start()
    try:
        assert cluster.scheduler.health() == []
        assert cluster.workers[0].health() == []
    finally:
        cluster.finalize()


# -- bench windowed rates (satellite) ----------------------------------------


def test_kv_storm_reports_windowed_rates():
    from pslite_tpu.benchmark import kv_loopback_storm

    r = kv_loopback_storm(n_workers=1, n_servers=1, msgs_per_worker=5)
    worker = next(v for k, v in r["telemetry"].items()
                  if k.startswith("worker"))
    rates = worker["windowed_per_s"]
    # 5 pushes over the measured wall: the windowed rate must agree
    # with msgs/wall, NOT with count/uptime (uptime >> wall here).
    assert rates["kv.pushes"] == pytest.approx(
        5.0 / r["wall_s"], rel=0.05)
    server = next(v for k, v in r["telemetry"].items()
                  if k.startswith("server"))
    assert server["windowed_per_s"]["kv.server_push_requests"] > 0


def test_bench_diff_ignores_windowed_fields():
    import bench_diff

    old = {"kv_storm_msgs_per_s": 100.0, "kv_windowed_kv_pushes_per_s": 5}
    new = {"kv_storm_msgs_per_s": 100.0,
           "kv_windowed_kv_pushes_per_s": 5000}
    lines, regressions = bench_diff.compare(old, new)
    assert regressions == []
    assert not any("kv_windowed" in ln for ln in lines)
