// pslite_core — native transport core for pslite_tpu.
//
// TPU-native counterpart of the reference's C++ Van layer hot path
// (src/zmq_van.h + src/van.cc framing): an epoll-driven TCP transport that
// frames messages with the shared wire format
//
//   u32 magic | u32 meta_len | u32 n_data | u64 data_len[n_data] | meta | data…
//
// (see pslite_tpu/wire.py — the Python and C++ sides interoperate on the
// byte level).  Socket IO, frame assembly, and the receive queue run on
// native threads with no GIL involvement; Python drives it through the
// C API below via ctypes.
//
// Build: make -C cpp   ->  cpp/libpslite_core.so

#include <arpa/inet.h>
#include <dirent.h>
#include <pthread.h>
#include <fcntl.h>
#include <netdb.h>
#include <poll.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/file.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <sys/un.h>
#include <unistd.h>

#include <algorithm>
#include <array>
#include <atomic>
#include <cmath>
#if defined(__x86_64__)
#include <immintrin.h>
#include <cpuid.h>
#endif
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x50535450;  // "PSTP", wire.py MAGIC
constexpr size_t kHeaderSize = 12;       // magic + meta_len + n_data

struct Frame {
  uint8_t* buf = nullptr;  // lens + meta + data, one allocation
  uint32_t meta_len = 0;
  uint32_t n_data = 0;
  // Offsets into buf:
  //   [0, 8*n_data)                 data lens
  //   [8*n_data, 8*n_data+meta_len) meta
  //   then data segments back to back
};

// ABI stamp: bumped whenever the C API surface changes so a stale .so
// (make -C cpp not rerun after a source update) is rejected LOUDLY at
// load time instead of silently falling back per-symbol.  Must match
// pslite_tpu/vans/native.py ABI_VERSION.
// 7: cross-rail direct-read reassembly — tcp_van no longer clamps
// PS_NATIVE_REASSEMBLY to a single rail, so a pre-7 (per-connection
// reassembly) library would wait forever for the other rails' stripes.
// 8: fused wire-codec kernels (psl_codec_encode/decode + the fp8 table
// registration) backing the quantized transport tier
// (docs/compression.md).
// 9: wire-plane observatory — per-core syscall/frame/byte counters
// exported through the one-struct psl_stats_snapshot call
// (docs/observability.md): a pre-9 library would leave the native
// lanes dark while Python reports them instrumented.
constexpr int kAbiVersion = 9;

// Fixed offsets inside the python wire format's meta block (wire.py
// _META_FIXED, little-endian, no padding): enough to peek a frame's
// send priority and control command for the express receive lane, and
// to stamp the per-peer sid at transmit time, without decoding the
// meta.  Keep in sync with wire.py (META_*_OFF constants).
constexpr size_t kMetaSidOff = 58;       // i32, stamped at lane dispatch
constexpr size_t kMetaPriorityOff = 70;  // i32
constexpr size_t kMetaControlCmdOff = 84;  // u8; 0 == EMPTY (data plane)
constexpr size_t kMetaFixedSize = 105;

// EXT_CHUNK payload layout (wire.py _EXT_CHUNK_FIXED "<QIIQB"): the
// native chunk splitter patches the per-chunk index and byte offset in
// place; everything else in the template meta is shared by every chunk
// of one transfer.
constexpr size_t kChunkIndexOff = 8;   // u32 within the ext payload
constexpr size_t kChunkTotalOff = 12;  // u32 within the ext payload
constexpr size_t kChunkOffsetOff = 16;  // u64 within the ext payload
constexpr size_t kChunkNsegOff = 24;   // u8 within the ext payload
constexpr size_t kChunkFixedSize = 25;
constexpr size_t kChunkSegEntry = 9;   // u64 len + u8 dtype code

// More fixed meta offsets (wire.py _META_FIXED) used by the native
// receive-side reassembly: sender id, and the variable-tail counters
// needed to locate the extension blocks after the (empty) node list.
constexpr size_t kMetaSenderOff = 17;     // i32
constexpr size_t kMetaNumNodesOff = 97;   // u16
constexpr size_t kMetaNumDtypesOff = 99;  // u16
constexpr size_t kMetaBodyLenOff = 101;   // u32
constexpr uint8_t kExtChunkTag = 2;       // wire.py EXT_CHUNK

// ChunkInfo.index sentinel stamped on a NATIVELY-REASSEMBLED frame:
// the payload is the COMPLETE transfer (original segments, original
// lens table) and Python finalizes the message without touching its
// ChunkAssembler.  Never produced by any sender, so it cannot collide
// with a real chunk index (senders cap transfers far below 2^32).
constexpr uint32_t kChunkCompleteIndex = 0xFFFFFFFFu;

// Wire-plane counter block (docs/observability.md): one POD struct of
// relaxed monotonic totals, snapshotted whole by psl_stats_snapshot so
// the Python side folds the native plane into the metrics registry as
// deltas with a single FFI call.  Layout is ABI-guarded: the abi field
// echoes kAbiVersion and the struct only ever grows at the end.
struct psl_wire_stats {
  uint64_t abi;
  uint64_t tx_syscalls;    // writev calls (socket path; pipes cost 0)
  uint64_t tx_frames;      // frames fully written (chunks individually)
  uint64_t tx_chunks;      // chunk frames from the native splitter
  uint64_t tx_bytes;       // wire bytes out (header+lens+meta+payload)
  uint64_t tx_msgs;        // logical sends completed clean (sync sends
                           // + lane descriptors)
  uint64_t rx_syscalls;    // read calls (socket pumps; pipes cost 0)
  uint64_t rx_frames;      // frames delivered to the recv queue
  uint64_t rx_bytes_copy;  // bytes staged into pool blocks / pipe ring
  uint64_t rx_bytes_zc;    // bytes scatter-read straight into transfer
                           // buffers (direct-read reassembly)
  uint64_t rx_pool_hits;   // frame blocks recycled from the pool
  uint64_t rx_pool_misses; // frame blocks freshly malloc'd
};

// True when this frame rides the express receive lane, mirroring the
// pure-Python PriorityRecvQueue discipline (utils/queues.py,
// docs/chunking.md): control frames (ACKs, heartbeats, barriers) ride
// above EVERY data level so a bulk chunk backlog can never starve the
// control plane, and priority>0 data bypasses the backlog too.
// TERMINATE stays in the ordinary queue — it must drain BEHIND queued
// traffic, or the receive loop would retire with frames undelivered.
static bool FrameIsExpress(const Frame& f) {
  if (f.meta_len < kMetaFixedSize) return false;
  const uint8_t* meta = f.buf + 8ull * f.n_data;
  uint8_t cmd = meta[kMetaControlCmdOff];
  if (cmd != 0) return cmd != 1;  // 1 == TERMINATE (message.py Command)
  int32_t prio;
  memcpy(&prio, meta + kMetaPriorityOff, sizeof(prio));
  return prio > 0;
}

// Cross-process SPSC byte pipe over a /dev/shm mapping — the reference's
// vendored in-process lock-free SPSC ring (spsc_queue.h) extended across
// processes for same-host meta traffic.  Stream semantics: the writer
// copies frame bytes in as space allows, the reader pumps them through
// the same reassembly state machine as a TCP stream, so a pipe is a
// drop-in replacement for the socket between two co-located nodes.
struct PipeHdr {
  uint32_t magic;  // kPipeMagic
  uint32_t pad;
  uint64_t size;  // data-region bytes
  alignas(64) std::atomic<uint64_t> head;  // consumed; reader-owned
  alignas(64) std::atomic<uint64_t> tail;  // produced; writer-owned
  // Reader-liveness heartbeat: CLOCK_MONOTONIC ms, stamped by the reader
  // at attach and on every liveness tick.  Comparable across processes
  // (same host by construction).  0 = no reader has ever attached.  The
  // writer probes it on ring-full waits: a full ring whose reader is not
  // beating means frames are streaming into the void (reader died,
  // desynced+blacklisted, or never enabled PS_SHM_RING) — the writer
  // retires the pipe and falls back to the socket instead of blocking
  // forever once the ring fills.
  alignas(64) std::atomic<uint64_t> reader_beat;
};

// "PSRC" — bumped from "PSRB" when reader_beat joined the header: an
// old-binary reader would otherwise attach cleanly, drain frames, and
// never heartbeat, which a new writer reads as "no reader" and falsely
// retires the pipe.  Mixed versions now refuse to pair instead.
constexpr uint32_t kPipeMagic = 0x50535243;
constexpr size_t kPipeDataOff = 4096;        // header page

struct WritePipe {
  PipeHdr* hdr = nullptr;
  uint8_t* data = nullptr;
  int fd = -1;  // holds LOCK_SH for writer-liveness
  size_t map_len = 0;
  std::string path;
  std::mutex mu;  // in-process senders serialize whole frames
  // Set once the writer declares the reader dead (see PipeHdr::
  // reader_beat); senders bail with -EPIPE and the van falls back to
  // the socket.  The mapping stays alive in a graveyard until shutdown
  // so concurrently-blocked senders never touch freed memory.
  std::atomic<bool> dead{false};
};

// Per-connection frame reassembly state machine.
// Process-global recv-frame buffer pool.  A fresh malloc per frame
// means every received byte lands in never-touched pages, and the soft
// page faults HALVE large-transfer goodput (measured: 64 MiB frames at
// ~6.7 Gbps fresh vs ~18 Gbps into recycled pages on loopback).
// Buffers round up to power-of-two classes and recycle on
// psl_frame_free.  Global and never torn down deliberately: Python
// holds frame views past Core destruction and psl_frame_free carries
// no core handle.  Bounded (PSL_FRAME_POOL_MB, default 256) — blocks
// past the budget free() as before.
class FramePool {
 public:
  static constexpr size_t kHdr = 16;  // capacity stash, keeps 16-align

  static uint8_t* Alloc(size_t n, bool* pool_hit = nullptr) {
    size_t cap = ClassOf(n);
    {
      std::lock_guard<std::mutex> lk(Mu());
      auto& cls = Free()[cap];
      if (!cls.empty()) {
        uint8_t* base = cls.back();
        cls.pop_back();
        Total() -= cap;
        if (pool_hit != nullptr) *pool_hit = true;
        return base + kHdr;
      }
    }
    if (pool_hit != nullptr) *pool_hit = false;
    auto* base = static_cast<uint8_t*>(malloc(cap + kHdr));
    if (base == nullptr) return nullptr;
    memcpy(base, &cap, sizeof(cap));
    return base + kHdr;
  }

  static void Release(uint8_t* p) {
    if (p == nullptr) return;
    uint8_t* base = p - kHdr;
    size_t cap;
    memcpy(&cap, base, sizeof(cap));
    {
      std::lock_guard<std::mutex> lk(Mu());
      if (Total() + cap <= Budget()) {
        Free()[cap].push_back(base);
        Total() += cap;
        return;
      }
    }
    free(base);
  }

 private:
  static size_t ClassOf(size_t n) {
    size_t cap = 4096;
    while (cap < n) cap <<= 1;
    return cap;
  }
  // Function-local statics: safe from any thread, never destroyed
  // before the last psl_frame_free (intentionally leaked at exit).
  static std::mutex& Mu() {
    static std::mutex* mu = new std::mutex();
    return *mu;
  }
  static std::map<size_t, std::vector<uint8_t*>>& Free() {
    static auto* f = new std::map<size_t, std::vector<uint8_t*>>();
    return *f;
  }
  static size_t& Total() {
    static size_t t = 0;
    return t;
  }
  static size_t Budget() {
    static size_t budget = [] {
      const char* v = getenv("PSL_FRAME_POOL_MB");
      long mb = v != nullptr ? atol(v) : 256;
      if (mb < 0) mb = 0;
      return static_cast<size_t>(mb) << 20;
    }();
    return budget;
  }
};

// Receive-side reassembly state of one in-flight chunked transfer
// (native scatter — docs/native_core.md): chunk payloads memcpy
// straight into the final frame body at their byte offset, GIL-free,
// and Python sees ONE complete frame per transfer instead of
// total-chunks pump round trips.
struct ConnXfer {
  uint64_t total_bytes = 0;
  uint32_t total = 0;
  uint32_t got = 0;
  uint32_t nseg = 0;
  uint32_t meta_len = 0;
  size_t body_size = 0;
  uint8_t* buf = nullptr;  // FramePool block: lens | meta | data
  std::vector<bool> received;
  uint64_t seq = 0;  // insertion order, oldest-first eviction
  // Cross-rail direct-read state (Core::xfers_mu_): pumps currently
  // reading a payload into buf hold a reader ref — the entry (and
  // buf) may not be evicted or freed until they finish.  dropped
  // marks an inconsistent transfer whose buffer the LAST reader
  // reclaims.
  int readers = 0;
  bool dropped = false;
};

struct Conn {
  int fd = -1;
  // Stage 0: header; stage 1: lens; stage 2: meta; stage 3: payload.
  // Meta is read BEFORE the payload so a reassembling receiver can
  // parse EXT_CHUNK and point the payload read STRAIGHT at the
  // transfer buffer's byte range (direct-read scatter: the kernel
  // copy-out is the only pass over the data — no intermediate frame
  // buffer, no second memcpy).
  int stage = 0;
  size_t want = kHeaderSize;
  size_t got = 0;
  uint8_t header[kHeaderSize];
  Frame frame;
  size_t body_size = 0;
  // Stage-3 direct-read scatter state (valid while stage == 3 and
  // scatter_dst != nullptr): the payload destination inside the
  // pending transfer's buffer, and the bookkeeping to finish the
  // absorb when the last byte lands.  Same-io-thread only.
  uint8_t* scatter_dst = nullptr;
  bool drop_frame = false;   // consume payload, deliver nothing
  bool dup_chunk = false;    // already-received index: bytes rewrite
  uint32_t pending_index = 0;
  std::pair<long long, unsigned long long> pending_key{0, 0};

  ~Conn() { FramePool::Release(frame.buf); }
};

struct ReadPipe {
  PipeHdr* hdr = nullptr;
  const uint8_t* data = nullptr;
  int fd = -1;
  size_t map_len = 0;
  std::string path;
  Conn conn;  // reassembly state for this byte stream
};

// One queued data-plane send: the meta bytes are COPIED at enqueue (the
// lanes patch sid/chunk fields in place at transmit time); the data
// segments are NOT — they point into Python-owned buffers that the van
// pins until the descriptor's ticket is reaped (docs/native_core.md,
// buffer-ownership rules).
struct SendDesc {
  uint64_t ticket = 0;
  int node_id = 0;
  int priority = 0;
  std::vector<uint8_t> meta;
  std::vector<iovec> data;
  uint64_t total_data = 0;
  // Native chunk split (0 = one monolithic frame): the descriptor
  // transmits as ceil(total_data / chunk_bytes) chunk frames, patching
  // the EXT_CHUNK payload at meta[chunk_ext_off..] per chunk.
  uint64_t chunk_bytes = 0;
  int32_t chunk_ext_off = -1;
  uint32_t next_index = 0;
  uint64_t sent_offset = 0;
  // Multi-rail bookkeeping (lane->mu): chunks of the ACTIVE descriptor
  // are claimed by any rail thread; the descriptor completes (ticket
  // reported, memory freed) only when fully claimed AND no rail is
  // still mid-writev on one of its chunks.
  int inflight = 0;
  bool canceled = false;
  long long error = 0;
};

// Per-peer native send lane: the GIL-free counterpart of the Python
// van's _SendLane (van.py) — highest priority first, FIFO within a
// level, one lazily-spawned sender thread per peer.  Completed tickets
// park in `done` until Python reaps them (releasing its buffer pins).
struct SendLane {
  std::mutex mu;
  std::condition_variable cv;
  std::map<int, std::deque<SendDesc*>, std::greater<int>> q;  // mu
  std::vector<std::pair<uint64_t, long long>> done;           // mu
  // Rail threads (PS_NATIVE_RAILS): rail 0 plus N-1 stripe threads.
  // All rails claim chunks of the ONE active descriptor (strict
  // FIFO-within-level descriptor order; only a strictly-higher
  // priority descriptor overtakes), so per-level transfer order — and
  // with it the server's apply order — matches the single-rail plane.
  std::vector<std::thread> threads;
  SendDesc* active = nullptr;  // mu: descriptor being claimed/transmitted
  // Per-peer data sid, stamped into the meta at CLAIM time under the
  // lane lock so the per-peer sid sequence equals the claim order (the
  // Python lanes' sid-at-dispatch contract; across rails the sids of
  // one transfer's chunks may land interleaved, which every consumer
  // of chunked frames already tolerates).
  std::atomic<int32_t> sid{0};
  bool stop = false;    // mu
  bool drained = false;  // mu: stop-drain ran (first rail to exit does it)
};

class Core {
 public:
  Core() : epfd_(epoll_create1(0)) {}

  ~Core() { StopAndJoin(); }

  int Bind(int port, int backlog) {
    // Non-blocking listener: AcceptAll drains until EAGAIN and must not
    // wedge the io thread.
    int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) return -errno;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = INADDR_ANY;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
      int err = -errno;
      close(fd);
      return err;
    }
    if (listen(fd, backlog) < 0) {
      int err = -errno;
      close(fd);
      return err;
    }
    socklen_t len = sizeof(addr);
    getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
    listen_fd_ = fd;
    StartIo();
    return ntohs(addr.sin_port);
  }

  // DMLC_LOCAL mode: listen on a unix-domain socket instead of TCP
  // (the zmq van's ipc:///tmp/<port> switch, zmq_van.h:107-115).  The
  // caller owns port-number retry; this binds exactly `path`.
  int BindLocal(const char* path, int backlog) {
    sockaddr_un addr{};
    if (strlen(path) >= sizeof(addr.sun_path)) return -ENAMETOOLONG;
    int fd = socket(AF_UNIX, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) return -errno;
    addr.sun_family = AF_UNIX;
    strncpy(addr.sun_path, path, sizeof(addr.sun_path) - 1);
    if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
      int err = -errno;
      close(fd);
      return err;
    }
    if (listen(fd, backlog) < 0) {
      int err = -errno;
      close(fd);
      unlink(path);
      return err;
    }
    bound_path_ = path;
    listen_fd_ = fd;
    StartIo();
    return 0;
  }

  int ConnectLocal(int node_id, const char* path, int timeout_ms) {
    sockaddr_un addr{};
    if (strlen(path) >= sizeof(addr.sun_path)) return -ENAMETOOLONG;
    int fd = socket(AF_UNIX, SOCK_STREAM, 0);
    if (fd < 0) return -errno;
    addr.sun_family = AF_UNIX;
    strncpy(addr.sun_path, path, sizeof(addr.sun_path) - 1);
    // Bounded connect, same invariant as the TCP path: a listener with a
    // wedged accept loop and full backlog must not stall forever.
    int flags = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, flags | O_NONBLOCK);
    int rc = connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
    if (rc < 0 && errno == EAGAIN) {
      // AF_UNIX semantics (unix(7)): EAGAIN means the listener's backlog
      // is full and NO connection is in progress — polling would report
      // the unconnected fd writable and fake a success.  Fail now; the
      // caller's retry loop redials.
      close(fd);
      return -EAGAIN;
    }
    if (rc < 0 && errno == EINPROGRESS) {
      pollfd pfd{fd, POLLOUT, 0};
      rc = poll(&pfd, 1, timeout_ms);
      if (rc <= 0) {
        close(fd);
        return rc == 0 ? -ETIMEDOUT : -errno;
      }
      int err = 0;
      socklen_t len = sizeof(err);
      getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
      if (err != 0) {
        close(fd);
        return -err;
      }
    } else if (rc < 0) {
      int err = -errno;
      close(fd);
      return err;
    }
    fcntl(fd, F_SETFL, flags);
    std::lock_guard<std::mutex> lk(send_mu_);
    auto it = send_fds_.find(node_id);
    if (it != send_fds_.end()) close(it->second);
    send_fds_[node_id] = fd;
    return 0;
  }

  // -- shm byte pipes (PS_SHM_RING) ---------------------------------------

  // Writer side: create the pipe for (me -> node_id).  Serialized against
  // same-host racers/stale files by an flock on a sibling .lock file; the
  // pipe fd then holds LOCK_SH for the writer's lifetime so readers can
  // probe liveness with LOCK_EX|LOCK_NB.
  int PipeConnect(int node_id, const char* path, uint64_t data_bytes) {
    {
      std::lock_guard<std::mutex> lk(send_mu_);
      auto it = pipes_by_path_.find(path);
      if (it != pipes_by_path_.end()) {
        pipes_[node_id] = it->second;  // re-connect of the same pair
        return 0;
      }
    }
    std::string lockp = std::string(path) + ".lock";
    int lock_fd = open(lockp.c_str(), O_CREAT | O_RDWR, 0600);
    if (lock_fd < 0) return -errno;
    flock(lock_fd, LOCK_EX);
    int rc = PipeCreateLocked(node_id, path, data_bytes);
    flock(lock_fd, LOCK_UN);
    close(lock_fd);
    return rc;
  }

  int PipeCreateLocked(int node_id, const char* path, uint64_t data_bytes) {
    // Reclaim a stale file (writer died): nobody holds LOCK_SH on it.
    int old_fd = open(path, O_RDWR);
    if (old_fd >= 0) {
      if (flock(old_fd, LOCK_EX | LOCK_NB) == 0) {
        unlink(path);
        close(old_fd);
      } else {
        close(old_fd);
        return -EEXIST;  // a live writer owns this name
      }
    }
    int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
    if (fd < 0) return -errno;
    size_t map_len = kPipeDataOff + data_bytes;
    if (ftruncate(fd, static_cast<off_t>(map_len)) < 0) {
      int err = -errno;
      close(fd);
      unlink(path);
      return err;
    }
    void* mem =
        mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    if (mem == MAP_FAILED) {
      int err = -errno;
      close(fd);
      unlink(path);
      return err;
    }
    auto* hdr = new (mem) PipeHdr();
    hdr->size = data_bytes;
    hdr->head.store(0);
    hdr->tail.store(0);
    hdr->magic = kPipeMagic;  // last: readers gate on it
    flock(fd, LOCK_SH);       // writer-liveness token
    auto* p = new WritePipe();
    p->hdr = hdr;
    p->data = static_cast<uint8_t*>(mem) + kPipeDataOff;
    p->fd = fd;
    p->map_len = map_len;
    p->path = path;
    std::lock_guard<std::mutex> lk(send_mu_);
    pipes_[node_id] = p;
    pipes_by_path_[p->path] = p;
    return 0;
  }

  // Take a dead-reader pipe out of service: unroute it (no new senders),
  // release the writer-liveness flock and unlink the name so a redial
  // creates a FRESH pipe (fresh inode — the reader's inode blacklist
  // won't match it), and park the mapping in a graveyard freed at
  // shutdown (a concurrently-blocked sender may still be reading
  // p->hdr; it will see p->dead and bail).  Idempotent under races:
  // only the first retirer acts.
  void RetirePipe(WritePipe* p) {
    bool first = false;
    {
      std::lock_guard<std::mutex> lk(send_mu_);
      // Pointer identity, not path presence: a redial may have already
      // recreated the SAME path as a fresh pipe — erasing by path alone
      // would unroute the new generation and double-park p.
      auto it = pipes_by_path_.find(p->path);
      if (it != pipes_by_path_.end() && it->second == p) {
        pipes_by_path_.erase(it);
        first = true;
      }
      for (auto pit = pipes_.begin(); pit != pipes_.end();) {
        if (pit->second == p) {
          pit = pipes_.erase(pit);
        } else {
          ++pit;
        }
      }
      if (first) dead_write_pipes_.push_back(p);
    }
    if (first) {
      p->dead.store(true, std::memory_order_relaxed);
      close(p->fd);  // releases the writer-liveness LOCK_SH
      p->fd = -1;
      unlink(p->path.c_str());
      fprintf(stderr,
              "[pslite_core] W shm pipe %s: reader dead or never drained; "
              "falling back to the socket\n",
              p->path.c_str());
    }
  }

  // Reader side: watch a directory for pipes named <prefix>*<suffix>
  // (ours are pslpipe_<ns>_<senderport>_<myport>); the poller attaches
  // them as they appear.  Discovery by scan — no announce handshake —
  // because a booting node sends ADD_NODE before the scheduler ever
  // learns its identity (van.cc:566-577 bootstrap ordering).
  int PipeWatch(const char* dir, const char* prefix, const char* suffix,
                int idle_cap_us) {
    std::lock_guard<std::mutex> lk(pipe_mu_);
    watches_.push_back({dir, prefix, suffix});
    if (idle_cap_us > 0) pipe_idle_cap_us_ = idle_cap_us;
    if (!pipe_thread_.joinable()) {
      pipe_thread_ = std::thread([this] { PipeLoop(); });
    }
    return 0;
  }

  long long PipeSendFrame(WritePipe* p, const iovec* iov, size_t cnt,
                          long long total) {
    // Whole frames are written under the pipe mutex: in-process sender
    // threads must not interleave bytes mid-frame.
    std::lock_guard<std::mutex> lk(p->mu);
    int rc = PipeWriteVec(p, iov, cnt);
    return rc < 0 ? rc : total;
  }

  // Stream the iovecs into the ring.  Frame atomicity rule: the timeout
  // applies only BEFORE the first byte is committed — once any byte is
  // published, aborting would leave a truncated frame and desync the
  // stream forever, so from then on this blocks like a socket sendall,
  // bailing on shutdown or on a DEAD READER: a full ring whose reader
  // has stopped beating (see PipeHdr::reader_beat) will never drain, so
  // blocking "like a socket" would wedge the sender permanently.  A
  // dead-reader bail abandons the pipe entirely (-EPIPE; Send() retires
  // it and falls back to the socket), so the truncated frame is
  // discarded along with the ring, never parsed.
  uint64_t ReaderDeadMs() {
    if (reader_dead_ms_ == 0) {
      const char* e = getenv("PS_SHM_RING_DEAD_MS");
      long v = e ? atol(e) : 0;
      uint64_t ms = v > 0 ? static_cast<uint64_t>(v) : 5000;
      // Floor well above the reader's beat staleness bound (one
      // PipeLoop iteration ≈ the idle cap, sub-ms by default): a
      // threshold at or below the beat cadence would falsely retire
      // live pipes and silently drop their parked frames.
      reader_dead_ms_ = ms < 1000 ? 1000 : ms;
    }
    return reader_dead_ms_;
  }

  int PipeWriteVec(WritePipe* p, const iovec* iov, size_t cnt) {
    if (p->dead.load(std::memory_order_relaxed)) return -EPIPE;
    uint64_t tail = p->hdr->tail.load(std::memory_order_relaxed);
    const uint64_t size = p->hdr->size;
    uint64_t slept_us = 0;
    uint64_t full_since_ms = 0;
    int spins = 0;
    bool committed = false;
    for (size_t i = 0; i < cnt; ++i) {
      const uint8_t* src = static_cast<const uint8_t*>(iov[i].iov_base);
      uint64_t len = iov[i].iov_len;
      while (len) {
        uint64_t head = p->hdr->head.load(std::memory_order_acquire);
        uint64_t space = size - (tail - head);
        if (space == 0) {
          // Reader stalled (or not yet attached): stream semantics mean
          // we must wait, not reroute — rerouting would reorder.
          if (stopped_) return -ECANCELED;
          if (p->dead.load(std::memory_order_relaxed)) return -EPIPE;
          if (++spins < 128) continue;
          timespec ts{0, 50 * 1000};
          nanosleep(&ts, nullptr);
          slept_us += 50;
          if (!committed && slept_us > 60ull * 1000 * 1000) {
            return -ETIMEDOUT;
          }
          // Reader-liveness probe (~every 100ms of full-ring waiting).
          // Inside this wait `head` is by definition frozen (any
          // advance makes space > 0 and exits), so liveness reduces to
          // the reader's heartbeat being recent.  The reader beats
          // every ~1s while attached; 5s of silence on a full ring
          // means dead, desynced-and-blacklisted, or never attached.
          if (slept_us % (100 * 1000) == 0) {
            uint64_t now = NowMs();
            if (full_since_ms == 0) full_since_ms = now;
            uint64_t beat =
                p->hdr->reader_beat.load(std::memory_order_relaxed);
            uint64_t ref = beat > full_since_ms ? beat : full_since_ms;
            // now > ref guard: a beat stamped between our NowMs() and
            // the load can make ref exceed now — unsigned subtraction
            // would underflow and falsely retire a healthy pipe.
            if (now > ref && now - ref > ReaderDeadMs()) {
              p->dead.store(true, std::memory_order_relaxed);
              return -EPIPE;
            }
          }
          continue;
        }
        spins = 0;
        uint64_t pos = tail % size;
        uint64_t n = space < len ? space : len;
        if (n > size - pos) n = size - pos;  // contiguous run
        memcpy(p->data + pos, src, n);
        tail += n;
        src += n;
        len -= n;
        p->hdr->tail.store(tail, std::memory_order_release);
        committed = true;
      }
    }
    return 0;
  }

  // Dial one outbound TCP connection (bounded connect: a black-holed
  // peer must not stall the caller for the kernel's full SYN-retry
  // period).  Returns the fd or -errno.
  int DialTcp(const char* host, int port, int timeout_ms) {
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    std::string port_s = std::to_string(port);
    if (getaddrinfo(host, port_s.c_str(), &hints, &res) != 0 || !res) {
      return -EHOSTUNREACH;
    }
    int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd < 0) {
      freeaddrinfo(res);
      return -errno;
    }
    int flags = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, flags | O_NONBLOCK);
    int rc = connect(fd, res->ai_addr, res->ai_addrlen);
    freeaddrinfo(res);
    if (rc < 0 && errno == EINPROGRESS) {
      pollfd pfd{fd, POLLOUT, 0};
      rc = poll(&pfd, 1, timeout_ms);
      if (rc <= 0) {
        close(fd);
        return rc == 0 ? -ETIMEDOUT : -errno;
      }
      int err = 0;
      socklen_t len = sizeof(err);
      getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
      if (err != 0) {
        close(fd);
        return -err;
      }
    } else if (rc < 0) {
      int err = -errno;
      close(fd);
      return err;
    }
    fcntl(fd, F_SETFL, flags);
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    int snd = sndbuf_.load();
    if (snd > 0) {
      // Same bounded-buffer discipline the Python van applies
      // (PS_TCP_SNDBUF): without it the native and pure-Python planes
      // would run against different kernel buffering.
      setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &snd, sizeof(snd));
    }
    return fd;
  }

  int Connect(int node_id, const char* host, int port, int timeout_ms) {
    int fd = DialTcp(host, port, timeout_ms);
    if (fd < 0) return fd;
    std::lock_guard<std::mutex> lk(send_mu_);
    auto it = send_fds_.find(node_id);
    if (it != send_fds_.end()) close(it->second);
    send_fds_[node_id] = fd;
    return 0;
  }

  // Extra data rail to a peer (PS_NATIVE_RAILS, docs/native_core.md):
  // rail `idx` (1-based beyond the main connection) carries a stripe of
  // each chunked transfer so one lane's goodput is no longer bounded by
  // a single TCP stream's per-byte kernel cost.  Re-dialing an index
  // replaces the old fd (peer recovery redial).
  int AddRail(int node_id, const char* host, int port, int timeout_ms,
              int idx) {
    if (idx < 1 || idx >= kMaxRails) return -EINVAL;
    int fd = DialTcp(host, port, timeout_ms);
    if (fd < 0) return fd;
    std::lock_guard<std::mutex> lk(send_mu_);
    auto& v = rail_fds_[node_id];
    if (v.size() < static_cast<size_t>(idx)) v.resize(idx, -1);
    if (v[idx - 1] >= 0) close(v[idx - 1]);
    v[idx - 1] = fd;
    return 0;
  }

  void SetRails(int n) {
    if (n < 1) n = 1;
    if (n > kMaxRails) n = kMaxRails;
    rails_.store(n);
  }

  void SetSockBuf(int snd, int rcv) {
    sndbuf_.store(snd > 0 ? snd : 0);
    rcvbuf_.store(rcv > 0 ? rcv : 0);
  }

  long long Send(int node_id, const uint8_t* meta, uint32_t meta_len,
                 uint32_t n_data, const uint8_t* const* data,
                 const uint64_t* lens) {
    std::vector<iovec> div(n_data);
    for (uint32_t i = 0; i < n_data; ++i) {
      div[i] = {const_cast<uint8_t*>(data[i]), static_cast<size_t>(lens[i])};
    }
    long long rc = TransmitFrame(node_id, meta, meta_len, div.data(), n_data);
    if (rc >= 0) wx_tx_msgs_.fetch_add(1, std::memory_order_relaxed);
    return rc;
  }

  // Frame one message and write it to the peer's route (pipe or
  // socket).  Shared by the synchronous control-plane Send() and the
  // per-peer sender lanes (TransmitDesc) — both serialize on the same
  // per-fd write locks, so lane frames and inline control frames never
  // interleave mid-frame.
  // The fd rail `rail` of a lane should transmit on, or -1 when the
  // rail has no dedicated connection (fall back to the main path, which
  // also serves pipes).  send_mu_.
  int RailFd(int node_id, int rail) {
    if (rail <= 0) return -1;
    std::lock_guard<std::mutex> lk(send_mu_);
    if (pipes_.count(node_id)) return -1;  // pipe = single ordered stream
    auto it = rail_fds_.find(node_id);
    if (it == rail_fds_.end()) return -1;
    if (static_cast<size_t>(rail) > it->second.size()) return -1;
    return it->second[rail - 1];
  }

  long long TransmitFrame(int node_id, const uint8_t* meta,
                          uint32_t meta_len, const iovec* data_iov,
                          uint32_t n_data, int rail_fd = -1) {
    // Gate against teardown: StopAndJoin must not free pipes while a
    // sender is mid-copy into the mapping.
    struct InflightGuard {
      std::atomic<int>* n;
      explicit InflightGuard(std::atomic<int>* c) : n(c) { ++*n; }
      ~InflightGuard() { --*n; }
    } guard(&inflight_sends_);
    if (stopped_) return -ECANCELED;
    WritePipe* pipe = nullptr;
    int fd = rail_fd;
    if (fd < 0) {
      std::lock_guard<std::mutex> lk(send_mu_);
      auto pit = pipes_.find(node_id);
      if (pit != pipes_.end()) {
        pipe = pit->second;
      } else {
        auto it = send_fds_.find(node_id);
        if (it == send_fds_.end()) return -ENOTCONN;
        fd = it->second;
      }
    }
    uint8_t header[kHeaderSize];
    memcpy(header, &kMagic, 4);
    memcpy(header + 4, &meta_len, 4);
    memcpy(header + 8, &n_data, 4);
    std::vector<uint64_t> lens(n_data);
    std::vector<iovec> iov;
    iov.reserve(3 + n_data);
    iov.push_back({header, kHeaderSize});
    iov.push_back({lens.data(), 8ull * n_data});
    iov.push_back({const_cast<uint8_t*>(meta), meta_len});
    long long total = kHeaderSize + 8ull * n_data + meta_len;
    for (uint32_t i = 0; i < n_data; ++i) {
      lens[i] = data_iov[i].iov_len;
      iov.push_back(data_iov[i]);
      total += static_cast<long long>(lens[i]);
    }
    // A connected pipe carries the WHOLE stream for this peer (mixing
    // pipe and socket frames would lose ordering).
    if (pipe != nullptr) {
      long long rc = PipeSendFrame(pipe, iov.data(), iov.size(), total);
      if (rc != -EPIPE) {
        if (rc >= 0) {
          // Pipe frames are ring memcpys: a frame and its bytes, zero
          // syscalls — exactly the story the observatory should tell.
          wx_tx_frames_.fetch_add(1, std::memory_order_relaxed);
          wx_tx_bytes_.fetch_add(static_cast<uint64_t>(rc),
                                 std::memory_order_relaxed);
        }
        return rc;
      }
      // Reader declared dead (see PipeWriteVec): retire the pipe and
      // fall back to the socket connection, which connect_transport
      // established before the pipe took over routing.  Frames already
      // committed to the abandoned ring are lost (the resender heals
      // them under PS_RESEND) — the reference behaves the same when a
      // transport dies mid-stream.
      RetirePipe(pipe);
      std::lock_guard<std::mutex> lk(send_mu_);
      auto it = send_fds_.find(node_id);
      if (it == send_fds_.end()) return -EPIPE;
      fd = it->second;
    }
    // Serialize writers per peer socket (frames must not interleave).
    std::lock_guard<std::mutex> lk(per_fd_send_mu_[fd % kSendLocks]);
    size_t idx = 0;
    size_t off = 0;
    long long sent_total = 0;
    uint64_t calls = 0;
    while (idx < iov.size()) {
      iovec cur[64];
      int cnt = 0;
      for (size_t i = idx; i < iov.size() && cnt < 64; ++i, ++cnt) {
        cur[cnt] = iov[i];
        if (i == idx && off) {
          cur[cnt].iov_base = static_cast<uint8_t*>(cur[cnt].iov_base) + off;
          cur[cnt].iov_len -= off;
        }
      }
      ssize_t n = writev(fd, cur, cnt);
      ++calls;
      if (n < 0) {
        if (errno == EINTR) continue;
        wx_tx_syscalls_.fetch_add(calls, std::memory_order_relaxed);
        return -errno;
      }
      sent_total += n;
      size_t left = static_cast<size_t>(n);
      // Consume fully-written entries; zero-length iovecs (empty payload
      // segments, e.g. a pull request's vals) must advance even when no
      // bytes remain, or the loop would respin writev forever.
      while (idx < iov.size()) {
        size_t avail = iov[idx].iov_len - off;
        if (avail <= left) {
          left -= avail;
          ++idx;
          off = 0;
        } else {
          off += left;
          break;
        }
      }
    }
    (void)total;
    // One committed batch per frame (local counter, like the Python
    // _sendv): a fully-accepted vector costs exactly one fetch_add.
    wx_tx_syscalls_.fetch_add(calls, std::memory_order_relaxed);
    wx_tx_frames_.fetch_add(1, std::memory_order_relaxed);
    wx_tx_bytes_.fetch_add(static_cast<uint64_t>(sent_total),
                           std::memory_order_relaxed);
    return sent_total;
  }

  // -- per-peer native sender lanes (docs/native_core.md) -----------------

  // Enqueue one data-plane frame (or, with chunk_bytes > 0, one whole
  // chunked transfer) onto the destination's native lane and return a
  // ticket (> 0) immediately; the lane thread transmits GIL-free.  The
  // caller owns keeping the data buffers alive until the ticket is
  // reaped.  chunk_ext_off locates the EXT_CHUNK payload inside the
  // meta template for per-chunk patching.
  long long EnqueueSend(int node_id, int priority, const uint8_t* meta,
                        uint32_t meta_len, uint32_t n_data,
                        const uint8_t* const* data, const uint64_t* lens,
                        uint64_t chunk_bytes, int32_t chunk_ext_off) {
    if (stopped_) return -ECANCELED;
    if (chunk_bytes > 0 &&
        (chunk_ext_off < 0 ||
         static_cast<size_t>(chunk_ext_off) + kChunkFixedSize > meta_len)) {
      return -EINVAL;
    }
    auto* d = new SendDesc();
    d->ticket = ticket_seq_.fetch_add(1) + 1;
    d->node_id = node_id;
    d->priority = priority;
    d->meta.assign(meta, meta + meta_len);
    d->data.resize(n_data);
    for (uint32_t i = 0; i < n_data; ++i) {
      d->data[i] = {const_cast<uint8_t*>(data[i]),
                    static_cast<size_t>(lens[i])};
      d->total_data += lens[i];
    }
    d->chunk_bytes = chunk_bytes;
    d->chunk_ext_off = chunk_ext_off;
    SendLane* lane = LaneFor(node_id);
    long long ticket = static_cast<long long>(d->ticket);
    {
      std::lock_guard<std::mutex> f(flush_mu_);
      pending_descs_.fetch_add(1);
    }
    {
      std::lock_guard<std::mutex> lk(lane->mu);
      if (lane->stop) {
        // Raced a shutdown: complete-as-canceled so the caller's
        // buffer pin is released on its next reap.
        lane->done.emplace_back(d->ticket, -ECANCELED);
        delete d;
        NoteDescDone();
        return ticket;
      }
      lane->q[priority].push_back(d);
    }
    lane->cv.notify_all();
    return ticket;
  }

  // Drain completed (ticket, status) pairs for one peer; status 0 = sent,
  // negative = -errno (including -ECANCELED for shutdown/cancel drops).
  int SendReap(int node_id, uint64_t* tickets, long long* status, int cap) {
    SendLane* lane = nullptr;
    {
      std::lock_guard<std::mutex> lk(lanes_mu_);
      auto it = lanes_.find(node_id);
      if (it == lanes_.end()) return 0;
      lane = it->second;
    }
    std::lock_guard<std::mutex> lk(lane->mu);
    int n = static_cast<int>(lane->done.size());
    if (n > cap) n = cap;
    for (int i = 0; i < n; ++i) {
      tickets[i] = lane->done[i].first;
      status[i] = lane->done[i].second;
    }
    lane->done.erase(lane->done.begin(), lane->done.begin() + n);
    return n;
  }

  // Block until every lane has transmitted (or failed) every queued
  // descriptor — the native analog of the Python _drain_send_lanes.
  int SendFlush(int timeout_ms) {
    std::unique_lock<std::mutex> lk(flush_mu_);
    auto pred = [&] { return pending_descs_.load() == 0 || stopped_; };
    if (timeout_ms < 0) {
      flush_cv_.wait(lk, pred);
      return 0;
    }
    return flush_cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                              pred)
               ? 0
               : -ETIMEDOUT;
  }

  // Drop every QUEUED descriptor for a dead peer (tickets complete as
  // -ECANCELED so Python can fail the owning requests fast).  A
  // descriptor already mid-transmit is not interrupted — its writev
  // fails on the broken socket.
  long long SendCancel(int node_id) {
    SendLane* lane = nullptr;
    {
      std::lock_guard<std::mutex> lk(lanes_mu_);
      auto it = lanes_.find(node_id);
      if (it == lanes_.end()) return 0;
      lane = it->second;
    }
    long long n = 0;
    {
      std::lock_guard<std::mutex> lk(lane->mu);
      for (auto& kv : lane->q) {
        for (SendDesc* d : kv.second) {
          if (d->inflight > 0) {
            // A preempted transfer with a rail still mid-writev on one
            // of its chunks: poison it — the last writer reports the
            // ticket as canceled (deleting here would be a UAF).
            d->canceled = true;
          } else {
            lane->done.emplace_back(d->ticket, -ECANCELED);
            delete d;
            ++n;
          }
        }
      }
      lane->q.clear();
    }
    lane->cv.notify_all();
    for (long long i = 0; i < n; ++i) NoteDescDone();
    return n;
  }

  // Peer recovery: a restarted peer expects the sid sequence to begin
  // at 0 again (the Python _reset_peer_sids counterpart).
  void SendResetSid(int node_id) {
    std::lock_guard<std::mutex> lk(lanes_mu_);
    auto it = lanes_.find(node_id);
    if (it != lanes_.end()) it->second->sid.store(0);
  }

  void SetReassembly(int on) { reassemble_.store(on != 0); }

  // Returns 1 with a frame, 0 on timeout, -1 when stopped.  Express
  // frames (priority > 0 data — see FrameIsExpress) pop first so a
  // priority op never waits behind a bulk chunk backlog; each lane is
  // FIFO, matching the Python PriorityRecvQueue discipline.
  int Recv(Frame* out, int timeout_ms) {
    std::unique_lock<std::mutex> lk(queue_mu_);
    auto ready = [this] {
      return stopped_ || !express_.empty() || !queue_.empty();
    };
    if (timeout_ms < 0) {
      queue_cv_.wait(lk, ready);
    } else if (!queue_cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   ready)) {
      return 0;
    }
    std::deque<Frame>* q =
        !express_.empty() ? &express_ : (!queue_.empty() ? &queue_ : nullptr);
    if (q != nullptr) {
      *out = q->front();
      q->pop_front();
      return 1;
    }
    return stopped_ ? -1 : 0;
  }

  // One-call wire-plane snapshot: every counter read relaxed into the
  // caller's struct.  Totals are monotonic; the Python side diffs
  // against its previous snapshot, so relaxed reads racing live
  // increments only ever defer a count to the next snapshot.
  void StatsSnapshot(psl_wire_stats* out) const {
    out->abi = kAbiVersion;
    out->tx_syscalls = wx_tx_syscalls_.load(std::memory_order_relaxed);
    out->tx_frames = wx_tx_frames_.load(std::memory_order_relaxed);
    out->tx_chunks = wx_tx_chunks_.load(std::memory_order_relaxed);
    out->tx_bytes = wx_tx_bytes_.load(std::memory_order_relaxed);
    out->tx_msgs = wx_tx_msgs_.load(std::memory_order_relaxed);
    out->rx_syscalls = wx_rx_syscalls_.load(std::memory_order_relaxed);
    out->rx_frames = wx_rx_frames_.load(std::memory_order_relaxed);
    out->rx_bytes_copy = wx_rx_bytes_copy_.load(std::memory_order_relaxed);
    out->rx_bytes_zc = wx_rx_bytes_zc_.load(std::memory_order_relaxed);
    out->rx_pool_hits = wx_rx_pool_hits_.load(std::memory_order_relaxed);
    out->rx_pool_misses =
        wx_rx_pool_misses_.load(std::memory_order_relaxed);
  }

  void Stop() {
    stopped_ = true;
    if (listen_fd_ >= 0) {
      shutdown(listen_fd_, SHUT_RDWR);
      close(listen_fd_);
      listen_fd_ = -1;
    }
    if (!bound_path_.empty()) {
      unlink(bound_path_.c_str());
      bound_path_.clear();
    }
    // Wake every sender lane (they drain-as-canceled and retire) and
    // unwedge any writev blocked on a black-holed peer: the Python van
    // flushes the lanes BEFORE stop, so anything still in flight here
    // is already abandoned.
    {
      std::lock_guard<std::mutex> lk(send_mu_);
      for (auto& kv : send_fds_) shutdown(kv.second, SHUT_RDWR);
      for (auto& kv : rail_fds_) {
        for (int fd : kv.second) {
          if (fd >= 0) shutdown(fd, SHUT_RDWR);
        }
      }
    }
    {
      std::lock_guard<std::mutex> lk(lanes_mu_);
      for (auto& kv : lanes_) kv.second->cv.notify_all();
    }
    flush_cv_.notify_all();
    queue_cv_.notify_all();
  }

  void StopAndJoin() {
    Stop();
    // Sender lanes first: their threads write through pipes/sockets the
    // teardown below frees.
    std::vector<SendLane*> lanes;
    {
      std::lock_guard<std::mutex> lk(lanes_mu_);
      for (auto& kv : lanes_) lanes.push_back(kv.second);
      lanes_.clear();
    }
    for (SendLane* lane : lanes) {
      {
        std::lock_guard<std::mutex> lk(lane->mu);
        lane->stop = true;
      }
      lane->cv.notify_all();
    }
    for (SendLane* lane : lanes) {
      for (std::thread& t : lane->threads) {
        if (t.joinable()) t.join();
      }
      delete lane;
    }
    if (io_thread_.joinable()) io_thread_.join();
    for (std::thread& t : io_threads_) {
      if (t.joinable()) t.join();
    }
    io_threads_.clear();
    if (pipe_thread_.joinable()) pipe_thread_.join();
    // Wait for in-flight Sends to drain: freeing a pipe mapping under a
    // sender's memcpy would be a use-after-munmap (stopped_ makes them
    // bail at their next ring-full or entry check).
    for (int i = 0; i < 5000 && inflight_sends_.load() > 0; ++i) {
      timespec ts{0, 1000 * 1000};
      nanosleep(&ts, nullptr);
    }
    for (auto& kv : rpipes_) ClosePipe(kv.second);
    rpipes_.clear();
    std::lock_guard<std::mutex> lk(send_mu_);
    for (auto& kv : pipes_by_path_) {
      WritePipe* p = kv.second;
      munmap(reinterpret_cast<void*>(p->hdr), p->map_len);
      close(p->fd);  // releases the writer-liveness LOCK_SH
      unlink(p->path.c_str());
      // The sibling .lock file stays behind (as the unix-socket path's
      // do): unlinking it would hand a concurrent locker a different
      // inode, reopening the reclaim/create race the flock exists to
      // close.  They are empty files; ReclaimIfDead removes them under
      // LOCK_EX when it reclaims a name.
      delete p;
    }
    pipes_by_path_.clear();
    pipes_.clear();
    for (WritePipe* p : dead_write_pipes_) {
      // Retired at runtime (dead reader): fd closed and name unlinked
      // then; only the parked mapping remains.
      munmap(reinterpret_cast<void*>(p->hdr), p->map_len);
      delete p;
    }
    dead_write_pipes_.clear();
    for (auto& kv : send_fds_) close(kv.second);
    send_fds_.clear();
    for (auto& kv : rail_fds_) {
      for (int fd : kv.second) {
        if (fd >= 0) close(fd);
      }
    }
    rail_fds_.clear();
    {
      std::lock_guard<std::mutex> clk(conns_mu_);
      for (auto& kv : conns_) {
        close(kv.second->fd);
        AbandonScatter(kv.second);
        delete kv.second;
      }
      conns_.clear();
    }
    {
      std::lock_guard<std::mutex> xlk(xfers_mu_);
      for (auto& kv : xfers_) FramePool::Release(kv.second.buf);
      xfers_.clear();
    }
    if (epfd_ >= 0) {
      close(epfd_);
      epfd_ = -1;
    }
    for (int ep : extra_epfds_) close(ep);
    extra_epfds_.clear();
    std::lock_guard<std::mutex> qlk(queue_mu_);
    for (auto& f : queue_) FramePool::Release(f.buf);
    queue_.clear();
    for (auto& f : express_) FramePool::Release(f.buf);
    express_.clear();
  }

 private:
  static constexpr int kSendLocks = 64;
  static constexpr int kMaxRails = 8;

  SendLane* LaneFor(int node_id) {
    std::lock_guard<std::mutex> lk(lanes_mu_);
    auto it = lanes_.find(node_id);
    if (it != lanes_.end()) return it->second;
    auto* lane = new SendLane();
    int n = rails_.load();
    for (int r = 0; r < n; ++r) {
      lane->threads.emplace_back([this, node_id, lane, r] {
        RailLoop(node_id, lane, r);
      });
    }
    lanes_[node_id] = lane;
    return lane;
  }

  void NoteDescDone() {
    {
      std::lock_guard<std::mutex> f(flush_mu_);
      pending_descs_.fetch_sub(1);
    }
    flush_cv_.notify_all();
  }

  void StampSid(uint8_t* meta, uint32_t meta_len, SendLane* lane) {
    if (meta_len < kMetaFixedSize) return;
    int32_t sid = lane->sid.fetch_add(1);
    memcpy(meta + kMetaSidOff, &sid, sizeof(sid));
  }

  // Whether rail `rail` can make progress right now.  lane->mu held.
  //
  // A monolithic frame — and the FINAL chunk of every transfer — is
  // reserved for rail 0: every descriptor's completion marker then
  // rides one FIFO stream, so the receiver observes transfer
  // completions (and with them the server's apply slots) in exactly
  // the claim order, no matter how the rails' socket buffers drain.
  bool RailHasClaim(SendLane* lane, int rail) {
    SendDesc* d = lane->active;
    if (d == nullptr) return !lane->q.empty();
    if (!lane->q.empty() && lane->q.begin()->first > d->priority) {
      return true;  // preemption is work for any rail
    }
    uint64_t remaining = d->total_data - d->sent_offset;
    if (d->chunk_bytes == 0) return rail == 0;
    if (remaining == 0) return false;  // fully claimed; writers draining
    if (remaining <= d->chunk_bytes && rail != 0) return false;
    return true;
  }

  // Claim-and-transmit loop of one rail thread (PS_NATIVE_RAILS rail
  // threads per peer).  Rails cooperatively drain the ONE active
  // descriptor: each claims the next chunk under the lane lock (sid
  // stamped at claim, so sid order == claim order), patches a
  // rail-local copy of the meta template, and writev's on its own
  // connection — one transfer's chunks stream in parallel over N TCP
  // streams while descriptor order stays strict FIFO-within-level.
  // Frames are byte-identical to the single-rail plane's.
  void RailLoop(int node_id, SendLane* lane, int rail) {
    char name[16];
    snprintf(name, sizeof(name), "psl-lane-%d.%d", node_id, rail);
    pthread_setname_np(pthread_self(), name);
    std::vector<uint8_t> tmeta;   // rail-local template copy
    std::vector<iovec> slices;
    std::unique_lock<std::mutex> lk(lane->mu);
    while (true) {
      lane->cv.wait(lk, [&] {
        return stopped_ || lane->stop || RailHasClaim(lane, rail);
      });
      if (stopped_ || lane->stop) break;
      // Promote the next descriptor / preempt a mid-transfer bulk.
      if (lane->active == nullptr) {
        auto it = lane->q.begin();  // highest priority, FIFO within
        lane->active = it->second.front();
        it->second.pop_front();
        if (it->second.empty()) lane->q.erase(it);
        // The promoted frame may be claimable only by ANOTHER rail
        // (monolithic / final chunk → rail 0).
        lane->cv.notify_all();
      } else if (!lane->q.empty() &&
                 lane->q.begin()->first > lane->active->priority) {
        // Partially-claimed transfer back to the FRONT of its level —
        // later same-priority sends still wait for the whole transfer
        // (Python lane order), only higher priority jumps.
        lane->q[lane->active->priority].push_front(lane->active);
        auto it = lane->q.begin();
        lane->active = it->second.front();
        it->second.pop_front();
        if (it->second.empty()) lane->q.erase(it);
        lane->cv.notify_all();
      }
      if (!RailHasClaim(lane, rail)) continue;
      SendDesc* d = lane->active;
      // Claim the next chunk (a monolithic frame claims whole).
      bool mono = d->chunk_bytes == 0;
      uint64_t lo = d->sent_offset;
      uint64_t hi = mono ? d->total_data : lo + d->chunk_bytes;
      if (hi > d->total_data) hi = d->total_data;
      uint32_t index = d->next_index;
      d->sent_offset = hi;
      d->next_index++;
      if (d->sent_offset >= d->total_data) {
        // Fully claimed: the next descriptor may start while this
        // one's last writev is still in flight (its completion marker
        // is already ordered ahead on rail 0).
        lane->active = nullptr;
        lane->cv.notify_all();
      }
      d->inflight++;
      long long rc = 0;
      if (d->error == 0 && !d->canceled) {
        tmeta.assign(d->meta.begin(), d->meta.end());
        uint32_t meta_len = static_cast<uint32_t>(tmeta.size());
        StampSid(tmeta.data(), meta_len, lane);
        if (mono) {
          lk.unlock();
          rc = TransmitFrame(node_id, tmeta.data(), meta_len,
                             d->data.data(),
                             static_cast<uint32_t>(d->data.size()));
          lk.lock();
        } else {
          uint8_t* ext = tmeta.data() + d->chunk_ext_off;
          memcpy(ext + kChunkIndexOff, &index, 4);
          memcpy(ext + kChunkOffsetOff, &lo, 8);
          // The byte range's slices of the original segments, in
          // order — exactly split_message's per-chunk data list
          // (wire.py lens table entries come out identical).
          slices.clear();
          uint64_t pos = 0;
          for (const iovec& seg : d->data) {
            uint64_t a = lo > pos ? lo : pos;
            uint64_t b = pos + seg.iov_len < hi ? pos + seg.iov_len : hi;
            if (a < b) {
              slices.push_back(
                  {static_cast<uint8_t*>(seg.iov_base) + (a - pos),
                   static_cast<size_t>(b - a)});
            }
            pos += seg.iov_len;
            if (pos >= hi) break;
          }
          lk.unlock();
          rc = TransmitFrame(node_id, tmeta.data(), meta_len,
                             slices.data(),
                             static_cast<uint32_t>(slices.size()),
                             RailFd(node_id, rail));
          if (rc >= 0) {
            wx_tx_chunks_.fetch_add(1, std::memory_order_relaxed);
          }
          lk.lock();
        }
      }
      d->inflight--;
      if (rc < 0 && d->error == 0) d->error = rc;
      bool poisoned = d->canceled || d->error != 0;
      if (d->inflight == 0 &&
          (poisoned || d->sent_offset >= d->total_data)) {
        if (lane->active == d) {
          lane->active = nullptr;
          lane->cv.notify_all();
        } else if (poisoned) {
          // A poisoned descriptor that was PREEMPTED mid-transfer
          // still sits at the front of its level's queue (the
          // preemption push_front) — unlink it before the delete, or
          // a later promotion pops freed memory (SendCancel clears
          // the queue itself; a writev error on a broken socket
          // reaches here with the descriptor still enqueued).
          auto qit = lane->q.find(d->priority);
          if (qit != lane->q.end()) {
            auto pos = std::find(qit->second.begin(), qit->second.end(),
                                 d);
            if (pos != qit->second.end()) qit->second.erase(pos);
            if (qit->second.empty()) lane->q.erase(qit);
          }
        }
        if (!d->canceled && d->error == 0) {
          wx_tx_msgs_.fetch_add(1, std::memory_order_relaxed);
        }
        lane->done.emplace_back(
            d->ticket, d->canceled ? -ECANCELED
                                   : (d->error < 0 ? d->error : 0));
        delete d;
        lk.unlock();
        NoteDescDone();
        lk.lock();
      }
    }
    // Stop-drain: cancel the backlog so every ticket still completes
    // (Python's reap releases the pinned buffers either way).  First
    // rail to exit does it; descriptors with writers still in flight
    // are only POISONED — their last writer reports the ticket.
    if (!lane->drained) {
      lane->drained = true;
      long long dropped = 0;
      for (auto& kv : lane->q) {
        for (SendDesc* d : kv.second) {
          if (d->inflight > 0) {
            d->canceled = true;
          } else {
            lane->done.emplace_back(d->ticket, -ECANCELED);
            delete d;
            ++dropped;
          }
        }
      }
      lane->q.clear();
      SendDesc* a = lane->active;
      if (a != nullptr && a->inflight == 0 &&
          a->sent_offset < a->total_data) {
        lane->active = nullptr;
        lane->done.emplace_back(a->ticket, -ECANCELED);
        delete a;
        ++dropped;
      } else if (a != nullptr && a->inflight > 0) {
        a->canceled = true;
      }
      lk.unlock();
      for (long long i = 0; i < dropped; ++i) NoteDescDone();
    }
  }

  void PipeLoop() {
    pthread_setname_np(pthread_self(), "psl-pipe");
    uint64_t idle_us = 0;
    uint64_t last_scan_ms = 0, last_live_ms = 0;
    while (!stopped_) {
      uint64_t now_ms = NowMs();
      if (now_ms - last_scan_ms >= 100) {
        last_scan_ms = now_ms;
        ScanPipes();
      }
      bool check_liveness = false;
      if (now_ms - last_live_ms >= 1000) {
        last_live_ms = now_ms;
        check_liveness = true;
      }
      long long moved = 0;
      for (auto it = rpipes_.begin(); it != rpipes_.end();) {
        ReadPipe* rp = it->second;
        // Reader heartbeat: tells a blocked writer this ring IS being
        // drained (see PipeHdr::reader_beat).  Stamped every loop
        // iteration — liveness, not progress — so its staleness is
        // bounded by one iteration (≈ the idle-backoff cap), far under
        // the 1000 ms floor of the writer's dead threshold.
        rp->hdr->reader_beat.store(NowMs(), std::memory_order_relaxed);
        long long n = PumpPipe(rp);
        if (n > 0) moved += n;
        bool drop = n < 0;
        if (drop) {
          struct stat st{};
          if (fstat(rp->fd, &st) == 0) {
            bad_pipes_[rp->path] = st.st_ino;
          }
        }
        if (!drop && check_liveness && n == 0) {
          drop = ReclaimIfDead(rp);
        }
        if (drop) {
          ClosePipe(rp);
          it = rpipes_.erase(it);
        } else {
          ++it;
        }
      }
      if (moved) {
        idle_us = 0;
      } else {
        // Exponential backoff, capped: the cap trades idle CPU for tail
        // latency (PS_SHM_RING_IDLE_US; single-core hosts want it high,
        // dedicated cores can spin near zero).
        uint64_t cap = pipe_idle_cap_us_;
        idle_us = idle_us ? (idle_us * 2 < cap ? idle_us * 2 : cap) : 2;
        timespec ts{0, static_cast<long>(idle_us * 1000)};
        nanosleep(&ts, nullptr);
      }
    }
  }

  static uint64_t NowMs() {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<uint64_t>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
  }

  // Detach (and possibly reclaim the name of) a pipe whose writer died.
  // Serialized under the sibling .lock and guarded by an inode check: a
  // restarted writer may have already recreated the NAME with a fresh
  // inode — unlinking blindly would orphan the new generation's pipe.
  bool ReclaimIfDead(ReadPipe* rp) {
    if (flock(rp->fd, LOCK_EX | LOCK_NB) != 0) return false;  // writer alive
    flock(rp->fd, LOCK_UN);
    std::string lockp = rp->path + ".lock";
    int lock_fd = open(lockp.c_str(), O_CREAT | O_RDWR, 0600);
    if (lock_fd < 0) return true;  // detach; scan re-attaches if live
    if (flock(lock_fd, LOCK_EX | LOCK_NB) != 0) {
      close(lock_fd);  // a writer is mid-create on this name: just detach
      return true;
    }
    struct stat st_name{}, st_mine{};
    if (stat(rp->path.c_str(), &st_name) != 0) {
      // Writer already unlinked the pipe; drop the .lock we just
      // recreated with O_CREAT or it leaks in /dev/shm forever.
      unlink(lockp.c_str());
    } else if (fstat(rp->fd, &st_mine) == 0 &&
               st_name.st_ino == st_mine.st_ino &&
               flock(rp->fd, LOCK_EX | LOCK_NB) == 0) {
      unlink(rp->path.c_str());
      unlink(lockp.c_str());
    }
    flock(lock_fd, LOCK_UN);
    close(lock_fd);
    return true;
  }

  void ScanPipes() {
    std::vector<std::array<std::string, 3>> watches;
    {
      std::lock_guard<std::mutex> lk(pipe_mu_);
      watches = watches_;
    }
    for (const auto& w : watches) {
      DIR* d = opendir(w[0].c_str());
      if (!d) continue;
      while (dirent* e = readdir(d)) {
        std::string name = e->d_name;
        if (name.size() < w[1].size() + w[2].size()) continue;
        if (name.compare(0, w[1].size(), w[1]) != 0) continue;
        if (name.compare(name.size() - w[2].size(), w[2].size(), w[2]) != 0)
          continue;
        std::string path = w[0] + "/" + name;
        if (rpipes_.count(path)) continue;
        // A pipe dropped for a protocol error stays blacklisted for its
        // inode's lifetime — re-attaching the same desynced stream would
        // loop attach/fail forever.  A fresh inode (writer restarted)
        // clears the entry.
        auto bad = bad_pipes_.find(path);
        if (bad != bad_pipes_.end()) {
          struct stat st{};
          if (stat(path.c_str(), &st) == 0 &&
              static_cast<uint64_t>(st.st_ino) == bad->second) {
            continue;
          }
          bad_pipes_.erase(bad);
        }
        TryAttachPipe(path);
      }
      closedir(d);
    }
  }

  void TryAttachPipe(const std::string& path) {
    std::string lockp = path + ".lock";
    int lock_fd = open(lockp.c_str(), O_CREAT | O_RDWR, 0600);
    if (lock_fd < 0) return;
    flock(lock_fd, LOCK_EX);
    int fd = open(path.c_str(), O_RDWR);
    if (fd < 0) {
      // Pipe vanished between scan and attach: drop the .lock we may
      // have just created.
      unlink(lockp.c_str());
    }
    if (fd >= 0) {
      if (flock(fd, LOCK_EX | LOCK_NB) == 0) {
        // No live writer: stale leftover — reclaim the name.
        unlink(path.c_str());
        unlink(lockp.c_str());
        close(fd);
      } else {
        struct stat st{};
        if (fstat(fd, &st) == 0 &&
            static_cast<size_t>(st.st_size) > kPipeDataOff) {
          size_t map_len = st.st_size;
          void* mem = mmap(nullptr, map_len, PROT_READ | PROT_WRITE,
                           MAP_SHARED, fd, 0);
          if (mem != MAP_FAILED) {
            auto* hdr = static_cast<PipeHdr*>(mem);
            if (hdr->magic == kPipeMagic &&
                hdr->size == map_len - kPipeDataOff) {
              auto* rp = new ReadPipe();
              rp->hdr = hdr;
              rp->data = static_cast<uint8_t*>(mem) + kPipeDataOff;
              rp->fd = fd;
              rp->map_len = map_len;
              rp->path = path;
              hdr->reader_beat.store(NowMs(), std::memory_order_relaxed);
              rpipes_[path] = rp;
              fd = -1;  // owned by rp now
            } else {
              munmap(mem, map_len);
            }
          }
        }
        if (fd >= 0) close(fd);
      }
    }
    flock(lock_fd, LOCK_UN);
    close(lock_fd);
  }

  // Drain available pipe bytes through the frame state machine.
  // Returns bytes consumed, or -1 on protocol error.
  long long PumpPipe(ReadPipe* rp) {
    Conn* c = &rp->conn;
    uint64_t head = rp->hdr->head.load(std::memory_order_relaxed);
    const uint64_t size = rp->hdr->size;
    long long consumed = 0;
    while (true) {
      uint64_t tail = rp->hdr->tail.load(std::memory_order_acquire);
      uint64_t avail = tail - head;
      if (avail == 0) break;
      uint64_t n = c->want - c->got;
      if (n > avail) n = avail;
      uint64_t pos = head % size;
      if (n > size - pos) n = size - pos;
      memcpy(StageDst(c), rp->data + pos, n);
      wx_rx_bytes_copy_.fetch_add(n, std::memory_order_relaxed);
      c->got += n;
      head += n;
      consumed += static_cast<long long>(n);
      rp->hdr->head.store(head, std::memory_order_release);
      // Same want == got transition loop as ReadConn: a meta-only
      // frame's lens and payload stages are zero-length.
      while (c->got == c->want) {
        if (!OnStageComplete(c)) return -1;
      }
    }
    return consumed;
  }

  void ClosePipe(ReadPipe* rp) {
    AbandonScatter(&rp->conn);
    munmap(const_cast<uint8_t*>(
               reinterpret_cast<const uint8_t*>(rp->hdr)),
           rp->map_len);
    close(rp->fd);
    delete rp;
  }

  // Register the listener (sentinel data.ptr == nullptr) on the primary
  // epoll and start the primary receive thread.  Further receive pumps
  // spawn lazily, one per ACCEPTED connection (capped, PSL_IO_THREADS):
  // round-robin sharding at accept used to put both of a 2-rail peer's
  // data streams on the same pump whenever an idle control conn
  // happened to occupy the other slot — a 50/50 accept-order lottery
  // that degraded multi-rail receive to single-stream goodput
  // (measured: the tcp bench's sticky ~13 vs ~18.5 Gbps modes).
  void StartIo() {
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = nullptr;
    epoll_ctl(epfd_, EPOLL_CTL_ADD, listen_fd_, &ev);
    const char* cap = getenv("PSL_IO_THREADS");
    max_io_threads_ = cap != nullptr ? atoi(cap) : 8;
    if (max_io_threads_ < 1) max_io_threads_ = 1;
    io_thread_ = std::thread([this] { IoLoop(epfd_); });
  }

  void IoLoop(int epfd) {
    pthread_setname_np(pthread_self(), "psl-io");
    epoll_event events[64];
    while (!stopped_) {
      int n = epoll_wait(epfd, events, 64, 100);
      if (n < 0) {
        if (errno == EINTR) continue;
        break;
      }
      for (int i = 0; i < n; ++i) {
        if (events[i].data.ptr == nullptr) {
          AcceptAll();  // listener lives on the primary epoll only
          continue;
        }
        auto* c = static_cast<Conn*>(events[i].data.ptr);
        if (!ReadConn(c)) {
          epoll_ctl(epfd, EPOLL_CTL_DEL, c->fd, nullptr);
          close(c->fd);
          {
            std::lock_guard<std::mutex> lk(conns_mu_);
            conns_.erase(c->fd);
          }
          AbandonScatter(c);
          delete c;
        }
      }
    }
  }

  void AcceptAll() {
    while (true) {
      int fd = accept4(listen_fd_, nullptr, nullptr, SOCK_NONBLOCK);
      if (fd < 0) break;
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      int rcv = rcvbuf_.load();
      if (rcv > 0) {
        setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &rcv, sizeof(rcv));
      }
      auto* conn = new Conn();
      conn->fd = fd;
      {
        std::lock_guard<std::mutex> lk(conns_mu_);
        conns_[fd] = conn;
      }
      // Each accepted conn gets its own epoll + pump thread while
      // under the cap (every stream drains independently — no
      // accept-order lottery pairing two hot rails on one pump);
      // beyond the cap, round-robin over the existing pumps.  Each
      // Conn is read by exactly one thread, so its frame state
      // machine stays single-threaded.  Only this (primary) thread
      // mutates extra_epfds_/io_threads_, and Stop() joins it first.
      int ep = epfd_;
      if (static_cast<int>(extra_epfds_.size()) < max_io_threads_ - 1) {
        int nep = epoll_create1(0);
        if (nep >= 0) {
          extra_epfds_.push_back(nep);
          io_threads_.emplace_back([this, nep] { IoLoop(nep); });
          ep = nep;
        }
      } else if (!extra_epfds_.empty()) {
        size_t slot = accept_rr_++ % (extra_epfds_.size() + 1);
        if (slot > 0) ep = extra_epfds_[slot - 1];
      }
      epoll_event ev{};
      ev.events = EPOLLIN;
      ev.data.ptr = conn;
      epoll_ctl(ep, EPOLL_CTL_ADD, fd, &ev);
    }
  }

  // Byte sink of the frame state machine for the current stage.
  static uint8_t* StageDst(Conn* c) {
    if (c->stage == 0) return c->header + c->got;
    if (c->stage == 3) {
      // Payload: straight into the transfer buffer (direct-read
      // scatter) or appended after lens+meta in the frame block.
      if (c->scatter_dst != nullptr) return c->scatter_dst + c->got;
      return c->frame.buf + 8ull * c->frame.n_data + c->frame.meta_len +
             c->got;
    }
    return c->frame.buf + c->got;
  }

  static void ResetStage(Conn* c) {
    c->scatter_dst = nullptr;
    c->drop_frame = false;
    c->dup_chunk = false;
    c->stage = 0;
    c->want = kHeaderSize;
    c->got = 0;
  }

  // Stage transition once got == want.  Returns false on protocol
  // error.  Shared by the fd reader and the shm-pipe pump: both are
  // byte streams feeding the same reassembly.  A stage may complete
  // with want == got (empty lens table, empty payload), so callers
  // must re-invoke until want > got (see ReadConn/PumpPipe).
  bool OnStageComplete(Conn* c) {
    if (c->stage == 0) {
      uint32_t magic, meta_len, n_data;
      memcpy(&magic, c->header, 4);
      memcpy(&meta_len, c->header + 4, 4);
      memcpy(&n_data, c->header + 8, 4);
      if (magic != kMagic) return false;
      c->frame.meta_len = meta_len;
      c->frame.n_data = n_data;
      // Lens + meta land in one right-sized block; the payload's
      // destination is decided only after the meta is readable.
      c->body_size = 8ull * n_data + meta_len;
      bool pool_hit = false;
      c->frame.buf = FramePool::Alloc(c->body_size, &pool_hit);
      (pool_hit ? wx_rx_pool_hits_ : wx_rx_pool_misses_)
          .fetch_add(1, std::memory_order_relaxed);
      c->stage = 1;
      c->want = 8ull * n_data;  // lens arrive first
      c->got = 0;
    } else if (c->stage == 1) {
      // Lens complete: meta follows in the same block (got continues).
      c->stage = 2;
      c->want = c->body_size;
    } else if (c->stage == 2) {
      return OnMetaComplete(c);
    } else {
      OnPayloadComplete(c);
    }
    return true;
  }

  // Meta complete: learn the payload size and route the payload bytes.
  // A reassembly-eligible chunk frame's payload reads DIRECTLY into
  // its transfer's buffer at the chunk's byte offset — the only pass
  // over the data; everything else grows the frame block to the full
  // body and delivers as-is.
  bool OnMetaComplete(Conn* c) {
    uint64_t payload = 0;
    const uint64_t* lens = reinterpret_cast<uint64_t*>(c->frame.buf);
    for (uint32_t i = 0; i < c->frame.n_data; ++i) payload += lens[i];
    if (reassemble_ && payload > 0 && BeginChunkScatter(c, payload)) {
      c->stage = 3;
      c->want = payload;
      c->got = 0;
      return true;
    }
    if (payload == 0) {
      // Meta-only frame (control, empty vals): deliver as-is.
      EnqueueFrame(c->frame);
      c->frame = Frame();
      ResetStage(c);
      return true;
    }
    // Pool-aware "realloc": move lens+meta into a full-body block.
    size_t full = c->body_size + payload;
    uint8_t* grown = FramePool::Alloc(full);
    if (grown != nullptr && c->frame.buf != nullptr) {
      memcpy(grown, c->frame.buf, c->body_size);
    }
    FramePool::Release(c->frame.buf);
    c->frame.buf = grown;
    c->stage = 3;
    c->want = payload;
    c->got = 0;
    return true;
  }

  // Payload complete: finish the direct-read absorb (complete
  // transfers deliver as ONE frame), discard a dropped frame, or
  // deliver the ordinary full frame.  Marking received + enqueueing
  // the completed transfer is ONE xfers_mu_ critical section: with
  // chunks striped over rails, transfer N+1's last chunk lands
  // strictly after transfer N's (per-rail FIFO + final-chunk-on-rail-0
  // sender discipline), so serialized mark+enqueue keeps completion
  // delivery in submission order.
  void OnPayloadComplete(Conn* c) {
    if (c->scatter_dst != nullptr) {
      std::lock_guard<std::mutex> lk(xfers_mu_);
      auto it = xfers_.find(c->pending_key);
      if (it != xfers_.end()) {
        ConnXfer& x = it->second;
        x.readers--;
        if (!c->dup_chunk && !x.dropped) {
          x.received[c->pending_index] = true;
          x.got++;
        }
        if (x.dropped) {
          if (x.readers == 0) {
            FramePool::Release(x.buf);
            xfers_.erase(it);
          }
        } else if (x.got == x.total && x.readers == 0) {
          Frame out;
          out.buf = x.buf;
          out.meta_len = x.meta_len;
          out.n_data = x.nseg;
          xfers_.erase(it);
          EnqueueFrame(out);
        }
      }
      FramePool::Release(c->frame.buf);
      c->frame = Frame();
    } else if (c->drop_frame) {
      FramePool::Release(c->frame.buf);
      c->frame = Frame();
    } else {
      EnqueueFrame(c->frame);
      c->frame = Frame();
    }
    ResetStage(c);
  }

  // A conn died mid-payload while direct-reading into a transfer
  // buffer: release its reader ref so the entry can be evicted (the
  // index was never marked received — the partial bytes are simply
  // dead weight until then).
  void AbandonScatter(Conn* c) {
    if (c->stage != 3 || c->scatter_dst == nullptr) return;
    std::lock_guard<std::mutex> lk(xfers_mu_);
    auto it = xfers_.find(c->pending_key);
    if (it == xfers_.end()) return;
    ConnXfer& x = it->second;
    x.readers--;
    if (x.dropped && x.readers == 0) {
      FramePool::Release(x.buf);
      xfers_.erase(it);
    }
    c->scatter_dst = nullptr;
  }

  void EnqueueFrame(const Frame& f) {
    wx_rx_frames_.fetch_add(1, std::memory_order_relaxed);
    {
      std::lock_guard<std::mutex> lk(queue_mu_);
      if (recv_priority_ && FrameIsExpress(f)) {
        express_.push_back(f);
      } else {
        queue_.push_back(f);
      }
    }
    queue_cv_.notify_one();
  }

  // The EXT_CHUNK payload inside a packed meta, or nullptr when the
  // frame is not a (reassembly-eligible) chunk.  Data frames carry no
  // node list, so the extension tail sits at a computable offset.
  static const uint8_t* FindChunkExt(const uint8_t* meta,
                                     uint32_t meta_len) {
    if (meta_len < kMetaFixedSize) return nullptr;
    if (meta[kMetaControlCmdOff] != 0) return nullptr;
    uint16_t num_nodes;
    memcpy(&num_nodes, meta + kMetaNumNodesOff, 2);
    if (num_nodes != 0) return nullptr;
    uint16_t ndt;
    memcpy(&ndt, meta + kMetaNumDtypesOff, 2);
    uint32_t body_len;
    memcpy(&body_len, meta + kMetaBodyLenOff, 4);
    size_t off = kMetaFixedSize + ndt + body_len;
    while (off + 2 <= meta_len) {
      uint8_t tag = meta[off];
      uint8_t len = meta[off + 1];
      off += 2;
      if (off + len > meta_len) return nullptr;
      if (tag == kExtChunkTag) {
        if (len < kChunkFixedSize) return nullptr;
        uint8_t nseg = meta[off + kChunkNsegOff];
        if (len != kChunkFixedSize + nseg * kChunkSegEntry) return nullptr;
        return meta + off;
      }
      off += len;  // unknown tags skip by length
    }
    return nullptr;
  }

  // Matches the Python ChunkAssembler's table cap.  Eviction of a
  // LIVE transfer (a high-fan-in receiver with 256+ concurrent
  // chunked pushes) loses it permanently — later chunks re-create a
  // phantom entry that can never complete and the sender only
  // recovers via its request deadline — so evictions warn loudly.
  static constexpr size_t kMaxXfers = 256;

  // Native receive-side DIRECT-READ scatter: called at meta-complete
  // time (the payload is still in the kernel), so an eligible chunk
  // frame's payload bytes can be read straight into the transfer's
  // reassembly buffer at the chunk's byte offset — the chunk's payload
  // is a contiguous byte range of the original segments'
  // concatenation, which is exactly the frame body layout.  Returns
  // true when stage 3 was routed (scatter_dst set, or drop_frame for
  // an inconsistent chunk whose payload must be consumed and
  // discarded); false leaves the ordinary deliver-raw path (not a
  // chunk, or allocation failure — Python's assembler remains the
  // fallback).
  bool BeginChunkScatter(Conn* c, uint64_t payload) {
    Frame& f = c->frame;
    const uint8_t* meta = f.buf + 8ull * f.n_data;
    const uint8_t* ext = FindChunkExt(meta, f.meta_len);
    if (ext == nullptr) return false;
    uint64_t xfer;
    uint32_t index, total;
    uint64_t offset;
    memcpy(&xfer, ext, 8);
    memcpy(&index, ext + kChunkIndexOff, 4);
    memcpy(&total, ext + kChunkTotalOff, 4);
    memcpy(&offset, ext + kChunkOffsetOff, 8);
    uint8_t nseg = ext[kChunkNsegOff];
    if (index == kChunkCompleteIndex || total == 0) return false;
    int sender;
    memcpy(&sender, meta + kMetaSenderOff, 4);
    auto key = std::make_pair(static_cast<long long>(sender),
                              static_cast<unsigned long long>(xfer));
    std::lock_guard<std::mutex> lk(xfers_mu_);
    auto it = xfers_.find(key);
    if (it != xfers_.end() && it->second.dropped) {
      // A rail already declared this transfer inconsistent: consume
      // and discard this stripe too (no reader ref — the entry may
      // reclaim under us otherwise).
      size_t full = c->body_size + payload;
      uint8_t* grown = FramePool::Alloc(full);
      if (grown != nullptr && f.buf != nullptr) {
        memcpy(grown, f.buf, c->body_size);
      }
      FramePool::Release(f.buf);
      f.buf = grown;
      c->drop_frame = true;
      return true;
    }
    if (it == xfers_.end()) {
      if (xfers_.size() >= kMaxXfers) {
        // Evict the stalest partial with no active readers (a sender
        // that died mid-transfer and reconnected would otherwise leak
        // its old entries).
        auto victim = xfers_.end();
        for (auto jt = xfers_.begin(); jt != xfers_.end(); ++jt) {
          if (jt->second.readers > 0) continue;
          if (victim == xfers_.end() ||
              jt->second.seq < victim->second.seq) {
            victim = jt;
          }
        }
        if (victim == xfers_.end()) return false;  // all active
        fprintf(stderr,
                "[pslite_core] W reassembly table full (%zu): evicting "
                "partial xfer %llu from %lld (%u/%u chunks) — the "
                "sender's request deadline will have to recover it\n",
                xfers_.size(),
                static_cast<unsigned long long>(victim->first.second),
                victim->first.first, victim->second.got,
                victim->second.total);
        FramePool::Release(victim->second.buf);
        xfers_.erase(victim);
      }
      ConnXfer x;
      x.total = total;
      x.nseg = nseg;
      x.meta_len = f.meta_len;
      for (uint8_t i = 0; i < nseg; ++i) {
        uint64_t ln;
        memcpy(&ln, ext + kChunkFixedSize + i * kChunkSegEntry, 8);
        x.total_bytes += ln;
      }
      x.body_size = 8ull * nseg + f.meta_len + x.total_bytes;
      x.buf = FramePool::Alloc(x.body_size);
      if (x.buf == nullptr) return false;  // deliver raw, Python copes
      // Lens table of the ORIGINAL segments, then the template meta
      // with the index patched to the completion sentinel.
      for (uint8_t i = 0; i < nseg; ++i) {
        memcpy(x.buf + 8ull * i,
               ext + kChunkFixedSize + i * kChunkSegEntry, 8);
      }
      memcpy(x.buf + 8ull * nseg, meta, f.meta_len);
      size_t ext_off = static_cast<size_t>(ext - meta);
      memcpy(x.buf + 8ull * nseg + ext_off + kChunkIndexOff,
             &kChunkCompleteIndex, 4);
      x.received.assign(total, false);
      x.seq = ++xfer_seq_;
      it = xfers_.emplace(key, std::move(x)).first;
    }
    ConnXfer& x = it->second;
    if (index >= x.total || x.total != total || x.meta_len != f.meta_len ||
        offset + payload > x.total_bytes) {
      // Inconsistent chunk: drop the whole transfer (matching the
      // Python assembler's bounds-check-before-scatter posture) —
      // never deliver a torn payload.  The chunk's payload bytes
      // still have to leave the stream: stage 3 consumes them into
      // the grown frame block and discards the frame.
      fprintf(stderr,
              "[pslite_core] W inconsistent chunk (xfer %llu from %d); "
              "dropping the transfer\n",
              static_cast<unsigned long long>(xfer), sender);
      if (x.readers == 0) {
        FramePool::Release(x.buf);
        xfers_.erase(it);
      } else {
        // Another rail is mid-read into x.buf: the last reader out
        // reclaims (OnPayloadComplete/AbandonScatter).
        x.dropped = true;
      }
      size_t full = c->body_size + payload;
      uint8_t* grown = FramePool::Alloc(full);
      if (grown != nullptr && f.buf != nullptr) {
        memcpy(grown, f.buf, c->body_size);
      }
      FramePool::Release(f.buf);
      f.buf = grown;
      c->drop_frame = true;
      return true;
    }
    // Duplicate index (reassembly runs only with the resender off, so
    // a dup carries identical bytes): rewrite them in place, but do
    // not advance the completion count.
    c->dup_chunk = x.received[index];
    c->pending_index = index;
    c->pending_key = key;
    c->scatter_dst = x.buf + 8ull * x.nseg + x.meta_len + offset;
    x.readers++;
    return true;
  }

  // Pump all available bytes through the frame state machine.  Returns
  // false when the peer closed or errored.
  bool ReadConn(Conn* c) {
    while (true) {
      ssize_t n = read(c->fd, StageDst(c), c->want - c->got);
      wx_rx_syscalls_.fetch_add(1, std::memory_order_relaxed);
      if (n == 0) return false;
      if (n < 0) {
        return errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR;
      }
      // Direct-read scatter (stage 3 into a transfer buffer) is the
      // zero-copy path; everything else stages into a pool block.
      if (c->stage == 3 && c->scatter_dst != nullptr) {
        wx_rx_bytes_zc_.fetch_add(static_cast<uint64_t>(n),
                                  std::memory_order_relaxed);
      } else {
        wx_rx_bytes_copy_.fetch_add(static_cast<uint64_t>(n),
                                    std::memory_order_relaxed);
      }
      c->got += static_cast<size_t>(n);
      // A stage may complete with want == got (empty lens table of a
      // meta-only frame, empty payload) — keep transitioning until the
      // machine wants bytes again (ResetStage always wants a header).
      while (c->got == c->want) {
        if (!OnStageComplete(c)) return false;
      }
    }
  }

  int epfd_;
  int listen_fd_ = -1;
  std::string bound_path_;
  std::thread io_thread_;
  // Extra receive pumps (lazily one per accepted conn, capped by
  // PSL_IO_THREADS): each owns an epoll set.  Primary io thread only.
  std::vector<int> extra_epfds_;
  std::vector<std::thread> io_threads_;
  size_t accept_rr_ = 0;  // primary io thread only
  int max_io_threads_ = 8;
  std::atomic<bool> stopped_{false};
  std::unordered_map<int, Conn*> conns_;  // conns_mu_ (reads io-threads)
  std::mutex conns_mu_;
  std::unordered_map<int, int> send_fds_;
  // Extra per-peer data connections (PS_NATIVE_RAILS).  send_mu_.
  std::unordered_map<int, std::vector<int>> rail_fds_;
  std::atomic<int> rails_{1};
  std::atomic<int> sndbuf_{0};
  std::atomic<int> rcvbuf_{0};
  std::unordered_map<int, WritePipe*> pipes_;                  // send_mu_
  std::unordered_map<std::string, WritePipe*> pipes_by_path_;  // send_mu_
  // Dead-reader pipes parked until shutdown (mapping must outlive any
  // sender blocked inside PipeWriteVec at retirement time).  send_mu_.
  std::vector<WritePipe*> dead_write_pipes_;
  // Lazily read from PS_SHM_RING_DEAD_MS (0 = not yet resolved).
  std::atomic<uint64_t> reader_dead_ms_{0};
  std::vector<std::array<std::string, 3>> watches_;  // pipe_mu_
  std::unordered_map<std::string, ReadPipe*> rpipes_;  // pipe thread only
  std::unordered_map<std::string, uint64_t> bad_pipes_;  // path -> inode
  std::thread pipe_thread_;
  std::mutex pipe_mu_;
  std::atomic<uint64_t> pipe_idle_cap_us_{500};
  std::atomic<int> inflight_sends_{0};
  std::mutex send_mu_;
  std::mutex per_fd_send_mu_[kSendLocks];
  // Per-peer native sender lanes (EnqueueSend/LaneLoop).
  std::unordered_map<int, SendLane*> lanes_;  // lanes_mu_
  std::mutex lanes_mu_;
  std::atomic<uint64_t> ticket_seq_{0};
  std::atomic<long long> pending_descs_{0};
  std::mutex flush_mu_;
  std::condition_variable flush_cv_;
  // Receive-side native reassembly (BeginChunkScatter): enabled by the
  // van when its config is compatible (no resender, no force-order) —
  // chunk-level ACK/ordering layers need to SEE the chunk frames, so
  // they keep the Python assembler.  In-flight transfers are
  // Core-level (xfers_mu_): chunks striped across rails land on
  // different receive pumps but scatter into ONE shared buffer (the
  // payload reads themselves are lock-free — disjoint byte ranges).
  std::atomic<bool> reassemble_{false};
  // Wire-plane observatory counters (StatsSnapshot): relaxed monotonic
  // totals — one cheap fetch_add at each syscall/frame event, mutable
  // so the const snapshot can load them.
  mutable std::atomic<uint64_t> wx_tx_syscalls_{0};
  mutable std::atomic<uint64_t> wx_tx_frames_{0};
  mutable std::atomic<uint64_t> wx_tx_chunks_{0};
  mutable std::atomic<uint64_t> wx_tx_bytes_{0};
  mutable std::atomic<uint64_t> wx_tx_msgs_{0};
  mutable std::atomic<uint64_t> wx_rx_syscalls_{0};
  mutable std::atomic<uint64_t> wx_rx_frames_{0};
  mutable std::atomic<uint64_t> wx_rx_bytes_copy_{0};
  mutable std::atomic<uint64_t> wx_rx_bytes_zc_{0};
  mutable std::atomic<uint64_t> wx_rx_pool_hits_{0};
  mutable std::atomic<uint64_t> wx_rx_pool_misses_{0};
  std::map<std::pair<long long, unsigned long long>, ConnXfer> xfers_;
  uint64_t xfer_seq_ = 0;  // xfers_mu_
  std::mutex xfers_mu_;
  std::deque<Frame> queue_;
  std::deque<Frame> express_;  // priority > 0 data frames pop first
  // PS_RECV_PRIORITY=0 restores the single strict-FIFO queue (process
  // env: the native core is per-process, unlike the per-node Python
  // Environment overrides of the in-process test clusters).
  const bool recv_priority_ = [] {
    const char* v = getenv("PS_RECV_PRIORITY");
    return v == nullptr || strcmp(v, "0") != 0;
  }();
  std::mutex queue_mu_;
  std::condition_variable queue_cv_;
};

// Parallel memcpy pool for the shm van's segment writes — the native
// counterpart of the reference IPC transport's async copy thread pool
// (rdma_transport.h:469-633, BYTEPS_IPC_COPY_NUM_THREADS): multi-MB
// payload copies are split across persistent native threads, GIL-free
// (Python enters through a ctypes call, which releases the GIL).
class CopyPool {
 public:
  explicit CopyPool(int n_threads)
      : n_(n_threads < 1 ? 1 : n_threads) {
    for (int i = 0; i < n_; ++i) {
      threads_.emplace_back([this] { Work(); });
    }
  }

  ~CopyPool() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : threads_) t.join();
  }

  void Copy(uint8_t* dst, const uint8_t* src, uint64_t n) {
    constexpr uint64_t kMinChunk = 1ull << 20;  // below this, inline memcpy
    uint64_t want = n / kMinChunk;
    int parts = static_cast<int>(
        want < 1 ? 1 : (want > static_cast<uint64_t>(n_) + 1
                            ? static_cast<uint64_t>(n_) + 1
                            : want));
    if (parts <= 1) {
      memcpy(dst, src, n);
      return;
    }
    // One job at a time per pool; concurrent callers serialize here.
    std::lock_guard<std::mutex> caller_lk(caller_mu_);
    Job job;
    job.dst = dst;
    job.src = src;
    job.n = n;
    job.parts = parts;
    {
      std::lock_guard<std::mutex> lk(mu_);
      job_ = &job;
      ++seq_;
    }
    cv_.notify_all();
    RunChunks(&job);  // the caller is a worker too
    // The job lives on this stack: wait until every chunk is copied AND
    // every attached worker detached before letting it go out of scope.
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [&] {
      return job.done.load() == job.parts && job.workers == 0;
    });
    job_ = nullptr;
  }

 private:
  struct Job {
    uint8_t* dst = nullptr;
    const uint8_t* src = nullptr;
    uint64_t n = 0;
    int parts = 0;
    std::atomic<int> next{0};
    std::atomic<int> done{0};
    int workers = 0;  // attached pool threads; guarded by mu_
  };

  void RunChunks(Job* job) {
    int finished = 0;
    for (int i = job->next.fetch_add(1); i < job->parts;
         i = job->next.fetch_add(1)) {
      uint64_t lo = job->n * i / job->parts;
      uint64_t hi = job->n * (i + 1) / job->parts;
      memcpy(job->dst + lo, job->src + lo, hi - lo);
      ++finished;
    }
    if (finished) job->done.fetch_add(finished);
  }

  void Work() {
    uint64_t seen = 0;
    while (true) {
      Job* job = nullptr;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] { return stop_ || seq_ != seen; });
        if (stop_) return;
        seen = seq_;
        job = job_;  // may already be null (job finished without us)
        if (job != nullptr) ++job->workers;
      }
      if (job == nullptr) continue;
      RunChunks(job);
      {
        std::lock_guard<std::mutex> lk(mu_);
        --job->workers;
      }
      done_cv_.notify_all();
    }
  }

  int n_;
  std::vector<std::thread> threads_;
  std::mutex mu_;
  std::mutex caller_mu_;
  std::condition_variable cv_;
  std::condition_variable done_cv_;
  Job* job_ = nullptr;
  uint64_t seq_ = 0;
  bool stop_ = false;
};

}  // namespace

// ---- wire codec kernels (docs/compression.md) ------------------------------
//
// Fused single-pass blockwise quantize for the Python codec tier
// (pslite_tpu/ops/codecs.py): one read of the span computes the block
// max AND stages the (optionally EF-folded) values in an L1-resident
// block buffer; the second loop quantizes from L1, writes the 1/4-width
// codes, and updates the error-feedback residual — ~5 bytes of memory
// traffic per element (13 with EF) where the numpy fallback's separate
// abs/max/mul/rint/clip/cast passes move ~40+.  Called per span from
// the codec thread pool (ctypes releases the GIL), so spans scale
// across cores while the caller's Python threads stay responsive.
//
// BIT-IDENTICAL to the numpy fallback by construction: same op order
// (finite-masked block max, scale = max(fmax, 1e-12)/qmax, y = eff *
// (1.0f/scale), rint/clip for int8; clip + f32->f16 RNE + the
// ml_dtypes-derived 64K lookup for fp8), every step an exactly-rounded
// IEEE f32 op — so mixed native/pure-Python clusters produce the same
// wire bytes (asserted in tests/test_ops.py).

namespace {

uint8_t g_fp8_enc_lut[65536];
float g_fp8_dec_lut[256];
std::atomic<int> g_fp8_tables_ready{0};

// Software f32 -> f16 bit conversion, exact round-to-nearest-even for
// normal f16 results.  Values below the f16 normal range all map to
// e4m3 code 0 through the lookup (e4m3's smallest nonzero is 2^-9, and
// ties round even at 2^-10), so sub-subnormal rounding minutiae cannot
// change the emitted byte — see the parity test.
inline uint16_t F32ToF16Bits(float f) {
  uint32_t x;
  memcpy(&x, &f, 4);
  uint32_t sign = (x >> 16) & 0x8000u;
  uint32_t exp = (x >> 23) & 0xFFu;
  uint32_t man = x & 0x7FFFFFu;
  if (exp == 0xFFu) {  // inf / nan
    return static_cast<uint16_t>(
        sign | 0x7C00u | (man ? (0x0200u | (man >> 13)) : 0));
  }
  int32_t e = static_cast<int32_t>(exp) - 127 + 15;
  if (e >= 31) return static_cast<uint16_t>(sign | 0x7C00u);  // -> inf
  if (e <= 0) return static_cast<uint16_t>(sign);  // below e4m3 range
  uint16_t h = static_cast<uint16_t>(sign | (static_cast<uint32_t>(e) << 10) |
                                     (man >> 13));
  uint32_t rem = man & 0x1FFFu;
  if (rem > 0x1000u || (rem == 0x1000u && (h & 1u))) ++h;  // RNE (carry ok)
  return h;
}

constexpr uint64_t kCodecMaxBlock = 1024;

#if defined(__x86_64__)
__attribute__((target("f16c")))
void F32ToF16SpanF16C(const float* src, uint16_t* dst, uint64_t m) {
  uint64_t i = 0;
  for (; i + 8 <= m; i += 8) {
    __m256 v = _mm256_loadu_ps(src + i);
    __m128i h =
        _mm256_cvtps_ph(v, _MM_FROUND_TO_NEAREST_INT | _MM_FROUND_NO_EXC);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(dst + i), h);
  }
  for (; i < m; ++i) dst[i] = F32ToF16Bits(src[i]);
}
#endif

// Hardware vs software f32->f16: identical FINAL e4m3 bytes either way
// — normals round RNE in both, every f16 subnormal result maps to
// e4m3 code 0 through the lookup, and all NaN payloads collapse onto
// the single e4m3fn NaN — so runtime dispatch cannot break the
// mixed-cluster bit-exactness contract.
// Persistent worker pool for the codec kernels: the Python tier makes
// ONE ctypes call per payload (GIL released once) and the spans fan
// out on C++ threads — dispatching spans from Python instead pays a
// GIL handoff per span, which under a busy receive pump stretches a
// ~2 ms decode into tens of ms (measured via the trace tier).
class CodecSpanPool {
 public:
  static CodecSpanPool& Get() {
    static CodecSpanPool* p = new CodecSpanPool();
    return *p;
  }

  // Run fn over block-aligned spans of [0, n); serializes concurrent
  // callers (they would only fight for memory bandwidth anyway).
  void Run(uint64_t n, uint64_t block, int nthreads,
           const std::function<void(uint64_t, uint64_t)>& fn) {
    if (nthreads <= 1 || n * 4 < (1u << 21)) {
      fn(0, n);
      return;
    }
    std::lock_guard<std::mutex> run_lk(run_mu_);
    std::unique_lock<std::mutex> lk(mu_);
    EnsureThreadsLocked(nthreads - 1);  // caller works too
    const uint64_t blocks = (n + block - 1) / block;
    const uint64_t per =
        (blocks + static_cast<uint64_t>(nthreads) - 1) / nthreads * block;
    spans_.clear();
    for (uint64_t a = 0; a < n; a += per)
      spans_.emplace_back(a, std::min(a + per, n));
    fn_ = &fn;
    next_ = 0;
    remaining_ = spans_.size();
    cv_.notify_all();
    // The caller drains spans alongside the workers.
    DrainLocked(lk);
    done_cv_.wait(lk, [&] { return remaining_ == 0; });
    fn_ = nullptr;
  }

 private:
  void EnsureThreadsLocked(int n) {
    while (static_cast<int>(threads_.size()) < n) {
      threads_.emplace_back([this] { Loop(); });
      threads_.back().detach();
    }
  }

  void DrainLocked(std::unique_lock<std::mutex>& lk) {
    while (fn_ && next_ < spans_.size()) {
      const auto span = spans_[next_++];
      const auto* fn = fn_;
      lk.unlock();
      (*fn)(span.first, span.second);
      lk.lock();
      if (--remaining_ == 0) done_cv_.notify_all();
    }
  }

  void Loop() {
    std::unique_lock<std::mutex> lk(mu_);
    for (;;) {
      cv_.wait(lk, [&] { return fn_ && next_ < spans_.size(); });
      DrainLocked(lk);
    }
  }

  std::mutex run_mu_;  // one payload at a time
  std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable done_cv_;
  std::vector<std::thread> threads_;
  std::vector<std::pair<uint64_t, uint64_t>> spans_;
  const std::function<void(uint64_t, uint64_t)>* fn_ = nullptr;
  size_t next_ = 0;
  size_t remaining_ = 0;
};

#if defined(__x86_64__)
inline bool CpuHasF16C() {
  unsigned int eax = 0, ebx = 0, ecx = 0, edx = 0;
  if (!__get_cpuid(1, &eax, &ebx, &ecx, &edx)) return false;
  return (ecx >> 29) & 1u;  // CPUID.1:ECX.F16C
}
#endif

inline void F32ToF16Span(const float* src, uint16_t* dst, uint64_t m) {
#if defined(__x86_64__)
  static const bool kHasF16C = CpuHasF16C();
  if (kHasF16C) {
    F32ToF16SpanF16C(src, dst, m);
    return;
  }
#endif
  for (uint64_t i = 0; i < m; ++i) dst[i] = F32ToF16Bits(src[i]);
}

}  // namespace

extern "C" {

struct psl_frame_view {
  uint8_t* buf;
  uint32_t meta_len;
  uint32_t n_data;
};

void* psl_create() { return new Core(); }

int psl_bind(void* h, int port, int backlog) {
  return static_cast<Core*>(h)->Bind(port, backlog);
}

int psl_connect(void* h, int node_id, const char* host, int port,
                int timeout_ms) {
  return static_cast<Core*>(h)->Connect(node_id, host, port, timeout_ms);
}

int psl_bind_local(void* h, const char* path, int backlog) {
  return static_cast<Core*>(h)->BindLocal(path, backlog);
}

int psl_pipe_connect(void* h, int node_id, const char* path,
                     uint64_t data_bytes) {
  return static_cast<Core*>(h)->PipeConnect(node_id, path, data_bytes);
}

int psl_pipe_watch(void* h, const char* dir, const char* prefix,
                   const char* suffix, int idle_cap_us) {
  return static_cast<Core*>(h)->PipeWatch(dir, prefix, suffix, idle_cap_us);
}

int psl_connect_local(void* h, int node_id, const char* path,
                      int timeout_ms) {
  return static_cast<Core*>(h)->ConnectLocal(node_id, path, timeout_ms);
}

long long psl_send(void* h, int node_id, const uint8_t* meta,
                   uint32_t meta_len, uint32_t n_data,
                   const uint8_t* const* data, const uint64_t* lens) {
  return static_cast<Core*>(h)->Send(node_id, meta, meta_len, n_data, data,
                                     lens);
}

int psl_abi_version() { return kAbiVersion; }

// Wire-plane observatory (docs/observability.md): fill the caller's
// counter block in one call.  Returns the struct size actually
// written, so a caller built against a newer layout can detect a
// short (older) library without a separate version probe.
int psl_stats_snapshot(void* h, psl_wire_stats* out) {
  static_cast<Core*>(h)->StatsSnapshot(out);
  return static_cast<int>(sizeof(psl_wire_stats));
}

long long psl_send_enqueue(void* h, int node_id, int priority,
                           const uint8_t* meta, uint32_t meta_len,
                           uint32_t n_data, const uint8_t* const* data,
                           const uint64_t* lens, uint64_t chunk_bytes,
                           int32_t chunk_ext_off) {
  return static_cast<Core*>(h)->EnqueueSend(node_id, priority, meta,
                                            meta_len, n_data, data, lens,
                                            chunk_bytes, chunk_ext_off);
}

int psl_send_reap(void* h, int node_id, uint64_t* tickets, long long* status,
                  int cap) {
  return static_cast<Core*>(h)->SendReap(node_id, tickets, status, cap);
}

int psl_send_flush(void* h, int timeout_ms) {
  return static_cast<Core*>(h)->SendFlush(timeout_ms);
}

long long psl_send_cancel(void* h, int node_id) {
  return static_cast<Core*>(h)->SendCancel(node_id);
}

void psl_send_reset_sid(void* h, int node_id) {
  static_cast<Core*>(h)->SendResetSid(node_id);
}

void psl_set_reassembly(void* h, int on) {
  static_cast<Core*>(h)->SetReassembly(on);
}

// Multi-rail data plane (PS_NATIVE_RAILS, docs/native_core.md): call
// psl_set_rails BEFORE the first data send (rail threads spawn with
// the lane; receive pumps spawn per accepted conn, rail-agnostic);
// psl_add_rail dials rail `idx` (1-based) to a peer.  psl_set_sockbuf
// mirrors the Python van's PS_TCP_SNDBUF/PS_TCP_RCVBUF bounds onto
// native sockets.
void psl_set_rails(void* h, int n) { static_cast<Core*>(h)->SetRails(n); }

int psl_add_rail(void* h, int node_id, const char* host, int port,
                 int timeout_ms, int idx) {
  return static_cast<Core*>(h)->AddRail(node_id, host, port, timeout_ms,
                                        idx);
}

void psl_set_sockbuf(void* h, int snd, int rcv) {
  static_cast<Core*>(h)->SetSockBuf(snd, rcv);
}

int psl_recv(void* h, psl_frame_view* out, int timeout_ms) {
  Frame f;
  int rc = static_cast<Core*>(h)->Recv(&f, timeout_ms);
  if (rc == 1) {
    out->buf = f.buf;
    out->meta_len = f.meta_len;
    out->n_data = f.n_data;
  }
  return rc;
}

void psl_frame_free(uint8_t* buf) { FramePool::Release(buf); }

// Single-shot GIL-free kernels for the RECEIVE-side Python hot loops
// (docs/native_core.md): ctypes releases the GIL around CDLL calls, so
// routing the chunk-scatter memcpy and the server's in-place apply add
// through these lets the van-recv thread, the apply shard threads, and
// the meta decoder stream concurrently instead of serializing on one
// GIL (numpy's copy/ufunc paths hold it).  The adds are plain
// element-wise IEEE ops — results are bit-identical to numpy's
// same-dtype in-place add, so enabling/disabling the native path can
// never change stored values.
void psl_memcpy(void* dst, const void* src, uint64_t n) {
  memcpy(dst, src, n);
}

void psl_iadd_f32(float* dst, const float* src, uint64_t n) {
  for (uint64_t i = 0; i < n; ++i) dst[i] += src[i];
}

// Register the fp8_e4m3fn lookup tables (built Python-side from
// ml_dtypes so both planes share ONE rounding definition): enc maps a
// f16 bit pattern to the e4m3 byte, dec maps the byte back to f32.
void psl_codec_set_fp8_tables(const uint8_t* enc, const float* dec) {
  memcpy(g_fp8_enc_lut, enc, sizeof(g_fp8_enc_lut));
  memcpy(g_fp8_dec_lut, dec, sizeof(g_fp8_dec_lut));
  g_fp8_tables_ready.store(1, std::memory_order_release);
}

// Encode one block-aligned span: kind 0 = int8 (NaN -> reserved -128,
// reported in the returned flag bit 1), kind 1 = fp8_e4m3fn (NaN is a
// native encoding).  ``resid`` (nullable) fuses error feedback: the
// effective value is x + resid and resid is left holding the new
// quantization error (0 where the input was non-finite).  Returns the
// flag bits, or -1 when this call cannot run natively (unsupported
// block / fp8 tables not registered) and the caller must take the
// numpy fallback.
int psl_codec_encode(int kind, const float* x, float* resid, uint64_t n,
                     uint64_t block, uint8_t* codes, float* scales) {
  if (block == 0 || block > kCodecMaxBlock) return -1;
  if (kind != 0 && kind != 1) return -1;
  if (kind == 1 && !g_fp8_tables_ready.load(std::memory_order_acquire))
    return -1;
  const float qmax = (kind == 1) ? 448.0f : 127.0f;
  int flags = 0;
  float eff[kCodecMaxBlock];
  for (uint64_t b0 = 0; b0 < n; b0 += block) {
    const uint64_t m = (n - b0 < block) ? (n - b0) : block;
    const float* xs = x + b0;
    float* rs = resid ? resid + b0 : nullptr;
    float fmax = 0.0f;
    for (uint64_t i = 0; i < m; ++i) {
      const float e = rs ? xs[i] + rs[i] : xs[i];
      eff[i] = e;
      const float a = fabsf(e);
      if (std::isfinite(a) && a > fmax) fmax = a;  // finite-masked max
    }
    const float scale = ((fmax > 1e-12f) ? fmax : 1e-12f) / qmax;
    const float inv = 1.0f / scale;
    scales[b0 / block] = scale;
    uint8_t* cs = codes + b0;
    if (kind == 0) {
      for (uint64_t i = 0; i < m; ++i) {
        const float q = rintf(eff[i] * inv);  // RNE, same as np.rint
        int8_t c;
        if (std::isnan(q)) {
          c = -128;
          flags |= 1;
        } else if (q > 127.0f) {
          c = 127;
        } else if (q < -127.0f) {
          c = -127;
        } else {
          c = static_cast<int8_t>(q);
        }
        cs[i] = static_cast<uint8_t>(c);
        if (rs) {
          // Matches the numpy EF path: reconstruct (the -128 sentinel
          // decodes as -128*scale there too) and zero non-finite
          // error so NaN/Inf inputs cannot poison later rounds.
          const float r2 = eff[i] - static_cast<float>(c) * scale;
          rs[i] = std::isfinite(r2) ? r2 : 0.0f;
        }
      }
    } else {
      float y[kCodecMaxBlock];
      uint16_t h16[kCodecMaxBlock];
      for (uint64_t i = 0; i < m; ++i) {
        float v = eff[i] * inv;
        if (v > 448.0f) {
          v = 448.0f;  // +/-Inf saturates; NaN falls through (np.clip)
        } else if (v < -448.0f) {
          v = -448.0f;
        }
        y[i] = v;
      }
      F32ToF16Span(y, h16, m);
      if (rs) {
        for (uint64_t i = 0; i < m; ++i) {
          const uint8_t c = g_fp8_enc_lut[h16[i]];
          cs[i] = c;
          const float r2 = eff[i] - g_fp8_dec_lut[c] * scale;
          rs[i] = std::isfinite(r2) ? r2 : 0.0f;
        }
      } else {
        for (uint64_t i = 0; i < m; ++i) cs[i] = g_fp8_enc_lut[h16[i]];
      }
    }
  }
  return flags;
}

// Decode one block-aligned span (inverse of psl_codec_encode; the
// int8 NaN sentinel is honored only when the encode flagged it, like
// the numpy decode).  Returns -1 -> caller falls back to numpy.
int psl_codec_decode(int kind, const uint8_t* codes, const float* scales,
                     uint64_t n, uint64_t block, int flags, float* out) {
  if (block == 0 || block > kCodecMaxBlock) return -1;
  if (kind != 0 && kind != 1) return -1;
  if (kind == 1 && !g_fp8_tables_ready.load(std::memory_order_acquire))
    return -1;
  for (uint64_t b0 = 0; b0 < n; b0 += block) {
    const uint64_t m = (n - b0 < block) ? (n - b0) : block;
    const float scale = scales[b0 / block];
    const uint8_t* cs = codes + b0;
    float* os = out + b0;
    if (kind == 0) {
      if (flags & 1) {
        for (uint64_t i = 0; i < m; ++i) {
          const int8_t c = static_cast<int8_t>(cs[i]);
          os[i] = (c == -128) ? NAN : static_cast<float>(c) * scale;
        }
      } else {
        for (uint64_t i = 0; i < m; ++i) {
          os[i] = static_cast<float>(static_cast<int8_t>(cs[i])) * scale;
        }
      }
    } else {
      for (uint64_t i = 0; i < m; ++i) {
        os[i] = g_fp8_dec_lut[cs[i]] * scale;
      }
    }
  }
  return 0;
}

// Whole-payload variants: ONE call from Python (one GIL release), the
// block-aligned span fan-out runs on the persistent CodecSpanPool —
// span boundaries never straddle a scale block, so the output is
// bit-identical to the single-threaded call for every thread count.
int psl_codec_encode_mt(int kind, const float* x, float* resid, uint64_t n,
                        uint64_t block, uint8_t* codes, float* scales,
                        int nthreads) {
  if (block == 0 || block > kCodecMaxBlock) return -1;
  if (kind != 0 && kind != 1) return -1;
  if (kind == 1 && !g_fp8_tables_ready.load(std::memory_order_acquire))
    return -1;
  std::atomic<int> flags{0};
  CodecSpanPool::Get().Run(n, block, nthreads,
                           [&](uint64_t a, uint64_t b) {
    const int f =
        psl_codec_encode(kind, x + a, resid ? resid + a : nullptr, b - a,
                         block, codes + a, scales + a / block);
    if (f > 0) flags.fetch_or(f, std::memory_order_relaxed);
  });
  return flags.load();
}

// Decode arbitrary element ranges of a payload (scales indexed by
// GLOBAL element position, so ranges need not align to scale blocks):
// the server's apply shards decode only their own keys' segments, in
// parallel on the shard threads, instead of serializing one whole-
// payload decode on the receive pump.  Output is written back to back
// in range order; values are bit-identical to the full decode.
int psl_codec_decode_ranges(int kind, const uint8_t* codes,
                            const float* scales, const uint64_t* starts,
                            const uint64_t* ends, int nranges,
                            uint64_t block, int flags, float* out) {
  if (block == 0) return -1;
  if (kind != 0 && kind != 1) return -1;
  if (kind == 1 && !g_fp8_tables_ready.load(std::memory_order_acquire))
    return -1;
  uint64_t off = 0;
  for (int r = 0; r < nranges; ++r) {
    uint64_t j = starts[r];
    const uint64_t e = ends[r];
    while (j < e) {
      // One scale block at a time: hoists the j/block divide out of
      // the element loop.
      const uint64_t bend = std::min(e, (j / block + 1) * block);
      const float scale = scales[j / block];
      if (kind == 0) {
        if (flags & 1) {
          for (; j < bend; ++j, ++off) {
            const int8_t c = static_cast<int8_t>(codes[j]);
            out[off] = (c == -128) ? NAN : static_cast<float>(c) * scale;
          }
        } else {
          for (; j < bend; ++j, ++off)
            out[off] = static_cast<float>(static_cast<int8_t>(codes[j]))
                       * scale;
        }
      } else {
        for (; j < bend; ++j, ++off) out[off] = g_fp8_dec_lut[codes[j]] * scale;
      }
    }
  }
  return 0;
}

int psl_codec_decode_mt(int kind, const uint8_t* codes, const float* scales,
                        uint64_t n, uint64_t block, int flags, float* out,
                        int nthreads) {
  if (block == 0 || block > kCodecMaxBlock) return -1;
  if (kind != 0 && kind != 1) return -1;
  if (kind == 1 && !g_fp8_tables_ready.load(std::memory_order_acquire))
    return -1;
  CodecSpanPool::Get().Run(n, block, nthreads,
                           [&](uint64_t a, uint64_t b) {
    psl_codec_decode(kind, codes + a, scales + a / block, b - a, block,
                     flags, out + a);
  });
  return 0;
}

void psl_iadd_f64(double* dst, const double* src, uint64_t n) {
  for (uint64_t i = 0; i < n; ++i) dst[i] += src[i];
}

void* psl_copy_pool_create(int n_threads) { return new CopyPool(n_threads); }

void psl_copy_pool_copy(void* p, void* dst, const void* src, uint64_t n) {
  static_cast<CopyPool*>(p)->Copy(static_cast<uint8_t*>(dst),
                                  static_cast<const uint8_t*>(src), n);
}

void psl_copy_pool_destroy(void* p) { delete static_cast<CopyPool*>(p); }

void psl_stop(void* h) { static_cast<Core*>(h)->Stop(); }

void psl_destroy(void* h) { delete static_cast<Core*>(h); }

}  // extern "C"
