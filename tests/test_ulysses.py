"""Ulysses (all-to-all) sequence parallelism vs the single-device
reference, and agreement with ring attention, on the 8-shard CPU mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from pslite_tpu.parallel.mesh import default_mesh, shard_map_compat
from pslite_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention,
)
from pslite_tpu.parallel.ulysses import ulysses_attention


def _inputs(S, H):
    B, T, D = 2, 4 * S, 16
    rng = np.random.default_rng(1)
    q = rng.normal(size=(B, T, H, D)).astype(np.float32)
    k = rng.normal(size=(B, T, H, D)).astype(np.float32)
    v = rng.normal(size=(B, T, H, D)).astype(np.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(causal):
    mesh = default_mesh(axis_name="sp")
    S = mesh.shape["sp"]
    H = 2 * S  # heads divisible by the axis (Ulysses requirement)
    q, k, v = _inputs(S, H)

    ref = np.asarray(
        reference_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                            causal=causal)
    )  # [B, T, H, D]

    fn = shard_map_compat(
        lambda a, b, c: ulysses_attention(a, b, c, "sp", causal=causal),
        mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    out = np.asarray(jax.jit(fn)(q, k, v))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_ulysses_agrees_with_ring():
    """The two sequence-parallel strategies are drop-in interchangeable:
    same sharded layout, same output."""
    mesh = default_mesh(axis_name="sp")
    S = mesh.shape["sp"]
    H = S
    q, k, v = _inputs(S, H)

    def run(attn):
        fn = shard_map_compat(
            lambda a, b, c: attn(a, b, c, "sp", causal=True),
            mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
        )
        return np.asarray(jax.jit(fn)(q, k, v))

    np.testing.assert_allclose(
        run(ulysses_attention), run(ring_attention), rtol=2e-4, atol=2e-5
    )
