"""Dead-reader detection on the shm ring pipes (PS_SHM_RING).

A writer whose pipe reader has died — or never attached, e.g. an env
mismatch where only the sender enabled PS_SHM_RING — must not wedge
forever once the ring fills.  The writer probes the reader-liveness
heartbeat in the pipe header (cpp/pslite_core.cc PipeHdr::reader_beat)
during ring-full waits, retires the pipe, and falls back to the socket
connection; this mirrors ReclaimIfDead on the read side.
"""

import os
import time

import numpy as np
import pytest

from pslite_tpu.vans import native


RING_BYTES = 1 << 16  # tiny ring so a few frames fill it
FRAME = 8192


@pytest.fixture
def dead_ms_env():
    # 700 requested, but the native core floors the threshold at 1000 ms
    # (values at/below the reader's beat staleness bound would falsely
    # retire live pipes) — the test still completes in ~1.2 s.
    os.environ["PS_SHM_RING_DEAD_MS"] = "700"
    yield
    os.environ.pop("PS_SHM_RING_DEAD_MS", None)


def test_dead_reader_falls_back_to_socket(dead_ms_env):
    if native.load() is None:
        pytest.skip("native core not built")
    path = f"/dev/shm/pslpipe_deadtest_{os.getpid()}"
    writer = native.NativeTransport()
    reader = native.NativeTransport()
    try:
        port = reader.bind(0)
        writer.connect(7, "127.0.0.1", port, timeout_ms=10000)
        writer.pipe_connect(7, path, RING_BYTES)
        assert os.path.exists(path)

        # NO pipe_watch on the reader: frames stream into a ring nobody
        # drains.  The early sends commit into the ring and "succeed";
        # once it fills, the writer must detect the silent reader within
        # ~PS_SHM_RING_DEAD_MS and reroute to the socket.
        payload = np.arange(FRAME // 8, dtype=np.float64)
        t0 = time.monotonic()
        for i in range(12):
            meta = f"frame-{i}".encode()
            writer.send(7, meta, [memoryview(payload.tobytes())])
        elapsed = time.monotonic() - t0
        # One dead-reader wait (~0.8-1s), not one per frame.
        assert elapsed < 10, f"sends took {elapsed:.1f}s (wedged per frame?)"

        # The pipe was retired: name unlinked so a redial gets a fresh
        # inode.
        assert not os.path.exists(path)

        # Post-fallback frames arrive over the socket.  Frames parked in
        # the abandoned ring are lost by design (PS_RESEND heals them in
        # a real cluster); the LAST frame was sent after the fallback and
        # must arrive.
        metas = []
        while True:
            try:
                got = reader.recv(timeout_ms=5000)
            except TimeoutError:
                break
            if got is None:
                break
            metas.append(got[0])
            if got[0] == b"frame-11":
                break
        assert b"frame-11" in metas, f"got {metas!r}"
        # Payload integrity across the fallback path (recv returns
        # zero-copy ndarray views over the pooled frame).
        assert bytes(got[1][0]) == payload.tobytes()
    finally:
        for core in (writer, reader):
            core.stop()
            core.destroy()  # joins the io/pipe threads (TSAN-clean exit)
        for leftover in (path, path + ".lock"):
            try:
                os.unlink(leftover)
            except OSError:
                pass
