"""Priority send scheduling within the per-peer send lanes.

Higher-priority pushes queued behind a busy link must overtake lower
ones (the BytePS communication-scheduling idea; the reference sends
strictly FIFO).  The link is made "busy" by gating the transport's
send_msg on an event while more pushes enqueue behind it.  Priority
ordering is a PER-LANE property: each destination's lane drains its own
queue highest-priority-first while lanes to other peers run
concurrently (PS_PRIORITY_SCHED remains accepted but lanes honor
priority unconditionally now).
"""

import collections
import threading
import time

import numpy as np

from pslite_tpu import KVServer, KVServerDefaultHandle, KVWorker

from helpers import LoopbackCluster


def _cluster():
    # PS_SEND_LANES pinned on: these tests gate the transport and rely
    # on an async lane thread carrying the send — the PS_TEST_SYNC_SEND
    # matrix (helpers.py forces lanes off) must not apply here.
    c = LoopbackCluster(num_workers=1, num_servers=1,
                        env_extra={"PS_PRIORITY_SCHED": "1",
                                   "PS_SEND_LANES": "1"})
    c.start()
    return c


def test_priority_overtakes_fifo():
    cluster = _cluster()
    try:
        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        kv = KVWorker(0, 0, postoffice=cluster.workers[0])

        van = cluster.workers[0].van
        orig = van.send_msg
        order = []
        first_in = threading.Event()
        gate = threading.Event()

        def gated(msg):
            if msg.meta.control.empty() and msg.meta.push:
                order.append(msg.meta.key)
                if len(order) == 1:
                    first_in.set()
                    assert gate.wait(timeout=30), "gate never released"
            return orig(msg)

        van.send_msg = gated
        try:
            ones = np.ones(8, np.float32)
            ts = [kv.push(np.array([1], np.uint64), ones, priority=0)]
            # First push is in send_msg, blocked on the gate; the rest
            # pile up in the heap with distinct priorities.
            assert first_in.wait(timeout=30)
            ts.append(kv.push(np.array([2], np.uint64), ones, priority=1))
            ts.append(kv.push(np.array([3], np.uint64), ones, priority=9))
            ts.append(kv.push(np.array([4], np.uint64), ones, priority=5))
            gate.set()
            for t in ts:
                kv.wait(t)
        finally:
            van.send_msg = orig
        # Dispatch order: FIFO head first (already in flight), then by
        # descending priority.
        assert order == [1, 3, 4, 2], order

        # Semantics unchanged: every push landed exactly once.
        for key in (1, 2, 3, 4):
            out = np.zeros(8, np.float32)
            kv.wait(kv.pull(np.array([key], np.uint64), out))
            np.testing.assert_allclose(out, 1.0)
        srv.stop()
    finally:
        cluster.finalize()


def test_priority_order_within_lane_while_peers_concurrent():
    """Priority is a per-lane property: with 3 servers receiving
    concurrently (proved by a barrier INSIDE the transport — all three
    lane threads must be in send_msg at once, impossible under a
    van-wide send lock), each lane still drains its queued pushes in
    descending priority order.  Lanes pinned on: the in-transport
    barrier deadlocks under the PS_TEST_SYNC_SEND (lanes-off) matrix."""
    cluster = LoopbackCluster(num_workers=1, num_servers=3,
                              env_extra={"PS_SEND_LANES": "1"})
    cluster.start()
    servers = []
    try:
        for po in cluster.servers:
            srv = KVServer(0, postoffice=po)
            srv.set_request_handle(KVServerDefaultHandle())
            servers.append(srv)
        kv = KVWorker(0, 0, postoffice=cluster.workers[0])
        van = cluster.workers[0].van
        orig = van.send_msg
        # All 3 lanes must reach the transport concurrently before any
        # may proceed; they then block on the gate while more pushes
        # (with distinct priorities) pile up in each lane's queue.
        rendezvous = threading.Barrier(3, timeout=30)
        gate = threading.Event()
        order = collections.defaultdict(list)
        first = set()
        mu = threading.Lock()

        def gated(msg):
            if msg.meta.control.empty() and msg.meta.push:
                recver = msg.meta.recver
                with mu:
                    order[recver].append(msg.meta.priority)
                    head = recver not in first
                    first.add(recver)
                if head:
                    rendezvous.wait()  # ≥3 peers in-flight at once
                    assert gate.wait(timeout=30), "gate never released"
            return orig(msg)

        van.send_msg = gated
        try:
            ranges = cluster.workers[0].get_server_key_ranges()
            # Keys spanning every range: each push lands one slice per
            # server, so each lane sees the same priority sequence.
            keys = np.array(sorted(r.begin + 1 for r in ranges),
                            dtype=np.uint64)
            vals = np.ones(len(keys) * 4, np.float32)
            tss = [kv.push(keys, vals, priority=0)]  # heads block
            # All three heads must be IN the transport before more
            # pushes queue: a lazily-spawned lane thread that starts
            # late (loaded host) would otherwise find {0,2,9,5} queued
            # and correctly drain the priority-0 head LAST.
            deadline = time.monotonic() + 30
            while True:
                with mu:
                    if len(first) == 3:
                        break
                assert time.monotonic() < deadline, "heads never sent"
                time.sleep(0.001)
            for prio in (2, 9, 5):
                tss.append(kv.push(keys, vals, priority=prio))
            gate.set()
            for ts in tss:
                kv.wait(ts)
        finally:
            van.send_msg = orig
        server_ids = {po.van.my_node.id for po in cluster.servers}
        assert set(order) == server_ids
        for recver, prios in order.items():
            # Head first (already in flight), then descending priority.
            assert prios == [0, 9, 5, 2], (recver, prios)
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_priority_sched_end_to_end():
    """A normal mixed-priority workload completes with correct values
    and a clean shutdown (the stop() drain path)."""
    cluster = _cluster()
    try:
        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        kv = KVWorker(0, 0, postoffice=cluster.workers[0])
        keys = np.arange(6, dtype=np.uint64)
        vals = np.arange(6 * 4, dtype=np.float32)
        for rounds in range(3):
            kv.wait(kv.push(keys, vals, priority=rounds % 3))

        # The bulk bytes of a pull travel in the RESPONSE: the server
        # must echo the request's priority so scheduling applies where
        # the payload is (wire-carried, not sender-local).
        seen = []
        server_van = cluster.servers[0].van
        orig = server_van.send_msg

        def spy(msg):
            if msg.meta.control.empty() and msg.meta.pull:
                seen.append(msg.meta.priority)
            return orig(msg)

        server_van.send_msg = spy
        try:
            out = np.zeros_like(vals)
            kv.wait(kv.pull(keys, out, priority=7))
        finally:
            server_van.send_msg = orig
        np.testing.assert_allclose(out, vals * 3)
        assert seen == [7], seen
        srv.stop()
    finally:
        cluster.finalize()
