// pslite_core — native transport core for pslite_tpu.
//
// TPU-native counterpart of the reference's C++ Van layer hot path
// (src/zmq_van.h + src/van.cc framing): an epoll-driven TCP transport that
// frames messages with the shared wire format
//
//   u32 magic | u32 meta_len | u32 n_data | u64 data_len[n_data] | meta | data…
//
// (see pslite_tpu/wire.py — the Python and C++ sides interoperate on the
// byte level).  Socket IO, frame assembly, and the receive queue run on
// native threads with no GIL involvement; Python drives it through the
// C API below via ctypes.
//
// Build: make -C cpp   ->  cpp/libpslite_core.so

#include <arpa/inet.h>
#include <dirent.h>
#include <fcntl.h>
#include <netdb.h>
#include <poll.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/file.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <sys/un.h>
#include <unistd.h>

#include <array>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x50535450;  // "PSTP", wire.py MAGIC
constexpr size_t kHeaderSize = 12;       // magic + meta_len + n_data

struct Frame {
  uint8_t* buf = nullptr;  // lens + meta + data, one allocation
  uint32_t meta_len = 0;
  uint32_t n_data = 0;
  // Offsets into buf:
  //   [0, 8*n_data)                 data lens
  //   [8*n_data, 8*n_data+meta_len) meta
  //   then data segments back to back
};

// Fixed offsets inside the python wire format's meta block (wire.py
// _META_FIXED, little-endian, no padding): enough to peek a frame's
// send priority and control command for the express receive lane
// without decoding the meta.  Keep in sync with wire.py.
constexpr size_t kMetaPriorityOff = 70;  // i32
constexpr size_t kMetaControlCmdOff = 84;  // u8; 0 == EMPTY (data plane)
constexpr size_t kMetaFixedSize = 105;

// True when this frame rides the express receive lane, mirroring the
// pure-Python PriorityRecvQueue discipline (utils/queues.py,
// docs/chunking.md): control frames (ACKs, heartbeats, barriers) ride
// above EVERY data level so a bulk chunk backlog can never starve the
// control plane, and priority>0 data bypasses the backlog too.
// TERMINATE stays in the ordinary queue — it must drain BEHIND queued
// traffic, or the receive loop would retire with frames undelivered.
static bool FrameIsExpress(const Frame& f) {
  if (f.meta_len < kMetaFixedSize) return false;
  const uint8_t* meta = f.buf + 8ull * f.n_data;
  uint8_t cmd = meta[kMetaControlCmdOff];
  if (cmd != 0) return cmd != 1;  // 1 == TERMINATE (message.py Command)
  int32_t prio;
  memcpy(&prio, meta + kMetaPriorityOff, sizeof(prio));
  return prio > 0;
}

// Cross-process SPSC byte pipe over a /dev/shm mapping — the reference's
// vendored in-process lock-free SPSC ring (spsc_queue.h) extended across
// processes for same-host meta traffic.  Stream semantics: the writer
// copies frame bytes in as space allows, the reader pumps them through
// the same reassembly state machine as a TCP stream, so a pipe is a
// drop-in replacement for the socket between two co-located nodes.
struct PipeHdr {
  uint32_t magic;  // kPipeMagic
  uint32_t pad;
  uint64_t size;  // data-region bytes
  alignas(64) std::atomic<uint64_t> head;  // consumed; reader-owned
  alignas(64) std::atomic<uint64_t> tail;  // produced; writer-owned
  // Reader-liveness heartbeat: CLOCK_MONOTONIC ms, stamped by the reader
  // at attach and on every liveness tick.  Comparable across processes
  // (same host by construction).  0 = no reader has ever attached.  The
  // writer probes it on ring-full waits: a full ring whose reader is not
  // beating means frames are streaming into the void (reader died,
  // desynced+blacklisted, or never enabled PS_SHM_RING) — the writer
  // retires the pipe and falls back to the socket instead of blocking
  // forever once the ring fills.
  alignas(64) std::atomic<uint64_t> reader_beat;
};

// "PSRC" — bumped from "PSRB" when reader_beat joined the header: an
// old-binary reader would otherwise attach cleanly, drain frames, and
// never heartbeat, which a new writer reads as "no reader" and falsely
// retires the pipe.  Mixed versions now refuse to pair instead.
constexpr uint32_t kPipeMagic = 0x50535243;
constexpr size_t kPipeDataOff = 4096;        // header page

struct WritePipe {
  PipeHdr* hdr = nullptr;
  uint8_t* data = nullptr;
  int fd = -1;  // holds LOCK_SH for writer-liveness
  size_t map_len = 0;
  std::string path;
  std::mutex mu;  // in-process senders serialize whole frames
  // Set once the writer declares the reader dead (see PipeHdr::
  // reader_beat); senders bail with -EPIPE and the van falls back to
  // the socket.  The mapping stays alive in a graveyard until shutdown
  // so concurrently-blocked senders never touch freed memory.
  std::atomic<bool> dead{false};
};

// Per-connection frame reassembly state machine.
struct Conn {
  int fd = -1;
  // Stage 0: header; stage 1: body (lens+meta+data).
  int stage = 0;
  size_t want = kHeaderSize;
  size_t got = 0;
  uint8_t header[kHeaderSize];
  Frame frame;
  size_t body_size = 0;

  ~Conn() { free(frame.buf); }
};

struct ReadPipe {
  PipeHdr* hdr = nullptr;
  const uint8_t* data = nullptr;
  int fd = -1;
  size_t map_len = 0;
  std::string path;
  Conn conn;  // reassembly state for this byte stream
};

class Core {
 public:
  Core() : epfd_(epoll_create1(0)) {}

  ~Core() { StopAndJoin(); }

  int Bind(int port, int backlog) {
    // Non-blocking listener: AcceptAll drains until EAGAIN and must not
    // wedge the io thread.
    int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) return -errno;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = INADDR_ANY;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
      int err = -errno;
      close(fd);
      return err;
    }
    if (listen(fd, backlog) < 0) {
      int err = -errno;
      close(fd);
      return err;
    }
    socklen_t len = sizeof(addr);
    getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
    listen_fd_ = fd;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = fd;
    epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev);
    io_thread_ = std::thread([this] { IoLoop(); });
    return ntohs(addr.sin_port);
  }

  // DMLC_LOCAL mode: listen on a unix-domain socket instead of TCP
  // (the zmq van's ipc:///tmp/<port> switch, zmq_van.h:107-115).  The
  // caller owns port-number retry; this binds exactly `path`.
  int BindLocal(const char* path, int backlog) {
    sockaddr_un addr{};
    if (strlen(path) >= sizeof(addr.sun_path)) return -ENAMETOOLONG;
    int fd = socket(AF_UNIX, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) return -errno;
    addr.sun_family = AF_UNIX;
    strncpy(addr.sun_path, path, sizeof(addr.sun_path) - 1);
    if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
      int err = -errno;
      close(fd);
      return err;
    }
    if (listen(fd, backlog) < 0) {
      int err = -errno;
      close(fd);
      unlink(path);
      return err;
    }
    bound_path_ = path;
    listen_fd_ = fd;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = fd;
    epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev);
    io_thread_ = std::thread([this] { IoLoop(); });
    return 0;
  }

  int ConnectLocal(int node_id, const char* path, int timeout_ms) {
    sockaddr_un addr{};
    if (strlen(path) >= sizeof(addr.sun_path)) return -ENAMETOOLONG;
    int fd = socket(AF_UNIX, SOCK_STREAM, 0);
    if (fd < 0) return -errno;
    addr.sun_family = AF_UNIX;
    strncpy(addr.sun_path, path, sizeof(addr.sun_path) - 1);
    // Bounded connect, same invariant as the TCP path: a listener with a
    // wedged accept loop and full backlog must not stall forever.
    int flags = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, flags | O_NONBLOCK);
    int rc = connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
    if (rc < 0 && errno == EAGAIN) {
      // AF_UNIX semantics (unix(7)): EAGAIN means the listener's backlog
      // is full and NO connection is in progress — polling would report
      // the unconnected fd writable and fake a success.  Fail now; the
      // caller's retry loop redials.
      close(fd);
      return -EAGAIN;
    }
    if (rc < 0 && errno == EINPROGRESS) {
      pollfd pfd{fd, POLLOUT, 0};
      rc = poll(&pfd, 1, timeout_ms);
      if (rc <= 0) {
        close(fd);
        return rc == 0 ? -ETIMEDOUT : -errno;
      }
      int err = 0;
      socklen_t len = sizeof(err);
      getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
      if (err != 0) {
        close(fd);
        return -err;
      }
    } else if (rc < 0) {
      int err = -errno;
      close(fd);
      return err;
    }
    fcntl(fd, F_SETFL, flags);
    std::lock_guard<std::mutex> lk(send_mu_);
    auto it = send_fds_.find(node_id);
    if (it != send_fds_.end()) close(it->second);
    send_fds_[node_id] = fd;
    return 0;
  }

  // -- shm byte pipes (PS_SHM_RING) ---------------------------------------

  // Writer side: create the pipe for (me -> node_id).  Serialized against
  // same-host racers/stale files by an flock on a sibling .lock file; the
  // pipe fd then holds LOCK_SH for the writer's lifetime so readers can
  // probe liveness with LOCK_EX|LOCK_NB.
  int PipeConnect(int node_id, const char* path, uint64_t data_bytes) {
    {
      std::lock_guard<std::mutex> lk(send_mu_);
      auto it = pipes_by_path_.find(path);
      if (it != pipes_by_path_.end()) {
        pipes_[node_id] = it->second;  // re-connect of the same pair
        return 0;
      }
    }
    std::string lockp = std::string(path) + ".lock";
    int lock_fd = open(lockp.c_str(), O_CREAT | O_RDWR, 0600);
    if (lock_fd < 0) return -errno;
    flock(lock_fd, LOCK_EX);
    int rc = PipeCreateLocked(node_id, path, data_bytes);
    flock(lock_fd, LOCK_UN);
    close(lock_fd);
    return rc;
  }

  int PipeCreateLocked(int node_id, const char* path, uint64_t data_bytes) {
    // Reclaim a stale file (writer died): nobody holds LOCK_SH on it.
    int old_fd = open(path, O_RDWR);
    if (old_fd >= 0) {
      if (flock(old_fd, LOCK_EX | LOCK_NB) == 0) {
        unlink(path);
        close(old_fd);
      } else {
        close(old_fd);
        return -EEXIST;  // a live writer owns this name
      }
    }
    int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
    if (fd < 0) return -errno;
    size_t map_len = kPipeDataOff + data_bytes;
    if (ftruncate(fd, static_cast<off_t>(map_len)) < 0) {
      int err = -errno;
      close(fd);
      unlink(path);
      return err;
    }
    void* mem =
        mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    if (mem == MAP_FAILED) {
      int err = -errno;
      close(fd);
      unlink(path);
      return err;
    }
    auto* hdr = new (mem) PipeHdr();
    hdr->size = data_bytes;
    hdr->head.store(0);
    hdr->tail.store(0);
    hdr->magic = kPipeMagic;  // last: readers gate on it
    flock(fd, LOCK_SH);       // writer-liveness token
    auto* p = new WritePipe();
    p->hdr = hdr;
    p->data = static_cast<uint8_t*>(mem) + kPipeDataOff;
    p->fd = fd;
    p->map_len = map_len;
    p->path = path;
    std::lock_guard<std::mutex> lk(send_mu_);
    pipes_[node_id] = p;
    pipes_by_path_[p->path] = p;
    return 0;
  }

  // Take a dead-reader pipe out of service: unroute it (no new senders),
  // release the writer-liveness flock and unlink the name so a redial
  // creates a FRESH pipe (fresh inode — the reader's inode blacklist
  // won't match it), and park the mapping in a graveyard freed at
  // shutdown (a concurrently-blocked sender may still be reading
  // p->hdr; it will see p->dead and bail).  Idempotent under races:
  // only the first retirer acts.
  void RetirePipe(WritePipe* p) {
    bool first = false;
    {
      std::lock_guard<std::mutex> lk(send_mu_);
      // Pointer identity, not path presence: a redial may have already
      // recreated the SAME path as a fresh pipe — erasing by path alone
      // would unroute the new generation and double-park p.
      auto it = pipes_by_path_.find(p->path);
      if (it != pipes_by_path_.end() && it->second == p) {
        pipes_by_path_.erase(it);
        first = true;
      }
      for (auto pit = pipes_.begin(); pit != pipes_.end();) {
        if (pit->second == p) {
          pit = pipes_.erase(pit);
        } else {
          ++pit;
        }
      }
      if (first) dead_write_pipes_.push_back(p);
    }
    if (first) {
      p->dead.store(true, std::memory_order_relaxed);
      close(p->fd);  // releases the writer-liveness LOCK_SH
      p->fd = -1;
      unlink(p->path.c_str());
      fprintf(stderr,
              "[pslite_core] W shm pipe %s: reader dead or never drained; "
              "falling back to the socket\n",
              p->path.c_str());
    }
  }

  // Reader side: watch a directory for pipes named <prefix>*<suffix>
  // (ours are pslpipe_<ns>_<senderport>_<myport>); the poller attaches
  // them as they appear.  Discovery by scan — no announce handshake —
  // because a booting node sends ADD_NODE before the scheduler ever
  // learns its identity (van.cc:566-577 bootstrap ordering).
  int PipeWatch(const char* dir, const char* prefix, const char* suffix,
                int idle_cap_us) {
    std::lock_guard<std::mutex> lk(pipe_mu_);
    watches_.push_back({dir, prefix, suffix});
    if (idle_cap_us > 0) pipe_idle_cap_us_ = idle_cap_us;
    if (!pipe_thread_.joinable()) {
      pipe_thread_ = std::thread([this] { PipeLoop(); });
    }
    return 0;
  }

  long long PipeSendFrame(WritePipe* p, const uint8_t* meta,
                          uint32_t meta_len, uint32_t n_data,
                          const uint8_t* const* data, const uint64_t* lens) {
    uint8_t header[kHeaderSize];
    memcpy(header, &kMagic, 4);
    memcpy(header + 4, &meta_len, 4);
    memcpy(header + 8, &n_data, 4);
    std::vector<iovec> iov;
    iov.reserve(3 + n_data);
    iov.push_back({header, kHeaderSize});
    iov.push_back({const_cast<uint64_t*>(lens), 8ull * n_data});
    iov.push_back({const_cast<uint8_t*>(meta), meta_len});
    long long total = kHeaderSize + 8ll * n_data + meta_len;
    for (uint32_t i = 0; i < n_data; ++i) {
      iov.push_back({const_cast<uint8_t*>(data[i]),
                     static_cast<size_t>(lens[i])});
      total += static_cast<long long>(lens[i]);
    }
    // Whole frames are written under the pipe mutex: in-process sender
    // threads must not interleave bytes mid-frame.
    std::lock_guard<std::mutex> lk(p->mu);
    int rc = PipeWriteVec(p, iov.data(), iov.size());
    return rc < 0 ? rc : total;
  }

  // Stream the iovecs into the ring.  Frame atomicity rule: the timeout
  // applies only BEFORE the first byte is committed — once any byte is
  // published, aborting would leave a truncated frame and desync the
  // stream forever, so from then on this blocks like a socket sendall,
  // bailing on shutdown or on a DEAD READER: a full ring whose reader
  // has stopped beating (see PipeHdr::reader_beat) will never drain, so
  // blocking "like a socket" would wedge the sender permanently.  A
  // dead-reader bail abandons the pipe entirely (-EPIPE; Send() retires
  // it and falls back to the socket), so the truncated frame is
  // discarded along with the ring, never parsed.
  uint64_t ReaderDeadMs() {
    if (reader_dead_ms_ == 0) {
      const char* e = getenv("PS_SHM_RING_DEAD_MS");
      long v = e ? atol(e) : 0;
      uint64_t ms = v > 0 ? static_cast<uint64_t>(v) : 5000;
      // Floor well above the reader's beat staleness bound (one
      // PipeLoop iteration ≈ the idle cap, sub-ms by default): a
      // threshold at or below the beat cadence would falsely retire
      // live pipes and silently drop their parked frames.
      reader_dead_ms_ = ms < 1000 ? 1000 : ms;
    }
    return reader_dead_ms_;
  }

  int PipeWriteVec(WritePipe* p, const iovec* iov, size_t cnt) {
    if (p->dead.load(std::memory_order_relaxed)) return -EPIPE;
    uint64_t tail = p->hdr->tail.load(std::memory_order_relaxed);
    const uint64_t size = p->hdr->size;
    uint64_t slept_us = 0;
    uint64_t full_since_ms = 0;
    int spins = 0;
    bool committed = false;
    for (size_t i = 0; i < cnt; ++i) {
      const uint8_t* src = static_cast<const uint8_t*>(iov[i].iov_base);
      uint64_t len = iov[i].iov_len;
      while (len) {
        uint64_t head = p->hdr->head.load(std::memory_order_acquire);
        uint64_t space = size - (tail - head);
        if (space == 0) {
          // Reader stalled (or not yet attached): stream semantics mean
          // we must wait, not reroute — rerouting would reorder.
          if (stopped_) return -ECANCELED;
          if (p->dead.load(std::memory_order_relaxed)) return -EPIPE;
          if (++spins < 128) continue;
          timespec ts{0, 50 * 1000};
          nanosleep(&ts, nullptr);
          slept_us += 50;
          if (!committed && slept_us > 60ull * 1000 * 1000) {
            return -ETIMEDOUT;
          }
          // Reader-liveness probe (~every 100ms of full-ring waiting).
          // Inside this wait `head` is by definition frozen (any
          // advance makes space > 0 and exits), so liveness reduces to
          // the reader's heartbeat being recent.  The reader beats
          // every ~1s while attached; 5s of silence on a full ring
          // means dead, desynced-and-blacklisted, or never attached.
          if (slept_us % (100 * 1000) == 0) {
            uint64_t now = NowMs();
            if (full_since_ms == 0) full_since_ms = now;
            uint64_t beat =
                p->hdr->reader_beat.load(std::memory_order_relaxed);
            uint64_t ref = beat > full_since_ms ? beat : full_since_ms;
            // now > ref guard: a beat stamped between our NowMs() and
            // the load can make ref exceed now — unsigned subtraction
            // would underflow and falsely retire a healthy pipe.
            if (now > ref && now - ref > ReaderDeadMs()) {
              p->dead.store(true, std::memory_order_relaxed);
              return -EPIPE;
            }
          }
          continue;
        }
        spins = 0;
        uint64_t pos = tail % size;
        uint64_t n = space < len ? space : len;
        if (n > size - pos) n = size - pos;  // contiguous run
        memcpy(p->data + pos, src, n);
        tail += n;
        src += n;
        len -= n;
        p->hdr->tail.store(tail, std::memory_order_release);
        committed = true;
      }
    }
    return 0;
  }

  int Connect(int node_id, const char* host, int port, int timeout_ms) {
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    std::string port_s = std::to_string(port);
    if (getaddrinfo(host, port_s.c_str(), &hints, &res) != 0 || !res) {
      return -EHOSTUNREACH;
    }
    int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd < 0) {
      freeaddrinfo(res);
      return -errno;
    }
    // Bounded connect: a black-holed peer must not stall the caller for
    // the kernel's full SYN-retry period.
    int flags = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, flags | O_NONBLOCK);
    int rc = connect(fd, res->ai_addr, res->ai_addrlen);
    freeaddrinfo(res);
    if (rc < 0 && errno == EINPROGRESS) {
      pollfd pfd{fd, POLLOUT, 0};
      rc = poll(&pfd, 1, timeout_ms);
      if (rc <= 0) {
        close(fd);
        return rc == 0 ? -ETIMEDOUT : -errno;
      }
      int err = 0;
      socklen_t len = sizeof(err);
      getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
      if (err != 0) {
        close(fd);
        return -err;
      }
    } else if (rc < 0) {
      int err = -errno;
      close(fd);
      return err;
    }
    fcntl(fd, F_SETFL, flags);
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::lock_guard<std::mutex> lk(send_mu_);
    auto it = send_fds_.find(node_id);
    if (it != send_fds_.end()) close(it->second);
    send_fds_[node_id] = fd;
    return 0;
  }

  long long Send(int node_id, const uint8_t* meta, uint32_t meta_len,
                 uint32_t n_data, const uint8_t* const* data,
                 const uint64_t* lens) {
    // Gate against teardown: StopAndJoin must not free pipes while a
    // sender is mid-copy into the mapping.
    struct InflightGuard {
      std::atomic<int>* n;
      explicit InflightGuard(std::atomic<int>* c) : n(c) { ++*n; }
      ~InflightGuard() { --*n; }
    } guard(&inflight_sends_);
    if (stopped_) return -ECANCELED;
    WritePipe* pipe = nullptr;
    int fd = -1;
    {
      std::lock_guard<std::mutex> lk(send_mu_);
      auto pit = pipes_.find(node_id);
      if (pit != pipes_.end()) {
        pipe = pit->second;
      } else {
        auto it = send_fds_.find(node_id);
        if (it == send_fds_.end()) return -ENOTCONN;
        fd = it->second;
      }
    }
    // A connected pipe carries the WHOLE stream for this peer (mixing
    // pipe and socket frames would lose ordering).
    if (pipe != nullptr) {
      long long rc = PipeSendFrame(pipe, meta, meta_len, n_data, data, lens);
      if (rc != -EPIPE) return rc;
      // Reader declared dead (see PipeWriteVec): retire the pipe and
      // fall back to the socket connection, which connect_transport
      // established before the pipe took over routing.  Frames already
      // committed to the abandoned ring are lost (the resender heals
      // them under PS_RESEND) — the reference behaves the same when a
      // transport dies mid-stream.
      RetirePipe(pipe);
      std::lock_guard<std::mutex> lk(send_mu_);
      auto it = send_fds_.find(node_id);
      if (it == send_fds_.end()) return -EPIPE;
      fd = it->second;
    }
    uint8_t header[kHeaderSize];
    memcpy(header, &kMagic, 4);
    memcpy(header + 4, &meta_len, 4);
    memcpy(header + 8, &n_data, 4);

    std::vector<iovec> iov;
    iov.reserve(3 + n_data);
    iov.push_back({header, kHeaderSize});
    iov.push_back({const_cast<uint64_t*>(lens), 8ull * n_data});
    iov.push_back({const_cast<uint8_t*>(meta), meta_len});
    long long total = kHeaderSize + 8ull * n_data + meta_len;
    for (uint32_t i = 0; i < n_data; ++i) {
      iov.push_back({const_cast<uint8_t*>(data[i]),
                     static_cast<size_t>(lens[i])});
      total += lens[i];
    }
    // Serialize writers per peer socket (frames must not interleave).
    std::lock_guard<std::mutex> lk(per_fd_send_mu_[fd % kSendLocks]);
    size_t idx = 0;
    size_t off = 0;
    long long sent_total = 0;
    while (idx < iov.size()) {
      iovec cur[64];
      int cnt = 0;
      for (size_t i = idx; i < iov.size() && cnt < 64; ++i, ++cnt) {
        cur[cnt] = iov[i];
        if (i == idx && off) {
          cur[cnt].iov_base = static_cast<uint8_t*>(cur[cnt].iov_base) + off;
          cur[cnt].iov_len -= off;
        }
      }
      ssize_t n = writev(fd, cur, cnt);
      if (n < 0) {
        if (errno == EINTR) continue;
        return -errno;
      }
      sent_total += n;
      size_t left = static_cast<size_t>(n);
      // Consume fully-written entries; zero-length iovecs (empty payload
      // segments, e.g. a pull request's vals) must advance even when no
      // bytes remain, or the loop would respin writev forever.
      while (idx < iov.size()) {
        size_t avail = iov[idx].iov_len - off;
        if (avail <= left) {
          left -= avail;
          ++idx;
          off = 0;
        } else {
          off += left;
          break;
        }
      }
    }
    (void)total;
    return sent_total;
  }

  // Returns 1 with a frame, 0 on timeout, -1 when stopped.  Express
  // frames (priority > 0 data — see FrameIsExpress) pop first so a
  // priority op never waits behind a bulk chunk backlog; each lane is
  // FIFO, matching the Python PriorityRecvQueue discipline.
  int Recv(Frame* out, int timeout_ms) {
    std::unique_lock<std::mutex> lk(queue_mu_);
    auto ready = [this] {
      return stopped_ || !express_.empty() || !queue_.empty();
    };
    if (timeout_ms < 0) {
      queue_cv_.wait(lk, ready);
    } else if (!queue_cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   ready)) {
      return 0;
    }
    std::deque<Frame>* q =
        !express_.empty() ? &express_ : (!queue_.empty() ? &queue_ : nullptr);
    if (q != nullptr) {
      *out = q->front();
      q->pop_front();
      return 1;
    }
    return stopped_ ? -1 : 0;
  }

  void Stop() {
    stopped_ = true;
    if (listen_fd_ >= 0) {
      shutdown(listen_fd_, SHUT_RDWR);
      close(listen_fd_);
      listen_fd_ = -1;
    }
    if (!bound_path_.empty()) {
      unlink(bound_path_.c_str());
      bound_path_.clear();
    }
    queue_cv_.notify_all();
  }

  void StopAndJoin() {
    Stop();
    if (io_thread_.joinable()) io_thread_.join();
    if (pipe_thread_.joinable()) pipe_thread_.join();
    // Wait for in-flight Sends to drain: freeing a pipe mapping under a
    // sender's memcpy would be a use-after-munmap (stopped_ makes them
    // bail at their next ring-full or entry check).
    for (int i = 0; i < 5000 && inflight_sends_.load() > 0; ++i) {
      timespec ts{0, 1000 * 1000};
      nanosleep(&ts, nullptr);
    }
    for (auto& kv : rpipes_) ClosePipe(kv.second);
    rpipes_.clear();
    std::lock_guard<std::mutex> lk(send_mu_);
    for (auto& kv : pipes_by_path_) {
      WritePipe* p = kv.second;
      munmap(reinterpret_cast<void*>(p->hdr), p->map_len);
      close(p->fd);  // releases the writer-liveness LOCK_SH
      unlink(p->path.c_str());
      // The sibling .lock file stays behind (as the unix-socket path's
      // do): unlinking it would hand a concurrent locker a different
      // inode, reopening the reclaim/create race the flock exists to
      // close.  They are empty files; ReclaimIfDead removes them under
      // LOCK_EX when it reclaims a name.
      delete p;
    }
    pipes_by_path_.clear();
    pipes_.clear();
    for (WritePipe* p : dead_write_pipes_) {
      // Retired at runtime (dead reader): fd closed and name unlinked
      // then; only the parked mapping remains.
      munmap(reinterpret_cast<void*>(p->hdr), p->map_len);
      delete p;
    }
    dead_write_pipes_.clear();
    for (auto& kv : send_fds_) close(kv.second);
    send_fds_.clear();
    for (auto& kv : conns_) {
      close(kv.second->fd);
      delete kv.second;
    }
    conns_.clear();
    if (epfd_ >= 0) {
      close(epfd_);
      epfd_ = -1;
    }
    std::lock_guard<std::mutex> qlk(queue_mu_);
    for (auto& f : queue_) free(f.buf);
    queue_.clear();
    for (auto& f : express_) free(f.buf);
    express_.clear();
  }

 private:
  static constexpr int kSendLocks = 64;

  void PipeLoop() {
    uint64_t idle_us = 0;
    uint64_t last_scan_ms = 0, last_live_ms = 0;
    while (!stopped_) {
      uint64_t now_ms = NowMs();
      if (now_ms - last_scan_ms >= 100) {
        last_scan_ms = now_ms;
        ScanPipes();
      }
      bool check_liveness = false;
      if (now_ms - last_live_ms >= 1000) {
        last_live_ms = now_ms;
        check_liveness = true;
      }
      long long moved = 0;
      for (auto it = rpipes_.begin(); it != rpipes_.end();) {
        ReadPipe* rp = it->second;
        // Reader heartbeat: tells a blocked writer this ring IS being
        // drained (see PipeHdr::reader_beat).  Stamped every loop
        // iteration — liveness, not progress — so its staleness is
        // bounded by one iteration (≈ the idle-backoff cap), far under
        // the 1000 ms floor of the writer's dead threshold.
        rp->hdr->reader_beat.store(NowMs(), std::memory_order_relaxed);
        long long n = PumpPipe(rp);
        if (n > 0) moved += n;
        bool drop = n < 0;
        if (drop) {
          struct stat st{};
          if (fstat(rp->fd, &st) == 0) {
            bad_pipes_[rp->path] = st.st_ino;
          }
        }
        if (!drop && check_liveness && n == 0) {
          drop = ReclaimIfDead(rp);
        }
        if (drop) {
          ClosePipe(rp);
          it = rpipes_.erase(it);
        } else {
          ++it;
        }
      }
      if (moved) {
        idle_us = 0;
      } else {
        // Exponential backoff, capped: the cap trades idle CPU for tail
        // latency (PS_SHM_RING_IDLE_US; single-core hosts want it high,
        // dedicated cores can spin near zero).
        uint64_t cap = pipe_idle_cap_us_;
        idle_us = idle_us ? (idle_us * 2 < cap ? idle_us * 2 : cap) : 2;
        timespec ts{0, static_cast<long>(idle_us * 1000)};
        nanosleep(&ts, nullptr);
      }
    }
  }

  static uint64_t NowMs() {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<uint64_t>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
  }

  // Detach (and possibly reclaim the name of) a pipe whose writer died.
  // Serialized under the sibling .lock and guarded by an inode check: a
  // restarted writer may have already recreated the NAME with a fresh
  // inode — unlinking blindly would orphan the new generation's pipe.
  bool ReclaimIfDead(ReadPipe* rp) {
    if (flock(rp->fd, LOCK_EX | LOCK_NB) != 0) return false;  // writer alive
    flock(rp->fd, LOCK_UN);
    std::string lockp = rp->path + ".lock";
    int lock_fd = open(lockp.c_str(), O_CREAT | O_RDWR, 0600);
    if (lock_fd < 0) return true;  // detach; scan re-attaches if live
    if (flock(lock_fd, LOCK_EX | LOCK_NB) != 0) {
      close(lock_fd);  // a writer is mid-create on this name: just detach
      return true;
    }
    struct stat st_name{}, st_mine{};
    if (stat(rp->path.c_str(), &st_name) != 0) {
      // Writer already unlinked the pipe; drop the .lock we just
      // recreated with O_CREAT or it leaks in /dev/shm forever.
      unlink(lockp.c_str());
    } else if (fstat(rp->fd, &st_mine) == 0 &&
               st_name.st_ino == st_mine.st_ino &&
               flock(rp->fd, LOCK_EX | LOCK_NB) == 0) {
      unlink(rp->path.c_str());
      unlink(lockp.c_str());
    }
    flock(lock_fd, LOCK_UN);
    close(lock_fd);
    return true;
  }

  void ScanPipes() {
    std::vector<std::array<std::string, 3>> watches;
    {
      std::lock_guard<std::mutex> lk(pipe_mu_);
      watches = watches_;
    }
    for (const auto& w : watches) {
      DIR* d = opendir(w[0].c_str());
      if (!d) continue;
      while (dirent* e = readdir(d)) {
        std::string name = e->d_name;
        if (name.size() < w[1].size() + w[2].size()) continue;
        if (name.compare(0, w[1].size(), w[1]) != 0) continue;
        if (name.compare(name.size() - w[2].size(), w[2].size(), w[2]) != 0)
          continue;
        std::string path = w[0] + "/" + name;
        if (rpipes_.count(path)) continue;
        // A pipe dropped for a protocol error stays blacklisted for its
        // inode's lifetime — re-attaching the same desynced stream would
        // loop attach/fail forever.  A fresh inode (writer restarted)
        // clears the entry.
        auto bad = bad_pipes_.find(path);
        if (bad != bad_pipes_.end()) {
          struct stat st{};
          if (stat(path.c_str(), &st) == 0 &&
              static_cast<uint64_t>(st.st_ino) == bad->second) {
            continue;
          }
          bad_pipes_.erase(bad);
        }
        TryAttachPipe(path);
      }
      closedir(d);
    }
  }

  void TryAttachPipe(const std::string& path) {
    std::string lockp = path + ".lock";
    int lock_fd = open(lockp.c_str(), O_CREAT | O_RDWR, 0600);
    if (lock_fd < 0) return;
    flock(lock_fd, LOCK_EX);
    int fd = open(path.c_str(), O_RDWR);
    if (fd < 0) {
      // Pipe vanished between scan and attach: drop the .lock we may
      // have just created.
      unlink(lockp.c_str());
    }
    if (fd >= 0) {
      if (flock(fd, LOCK_EX | LOCK_NB) == 0) {
        // No live writer: stale leftover — reclaim the name.
        unlink(path.c_str());
        unlink(lockp.c_str());
        close(fd);
      } else {
        struct stat st{};
        if (fstat(fd, &st) == 0 &&
            static_cast<size_t>(st.st_size) > kPipeDataOff) {
          size_t map_len = st.st_size;
          void* mem = mmap(nullptr, map_len, PROT_READ | PROT_WRITE,
                           MAP_SHARED, fd, 0);
          if (mem != MAP_FAILED) {
            auto* hdr = static_cast<PipeHdr*>(mem);
            if (hdr->magic == kPipeMagic &&
                hdr->size == map_len - kPipeDataOff) {
              auto* rp = new ReadPipe();
              rp->hdr = hdr;
              rp->data = static_cast<uint8_t*>(mem) + kPipeDataOff;
              rp->fd = fd;
              rp->map_len = map_len;
              rp->path = path;
              hdr->reader_beat.store(NowMs(), std::memory_order_relaxed);
              rpipes_[path] = rp;
              fd = -1;  // owned by rp now
            } else {
              munmap(mem, map_len);
            }
          }
        }
        if (fd >= 0) close(fd);
      }
    }
    flock(lock_fd, LOCK_UN);
    close(lock_fd);
  }

  // Drain available pipe bytes through the frame state machine.
  // Returns bytes consumed, or -1 on protocol error.
  long long PumpPipe(ReadPipe* rp) {
    Conn* c = &rp->conn;
    uint64_t head = rp->hdr->head.load(std::memory_order_relaxed);
    const uint64_t size = rp->hdr->size;
    long long consumed = 0;
    while (true) {
      uint64_t tail = rp->hdr->tail.load(std::memory_order_acquire);
      uint64_t avail = tail - head;
      if (avail == 0) break;
      uint64_t n = c->want - c->got;
      if (n > avail) n = avail;
      uint64_t pos = head % size;
      if (n > size - pos) n = size - pos;
      memcpy(StageDst(c), rp->data + pos, n);
      c->got += n;
      head += n;
      consumed += static_cast<long long>(n);
      rp->hdr->head.store(head, std::memory_order_release);
      if (c->got == c->want && !OnStageComplete(c)) return -1;
    }
    return consumed;
  }

  void ClosePipe(ReadPipe* rp) {
    munmap(const_cast<uint8_t*>(
               reinterpret_cast<const uint8_t*>(rp->hdr)),
           rp->map_len);
    close(rp->fd);
    delete rp;
  }

  void IoLoop() {
    epoll_event events[64];
    while (!stopped_) {
      int n = epoll_wait(epfd_, events, 64, 100);
      if (n < 0) {
        if (errno == EINTR) continue;
        break;
      }
      for (int i = 0; i < n; ++i) {
        int fd = events[i].data.fd;
        if (fd == listen_fd_) {
          AcceptAll();
        } else {
          auto it = conns_.find(fd);
          if (it != conns_.end() && !ReadConn(it->second)) {
            epoll_ctl(epfd_, EPOLL_CTL_DEL, fd, nullptr);
            close(fd);
            delete it->second;
            conns_.erase(it);
          }
        }
      }
    }
  }

  void AcceptAll() {
    while (true) {
      int fd = accept4(listen_fd_, nullptr, nullptr, SOCK_NONBLOCK);
      if (fd < 0) break;
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      auto* conn = new Conn();
      conn->fd = fd;
      conns_[fd] = conn;
      epoll_event ev{};
      ev.events = EPOLLIN;
      ev.data.fd = fd;
      epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev);
    }
  }

  // Byte sink of the frame state machine for the current stage.
  static uint8_t* StageDst(Conn* c) {
    return (c->stage == 0 ? c->header : c->frame.buf) + c->got;
  }

  // Stage transition once got == want.  Returns false on protocol error.
  // Shared by the fd reader and the shm-pipe pump: both are byte streams
  // feeding the same reassembly.
  bool OnStageComplete(Conn* c) {
    if (c->stage == 0) {
      uint32_t magic, meta_len, n_data;
      memcpy(&magic, c->header, 4);
      memcpy(&meta_len, c->header + 4, 4);
      memcpy(&n_data, c->header + 8, 4);
      if (magic != kMagic) return false;
      c->frame.meta_len = meta_len;
      c->frame.n_data = n_data;
      // Read lens first to learn the body size.
      c->body_size = 8ull * n_data + meta_len;
      c->frame.buf = static_cast<uint8_t*>(malloc(c->body_size));
      c->stage = 1;
      c->want = 8ull * n_data;  // lens arrive first
      c->got = 0;
      if (c->want == 0) {
        c->stage = 2;
        c->want = meta_len;
      }
    } else if (c->stage == 1) {
      // Lens complete: total body = lens + meta + sum(data).
      uint64_t total = 0;
      const uint64_t* lens = reinterpret_cast<uint64_t*>(c->frame.buf);
      for (uint32_t i = 0; i < c->frame.n_data; ++i) total += lens[i];
      size_t full = 8ull * c->frame.n_data + c->frame.meta_len + total;
      c->frame.buf = static_cast<uint8_t*>(realloc(c->frame.buf, full));
      c->body_size = full;
      c->stage = 2;
      c->want = full;
      // got already == 8*n_data
    } else {
      // Frame complete.
      {
        std::lock_guard<std::mutex> lk(queue_mu_);
        if (recv_priority_ && FrameIsExpress(c->frame)) {
          express_.push_back(c->frame);
        } else {
          queue_.push_back(c->frame);
        }
      }
      queue_cv_.notify_one();
      c->frame = Frame();
      c->stage = 0;
      c->want = kHeaderSize;
      c->got = 0;
    }
    return true;
  }

  // Pump all available bytes through the frame state machine.  Returns
  // false when the peer closed or errored.
  bool ReadConn(Conn* c) {
    while (true) {
      ssize_t n = read(c->fd, StageDst(c), c->want - c->got);
      if (n == 0) return false;
      if (n < 0) {
        return errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR;
      }
      c->got += static_cast<size_t>(n);
      if (c->got < c->want) continue;
      if (!OnStageComplete(c)) return false;
    }
  }

  int epfd_;
  int listen_fd_ = -1;
  std::string bound_path_;
  std::thread io_thread_;
  std::atomic<bool> stopped_{false};
  std::unordered_map<int, Conn*> conns_;  // io thread only
  std::unordered_map<int, int> send_fds_;
  std::unordered_map<int, WritePipe*> pipes_;                  // send_mu_
  std::unordered_map<std::string, WritePipe*> pipes_by_path_;  // send_mu_
  // Dead-reader pipes parked until shutdown (mapping must outlive any
  // sender blocked inside PipeWriteVec at retirement time).  send_mu_.
  std::vector<WritePipe*> dead_write_pipes_;
  // Lazily read from PS_SHM_RING_DEAD_MS (0 = not yet resolved).
  std::atomic<uint64_t> reader_dead_ms_{0};
  std::vector<std::array<std::string, 3>> watches_;  // pipe_mu_
  std::unordered_map<std::string, ReadPipe*> rpipes_;  // pipe thread only
  std::unordered_map<std::string, uint64_t> bad_pipes_;  // path -> inode
  std::thread pipe_thread_;
  std::mutex pipe_mu_;
  std::atomic<uint64_t> pipe_idle_cap_us_{500};
  std::atomic<int> inflight_sends_{0};
  std::mutex send_mu_;
  std::mutex per_fd_send_mu_[kSendLocks];
  std::deque<Frame> queue_;
  std::deque<Frame> express_;  // priority > 0 data frames pop first
  // PS_RECV_PRIORITY=0 restores the single strict-FIFO queue (process
  // env: the native core is per-process, unlike the per-node Python
  // Environment overrides of the in-process test clusters).
  const bool recv_priority_ = [] {
    const char* v = getenv("PS_RECV_PRIORITY");
    return v == nullptr || strcmp(v, "0") != 0;
  }();
  std::mutex queue_mu_;
  std::condition_variable queue_cv_;
};

// Parallel memcpy pool for the shm van's segment writes — the native
// counterpart of the reference IPC transport's async copy thread pool
// (rdma_transport.h:469-633, BYTEPS_IPC_COPY_NUM_THREADS): multi-MB
// payload copies are split across persistent native threads, GIL-free
// (Python enters through a ctypes call, which releases the GIL).
class CopyPool {
 public:
  explicit CopyPool(int n_threads)
      : n_(n_threads < 1 ? 1 : n_threads) {
    for (int i = 0; i < n_; ++i) {
      threads_.emplace_back([this] { Work(); });
    }
  }

  ~CopyPool() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : threads_) t.join();
  }

  void Copy(uint8_t* dst, const uint8_t* src, uint64_t n) {
    constexpr uint64_t kMinChunk = 1ull << 20;  // below this, inline memcpy
    uint64_t want = n / kMinChunk;
    int parts = static_cast<int>(
        want < 1 ? 1 : (want > static_cast<uint64_t>(n_) + 1
                            ? static_cast<uint64_t>(n_) + 1
                            : want));
    if (parts <= 1) {
      memcpy(dst, src, n);
      return;
    }
    // One job at a time per pool; concurrent callers serialize here.
    std::lock_guard<std::mutex> caller_lk(caller_mu_);
    Job job;
    job.dst = dst;
    job.src = src;
    job.n = n;
    job.parts = parts;
    {
      std::lock_guard<std::mutex> lk(mu_);
      job_ = &job;
      ++seq_;
    }
    cv_.notify_all();
    RunChunks(&job);  // the caller is a worker too
    // The job lives on this stack: wait until every chunk is copied AND
    // every attached worker detached before letting it go out of scope.
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [&] {
      return job.done.load() == job.parts && job.workers == 0;
    });
    job_ = nullptr;
  }

 private:
  struct Job {
    uint8_t* dst = nullptr;
    const uint8_t* src = nullptr;
    uint64_t n = 0;
    int parts = 0;
    std::atomic<int> next{0};
    std::atomic<int> done{0};
    int workers = 0;  // attached pool threads; guarded by mu_
  };

  void RunChunks(Job* job) {
    int finished = 0;
    for (int i = job->next.fetch_add(1); i < job->parts;
         i = job->next.fetch_add(1)) {
      uint64_t lo = job->n * i / job->parts;
      uint64_t hi = job->n * (i + 1) / job->parts;
      memcpy(job->dst + lo, job->src + lo, hi - lo);
      ++finished;
    }
    if (finished) job->done.fetch_add(finished);
  }

  void Work() {
    uint64_t seen = 0;
    while (true) {
      Job* job = nullptr;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] { return stop_ || seq_ != seen; });
        if (stop_) return;
        seen = seq_;
        job = job_;  // may already be null (job finished without us)
        if (job != nullptr) ++job->workers;
      }
      if (job == nullptr) continue;
      RunChunks(job);
      {
        std::lock_guard<std::mutex> lk(mu_);
        --job->workers;
      }
      done_cv_.notify_all();
    }
  }

  int n_;
  std::vector<std::thread> threads_;
  std::mutex mu_;
  std::mutex caller_mu_;
  std::condition_variable cv_;
  std::condition_variable done_cv_;
  Job* job_ = nullptr;
  uint64_t seq_ = 0;
  bool stop_ = false;
};

}  // namespace

extern "C" {

struct psl_frame_view {
  uint8_t* buf;
  uint32_t meta_len;
  uint32_t n_data;
};

void* psl_create() { return new Core(); }

int psl_bind(void* h, int port, int backlog) {
  return static_cast<Core*>(h)->Bind(port, backlog);
}

int psl_connect(void* h, int node_id, const char* host, int port,
                int timeout_ms) {
  return static_cast<Core*>(h)->Connect(node_id, host, port, timeout_ms);
}

int psl_bind_local(void* h, const char* path, int backlog) {
  return static_cast<Core*>(h)->BindLocal(path, backlog);
}

int psl_pipe_connect(void* h, int node_id, const char* path,
                     uint64_t data_bytes) {
  return static_cast<Core*>(h)->PipeConnect(node_id, path, data_bytes);
}

int psl_pipe_watch(void* h, const char* dir, const char* prefix,
                   const char* suffix, int idle_cap_us) {
  return static_cast<Core*>(h)->PipeWatch(dir, prefix, suffix, idle_cap_us);
}

int psl_connect_local(void* h, int node_id, const char* path,
                      int timeout_ms) {
  return static_cast<Core*>(h)->ConnectLocal(node_id, path, timeout_ms);
}

long long psl_send(void* h, int node_id, const uint8_t* meta,
                   uint32_t meta_len, uint32_t n_data,
                   const uint8_t* const* data, const uint64_t* lens) {
  return static_cast<Core*>(h)->Send(node_id, meta, meta_len, n_data, data,
                                     lens);
}

int psl_recv(void* h, psl_frame_view* out, int timeout_ms) {
  Frame f;
  int rc = static_cast<Core*>(h)->Recv(&f, timeout_ms);
  if (rc == 1) {
    out->buf = f.buf;
    out->meta_len = f.meta_len;
    out->n_data = f.n_data;
  }
  return rc;
}

void psl_frame_free(uint8_t* buf) { free(buf); }

void* psl_copy_pool_create(int n_threads) { return new CopyPool(n_threads); }

void psl_copy_pool_copy(void* p, void* dst, const void* src, uint64_t n) {
  static_cast<CopyPool*>(p)->Copy(static_cast<uint8_t*>(dst),
                                  static_cast<const uint8_t*>(src), n);
}

void psl_copy_pool_destroy(void* p) { delete static_cast<CopyPool*>(p); }

void psl_stop(void* h) { static_cast<Core*>(h)->Stop(); }

void psl_destroy(void* h) { delete static_cast<Core*>(h); }

}  // extern "C"
