"""IciTcpVan: collective data plane over the TCP control plane, across
real OS processes — the fabric_van pattern (fabric_van.h:123-127) with
jax.distributed supplying the cross-process device mesh.

2 worker processes x 4 virtual CPU devices each = one global 8-device
mesh; a dense push_pull must aggregate across both processes and match
the host model (the PS aggregation contract of kv_app.h:430-452).
"""

import os
import subprocess
import sys

from pslite_tpu.utils.network import get_available_port


def test_ici_tcp_two_process_push_pull():
    port = get_available_port()
    child = os.path.join(os.path.dirname(__file__), "ici_tcp_child.py")
    base_env = dict(
        os.environ,
        DMLC_NUM_WORKER="2",
        DMLC_NUM_SERVER="1",
        DMLC_PS_ROOT_URI="127.0.0.1",
        DMLC_PS_ROOT_PORT=str(port),
        DMLC_NODE_HOST="127.0.0.1",
        PS_VAN_TYPE="ici_tcp",
        PS_ICI_MULTIHOST="1",
        PS_VERBOSE="1",
    )
    # The children pin their own platform; scrub any inherited forcing.
    for var in ("JAX_PLATFORMS", "XLA_FLAGS"):
        base_env.pop(var, None)
    roles = [("scheduler", None), ("server", None), ("worker", 0),
             ("worker", 1)]
    procs = []
    for role, rank in roles:
        env = dict(base_env, DMLC_ROLE=role)
        if rank is not None:
            env["DMLC_RANK"] = str(rank)
        procs.append(
            subprocess.Popen(
                [sys.executable, child],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )
        )
    outputs = []
    for p in procs:
        try:
            # 1-CPU host: 4 interpreter startups serialize, plus the
            # cross-process shard_map compile; be generous.
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outputs.append(out.decode())
    for p, out in zip(procs, outputs):
        assert p.returncode == 0, f"child failed:\n{out}"
    worker_outs = [o for o in outputs if "WORKER_OK 24.0" in o]
    assert len(worker_outs) == 2, f"expected 2 worker OKs, got: {outputs}"
