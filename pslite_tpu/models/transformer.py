"""PSFormer — flagship transformer LM, written TPU-first.

Pure-JAX (functional params pytree), bfloat16-friendly matmuls for the MXU,
ring attention over a sequence-parallel mesh axis for long context, and a
training step where the parameter server IS the optimizer loop:

    pull   = all_gather of the sharded flat parameter store
    push   = psum_scatter of the flat gradient (cross-worker aggregation)
    update = server handle applied to the local store shard

i.e. the BytePS gradient push/pull cycle (reference docs/overview.md:44-125)
as one jit-compiled SPMD program.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional


@dataclass(frozen=True)
class ModelConfig:
    vocab: int = 256
    dim: int = 128
    heads: int = 4
    layers: int = 2
    mlp_ratio: int = 4
    moe_experts: int = 0  # >0: replace the MLP with a top-1 routed MoE
    dtype: str = "float32"  # params dtype; matmuls cast to bfloat16 on TPU
    remat: bool = False  # jax.checkpoint each layer: trade FLOPs for HBM


@dataclass(frozen=True)
class ParallelCtx:
    """How a forward pass is sharded (inside shard_map).

    ``attn_fn`` handles sequence parallelism (ring attention over sp);
    ``tp_axis`` shards the MLP matmuls column/row-wise with a closing psum
    (tensor parallelism); ``ep_axis`` shards MoE experts (expert
    parallelism).  All None => single-device execution.
    """

    attn_fn: Optional[Callable] = None
    pos_offset: int = 0
    tp_axis: Optional[str] = None
    ep_axis: Optional[str] = None


def init_params(rng, cfg: ModelConfig):
    import jax
    import jax.numpy as jnp

    from ..parallel.moe import init_moe_params

    dt = jnp.dtype(cfg.dtype)
    keys = jax.random.split(rng, 2 + cfg.layers)
    D, H = cfg.dim, cfg.heads
    scale = D ** -0.5

    def dense(key, shape):
        return (jax.random.normal(key, shape) * scale).astype(dt)

    params = {
        "embed": dense(keys[0], (cfg.vocab, D)),
        "ln_f": jnp.ones((D,), dt),
        "layers": [],
    }
    for i in range(cfg.layers):
        k1, k2, k3, k4 = jax.random.split(keys[2 + i], 4)
        layer = {
            "ln1": jnp.ones((D,), dt),
            "ln2": jnp.ones((D,), dt),
            "qkv": dense(k1, (D, 3 * D)),
            "proj": dense(k2, (D, D)),
        }
        if cfg.moe_experts > 0:
            layer["moe"] = init_moe_params(
                k3, D, cfg.mlp_ratio * D, cfg.moe_experts, dt
            )
        else:
            layer["mlp_in"] = dense(k3, (D, cfg.mlp_ratio * D))
            layer["mlp_out"] = dense(k4, (cfg.mlp_ratio * D, D))
        params["layers"].append(layer)
    return params


def _rmsnorm(x, scale):
    import jax
    import jax.numpy as jnp

    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(var + 1e-6) * scale


def _mlp(layer, h, compute_dt, ctx: "ParallelCtx", cfg: ModelConfig):
    """Dense MLP.

    With ``ctx.tp_axis`` set, the Megatron sequence<->tensor parallel
    transition (activations are sequence-sharded on the same axis):
    all_gather the token blocks, run the column/row-sharded matmul pair,
    and reduce-scatter the partial sums back to sequence shards — the
    closing collective both sums the feature-sharded partials and
    re-shards the sequence.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    x_dt = h.dtype
    w_in, w_out = layer["mlp_in"], layer["mlp_out"]
    if ctx.tp_axis is None:
        h = h.astype(compute_dt) @ w_in.astype(compute_dt)
        h = jax.nn.gelu(h.astype(x_dt))
        return (h.astype(compute_dt) @ w_out.astype(compute_dt)).astype(x_dt)

    axis = ctx.tp_axis
    S = lax.psum(1, axis)
    my = lax.axis_index(axis)
    f_local = w_in.shape[1] // S
    w_in = lax.dynamic_slice_in_dim(w_in, my * f_local, f_local, axis=1)
    w_out = lax.dynamic_slice_in_dim(w_out, my * f_local, f_local, axis=0)

    h_full = lax.all_gather(h, axis, axis=1, tiled=True)  # [B, T, D]
    h1 = h_full.astype(compute_dt) @ w_in.astype(compute_dt)
    h1 = jax.nn.gelu(h1.astype(x_dt))
    part = (h1.astype(compute_dt) @ w_out.astype(compute_dt)).astype(x_dt)
    # Sum feature partials across the axis AND return to sequence shards.
    return lax.psum_scatter(part, axis, scatter_dimension=1, tiled=True)


def _moe(layer, h, compute_dt, ctx: "ParallelCtx", cfg: ModelConfig):
    """Routed MoE; with ``ctx.ep_axis`` set, experts shard blockwise over
    the axis and tokens route via gather + psum_scatter."""
    from jax import lax

    from ..parallel.moe import moe_ffn

    moe_p = layer["moe"]
    if ctx.ep_axis is None:
        return moe_ffn(moe_p, h, None, compute_dtype=compute_dt)
    S = lax.psum(1, ctx.ep_axis)
    my = lax.axis_index(ctx.ep_axis)
    e_local = cfg.moe_experts // S
    local = {
        "gate": moe_p["gate"],  # gating over global expert ids, replicated
        "w_in": lax.dynamic_slice_in_dim(
            moe_p["w_in"], my * e_local, e_local, axis=0
        ),
        "w_out": lax.dynamic_slice_in_dim(
            moe_p["w_out"], my * e_local, e_local, axis=0
        ),
    }
    return moe_ffn(local, h, ctx.ep_axis, compute_dtype=compute_dt)


def forward(
    params,
    tokens,
    cfg: ModelConfig,
    attn_fn: Optional[Callable] = None,
    pos_offset=0,
    ctx: Optional["ParallelCtx"] = None,
):
    """Token ids [B, T_local] -> logits [B, T_local, vocab].

    Single-device by default; pass a :class:`ParallelCtx` (or the legacy
    ``attn_fn``/``pos_offset``) inside shard_map for sp/tp/ep execution.
    """
    import jax
    import jax.numpy as jnp

    from ..parallel.ring_attention import reference_attention

    if ctx is None:
        ctx = ParallelCtx(attn_fn=attn_fn, pos_offset=pos_offset)
    attn = ctx.attn_fn or (
        lambda q, k, v: reference_attention(q, k, v, causal=True)
    )

    D, H = cfg.dim, cfg.heads
    hd = D // H
    x = params["embed"][tokens]  # [B, T, D]
    B, T, _ = x.shape
    # Sinusoidal positions; global under sequence parallelism.
    pos = ctx.pos_offset + jnp.arange(T)
    freqs = jnp.exp(-jnp.arange(0, D, 2) / D * jnp.log(10000.0))
    ang = pos[:, None] * freqs[None, :]
    pe = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)
    x = x + pe[None].astype(x.dtype)

    compute_dt = jnp.bfloat16 if x.dtype != jnp.float64 else x.dtype

    def layer_fn(layer, x):
        h = _rmsnorm(x, layer["ln1"])
        qkv = (h.astype(compute_dt) @ layer["qkv"].astype(compute_dt)).astype(
            x.dtype
        )
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, hd)
        k = k.reshape(B, T, H, hd)
        v = v.reshape(B, T, H, hd)
        o = attn(q, k, v).reshape(B, T, D)
        x = x + (o.astype(compute_dt) @ layer["proj"].astype(compute_dt)
                 ).astype(x.dtype)
        h = _rmsnorm(x, layer["ln2"])
        if "moe" in layer:
            return x + _moe(layer, h, compute_dt, ctx, cfg)
        return x + _mlp(layer, h, compute_dt, ctx, cfg)

    if cfg.remat:
        # Rematerialize activations in the backward pass: per-layer
        # jax.checkpoint trades recompute FLOPs for HBM residency (long
        # sequences / deep stacks).
        layer_fn = jax.checkpoint(layer_fn, static_argnums=())

    for layer in params["layers"]:
        x = layer_fn(layer, x)

    x = _rmsnorm(x, params["ln_f"])
    logits = (x.astype(compute_dt) @ params["embed"].T.astype(compute_dt)
              ).astype(jnp.float32)
    return logits


def loss_fn(params, inputs, targets, cfg: ModelConfig, attn_fn=None,
            pos_offset=0, ctx=None):
    """Mean next-token cross-entropy over the local block."""
    import jax
    import jax.numpy as jnp

    logits = forward(params, inputs, cfg, attn_fn=attn_fn,
                     pos_offset=pos_offset, ctx=ctx)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()
