"""Mini logging layer: CHECK macros and PS_VERBOSE-gated vlog.

Equivalent of the reference's dmlc mini-glog (``include/dmlc/logging.h``) and
``PS_VLOG`` (``include/ps/internal/postoffice.h:315``): verbosity 1 logs
connection-level events, 2 logs every message.
"""

from __future__ import annotations

import logging
import os
import sys

_logger = logging.getLogger("pslite_tpu")
if not _logger.handlers:
    _handler = logging.StreamHandler(sys.stderr)
    _handler.setFormatter(
        logging.Formatter("[%(asctime)s %(levelname).1s pslite_tpu] %(message)s")
    )
    _logger.addHandler(_handler)
    _logger.setLevel(logging.INFO)
    _logger.propagate = False


class CheckError(AssertionError):
    """Raised by check() — the CHECK()-failure equivalent."""


def check(cond: bool, msg: str = "") -> None:
    if not cond:
        raise CheckError(msg or "check failed")


def check_eq(a, b, msg: str = "") -> None:
    if a != b:
        raise CheckError(f"check failed: {a!r} != {b!r} {msg}")


_verbosity_override = 0


def set_verbosity(level: int) -> None:
    """Raise process-wide verbosity (used by Postoffice instances whose
    PS_VERBOSE arrives via an injected Environment rather than os.environ)."""
    global _verbosity_override
    _verbosity_override = max(_verbosity_override, level)


_env_level = None  # lazily cached; PS_VERBOSE is fixed at process start


def verbosity() -> int:
    """Effective level.  The os.environ read is cached — vlog gates sit
    on the per-message hot path, and PS_VERBOSE only ever arrives in a
    child's environment before python starts (in-process clusters raise
    the level via set_verbosity instead)."""
    global _env_level
    if _env_level is None:
        try:
            _env_level = int(os.environ.get("PS_VERBOSE", "0"))
        except ValueError:
            _env_level = 0
    return max(_env_level, _verbosity_override)


def vlog(level: int, msg) -> None:
    """Log ``msg`` when PS_VERBOSE >= level (1=connection, 2=per-message).

    ``msg`` may be a zero-arg callable: per-message call sites pass
    ``lambda: f"...{m.debug_string()}"`` so the (expensive) formatting
    only runs when the level is actually enabled."""
    if verbosity() >= level:
        _logger.info(msg() if callable(msg) else msg)


def info(msg: str) -> None:
    _logger.info(msg)


def warning(msg: str) -> None:
    _logger.warning(msg)


def fatal(msg: str) -> None:
    _logger.error(msg)
    raise CheckError(msg)


def fatal_log(msg: str) -> None:
    """Log at error level without raising (for use in except blocks)."""
    _logger.error(msg)
