"""ICI van end-to-end: the KV contract riding jitted collectives.

The cluster control plane (scheduler bootstrap, barriers) runs in-process;
dense registered buckets and sparse tables go through the CollectiveEngine;
unregistered keys fall back to the async message path served by a KVServer —
the sync/async duality SURVEY §7 requires.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from pslite_tpu import KVServer, KVServerDefaultHandle, KVWorker

from helpers import LoopbackCluster


@pytest.fixture()
def cluster():
    c = LoopbackCluster(num_workers=1, num_servers=1, van_type="ici")
    c.start()
    yield c
    c.finalize()


def test_dense_bucket_push_pull(cluster):
    worker = KVWorker(0, 0, postoffice=cluster.workers[0])
    assert worker.engine is not None
    W = worker.engine.num_shards

    keys = np.arange(8, dtype=np.uint64)
    val_len = 50
    worker.register_dense("grads", keys, val_len)

    base = np.linspace(-1, 1, 8 * val_len).astype(np.float32)
    grads = np.stack([(w + 1) * base for w in range(W)])

    outs = np.zeros(8 * val_len, dtype=np.float32)
    ts = worker.push_pull(keys, grads, outs)
    worker.wait(ts)
    np.testing.assert_allclose(outs, base * sum(range(1, W + 1)), rtol=1e-5)

    # Device-resident result is also available (zero host copy).
    dev = worker.get_pulled(ts)
    assert dev is not None and dev.shape == (8 * val_len,)


def test_dense_push_then_pull_separately(cluster):
    worker = KVWorker(0, 0, postoffice=cluster.workers[0])
    keys = np.arange(4, dtype=np.uint64) + 100
    worker.register_dense("acc", keys, 16)
    ones = np.ones(4 * 16, dtype=np.float32)
    worker.wait(worker.push(keys, ones))
    out = np.zeros_like(ones)
    worker.wait(worker.pull(keys, out))
    W = worker.engine.num_shards
    np.testing.assert_allclose(out, W * ones)


def test_back_to_back_pushes_same_bucket(cluster):
    """Regression: the store a push returns is donated by the NEXT push of
    the same bucket; wait(ts1) after issuing push ts2 must not block on the
    escaped (deleted) reference."""
    worker = KVWorker(0, 0, postoffice=cluster.workers[0])
    keys = np.arange(4, dtype=np.uint64) + 300
    worker.register_dense("b2b", keys, 16)
    ones = np.ones(4 * 16, dtype=np.float32)
    ts1 = worker.push(keys, ones)
    ts2 = worker.push(keys, ones)
    ts3 = worker.push(keys, ones)
    worker.wait(ts1)
    worker.wait(ts2)
    worker.wait(ts3)
    out = np.zeros_like(ones)
    worker.wait(worker.pull(keys, out))
    W = worker.engine.num_shards
    np.testing.assert_allclose(out, 3 * W * ones)


def test_unregistered_keys_fall_back_to_messages(cluster):
    srv = KVServer(0, postoffice=cluster.servers[0])
    srv.set_request_handle(KVServerDefaultHandle())
    try:
        worker = KVWorker(0, 0, postoffice=cluster.workers[0])
        keys = np.array([7777], dtype=np.uint64)
        vals = np.full(32, 2.0, dtype=np.float32)
        worker.wait(worker.push(keys, vals))
        out = np.zeros_like(vals)
        worker.wait(worker.pull(keys, out))
        np.testing.assert_allclose(out, vals)
    finally:
        srv.stop()


def test_engine_callback_fires_without_wait(cluster):
    """ps-lite's callback-driven pipelining: callbacks must fire on
    completion even if the app never calls wait()."""
    import threading

    worker = KVWorker(0, 0, postoffice=cluster.workers[0])
    keys = np.arange(2, dtype=np.uint64) + 500
    worker.register_dense("cb", keys, 8)
    done = threading.Event()
    worker.push(keys, np.ones(16, dtype=np.float32), callback=done.set)
    assert done.wait(timeout=30), "engine-path callback never fired"


def test_engine_route_rejects_different_keys(cluster):
    """Same (count, first, last) signature but different keys must NOT hijack
    the collective fast path."""
    worker = KVWorker(0, 0, postoffice=cluster.workers[0])
    keys = np.array([0, 5, 10], dtype=np.uint64)
    worker.register_dense("sig", keys, 4)
    other = np.array([0, 7, 10], dtype=np.uint64)
    assert worker._engine_route(other) is None
    assert worker._engine_route(keys) == "sig"
    assert worker._engine_route(keys, cmd=3) is None


def test_sparse_table_via_worker(cluster):
    worker = KVWorker(0, 0, postoffice=cluster.workers[0])
    eng = cluster.workers[0].van.sparse_engine
    assert eng is not None
    eng.register_sparse("emb", num_rows=64, dim=8)
    W = eng.num_shards
    rng = np.random.default_rng(3)
    idx = rng.integers(0, 64, size=(W, 5)).astype(np.int32)
    grads = rng.normal(size=(W, 5, 8)).astype(np.float32)
    worker.wait(worker.push_sparse("emb", idx, grads))

    out = np.zeros((W, 5, 8), dtype=np.float32)
    worker.wait(worker.pull_sparse("emb", idx, out=out))

    ref = np.zeros((64, 8), dtype=np.float32)
    for w in range(W):
        for i in range(5):
            ref[idx[w, i]] += grads[w, i]
    for w in range(W):
        np.testing.assert_allclose(out[w], ref[idx[w]], rtol=1e-4, atol=1e-5)


def test_worker_pinned_pull_buffer(cluster):
    """App-level PinMemory: after register_pull_buffer, engine pulls for
    the bucket land in one persistent device buffer (address identity),
    while the message-level out= contract is unchanged."""
    worker = KVWorker(0, 0, postoffice=cluster.workers[0])
    keys = np.arange(4, dtype=np.uint64)
    worker.register_dense("pinned", keys, 64)
    worker.register_pull_buffer("pinned")

    ones = np.ones(4 * 64, dtype=np.float32)
    W = worker.engine.num_shards
    grads = np.stack([ones for _ in range(W)])
    out = np.zeros_like(ones)
    worker.wait(worker.push(keys, grads))
    worker.wait(worker.pull(keys, out))
    np.testing.assert_allclose(out, W * ones)

    def addrs(arr):
        return sorted(
            s.data.unsafe_buffer_pointer() for s in arr.addressable_shards
        )

    a1 = addrs(worker.engine.pinned_pull_buffer("pinned"))
    out2 = np.zeros_like(ones)
    worker.wait(worker.pull(keys, out2))
    np.testing.assert_allclose(out2, W * ones)
    a2 = addrs(worker.engine.pinned_pull_buffer("pinned"))
    assert a1 == a2, "pinned pull buffer moved between app-level pulls"


def test_worker_pinned_pull_pipelined(cluster):
    """Back-to-back pinned pulls without wait() must not use-after-donate:
    the app layer serializes on the previous completion."""
    worker = KVWorker(0, 0, postoffice=cluster.workers[0])
    keys = np.arange(2, dtype=np.uint64)
    worker.register_dense("pin_pipe", keys, 128)
    worker.register_pull_buffer("pin_pipe")
    ones = np.ones(2 * 128, dtype=np.float32)
    W = worker.engine.num_shards
    worker.wait(worker.push(keys, np.stack([ones] * W)))
    outs = [np.zeros_like(ones) for _ in range(4)]
    tss = [worker.pull(keys, o) for o in outs]  # no wait between
    for ts in tss:
        worker.wait(ts)
    for o in outs:
        np.testing.assert_allclose(o, W * ones)


def test_ici_shm_single_process_cluster():
    """PS_VAN_TYPE=ici_shm in one process: shm control plane under the
    collective data plane — registered buckets ride the engine, message
    traffic rides /dev/shm."""
    c = LoopbackCluster(num_workers=1, num_servers=1, van_type="ici_shm")
    c.start()
    servers = []
    try:
        from pslite_tpu import KVServerDefaultHandle

        srv = KVServer(0, postoffice=c.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        worker = KVWorker(0, 0, postoffice=c.workers[0])
        assert worker.engine is not None

        # Engine path (registered bucket).
        keys = np.arange(4, dtype=np.uint64)
        worker.register_dense("g", keys, 32)
        W = worker.engine.num_shards
        grads = np.ones((W, 4 * 32), np.float32)
        outs = np.zeros(4 * 32, np.float32)
        worker.wait(worker.push_pull(keys, grads, outs))
        np.testing.assert_allclose(outs, W * np.ones(4 * 32))

        # Message fallback (unregistered keys) rides the shm plane.
        mkeys = np.array([1 << 40], dtype=np.uint64)
        mvals = np.ones(64 * 1024, np.float32)  # > PS_SHM_MIN_BYTES
        worker.wait(worker.push(mkeys, mvals))
        mout = np.zeros_like(mvals)
        worker.wait(worker.pull(mkeys, mout))
        np.testing.assert_allclose(mout, mvals)
    finally:
        for s in servers:
            s.stop()
        c.finalize()


def test_worker_level_replay_and_stream(cluster):
    """KVWorker.replay / push_pull_stream surface the engine's
    dispatch-amortization tiers at the app level."""
    worker = KVWorker(0, 0, postoffice=cluster.workers[0])
    keys = np.arange(4, dtype=np.uint64)
    val_len = 64
    worker.register_dense("amort", keys, val_len)
    W = worker.engine.num_shards
    total = 4 * val_len

    # replay: T fused steps of sum-of-ones == step * W broadcast.
    T = 3
    seq = np.ones((T, total), np.float32)
    pulled = np.asarray(worker.replay("amort", seq))
    assert pulled.shape == (T, total)
    for t in range(T):
        np.testing.assert_allclose(pulled[t], (t + 1) * W)

    # stream continues from the replayed store.
    outs = [np.asarray(o) for o in
            worker.push_pull_stream("amort", iter(seq))]
    assert len(outs) == T
    np.testing.assert_allclose(outs[-1], 2 * T * W)
