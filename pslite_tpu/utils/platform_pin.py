"""Force the JAX CPU backend with N virtual devices — reliably.

The axon sitecustomize (TPU tunnel) force-sets ``jax_platforms``
programmatically at interpreter start, so ``JAX_PLATFORMS=cpu`` in the
environment alone is not enough once jax has been imported: the config
must be updated before first backend use.  This is the single shared
implementation behind tests/conftest.py, ``__graft_entry__.dryrun_multichip``
and any CPU-mesh tooling; keep the counter-measures here in sync with the
sitecustomize's behavior.
"""

from __future__ import annotations

import os
import re


def pin_cpu(n_devices: int = 8) -> None:
    """Pin the CPU backend with ``n_devices`` virtual devices.

    Must run before jax initializes a backend; raises RuntimeError if a
    non-CPU backend (or too few devices) already initialized.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""  # disable tunnel registration
    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={n_devices}"
    if "xla_force_host_platform_device_count" in flags:
        # Replace a stale count rather than trusting it.
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", flag, flags
        )
        os.environ["XLA_FLAGS"] = flags
    else:
        os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()
    if devices[0].platform != "cpu" or len(devices) < n_devices:
        raise RuntimeError(
            f"need {n_devices} virtual CPU devices but the backend already "
            f"initialized with {len(devices)} {devices[0].platform!r} "
            f"device(s); call pin_cpu in a fresh process before any jax "
            f"backend use"
        )
