"""Beyond-RAM tiered store (pslite_tpu/kv/tiered.py —
docs/durability.md): unit behavior of the two-tier mapping, and the
bit-identity matrix — a tiered server must end BIT-EXACT vs the
all-RAM twin across PS_APPLY_SHARDS x replication x codec."""

import numpy as np
import pytest

from helpers import LoopbackCluster
from pslite_tpu.kv.kv_app import (KVServer, KVServerDefaultHandle,
                                  KVServerOptimizerHandle, KVWorker)
from pslite_tpu.kv.tiered import TieredStore
from pslite_tpu.telemetry.metrics import Registry


# -- unit behavior -----------------------------------------------------------


def _store(ram_bytes=4096, shards=1, **kw):
    reg = Registry()
    return TieredStore(ram_bytes=ram_bytes, shards=shards,
                       metrics=reg, **kw), reg


def test_mapping_protocol_roundtrip():
    st, _ = _store()
    try:
        a = np.arange(8, dtype=np.float32)
        st[3] = a
        assert 3 in st and 4 not in st
        assert len(st) == 1
        assert np.array_equal(st[3], a)
        assert st.get(4) is None
        with pytest.raises(KeyError):
            st[4]
        got = st.pop(3)
        assert np.array_equal(got, a)
        assert len(st) == 0 and not st
    finally:
        st.close()


def test_eviction_and_promotion_across_tiers():
    # 4 KiB budget, 1 KiB values: steady-state RAM holds a handful of
    # keys; the rest demote to segments and promote back on access.
    st, reg = _store(ram_bytes=4096)
    try:
        vals = {k: np.full(256, float(k), np.float32) for k in range(32)}
        for k, v in vals.items():
            st[k] = v.copy()
            st.get(k)  # setitem never evicts; get enforces the budget
        assert reg.counter("kv.evictions").value > 0
        assert st.ram_bytes <= 4096
        assert any(st.tier_of(k) == "cold" for k in vals)
        # Every key reads back bit-exact from whichever tier holds it.
        for k, v in vals.items():
            assert np.array_equal(st.get(k), v), k
        assert reg.counter("kv.cold_hits").value > 0
        assert reg.counter("kv.promotions").value > 0
        # items() materializes BOTH tiers (the export_range currency).
        snap = dict(st.items())
        assert set(snap) == set(vals)
        for k, v in vals.items():
            assert np.array_equal(snap[k], v)
    finally:
        st.close()


def test_promoted_key_mutates_in_place():
    # The correctness core: get() of a cold key must return the array
    # the store keeps, so the handle's `cur += seg` persists.
    st, _ = _store(ram_bytes=1024)
    try:
        for k in range(8):
            st[k] = np.full(128, float(k), np.float32)
            st.get(k)
        cold = [k for k in range(8) if st.tier_of(k) == "cold"]
        assert cold
        k = cold[0]
        arr = st.get(k)  # promotes
        arr += 1.0       # in-place, like KVServerDefaultHandle's push
        assert np.array_equal(st.get(k), np.full(128, k + 1.0,
                                                 np.float32))
    finally:
        st.close()


def test_overwrite_drops_stale_cold_entry():
    st, _ = _store(ram_bytes=512)
    try:
        st[1] = np.full(128, 1.0, np.float32)
        st[2] = np.full(128, 2.0, np.float32)
        st.get(2)  # evicts key 1 (class 0, LRU) past the 512 B budget
        assert st.tier_of(1) == "cold"
        st[1] = np.full(128, 9.0, np.float32)  # overwrite while cold
        assert st.tier_of(1) == "ram"
        assert np.array_equal(st.get(1), np.full(128, 9.0, np.float32))
    finally:
        st.close()


def test_hot_set_preferred_for_ram():
    st, _ = _store(ram_bytes=2048, hot_fn=lambda: [7])
    try:
        # Force a hot-set refresh cadence-independently: touch enough
        # for the budget to bite, with key 7 the declared hot one.
        st._refresh_hot()
        for k in range(16):
            st[k] = np.full(128, float(k), np.float32)
        for _ in range(4):
            for k in range(16):
                st.get(k)
        assert st.tier_of(7) == "ram"  # heat kept it resident
    finally:
        st.close()


def test_transient_cold_read_failure_keeps_key_retryable():
    """A cold read that fails (flaky mmap/IO) must leave the key in
    the cold index — a transient disk error must not become permanent
    key loss."""
    st, _ = _store(ram_bytes=512)
    try:
        st[1] = np.full(128, 1.0, np.float32)
        st[2] = np.full(128, 2.0, np.float32)
        st.get(2)  # evicts key 1 past the 512 B budget
        assert st.tier_of(1) == "cold"
        orig = st._read
        state = {"failed": False}

        def flaky(ent):
            if not state["failed"]:
                state["failed"] = True
                raise OSError("transient mmap failure")
            return orig(ent)

        st._read = flaky
        with pytest.raises(OSError):
            st.get(1)
        assert st.tier_of(1) == "cold"  # still there, not dropped
        assert np.array_equal(st.get(1),
                              np.full(128, 1.0, np.float32))
    finally:
        st.close()


def test_evict_on_insert_bounds_boot_restore():
    """The boot-restore window (set_evict_on_insert) enforces the
    budget on __setitem__: a beyond-RAM restore must not materialize
    the whole table in RAM before the first get()."""
    st, _ = _store(ram_bytes=4096)
    try:
        st.set_evict_on_insert(True)
        vals = {k: np.full(256, float(k), np.float32)
                for k in range(32)}  # 32 KiB into a 4 KiB budget
        for k, v in vals.items():
            st[k] = v.copy()
        # Bounded THROUGHOUT the import (hysteresis target is 90%,
        # +1 value of slack before the next insert's enforcement).
        assert st.ram_bytes <= 4096 + 1024
        st.set_evict_on_insert(False)
        for k, v in vals.items():
            assert np.array_equal(st.get(k), v), k
    finally:
        st.close()


def test_discard_drops_cold_key_without_reading():
    """Migration drops must not deserialize segment bytes nobody
    reads: discard() is index-only."""
    st, _ = _store(ram_bytes=512)
    try:
        st[1] = np.full(128, 1.0, np.float32)
        st[2] = np.full(128, 2.0, np.float32)
        st.get(2)  # evicts key 1
        assert st.tier_of(1) == "cold"

        def boom(ent):  # any read attempt is a failure
            raise AssertionError("discard must not read the segment")

        st._read = boom
        assert st.discard(1) is True
        assert st.tier_of(1) is None
        assert st.discard(1) is False
        assert st.discard(2) is True  # ram-tier discard
        assert len(st) == 0
    finally:
        st.close()


def test_close_removes_owned_segment_dir():
    import os

    st, _ = _store(ram_bytes=256)
    try:
        for k in range(8):
            st[k] = np.full(128, float(k), np.float32)
            st.get(k)
        d = st.directory
        assert os.path.isdir(d)
    finally:
        st.close()
    assert not os.path.isdir(d)


# -- bit-identity matrix (tiered vs all-RAM) ---------------------------------


def _run_cluster(ram_mb, shards, replication, codec, handle_kind):
    """One leg: boot, storm (bulk push + incremental subset pushes +
    interleaved pulls), return the final pulled table."""
    env = {
        "PS_APPLY_SHARDS": str(shards),
        "PS_KV_REPLICATION": str(replication),
    }
    if ram_mb:
        env["PS_STORE_RAM_MB"] = str(ram_mb)
    n_servers = 2 if replication > 1 else 1
    cl = LoopbackCluster(num_workers=1, num_servers=n_servers,
                         env_extra=env)
    cl.start()
    servers = []
    try:
        for po in cl.servers:
            s = KVServer(0, postoffice=po)
            s.set_request_handle(
                KVServerOptimizerHandle(kind="sgd_momentum", lr=0.1)
                if handle_kind == "opt" else KVServerDefaultHandle()
            )
            servers.append(s)
        w = KVWorker(0, 0, postoffice=cl.workers[0])
        rng = np.random.default_rng(42)
        nk, vl = 64, 256
        keys = np.arange(nk, dtype=np.uint64)
        base = rng.normal(size=nk * vl).astype(np.float32)
        w.wait(w.push(keys, base, codec=codec))
        for _ in range(10):
            sub = np.unique(rng.integers(0, nk, 16)).astype(np.uint64)
            dv = rng.normal(size=len(sub) * vl).astype(np.float32)
            w.wait(w.push(sub, dv, codec=codec))
            probe = np.zeros(len(sub) * vl, np.float32)
            w.wait(w.pull(sub, probe))
        out = np.zeros(nk * vl, np.float32)
        w.wait(w.pull(keys, out))
        if ram_mb and handle_kind == "default":
            store = servers[0]._handle.store
            assert isinstance(store, TieredStore)
        return out
    finally:
        cl.finalize()
        for s in servers:
            s.stop()


@pytest.mark.parametrize(
    "shards,replication,codec",
    [
        (0, 1, None),       # serial apply path
        (4, 1, None),       # sharded
        (4, 2, None),       # sharded + chain replication
        (4, 1, "int8"),     # sharded + quantized wire (decode-side
                            # identical on both legs, so stores match)
    ],
)
def test_tiered_bit_identity_matrix(shards, replication, codec):
    """A ~16x-over-budget tiered store must end bit-exact vs the
    all-RAM twin under the same traffic, across the apply/replication/
    codec matrix — the docs/durability.md placement invariant."""
    ram = _run_cluster(0, shards, replication, codec, "default")
    tiered = _run_cluster(0.004, shards, replication, codec, "default")
    assert np.array_equal(ram, tiered)


def test_tiered_bit_identity_optimizer_handle():
    ram = _run_cluster(0, 4, 1, None, "opt")
    tiered = _run_cluster(0.004, 4, 1, None, "opt")
    assert np.array_equal(ram, tiered)
