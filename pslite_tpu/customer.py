"""Customer — per-app request/response tracker and receive pump.

Capability parity with the reference's ``include/ps/internal/customer.h`` /
``src/customer.cc``: ``new_request(recver)`` allocates a timestamp and records
how many responses to expect; a dedicated thread pops the receive queue, runs
the app's handle, then counts the response (the count is incremented *after*
the handle runs, which KVWorker's completion logic relies on —
``customer.cc:59-74``).

One extension for the TPU data plane: a timestamp can carry *completion
hooks* (e.g. ``jax.Array.block_until_ready``) so ICI-van requests — which
never produce response messages — still honor ``wait_request`` semantics.

Executor mode (``PS_CUSTOMER_EXECUTOR=N``): handler calls run on N
worker threads fed by a BOUNDED queue, so the pump keeps draining the
receive queue while handlers run — the feed stage of the server's
sharded apply pipeline (docs/apply_shards.md).  ``N=1`` preserves
handler order (one drainer); ``N>1`` is only for order-insensitive
handlers.  Backpressure: a full executor queue blocks the pump instead
of ballooning memory.
"""

from __future__ import annotations

import threading
import traceback
from typing import Callable, Dict, List, Optional

from .message import Message
from .utils.queues import PriorityRecvQueue, ThreadsafeQueue


class Customer:
    def __init__(
        self,
        app_id: int,
        customer_id: int,
        recv_handle: Callable[[Message], None],
        postoffice,
        on_request_error: Optional[
            Callable[[Message, Exception], None]
        ] = None,
        executor_workers: Optional[int] = None,
    ):
        self.app_id = app_id
        self.customer_id = customer_id
        self._recv_handle = recv_handle
        self._po = postoffice
        # Hook: a handler exception on a REQUEST message (the remote
        # side is waiting) — KVServer uses it to send an error-marked
        # response so the waiter fails fast instead of hanging.
        self._on_request_error = on_request_error
        # ts -> [expected, received]; insertion-ordered and pruned of old
        # completed entries (bounded, unlike the reference's ever-growing
        # vector) — see _prune_tracker_locked.
        self._tracker: Dict[int, List[int]] = {}
        self._next_ts = 0
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        # Priority intake (PS_RECV_PRIORITY, same knob as the van's
        # receive queues — docs/chunking.md): a priority op must not
        # wait behind the queued handling of earlier bulk messages
        # (e.g. the codec tier's payload decode, docs/compression.md)
        # any more than it waits behind their frames on the wire.
        # FIFO within a level preserves per-sender arrival order for
        # same-priority traffic — the apply pool's bit-exactness
        # contract; the shutdown sentinel drains LAST, preserving the
        # deliver-queued-traffic-before-retiring contract.
        env = getattr(postoffice, "env", None)
        prio = (env.find_int("PS_RECV_PRIORITY", 1) != 0
                if env is not None else True)
        # Tenant weights (docs/qos.md): bulk intake dequeues weighted-
        # fair across tenants, like the lanes and the van queues —
        # sharing ONE tenant/cost model (vans/chunking.py) so the two
        # intake hops can never diverge.
        from .tenants import table_for
        from .vans.chunking import recv_cost, recv_tenant

        tenant_table = table_for(env)
        self._queue = (
            PriorityRecvQueue(
                self._recv_priority, tenant_fn=recv_tenant,
                cost_fn=recv_cost,
                weights=(tenant_table.weights_by_id()
                         if tenant_table.enabled else None),
            ) if prio else ThreadsafeQueue()
        )
        self._hooks: Dict[int, List[Callable[[], None]]] = {}
        if executor_workers is None:
            env = getattr(postoffice, "env", None)
            executor_workers = (
                env.find_int("PS_CUSTOMER_EXECUTOR", 0)
                if env is not None else 0
            )
        self._exec_workers = max(0, int(executor_workers))
        self._exec_queue: Optional[ThreadsafeQueue] = None
        self._exec_threads: List[threading.Thread] = []
        if self._exec_workers:
            self._exec_queue = ThreadsafeQueue(
                maxsize=4 * self._exec_workers
            )
            for i in range(self._exec_workers):
                t = threading.Thread(
                    target=self._exec_loop,
                    name=f"customer-exec-{app_id}-{customer_id}-{i}",
                    daemon=True,
                )
                t.start()
                self._exec_threads.append(t)
        self._thread = threading.Thread(
            target=self._receiving, name=f"customer-{app_id}-{customer_id}", daemon=True
        )
        self._thread.start()
        postoffice.add_customer(self)

    # -- request tracking ----------------------------------------------------

    def new_request(self, recver: int, num_responses: Optional[int] = None) -> int:
        """Allocate a timestamp expecting one response per addressed node.

        With instance groups, a worker instance only talks to the matching
        server instance in each group, so the expected count is
        ``len(node_ids(recver)) / group_size`` (reference: customer.cc:32-40).
        """
        if num_responses is None:
            ids = self._po.get_node_ids(recver)
            if recver < 8:
                # Group bitmask: one response per matching instance of each
                # group — the scheduler (a singleton) is counted apart so it
                # is not swallowed by the group_size division.
                sched = 1 if any(i == 1 for i in ids) else 0
                num = max(sched + (len(ids) - sched) // self._po.group_size, 1)
            else:  # direct node id
                num = len(ids)
        else:
            num = num_responses
        with self._cv:
            ts = self._next_ts
            self._next_ts += 1
            self._tracker[ts] = [num, 0]
            self._prune_tracker_locked()
            return ts

    _MAX_TRACKER_ENTRIES = 8192

    def _prune_tracker_locked(self) -> None:
        """Bound tracker growth (the reference grows forever,
        customer.cc:32-40): sweep out old COMPLETED entries beyond the
        window; a pruned timestamp reads back as complete.  In-flight
        entries are skipped (never pruned), so one stuck request cannot
        re-unbound the tracker — only genuinely outstanding ones remain."""
        if len(self._tracker) <= self._MAX_TRACKER_ENTRIES:
            return
        keep_recent = self._MAX_TRACKER_ENTRIES // 2
        completed = [
            ts for ts, (exp, got) in self._tracker.items() if got >= exp
        ]
        if len(completed) > keep_recent:
            for ts in completed[: len(completed) - keep_recent]:
                del self._tracker[ts]

    def _entry(self, timestamp: int):
        entry = self._tracker.get(timestamp)
        if entry is not None:
            return entry
        # Only timestamps we actually issued may read back as "pruned =
        # long complete"; a future/bogus ts is a caller bug — fail loud
        # (the pre-bounded tracker raised IndexError here).
        if 0 <= timestamp < self._next_ts:
            return (0, 0)
        raise KeyError(f"unknown timestamp {timestamp}")

    def wait_request(self, timestamp: int, timeout: Optional[float] = None) -> bool:
        if self._hooks:  # unlocked probe: hooks are an ICI-path feature
            for hook in self._take_hooks(timestamp):
                hook()
        with self._cv:
            done = lambda: (  # noqa: E731
                self._entry(timestamp)[0] <= self._entry(timestamp)[1]
            )
            if timeout is None:
                self._cv.wait_for(done)
                return True
            return self._cv.wait_for(done, timeout)

    def num_response(self, timestamp: int) -> int:
        with self._mu:
            return self._entry(timestamp)[1]

    def num_expected(self, timestamp: int) -> int:
        """Responses this timestamp was issued expecting (0 for pruned
        = long-complete entries).  Under elastic routing the per-slice
        fan-out varies per request, so completion checks must read the
        count recorded at issue time, not a global server count."""
        with self._mu:
            return self._entry(timestamp)[0]

    def add_response(self, timestamp: int, num: int = 1) -> None:
        with self._cv:
            if timestamp in self._tracker:
                self._tracker[timestamp][1] += num
            self._cv.notify_all()

    _MAX_HOOK_ENTRIES = 256

    def add_wait_hook(self, timestamp: int, hook: Callable[[], None]) -> None:
        """Attach a device-completion hook run by wait_request (ICI path).

        Hooks must be idempotent (e.g. ``Future.result``): they run on
        *every* wait of the timestamp so concurrent waiters all observe
        completion.  Entries are evicted FIFO beyond a bounded window."""
        with self._mu:
            self._hooks.setdefault(timestamp, []).append(hook)
            while len(self._hooks) > self._MAX_HOOK_ENTRIES:
                self._hooks.pop(next(iter(self._hooks)))

    def _take_hooks(self, timestamp: int) -> List[Callable[[], None]]:
        with self._mu:
            return list(self._hooks.get(timestamp, ()))

    # -- receive pump --------------------------------------------------------

    @staticmethod
    def _recv_priority(msg: Optional[Message]) -> int:
        """Intake level: None (shutdown sentinel) and TERMINATE drain
        last; data messages use their wire priority."""
        if msg is None:
            return -(1 << 30)
        c = msg.meta.control
        if not c.empty():
            from .message import Command

            if c.cmd == Command.TERMINATE:
                return -(1 << 30)
            return 1 << 20
        return msg.meta.priority

    def accept(self, msg: Message) -> None:
        self._queue.push(msg)

    def _receiving(self) -> None:
        while True:
            msg = self._queue.wait_and_pop()
            if msg is None or msg.meta.control.cmd.name == "TERMINATE":
                break
            if self._exec_queue is not None:
                # Bounded push: blocks when the executor is saturated,
                # so backpressure reaches the van instead of memory.
                self._exec_queue.push(msg)
            else:
                self._handle_msg(msg)
        if self._exec_queue is not None:
            # FIFO sentinels ride behind any queued messages; join so
            # stop() returns only after in-flight handlers finish.
            for _ in self._exec_threads:
                self._exec_queue.push(None)
            for t in self._exec_threads:
                t.join(timeout=5)

    def _exec_loop(self) -> None:
        while True:
            msg = self._exec_queue.wait_and_pop()
            if msg is None:
                return
            self._handle_msg(msg)

    def _handle_msg(self, msg: Message) -> None:
        try:
            self._recv_handle(msg)
        except Exception as exc:
            # A handler bug must not kill the pump: responses still have
            # to be counted or every waiter on this node hangs silently.
            # Log the FULL traceback (a one-line repr buried the actual
            # bug site) and, for requests, let the app fail the remote
            # waiter fast instead of leaving it to hang until timeout.
            from .utils import logging as _log

            _log.warning(
                f"recv handle raised: {exc!r}\n{traceback.format_exc()}"
            )
            if msg.meta.request and self._on_request_error is not None:
                try:
                    self._on_request_error(msg, exc)
                except Exception as hook_exc:
                    _log.warning(
                        f"on_request_error hook failed: {hook_exc!r}"
                    )
        finally:
            # A batched response envelope (docs/batching.md) carries N
            # sub-ops with N distinct timestamps — the app layer counts
            # each sub-op itself; the envelope's own timestamp is just
            # the first op's and must not be double-counted.
            if not msg.meta.request and msg.meta.batch is None:
                self.add_response(msg.meta.timestamp)

    def stop(self) -> None:
        self._queue.push(None)
        self._thread.join(timeout=5)
        self._po.remove_customer(self)
