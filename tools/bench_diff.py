#!/usr/bin/env python
"""bench_diff — compare the two newest ``BENCH_r*.json`` records.

Prints per-section deltas for the always-on transport sections (the
ones ``bench.py`` runs regardless of device availability) and exits
nonzero when any DIRECTIONAL metric regressed by more than the
threshold (default 25%) — the trajectory guard ``make bench-check``
runs, referenced from ``tests/test_bench_smoke.py``.

Only metrics listed in ``TRANSPORT_METRICS`` gate the exit status:
each entry knows which direction is good, so a higher p99 fails while
a higher goodput passes.  Everything else numeric is printed as
context but never fails the check (absolute walls move with host
load; the curated list holds the ratios and rates that are
host-comparable).

Usage::

    python tools/bench_diff.py                 # newest two BENCH_r*.json
    python tools/bench_diff.py OLD.json NEW.json
    python tools/bench_diff.py --threshold 0.4
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

# metric -> "higher" (bigger is better) or "lower".  Grouped by the
# bench section that emits them; every section here is always-on
# (bench.py runs it with or without a device backend).
TRANSPORT_METRICS: Dict[str, str] = {
    # send_lanes
    "send_lanes_overlap_x": "higher",
    # server_apply
    "server_apply_sharded_msgs_per_s": "higher",
    "server_apply_speedup_x": "higher",
    # chunk_streaming
    "chunk_chunked_push_gbps": "higher",
    "chunk_hol_p99_ratio": "higher",
    # native_goodput
    "native_native_push_gbps": "higher",
    "native_goodput_ratio": "higher",
    # quantized_push (docs/compression.md) — BOTH halves of the
    # acceptance: effective goodput up, priority-pull tail bounded.
    "quantized_int8_push_gbps": "higher",
    "quantized_fp8_e4m3_push_gbps": "higher",
    "quantized_goodput_ratio_int8": "higher",
    "quantized_goodput_ratio_fp8_e4m3": "higher",
    "quantized_p99_ratio_int8": "lower",
    "quantized_p99_ratio_fp8_e4m3": "lower",
    # multi_tenant (docs/qos.md) — isolation, cache, and hit rate.
    "multi_tenant_p99_ratio": "lower",
    "multi_tenant_dlrm_p50_ratio": "higher",
    "multi_tenant_hit_rate": "higher",
    # small_op_batching (docs/batching.md) — the ops/s multiple of the
    # aggregation plane, and the low-load latency it must not cost.
    "small_op_batching_msgs_ratio": "higher",
    "small_op_batching_batched_msgs_per_s": "higher",
    "small_op_batching_low_load_p50_ratio": "lower",
    # serving_fanin (docs/batching.md) — multi-get + response
    # aggregation: the requests/s multiple of the fan-in plane, the
    # ~1-RTT response-frames-per-request it must hold, and the
    # low-load single-pull latency it must not cost.
    "serving_fanin_req_ratio": "higher",
    "serving_fanin_agg_reqs_per_s": "higher",
    "serving_fanin_frames_per_req": "lower",
    "serving_fanin_low_load_p50_ratio": "lower",
    # replica_read (docs/serving_reads.md) — the reads/s multiple of
    # spreading pulls over the whole replica chain (k=3 vs k=1), and
    # the read-your-writes guarantee it must NEVER trade away.
    "replica_read_tput_ratio": "higher",
    "replica_read_k3_reqs_per_s": "higher",
    "replica_read_ryw_violations": "lower",
    "replica_read_ns_flip_errors": "lower",
    # elastic_scale (docs/elasticity.md) — the serving tail must stay
    # bounded through a live 2->4->2 migration window, and the scale
    # round trip itself must not regress.
    "elastic_p99_ratio": "lower",
    "elastic_scale_2_4_2_wall_s": "lower",
    # autopilot (docs/autopilot.md) — the self-driving loop must keep
    # per-server load near the mean (a ratio drifting back toward ~2
    # means the skew remediation stopped working) with ZERO manual
    # operator actions (any nonzero value is a regression by
    # definition: the loop needed a human).
    "autopilot_load_skew_ratio": "lower",
    "autopilot_operator_actions": "lower",
    # durable_store (docs/durability.md) — the beyond-RAM serving tax
    # (Zipf hot-set p99, tiered vs all-RAM; acceptance <= 2x) and the
    # full-cluster-kill restore wall.
    "durable_hot_p99_ratio": "lower",
    "durable_restore_s": "lower",
    # kv_telemetry
    "kv_storm_msgs_per_s": "higher",
    # wire (docs/observability.md) — wire-plane efficiency of the
    # bursty small-op tcp storm: kernel crossings and frames per
    # logical op must not creep up (batching regressing to singletons
    # or the vectored writer degenerating shows up here first).
    "wire_syscalls_per_op": "lower",
    "wire_frames_per_op": "lower",
    # fault_recovery
    "fault_recovery_detect_s": "lower",
    "fault_recovery_failover_pull_s": "lower",
}

# Section key prefixes, used to map a guarded metric back to the
# section that emits it.  A section that degraded on purpose emits
# ``{"skipped": <reason>}`` — its fields then land as
# ``<prefix>skipped`` in the record — and its guarded metrics are
# treated as ABSENT (a device-down round must not read as a vanished-
# metric regression) rather than failed.
SECTION_PREFIXES = (
    "send_lanes_", "server_apply_", "chunk_", "native_", "quantized_",
    "multi_tenant_", "small_op_batching_", "serving_fanin_",
    "replica_read_", "elastic_", "autopilot_", "durable_",
    "kv_tracing_", "kv_", "fault_recovery_", "van_", "wire_",
)

# Hard invariants: metrics that must be exactly ZERO in every record.
# The ratio guard above cannot express them (a 0 -> 0 pair is skipped,
# and 0 -> N has no finite delta); any nonzero value here is a
# regression outright — e.g. the autopilot acceptance requires the
# storm to complete with no manual operator actions at all.
MUST_BE_ZERO = ("autopilot_operator_actions",)


def _section_skipped(rec: dict, key: str) -> bool:
    """True when the section emitting guarded metric ``key`` recorded
    an explicit skip in ``rec`` instead of running."""
    for p in SECTION_PREFIXES:
        if key.startswith(p) and f"{p}skipped" in rec:
            return True
    return False


def _round_of(path: str) -> int:
    m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else -1


def newest_two(directory: str) -> Optional[Tuple[str, str]]:
    """(older, newer) of the two highest-numbered BENCH_r*.json."""
    recs = sorted(
        (p for p in glob.glob(os.path.join(directory, "BENCH_r*.json"))
         if _round_of(p) >= 0),
        key=_round_of,
    )
    if len(recs) < 2:
        return None
    return recs[-2], recs[-1]


# Top-level fields that are context-only by construction and never
# comparable across rounds: the kv_telemetry section's windowed-rate
# roll-ups depend on the measured interval and host load, and the
# kv_tracing section's tail-trace counts/stage shares are shaped by
# host load and the uniform keep floor — diffing either only produces
# noise lines (docs/observability.md).
IGNORED_PREFIXES = ("kv_windowed_", "kv_tracing_")


def _numeric_items(rec: dict) -> Dict[str, float]:
    out = {}
    for k, v in rec.items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        if any(k.startswith(p) for p in IGNORED_PREFIXES):
            continue
        out[k] = float(v)
    return out


def compare(old: dict, new: dict,
            threshold: float = 0.25) -> Tuple[List[str], List[str]]:
    """(report lines, regression lines)."""
    o, n = _numeric_items(old), _numeric_items(new)
    lines: List[str] = []
    regressions: List[str] = []
    for key in sorted(set(o) & set(n)):
        ov, nv = o[key], n[key]
        if ov == 0:
            continue
        delta = (nv - ov) / abs(ov)
        direction = TRANSPORT_METRICS.get(key)
        tag = ""
        if direction is not None:
            adverse = -delta if direction == "higher" else delta
            if adverse > threshold:
                tag = "  << REGRESSION"
                regressions.append(
                    f"{key}: {ov:g} -> {nv:g} "
                    f"({delta:+.1%}, {direction} is better)"
                )
            else:
                tag = "  [guarded]"
        lines.append(f"  {key:<44} {ov:>12g} -> {nv:>12g} "
                     f"({delta:+7.1%}){tag}")
    # A guarded metric that VANISHED from the newer record is the
    # worst regression of all — a crashed/blind section (the r04/r05
    # failure mode this tool exists to catch) must not read as a pass.
    # Exception: a section that recorded an EXPLICIT skip reason
    # (``{"skipped": ...}`` — device down, toolchain absent) is noted
    # but never fails the check; skipping loudly is the designed
    # degrade, not a regression.
    for key in sorted(set(TRANSPORT_METRICS) & set(o) - set(n)):
        if _section_skipped(new, key):
            lines.append(f"  {key:<44} {o[key]:>12g} ->      skipped"
                         f"  [section skipped]")
            continue
        regressions.append(
            f"{key}: {o[key]:g} -> MISSING (section absent or failed "
            f"in the newer record)"
        )
        lines.append(f"  {key:<44} {o[key]:>12g} ->      MISSING"
                     f"  << REGRESSION")
    # Zero-invariant metrics: the ov == 0 guard above skips them, so
    # check the newer record directly — any nonzero value fails.
    for key in MUST_BE_ZERO:
        nv = n.get(key)
        if nv:
            regressions.append(f"{key}: must be 0, got {nv:g}")
            lines.append(f"  {key:<44} {'0':>12} -> {nv:>12g}"
                         f"  << REGRESSION (must be 0)")
    # Sections that disappeared or newly failed are worth a loud note.
    for field in ("sections_failed",):
        if new.get(field):
            lines.append(f"  note: {field} = {new[field]}")
    return lines, regressions


_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(series: List[Optional[float]],
               blind: Optional[List[bool]] = None) -> str:
    """Unicode mini-chart of one metric's round-by-round values.
    Rounds where the metric was absent render as '·' — EXCEPT blind
    device rounds (the record carries an ``error``, e.g. "backend
    init timed out": nothing device-side ran at all), which render as
    an explicit '∅' so a tunnel outage reads as an outage, not as a
    metric that merely hadn't been invented yet."""
    blind = blind or [False] * len(series)

    def absent(i: int) -> str:
        return "∅" if blind[i] else "·"

    vals = [v for v in series if v is not None]
    if not vals:
        return "".join(absent(i) for i in range(len(series)))
    lo, hi = min(vals), max(vals)
    span = hi - lo
    out = []
    for i, v in enumerate(series):
        if v is None:
            out.append(absent(i))
        elif span <= 0:
            out.append(_SPARK[3])
        else:
            out.append(_SPARK[min(7, int((v - lo) / span * 7.999))])
    return "".join(out)


def history(directory: str) -> List[str]:
    """Render the FULL ``BENCH_r*.json`` trajectory of every guarded
    transport metric as a min/max/last sparkline table — the
    at-a-glance view that makes a blind stretch (the r04/r05 tunnel
    outage produced two rounds of silently missing device numbers)
    visible immediately instead of only when the newest two records
    happen to straddle it."""
    recs = sorted(
        (p for p in glob.glob(os.path.join(directory, "BENCH_r*.json"))
         if _round_of(p) >= 0),
        key=_round_of,
    )
    if not recs:
        return [f"bench_diff --history: no BENCH_r*.json in {directory}"]
    rounds = [_round_of(p) for p in recs]
    objs = []
    for p in recs:
        try:
            rec = json.load(open(p))
        except Exception:  # noqa: BLE001 - a corrupt record renders absent
            rec = {}
        # The driver wraps bench.py's emitted JSON under "parsed"
        # (alongside the raw cmd/rc/tail provenance) — unwrap so the
        # committed records render their metric fields.
        if isinstance(rec.get("parsed"), dict) and not any(
                k in rec for k in TRANSPORT_METRICS):
            rec = rec["parsed"]
        objs.append(rec)
    lines = [
        f"bench_diff history: rounds r{rounds[0]:02d}..r{rounds[-1]:02d} "
        f"({len(recs)} records, {len(TRANSPORT_METRICS)} guarded metrics)",
    ]
    # Per-round status first: a blind round (error field, zero sections,
    # or no transport fields at all) must be visible even when no
    # guarded metric ever rendered a sparkline cell for it.
    for rnd, rec in zip(rounds, objs):
        sha = str(rec.get("git_sha", ""))[:9] or "-"
        n_metrics = sum(1 for k in TRANSPORT_METRICS if k in rec)
        done = rec.get("sections_done")
        failed = rec.get("sections_failed")
        status = []
        if rec.get("error"):
            status.append(f"ERROR: {str(rec['error'])[:60]}")
        if done is not None:
            status.append(f"{len(done)} sections done"
                          + (f", {len(failed)} failed" if failed else ""))
        if n_metrics == 0:
            status.append("BLIND (no guarded transport fields)")
        lines.append(f"  r{rnd:02d}  sha={sha:<9} "
                     f"guarded={n_metrics:>2}  " + "; ".join(status))
    # Blind device rounds: the record carries an explicit error
    # ("backend init timed out...") — every guarded cell of that round
    # renders '∅', distinct from '·' (metric predates its section).
    blind_rounds = [bool(rec.get("error")) for rec in objs]
    if any(blind_rounds):
        lines.append("")
        lines.append("  legend: ∅ = blind device round (bench errored; "
                     "no device numbers exist), · = metric absent")
    lines.append("")
    lines.append(
        f"  {'metric':<44} {'trend':<{max(5, len(recs))}} "
        f"{'min':>10} {'max':>10} {'last':>10}  dir"
    )
    for key in sorted(TRANSPORT_METRICS):
        series: List[Optional[float]] = []
        for rec in objs:
            v = rec.get(key)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                v = None
            series.append(None if v is None else float(v))
        vals = [v for v in series if v is not None]
        if not vals:
            continue  # metric never emitted (older than its section)
        spark = _sparkline(series, blind_rounds)
        tail = ""
        if series[-1] is None:
            tail = ("   << ∅ blind (newest round errored)"
                    if blind_rounds[-1]
                    else "   << BLIND (absent in newest record)")
        lines.append(
            f"  {key:<44} {spark:<{max(5, len(recs))}} "
            f"{min(vals):>10g} {max(vals):>10g} "
            f"{(series[-1] if series[-1] is not None else float('nan')):>10g}"
            f"  {TRANSPORT_METRICS[key]}" + tail
        )
    return lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("files", nargs="*",
                    help="explicit OLD NEW records (default: the two "
                         "newest BENCH_r*.json in --dir)")
    ap.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    ap.add_argument("--threshold", type=float, default=0.25,
                    help="adverse fractional change that fails the "
                         "check (default 0.25)")
    ap.add_argument("--history", action="store_true",
                    help="render every BENCH_r*.json round per guarded "
                         "metric (min/max/last sparkline table) instead "
                         "of diffing the newest two")
    args = ap.parse_args(argv)
    if args.history:
        print("\n".join(history(args.dir)))
        return 0
    if args.files:
        if len(args.files) != 2:
            ap.error("pass exactly two files (OLD NEW) or none")
        old_path, new_path = args.files
    else:
        pair = newest_two(args.dir)
        if pair is None:
            print("bench_diff: fewer than two BENCH_r*.json records in "
                  f"{args.dir}; nothing to compare")
            return 0
        old_path, new_path = pair
    old = json.load(open(old_path))
    new = json.load(open(new_path))
    print(f"bench_diff: {os.path.basename(old_path)} -> "
          f"{os.path.basename(new_path)} "
          f"(threshold {args.threshold:.0%} on "
          f"{len(TRANSPORT_METRICS)} guarded transport metrics)")
    lines, regressions = compare(old, new, args.threshold)
    print("\n".join(lines) if lines else "  (no shared numeric fields)")
    if regressions:
        print(f"\nbench_diff: {len(regressions)} transport "
              f"regression(s) > {args.threshold:.0%}:")
        for r in regressions:
            print(f"  {r}")
        return 1
    print("\nbench_diff: no guarded transport metric regressed beyond "
          f"{args.threshold:.0%}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
